"""Gradient-based hyperparameter tuning THROUGH the QP solver.

The reference tunes hyperparameters (ridge strength, turnover penalty,
box widths) by grid search over whole backtests — its solver boundary
(``src/qp_problems.py:211``) is opaque to derivatives. Here the solve
is differentiable (``porqua_tpu.qp.diff``, implicit-function vjp), so
"pick the ridge that minimizes NEXT-window tracking error" is a
first-order optimization: every gradient step backpropagates through
objective assembly -> batched QP solve -> out-of-sample tracking error,
all inside one jitted XLA program.

Run: python examples/differentiable_tuning.py  (CPU, ~30 s)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.diff import solve_qp_diff
from porqua_tpu.qp.solve import SolverParams

PARAMS = SolverParams(max_iter=20000, eps_abs=1e-10, eps_rel=1e-10)


def make_panel(rng, n_dates=12, T=60, n=24, noise=0.004):
    """Rolling factor-model windows with noisy observations: in-sample
    LS overfits, so an out-of-sample-optimal ridge exists."""
    w_true = rng.dirichlet(np.ones(n))
    Xs = rng.standard_normal((n_dates, 2 * T, n)) * 0.01
    ys = Xs @ w_true + rng.standard_normal((n_dates, 2 * T)) * noise
    # Fit window = first T rows, evaluation window = next T rows.
    return (jnp.asarray(Xs[:, :T]), jnp.asarray(ys[:, :T]),
            jnp.asarray(Xs[:, T:]), jnp.asarray(ys[:, T:]))


def build_qp(X, y, ridge):
    n = X.shape[-1]
    dtype = X.dtype
    return CanonicalQP(
        P=2.0 * X.T @ X + 2.0 * ridge * jnp.eye(n, dtype=dtype),
        q=-2.0 * X.T @ y,
        C=jnp.ones((1, n), dtype), l=jnp.ones(1, dtype),
        u=jnp.ones(1, dtype),
        lb=jnp.zeros(n, dtype), ub=jnp.ones(n, dtype),
        var_mask=jnp.ones(n, dtype), row_mask=jnp.ones(1, dtype),
        constant=jnp.dot(y, y),
    )


def main():
    rng = np.random.default_rng(7)
    X_fit, y_fit, X_oos, y_oos = make_panel(rng)

    @jax.jit
    def oos_te(log_ridge):
        """Median-free smooth objective: mean out-of-sample tracking
        error over the date batch, as a function of log10(ridge)."""
        ridge = 10.0 ** log_ridge

        def one(Xf, yf, Xo, yo):
            w = solve_qp_diff(build_qp(Xf, yf, ridge), PARAMS)
            r = Xo @ w - yo
            return jnp.sqrt(jnp.mean(r * r))

        return jnp.mean(jax.vmap(one)(X_fit, y_fit, X_oos, y_oos))

    grad = jax.jit(jax.grad(oos_te))

    # Plain gradient descent on log10(ridge). The landscape is gentle
    # (TE moves ~1e-5 per log-unit), so the raw gradient needs a large
    # learning rate with a trust-region-style step cap.
    log_r = jnp.asarray(-5.0, jnp.float64)
    print(f"start: ridge=1e{float(log_r):.2f} "
          f"oos_te={float(oos_te(log_r)):.6e}")
    lr, cap = 2e4, 0.5
    for step in range(40):
        g = grad(log_r)
        log_r = log_r - jnp.clip(lr * g, -cap, cap)
    te_tuned = float(oos_te(log_r))
    print(f"tuned: ridge=1e{float(log_r):.2f} oos_te={te_tuned:.6e}")

    # Compare against a coarse grid — the reference's only option.
    grid = [-7.0, -6.0, -5.0, -4.0, -3.0, -2.0, -1.0]
    tes = [float(oos_te(jnp.asarray(g, jnp.float64))) for g in grid]
    best = int(np.argmin(tes))
    print("grid  :", ", ".join(f"1e{g:.0f}->{t:.3e}"
                               for g, t in zip(grid, tes)))
    print(f"grid best: ridge=1e{grid[best]:.0f} oos_te={tes[best]:.6e}")
    assert te_tuned <= tes[best] * 1.02, (
        "gradient tuning should match or beat the coarse grid")
    print("OK: gradient-tuned ridge matches/beats the grid search")


if __name__ == "__main__":
    main()
