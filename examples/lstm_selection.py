"""LSTM next-day-return ranking for asset selection.

Runnable equivalent of the reference's ``example/lstm.ipynb``: sliding
100-day windows of the MSCI country returns -> LSTM(32) -> Dropout ->
Dense(24) next-day predictions trained with Adam/MSE, then rank assets
and score ranking quality with NDCG on a held-out tail. Training is one
jitted lax.scan; the model serializes to msgpack.
"""

import numpy as np

from _common import init_platform, load_msci_or_synthetic

init_platform()

from porqua_tpu.models import make_windows, ndcg, train_lstm  # noqa: E402


def main():
    data = load_msci_or_synthetic()
    returns = data["return_series"].tail(2000)
    window, test_size = 100, 50

    X, y = make_windows(returns.values, window)
    X_train, y_train = X[:-test_size], y[:-test_size]
    X_test, y_test = X[-test_size:], y[-test_size:]
    print(f"dataset: {X_train.shape[0]} train windows of "
          f"({window} days x {returns.shape[1]} assets)")

    model = train_lstm(X_train, y_train, hidden=32, dropout=0.2,
                       epochs=30, batch_size=128, seed=0)
    print(f"train MSE: {model.loss_history[0]:.3e} -> {model.loss_history[-1]:.3e}")

    pred = model.predict(X_test)
    rmse = float(np.sqrt(np.mean((pred - y_test) ** 2)))
    # rank quality: realized-return ranks as graded relevance (cell 10)
    rel = np.argsort(np.argsort(y_test, axis=1), axis=1).astype(float)
    scores = np.asarray(ndcg(pred, rel, k=returns.shape[1]))
    print(f"held-out ({test_size} days): RMSE {rmse:.3e}, "
          f"mean NDCG@{returns.shape[1]} {scores.mean():.3f}")

    top = np.argsort(-pred[-1])[:10]
    print("top-10 assets on the last day:", list(returns.columns[top]))


if __name__ == "__main__":
    main()
