"""LSTM next-day-return ranking for asset selection.

Runnable equivalent of the reference's ``example/lstm.ipynb``: sliding
100-day windows of the MSCI country returns -> LSTM(32) -> Dropout ->
Dense(24) next-day predictions trained with Adam/MSE, then rank assets
and score ranking quality with NDCG on a held-out tail. Training is one
jitted lax.scan; the model serializes to msgpack.
"""

import numpy as np

from _common import init_platform, load_msci_or_synthetic

init_platform()

from porqua_tpu.models import make_windows, ndcg, train_lstm  # noqa: E402


def main():
    data = load_msci_or_synthetic()
    returns = data["return_series"].tail(2000)
    window, test_size = 100, 50

    X, y = make_windows(returns.values, window)
    X_train, y_train = X[:-test_size], y[:-test_size]
    X_test, y_test = X[-test_size:], y[-test_size:]
    print(f"dataset: {X_train.shape[0]} train windows of "
          f"({window} days x {returns.shape[1]} assets)")

    model = train_lstm(X_train, y_train, hidden=32, dropout=0.2,
                       epochs=30, batch_size=128, seed=0)
    print(f"train MSE: {model.loss_history[0]:.3e} -> {model.loss_history[-1]:.3e}")

    pred = model.predict(X_test)
    rmse = float(np.sqrt(np.mean((pred - y_test) ** 2)))
    # rank quality: realized-return ranks as graded relevance (cell 10)
    rel = np.argsort(np.argsort(y_test, axis=1), axis=1).astype(float)
    scores = np.asarray(ndcg(pred, rel, k=returns.shape[1]))
    print(f"held-out ({test_size} days): RMSE {rmse:.3e}, "
          f"mean NDCG@{returns.shape[1]} {scores.mean():.3f}")

    top = np.argsort(-pred[-1])[:10]
    print("top-10 assets on the last day:", list(returns.columns[top]))

    # Quality comparison against the reference's shipped trained model
    # (model/lstm_msci.keras, evaluated through the same NDCG harness —
    # the lstm.ipynb cell-10 workflow, no tensorflow required).
    import os

    ref_path = "/root/reference/model/lstm_msci.keras"
    if os.path.exists(ref_path):
        from porqua_tpu.models.lstm import (
            load_reference_lstm, reference_lstm_windows)

        ref_model = load_reference_lstm(ref_path)
        X_ref, y_ref = reference_lstm_windows(
            returns.values.astype(np.float32), window)
        X_ref, y_ref = X_ref[-test_size:], y_ref[-test_size:]
        ref_pred = ref_model.predict(X_ref)
        rel_ref = np.argsort(np.argsort(y_ref, axis=1), axis=1).astype(float)
        for k in (5, 10):
            ours = float(np.mean(np.asarray(ndcg(pred, rel, k=k))))
            theirs = float(np.mean(np.asarray(ndcg(ref_pred, rel_ref, k=k))))
            print(f"NDCG@{k}: this model {ours:.3f} vs "
                  f"reference saved model {theirs:.3f}")


if __name__ == "__main__":
    main()
