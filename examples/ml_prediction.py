"""Per-asset return prediction: OLS, PCA+OLS, gradient boosting, LTR.

Runnable equivalent of the reference's ``example/ml.ipynb``: predict one
asset's monthly return from the cross-section of the others, compare
OLS / PCA+OLS / grid-searched boosting by RMSE and MAPE on a
chronological holdout, then show rank-label construction for learning
to rank.
"""

import numpy as np

from _common import init_platform, load_msci_or_synthetic

init_platform()

from porqua_tpu.models import (  # noqa: E402
    OLS,
    PCA,
    PCAOLS,
    boosted_regression,
    decile_rank_labels,
)
from porqua_tpu.optimization_data import OptimizationData  # noqa: E402
from porqua_tpu.utils.helpers import calculate_mape, calculate_rmse  # noqa: E402


def main():
    data = load_msci_or_synthetic()
    rets = data["return_series"]
    monthly = np.exp(np.log1p(rets).resample("ME").sum()) - 1
    target = monthly.columns[0]
    y = monthly[target]
    X = monthly.drop(columns=target)
    print(f"predicting {target} monthly returns from {X.shape[1]} series, "
          f"{len(y)} months")

    od = OptimizationData(align=True, X=X, y=y)
    train, test = od.train_test_split(test_size=0.2)

    pca = PCA(n_components=10).fit(train["X"])
    evr = pca.explained_variance_ratio_
    print(f"PCA scree: first 5 components explain {evr[:5].sum():.1%}")

    models = {
        "OLS": OLS(add_constant=True).fit(train["X"], train["y"]),
        "PCA+OLS": PCAOLS(n_components=10, add_constant=True).fit(
            train["X"], train["y"]),
    }
    est, best, cv_rmse = boosted_regression(
        train["X"], train["y"],
        param_grid={"max_depth": [3, 6], "max_iter": [100, 200]})
    print(f"boosting grid search: best {best}, CV RMSE {cv_rmse:.4f}")

    preds = {name: m.predict(test["X"]) for name, m in models.items()}
    preds["boosted"] = est.predict(np.asarray(test["X"]))
    for name, p in preds.items():
        print(f"{name:8s}: holdout RMSE {calculate_rmse(test['y'].values, p):.4f}, "
              f"MAPE {calculate_mape(test['y'].values, p):.1f}%")

    labels = decile_rank_labels(monthly, n_bins=10)
    print(f"LTR labels: decile ranks per month, e.g. last month's top asset "
          f"is {labels.iloc[-1].idxmin()} (rank 0 = best)")


if __name__ == "__main__":
    main()
