"""Ordinal regression on cross-sectional return-rank labels.

Runnable equivalent of the reference's ``example/ordinal_regression.ipynb``:
build quintile rank labels from winsorized monthly returns (rank 0 =
highest, the reference's ``(-ret).rank()`` convention), fit ordered
probit and logit models on trailing cross-sections, and report the
fraction of correct choice predictions on a holdout (notebook cells
6-13).
"""

import numpy as np

from _common import init_platform, load_msci_or_synthetic

init_platform()

from porqua_tpu.models import OrdinalRegression, decile_rank_labels  # noqa: E402


def main():
    data = load_msci_or_synthetic()
    rets = data["return_series"]
    monthly = np.exp(np.log1p(rets).resample("ME").sum()) - 1
    monthly = monthly.clip(-0.5, 0.5)  # winsorize, notebook cell 2
    n_bins = 5
    labels = decile_rank_labels(monthly, n_bins=n_bins)

    # features: this month's return cross-section; target: next month's rank
    X = monthly.iloc[:-1].to_numpy().reshape(-1, 1)
    y = labels.iloc[1:].to_numpy().reshape(-1)
    keep = np.isfinite(X[:, 0])
    X, y = X[keep], y[keep].astype(int)
    cut = int(0.8 * len(y))
    X_train, y_train, X_test, y_test = X[:cut], y[:cut], X[cut:], y[cut:]
    print(f"{len(y_train)} train / {len(y_test)} test observations, "
          f"{n_bins} ordered classes")

    for distr in ("probit", "logit"):
        model = OrdinalRegression(distr=distr).fit(X_train, y_train,
                                                   n_classes=n_bins)
        acc_train = (model.predict(X_train) == y_train).mean()
        acc_test = (model.predict(X_test) == y_test).mean()
        print(f"{distr:6s}: cutpoints {np.round(model.cutpoints_, 3)}, "
              f"fraction of correct choice predictions "
              f"train {acc_train:.3f} / test {acc_test:.3f} "
              f"(chance {1 / n_bins:.2f})")


if __name__ == "__main__":
    main()
