"""Tune the optimizer's turnover-penalty knob by gradient descent.

A cost-aware strategy solves  min ‖Xw − y‖² + λ·‖w − w_prev‖₁  — but
the right λ is NOT the market's transaction cost: it is a churn-control
knob whose best value depends on signal stability, and the reference
can only grid-search it with a full backtest per point. Here the
lifted form of the L1 term (reference ``qp_problems.py:120-157``,
``porqua_tpu/qp/lift.py``) is an ordinary QP in 2n variables whose
``q`` carries λ — so realized NET performance (out-of-sample tracking
error + actual costs paid on turnover) is differentiable in λ through
the solver (``porqua_tpu.qp.diff``), and the knob tunes itself.

Run: python examples/cost_penalty_tuning.py  (CPU, ~1 min)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.diff import solve_qp_diff
from porqua_tpu.qp.solve import SolverParams

PARAMS = SolverParams(max_iter=20000, eps_abs=1e-10, eps_rel=1e-10)
REAL_TC = 0.003          # the market's actual cost per unit turnover
N, T, B = 16, 40, 8


def lifted_tracking_qp(X, y, w_prev, lam):
    """jnp build of the reference's turnover-cost lift: variables
    [w, t], objective ‖Xw−y‖² + λ Σt, rows t >= |w − w_prev| — a plain
    QP, so the solve is differentiable in λ (via q) and w_prev (via the
    row bounds)."""
    n = X.shape[1]
    dtype = X.dtype
    inf = jnp.asarray(jnp.inf, dtype)
    P = jnp.zeros((2 * n, 2 * n), dtype)
    P = P.at[:n, :n].set(2.0 * X.T @ X)
    q = jnp.concatenate([-2.0 * X.T @ y, jnp.full(n, lam, dtype)])
    eye = jnp.eye(n, dtype=dtype)
    zero = jnp.zeros((n, n), dtype)
    C = jnp.concatenate([
        jnp.concatenate([jnp.ones((1, n), dtype),
                         jnp.zeros((1, n), dtype)], axis=1),
        jnp.concatenate([eye, -eye], axis=1),      # w - t <=  w_prev
        jnp.concatenate([-eye, -eye], axis=1),     # -w - t <= -w_prev
    ], axis=0)
    l = jnp.concatenate([jnp.ones(1, dtype), jnp.full(2 * n, -inf)])
    u = jnp.concatenate([jnp.ones(1, dtype), w_prev, -w_prev])
    return CanonicalQP(
        P=P, q=q, C=C, l=l, u=u,
        lb=jnp.concatenate([jnp.zeros(n, dtype), jnp.zeros(n, dtype)]),
        ub=jnp.concatenate([jnp.ones(n, dtype), jnp.full(n, inf)]),
        var_mask=jnp.ones(2 * n, dtype), row_mask=jnp.ones(1 + 2 * n, dtype),
        constant=jnp.dot(y, y),
    )


def main():
    rng = np.random.default_rng(11)
    w_prev = jnp.asarray(rng.dirichlet(np.ones(N)))
    w_true = rng.dirichlet(np.ones(N))
    Xs = rng.standard_normal((B, 2 * T, N)) * 0.01
    ys = Xs @ w_true + rng.standard_normal((B, 2 * T)) * 0.002
    X_fit, y_fit = jnp.asarray(Xs[:, :T]), jnp.asarray(ys[:, :T])
    X_oos, y_oos = jnp.asarray(Xs[:, T:]), jnp.asarray(ys[:, T:])

    @jax.jit
    def net_loss(log_lam):
        lam = 10.0 ** log_lam

        def one(Xf, yf, Xo, yo):
            wt = solve_qp_diff(lifted_tracking_qp(Xf, yf, w_prev, lam),
                               PARAMS)
            w = wt[:N]
            te = jnp.sqrt(jnp.mean((Xo @ w - yo) ** 2))
            turnover = jnp.sum(jnp.abs(w - w_prev))
            return te + REAL_TC * turnover

        return jnp.mean(jax.vmap(one)(X_fit, y_fit, X_oos, y_oos))

    loss_and_grad = jax.jit(jax.value_and_grad(net_loss))
    log_l = jnp.asarray(-5.0, jnp.float64)
    print(f"start: lambda=1e{float(log_l):.2f} "
          f"net={float(net_loss(log_l)):.6e}")
    # Gradient descent with best-iterate tracking: past the point where
    # lambda pins w = w_prev exactly, the loss is a flat plateau (the
    # L1 solution map is piecewise constant there, gradient identically
    # zero), so the final iterate can stall on the plateau — the best
    # iterate seen cannot.
    lr, cap = 2e3, 0.2
    best_log, best_net = float(log_l), float(net_loss(log_l))
    for step in range(60):
        v, g = loss_and_grad(log_l)
        if float(v) < best_net:
            best_net, best_log = float(v), float(log_l)
        log_l = log_l - jnp.clip(lr * g, -cap, cap)
    # The loop scores iterates 0..59; score the final update too.
    v_last = float(net_loss(log_l))
    if v_last < best_net:
        best_net, best_log = v_last, float(log_l)
    net_tuned = best_net
    print(f"tuned: lambda=1e{best_log:.2f} net={net_tuned:.6e}")

    grid = [-5.0, -4.0, -3.0, -2.5, -2.0, -1.5, -1.0]
    nets = [float(net_loss(jnp.asarray(g, jnp.float64))) for g in grid]
    best = int(np.argmin(nets))
    print("grid  :", ", ".join(f"1e{g:.1f}->{v:.4e}"
                               for g, v in zip(grid, nets)))
    print(f"grid best: lambda=1e{grid[best]:.1f} net={nets[best]:.6e}")
    assert net_tuned <= nets[best] * 1.001, (
        "gradient tuning should match or beat the grid")
    print("OK: gradient-tuned turnover penalty matches/beats the grid")

    # The same gradient through the NATIVE n-variable prox path
    # (solve_qp_l1_diff) — no 2n lift — must agree with the lifted one:
    # two independent formulations of the identical piecewise-smooth
    # solution map.
    from porqua_tpu.qp.diff import solve_qp_l1_diff

    def plain_qp(X, y):
        n = X.shape[1]
        dtype = X.dtype
        return CanonicalQP(
            P=2.0 * X.T @ X, q=-2.0 * X.T @ y,
            C=jnp.ones((1, n), dtype), l=jnp.ones(1, dtype),
            u=jnp.ones(1, dtype),
            lb=jnp.zeros(n, dtype), ub=jnp.ones(n, dtype),
            var_mask=jnp.ones(n, dtype), row_mask=jnp.ones(1, dtype),
            constant=jnp.dot(y, y),
        )

    @jax.jit
    def net_loss_native(log_lam):
        lam = 10.0 ** log_lam

        def one(Xf, yf, Xo, yo):
            wv = solve_qp_l1_diff(
                plain_qp(Xf, yf), jnp.full(N, lam, jnp.float64), w_prev,
                PARAMS)
            te = jnp.sqrt(jnp.mean((Xo @ wv - yo) ** 2))
            return te + REAL_TC * jnp.sum(jnp.abs(wv - w_prev))

        return jnp.mean(jax.vmap(one)(X_fit, y_fit, X_oos, y_oos))

    probe = jnp.asarray(-3.2, jnp.float64)
    g_lift = float(jax.grad(net_loss)(probe))
    g_native = float(jax.grad(net_loss_native)(probe))
    print(f"d(net)/d(log lambda) at 1e-3.2: lifted {g_lift:+.6e}, "
          f"native prox {g_native:+.6e}")
    assert abs(g_lift - g_native) <= 1e-6 + 1e-3 * abs(g_lift)
    print("OK: native-prox gradient agrees with the lifted-QP gradient")


if __name__ == "__main__":
    main()
