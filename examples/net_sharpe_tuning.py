"""Differentiable END-TO-END P&L: tune the turnover knob on net Sharpe.

This composes the two device engines nothing in the reference can
chain a gradient through (its solver boundary is qpsolvers, its P&L a
pandas loop — ``src/qp_problems.py:211``, ``src/portfolio.py:205-245``):

    lambda -> [scan over rebalances: tracking QP + native L1 turnover
               prox, each date's solution seeding the next date's L1
               center]                      (porqua_tpu.qp.diff)
           -> rebalance weights (D, N)
           -> the device accounting engine: drifted weights, levels,
              turnover, NET returns after variable costs
                                            (porqua_tpu.accounting)
           -> annualized net Sharpe

and differentiates the whole pipeline in ONE ``jax.grad`` — the
optimizer's churn-control knob lambda is tuned directly against the
money the strategy actually keeps, costs, drift, and compounding
included. A finite-difference cross-check validates the gradient at
the optimum found.

Run: python examples/net_sharpe_tuning.py  (CPU, ~2 min)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

from porqua_tpu.accounting import simulate
from porqua_tpu.qp.diff import solve_qp_l1_diff
from porqua_tpu.qp.solve import SolverParams
from porqua_tpu.tracking import build_tracking_qp

PARAMS = SolverParams(max_iter=20000, eps_abs=1e-9, eps_rel=1e-9)
VC = 0.01               # the market's actual cost per unit turnover
N, WINDOW, D, STEP = 12, 42, 10, 21
ANN = 252


def make_market(seed=7):
    """Synthetic market where the *benchmark carries alpha*: a sparse
    basket that slowly rotates into the assets whose drift is
    temporarily high. Tracking it closely captures the alpha but
    churns; freezing the portfolio saves costs but loses the rotation.
    The knob lambda trades exactly that off, so net Sharpe has an
    interior optimum in lambda."""
    rng = np.random.default_rng(seed)
    T = WINDOW + D * STEP + 1
    k = 3
    B = 0.5 + 0.5 * rng.random((N, k))
    F = 0.009 * rng.standard_normal((T, k))
    noise = 0.004 * rng.standard_normal((T, N))
    mu = np.full((T, N), 0.0006)     # common market drift
    w_bm = np.zeros((T, N))
    idx = rng.choice(N, 4, replace=False)
    hold = rng.dirichlet(np.ones(4))
    for t in range(T):
        if t % (2 * STEP) == 0 and t:
            idx = rng.choice(N, 4, replace=False)
            hold = rng.dirichlet(np.ones(4))
        w_bm[t, idx] = hold
        mu[t, idx] += 0.0035         # the rotating alpha (~0.35%/day)
    R = F @ B.T + mu + noise
    y = np.einsum("tn,tn->t", R, w_bm) + 0.0005 * rng.standard_normal(T)
    reb_idx = np.arange(WINDOW, WINDOW + D * STEP, STEP)
    return jnp.asarray(R), jnp.asarray(y), jnp.asarray(reb_idx)


R, y_bm, reb_idx = make_market()
Xs = jnp.stack([jax.lax.dynamic_slice_in_dim(R, int(i) - WINDOW, WINDOW)
                for i in reb_idx])
ys = jnp.stack([jax.lax.dynamic_slice_in_dim(y_bm, int(i) - WINDOW, WINDOW)
                for i in reb_idx])
w0 = jnp.full((N,), 1.0 / N)


def weights_chain(lam):
    """All D rebalance solves, turnover-coupled through the L1 center."""
    def body(w_prev, Xy):
        X, yb = Xy
        w = solve_qp_l1_diff(build_tracking_qp(X, yb),
                             jnp.full(N, lam), w_prev, PARAMS)
        return w, w

    _, ws = jax.lax.scan(body, w0, (Xs, ys))
    return ws


def net_sharpe(lam):
    ws = weights_chain(lam)
    sim = simulate(ws, R, reb_idx, vc=VC)
    nv = jnp.sum(sim.valid)
    mean = jnp.sum(sim.returns) / nv
    var = jnp.sum(jnp.where(sim.valid, (sim.returns - mean) ** 2, 0.0)) / (
        nv - 1.0)
    return mean / jnp.sqrt(var) * jnp.sqrt(float(ANN))


def main():
    # Tune theta = log(lambda): multiplicative steps cannot rail the
    # knob against a clip bound in one update, and the scale of
    # dS/dtheta = lambda * dS/dlambda is self-normalizing.
    val_and_grad = jax.jit(jax.value_and_grad(
        lambda th: net_sharpe(jnp.exp(th))))
    # Start on the disciplined (high-lambda) side of the live region:
    # the net-Sharpe landscape is multimodal (chase-everything is a
    # separate, worse local basin at lambda ~ 1e-4) and above ~3e-3
    # every coordinate kink-rests, the solution is locally constant in
    # lambda, and the (correct) gradient is identically zero — the
    # piecewise-smooth solution map only promises a local ascent from
    # where the knob still bites.
    theta = jnp.log(jnp.asarray(8e-4, jnp.float64))
    lr = 0.4
    print(f"actual cost vc={VC}; tuning the solver's lambda on NET Sharpe")
    for it in range(14):
        s, g = val_and_grad(theta)
        lam = float(jnp.exp(theta))
        to = float(jnp.sum(simulate(weights_chain(jnp.exp(theta)), R,
                                    reb_idx, vc=VC).turnover))
        print(f"  it {it:2d}: lambda {lam:.5f}  net Sharpe "
              f"{float(s):+.3f}  dS/dtheta {float(g):+8.3f}  "
              f"total turnover {to:.2f}", flush=True)
        theta = theta + lr * jnp.clip(g, -2.0, 2.0)

    # Gradient sanity at the end point: central finite difference.
    lam = jnp.exp(theta)
    h = 1e-6
    fd = (float(net_sharpe(lam + h)) - float(net_sharpe(lam - h))) / (2 * h)
    g = float(jax.grad(net_sharpe)(lam))
    print(f"FD check at lambda={float(lam):.5f}: grad {g:+.4f} "
          f"vs FD {fd:+.4f}")
    s_final = float(net_sharpe(lam))
    s_zero = float(net_sharpe(jnp.asarray(1e-6)))
    s_frozen = float(net_sharpe(jnp.asarray(0.1)))
    print(f"net Sharpe: chase-everything (lambda~0) {s_zero:+.3f}, "
          f"frozen (lambda=0.1) {s_frozen:+.3f}, tuned {s_final:+.3f}")


if __name__ == "__main__":
    main()
