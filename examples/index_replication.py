"""Index replication: track a benchmark with a constrained LS portfolio.

Runnable equivalent of the reference's ``example/index_replication.ipynb``:
minimize ||Xw - y||^2 in log-return space over the constraint polytope
(budget + long-only box), backtest it monthly, and report tracking error
and cumulative performance vs the benchmark. The solve path is the
batched device engine — all rebalance dates in one XLA program.
"""

import numpy as np

from _common import init_platform, load_msci_or_synthetic

init_platform()

import jax.numpy as jnp  # noqa: E402
import pandas as pd  # noqa: E402

from porqua_tpu import (  # noqa: E402
    BacktestService,
    LeastSquares,
    OptimizationItemBuilder,
    SelectionItemBuilder,
)
from porqua_tpu.accounting import simulate_strategy  # noqa: E402
from porqua_tpu.batch import run_batch  # noqa: E402
from porqua_tpu.builders import (  # noqa: E402
    bibfn_bm_series,
    bibfn_box_constraints,
    bibfn_budget_constraint,
    bibfn_return_series,
    bibfn_selection_data,
)


def monthly_rebdates(index, start="2018-01-01", k=36):
    me = pd.Series(index=index, data=1).resample("ME").last().index
    out = [str(index[index <= d][-1].date()) for d in me
           if str(start) <= str(d.date()) and (index <= d).any()]
    return out[:k]


def main():
    data = load_msci_or_synthetic()
    returns = data["return_series"]
    bm = data["bm_series"]
    rebdates = monthly_rebdates(returns.index)
    print(f"tracking {bm.columns[0]} with {returns.shape[1]} assets, "
          f"{len(rebdates)} monthly rebalances")

    bs = BacktestService(
        data=data,
        selection_item_builders={
            "data": SelectionItemBuilder(bibfn=bibfn_selection_data),
        },
        optimization_item_builders={
            "returns": OptimizationItemBuilder(bibfn=bibfn_return_series, width=252),
            "bm": OptimizationItemBuilder(bibfn=bibfn_bm_series, width=252, align=True),
            "budget": OptimizationItemBuilder(bibfn=bibfn_budget_constraint),
            "box": OptimizationItemBuilder(bibfn=bibfn_box_constraints, upper=0.5),
        },
        # log-space LS objective, as in the notebook's formulation
        optimization=LeastSquares(log_transform=True, dtype=jnp.float64),
        settings={"rebdates": rebdates, "quiet": True},
    )
    bt = run_batch(bs, dtype=jnp.float64)
    stats = bt.output["batch"]
    print(f"solved {int((stats['status'] == 1).sum())}/{len(rebdates)} dates, "
          f"median iters n/a, max primal residual {stats['prim_res'].max():.2e}")

    sim = simulate_strategy(bt.strategy, returns, fc=0.0, vc=0.0)
    bm_ret = bm.iloc[:, 0].reindex(sim.index)
    te = float((sim - bm_ret).std() * np.sqrt(252))
    print(f"annualized tracking error vs benchmark: {te:.4f}")
    print(f"cumulative log-return: portfolio {float(np.log1p(sim).sum()):+.4f}, "
          f"benchmark {float(np.log1p(bm_ret).sum()):+.4f}")


if __name__ == "__main__":
    main()
