"""Cross-solver comparison on the MSCI index-tracking problem.

Runnable equivalent of the reference's ``example/compare_solver.ipynb``:
build one LeastSquares tracking problem (budget, long-only box with a
0.1 cap), run it through every available solver backend, and print the
accuracy/reliability/runtime table (notebook cells 6-9). Here the
backends are the device ADMM solver at f32 and f64, the native C++ ADMM
core, and scipy SLSQP — plus any installed qpsolvers backends.
"""

from _common import init_platform, load_msci_or_synthetic

init_platform()

import jax.numpy as jnp  # noqa: E402
import pandas as pd  # noqa: E402

from porqua_tpu import (  # noqa: E402
    Constraints,
    LeastSquares,
    OptimizationData,
    compare_solvers,
)


def main():
    data = load_msci_or_synthetic()
    X = data["return_series"].tail(1260)
    y = data["bm_series"].reindex(X.index).iloc[:, 0]
    universe = list(X.columns)

    constraints = Constraints(selection=universe)
    constraints.add_budget()
    constraints.add_box("LongOnly", upper=0.1)

    opt = LeastSquares(dtype=jnp.float64)
    opt.constraints = constraints
    opt.set_objective(OptimizationData(align=False, return_series=X, bm_series=y))
    qp = opt.model_canonical()
    print(f"problem: n={qp.n} assets, m={qp.m} constraint rows, "
          f"T={len(X)} observations")

    df = compare_solvers(qp)
    pd.set_option("display.width", 160)
    pd.set_option("display.float_format", lambda v: f"{v:.3e}")
    print(df)

    objs = df.loc[df["solution_found"], "objective_value"]
    print(f"\nobjective spread across backends: {objs.max() - objs.min():.2e}")


if __name__ == "__main__":
    main()
