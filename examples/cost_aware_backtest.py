"""Turnover-cost-aware backtest three ways + checkpoint/resume + profiling.

Shows the capabilities around the reference's transaction-cost machinery
(reference ``qp_problems.py:120-157`` + ``optimization.py:126-137``),
re-designed for the device:

1. **Lifted** (reference-faithful): each date's QP doubles to 2n
   variables with the |w - x0|_1 epigraph rows.
2. **Native prox** (`l1_native=True`): the same cost term handled inside
   the ADMM w-block soft-threshold at n variables.
3. **Sequential scan** (`solve_scan_l1`): the cost chains *solved* dates
   (w_prev feeds the next date's L1 center) — one `lax.scan` program,
   warm-started, no host round-trips.

Plus: chunk-granular checkpoint/resume (`run_batch_checkpointed`) and
the stage tracer (`porqua_tpu.profiling`).
"""

import shutil
import tempfile

import numpy as np

from _common import init_platform, load_msci_or_synthetic, quarterly_rebdates

init_platform()

import jax.numpy as jnp  # noqa: E402

from porqua_tpu import (  # noqa: E402
    BacktestService,
    LeastSquares,
    OptimizationItemBuilder,
    SelectionItemBuilder,
)
from porqua_tpu.batch import build_problems, run_batch, solve_scan_l1  # noqa: E402
from porqua_tpu.builders import (  # noqa: E402
    bibfn_bm_series,
    bibfn_box_constraints,
    bibfn_budget_constraint,
    bibfn_return_series,
    bibfn_selection_data,
)
from porqua_tpu.checkpoint import run_batch_checkpointed  # noqa: E402
from porqua_tpu.profiling import Tracer  # noqa: E402
from porqua_tpu.qp.solve import SolverParams  # noqa: E402

TC = 0.005  # 50 bps per unit of one-way turnover


def make_service(data, rebdates, **opt_kwargs):
    n = data["return_series"].shape[1]
    x0 = {a: 1.0 / n for a in data["return_series"].columns}
    return BacktestService(
        data=data,
        selection_item_builders={
            "data": SelectionItemBuilder(bibfn=bibfn_selection_data),
        },
        optimization_item_builders={
            "returns": OptimizationItemBuilder(bibfn=bibfn_return_series, width=252),
            "bm": OptimizationItemBuilder(bibfn=bibfn_bm_series, width=252, align=True),
            "budget": OptimizationItemBuilder(bibfn=bibfn_budget_constraint),
            "box": OptimizationItemBuilder(bibfn=bibfn_box_constraints),
        },
        optimization=LeastSquares(
            transaction_cost=TC, x0=x0, dtype=np.float64,
            eps_abs=1e-8, eps_rel=1e-8, max_iter=20000, **opt_kwargs,
        ),
        settings={"rebdates": rebdates, "quiet": True},
    )


def main():
    data = load_msci_or_synthetic()
    rebdates = quarterly_rebdates(data["return_series"].index, k=12)
    tracer = Tracer()

    # 1) Reference-style lifted formulation (2n variables per date).
    with tracer.stage("lifted", dates=len(rebdates)):
        bt_lift = run_batch(make_service(data, rebdates), dtype=np.float64)
    w_lift = bt_lift.strategy.get_weights_df()

    # 2) Native prox path at n variables.
    with tracer.stage("l1_native"):
        bt_nat = run_batch(
            make_service(data, rebdates, l1_native=True), dtype=np.float64
        )
    w_nat = bt_nat.strategy.get_weights_df()
    print("lifted vs native-prox max|dw|:",
          f"{np.abs(w_lift.values - w_nat.values).max():.2e}")
    print("per-date iters (native):", bt_nat.output["batch"]["iters"].tolist())

    # 3) Sequential chain: each date pays cost against the *previous
    #    solved* weights (one lax.scan program).
    bs = make_service(data, rebdates, l1_native=True)
    problems = build_problems(bs, dtype=jnp.float64)
    n = problems.n_assets_max
    with tracer.stage("scan_chain") as holder:
        sols = solve_scan_l1(
            problems.qp, n_assets=n,
            w_init=np.full(n, 1.0 / n), transaction_cost=TC,
            params=SolverParams(eps_abs=1e-8, eps_rel=1e-8, max_iter=20000),
            universes=problems.universes,
        )
        holder["value"] = sols.x
    chain_turnover = float(np.abs(np.diff(np.asarray(sols.x)[:, :n], axis=0)).sum())
    static_turnover = float(np.abs(np.diff(w_nat.values, axis=0)).sum())
    print(f"chained-cost turnover {chain_turnover:.4f} "
          f"vs static-x0 turnover {static_turnover:.4f}")

    # 3b) The same chained-cost engine for a whole strategy grid:
    #     lax.scan over the coupled dates x vmap over strategies, the
    #     strategy axis sharded over the device mesh (here: the virtual
    #     CPU mesh; identical program on real chips over ICI).
    import jax

    from porqua_tpu.batch import solve_scan_l1_grid
    from porqua_tpu.parallel import make_mesh

    n_dev = min(2, len(jax.devices()))
    t_demo = min(4, problems.n_dates)  # keep the demo horizon short
    qp_head = jax.tree.map(lambda a: a[:t_demo], problems.qp)
    grid = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (n_dev,) + a.shape), qp_head)
    mesh = make_mesh(n_dev, axis_names=("bench",))
    with tracer.stage("scan_grid_sharded") as holder:
        grid_sols = solve_scan_l1_grid(
            grid, n_assets=n, w_init=np.full((n_dev, n), 1.0 / n),
            transaction_cost=TC, mesh=mesh,
            params=SolverParams(eps_abs=1e-8, eps_rel=1e-8, max_iter=20000),
            universes=problems.universes[:t_demo],
        )
        holder["value"] = grid_sols.x
    dgrid = float(np.abs(np.asarray(grid_sols.x)
                         - np.asarray(sols.x)[None, :t_demo]).max())
    print(f"grid engine ({n_dev}-way sharded, {t_demo} dates) vs single "
          f"column max|dx|: {dgrid:.2e}")

    # 4) Checkpoint/resume: run chunked, then resume from disk (no-op
    #    second pass — all chunks present).
    ckdir = tempfile.mkdtemp(prefix="porqua_ck_")
    try:
        bt_ck = run_batch_checkpointed(
            make_service(data, rebdates, l1_native=True), ckdir,
            chunk_size=4,
            params=SolverParams(eps_abs=1e-8, eps_rel=1e-8, max_iter=20000),
            dtype=jnp.float64,
        )
        bt_resume = run_batch_checkpointed(
            make_service(data, rebdates, l1_native=True), ckdir,
            chunk_size=4,
            params=SolverParams(eps_abs=1e-8, eps_rel=1e-8, max_iter=20000),
            dtype=jnp.float64,
        )
        print("checkpoint chunks:", bt_ck.output["checkpoint"],
              "-> resume:", bt_resume.output["checkpoint"])
        dw = np.abs(bt_ck.strategy.get_weights_df().values
                    - bt_resume.strategy.get_weights_df().values).max()
        print(f"checkpointed vs resumed max|dw|: {dw:.2e}")
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)

    print(tracer.report())


if __name__ == "__main__":
    main()
