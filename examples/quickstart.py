"""End-to-end tour: four strategies, serial + batched engines, P&L.

Runnable equivalent of the reference's interactive smoke script
(reference ``src/_quick_and_dirty_interactive_testing.py``): MSCI data
-> quarterly rebalance dates -> selection/optimization item builders ->
``BacktestService`` -> backtests of QEQW / LeastSquares /
WeightedLeastSquares / LAD -> ``simulate`` with costs -> cumulative
log-returns. Then the same LeastSquares backtest again through the
batched one-XLA-program engine (``porqua_tpu.batch.run_batch``) to show
the two paths agree.
"""

import numpy as np

from _common import init_platform, load_msci_or_synthetic, quarterly_rebdates

init_platform()

import jax.numpy as jnp  # noqa: E402

from porqua_tpu import (  # noqa: E402
    Backtest,
    BacktestService,
    LAD,
    LeastSquares,
    OptimizationItemBuilder,
    QEQW,
    SelectionItemBuilder,
    WeightedLeastSquares,
)
from porqua_tpu.accounting import simulate_strategy  # noqa: E402
from porqua_tpu.batch import run_batch  # noqa: E402
from porqua_tpu.builders import (  # noqa: E402
    bibfn_bm_series,
    bibfn_box_constraints,
    bibfn_budget_constraint,
    bibfn_return_series,
    bibfn_selection_data,
)


def make_service(data, rebdates, optimization, width=252):
    return BacktestService(
        data=data,
        selection_item_builders={
            "data": SelectionItemBuilder(bibfn=bibfn_selection_data),
        },
        optimization_item_builders={
            "returns": OptimizationItemBuilder(bibfn=bibfn_return_series, width=width),
            "bm": OptimizationItemBuilder(bibfn=bibfn_bm_series, width=width, align=True),
            "budget": OptimizationItemBuilder(bibfn=bibfn_budget_constraint),
            "box": OptimizationItemBuilder(bibfn=bibfn_box_constraints),
        },
        optimization=optimization,
        settings={"rebdates": rebdates, "quiet": True},
    )


def main():
    data = load_msci_or_synthetic()
    returns = data["return_series"]
    rebdates = quarterly_rebdates(returns.index, start="2018-01-01", k=12)
    print(f"universe: {returns.shape[1]} assets, {len(rebdates)} rebalances "
          f"({rebdates[0]} .. {rebdates[-1]})")

    strategies = {
        "qeqw": QEQW(dtype=jnp.float64),
        "ls": LeastSquares(dtype=jnp.float64),
        "wls": WeightedLeastSquares(tau=126, dtype=jnp.float64),
        "lad": LAD(dtype=jnp.float64),
    }
    sims = {}
    for name, opt in strategies.items():
        bs = make_service(data, rebdates, opt)
        bt = Backtest()
        bt.run(bs)
        sim = simulate_strategy(bt.strategy, returns, fc=0.0, vc=0.002)
        sims[name] = sim
        cum = float(np.log1p(sim).sum())
        to = bt.strategy.turnover(return_series=returns).mean()
        print(f"{name:5s}: cumulative log-return {cum:+.4f}, "
              f"mean turnover {float(to):.3f}")

    # Batched engine on the same LeastSquares service: one XLA program.
    bs = make_service(data, rebdates, LeastSquares(dtype=jnp.float64))
    batched = run_batch(bs, dtype=jnp.float64)
    W_batch = batched.strategy.get_weights_df()
    sim_b = simulate_strategy(batched.strategy, returns, fc=0.0, vc=0.002)
    drift = float(np.abs(np.log1p(sims["ls"]).sum() - np.log1p(sim_b).sum()))
    print(f"batched engine: {W_batch.shape[0]} dates solved in one program; "
          f"|serial - batched| cumulative log-return = {drift:.2e}")
    assert drift < 1e-6

    # Percentile (quintile) portfolios on geometric-mean momentum scores,
    # recorded per-date via append_custom, then one strategy per quantile
    # (the reference driver's second half, lines 230-270).
    percentile_backtest(data, rebdates, returns)


def percentile_backtest(data, rebdates, returns):
    from porqua_tpu import PercentilePortfolios
    from porqua_tpu.backtest import append_custom
    from porqua_tpu.estimators.mean import MeanEstimator
    from porqua_tpu.utils.helpers import output_to_strategies

    bs = make_service(
        data, rebdates,
        PercentilePortfolios(
            n_percentiles=5,
            estimator=MeanEstimator(method="geometric", n_mom=252, n_rev=21)),
    )
    bs.settings["append_fun"] = append_custom
    bs.settings["append_fun_args"] = ["w_dict"]
    bt = Backtest()
    bt.run(bs)
    per_quantile = output_to_strategies(bt.output)
    print("quintile portfolios (top minus bottom spread):")
    cums = {}
    for name, strat in per_quantile.items():
        sim = simulate_strategy(strat, returns, fc=0.0, vc=0.0)
        cums[name] = float(np.log1p(sim).sum())
    spread = cums["q1"] - cums["q5"]
    print("  " + ", ".join(f"{k}: {v:+.3f}" for k, v in cums.items())
          + f" | q1-q5 spread {spread:+.3f}")


if __name__ == "__main__":
    main()
