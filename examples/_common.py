"""Shared plumbing for the example scripts.

The reference ships its examples as Jupyter notebooks (`example/*.ipynb`)
that double as the only documentation; these scripts are their runnable
equivalents. Each script prints what it computes — run any of them with
``python examples/<name>.py``.

The examples run on the XLA CPU backend by default: they cross-check f64
parity paths, and f64 on TPU is emulated (slow), while the serial-engine
demos dispatch per date (tunnel round-trips dominate). Set
``PORQUA_PLATFORM=tpu`` to run on the accelerator (the container's
sitecustomize pins ``jax_platforms`` at the config level, so the plain
JAX_PLATFORMS env var alone is not enough — this helper handles it).
"""

from __future__ import annotations

import os
import sys

import numpy as np
import pandas as pd

# the package is used in-place, not installed (the reference's notebooks
# do the same with sys.path.insert(1, '../src'))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REFERENCE_DATA = os.environ.get("PORQUA_DATA", "/root/reference/data/")


def init_platform() -> None:
    import jax

    platform = os.environ.get("PORQUA_PLATFORM", "cpu")
    if platform != "tpu":
        jax.config.update("jax_platforms", platform)
    # the examples cross-check f64 parity paths; solver code is
    # dtype-parametric and defaults to f32 on device
    jax.config.update("jax_enable_x64", True)


def load_msci_or_synthetic():
    """The 24-country MSCI universe if the data mount exists, else a
    synthetic factor market of the same shape."""
    from porqua_tpu.data_loader import load_data_msci

    if os.path.isdir(REFERENCE_DATA):
        return load_data_msci(path=REFERENCE_DATA)
    rng = np.random.default_rng(0)
    dates = pd.bdate_range("1999-01-01", periods=6000)
    n = 24
    X = pd.DataFrame(0.01 * rng.standard_normal((len(dates), n)),
                     index=dates, columns=[f"A{i}" for i in range(n)])
    w = rng.dirichlet(np.ones(n))
    y = pd.DataFrame({"bm": X.to_numpy() @ w + 0.001 * rng.standard_normal(len(dates))},
                     index=dates)
    return {"return_series": X, "bm_series": y}


def quarterly_rebdates(index: pd.Index, start: str = "2015-01-01", k: int = 24):
    """Quarter-end rebalance dates inside the index (the reference's
    canonical cadence, ``_quick_and_dirty_interactive_testing.py:75-79``)."""
    qe = pd.Series(index=index, data=1).resample("QE").last().index
    dates = [str(index[index <= d][-1].date()) for d in qe
             if str(start) <= str(d.date()) and (index <= d).any()]
    return dates[:k]
