"""Large-universe backtest: ~500 assets, monthly rebalance, one program.

Runnable equivalent of the reference's ``example/backtest.ipynb`` (S&P
500 TR tracking over the ~489-stock USA universe, monthly rebalance,
width=252). The ``usa_returns`` blob is stripped from the reference
snapshot (``.MISSING_LARGE_BLOBS``), so both the universe and the
benchmark are a synthetic factor market at the same scale (a tracking
problem against a benchmark unrelated to the universe would be
meaningless). Reports the quantstats-style summary the notebook prints:
Sharpe, max drawdown, VaR, tracking error.

Two configurations:

* **notebook parity** — the notebook's cell-1 setup (budget + LongOnly
  box, LeastSquares) over the full universe through the batched
  one-XLA-program engine (``run_batch``).
* **filtered + turnover** — the production composition the notebook
  stops short of: a min-volume selection filter (520 raw -> ~489
  admitted) plus a turnover budget chaining consecutive dates through
  the previous portfolio. Runs through BOTH engines: the serial loop
  (per-date selection + ``prev_weights`` threading) and the device
  scan (``solve_scan_turnover``: one ``lax.scan`` whose carry is the
  holdings vector), and checks they produce the same weights.
  Golden-file regression: ``tests/test_backtest_usa.py``.
"""

import time

import numpy as np
import pandas as pd

from _common import init_platform

init_platform()

import jax.numpy as jnp  # noqa: E402

from porqua_tpu import (  # noqa: E402
    Backtest,
    BacktestService,
    LeastSquares,
    OptimizationItemBuilder,
    SelectionItemBuilder,
)
from porqua_tpu.accounting import performance_summary, simulate_strategy  # noqa: E402
from porqua_tpu.batch import (  # noqa: E402
    assemble_backtest,
    build_problems,
    run_batch,
    solve_scan_turnover,
)
from porqua_tpu.builders import (  # noqa: E402
    bibfn_bm_series,
    bibfn_box_constraints,
    bibfn_budget_constraint,
    bibfn_return_series,
    bibfn_selection_data,
    bibfn_selection_min_volume,
    bibfn_turnover_constraint,
)

N_RAW = 520      # raw synthetic universe
N_ASSETS = 489   # the reference USA universe size (usa_features.parquet)
MIN_VOLUME = 1e6


def synthetic_usa(n_days=1500, n_assets=N_RAW, seed=7):
    """Synthetic factor market + volumes at the reference's USA scale.

    The first ``N_ASSETS`` names carry liquid volumes comfortably above
    the example's floor; the remaining ``N_RAW - N_ASSETS`` sit well
    below it, so the min-volume filter reproduces the notebook's ~489
    universe. (A name drifting across the floor mid-backtest is handled
    by the serial engine per-date; the device scan masks exits with
    lb = ub = 0 instead of reshaping — see batch._require_fixed_universe.)
    """
    rng = np.random.default_rng(seed)
    dates = pd.bdate_range("2018-01-01", periods=n_days)
    k = 10  # common factors
    B = 0.5 + 0.5 * rng.random((n_assets, k))
    F = 0.008 * rng.standard_normal((n_days, k))
    eps = 0.01 * rng.standard_normal((n_days, n_assets))
    X = pd.DataFrame(F @ B.T + eps, index=dates,
                     columns=[f"S{i:04d}" for i in range(n_assets)])
    base = np.where(np.arange(n_assets) < N_ASSETS, 10.0, 0.2) * MIN_VOLUME
    noise = rng.lognormal(sigma=0.3, size=(n_days, n_assets))
    V = pd.DataFrame(base * noise, index=dates, columns=X.columns)
    return X, V


def common_opt_builders(width=252, upper=0.05):
    return {
        "returns": OptimizationItemBuilder(bibfn=bibfn_return_series, width=width),
        "bm": OptimizationItemBuilder(bibfn=bibfn_bm_series, width=width, align=True),
        "budget": OptimizationItemBuilder(bibfn=bibfn_budget_constraint),
        "box": OptimizationItemBuilder(bibfn=bibfn_box_constraints, upper=upper),
    }


def main():
    X, V = synthetic_usa()
    # cap-weight-style composite of the universe itself, like SPTR over
    # the real USA stocks in the notebook
    w = np.random.default_rng(0).dirichlet(np.ones(X.shape[1]) * 5.0)
    bm = pd.DataFrame({"SPTR": X.to_numpy() @ w}, index=X.index)

    me = pd.Series(index=X.index, data=1).resample("ME").last().index
    rebdates = [str(X.index[X.index <= d][-1].date()) for d in me][13:-1]
    print(f"universe {X.shape[1]} raw assets x {X.shape[0]} days, "
          f"{len(rebdates)} monthly rebalances, width 252")

    # ------------------------------------------------------------------
    # Configuration 1: the notebook's setup through the batched engine.
    # ------------------------------------------------------------------
    bs = BacktestService(
        data={"return_series": X, "bm_series": bm},
        selection_item_builders={
            "data": SelectionItemBuilder(bibfn=bibfn_selection_data),
        },
        optimization_item_builders=common_opt_builders(),
        optimization=LeastSquares(),
        settings={"rebdates": rebdates, "quiet": True},
    )

    # f32 on device: loose in-loop tolerance + LU polish (the f32 recipe
    # bench.py uses — pushing f32 ADMM to 1e-6 stalls at the residual
    # floor while the polish already lands on the active set)
    from porqua_tpu.qp import SolverParams

    t0 = time.perf_counter()
    bt = run_batch(bs, params=SolverParams(eps_abs=1e-3, eps_rel=1e-3))
    wall = time.perf_counter() - t0
    stats = bt.output["batch"]
    print(f"[notebook parity] solved "
          f"{int((stats['status'] == 1).sum())}/{len(rebdates)} "
          f"dates in {wall:.2f}s (build + one XLA program)")

    sim = simulate_strategy(bt.strategy, X, fc=0.0, vc=0.001)
    perf = performance_summary(sim, benchmark=bm.iloc[:, 0])
    print(f"  Sharpe {perf['sharpe']:.2f} | "
          f"max drawdown {perf['max_drawdown']:.2%} | "
          f"daily VaR(95) {perf['var_95']:.4f} | "
          f"tracking error {perf['tracking_error']:.4f}")

    # ------------------------------------------------------------------
    # Configuration 2: min-volume selection filter + turnover budget.
    # ------------------------------------------------------------------
    turnover_budget = 0.25

    def filtered_service():
        return BacktestService(
            data={"return_series": X, "bm_series": bm, "volume_series": V},
            selection_item_builders={
                "volume": SelectionItemBuilder(
                    bibfn=bibfn_selection_min_volume, width=90,
                    min_volume=MIN_VOLUME),
            },
            optimization_item_builders={
                **common_opt_builders(),
                "turnover": OptimizationItemBuilder(
                    bibfn=bibfn_turnover_constraint,
                    turnover_budget=turnover_budget),
            },
            # Small ridge: with N ~ 489 assets against a 252-row window
            # the Gram objective is rank-deficient (n > T), so the
            # minimizer is a whole affine set and two solvers can land
            # on different optima; l2_penalty pins a unique one (and is
            # standard practice at this shape).
            optimization=LeastSquares(dtype=jnp.float64, l2_penalty=1e-4),
            settings={"rebdates": rebdates, "quiet": True},
        )

    # Pre-backtest holdings: equal weight over the initially-admitted
    # set (a cash start is infeasible under sum w = 1 + turnover < 1).
    bs_probe = filtered_service()
    bs_probe.prepare_rebalancing(rebalancing_date=rebdates[0])
    universe = list(bs_probe.optimization.constraints.selection)
    w0 = {a: 1.0 / len(universe) for a in universe}
    print(f"[filtered + turnover] min-volume filter admits "
          f"{len(universe)}/{X.shape[1]} assets; "
          f"turnover budget {turnover_budget}")

    tight = SolverParams(eps_abs=1e-8, eps_rel=1e-8)

    # Serial engine: per-date selection, prev_weights threaded by the
    # loop (reference backtest.py:201-224 semantics). Cross-checked on
    # the first 12 rebalances only — the turnover chain over a shared
    # date prefix is identical, and the serial loop at this scale is
    # ~10 s/date on the CPU host (the full-calendar serial/scan parity
    # lives in tests/test_backtest_usa.py).
    n_check = min(12, len(rebdates))
    bs_serial = filtered_service()
    bs_serial.settings["rebdates"] = rebdates[:n_check]
    bs_serial.settings["prev_weights"] = dict(w0)
    bs_serial.optimization.params.update(tight.__dict__)
    t0 = time.perf_counter()
    bt_serial = Backtest()
    bt_serial.run(bs_serial)
    t_serial = time.perf_counter() - t0

    # Device scan engine: problems built once (placeholder x0), then one
    # lax.scan carrying the holdings vector through the lifted turnover
    # rows with warm starts.
    bs_scan = filtered_service()
    bs_scan.settings["prev_weights"] = dict(w0)
    t0 = time.perf_counter()
    problems = build_problems(bs_scan, dtype=jnp.float64)
    w_init = np.array([w0.get(a, 0.0) for a in problems.universes[0]])
    sols = solve_scan_turnover(
        problems.qp, n_assets=len(problems.universes[0]), row_start=1,
        w_init=jnp.asarray(w_init), params=tight,
        universes=problems.universes)
    bt_scan = assemble_backtest(problems, sols)
    t_scan = time.perf_counter() - t0

    # The two engines must agree date by date (over the checked prefix).
    # skipna=False + np.maximum: a missing asset's NaN propagates into
    # max_dw and fails the finiteness assert (pandas' default max()
    # would skip it, and builtin max() discards NaN).
    max_dw = 0.0
    for date in rebdates[:n_check]:
        ws = pd.Series(bt_serial.strategy.get_weights(date))
        wb = pd.Series(bt_scan.strategy.get_weights(date))
        d = (wb.reindex(ws.index) - ws).abs().max(skipna=False)
        max_dw = float(np.maximum(max_dw, d))
    print(f"  serial {t_serial:.1f}s/{n_check} dates vs scan "
          f"{t_scan:.1f}s/{len(rebdates)} dates (incl. compile); "
          f"max |dw| serial-vs-scan {max_dw:.2e} over {n_check} dates")
    # 5e-4 = the ridge-conditioning bound, see tests/test_backtest_usa.py
    # — this example is part of the examples regression gate, so the
    # parity claim must be an assertion, not a printout.
    assert np.isfinite(max_dw) and max_dw < 5e-4, max_dw

    sim_to = simulate_strategy(bt_scan.strategy, X, fc=0.0, vc=0.001)
    perf_to = performance_summary(sim_to, benchmark=bm.iloc[:, 0])
    wdf = bt_scan.strategy.get_weights_df().fillna(0.0)
    realized = wdf.diff().abs().sum(axis=1).iloc[1:]
    print(f"  Sharpe {perf_to['sharpe']:.2f} | "
          f"max drawdown {perf_to['max_drawdown']:.2%} | "
          f"tracking error {perf_to['tracking_error']:.4f} | "
          f"realized turnover median {realized.median():.3f} "
          f"(budget {turnover_budget})")


if __name__ == "__main__":
    main()
