"""Large-universe backtest: ~500 assets, monthly rebalance, one program.

Runnable equivalent of the reference's ``example/backtest.ipynb`` (S&P
500 TR tracking over the ~489-stock USA universe, monthly rebalance,
width=252). The ``usa_returns`` blob is stripped from the reference
snapshot (``.MISSING_LARGE_BLOBS``), so both the universe and the
benchmark are a synthetic factor market at the same scale (a tracking
problem against a benchmark unrelated to the universe would be
meaningless). Reports the quantstats-style summary the notebook prints:
Sharpe, max drawdown, VaR, tracking error.
"""

import time

import numpy as np
import pandas as pd

from _common import init_platform

init_platform()

import jax.numpy as jnp  # noqa: E402

from porqua_tpu import (  # noqa: E402
    BacktestService,
    LeastSquares,
    OptimizationItemBuilder,
    SelectionItemBuilder,
)
from porqua_tpu.accounting import performance_summary, simulate_strategy  # noqa: E402
from porqua_tpu.batch import run_batch  # noqa: E402
from porqua_tpu.builders import (  # noqa: E402
    bibfn_bm_series,
    bibfn_box_constraints,
    bibfn_budget_constraint,
    bibfn_return_series,
    bibfn_selection_data,
)

N_ASSETS = 489  # the reference USA universe size (usa_features.parquet)


def synthetic_usa(n_days=1500, n_assets=N_ASSETS, seed=7):
    rng = np.random.default_rng(seed)
    dates = pd.bdate_range("2018-01-01", periods=n_days)
    k = 10  # common factors
    B = 0.5 + 0.5 * rng.random((n_assets, k))
    F = 0.008 * rng.standard_normal((n_days, k))
    eps = 0.01 * rng.standard_normal((n_days, n_assets))
    X = pd.DataFrame(F @ B.T + eps, index=dates,
                     columns=[f"S{i:04d}" for i in range(n_assets)])
    return X


def main():
    X = synthetic_usa()
    # cap-weight-style composite of the universe itself, like SPTR over
    # the real USA stocks in the notebook
    w = np.random.default_rng(0).dirichlet(np.ones(X.shape[1]) * 5.0)
    bm = pd.DataFrame({"SPTR": X.to_numpy() @ w}, index=X.index)

    me = pd.Series(index=X.index, data=1).resample("ME").last().index
    rebdates = [str(X.index[X.index <= d][-1].date()) for d in me][13:-1]
    print(f"universe {X.shape[1]} assets x {X.shape[0]} days, "
          f"{len(rebdates)} monthly rebalances, width 252")

    bs = BacktestService(
        data={"return_series": X, "bm_series": bm},
        selection_item_builders={
            "data": SelectionItemBuilder(bibfn=bibfn_selection_data),
        },
        optimization_item_builders={
            "returns": OptimizationItemBuilder(bibfn=bibfn_return_series, width=252),
            "bm": OptimizationItemBuilder(bibfn=bibfn_bm_series, width=252, align=True),
            "budget": OptimizationItemBuilder(bibfn=bibfn_budget_constraint),
            "box": OptimizationItemBuilder(bibfn=bibfn_box_constraints, upper=0.05),
        },
        optimization=LeastSquares(),
        settings={"rebdates": rebdates, "quiet": True},
    )

    # f32 on device: loose in-loop tolerance + LU polish (the f32 recipe
    # bench.py uses — pushing f32 ADMM to 1e-6 stalls at the residual
    # floor while the polish already lands on the active set)
    from porqua_tpu.qp import SolverParams

    t0 = time.perf_counter()
    bt = run_batch(bs, params=SolverParams(eps_abs=1e-3, eps_rel=1e-3))
    wall = time.perf_counter() - t0
    stats = bt.output["batch"]
    print(f"solved {int((stats['status'] == 1).sum())}/{len(rebdates)} "
          f"dates in {wall:.2f}s (build + one XLA program)")

    sim = simulate_strategy(bt.strategy, X, fc=0.0, vc=0.001)
    perf = performance_summary(sim, benchmark=bm.iloc[:, 0])
    print(f"Sharpe {perf['sharpe']:.2f} | "
          f"max drawdown {perf['max_drawdown']:.2%} | "
          f"daily VaR(95) {perf['var_95']:.4f} | "
          f"tracking error {perf['tracking_error']:.4f}")


if __name__ == "__main__":
    main()
