"""Declarative portfolio-constraint container and canonicalization.

Covers the same capability surface as the reference's constraints layer
(``/root/reference/src/constraints.py``: budget, box, linear rows with
``=``/``<=``/``>=`` senses, symbolic L1 terms) but with a different
internal architecture, designed for the TPU lowering path:

every linear constraint is stored as one *interval row*
``lower <= a . x <= upper`` from the moment it is added. Equalities are
rows with ``lower == upper``; one-sided inequalities have an infinite
bound. This is exactly the row form the batched device solver consumes
(:class:`~porqua_tpu.qp.canonical.CanonicalQP` interval form), so the
TPU lowering :meth:`Constraints.to_canonical` is a direct stack of the
stored rows — no sense bookkeeping, no sign flipping at solve time.

The reference's standard form ``G x <= h`` / ``A x = b`` is kept as a
*view* (:meth:`Constraints.to_GhAb`) for API parity and for the ported
shape-contract tests; it is derived from the interval rows on demand.

Everything here is pandas/numpy; nothing is traced. This is the host
side of the host-build / device-solve split.
"""

from __future__ import annotations

import math
import warnings
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np
import pandas as pd

_INF = float("inf")


def match_arg(x, lst):
    """First element of ``lst`` containing ``x`` as a substring (the
    R-style partial matching the reference DSL exposes)."""
    for candidate in lst:
        if x in candidate:
            return candidate
    raise ValueError(f"{x!r} does not match any of {lst}")


def box_constraint(box_type: str = "LongOnly", lower=None, upper=None) -> dict:
    """Resolve box-type defaults into concrete lower/upper values.

    Same semantics as the reference helper (``constraints.py:178-204``):
    Unbounded -> (-inf, inf), LongShort -> (-1, 1), LongOnly -> (0, 1),
    with caller-supplied values taking precedence and LongOnly rejecting
    negative lower bounds.
    """
    kind = match_arg(box_type, ["LongOnly", "LongShort", "Unbounded"])
    defaults = {"Unbounded": (-_INF, _INF), "LongShort": (-1, 1),
                "LongOnly": (0, 1)}
    dlo, dhi = defaults[kind]

    if kind == "LongOnly":
        if lower is not None:
            bad = (lower < 0) if np.isscalar(lower) else any(
                v < 0 for v in lower)
            if bad:
                raise ValueError(
                    "LongOnly boxes need nonnegative lower bounds; use "
                    "box_type='LongShort' to allow short positions.")
            if upper is None:
                upper = lower * 0 + 1 if not np.isscalar(lower) else 1
        elif upper is not None:
            lower = upper * 0 if not np.isscalar(upper) else 0

    lower = dlo if lower is None else lower
    upper = dhi if upper is None else upper
    return {"box_type": kind, "lower": lower, "upper": upper}


def linear_constraint(Amat=None, sense: str = "=", rhs=_INF,
                      index_or_name=None, a_values=None) -> dict:
    """Plain-dict linear-constraint record (reference API parity,
    ``constraints.py:206-218``)."""
    out = {"Amat": Amat, "sense": sense, "rhs": rhs}
    if index_or_name is not None:
        out["index_or_name"] = index_or_name
    if a_values is not None:
        out["a_values"] = a_values
    return out


def _interval_from_sense(sense: str, rhs: float):
    """Map a (sense, rhs) pair onto the interval [lower, upper]."""
    if sense == "=":
        return float(rhs), float(rhs)
    if sense == "<=":
        return -_INF, float(rhs)
    if sense == ">=":
        return float(rhs), _INF
    raise ValueError(f"unknown constraint sense {sense!r}")


@dataclass
class IntervalRow:
    """One stored constraint row: ``lower <= coeffs . x <= upper``."""

    coeffs: pd.Series           # aligned to the selection, zeros filled
    lower: float
    upper: float
    name: str = ""

    @property
    def is_equality(self) -> bool:
        return self.lower == self.upper


@dataclass
class _Box:
    """Per-variable bounds; ``kind == 'NA'`` means not configured."""

    kind: str = "NA"
    lower: Optional[pd.Series] = None
    upper: Optional[pd.Series] = None


class Constraints:
    """Constraint set for one asset universe.

    Same builder surface as the reference DSL (``add_budget``,
    ``add_box``, ``add_linear``, ``add_l1``, ``to_GhAb``) plus the
    TPU-native lowerings (``interval_rows``, ``bounds``,
    ``to_canonical``). Internally everything is interval rows — see the
    module docstring.
    """

    def __init__(self, selection="NA") -> None:
        for item in selection:
            if not isinstance(item, str):
                raise ValueError(
                    "'selection' must be an iterable of asset-name strings")
        self.selection = selection
        self._budget: Optional[IntervalRow] = None
        self._rows: List[IntervalRow] = []
        self._box = _Box()
        self.l1: Dict[str, dict] = {}

    def __str__(self) -> str:
        parts = [f"selection: {list(self.selection)}",
                 f"budget: {self.budget}", f"box: {self.box}"]
        parts += [f"row[{r.name}]: {r.lower} <= {dict(r.coeffs)} <= "
                  f"{r.upper}" for r in self._rows]
        parts += [f"l1[{k}]: {v}" for k, v in self.l1.items()]
        return "\n".join(parts)

    # ------------------------------------------------------------------
    # Reference-compatible dict views
    # ------------------------------------------------------------------

    @property
    def budget(self) -> dict:
        if self._budget is None:
            return {"Amat": None, "sense": None, "rhs": None}
        row = self._budget
        if row.is_equality:
            sense, rhs = "=", row.upper
        elif math.isfinite(row.upper):
            sense, rhs = "<=", row.upper
        else:
            sense, rhs = ">=", row.lower
        return {"Amat": row.coeffs, "sense": sense, "rhs": rhs}

    @property
    def box(self) -> dict:
        return {"box_type": self._box.kind, "lower": self._box.lower,
                "upper": self._box.upper}

    @property
    def linear(self) -> dict:
        if not self._rows:
            return {"Amat": None, "sense": None, "rhs": None}
        senses, rhs = [], []
        for r in self._rows:
            if r.is_equality:
                senses.append("=")
                rhs.append(r.upper)
            elif math.isfinite(r.upper):
                senses.append("<=")
                rhs.append(r.upper)
            else:
                senses.append(">=")
                rhs.append(r.lower)
        Amat = pd.DataFrame([r.coeffs for r in self._rows])
        Amat.index = [r.name for r in self._rows]
        return {"Amat": Amat, "sense": pd.Series(senses, index=Amat.index),
                "rhs": pd.Series(rhs, index=Amat.index)}

    # ------------------------------------------------------------------
    # Builders
    # ------------------------------------------------------------------

    def _aligned(self, values) -> pd.Series:
        """Coerce coefficients to a float Series over the selection."""
        s = pd.Series(values, dtype=float) if not isinstance(
            values, pd.Series) else values.astype(float)
        return s.reindex(list(self.selection)).fillna(0.0)

    def add_budget(self, rhs=1, sense: str = "=") -> None:
        if self._budget is not None:
            warnings.warn("replacing the existing budget constraint")
        ones = pd.Series(1.0, index=list(self.selection))
        lo, hi = _interval_from_sense(sense, rhs)
        self._budget = IntervalRow(ones, lo, hi, name="budget")

    def add_box(self, box_type: str = "LongOnly", lower=None,
                upper=None) -> None:
        spec = box_constraint(box_type, lower, upper)
        idx = list(self.selection)
        lb = spec["lower"]
        ub = spec["upper"]
        lb = pd.Series(float(lb), index=idx) if np.isscalar(lb) \
            else pd.Series(lb, index=idx, dtype=float)
        ub = pd.Series(float(ub), index=idx) if np.isscalar(ub) \
            else pd.Series(ub, index=idx, dtype=float)
        if (ub < lb).any():
            raise ValueError(
                "box upper bounds must not be below the lower bounds")
        self._box = _Box(spec["box_type"], lb, ub)

    def add_linear(self,
                   Amat: Optional[pd.DataFrame] = None,
                   a_values: Optional[pd.Series] = None,
                   sense="=",
                   rhs=None,
                   name: Optional[str] = None) -> None:
        """Append one or more rows. ``Amat`` is a (rows x assets) frame;
        alternatively a single row via ``a_values``. ``sense``/``rhs``
        may be scalars (broadcast) or Series aligned to the rows."""
        if Amat is None:
            if a_values is None:
                raise ValueError("provide 'Amat' or 'a_values'")
            Amat = pd.DataFrame(
                [self._aligned(a_values)],
                index=[name if name is not None else len(self._rows)])

        n_rows = Amat.shape[0]
        senses = list(sense) if not isinstance(sense, str) else [sense] * n_rows
        rhss = [rhs] * n_rows if np.isscalar(rhs) or rhs is None else list(rhs)
        for i in range(n_rows):
            lo, hi = _interval_from_sense(senses[i], rhss[i])
            self._rows.append(IntervalRow(
                self._aligned(Amat.iloc[i]), lo, hi, name=str(Amat.index[i])))

    def add_l1(self, name: str, rhs=None, x0=None, *args, **kwargs) -> None:
        """Record an L1 term symbolically (turnover / leverage).

        The solve path consumes these either via static-shape
        linearization (:mod:`porqua_tpu.qp.lift`) or as prox terms in
        the ADMM solver — never as expanded rows here, so shapes stay
        static across a backtest.
        """
        if rhs is None:
            raise TypeError("add_l1 needs an 'rhs' budget value")
        record = dict(kwargs)
        record["rhs"] = rhs
        if x0:
            record["x0"] = x0
        for i, extra in enumerate(args):
            record[f"arg{i}"] = extra
        self.l1[name] = record

    # ------------------------------------------------------------------
    # Lowerings
    # ------------------------------------------------------------------

    def _ordered_rows(self) -> List[IntervalRow]:
        """Budget first, then user rows in insertion order."""
        rows = [self._budget] if self._budget is not None else []
        return rows + self._rows

    def interval_rows(self):
        """Stack all rows as ``(C, l, u)`` numpy arrays, equalities
        first (then inequalities), preserving insertion order within
        each group. This is the direct input to the device solver."""
        n = len(self.selection)
        rows = self._ordered_rows()
        eq = [r for r in rows if r.is_equality]
        ineq = [r for r in rows if not r.is_equality]
        ordered = eq + ineq
        if not ordered:
            return (np.zeros((0, n)), np.zeros((0,)), np.zeros((0,)))
        C = np.stack([r.coeffs.to_numpy() for r in ordered])
        l = np.array([r.lower for r in ordered])
        u = np.array([r.upper for r in ordered])
        return C, l, u

    def bounds(self):
        """Per-variable ``(lb, ub)`` numpy arrays (±inf when no box)."""
        n = len(self.selection)
        if self._box.kind == "NA":
            return np.full(n, -_INF), np.full(n, _INF)
        return (self._box.lower.to_numpy(dtype=float),
                self._box.upper.to_numpy(dtype=float))

    def to_GhAb(self, lbub_to_G: bool = False) -> Dict[str, Optional[np.ndarray]]:
        """Standard-form view ``{'G','h','A','b'}``: equality rows in
        ``A x = b``, everything else as ``G x <= h`` (lower-bounded rows
        negated). Row order matches the reference contract: budget, then
        (optionally) box rows as ``[-I; I]``, then user rows."""
        n = len(self.selection)
        A_rows, b_vals, G_rows, h_vals = [], [], [], []

        def lower_one(row: IntervalRow):
            a = row.coeffs.to_numpy()
            if row.is_equality:
                A_rows.append(a)
                b_vals.append(row.upper)
            elif math.isfinite(row.upper):
                G_rows.append(a)
                h_vals.append(row.upper)
            else:
                G_rows.append(-a)
                h_vals.append(-row.lower)

        if self._budget is not None:
            lower_one(self._budget)
        if lbub_to_G:
            lb, ub = self.bounds()
            eye = np.eye(n)
            G_rows.extend(-eye)
            h_vals.extend(-lb)
            G_rows.extend(eye)
            h_vals.extend(ub)
        for row in self._rows:
            lower_one(row)

        out: Dict[str, Optional[np.ndarray]] = {
            "G": None, "h": None, "A": None, "b": None}
        if A_rows:
            out["A"] = np.stack(A_rows).reshape(-1, n)
            out["b"] = np.asarray(b_vals, dtype=float)
        if G_rows:
            out["G"] = np.stack(G_rows).reshape(-1, n)
            out["h"] = np.asarray(h_vals, dtype=float)
        return out

    def to_canonical(self,
                     P: Optional[np.ndarray] = None,
                     q: Optional[np.ndarray] = None,
                     constant: float = 0.0,
                     n_max: Optional[int] = None,
                     m_max: Optional[int] = None):
        """Lower constraints (+ optional objective) to a padded
        :class:`~porqua_tpu.qp.canonical.CanonicalQP`.

        A direct stack of the stored interval rows: no sense handling
        happens here because none was stored. Rows are padded to
        ``m_max`` and variables to ``n_max`` so per-date problems of
        differing universe size batch into one device array.
        """
        from porqua_tpu.qp.canonical import CanonicalQP

        n = len(self.selection)
        C, l, u = self.interval_rows()
        lb, ub = self.bounds()
        return CanonicalQP.build(
            P=np.zeros((n, n)) if P is None else np.asarray(P, dtype=float),
            q=np.zeros(n) if q is None else np.asarray(q, dtype=float),
            C=C, l=l, u=u, lb=lb, ub=ub,
            constant=float(constant),
            n_max=n_max, m_max=m_max,
        )
