"""Declarative portfolio-constraint container and canonicalization.

Host-side mirror of the reference's constraints DSL
(``/root/reference/src/constraints.py``): budget (eq/ineq), box
(LongOnly / LongShort / Unbounded), arbitrary linear rows with
``=``/``<=``/``>=`` senses, and symbolic L1 constraints (turnover,
leverage). Two lowerings are provided:

* :meth:`Constraints.to_GhAb` — the reference's standard-form output
  ``G x <= h``, ``A x = b`` (``constraints.py:114-167``), kept for API
  parity and the shape-contract unit tests.
* :meth:`Constraints.to_canonical` — the TPU-native lowering to a
  *static-shape* :class:`~porqua_tpu.qp.canonical.CanonicalQP`: rows are
  padded to a fixed count with +/-inf bounds so a whole backtest of
  per-date problems stacks into one batched device array.

Everything here is pandas/numpy; nothing is traced. This is the host
side of the host-build / device-solve split.
"""

from __future__ import annotations

import warnings
from typing import Dict, Optional

import numpy as np
import pandas as pd


def match_arg(x, lst):
    """First element of ``lst`` containing ``x`` (R-style partial matching,
    reference ``constraints.py:175``)."""
    matches = [el for el in lst if x in el]
    if not matches:
        raise ValueError(f"{x!r} does not match any of {lst}")
    return matches[0]


def box_constraint(box_type: str = "LongOnly", lower=None, upper=None) -> dict:
    """Resolve box-type defaults (reference ``constraints.py:178-204``)."""
    box_type = match_arg(box_type, ["LongOnly", "LongShort", "Unbounded"])

    if box_type == "Unbounded":
        lower = float("-inf") if lower is None else lower
        upper = float("inf") if upper is None else upper
    elif box_type == "LongShort":
        lower = -1 if lower is None else lower
        upper = 1 if upper is None else upper
    else:  # LongOnly
        if lower is None:
            if upper is None:
                lower, upper = 0, 1
            else:
                lower = upper * 0
        else:
            if not np.isscalar(lower) and any(l < 0 for l in lower):
                raise ValueError(
                    "Inconsistent lower bounds for box_type 'LongOnly'. "
                    "Change box_type to LongShort or ensure that lower >= 0."
                )
            upper = lower * 0 + 1 if upper is None else upper

    return {"box_type": box_type, "lower": lower, "upper": upper}


def linear_constraint(Amat=None, sense: str = "=", rhs=float("inf"),
                      index_or_name=None, a_values=None) -> dict:
    """Plain-dict linear-constraint record (reference ``constraints.py:206-218``)."""
    ans = {"Amat": Amat, "sense": sense, "rhs": rhs}
    if index_or_name is not None:
        ans["index_or_name"] = index_or_name
    if a_values is not None:
        ans["a_values"] = a_values
    return ans


class Constraints:
    """Constraint container for one asset universe (``selection``).

    API-compatible with the reference class (``constraints.py:23-167``):
    ``add_budget``, ``add_box``, ``add_linear``, ``add_l1``, ``to_GhAb``.
    """

    def __init__(self, selection="NA") -> None:
        if not all(isinstance(item, str) for item in selection):
            raise ValueError("argument 'selection' has to be a character vector.")
        self.selection = selection
        self.budget = {"Amat": None, "sense": None, "rhs": None}
        self.box = {"box_type": "NA", "lower": None, "upper": None}
        self.linear = {"Amat": None, "sense": None, "rhs": None}
        self.l1 = {}

    def __str__(self) -> str:
        return " ".join(f"\n{key}:\n\n{vars(self)[key]}\n" for key in vars(self))

    # ------------------------------------------------------------------
    # Builders
    # ------------------------------------------------------------------

    def add_budget(self, rhs=1, sense: str = "=") -> None:
        if self.budget.get("rhs") is not None:
            warnings.warn("Existing budget constraint is overwritten\n")
        a_values = pd.Series(np.ones(len(self.selection)), index=self.selection)
        self.budget = {"Amat": a_values, "sense": sense, "rhs": rhs}

    def add_box(self, box_type: str = "LongOnly", lower=None, upper=None) -> None:
        boxcon = box_constraint(box_type, lower, upper)
        if np.isscalar(boxcon["lower"]):
            boxcon["lower"] = pd.Series(
                np.full(len(self.selection), float(boxcon["lower"])), index=self.selection
            )
        if np.isscalar(boxcon["upper"]):
            boxcon["upper"] = pd.Series(
                np.full(len(self.selection), float(boxcon["upper"])), index=self.selection
            )
        if (boxcon["upper"] < boxcon["lower"]).any():
            raise ValueError("Some lower bounds are higher than the corresponding upper bounds.")
        self.box = boxcon

    def add_linear(self,
                   Amat: Optional[pd.DataFrame] = None,
                   a_values: Optional[pd.Series] = None,
                   sense="=",
                   rhs=None,
                   name: Optional[str] = None) -> None:
        if Amat is None:
            if a_values is None:
                raise ValueError("Either 'Amat' or 'a_values' must be provided.")
            Amat = pd.DataFrame(a_values).T.reindex(columns=self.selection).fillna(0)
            if name is not None:
                Amat.index = [name]

        if isinstance(sense, str):
            sense = pd.Series([sense])
        if isinstance(rhs, (int, float)):
            rhs = pd.Series([rhs])

        if self.linear["Amat"] is not None:
            Amat = pd.concat([self.linear["Amat"], Amat], axis=0, ignore_index=False)
            sense = pd.concat([self.linear["sense"], sense], axis=0, ignore_index=False)
            rhs = pd.concat([self.linear["rhs"], rhs], axis=0, ignore_index=False)

        Amat = Amat.fillna(0)
        self.linear = {"Amat": Amat, "sense": sense, "rhs": rhs}

    def add_l1(self, name: str, rhs=None, x0=None, *args, **kwargs) -> None:
        """Record an L1 constraint symbolically (turnover / leverage).

        Mirror of reference ``constraints.py:97-112``. The TPU solve path
        consumes these either through static-shape linearization
        (:mod:`porqua_tpu.qp.lift`) or as prox terms in the ADMM solver.
        """
        if rhs is None:
            raise TypeError("argument 'rhs' is required.")
        con = {"rhs": rhs}
        if x0:
            con["x0"] = x0
        for i, arg in enumerate(args):
            con[f"arg{i}"] = arg
        con.update(kwargs)
        self.l1[name] = con

    # ------------------------------------------------------------------
    # Lowerings
    # ------------------------------------------------------------------

    def to_GhAb(self, lbub_to_G: bool = False) -> Dict[str, Optional[np.ndarray]]:
        """Standard form ``{'G','h','A','b'}`` with all inequalities as ``<=``.

        Reference-parity output (``constraints.py:114-167``) including the
        row ordering: budget first, then (optionally) box-as-G rows, then
        user linear rows split into equalities and inequalities with
        ``>=`` rows sign-flipped.
        """
        A = b = G = h = None

        if self.budget["Amat"] is not None:
            if self.budget["sense"] == "=":
                A = np.asarray(self.budget["Amat"], dtype=float)
                b = np.array(self.budget["rhs"], dtype=float)
            else:
                G = np.asarray(self.budget["Amat"], dtype=float)
                h = np.array(self.budget["rhs"], dtype=float)

        if lbub_to_G:
            eye = np.eye(len(self.selection))
            G_tmp = np.concatenate((-eye, eye), axis=0)
            h_tmp = np.concatenate(
                (-np.asarray(self.box["lower"], dtype=float),
                 np.asarray(self.box["upper"], dtype=float))
            )
            G = np.vstack((G, G_tmp)) if G is not None else G_tmp
            h = np.concatenate((h, h_tmp), axis=None) if h is not None else h_tmp

        if self.linear["Amat"] is not None:
            Amat = self.linear["Amat"].copy()
            rhs = self.linear["rhs"].copy()

            idx_geq = np.asarray(self.linear["sense"] == ">=")
            if idx_geq.sum() > 0:
                Amat[idx_geq] = -Amat[idx_geq]
                rhs[idx_geq] = -rhs[idx_geq]

            G_tmp = h_tmp = None
            idx_eq = np.asarray(self.linear["sense"] == "=")
            if idx_eq.sum() > 0:
                A_tmp = Amat[idx_eq].to_numpy()
                b_tmp = rhs[idx_eq].to_numpy()
                A = np.vstack((A, A_tmp)) if A is not None else A_tmp
                b = np.concatenate((b, b_tmp), axis=None) if b is not None else b_tmp
                if idx_eq.sum() < Amat.shape[0]:
                    G_tmp = Amat[~idx_eq].to_numpy()
                    h_tmp = rhs[~idx_eq].to_numpy()
            else:
                G_tmp = Amat.to_numpy()
                h_tmp = rhs.to_numpy()

            if G_tmp is not None:
                G = np.vstack((G, G_tmp)) if G is not None else G_tmp
                h = np.concatenate((h, h_tmp), axis=None) if h is not None else h_tmp

        A = A.reshape(-1, A.shape[-1]) if A is not None else None
        G = G.reshape(-1, G.shape[-1]) if G is not None else None
        return {"G": G, "h": h, "A": A, "b": b}

    def to_canonical(self,
                     P: Optional[np.ndarray] = None,
                     q: Optional[np.ndarray] = None,
                     constant: float = 0.0,
                     n_max: Optional[int] = None,
                     m_max: Optional[int] = None):
        """Lower constraints (+ optional objective) to a padded CanonicalQP.

        All row types collapse into interval form ``l <= Cx <= u`` (eq
        rows have ``l == u``); the box becomes per-variable ``lb/ub``.
        Rows are padded to ``m_max`` and variables to ``n_max`` so that
        per-date problems of differing active-universe size batch into
        one array. See :class:`porqua_tpu.qp.canonical.CanonicalQP`.
        """
        from porqua_tpu.qp.canonical import CanonicalQP

        n = len(self.selection)
        GhAb = self.to_GhAb()

        rows, lo, hi = [], [], []
        if GhAb["A"] is not None:
            rows.append(GhAb["A"])
            lo.append(np.atleast_1d(GhAb["b"]))
            hi.append(np.atleast_1d(GhAb["b"]))
        if GhAb["G"] is not None:
            rows.append(GhAb["G"])
            lo.append(np.full(GhAb["G"].shape[0], -np.inf))
            hi.append(np.atleast_1d(GhAb["h"]))

        C = np.concatenate(rows, axis=0) if rows else np.zeros((0, n))
        l = np.concatenate(lo) if lo else np.zeros((0,))
        u = np.concatenate(hi) if hi else np.zeros((0,))

        if self.box["box_type"] != "NA":
            lb = np.asarray(self.box["lower"], dtype=float)
            ub = np.asarray(self.box["upper"], dtype=float)
        else:
            lb = np.full(n, -np.inf)
            ub = np.full(n, np.inf)

        if P is None:
            P = np.zeros((n, n))
        if q is None:
            q = np.zeros(n)

        return CanonicalQP.build(
            P=np.asarray(P, dtype=float),
            q=np.asarray(q, dtype=float),
            C=C, l=l, u=u, lb=lb, ub=ub,
            constant=float(constant),
            n_max=n_max, m_max=m_max,
        )
