"""Portfolio & strategy accounting (mirror of reference ``src/portfolio.py``).

Host-side API parity: ``Portfolio`` (rebalancing date + weights dict),
``Strategy`` (list of portfolios, turnover, simulate), and the
``floating_weights`` drift helper. The device-side vectorized return
engine — the whole simulation as one XLA program over (dates x assets)
— lives in :mod:`porqua_tpu.accounting`; ``Strategy.simulate`` here
keeps the reference's pandas semantics and is the golden reference the
vectorized engine is tested against.
"""

from __future__ import annotations

import numpy as np
import pandas as pd


class Portfolio:

    def __init__(self,
                 rebalancing_date: str = None,
                 weights: dict = {},
                 name: str = None,
                 init_weights: dict = {}):
        self.rebalancing_date = rebalancing_date
        self.weights = weights
        self.name = name
        self.init_weights = init_weights

    @staticmethod
    def empty() -> "Portfolio":
        return Portfolio()

    @property
    def weights(self):
        return self._weights

    @weights.setter
    def weights(self, new_weights: dict):
        if not isinstance(new_weights, dict):
            if hasattr(new_weights, "to_dict"):
                new_weights = new_weights.to_dict()
            else:
                raise TypeError("weights must be a dictionary")
        self._weights = new_weights

    def get_weights_series(self) -> pd.Series:
        return pd.Series(self._weights)

    @property
    def rebalancing_date(self):
        return self._rebalancing_date

    @rebalancing_date.setter
    def rebalancing_date(self, new_date: str):
        if new_date and not isinstance(new_date, str):
            raise TypeError("date must be a string")
        self._rebalancing_date = new_date

    @property
    def name(self):
        return self._name

    @name.setter
    def name(self, new_name: str):
        if new_name is not None and not isinstance(new_name, str):
            raise TypeError("name must be a string")
        self._name = new_name

    def __repr__(self):
        return f"Portfolio(rebalancing_date={self.rebalancing_date}, weights={self.weights})"

    def float_weights(self, return_series: pd.DataFrame, end_date: str, rescale: bool = False):
        if self.weights is not None:
            return floating_weights(
                X=return_series,
                w=self.weights,
                start_date=self.rebalancing_date,
                end_date=end_date,
                rescale=rescale,
            )
        return None

    def initial_weights(self,
                        selection,
                        return_series: pd.DataFrame,
                        end_date: str,
                        rescale: bool = True):
        if not hasattr(self, "_initial_weights"):
            if self.rebalancing_date is not None and self.weights is not None:
                w_init = dict.fromkeys(selection, 0)
                w_float = self.float_weights(
                    return_series=return_series, end_date=end_date, rescale=rescale
                )
                w_floated = w_float.iloc[-1]
                w_init.update({key: w_floated[key] for key in w_init.keys() & w_floated.keys()})
                self._initial_weights = w_init
            else:
                self._initial_weights = None
        return self._initial_weights

    def turnover(self, portfolio: "Portfolio", return_series: pd.DataFrame, rescale=True):
        """Two-sided turnover: drifted old weights vs the newly decided ones.

        The reference's older-portfolio branch subtracts the *old*
        weights from their own drifted values (reference
        ``portfolio.py:109-121``), i.e. measures drift rather than
        trading — inconsistent with its other branch. Both branches here
        compare the drifted old portfolio against the *newer* portfolio's
        weights (SURVEY.md section 2, quirks-to-fix list).
        """
        if portfolio.rebalancing_date is not None and portfolio.rebalancing_date < self.rebalancing_date:
            w_init = portfolio.initial_weights(
                selection=self.weights.keys(),
                return_series=return_series,
                end_date=self.rebalancing_date,
                rescale=rescale,
            )
            new_weights = self.weights
        else:
            w_init = self.initial_weights(
                selection=portfolio.weights.keys(),
                return_series=return_series,
                end_date=portfolio.rebalancing_date,
                rescale=rescale,
            )
            new_weights = portfolio.weights
        return pd.Series(w_init).sub(pd.Series(new_weights), fill_value=0).abs().sum()


class Strategy:

    def __init__(self, portfolios: list):
        self.portfolios = portfolios

    @property
    def portfolios(self):
        return self._portfolios

    @portfolios.setter
    def portfolios(self, new_portfolios: list):
        if not isinstance(new_portfolios, list):
            raise TypeError("portfolios must be a list")
        if not all(isinstance(p, Portfolio) for p in new_portfolios):
            raise TypeError("all elements in portfolios must be of type Portfolio")
        self._portfolios = new_portfolios

    def clear(self) -> None:
        self.portfolios.clear()

    def get_rebalancing_dates(self):
        return [portfolio.rebalancing_date for portfolio in self.portfolios]

    def get_weights(self, rebalancing_date: str):
        for portfolio in self.portfolios:
            if portfolio.rebalancing_date == rebalancing_date:
                return portfolio.weights
        return None

    def get_weights_df(self) -> pd.DataFrame:
        weights_dict = {p.rebalancing_date: p.weights for p in self.portfolios}
        return pd.DataFrame(weights_dict).T

    def get_portfolio(self, rebalancing_date: str) -> Portfolio:
        if rebalancing_date in self.get_rebalancing_dates():
            idx = self.get_rebalancing_dates().index(rebalancing_date)
            return self.portfolios[idx]
        raise ValueError(f"No portfolio found for rebalancing date {rebalancing_date}")

    def has_previous_portfolio(self, rebalancing_date: str) -> bool:
        dates = self.get_rebalancing_dates()
        return len(dates) > 0 and dates[0] < rebalancing_date

    def get_previous_portfolio(self, rebalancing_date: str) -> Portfolio:
        if not self.has_previous_portfolio(rebalancing_date):
            return Portfolio.empty()
        yesterday = [x for x in self.get_rebalancing_dates() if x < rebalancing_date][-1]
        return self.get_portfolio(yesterday)

    def get_initial_portfolio(self, rebalancing_date: str) -> Portfolio:
        if self.has_previous_portfolio(rebalancing_date=rebalancing_date):
            return self.get_previous_portfolio(rebalancing_date)
        return Portfolio(rebalancing_date=None, weights={})

    def __repr__(self):
        return f"Strategy(portfolios={self.portfolios})"

    def number_of_assets(self, th: float = 0.0001) -> pd.Series:
        return self.get_weights_df().apply(lambda x: sum(np.abs(x) > th), axis=1)

    def turnover(self, return_series, rescale=True) -> pd.Series:
        dates = self.get_rebalancing_dates()
        turnover = {}
        for rebalancing_date in dates:
            previous_portfolio = self.get_previous_portfolio(rebalancing_date)
            current_portfolio = self.get_portfolio(rebalancing_date)
            if previous_portfolio.rebalancing_date is None:
                # First rebalance: the full initial acquisition is traded.
                # (The reference's empty-previous branch degenerates to 0
                # through a None end_date — SURVEY.md section 2.)
                turnover[rebalancing_date] = (
                    pd.Series(current_portfolio.weights).abs().sum()
                )
                continue
            turnover[rebalancing_date] = current_portfolio.turnover(
                portfolio=previous_portfolio,
                return_series=return_series,
                rescale=rescale,
            )
        return pd.Series(turnover)

    def simulate(self,
                 return_series=None,
                 fc: float = 0,
                 vc: float = 0,
                 n_days_per_year: int = 252) -> pd.Series:
        """Pandas return engine (reference ``portfolio.py:205-245`` parity).

        For the device-vectorized equivalent see
        :func:`porqua_tpu.accounting.simulate`.
        """
        rebdates = self.get_rebalancing_dates()
        ret_list = []
        for rebdate in rebdates:
            next_rebdate = (
                rebdates[rebdates.index(rebdate) + 1]
                if rebdate < rebdates[-1]
                else return_series.index[-1]
            )
            portfolio = self.get_portfolio(rebdate)
            w_float = portfolio.float_weights(
                return_series=return_series, end_date=next_rebdate, rescale=False
            )
            short_positions = [v for v in portfolio.weights.values() if v < 0]
            long_positions = [v for v in portfolio.weights.values() if v >= 0]
            margin = abs(sum(short_positions))
            cash = max(min(1 - sum(long_positions), 1), 0)
            loan = 1 - (sum(long_positions) + cash) - (sum(short_positions) + margin)
            w_float.insert(0, "margin", margin)
            w_float.insert(0, "cash", cash)
            w_float.insert(0, "loan", loan)
            level = w_float.sum(axis=1)
            ret_list.append(level.pct_change(1))

        portf_ret = pd.concat(ret_list).dropna()

        if vc != 0:
            to = self.turnover(return_series=return_series, rescale=False)
            varcost = to * vc
            portf_ret.iloc[0] -= varcost.iloc[0]
            portf_ret[varcost[1:].index] -= varcost[1:].values
        if fc != 0:
            n_days = (
                (portf_ret.index[1:] - portf_ret.index[:-1])
                .to_numpy()
                .astype("timedelta64[D]")
                .astype(int)
            )
            fixcost = (1 + fc) ** (n_days / n_days_per_year) - 1
            portf_ret.iloc[1:] -= fixcost

        return portf_ret


def floating_weights(X, w, start_date, end_date, rescale=True):
    """Drift weights by cumulative returns (reference ``portfolio.py:254-288``)."""
    start_date = pd.to_datetime(start_date)
    end_date = pd.to_datetime(end_date)
    if start_date < X.index[0]:
        raise ValueError("start_date must be contained in dataset")
    if end_date > X.index[-1]:
        raise ValueError("end_date must be contained in dataset")

    w = pd.Series(w, index=w.keys())
    if w.isna().any():
        raise ValueError("weights (w) contain NaN which is not allowed.")
    w = w.to_frame().T
    xnames = X.columns
    wnames = w.columns
    if not all(wnames.isin(xnames)):
        raise ValueError("Not all assets in w are contained in X.")

    X_tmp = X.loc[start_date:end_date, wnames].copy().fillna(0)
    xmat = 1 + X_tmp
    xmat.iloc[0] = w.dropna(how="all").fillna(0)
    w_float = xmat.cumprod()

    if rescale:
        w_float_long = (
            w_float.where(w_float >= 0)
            .div(w_float[w_float >= 0].abs().sum(axis=1), axis="index")
            .fillna(0)
        )
        w_float_short = (
            w_float.where(w_float < 0)
            .div(w_float[w_float < 0].abs().sum(axis=1), axis="index")
            .fillna(0)
        )
        w_float = pd.DataFrame(w_float_long + w_float_short, index=xmat.index, columns=wnames)

    return w_float
