"""Portfolio & strategy accounting (host-side pandas engine).

Covers the reference's accounting layer capabilities
(``/root/reference/src/portfolio.py``: dated weight snapshots, drifted
weights, turnover, cost-aware return simulation) with a different
architecture: weights are held as aligned numpy/Series data, drift is a
single vectorized cumulative-product per holding period, and the sleeve
(margin/cash/loan) arithmetic happens on the summed level directly
instead of widening the weight frame with synthetic columns.

Two known reference defects are deliberately not reproduced (SURVEY.md
section 2): turnover compares the drifted *old* portfolio against the
*new* weights in both branches, and the first rebalance books the full
initial acquisition as trading volume.

The device-vectorized simulation — whole backtest as one XLA program —
lives in :mod:`porqua_tpu.accounting`; this module is the independent
behavioral model it is tested against.
"""

from __future__ import annotations

import bisect
from typing import Optional

import numpy as np
import pandas as pd


def floating_weights(X: pd.DataFrame, w, start_date, end_date,
                     rescale: bool = True) -> pd.DataFrame:
    """Drift weights ``w`` by cumulative asset returns.

    Row 0 (at ``start_date``) holds ``w`` itself; each later row is the
    previous row compounded by that day's returns. With ``rescale``,
    every row is renormalized so the long and short sides each sum to
    +/-1 of their own gross (the reference's long/short renormalization,
    ``portfolio.py:283-286``).
    """
    start = pd.to_datetime(start_date)
    end = pd.to_datetime(end_date)
    if start < X.index[0] or end > X.index[-1]:
        raise ValueError(
            f"the window [{start_date}, {end_date}] must lie inside the "
            f"return series range [{X.index[0]}, {X.index[-1]}]")

    w = pd.Series(w, dtype=float)
    if w.isna().any():
        raise ValueError("weights contain NaN")
    unknown = w.index.difference(X.columns)
    if len(unknown):
        raise ValueError(f"assets missing from the return series: "
                         f"{list(unknown[:5])}")

    window = X.loc[start:end, w.index]
    growth = 1.0 + np.nan_to_num(window.to_numpy(dtype=float))
    growth[0] = w.to_numpy()
    drift = np.cumprod(growth, axis=0)

    if rescale:
        longs = np.where(drift >= 0, drift, 0.0)
        shorts = drift - longs
        long_gross = longs.sum(axis=1, keepdims=True)
        short_gross = np.abs(shorts).sum(axis=1, keepdims=True)
        drift = (np.divide(longs, long_gross,
                           out=np.zeros_like(longs),
                           where=long_gross != 0)
                 + np.divide(shorts, short_gross,
                             out=np.zeros_like(shorts),
                             where=short_gross != 0))

    return pd.DataFrame(drift, index=window.index, columns=w.index)


class Portfolio:
    """One dated weight snapshot."""

    def __init__(self,
                 rebalancing_date: Optional[str] = None,
                 weights: Optional[dict] = None,
                 name: Optional[str] = None,
                 init_weights: Optional[dict] = None):
        if rebalancing_date is not None and not isinstance(
                rebalancing_date, str):
            raise TypeError("rebalancing_date must be a string (or None)")
        if name is not None and not isinstance(name, str):
            raise TypeError("name must be a string (or None)")
        self.rebalancing_date = rebalancing_date
        self._w = self._coerce(weights)
        self.name = name
        self.init_weights = dict(init_weights) if init_weights else {}
        self._initial_cache: dict = {}

    @staticmethod
    def _coerce(weights) -> pd.Series:
        if weights is None:
            return pd.Series(dtype=float)
        if isinstance(weights, pd.Series):
            return weights.astype(float)
        if isinstance(weights, dict):
            return pd.Series(weights, dtype=float)
        if hasattr(weights, "to_dict"):
            return pd.Series(weights.to_dict(), dtype=float)
        raise TypeError("weights must be dict-like")

    @staticmethod
    def empty() -> "Portfolio":
        return Portfolio()

    @property
    def weights(self) -> dict:
        return self._w.to_dict()

    @weights.setter
    def weights(self, value) -> None:
        self._w = self._coerce(value)
        self._initial_cache = {}

    def get_weights_series(self) -> pd.Series:
        return self._w.copy()

    def __repr__(self):
        return (f"Portfolio({self.rebalancing_date!r}, "
                f"{len(self._w)} assets)")

    def float_weights(self, return_series: pd.DataFrame, end_date: str,
                      rescale: bool = False):
        if self._w.empty:
            return None
        return floating_weights(
            return_series, self._w, self.rebalancing_date, end_date,
            rescale=rescale)

    def initial_weights(self,
                        selection,
                        return_series: pd.DataFrame,
                        end_date: str,
                        rescale: bool = True) -> Optional[dict]:
        """This portfolio's weights drifted to ``end_date``, expressed
        over ``selection`` (zeros for ids we never held). Memoized per
        (selection, end_date, rescale) argument combination."""
        if self.rebalancing_date is None or self._w.empty:
            return None
        key = (tuple(selection), end_date, rescale)
        if key not in self._initial_cache:
            drifted = self.float_weights(
                return_series, end_date, rescale=rescale).iloc[-1]
            out = pd.Series(0.0, index=list(selection))
            held = out.index.intersection(drifted.index)
            out[held] = drifted[held]
            self._initial_cache[key] = out.to_dict()
        return self._initial_cache[key]

    def turnover(self, portfolio: "Portfolio", return_series: pd.DataFrame,
                 rescale: bool = True) -> float:
        """L1 distance between the older portfolio drifted to the newer
        rebalance date and the newer portfolio's fresh weights."""
        mine = self.rebalancing_date
        theirs = portfolio.rebalancing_date
        older, newer = ((portfolio, self)
                        if theirs is not None and theirs < mine
                        else (self, portfolio))
        drifted = older.initial_weights(
            selection=list(newer._w.index),
            return_series=return_series,
            end_date=newer.rebalancing_date,
            rescale=rescale)
        diff = pd.Series(drifted).sub(newer._w, fill_value=0.0)
        return float(diff.abs().sum())


class Strategy:
    """An ordered collection of dated portfolios."""

    def __init__(self, portfolios: list):
        if not isinstance(portfolios, list) or any(
                not isinstance(p, Portfolio) for p in portfolios):
            raise TypeError("Strategy takes a list of Portfolio objects")
        self.portfolios = portfolios

    def __repr__(self):
        return f"Strategy({len(self.portfolios)} portfolios)"

    def clear(self) -> None:
        self.portfolios.clear()

    def get_rebalancing_dates(self) -> list:
        return [p.rebalancing_date for p in self.portfolios]

    def get_portfolio(self, rebalancing_date: str) -> Portfolio:
        for p in self.portfolios:
            if p.rebalancing_date == rebalancing_date:
                return p
        raise ValueError(
            f"no portfolio is dated {rebalancing_date!r}")

    def get_weights(self, rebalancing_date: str) -> Optional[dict]:
        for p in self.portfolios:
            if p.rebalancing_date == rebalancing_date:
                return p.weights
        return None

    def get_weights_df(self) -> pd.DataFrame:
        """(dates x assets) weight matrix, NaN where an asset was not
        in that date's universe."""
        return pd.DataFrame.from_dict(
            {p.rebalancing_date: p.weights for p in self.portfolios},
            orient="index")

    def has_previous_portfolio(self, rebalancing_date: str) -> bool:
        dates = self.get_rebalancing_dates()
        return bool(dates) and dates[0] < rebalancing_date

    def get_previous_portfolio(self, rebalancing_date: str) -> Portfolio:
        dates = self.get_rebalancing_dates()
        pos = bisect.bisect_left(dates, rebalancing_date)
        return self.portfolios[pos - 1] if pos else Portfolio.empty()

    def get_initial_portfolio(self, rebalancing_date: str) -> Portfolio:
        if self.has_previous_portfolio(rebalancing_date):
            return self.get_previous_portfolio(rebalancing_date)
        return Portfolio(rebalancing_date=None, weights={})

    def number_of_assets(self, th: float = 0.0001) -> pd.Series:
        return (self.get_weights_df().abs() > th).sum(axis=1)

    def turnover(self, return_series, rescale: bool = True) -> pd.Series:
        """Per-date traded volume. The first rebalance books the full
        initial acquisition (the reference's empty-previous branch
        degenerates to zero through a None end date)."""
        out = {}
        for p in self.portfolios:
            prev = self.get_previous_portfolio(p.rebalancing_date)
            if prev.rebalancing_date is None:
                out[p.rebalancing_date] = float(p._w.abs().sum())
            else:
                out[p.rebalancing_date] = p.turnover(
                    portfolio=prev, return_series=return_series,
                    rescale=rescale)
        return pd.Series(out)

    def simulate(self,
                 return_series: Optional[pd.DataFrame] = None,
                 fc: float = 0,
                 vc: float = 0,
                 n_days_per_year: int = 252) -> pd.Series:
        """Daily strategy returns net of costs.

        Per holding period: drift the weights (un-rescaled), add the
        constant margin/cash/loan sleeves implied by the period's
        long/short gross, and difference the summed level. Variable
        costs subtract turnover * ``vc`` at each rebalance; fixed costs
        compound ``fc`` over calendar-day gaps.

        The device-vectorized equivalent is
        :func:`porqua_tpu.accounting.simulate`.
        """
        dates = self.get_rebalancing_dates()
        period_ends = dates[1:] + [return_series.index[-1]]

        pieces = []
        for date, period_end in zip(dates, period_ends):
            p = self.get_portfolio(date)
            drift = p.float_weights(return_series, period_end,
                                    rescale=False)
            w = p._w
            long_total = float(w[w >= 0].sum())
            short_total = float(w[w < 0].sum())
            margin = abs(short_total)
            cash = min(max(1.0 - long_total, 0.0), 1.0)
            loan = (1.0 - (long_total + cash)
                    - (short_total + margin))
            level = drift.sum(axis=1) + (margin + cash + loan)
            pieces.append(level.pct_change())
        returns = pd.concat(pieces).dropna()

        if vc != 0:
            traded = self.turnover(return_series=return_series,
                                   rescale=False) * vc
            missing = [d for d in traded.index[1:] if d not in returns.index]
            if missing:
                # Same convention as the reference (costs are charged on
                # the rebalance date's own return row), surfaced as a
                # diagnosis instead of a pandas KeyError deep in .loc.
                raise ValueError(
                    "variable costs are charged on rebalance dates, but "
                    f"{[str(d)[:10] for d in missing[:3]]}"
                    f"{'...' if len(missing) > 3 else ''} are not in the "
                    "return series — pick rebalance dates from the data's "
                    "index (trading days)")
            # The first rebalance date has no return row; its cost hits
            # the first available return instead.
            returns.iloc[0] -= traded.iloc[0]
            returns[traded.index[1:]] -= traded.iloc[1:].values
        if fc != 0:
            gaps = np.diff(returns.index.to_numpy()).astype(
                "timedelta64[D]").astype(int)
            returns.iloc[1:] -= (1 + fc) ** (gaps / n_days_per_year) - 1

        return returns
