"""Render one serving run as text: waterfall, latency, sparklines.

The reader side of the observability pillar — consumes the three
artifacts a traced run emits (the Chrome-trace span file, the event
JSONL, the metrics snapshot) and renders what an operator actually
asks: *where did the time go* (stage waterfall aggregated over every
request span), *what did callers see* (latency/throughput table), and
*what did the solver do on device* (convergence sparklines from ring
events). ``scripts/obs_report.py`` is the CLI. Pure host/stdlib+numpy:
rendering a report must never initialize a JAX backend.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: Canonical request-span order, for waterfall sorting.
STAGE_ORDER = ("submit", "queue_wait", "assemble", "solve", "resolve")

_SPARK_GLYPHS = "▁▂▃▄▅▆▇█"


def _trace_events(trace: Any) -> List[Dict[str, Any]]:
    """Accept either the Chrome trace object or its event list."""
    if isinstance(trace, dict):
        return list(trace.get("traceEvents", []))
    return list(trace or [])


def span_aggregate(trace: Any) -> Dict[str, Dict[str, float]]:
    """Per-stage rollup over every ``"X"`` event: count, total/mean/max
    milliseconds."""
    agg: Dict[str, List[float]] = {}
    for e in _trace_events(trace):
        if e.get("ph") != "X":
            continue
        agg.setdefault(e["name"], []).append(float(e.get("dur", 0.0)))
    out: Dict[str, Dict[str, float]] = {}
    for name, durs in agg.items():
        a = np.asarray(durs)
        out[name] = {
            "count": float(a.size),
            "total_ms": float(a.sum()) * 1e-3,
            "mean_ms": float(a.mean()) * 1e-3,
            "p99_ms": float(np.percentile(a, 99)) * 1e-3,
            "max_ms": float(a.max()) * 1e-3,
        }
    return out


def span_coverage(trace: Any) -> List[Tuple[str, float, float]]:
    """Per-request ``(trace_id, spans_sum_s, extent_s)``.

    ``spans_sum_s`` adds every span duration carrying the trace id;
    ``extent_s`` is last-span-end minus first-span-start — the
    request's observed wall-clock. A well-instrumented pipeline has the
    two within a few percent (the acceptance bar: 10%); a gap means a
    stage is living outside any span.
    """
    per: Dict[str, List[Tuple[float, float]]] = {}
    for e in _trace_events(trace):
        if e.get("ph") != "X":
            continue
        tid = (e.get("args") or {}).get("trace_id")
        if tid is None:
            continue
        ts, dur = float(e.get("ts", 0.0)), float(e.get("dur", 0.0))
        per.setdefault(tid, []).append((ts, dur))
    out = []
    for tid, spans in per.items():
        total = sum(d for _, d in spans) * 1e-6
        extent = (max(ts + d for ts, d in spans)
                  - min(ts for ts, _ in spans)) * 1e-6
        out.append((tid, total, extent))
    return out


def coverage_stats(trace: Any) -> Dict[str, float]:
    """Summary of :func:`span_coverage`: median/min cover ratio."""
    cov = span_coverage(trace)
    if not cov:
        return {"n_traces": 0, "cover_median": 0.0, "cover_min": 0.0}
    ratios = sorted(t / e if e > 0 else 1.0 for _, t, e in cov)
    return {
        "n_traces": len(ratios),
        "cover_median": ratios[len(ratios) // 2],
        "cover_min": ratios[0],
    }


def sparkline(values: Sequence[float], width: int = 40,
              log: bool = False) -> str:
    """A one-line unicode sparkline (``log=True`` for residual decay —
    linear scale renders a 1e6-range trajectory as one step)."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    if log:
        floor = 1e-300
        vals = [math.log10(max(abs(v), floor)) for v in vals]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK_GLYPHS[0] * len(vals)
    return "".join(
        _SPARK_GLYPHS[min(int((v - lo) / span * len(_SPARK_GLYPHS)),
                          len(_SPARK_GLYPHS) - 1)]
        for v in vals)


# ---------------------------------------------------------------------------
# sections
# ---------------------------------------------------------------------------

def waterfall_section(trace: Any) -> str:
    agg = span_aggregate(trace)
    if not agg:
        return "stage waterfall: (no spans)"
    order = {name: i for i, name in enumerate(STAGE_ORDER)}
    names = sorted(agg, key=lambda n: (order.get(n, len(order)), n))
    width = max(len(n) for n in names)
    total = sum(agg[n]["total_ms"] for n in names)
    lines = ["stage waterfall (all requests)",
             f"{'stage':<{width}}  {'count':>7} {'total ms':>10} "
             f"{'mean ms':>9} {'p99 ms':>9}  share"]
    for n in names:
        a = agg[n]
        share = a["total_ms"] / total if total else 0.0
        bar = "#" * max(int(share * 30), 1 if a["total_ms"] else 0)
        lines.append(
            f"{n:<{width}}  {int(a['count']):>7} {a['total_ms']:>10.1f} "
            f"{a['mean_ms']:>9.3f} {a['p99_ms']:>9.3f}  {bar}")
    cov = coverage_stats(trace)
    if cov["n_traces"]:
        lines.append(
            f"span coverage: {cov['n_traces']} traces, median "
            f"{cov['cover_median']:.2f}x of request wall-clock "
            f"(min {cov['cover_min']:.2f}x)")
    return "\n".join(lines)


def latency_section(snapshot: Dict[str, Any]) -> str:
    rows = [
        ("completed", snapshot.get("completed", 0)),
        ("failed", snapshot.get("failed", 0)),
        ("expired", snapshot.get("expired", 0)),
        ("rejected", snapshot.get("rejected", 0)),
        ("throughput solves/s",
         round(float(snapshot.get("throughput_solves_per_s", 0.0)), 1)),
        ("latency p50 ms",
         round(float(snapshot.get("latency_p50_ms", 0.0)), 3)),
        ("latency p90 ms",
         round(float(snapshot.get("latency_p90_ms", 0.0)), 3)),
        ("latency p99 ms",
         round(float(snapshot.get("latency_p99_ms", 0.0)), 3)),
        ("occupancy mean",
         round(float(snapshot.get("occupancy_mean", 0.0)), 4)),
        ("queue wait s",
         round(float(snapshot.get("queue_wait_seconds", 0.0)), 3)),
        ("solve s", round(float(snapshot.get("solve_seconds", 0.0)), 3)),
        ("recompiles", snapshot.get("compiles", 0)),
        ("device", snapshot.get("device")),
        ("degraded", snapshot.get("degraded", False)),
    ]
    width = max(len(k) for k, _ in rows)
    return "\n".join(["latency / throughput"]
                     + [f"{k:<{width}}  {v}" for k, v in rows])


def convergence_section(events: Sequence[Dict[str, Any]],
                        max_rings: int = 8) -> str:
    """Sparklines from ``convergence_ring`` events (the decoded ring
    payloads the load generator emits for a sample of requests)."""
    rings = [e for e in events if e.get("kind") == "convergence_ring"]
    if not rings:
        return "convergence: (no ring events)"
    lines = ["convergence rings (log10 residual sparklines)"]
    for e in rings[:max_rings]:
        label = e.get("trace_id") or e.get("request", "?")
        iters = e.get("iters_final", (e.get("iters") or [0])[-1])
        prim = e.get("prim_res", [])
        dual = e.get("dual_res", [])
        final_p = prim[-1] if prim else float("nan")
        final_d = dual[-1] if dual else float("nan")
        lines.append(f"  {label}: {iters} iters, "
                     f"final prim {final_p:.2e} dual {final_d:.2e}")
        lines.append(f"    prim {sparkline(prim, log=True)}")
        lines.append(f"    dual {sparkline(dual, log=True)}")
    return "\n".join(lines)


#: Event kinds the faults/recovery section treats as recovery-side
#: (emitted by porqua_tpu.resilience.retry and the breaker), keyed to
#: the injected faults they answer.
_RECOVERY_KINDS = ("retry_scheduled", "retry_giveup", "hedge_fired",
                   "validation_failed", "breaker_open", "breaker_close",
                   "probe_failure", "dispatch_failure")


def faults_section(events: Sequence[Dict[str, Any]],
                   max_shown: int = 8) -> str:
    """Faults vs recovery, from the event log: what the injector (or
    the real world) did, per seam and kind, next to what the recovery
    machinery did about it — the at-a-glance answer to "did the chaos
    scenario exercise the paths it claimed to" (the invariant-level
    verdicts live in ``scripts/chaos_suite.py``'s JSON report)."""
    injected = [e for e in events if e.get("kind") == "fault_injected"]
    recovery: Dict[str, int] = {}
    for e in events:
        k = e.get("kind")
        if k in _RECOVERY_KINDS:
            recovery[k] = recovery.get(k, 0) + 1
    if not injected and not recovery:
        return "faults / recovery: (no fault or recovery events)"
    lines = ["faults / recovery"]
    by_fault: Dict[Tuple[str, str], int] = {}
    for e in injected:
        key = (e.get("seam", "?"), e.get("fault_kind", "?"))
        by_fault[key] = by_fault.get(key, 0) + 1
    scenarios = sorted({e.get("scenario") for e in injected
                        if e.get("scenario")})
    if scenarios:
        lines.append(f"  scenario(s): {', '.join(scenarios)}")
    for (seam, kind), n in sorted(by_fault.items()):
        lines.append(f"  injected {seam:<18} {kind:<14} x{n}")
    for kind in _RECOVERY_KINDS:
        if kind in recovery:
            lines.append(f"  recovery {kind:<24} x{recovery[kind]}")
    # A breaker that opened and never re-closed is the one line an
    # operator must not miss.
    opens = recovery.get("breaker_open", 0)
    closes = recovery.get("breaker_close", 0)
    if opens or closes:
        state = ("re-closed" if closes >= opens else
                 "STILL OPEN (degraded)")
        lines.append(f"  breaker: {opens} open / {closes} close -> {state}")
    giveups = [e for e in events if e.get("kind") == "retry_giveup"]
    for e in giveups[-max_shown:]:
        detail = {k: v for k, v in e.items()
                  if k not in ("t", "kind", "severity")}
        lines.append(f"  ! giveup {detail}")
    return "\n".join(lines)


#: Status code -> short label for harvest convergence classes (mirrors
#: qp.admm.Status; literal so the report stays backend-free).
_STATUS_LABELS = {1: "solved", 2: "max_iter", 3: "primal_infeasible",
                  4: "dual_infeasible"}


def harvest_section(records: Sequence[Dict[str, Any]],
                    max_rings_per_class: int = 3) -> str:
    """Convergence analytics from a harvest dataset: ring-trajectory
    sparklines grouped per terminal-status class (a stalled MAX_ITER
    trajectory looks nothing like a converging one — the at-a-glance
    view of WHY the tail is slow), then the per-(bucket, eps)
    wasted-iteration attribution table the learned-policy work trains
    against (full aggregation: ``scripts/harvest_report.py``)."""
    from porqua_tpu.obs.harvest import aggregate

    records = list(records)
    if not records:
        return "harvest: (no records)"
    lines = [f"harvest convergence analytics ({len(records)} records)"]
    by_class: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("ring"):
            label = _STATUS_LABELS.get(int(rec.get("status", 0)),
                                       str(rec.get("status")))
            by_class.setdefault(label, []).append(rec)
    if not by_class:
        lines.append("  (no ring trajectories in the dataset — "
                     "harvest with SolverParams(ring_size>0))")
    for label in sorted(by_class):
        recs = by_class[label]
        lines.append(f"  {label}: {len(recs)} trajectories")
        for rec in recs[:max_rings_per_class]:
            ring = rec["ring"]
            who = rec.get("trace_id") or f"lane {rec.get('lane', '?')}"
            lines.append(
                f"    {who}: {rec['iters']} iters, final prim "
                f"{rec['prim_res']:.2e} dual {rec['dual_res']:.2e}")
            lines.append(f"      prim {sparkline(ring['prim_res'], log=True)}")
            lines.append(f"      dual {sparkline(ring['dual_res'], log=True)}")
    agg = aggregate(records)
    lines.append("  wasted-iteration attribution by (bucket, eps):")
    for g in agg["groups"]:
        eps = g["eps_abs"]
        wc = g.get("warm_minus_cold_iters_mean")
        lines.append(
            f"    {g['bucket']:<12} eps "
            f"{(f'{eps:.0e}' if eps is not None else '-'):>7}  "
            f"x{g['count']:<5} iters p50/p95 "
            f"{g['iters']['p50']:.0f}/{g['iters']['p95']:.0f}  "
            f"wasted {g['wasted_iteration_fraction']:.3f}"
            + (f"  warm-cold {wc:+.1f} iters" if wc is not None else ""))
    return "\n".join(lines)


def costs_section(records: Sequence[Dict[str, Any]],
                  harvest: Optional[Sequence[Dict[str, Any]]] = None,
                  max_rows: int = 12) -> str:
    """Device cost / memory from a CostRecord dataset: per-bucket peak
    device memory and XLA-measured bytes per executable (what the
    compiler said the programs cost), plus — when harvest records with
    measured (``cost_source: "xla"``) profiles are given — the
    measured-vs-model table: the analytic flop model's drift against
    the compiler per bucket, the number that says whether the hand
    roofline can still be trusted."""
    records = list(records)
    if not records:
        return "device cost / memory: (no CostRecords)"
    lines = [f"device cost / memory ({len(records)} CostRecords)"]
    by_bucket: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        by_bucket.setdefault(str(rec.get("bucket", "?")), []).append(rec)
    lines.append(f"  {'bucket':<14} {'exes':>4} {'peak MB (max)':>13} "
                 f"{'MB accessed (max)':>17} {'compile s':>9}")
    for bucket in sorted(by_bucket):
        recs = by_bucket[bucket]
        peaks = [r["peak_bytes"] for r in recs if r.get("peak_bytes")]
        bytes_ = [r["bytes_accessed"] for r in recs
                  if r.get("bytes_accessed")]
        compile_s = sum(float(r.get("compile_s") or 0.0) for r in recs)
        lines.append(
            f"  {bucket:<14} {len(recs):>4} "
            f"{(max(peaks) / 1e6 if peaks else 0.0):>13.2f} "
            f"{(max(bytes_) / 1e6 if bytes_ else 0.0):>17.2f} "
            f"{compile_s:>9.2f}")
    rows = records[:max_rows]
    lines.append("  per executable (bytes = XLA cost analysis):")
    for r in rows:
        ba, pk = r.get("bytes_accessed"), r.get("peak_bytes")
        lines.append(
            f"    {str(r.get('kind')):<11} {str(r.get('entry')):<9} "
            f"{str(r.get('bucket')):<12} x{r.get('slots') or 0:<5} "
            f"{(ba or 0) / 1e6:>10.2f} MB acc  "
            f"{(pk or 0) / 1e6:>8.2f} MB peak  "
            f"hlo {str(r.get('hlo_hash'))[:8]}")
    if harvest:
        measured = [h for h in harvest
                    if (h.get("profile") or {}).get("cost_source")
                    == "xla"]
        if measured:
            lines.append("  measured-vs-model (per bucket; ratio = "
                         "analytic model / XLA):")
            groups: Dict[str, List[Dict[str, Any]]] = {}
            for h in measured:
                groups.setdefault(str(h.get("bucket", "?")),
                                  []).append(h["profile"])
            for bucket in sorted(groups):
                profs = groups[bucket]
                fr = [p["flops_model_ratio"] for p in profs
                      if p.get("flops_model_ratio")]
                br = [p["bytes_model_ratio"] for p in profs
                      if p.get("bytes_model_ratio")]
                mfu = [p["mfu_bf16_peak"] for p in profs
                       if p.get("mfu_bf16_peak") is not None]
                line = (f"    {bucket:<14} x{len(profs):<5}"
                        f" flops model/xla "
                        f"{(np.mean(fr) if fr else 0.0):.3f}"
                        f"  bytes model/xla "
                        f"{(np.mean(br) if br else 0.0):.3f}")
                if mfu:
                    line += f"  mfu(bf16) {np.mean(mfu):.4f}"
                lines.append(line)
        else:
            lines.append("  measured-vs-model: (no harvest records "
                         "with XLA-measured profiles)")
    return "\n".join(lines)


#: Event kinds rendered on the SLO/alert timeline (alert transitions
#: interleaved with the breaker and anomaly activity that explains
#: them).
_TIMELINE_KINDS = ("slo_alert", "breaker_open", "breaker_close",
                   "convergence_anomaly")


def slo_section(events: Sequence[Dict[str, Any]],
                max_shown: int = 40) -> str:
    """The SLO / alert timeline: every ``slo_alert`` state transition
    (pending -> firing -> resolved, with its burn rates) interleaved
    chronologically with breaker open/close and convergence-anomaly
    events — the one view that answers "the alert fired; what was the
    service doing at that moment"."""
    rows = [e for e in events if e.get("kind") in _TIMELINE_KINDS]
    if not rows:
        return "slo / alert timeline: (no slo, breaker or anomaly events)"
    rows = sorted(rows, key=lambda e: float(e.get("t", 0.0)))
    t0 = float(rows[0].get("t", 0.0))
    lines = ["slo / alert timeline"]
    # Count over EVERY row (only the tail is rendered): a firing
    # transition trimmed out of the displayed window must still show
    # in the totals and the STILL-FIRING verdict.
    fired = sum(1 for e in rows if e.get("kind") == "slo_alert"
                and e.get("state") == "firing")
    resolved = sum(1 for e in rows if e.get("kind") == "slo_alert"
                   and e.get("state") == "resolved")
    for e in rows[-max_shown:]:
        dt = float(e.get("t", 0.0)) - t0
        kind = e.get("kind")
        if kind == "slo_alert":
            state = e.get("state", "?")
            lines.append(
                f"  +{dt:8.2f}s  slo_alert  {e.get('slo', '?')}/"
                f"{e.get('rule', '?')} -> {state}  "
                f"(burn short {e.get('burn_short', 0.0):.1f} / long "
                f"{e.get('burn_long', 0.0):.1f}, thr "
                f"{e.get('threshold', 0.0):g})")
        elif kind == "convergence_anomaly":
            lines.append(
                f"  +{dt:8.2f}s  anomaly    {e.get('bucket', '?')} -> "
                f"{e.get('state', '?')}  (ewma iters "
                f"{e.get('ewma_iters', 0.0):g} vs band "
                f"{e.get('iters_band', 0.0):g})")
        else:
            who = e.get("primary") or e.get("device") or "?"
            lines.append(f"  +{dt:8.2f}s  {kind:<10} {who}")
    lines.append(f"  alerts: {fired} fired / {resolved} resolved"
                 + ("  !! STILL FIRING" if fired > resolved else ""))
    return "\n".join(lines)


#: Event kinds rendered on the calibration timeline (the closed
#: route-calibration loop's lifecycle — porqua_tpu/obs/calibrate.py).
_CALIBRATION_KINDS = ("route_reseed", "route_rollback",
                      "solver_routes_seeded")


def calibration_section(events: Sequence[Dict[str, Any]],
                        max_shown: int = 40) -> str:
    """The route-calibration timeline: every ``route_reseed`` state
    transition (candidate → promoted → settled, or abandoned) with its
    table version and changed cells, every ``route_rollback`` with the
    breach that caused it, plus offline ``solver_routes_seeded``
    bootstraps — the view that answers "who changed the route table,
    when, and on what evidence"."""
    rows = [e for e in events if e.get("kind") in _CALIBRATION_KINDS]
    if not rows:
        return ("calibration timeline: (no route_reseed / "
                "route_rollback events)")
    rows = sorted(rows, key=lambda e: float(e.get("t", 0.0)))
    t0 = float(rows[0].get("t", 0.0))
    lines = ["calibration timeline"]
    promoted = sum(1 for e in rows if e.get("kind") == "route_reseed"
                   and e.get("state") == "promoted")
    rolled = sum(1 for e in rows if e.get("kind") == "route_rollback")
    for e in rows[-max_shown:]:
        dt = float(e.get("t", 0.0)) - t0
        kind = e.get("kind")
        if kind == "route_reseed":
            diff = e.get("diff") or {}
            cells = ", ".join(
                f"{c}:{d.get('old', '?')}->{d.get('new', '?')}"
                for c, d in sorted(diff.items())) or "(no cells)"
            lines.append(
                f"  +{dt:8.2f}s  route_reseed   "
                f"{e.get('state', '?'):<9} v{e.get('table_version', 0)}"
                f"  {cells}")
        elif kind == "route_rollback":
            lines.append(
                f"  +{dt:8.2f}s  route_rollback v"
                f"{e.get('table_version', 0)}  "
                f"[{e.get('reason', '?')}]")
        else:
            routes = e.get("routes") or {}
            lines.append(
                f"  +{dt:8.2f}s  routes_seeded  offline   "
                + (", ".join(f"{c}:{m}"
                             for c, m in sorted(routes.items()))
                   or "(none)"))
    lines.append(
        f"  promotions: {promoted} / rollbacks: {rolled}"
        + ("  !! ROLLED BACK" if rolled else ""))
    return "\n".join(lines)


def fleet_section(report: Dict[str, Any]) -> str:
    """The fleet view of a ``scripts/fleet_loadgen.py`` run: the
    per-worker throughput/latency table, the reconciliation verdict,
    the worker-liveness verdict line, the bounded-rollup throughput
    sparkline, and the fleet SLO/alert summary. (Pair with
    ``--events`` on the fleet event log for the full chronological
    SLO/alert timeline — :func:`slo_section` renders it.)"""
    rows = report.get("rows") or []
    lines = [f"fleet workers ({len(rows)})"]
    lines.append(f"  {'worker':<8} {'status':<8} {'completed':>10} "
                 f"{'failed':>7} {'thr/s':>9} {'p50 ms':>8} "
                 f"{'p99 ms':>8} {'recomp':>7} {'rss MB':>8}")
    for r in rows:
        vit = r.get("vitals") or {}
        rss = vit.get("rss_bytes")
        lines.append(
            f"  {r.get('worker', '?'):<8} {r.get('status', '?'):<8} "
            f"{r.get('completed', 0):>10} {r.get('failed', 0):>7} "
            f"{r.get('throughput_solves_per_s', 0.0):>9.1f} "
            f"{r.get('latency_p50_ms', 0.0):>8.2f} "
            f"{r.get('latency_p99_ms', 0.0):>8.2f} "
            f"{r.get('recompiles_after_warmup', 0):>7} "
            f"{(rss / 1e6 if rss else 0.0):>8.1f}")
    fleet = report.get("fleet") or {}
    lines.append(
        f"  fleet: {fleet.get('completed', 0)} completed, "
        f"{fleet.get('failed', 0)} failed, "
        f"{fleet.get('throughput_solves_per_s', 0.0):.1f}/s merged, "
        f"harvest {fleet.get('harvest_records')}")
    recon = report.get("reconciliation") or {}
    lines.append(
        ("  reconciliation: OK — fleet completed == sum(worker "
         "completed) == merged harvest records")
        if report.get("reconciled") else
        f"  reconciliation: !! MISMATCH {recon}")
    lost = report.get("workers_lost") or []
    n_ok = sum(1 for r in rows if r.get("status") != "lost")
    lines.append(
        f"  worker liveness: {n_ok} ok, {len(lost)} lost"
        + (f" — LOST: {', '.join(lost)} "
           f"({report.get('worker_lost_bundles', 0)} worker_lost "
           f"incident bundle(s) dumped)" if lost else " — all alive"))
    roll = report.get("rollups_tail") or []
    if roll:
        spark = sparkline([float(r.get("completed", 0)) for r in roll],
                          width=min(len(roll) * 2, 32))
        lines.append(
            f"  rollups (last {len(roll)} x "
            f"{roll[-1].get('span_s', 0):g}s windows) completed/window: "
            f"{spark}  [{report.get('rollup_windows', len(roll))} "
            f"windows total, ring-bounded]")
    slo = report.get("slo")
    if slo:
        firing = slo.get("firing") or []
        compl = ", ".join(
            f"{name} {entry.get('compliance', 1.0):.4f}"
            for name, entry in sorted(slo.get("slos", {}).items()))
        lines.append(
            f"  fleet slo: {compl}; alerts fired "
            f"{slo.get('alerts_fired', 0)}"
            + (f"; !! FIRING: {', '.join(firing)}" if firing
               else "; none firing"))
    if report.get("vitals_anomalous"):
        lines.append("  vitals: !! trending "
                     + ", ".join(report["vitals_anomalous"]))
    return "\n".join(lines)


def tenant_section(report: Dict[str, Any]) -> str:
    """The tenant view of a multi-tenant loadgen run (the
    ``run_loadgen(tenants=...)`` report, or any dict carrying its
    ``tenants`` / ``tenant_fairness`` / ``tenant_slo`` keys): the
    per-tenant counter/latency table, each tenant's SLO alert totals,
    and the fairness/isolation verdict line the bench gate's fairness
    rules machine-check."""
    tenants = report.get("tenants") or {}
    if not tenants:
        return "tenants: (no per-tenant data)"
    slo = report.get("tenant_slo") or {}
    lines = [f"tenants ({len(tenants)})"]
    lines.append(f"  {'tenant':<12} {'submitted':>9} {'completed':>9} "
                 f"{'rejected':>8} {'expired':>7} {'failed':>7} "
                 f"{'p50 ms':>8} {'p99 ms':>8} {'alerts':>7}")
    for t, row in sorted(tenants.items()):
        fired = (slo.get(t, {}).get("alerts_fired", 0)
                 if isinstance(slo.get(t), dict) else 0)
        lines.append(
            f"  {t:<12} {row.get('submitted', 0):>9} "
            f"{row.get('completed', 0):>9} {row.get('rejected', 0):>8} "
            f"{row.get('expired', 0):>7} {row.get('failed', 0):>7} "
            f"{row.get('latency_p50_ms', 0.0):>8.2f} "
            f"{row.get('latency_p99_ms', 0.0):>8.2f} {fired:>7}")
    fair = report.get("tenant_fairness")
    if fair:
        offenders = fair.get("offenders") or []
        verdict_ok = (fair.get("victim_shed_share", 0.0) == 0.0
                      and fair.get("nonoffender_alerts", 0) == 0)
        lines.append(
            f"  fairness: quiet p99 ratio "
            f"{fair.get('quiet_p99_ratio', 1.0):.2f}, victim shed "
            f"share {fair.get('victim_shed_share', 0.0):.4f}, alerts "
            f"offender={fair.get('offender_alerts', 0)} / others="
            f"{fair.get('nonoffender_alerts', 0)}"
            + (f" (offenders: {', '.join(offenders)})" if offenders
               else ""))
        lines.append("  isolation: "
                     + ("OK — no victim sheds, no non-offender alerts"
                        if verdict_ok else "!! VIOLATED"))
        if fair.get("harvest_reconciled") is not None:
            lines.append(
                "  per-tenant reconciliation: "
                + ("exact — tenant completed == tenant harvest records"
                   if fair["harvest_reconciled"] else "!! MISMATCH"))
    return "\n".join(lines)


def events_section(events: Sequence[Dict[str, Any]],
                   max_shown: int = 12) -> str:
    """Severity rollup + the most recent warn/error lines."""
    by_kind: Dict[Tuple[str, str], int] = {}
    for e in events:
        key = (e.get("severity", "info"), e.get("kind", "?"))
        by_kind[key] = by_kind.get(key, 0) + 1
    lines = ["events"]
    for (sev, kind), n in sorted(by_kind.items()):
        lines.append(f"  {sev:<5} {kind:<24} x{n}")
    notable = [e for e in events
               if e.get("severity") in ("warn", "error")
               and e.get("kind") != "convergence_ring"]
    for e in notable[-max_shown:]:
        detail = {k: v for k, v in e.items()
                  if k not in ("t", "kind", "severity")}
        lines.append(f"  ! {e['severity']} {e['kind']} {detail}")
    return "\n".join(lines)


def render_report(trace: Any = None,
                  events: Optional[Sequence[Dict[str, Any]]] = None,
                  snapshot: Optional[Dict[str, Any]] = None,
                  harvest: Optional[Sequence[Dict[str, Any]]] = None,
                  costs: Optional[Sequence[Dict[str, Any]]] = None,
                  fleet: Optional[Dict[str, Any]] = None,
                  tenants: Optional[Dict[str, Any]] = None) -> str:
    """The full text report from whichever artifacts exist."""
    sections = []
    if fleet is not None:
        sections.append(fleet_section(fleet))
    if tenants is not None:
        sections.append(tenant_section(tenants))
    if snapshot is not None:
        sections.append(latency_section(snapshot))
    if trace is not None:
        sections.append(waterfall_section(trace))
    if events is not None:
        sections.append(convergence_section(events))
        sections.append(faults_section(events))
        sections.append(slo_section(events))
        sections.append(calibration_section(events))
        sections.append(events_section(events))
    if harvest is not None:
        sections.append(harvest_section(harvest))
    if costs is not None:
        sections.append(costs_section(costs, harvest=harvest))
    if not sections:
        return ("obs_report: no artifacts given (need --trace/--events"
                "/--metrics/--harvest/--costs/--fleet/--tenants)")
    rule = "-" * 64
    return f"\n{rule}\n".join(sections)
