"""The longitudinal run ledger: one row per measured run, forever.

Every committed BENCH artifact so far is a point-in-time file a human
eyeballed once; nothing machine-readable strings them into a
trajectory. The ledger is that time series: ``bench.py``,
``scripts/serve_loadgen.py``, and ``scripts/fleet_loadgen.py`` append
ONE schema-versioned JSONL row per run (``--ledger``) carrying the git
revision, the run kind, the key metrics (a FLAT dict of the same
dotted metric paths the bench gate's rule table uses), the gate
verdict when one was computed, and the artifact path. Readers:

* ``scripts/trend_report.py`` renders the per-metric trajectory (and
  ``--backfill`` seeds the ledger from the committed
  ``BENCH_r01``-``BENCH_r05`` / ``BENCH_GATE_r07`` / ``SLO_r09``
  artifacts, so the series starts with real history);
* ``scripts/bench_gate.py --trend`` gates a fresh payload against the
  **rolling median of the last K rows** instead of a single committed
  baseline — a slow three-PR drift that stays inside each PR's
  pairwise tolerance is exactly what the rolling window catches.

Rows are append-only and self-describing; :func:`rolling_median` is
the single definition of the trend baseline (median, not mean — one
outlier host must not drag the bar). Pure host code, stdlib only.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "LEDGER_SCHEMA_VERSION",
    "append_row",
    "git_rev",
    "ledger_row",
    "load_ledger",
    "metrics_from_bench",
    "metrics_from_fleet",
    "metrics_from_loadgen",
    "nest_metrics",
    "rolling_median",
]

#: Bump when a field changes meaning; additive fields don't need it.
LEDGER_SCHEMA_VERSION = 1

#: Known values of a row's ``kind`` field (the producer inventory).
KINDS = ("bench", "serve_loadgen", "fleet_loadgen")

#: Bench-payload metric paths lifted into a ledger row (the same
#: dotted paths the bench-gate RULES table reads, so ``--trend`` can
#: rebuild a baseline payload from rolling medians 1:1).
BENCH_METRICS = (
    "value",
    "vs_baseline",
    "vs_baseline_steady_state",
    "device_solved",
    "device_median_te",
    "iters_p50",
    "iters_p95",
    "iters_max",
    "wasted_iteration_fraction",
    "xla_cost.flops",
    "xla_cost.bytes_accessed",
    "xla_cost.peak_bytes",
    "config_serving.throughput_solves_per_s",
    "config_serving.latency_p50_ms",
    "config_serving.latency_p99_ms",
    "config_serving.occupancy_mean",
    "config_serving.recompiles_after_warmup",
    "config_serving.cost_summary.bytes_accessed_max",
    "config_serving.cost_summary.peak_bytes_max",
    "config_compaction.recompiles_in_measured_solve",
    "config_compaction.te_drift",
    "config_compaction.lane_segments_reduction",
    "config_hlo.programs",
    "config_hlo.findings_total",
    "config_hlo.findings_max_per_program",
    "config_hlo.fingerprint_flips",
    "config_hlo.top_target_bytes",
    "config_calibration.recompiles_after_warmup",
    "config_calibration.harvest_reconciled",
    "config_calibration.unsolved",
    "config_calibration.promotions",
    "config_calibration.rollbacks",
    "config_calibration.route_table_version",
    "config_calibration.win_rate",
    "config_napg.napg_te_rel_drift",
    "config_napg.vs_baseline",
    "config_routing.napg_routed_any",
    "config_routing.recompiles_after_warmup",
    "config_northstar_5k.gram_rel_err",
    "config_northstar_5k.te_rel_drift_max",
    "config_northstar_5k.vs_dense",
    "config_northstar_5k.recompiles_after_warmup",
)

#: Loadgen-report metrics lifted into a ledger row. The
#: ``tenant_fairness.*`` paths exist only on multi-tenant runs
#: (``run_loadgen(tenants=...)`` — README "Multi-tenant serving &
#: workload library"); absent metrics are simply not recorded, same
#: as any older report shape.
LOADGEN_METRICS = (
    "throughput_solves_per_s",
    "latency_p50_ms",
    "latency_p99_ms",
    "occupancy_mean",
    "recompiles_after_warmup",
    "errors",
    "solved",
    "dropped_arrivals",
    "route_table_version",
    "tenant_fairness.tenants",
    "tenant_fairness.quiet_p99_ratio",
    "tenant_fairness.victim_shed_share",
    "tenant_fairness.offender_alerts",
    "tenant_fairness.nonoffender_alerts",
    "tenant_fairness.harvest_reconciled",
)

#: Fleet-report metrics lifted into a ledger row.
FLEET_METRICS = (
    "workers",
    "workers_lost",
    "duration_s",
    "fleet.completed",
    "fleet.failed",
    "fleet.dropped_arrivals",
    "fleet.throughput_solves_per_s",
    "fleet.harvest_records",
    "fleet.recompiles_after_warmup",
    "incident_bundles",
    "reconciled",
)


def git_rev(root: Optional[str] = None) -> Optional[str]:
    """Short git revision of ``root`` (best-effort: a ledger row from
    an exported tarball simply has no rev)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=root or os.getcwd(), capture_output=True, text=True,
            timeout=10)
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else None
    except (OSError, subprocess.SubprocessError):
        return None


def _lookup(payload: Dict[str, Any], dotted: str):
    cur: Any = payload
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _extract(payload: Dict[str, Any],
             paths: Iterable[str]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for path in paths:
        val = _lookup(payload, path)
        if isinstance(val, bool):
            val = int(val)
        if isinstance(val, (int, float)):
            out[path] = val
    return out


def metrics_from_bench(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Flat ``{dotted_path: value}`` metrics from one bench payload."""
    return _extract(payload, BENCH_METRICS)


def metrics_from_loadgen(report: Dict[str, Any]) -> Dict[str, Any]:
    """Flat metrics from one ``run_loadgen`` report."""
    return _extract(report, LOADGEN_METRICS)


def metrics_from_fleet(report: Dict[str, Any]) -> Dict[str, Any]:
    """Flat metrics from one ``fleet_loadgen`` merged report. The
    report's ``workers_lost`` is a list of worker ids; the ledger
    records its COUNT (a crash cell's loss must be visible in the
    trend series, and ids don't aggregate)."""
    out = _extract(report, FLEET_METRICS)
    lost = report.get("workers_lost")
    if isinstance(lost, (list, tuple)):
        out["workers_lost"] = len(lost)
    return out


def ledger_row(kind: str,
               metrics: Dict[str, Any],
               run_id: Optional[str] = None,
               rev: Optional[str] = None,
               gate: Optional[Dict[str, Any]] = None,
               artifact: Optional[str] = None,
               note: Optional[str] = None,
               t: Optional[float] = None) -> Dict[str, Any]:
    """Build one ledger row (the schema's single constructor).

    ``metrics`` is a FLAT dict of dotted metric paths; ``gate`` is a
    compact bench-gate verdict summary (``ok`` / ``n_pass`` /
    ``n_fail`` / ``failed``); ``run_id`` defaults to a
    ``<kind>-<unix-time>`` stamp and is the idempotency key backfill
    dedupes on."""
    if kind not in KINDS:
        raise ValueError(f"unknown ledger kind {kind!r}; known: "
                         f"{', '.join(KINDS)}")
    t = time.time() if t is None else float(t)
    row: Dict[str, Any] = {
        "v": LEDGER_SCHEMA_VERSION,
        "t": t,
        "run_id": run_id if run_id is not None else f"{kind}-{int(t)}",
        "kind": kind,
        "metrics": dict(metrics),
    }
    if rev is not None:
        row["rev"] = str(rev)
    if gate is not None:
        row["gate"] = {"ok": bool(gate.get("ok")),
                       "n_pass": gate.get("n_pass"),
                       "n_fail": gate.get("n_fail"),
                       "failed": list(gate.get("failed", ()))[:8]}
    if artifact is not None:
        row["artifact"] = str(artifact)
    if note is not None:
        row["note"] = str(note)
    return row


def append_row(path: str, row: Dict[str, Any]) -> Dict[str, Any]:
    """Append one row to the ledger file (one json.dumps line);
    returns the row. Plain O_APPEND semantics: concurrent producers
    each land a whole line."""
    with open(path, "a") as f:
        f.write(json.dumps(row, default=str) + "\n")
    return row


def load_ledger(path: str) -> List[Dict[str, Any]]:
    """Read a ledger back, oldest row first (blank lines skipped;
    a missing file is an empty ledger, not an error — the first run
    creates it)."""
    if not os.path.exists(path):
        return []
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _median(values: List[float]) -> float:
    vals = sorted(values)
    n = len(vals)
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


def rolling_median(rows: Iterable[Dict[str, Any]],
                   metric: str,
                   window: int = 5,
                   kind: Optional[str] = None) -> Optional[float]:
    """THE trend baseline: the median of ``metric`` over the last
    ``window`` rows that actually carry it (optionally restricted to
    one producer ``kind``). ``None`` when no row carries the metric —
    an empty series gates nothing, it never fails a candidate."""
    series = [float(r["metrics"][metric]) for r in rows
              if (kind is None or r.get("kind") == kind)
              and isinstance(r.get("metrics"), dict)
              and isinstance(r["metrics"].get(metric), (int, float))]
    if not series:
        return None
    return _median(series[-int(window):])


def nest_metrics(flat: Dict[str, Any]) -> Dict[str, Any]:
    """Re-nest a flat ``{dotted_path: value}`` dict into the payload
    shape the bench-gate rule table looks metrics up in."""
    out: Dict[str, Any] = {}
    for path, value in flat.items():
        cur = out
        parts = path.split(".")
        for part in parts[:-1]:
            cur = cur.setdefault(part, {})
            if not isinstance(cur, dict):  # pragma: no cover - key clash
                break
        else:
            cur[parts[-1]] = value
    return out
