"""Fleet telemetry federation: N worker processes, one obs plane.

Everything the obs stack built through the live operational plane is
single-process: every counter, alert, and incident bundle lives inside
one ``SolveService``. The millions-of-users loadgen regime (ROADMAP)
is multi-process by construction — N workers each with their own XLA
client, solve service, and open-loop arrival shard — so this module
federates their telemetry:

* :class:`WorkerStream` — the worker-side emitter: one append-only
  JSONL stream per worker (``hello`` / ``sample`` / ``event`` /
  ``heartbeat`` / ``report`` envelopes). ``emit`` never raises (same
  posture as :class:`~porqua_tpu.obs.harvest.HarvestSink`): a dead
  stream degrades to counting ``write_failures``, never to failing a
  solve. Cumulative ``sample`` envelopes carry the worker's raw
  ``ServeMetrics.slo_sample()`` counters + histogram state, a
  snapshot subset, and :func:`porqua_tpu.obs.vitals.process_vitals`.
* :class:`FleetCollector` — the parent-side aggregator: incrementally
  drains every worker stream (byte offsets, partial trailing lines
  left for the next drain), namespaces trace/request ids by worker
  (``w3/a1b2...``), merges fleet counters and **raw latency
  histograms** (bucket-count sums — never percentiles, which do not
  compose), evaluates fleet-wide SLOs and burn rates through the
  existing :class:`~porqua_tpu.obs.slo.SLOEngine` (the collector IS
  the engine's metrics source: it implements ``slo_sample()``),
  forwards worker events onto a fleet :class:`EventBus` (where the
  :class:`~porqua_tpu.obs.flight.FlightRecorder` listens), serves a
  fleet ``/metrics`` + ``/healthz`` with per-worker labeled gauges
  (``prometheus_text(labeled_gauges=)``), keeps **bounded** sustained-
  soak rollups (a fixed-size ring of per-window aggregates — never
  unbounded event retention), feeds per-worker vitals into
  :class:`~porqua_tpu.obs.vitals.VitalsTrend` leak detection, and
  tracks worker **liveness**: a stream that goes stale past
  ``heartbeat_timeout_s`` without a clean final ``report`` fires ONE
  ``worker_lost`` event — a flight-recorder trigger — so a crashed
  loadgen shard produces a fleet incident bundle, not a silent
  throughput dip.

``scripts/fleet_loadgen.py`` is the driver that wires both halves.
The whole plane is pure host file/dict code — no JAX import, nothing
traced; contract GC108 (:func:`porqua_tpu.analysis.contracts.
check_federation_identity`) machine-checks that a fully exercised
collector (drains, merges, a lost worker, a dumped bundle) leaves the
solve/serve jaxprs string-identical.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from porqua_tpu.analysis import tsan

__all__ = ["FleetCollector", "WorkerStream"]

#: Envelope kinds a worker stream may carry (unknown kinds are counted
#: and skipped — a newer worker must not wedge an older collector).
STREAM_KINDS = ("hello", "sample", "event", "heartbeat", "report")

#: Fields of ``ServeMetrics.slo_sample()`` merged by summation (the
#: latency histogram fields are merged element-wise separately).
_SLO_COUNTER_KEYS = ("completed", "failed", "expired", "retry_giveups",
                     "validation_failures")


class WorkerStream:
    """Worker-side JSONL telemetry emitter (one file per worker).

    Each line is one envelope: ``{"t": <unix>, "w": <worker_id>,
    "kind": <kind>, ...payload}``. Writes flush per line so the
    collector can tail the stream live; a mid-line crash leaves a
    partial trailing line the collector simply does not consume.
    Thread-safety: ``event`` runs on whatever thread emits (the
    worker's EventBus listener feed), ``sample``/``report`` on the
    worker's main loop — all writes are serialized by the lock.
    """

    def __init__(self, path: str, worker_id: str) -> None:
        self.path = str(path)
        self.worker_id = str(worker_id)
        self._lock = tsan.lock("WorkerStream")
        self._records = 0             # guarded-by: self._lock
        self._write_failures = 0      # guarded-by: self._lock
        self._sink = None             # guarded-by: self._lock
        try:
            self._sink = open(path, "a")
        except OSError:
            self._write_failures += 1

    def _emit(self, kind: str, **payload) -> None:
        """Append one envelope; never raises (a dead stream makes this
        worker go stale, which the collector's liveness tracking
        reports as ``worker_lost`` — exactly what it looks like from
        the fleet's side)."""
        rec = {"t": time.time(), "w": self.worker_id, "kind": kind}
        rec.update(payload)
        line = json.dumps(rec, default=str)
        with self._lock:
            if self._sink is None:
                self._write_failures += 1
                return
            try:
                self._sink.write(line + "\n")
                self._sink.flush()
            except (OSError, ValueError):
                self._write_failures += 1
                self._sink = None  # dead stream: keep the worker alive
            else:
                self._records += 1

    # -- envelope constructors ---------------------------------------

    def hello(self, latency_le=(), **meta) -> None:
        """Stream header: the worker's pid and latency-histogram
        ladder (the collector refuses to merge mismatched ladders —
        summed bucket counts would be meaningless)."""
        self._emit("hello", pid=os.getpid(),
                   latency_le=[float(b) for b in latency_le], **meta)

    def sample(self, slo: Dict[str, Any],
               hist: Optional[Dict[str, Any]] = None,
               snap: Optional[Dict[str, Any]] = None,
               vitals: Optional[Dict[str, Any]] = None) -> None:
        """One cumulative telemetry sample: raw ``slo_sample()``
        counters (+ optional ``histograms()`` state, snapshot subset,
        process vitals). Samples double as heartbeats."""
        payload: Dict[str, Any] = {"slo": slo}
        if hist is not None:
            payload["hist"] = hist
        if snap is not None:
            payload["snap"] = snap
        if vitals is not None:
            payload["vitals"] = vitals
        self._emit("sample", **payload)

    def event(self, event: Dict[str, Any]) -> None:
        """Forward one structured event record (an EventBus listener
        feeds this, so the fleet sees breaker flips, SLO alerts, and
        fault injections from every worker)."""
        self._emit("event", event=event)

    def heartbeat(self) -> None:
        self._emit("heartbeat")

    def report(self, report: Dict[str, Any]) -> None:
        """The worker's final merged report — also the clean-shutdown
        marker: a worker that reported is *finished*, never *lost*."""
        self._emit("report", report=report)

    # -- readers / lifecycle -----------------------------------------

    @property
    def records(self) -> int:
        with self._lock:
            return self._records

    @property
    def write_failures(self) -> int:
        with self._lock:
            return self._write_failures

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    self._write_failures += 1
                self._sink = None


class _WorkerState:
    """Collector-side per-worker state (guarded by the collector lock)."""

    __slots__ = ("worker_id", "path", "offset", "last_seen", "lost",
                 "finished", "refused", "hello", "slo", "hist", "snap",
                 "vitals", "report", "records", "events", "parse_errors",
                 "vitals_pending")

    def __init__(self, worker_id: str, path: str, now: float) -> None:
        self.worker_id = worker_id
        self.path = path
        self.offset = 0                 # consumed byte offset
        self.last_seen = now            # collector clock, not stream t
        self.lost = False
        self.finished = False
        self.refused = False            # sticky: ladder mismatch at hello
        self.hello: Optional[Dict[str, Any]] = None
        self.slo: Optional[Dict[str, Any]] = None
        self.hist: Optional[Dict[str, Any]] = None
        self.snap: Dict[str, Any] = {}
        self.vitals: Dict[str, Any] = {}
        self.report: Optional[Dict[str, Any]] = None
        self.records = 0
        self.events = 0
        self.parse_errors = 0
        self.vitals_pending = False     # new vitals since last trend obs


class FleetCollector:
    """Aggregate N worker telemetry streams into one fleet plane.

    The collector deliberately implements the :class:`ServeMetrics`
    *reader* surface the rest of the obs stack consumes —
    ``slo_sample()`` (the SLO engine's feed), ``snapshot()`` (the
    flight recorder's counter dump + the ``/metrics`` exposition),
    ``histograms()`` (merged raw latency histograms) — so the existing
    :class:`~porqua_tpu.obs.slo.SLOEngine` and
    :class:`~porqua_tpu.obs.flight.FlightRecorder` run over the fleet
    unchanged. ``events`` is the fleet bus: every worker event is
    re-emitted there with its trace id namespaced ``<worker>/<id>``
    and a ``worker`` field, and collector-originated events
    (``worker_lost``) land next to them.

    Thread-safety: ``drain``/``check_liveness`` run on the driver
    loop; the reader surface on scrape threads and (via the engine /
    recorder) on listener threads. All collector state is guarded by
    the instance lock; event emission, SLO evaluation, and vitals
    trending run OUTSIDE it — the flight recorder's dump path calls
    ``snapshot()`` back from inside an event listener, and the engine
    holds its own lock while reading ``slo_sample()`` (one-way
    engine -> collector edge, mirroring engine -> metrics).
    """

    def __init__(self,
                 heartbeat_timeout_s: float = 15.0,
                 rollup_window_s: float = 30.0,
                 rollup_capacity: int = 512,
                 events=None,
                 slo=None,
                 flight=None,
                 vitals_trend=None,
                 clock=None) -> None:
        from porqua_tpu.obs.events import EventBus

        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.rollup_window_s = float(rollup_window_s)
        self.clock = time.monotonic if clock is None else clock
        self.events = EventBus() if events is None else events
        self.slo = slo
        self.flight = flight
        self.vitals_trend = vitals_trend
        if slo is not None:
            slo.bind(self, events=self.events)
        if vitals_trend is not None and vitals_trend.events is None:
            vitals_trend.events = self.events
        if flight is not None:
            flight.attach(metrics=self, slo=slo)
            self.events.add_listener(flight.on_event)
        self._lock = tsan.lock("FleetCollector")
        # guarded-by: self._lock
        self._workers: Dict[str, _WorkerState] = {}
        self._records = 0               # guarded-by: self._lock
        self._events_forwarded = 0      # guarded-by: self._lock
        self._parse_errors = 0          # guarded-by: self._lock
        self._unknown_kinds = 0         # guarded-by: self._lock
        self._lost_total = 0            # guarded-by: self._lock
        self._refusals = 0              # guarded-by: self._lock
        self._latency_le: Optional[Tuple[float, ...]] = None  # guarded-by: self._lock
        self._start_mono = self.clock()
        self._start_wall = time.time()
        # Bounded soak rollups: one aggregate row per closed
        # rollup_window_s window, newest rollup_capacity kept.
        # guarded-by: self._lock
        self._rollups: "collections.deque[Dict[str, Any]]" = (
            collections.deque(maxlen=int(rollup_capacity)))
        self._window_idx = 0            # guarded-by: self._lock
        self._window_base: Dict[str, float] = {}  # guarded-by: self._lock
        self._http = None

    # -- wiring -------------------------------------------------------

    def add_worker(self, worker_id: str, path: str) -> None:
        """Register one worker stream (before or after the file
        exists — a not-yet-created stream is simply empty). The
        liveness clock starts at registration."""
        with self._lock:
            if worker_id in self._workers:
                raise ValueError(f"worker {worker_id!r} already registered")
            self._workers[worker_id] = _WorkerState(
                str(worker_id), str(path), self.clock())

    # -- draining -----------------------------------------------------

    @staticmethod
    def _read_new(st: _WorkerState) -> List[Dict[str, Any]]:
        """New COMPLETE lines from one stream since the last drain.
        A partial trailing line (mid-write, or mid-crash) is left
        unconsumed — the byte offset only advances past newlines."""
        try:
            with open(st.path, "rb") as f:
                f.seek(st.offset)
                chunk = f.read()
        except OSError:
            return []
        if not chunk:
            return []
        cut = chunk.rfind(b"\n")
        if cut < 0:
            return []
        chunk = chunk[:cut + 1]
        st.offset += len(chunk)
        out: List[Dict[str, Any]] = []
        for raw in chunk.split(b"\n"):
            if not raw.strip():
                continue
            try:
                out.append(json.loads(raw))
            except (ValueError, UnicodeDecodeError):
                st.parse_errors += 1
        return out

    def _ingest(self, st, rec, forward) -> Optional[str]:  # guarded-by: self._lock
        """Fold one envelope into the worker state. Returns an error
        string instead of raising so ``drain`` can finish the round
        (other workers' records and events must land — the byte
        offsets already advanced past them) before surfacing it."""
        kind = rec.get("kind")
        st.records += 1
        if kind == "hello":
            st.hello = rec
            le = tuple(float(b) for b in rec.get("latency_le", ()))
            if le:
                if self._latency_le is None:
                    self._latency_le = le
                elif self._latency_le != le:
                    # Sticky refusal: every merge surface skips this
                    # worker from now on — a caller that swallows the
                    # error and keeps draining must never see its
                    # mismatched buckets summed into the fleet's.
                    st.refused = True
                    st.slo = None
                    st.hist = None
                    return (
                        f"worker {st.worker_id!r} declares a latency "
                        f"histogram ladder different from the fleet's "
                        f"({le} vs {self._latency_le}); merged bucket "
                        f"counts would be meaningless — align "
                        f"ServeMetrics(latency_buckets=) across workers")
        elif kind == "sample":
            if st.refused:
                return None
            slo = rec.get("slo")
            if isinstance(slo, dict):
                st.slo = slo
            hist = rec.get("hist")
            if isinstance(hist, dict):
                st.hist = hist
            snap = rec.get("snap")
            if isinstance(snap, dict):
                st.snap = snap
            vitals = rec.get("vitals")
            if isinstance(vitals, dict):
                st.vitals = vitals
                st.vitals_pending = True
        elif kind == "event":
            ev = rec.get("event")
            if isinstance(ev, dict):
                st.events += 1
                forward.append((st.worker_id, ev))
        elif kind == "report":
            rep = rec.get("report")
            if isinstance(rep, dict):
                st.report = rep
            st.finished = True
        elif kind == "heartbeat":
            pass
        else:
            self._unknown_kinds += 1

    def drain(self) -> Dict[str, Any]:
        """Consume every stream's new lines, fold rollups, forward
        events, feed vitals trends, evaluate fleet SLOs, and check
        liveness. The driver loop calls this on its poll interval;
        call it one final time after the workers exit so the tail of
        every stream lands. Returns drain stats."""
        forward: List[Tuple[str, Dict[str, Any]]] = []
        vitals_obs: List[Tuple[str, Dict[str, Any]]] = []
        errors: List[str] = []
        now = self.clock()
        with self._lock:
            new_records = 0
            for st in self._workers.values():
                recs = self._read_new(st)
                if recs:
                    st.last_seen = now
                    new_records += len(recs)
                for rec in recs:
                    err = self._ingest(st, rec, forward)
                    if err is not None:
                        self._refusals += 1
                        errors.append(err)
                if st.vitals_pending:
                    st.vitals_pending = False
                    vitals_obs.append((st.worker_id, dict(st.vitals)))
                self._parse_errors += st.parse_errors
                st.parse_errors = 0
            self._records += new_records
            self._events_forwarded += len(forward)
            self._roll(now)
        # Everything below runs OUTSIDE the collector lock: emit()
        # fans out to the flight recorder, whose dump path reads
        # snapshot()/status() back through this collector's lock.
        for wid, ev in forward:
            self._forward(wid, ev)
        if self.vitals_trend is not None:
            for wid, v in vitals_obs:
                self.vitals_trend.observe(wid, v)
        if self._slo_ready():
            self.slo.maybe_evaluate()
        lost = self.check_liveness()
        if errors:
            # Raised once, on the drain that discovered the mismatch
            # — AFTER the round landed (the refusal itself is sticky,
            # so a supervisor that catches this and keeps polling gets
            # clean merges that simply exclude the refused worker).
            raise ValueError("; ".join(errors))
        return {"records": new_records, "events": len(forward),
                "workers_lost": lost}

    def _slo_ready(self) -> bool:
        """The fleet SLO engine only evaluates once at least one
        worker has declared its histogram ladder (``hello``): before
        that the merged latency histogram has no edges for the
        latency SLO to read a target off."""
        if self.slo is None:
            return False
        with self._lock:
            return self._latency_le is not None

    def _forward(self, worker_id: str, event: Dict[str, Any]) -> None:
        """Re-emit one worker event on the fleet bus, trace/request
        ids namespaced by worker so two workers' request #17 stay
        distinguishable in the merged log."""
        fields = {k: v for k, v in event.items()
                  if k not in ("kind", "severity", "trace_id")}
        for key in ("request_id",):
            if key in fields and fields[key] is not None:
                fields[key] = f"{worker_id}/{fields[key]}"
        fields["worker"] = worker_id
        trace_id = event.get("trace_id")
        self.events.emit(
            str(event.get("kind", "?")),
            str(event.get("severity", "info")),
            trace_id=(None if trace_id is None
                      else f"{worker_id}/{trace_id}"),
            **fields)

    # -- liveness -----------------------------------------------------

    def check_liveness(self, now: Optional[float] = None) -> List[str]:
        """Mark workers whose stream went stale past the heartbeat
        deadline as lost; emits ONE ``worker_lost`` event each (the
        flight-recorder trigger). A worker that sent its final
        ``report`` is finished, never lost. Returns the newly-lost
        worker ids."""
        now = self.clock() if now is None else float(now)
        newly: List[Tuple[str, float]] = []
        with self._lock:
            for st in self._workers.values():
                if st.lost or st.finished:
                    continue
                age = now - st.last_seen
                if age > self.heartbeat_timeout_s:
                    st.lost = True
                    self._lost_total += 1
                    newly.append((st.worker_id, age))
        for wid, age in newly:  # outside the lock: emit -> flight dump
            self.events.emit(
                "worker_lost", "error", worker=wid,
                stale_s=round(age, 3),
                heartbeat_timeout_s=self.heartbeat_timeout_s,
                last_completed=self._worker_completed(wid))
        return [wid for wid, _ in newly]

    def _worker_completed(self, worker_id: str) -> Optional[int]:
        with self._lock:
            st = self._workers.get(worker_id)
            if st is None or st.slo is None:
                return None
            return int(st.slo.get("completed", 0))

    # -- rollups ------------------------------------------------------

    def _totals(self) -> Dict[str, float]:  # guarded-by: self._lock
        out = {k: 0.0 for k in _SLO_COUNTER_KEYS}
        out["latency_count"] = 0.0
        for st in self._workers.values():
            if st.slo is None or st.refused:
                continue
            for k in _SLO_COUNTER_KEYS:
                out[k] += float(st.slo.get(k, 0))
            out["latency_count"] += float(st.slo.get("latency_count", 0))
        return out

    def _totals_tenants(self) -> Dict[str, Dict[str, float]]:  # guarded-by: self._lock
        """Fleet-wide per-tenant counter sums from the workers' latest
        snapshot sections (counters only — per-tenant latency
        percentiles do NOT compose across workers and are never
        merged; the fleet-level latency story stays with the raw
        merged histograms)."""
        out: Dict[str, Dict[str, float]] = {}
        for st in self._workers.values():
            if st.refused:
                continue
            tenants = st.snap.get("tenants")
            if not isinstance(tenants, dict):
                continue
            for tenant, row in tenants.items():
                if not isinstance(row, dict):
                    continue
                tgt = out.setdefault(str(tenant), {})
                for k, v in row.items():
                    if k.startswith("latency_"):
                        continue
                    if isinstance(v, bool) or not isinstance(
                            v, (int, float)):
                        continue
                    tgt[k] = tgt.get(k, 0.0) + float(v)
        return out

    def _roll(self, now: float) -> None:  # guarded-by: self._lock
        """Close any elapsed rollup window: one bounded aggregate row
        of the fleet's *deltas* over the window plus the vitals
        high-water marks — the whole sustained-soak record the
        collector retains (the ring is the memory bound; individual
        samples/events are never retained past their drain)."""
        idx = int((now - self._start_mono) // self.rollup_window_s)
        if idx <= self._window_idx:
            return
        totals = self._totals()
        base = self._window_base
        active = [st for st in self._workers.values()
                  if not st.lost and not st.finished]
        row = {
            "window": self._window_idx,
            "t": time.time(),
            # A poll stall can close several windows at once; the row
            # then carries every elapsed window's deltas, so its span
            # must say so — rates derived from rollups stay honest.
            "span_s": (idx - self._window_idx) * self.rollup_window_s,
            "workers_active": len(active),
            **{k: totals[k] - base.get(k, 0.0) for k in totals},
        }
        # Vitals aggregate over ACTIVE workers only: a dead worker's
        # process is gone, so folding its last pre-crash sample into
        # every later window would inflate the soak's memory record.
        rss = [float(st.vitals["rss_bytes"]) for st in active
               if st.vitals.get("rss_bytes") is not None]
        if rss:
            row["rss_max_bytes"] = max(rss)
            row["rss_sum_bytes"] = sum(rss)
        depth = [float(st.vitals["queue_depth"]) for st in active
                 if st.vitals.get("queue_depth") is not None]
        if depth:
            row["queue_depth_max"] = max(depth)
        self._rollups.append(row)
        self._window_base = totals
        self._window_idx = idx

    def rollups(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            rows = list(self._rollups)
        return rows if last is None else rows[-int(last):]

    # -- the ServeMetrics reader surface ------------------------------

    def slo_sample(self) -> Dict[str, Any]:
        """The fleet's cumulative SLO sample: worker counters summed,
        RAW latency histograms merged bucket-wise (the engine reads
        good/bad counts off exact bucket edges of the merged
        histogram — percentiles are never merged, they do not
        compose). Workers whose ladder disagrees were refused at
        ``hello``, so the element-wise sum is well-defined."""
        with self._lock:
            totals = self._totals()
            le = self._latency_le or ()
            counts = [0] * (len(le) + 1)
            for st in self._workers.values():
                if st.slo is None or st.refused:
                    continue
                wc = st.slo.get("latency_counts", ())
                for i, c in enumerate(wc):
                    if i < len(counts):
                        counts[i] += int(c)
            return {
                **{k: int(totals[k]) for k in _SLO_COUNTER_KEYS},
                "latency_le": tuple(le),
                "latency_counts": tuple(counts),
                "latency_count": int(totals["latency_count"]),
            }

    def histograms(self) -> Dict[str, Dict[str, Any]]:
        """Merged cumulative histogram state in the
        ``ServeMetrics.histograms()`` shape (the Prometheus renderer
        consumes it unchanged)."""
        with self._lock:
            merged: Dict[str, Dict[str, Any]] = {}
            for st in self._workers.values():
                if not st.hist or st.refused:
                    continue
                for name, h in st.hist.items():
                    le = tuple(float(b) for b in h.get("le", ()))
                    tgt = merged.get(name)
                    if tgt is None:
                        merged[name] = {"le": le,
                                        "counts": [int(c) for c
                                                   in h.get("counts", ())],
                                        "sum": float(h.get("sum", 0.0)),
                                        "count": int(h.get("count", 0))}
                        continue
                    if tgt["le"] != le:
                        continue  # refused at hello; belt-and-braces
                    for i, c in enumerate(h.get("counts", ())):
                        if i < len(tgt["counts"]):
                            tgt["counts"][i] += int(c)
                    tgt["sum"] += float(h.get("sum", 0.0))
                    tgt["count"] += int(h.get("count", 0))
            return merged

    def snapshot(self) -> Dict[str, Any]:
        """One JSON-able fleet snapshot: merged counters, liveness
        totals, and derived throughput — the ``/metrics`` exposition
        body and the flight bundle's ``counters`` section."""
        with self._lock:
            totals = self._totals()
            elapsed = self.clock() - self._start_mono
            snap_keys: Dict[str, float] = {}
            snap_n: Dict[str, int] = {}
            for st in self._workers.values():
                if st.refused:
                    continue
                for k, v in st.snap.items():
                    if isinstance(v, bool) or not isinstance(v, (int, float)):
                        continue
                    snap_keys[k] = snap_keys.get(k, 0.0) + float(v)
                    snap_n[k] = snap_n.get(k, 0) + 1
            # Mean-shaped keys (occupancy_mean, ...) average across the
            # contributing workers — 4 workers at 0.8 occupancy are a
            # fleet at 0.8, not an impossible 3.2.
            for k, n in snap_n.items():
                if k.endswith("_mean") and n > 1:
                    snap_keys[k] /= n
            lost = sum(1 for st in self._workers.values() if st.lost)
            finished = sum(1 for st in self._workers.values()
                           if st.finished)
            out: Dict[str, Any] = {
                "t": time.time(),
                "window_seconds": elapsed,
                **snap_keys,
                **{k: int(v) for k, v in totals.items()
                   if k != "latency_count"},
                "workers": len(self._workers),
                "workers_lost": lost,
                "workers_finished": finished,
                "throughput_solves_per_s": (
                    totals["completed"] / elapsed if elapsed > 0 else 0.0),
                "rollup_windows": len(self._rollups),
            }
            tenants = self._totals_tenants()
            if tenants:
                out["tenants"] = {t: {k: int(v) for k, v in row.items()}
                                  for t, row in sorted(tenants.items())}
            return out

    def worker_gauges(self) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
        """Per-worker labeled gauge series for
        ``prometheus_text(labeled_gauges=)``: completed/failed
        counters, liveness (``worker_up``), and the last vitals sample
        — each labeled ``{worker="<id>"}``."""
        with self._lock:
            series: Dict[str, List[Tuple[Dict[str, str], float]]] = {
                "worker_up": [], "worker_completed": [],
                "worker_failed": [], "worker_rss_bytes": [],
                "worker_open_fds": [], "worker_threads": [],
                "worker_queue_depth": [],
            }
            for wid, st in sorted(self._workers.items()):
                lbl = {"worker": wid}
                series["worker_up"].append(
                    (lbl, 0.0 if st.lost else 1.0))
                if st.slo is not None:
                    series["worker_completed"].append(
                        (lbl, float(st.slo.get("completed", 0))))
                    series["worker_failed"].append(
                        (lbl, float(st.slo.get("failed", 0))))
                if st.lost or st.finished or st.refused:
                    # The process is gone (or was never merged): a
                    # frozen last-known vitals gauge would read as a
                    # live sample. worker_up already says why.
                    continue
                for key, name in (("rss_bytes", "worker_rss_bytes"),
                                  ("open_fds", "worker_open_fds"),
                                  ("threads", "worker_threads"),
                                  ("queue_depth", "worker_queue_depth")):
                    v = st.vitals.get(key)
                    if v is not None:
                        series[name].append((lbl, float(v)))
            # Fleet-wide per-tenant labeled series (merged across
            # workers): porqua_fleet_tenant_<counter>{tenant="..."}.
            for tenant, row in sorted(self._totals_tenants().items()):
                lbl = {"tenant": tenant}
                for k, v in row.items():
                    series.setdefault(f"tenant_{k}", []).append(
                        (lbl, float(v)))
            return {k: v for k, v in series.items() if v}

    def counters(self) -> Dict[str, int]:
        """Collector health counters (``/metrics`` extra_counters)."""
        with self._lock:
            return {"fleet_records_drained": self._records,
                    "fleet_events_forwarded": self._events_forwarded,
                    "fleet_parse_errors": self._parse_errors,
                    "fleet_unknown_kinds": self._unknown_kinds,
                    "fleet_workers_lost": self._lost_total,
                    "fleet_ladder_refusals": self._refusals}

    # -- reporting ----------------------------------------------------

    def worker_rows(self) -> List[Dict[str, Any]]:
        """Per-worker summary rows. A finished worker's row comes from
        its final report; a lost/running worker's from its last-seen
        cumulative sample (so the merged totals reconcile over exactly
        the numbers the rows show)."""
        with self._lock:
            rows = []
            for wid, st in sorted(self._workers.items()):
                status = ("refused" if st.refused
                          else "lost" if st.lost
                          else "ok" if st.finished else "running")
                row: Dict[str, Any] = {"worker": wid, "status": status,
                                       "stream_records": st.records,
                                       "events": st.events}
                if st.report is not None:
                    for k in ("completed", "failed", "errors",
                              "dropped_arrivals", "harvest_records",
                              "recompiles_after_warmup",
                              "throughput_solves_per_s",
                              "latency_p50_ms", "latency_p99_ms",
                              "status_counts"):
                        if k in st.report:
                            row[k] = st.report[k]
                elif st.slo is not None:
                    row["completed"] = int(st.slo.get("completed", 0))
                    row["failed"] = int(st.slo.get("failed", 0))
                if st.vitals:
                    row["vitals"] = {k: st.vitals[k] for k in
                                     ("rss_bytes", "open_fds", "threads",
                                      "queue_depth") if k in st.vitals}
                rows.append(row)
            return rows

    def report(self) -> Dict[str, Any]:
        """The merged fleet report + exact reconciliation: fleet
        ``completed`` is DEFINED as the sum over the per-worker rows,
        and the ``reconciliation`` section re-derives it from the
        independently-merged SLO sample and the workers' harvest
        counts — over the surviving (non-lost) workers the three
        numbers must agree exactly, crash or no crash."""
        rows = self.worker_rows()
        sample = self.slo_sample()
        lost_ids = [r["worker"] for r in rows if r["status"] == "lost"]
        # Refused workers (ladder mismatch) were never merged into the
        # SLO sample, so they stay out of the row sums too — both sides
        # of every reconciliation identity cover the same workers.
        merged = [r for r in rows if r["status"] != "refused"]
        completed_rows = sum(int(r.get("completed", 0)) for r in merged)
        surv = [r for r in merged if r["status"] != "lost"]
        surv_completed = sum(int(r.get("completed", 0)) for r in surv)
        surv_harvest = sum(int(r["harvest_records"]) for r in surv
                           if "harvest_records" in r)
        harvest_known = any("harvest_records" in r for r in surv)
        recon = {
            # The merged cumulative sample vs the per-row sum: every
            # worker's latest counters made it through the merge.
            "completed_sample_equals_rows": (
                int(sample["completed"]) == completed_rows),
            # Survivors' harvest datasets vs survivors' completions:
            # one SolveRecord per resolved request, no double-count.
            "harvest_equals_completed": (
                surv_harvest == surv_completed if harvest_known
                else None),
        }
        reconciled = all(v for v in recon.values() if v is not None)
        elapsed = self.clock() - self._start_mono
        # Fleet throughput: the sum of the workers' own measured-window
        # rates (each worker times exactly its soak window). Collector
        # lifetime is NOT the denominator — it starts before spawn +
        # prewarm + warmup, so completed/elapsed would deflate with
        # host compile speed and poison the trend-gated ledger series.
        # Mid-run (no reports yet) the lifetime rate is all there is.
        row_thr = [float(r["throughput_solves_per_s"]) for r in surv
                   if isinstance(r.get("throughput_solves_per_s"),
                                 (int, float))]
        out: Dict[str, Any] = {
            "workers": len(rows),
            "workers_lost": lost_ids,
            "rows": rows,
            "fleet": {
                "completed": completed_rows,
                "failed": sum(int(r.get("failed", 0)) for r in merged),
                "dropped_arrivals": sum(
                    int(r.get("dropped_arrivals", 0)) for r in merged),
                "harvest_records": surv_harvest if harvest_known else None,
                "recompiles_after_warmup": (
                    sum(int(r["recompiles_after_warmup"]) for r in surv
                        if "recompiles_after_warmup" in r)
                    if any("recompiles_after_warmup" in r for r in surv)
                    else None),
                "throughput_solves_per_s": (
                    sum(row_thr) if row_thr
                    else completed_rows / elapsed if elapsed > 0
                    else 0.0),
            },
            "reconciliation": recon,
            "reconciled": reconciled,
            "collector": self.counters(),
            "rollups_tail": self.rollups(last=8),
            "rollup_windows": len(self.rollups()),
        }
        snap_tenants = self.snapshot().get("tenants")
        if snap_tenants:
            # The fleet tenant axis: merged per-tenant counters (the
            # per-worker split stays in the rows' own reports).
            out["tenants"] = snap_tenants
        if self.slo is not None:
            out["slo"] = self.slo.status()
        if self.vitals_trend is not None:
            vt = self.vitals_trend.status()
            out["vitals_anomalies"] = vt["fired"]
            out["vitals_anomalous"] = vt["anomalous"]
        if self.flight is not None:
            fc = self.flight.counters()
            out["incident_bundles"] = fc["flight_bundles"]
            out["incident_bundle_paths"] = [
                p for p in self.flight.bundles() if isinstance(p, str)][:8]
        return out

    # -- exposition ---------------------------------------------------

    def start_http(self, port: int = 0, host: str = "127.0.0.1") -> int:
        """The fleet ``/metrics`` + ``/healthz`` endpoint: merged
        snapshot + merged histograms + per-worker labeled gauges +
        fleet SLO gauges, served by the same stdlib
        :class:`~porqua_tpu.obs.exposition.ObsHTTPServer` the single
        service uses. Returns the bound port."""
        from porqua_tpu.obs.exposition import ObsHTTPServer, prometheus_text

        def metrics_fn() -> str:
            extra_gauges = None
            if self._slo_ready():
                self.slo.maybe_evaluate()
                extra_gauges = self.slo.gauges()
            extra_counters = dict(self.counters())
            extra_counters["events_dropped"] = self.events.dropped
            if self.flight is not None:
                extra_counters.update(self.flight.counters())
            if self.vitals_trend is not None:
                extra_counters.update(self.vitals_trend.counters())
            return prometheus_text(
                self.snapshot(), prefix="porqua_fleet",
                histograms=self.histograms(),
                extra_counters=extra_counters,
                extra_gauges=extra_gauges,
                labeled_gauges=self.worker_gauges())

        def health_fn() -> Dict[str, Any]:
            snap = self.snapshot()
            payload: Dict[str, Any] = {
                # A fleet with every worker lost is down; a fleet with
                # SOME workers lost is degraded-but-serving (same
                # posture as the breaker: slowdown, not outage).
                "ok": snap["workers_lost"] < max(snap["workers"], 1),
                "workers": snap["workers"],
                "workers_lost": snap["workers_lost"],
                "workers_finished": snap["workers_finished"],
                "completed": snap.get("completed", 0),
                **self.counters(),
            }
            if self._slo_ready():
                self.slo.maybe_evaluate()
            if self.slo is not None:
                payload["slo"] = self.slo.status()
            if self.vitals_trend is not None:
                payload["vitals"] = self.vitals_trend.status()
            return payload

        if self._http is None:
            self._http = ObsHTTPServer(metrics_fn=metrics_fn,
                                       health_fn=health_fn,
                                       host=host, port=port)
        return self._http.start()

    def stop_http(self) -> None:
        if self._http is not None:
            self._http.stop()
            self._http = None
