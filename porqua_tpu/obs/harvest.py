"""The solver telemetry warehouse: per-solve harvest records.

The convergence rings (:mod:`porqua_tpu.obs.rings`) already record the
residual trajectory of every solve on device, and the serve/bench
stacks ship iteration *distributions* — but nothing persisted
per-solve trajectories joined with problem features, so the ROADMAP's
learned-adaptive-policy work ("Learning context-aware adaptive solvers
to accelerate quadratic programming", "A Learning-Based Inexact ADMM",
PAPERS.md) had no dataset to fit on. This module closes that gap:

* :func:`solve_record` — ONE schema (``SCHEMA_VERSION``) for a solved
  problem wherever it was solved: problem features (n, m, eps bucket,
  warm-start provenance), outcomes (status, iters, segments, final
  residuals, objective), the decoded ring trajectory (rho trace
  included), timing (wall/solve seconds, device), correlation ids
  (trace id, source), and optional compaction / stage-profile stats.
* :class:`HarvestSink` — a thread-safe, append-only JSONL (``.gz``
  transparently gzipped) dataset writer. ``emit`` never raises and
  never blocks on anything but its lock + one buffered write: it runs
  on the serve dispatch thread, so a dead disk degrades to counting
  ``write_failures`` (surfaced in ``/metrics`` and ``/healthz``), not
  to failing solves.
* :func:`harvest_solution` — the batched-producer bridge: explode one
  stacked :class:`~porqua_tpu.qp.solve.QPSolution` (vmap batch,
  compacted batch, or one scan-driver chunk) into per-lane records.
* :func:`load_harvest` / :func:`aggregate` — the reader half:
  ``scripts/harvest_report.py`` renders :func:`aggregate`'s
  policy-ready table (per-(bucket, eps) iteration quantiles,
  wasted-iteration attribution, warm-vs-cold deltas).

Harvesting is pure host post-processing of arrays the producers
already fetched (or fetch once, after the timed region): a disabled
sink is a ``None`` check, and the enabled path reads device results
without touching the jitted programs — contract GC105
(:func:`porqua_tpu.analysis.contracts.check_telemetry_identity`)
machine-checks that the traced solve/serve programs are
string-identical with the telemetry plane active.
"""

from __future__ import annotations

import gzip
import json
import time
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from porqua_tpu.analysis import tsan
from porqua_tpu.obs.rings import ring_history

__all__ = [
    "SCHEMA_VERSION",
    "HarvestSink",
    "aggregate",
    "device_label_of",
    "harvest_solution",
    "load_harvest",
    "solve_record",
]


def device_label_of(tree) -> Optional[str]:
    """Best-effort ``platform:id`` label of the device holding a
    solution pytree (jax-version tolerant; ``None`` for host numpy —
    this module itself never imports jax at module level)."""
    try:
        import jax

        leaf = jax.tree.leaves(tree)[0]
        dev = getattr(leaf, "device", None)
        if callable(dev):  # older jax: .device() method
            dev = dev()
        if dev is None:
            dev = next(iter(leaf.devices()))
        return f"{dev.platform}:{dev.id}"
    except Exception:  # noqa: BLE001 - labeling must never fail a solve
        return None

#: Bump when a field changes meaning; additive fields don't need it.
#: v2: records carry a ``tenant`` id (always — untagged producers
#: write :data:`DEFAULT_TENANT`) and :func:`aggregate` groups per
#: ``(tenant, bucket, eps)``. v1 records (e.g. the committed
#: ``HARVEST_r07.json``) load unchanged with ``tenant`` defaulting to
#: :data:`LEGACY_TENANT` — the sentinel keeps pre-tenant history
#: distinguishable from a real ``"default"``-lane record.
SCHEMA_VERSION = 2

#: The tenant id a tenancy-unaware producer writes (matches
#: ``porqua_tpu.serve.tenancy.DEFAULT_TENANT`` — literal here so the
#: warehouse stays import-light).
DEFAULT_TENANT = "default"

#: What a v1 record's missing ``tenant`` field reads as.
LEGACY_TENANT = "(pre-tenant)"

#: Known values of a record's ``source`` field (producer provenance).
SOURCES = ("serve", "serve.continuous", "serve.shadow", "batch",
           "batch.compacted", "backtest.scan")


def solve_record(source: str,
                 n: int,
                 m: int,
                 status: int,
                 iters: int,
                 prim_res: float,
                 dual_res: float,
                 obj_val: float,
                 params=None,
                 bucket: Optional[str] = None,
                 warm: bool = False,
                 warm_src: Optional[str] = None,
                 wall_s: Optional[float] = None,
                 solve_s: Optional[float] = None,
                 device: Optional[str] = None,
                 trace_id: Optional[str] = None,
                 ring: Optional[Dict[str, Any]] = None,
                 segments: Optional[int] = None,
                 batch: Optional[int] = None,
                 compaction: Optional[Dict[str, Any]] = None,
                 profile: Optional[Dict[str, Any]] = None,
                 tenant: Optional[str] = None,
                 **extra) -> Dict[str, Any]:
    """Build one SolveRecord dict (the schema's single constructor —
    every producer goes through here so fields cannot drift apart).

    ``params`` is the :class:`~porqua_tpu.qp.solve.SolverParams` the
    solve ran with; its tolerance/iteration knobs are flattened into
    the record (they are problem features for a learned policy, not
    metadata). ``ring`` is a decoded trajectory from
    :func:`porqua_tpu.obs.rings.ring_history` — its ``rho`` list IS
    the rho trace. ``segments`` defaults to the executed-segment count
    derived from ``iters`` and the params' check interval. ``batch``
    is the dispatch width this lane solved inside (``solve_s`` is the
    whole dispatch's device seconds, shared by its lanes)."""
    rec: Dict[str, Any] = {
        "v": SCHEMA_VERSION,
        "t": time.time(),
        "source": source,
        # Always present since v2 (DEFAULT_TENANT for tenancy-unaware
        # producers) so per-tenant reconciliation — tenant completed
        # == tenant records — holds by construction.
        "tenant": str(tenant) if tenant is not None else DEFAULT_TENANT,
        "n": int(n),
        "m": int(m),
        "status": int(status),
        "iters": int(iters),
        "prim_res": float(prim_res),
        "dual_res": float(dual_res),
        "obj_val": float(obj_val),
        "warm": bool(warm),
    }
    if params is not None:
        rec["eps_abs"] = float(params.eps_abs)
        rec["eps_rel"] = float(params.eps_rel)
        rec["max_iter"] = int(params.max_iter)
        rec["check_interval"] = int(params.check_interval)
        # Which first-order backend produced the lane ("admm" | "pdhg")
        # — the routing tables train on this axis. Additive: records
        # predating the field (or written without params) read back as
        # "admm" everywhere (aggregate / harvest_report), which is what
        # every pre-PDHG record actually ran. An explicit ``solver=``
        # kwarg (e.g. shadow-compare records) overrides via ``extra``.
        rec["solver"] = str(getattr(params, "method", "admm"))
        if segments is None:
            ci = int(params.check_interval)
            segments = max(-(-int(iters) // ci), 1)
    rec["bucket"] = bucket if bucket is not None else f"{int(n)}x{int(m)}"
    if segments is not None:
        rec["segments"] = int(segments)
    if warm_src is not None:
        rec["warm_src"] = str(warm_src)
    if wall_s is not None:
        rec["wall_s"] = float(wall_s)
    if solve_s is not None:
        rec["solve_s"] = float(solve_s)
    if batch is not None:
        rec["batch"] = int(batch)
    if device is not None:
        rec["device"] = str(device)
    if trace_id is not None:
        rec["trace_id"] = str(trace_id)
    if ring is not None:
        rec["ring"] = ring
    if compaction is not None:
        rec["compaction"] = compaction
    if profile is not None:
        rec["profile"] = profile
    rec.update(extra)
    return rec


class HarvestSink:
    """Thread-safe append-only SolveRecord dataset.

    ``path`` ending in ``.gz`` writes through :mod:`gzip`
    transparently; ``path=None`` keeps an in-memory bounded buffer
    (tests, short diagnostic runs). ``emit`` is called from serving
    hot paths, so it NEVER raises: a broken sink counts
    ``write_failures`` (and emits one ``harvest_sink_failed`` event
    when an :class:`~porqua_tpu.obs.events.EventBus` was given) and
    keeps serving. Counters are exposed in the Prometheus exposition
    and the ``/healthz`` payload via ``SolveService``.
    """

    def __init__(self, path: Optional[str] = None,
                 events=None, buffer_capacity: int = 65536) -> None:
        self.path = path
        self.events = events
        self._lock = tsan.lock("HarvestSink")
        self._records = 0                 # guarded-by: self._lock
        self._write_failures = 0          # guarded-by: self._lock
        self._dropped = 0                 # guarded-by: self._lock
        self._buffer_capacity = int(buffer_capacity)
        self._buffer: List[Dict[str, Any]] = []  # guarded-by: self._lock
        self._sink = None                 # guarded-by: self._lock
        if path is not None:
            try:
                self._sink = (gzip.open(path, "at")
                              if str(path).endswith(".gz")
                              else open(path, "a"))
            except OSError as exc:
                self._write_failures += 1
                self._note_failure(exc)

    def _note_failure(self, exc) -> None:
        if self.events is not None:
            self.events.emit("harvest_sink_failed", "error",
                             path=str(self.path),
                             error=f"{type(exc).__name__}: {exc}")

    def emit(self, record: Dict[str, Any]) -> None:
        """Append one record; never raises (see class docstring)."""
        # Serialize only for a live file sink: the in-memory buffer
        # stores the dict, and a dead sink drops the record — neither
        # should pay a per-record json.dumps of the ring trajectory on
        # the dispatch thread (unlocked read is a one-way race: _sink
        # only ever transitions to None).
        line = (json.dumps(record, default=str)
                if self._sink is not None else None)
        failed = None
        with self._lock:
            self._records += 1
            if self._sink is not None and line is not None:
                try:
                    self._sink.write(line + "\n")
                except (OSError, ValueError) as exc:
                    # ValueError: write on a closed file — a racing
                    # close() is a shutdown artifact, not a crash.
                    self._write_failures += 1
                    self._sink = None  # dead sink: keep serving
                    failed = exc
            elif self.path is None:
                if len(self._buffer) < self._buffer_capacity:
                    self._buffer.append(record)
                else:
                    self._dropped += 1
            else:
                # File sink died earlier; count what the dataset lost.
                self._dropped += 1
        if failed is not None:
            self._note_failure(failed)

    # -- readers -----------------------------------------------------

    @property
    def records(self) -> int:
        with self._lock:
            return self._records

    @property
    def write_failures(self) -> int:
        with self._lock:
            return self._write_failures

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def buffered(self) -> List[Dict[str, Any]]:
        """In-memory records (``path=None`` sinks only)."""
        with self._lock:
            return list(self._buffer)

    def counters(self) -> Dict[str, int]:
        """One dict of the sink's health counters, for exposition."""
        with self._lock:
            return {"harvest_records": self._records,
                    "harvest_write_failures": self._write_failures,
                    "harvest_dropped": self._dropped}

    def flush(self) -> None:
        failed = None
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.flush()
                except OSError as exc:
                    # Same posture (and the same event) as an emit-time
                    # failure: a disk that fills between the last emit
                    # and the end-of-run flush lost buffered tail
                    # records, and the event log must say so.
                    self._write_failures += 1
                    self._sink = None
                    failed = exc
        if failed is not None:
            self._note_failure(failed)

    def close(self) -> None:
        failed = None
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError as exc:
                    self._write_failures += 1
                    failed = exc
                self._sink = None
        if failed is not None:
            self._note_failure(failed)

    def __enter__(self) -> "HarvestSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def load_harvest(path: str) -> List[Dict[str, Any]]:
    """Read a harvest dataset (JSONL, ``.gz`` transparently) back into
    a list of record dicts; blank lines skipped."""
    opener = gzip.open if str(path).endswith(".gz") else open
    out: List[Dict[str, Any]] = []
    with opener(path, "rt") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# ---------------------------------------------------------------------------
# batched producers
# ---------------------------------------------------------------------------

def harvest_solution(sink: Optional[HarvestSink],
                     solution,
                     params,
                     source: str,
                     n: Optional[int] = None,
                     m: Optional[int] = None,
                     wall_s: Optional[float] = None,
                     solve_s: Optional[float] = None,
                     device: Optional[str] = None,
                     warm: bool = False,
                     warm_src: Optional[str] = None,
                     warm_mask=None,
                     compaction: Optional[Dict[str, Any]] = None,
                     profile: Optional[Dict[str, Any]] = None,
                     date_offset: int = 0,
                     tenant: Optional[str] = None) -> int:
    """Explode one (possibly batched) QPSolution into SolveRecords.

    The shared device->dataset bridge for every batched producer
    (``batch.solve_batch``, the compacting driver wrapper, the
    checkpointed scan driver): fetches the outcome arrays ONCE (host
    numpy — the producers have already left their timed region),
    decodes each lane's ring trajectory when the solve carried rings,
    and emits one record per lane. ``warm_mask`` (a per-lane boolean
    sequence) overrides the batch-wide ``warm`` flag where lanes
    differ — e.g. a scan chunk whose first date solves from the cold
    initial carry while the rest chain warm starts (a cold lane's
    record drops ``warm_src`` too, so the warm-vs-cold aggregation
    stays unbiased). Returns the number of records emitted;
    ``sink=None`` emits nothing and touches nothing."""
    if sink is None:
        return 0
    xs = np.atleast_2d(np.asarray(solution.x))
    status = np.atleast_1d(np.asarray(solution.status))
    iters = np.atleast_1d(np.asarray(solution.iters))
    prim = np.atleast_1d(np.asarray(solution.prim_res))
    dual = np.atleast_1d(np.asarray(solution.dual_res))
    obj = np.atleast_1d(np.asarray(solution.obj_val))
    ys = np.atleast_2d(np.asarray(solution.y))
    rp = getattr(solution, "ring_prim", None)
    if rp is not None:
        rp = np.atleast_2d(np.asarray(rp))
        rd = np.atleast_2d(np.asarray(solution.ring_dual))
        rr = np.atleast_2d(np.asarray(solution.ring_rho))
    B = int(status.shape[0])
    n = int(xs.shape[-1]) if n is None else int(n)
    m = int(ys.shape[-1]) if m is None else int(m)
    for i in range(B):
        ring = None
        if rp is not None:
            ring = ring_history(rp[i], rd[i], rr[i], int(iters[i]),
                                int(params.check_interval))
        lane_warm = bool(warm_mask[i]) if warm_mask is not None else warm
        sink.emit(solve_record(
            source, n, m, int(status[i]), int(iters[i]),
            float(prim[i]), float(dual[i]), float(obj[i]),
            params=params, warm=lane_warm,
            warm_src=warm_src if lane_warm else None,
            wall_s=wall_s, solve_s=solve_s, device=device,
            ring=ring, batch=B, compaction=compaction, profile=profile,
            tenant=tenant, lane=int(date_offset) + i))
    return B


# ---------------------------------------------------------------------------
# the policy-ready aggregation (scripts/harvest_report.py renders it)
# ---------------------------------------------------------------------------

def _quantiles(values: List[float]) -> Dict[str, float]:
    a = np.asarray(values, dtype=np.float64)
    if not a.size:
        return {"p50": 0.0, "p95": 0.0, "max": 0.0, "mean": 0.0}
    return {"p50": float(np.percentile(a, 50)),
            "p95": float(np.percentile(a, 95)),
            "max": float(a.max()),
            "mean": float(a.mean())}


def aggregate(records: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Roll a harvest dataset up into the policy-ready table.

    Per ``(tenant, bucket, eps_abs)`` group (since schema v2 —
    tenancy is the workload-segmentation axis the learned-policy loop
    needs; v1 records group under :data:`LEGACY_TENANT`): record
    count, iteration quantiles, status counts, the group's
    wasted-iteration attribution (``1 - sum(segments) /
    (count * max(segments))`` — the straggler tax a fused batch of
    exactly this group would pay), and the warm-vs-cold
    mean-iteration delta (negative = warm starts help, the figure a
    warm-start-seed policy trains against). The overall section
    carries totals and per-source / per-tenant counts."""
    groups: Dict[tuple, List[Dict[str, Any]]] = {}
    sources: Dict[str, int] = {}
    tenants: Dict[str, int] = {}
    ring_records = 0
    annotations = 0
    for rec in records:
        src = str(rec.get("source", "?"))
        if "iters" not in rec:
            # Annotation records (the calibration plane's
            # ``calibration.audit`` chain) share the dataset but are
            # not solves: counted per source, excluded from the
            # per-cell solve statistics.
            sources[src] = sources.get(src, 0) + 1
            annotations += 1
            continue
        tenant = str(rec.get("tenant", LEGACY_TENANT))
        key = (tenant, str(rec.get("bucket", "?")), rec.get("eps_abs"))
        groups.setdefault(key, []).append(rec)
        sources[src] = sources.get(src, 0) + 1
        tenants[tenant] = tenants.get(tenant, 0) + 1
        if rec.get("ring"):
            ring_records += 1

    table = []
    total = 0
    for (tenant, bucket, eps), recs in sorted(
            groups.items(),
            key=lambda kv: (kv[0][0], kv[0][1], kv[0][2] or 0.0)):
        total += len(recs)
        iters = [int(r["iters"]) for r in recs]
        segs = [int(r.get("segments", 1)) for r in recs]
        status: Dict[str, int] = {}
        for r in recs:
            s = str(r["status"])
            status[s] = status.get(s, 0) + 1
        dense = len(segs) * max(segs) if segs else 0
        warm_iters = [int(r["iters"]) for r in recs if r.get("warm")]
        cold_iters = [int(r["iters"]) for r in recs if not r.get("warm")]
        row: Dict[str, Any] = {
            "tenant": tenant,
            "bucket": bucket,
            "eps_abs": eps,
            "count": len(recs),
            "iters": _quantiles([float(v) for v in iters]),
            "segments_sum": int(sum(segs)),
            "wasted_iteration_fraction": (
                float(1.0 - sum(segs) / dense) if dense else 0.0),
            "status_counts": status,
            "warm_count": len(warm_iters),
            "cold_count": len(cold_iters),
        }
        if warm_iters and cold_iters:
            row["warm_minus_cold_iters_mean"] = float(
                np.mean(warm_iters) - np.mean(cold_iters))
        if any("solver" in r for r in recs):
            # The backend axis (records written since the PDHG PR carry
            # it; solver-absent records — every pre-PDHG dataset — read
            # back as "admm", which is what they ran). Per-backend
            # iteration quantiles + mean dispatch latency: the
            # comparison table harvest_report renders and the
            # SolverRouter's seed (serve/routing.py) — a backend's
            # entry is its evidence for winning this (tenant, bucket,
            # eps) cell.
            by_solver: Dict[str, Dict[str, Any]] = {}
            for sv in sorted({str(r.get("solver", "admm"))
                              for r in recs}):
                srecs = [r for r in recs
                         if str(r.get("solver", "admm")) == sv]
                sstat: Dict[str, int] = {}
                for r in srecs:
                    s = str(r["status"])
                    sstat[s] = sstat.get(s, 0) + 1
                entry: Dict[str, Any] = {
                    "count": len(srecs),
                    "iters": _quantiles([float(r["iters"])
                                         for r in srecs]),
                    "status_counts": sstat,
                }
                lat = [float(r["solve_s"]) for r in srecs
                       if r.get("solve_s") is not None]
                if lat:
                    entry["solve_s_mean"] = float(np.mean(lat))
                # Routed decisions: dispatches the router actually sent
                # to this backend, i.e. everything that is not a shadow
                # re-solve (source "serve.shadow" / shadow_of set). The
                # count harvest_report's solver table shows next to the
                # win column — evidence volume behind each cell.
                entry["routed"] = sum(
                    1 for r in srecs
                    if not r.get("shadow_of")
                    and str(r.get("source", "")) != "serve.shadow")
                by_solver[sv] = entry
            row["by_solver"] = by_solver
        table.append(row)
    return {
        "schema_version": SCHEMA_VERSION,
        "records": total,
        "ring_records": ring_records,
        "annotations": annotations,
        "sources": sources,
        "tenants": tenants,
        "groups": table,
    }
