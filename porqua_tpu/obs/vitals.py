"""Process vitals: RSS / fd / thread / queue-depth gauges + leak trends.

A sustained soak dies of different causes than a benchmark window: a
slow RSS leak, an fd leak from a reopened sink, a thread leak from
un-joined timers, a queue that grows a little every window. None of
those are visible in the solve counters — they are *process* health,
so this module samples them from ``/proc/self`` (portable fallbacks
where procfs is absent) and detects trends with a two-rate EWMA pair.

* :func:`process_vitals` — one cheap sample (two procfs reads): RSS
  bytes, open fd count, live thread count, and the caller-supplied
  queue depth. Exported as gauges on the single-service ``/metrics``
  + ``/healthz`` (``SolveService``) and per worker, labeled, on the
  fleet endpoint (:mod:`porqua_tpu.obs.federation`).
* :class:`VitalsTrend` — EWMA leak/trend detection: per
  (worker, metric) a fast and a slow EWMA; when the fast average runs
  ``grow_margin`` above the slow one for ``min_samples`` samples the
  metric is *trending up faster than its own history* — the leak
  signature — and ONE ``vitals_anomaly`` event (``state="firing"``)
  is emitted, resolving with hysteresis. ``vitals_anomaly`` is a
  flight-recorder trigger (same firing-edge-only contract as
  ``convergence_anomaly``), so a leaking soak produces an incident
  bundle while the evidence still exists.

Pure host code — no JAX import, nothing on any hot path beyond
lock-bounded arithmetic; the GC108 federation-identity contract
(:func:`porqua_tpu.analysis.contracts.check_federation_identity`)
machine-checks the whole fleet plane invisible to XLA.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from porqua_tpu.analysis import tsan

__all__ = ["TREND_METRICS", "VITAL_METRICS", "VitalsTrend",
           "process_vitals"]

#: The metric keys every vitals sample carries (``queue_depth`` only
#: when the caller supplied one).
VITAL_METRICS = ("rss_bytes", "open_fds", "threads", "queue_depth")

#: The metrics the trend detector judges by default: the LEAK-shaped
#: ones, which grow monotonically when something is wrong and sit flat
#: otherwise. ``queue_depth`` is deliberately excluded — it is bursty
#: by design (open-loop arrivals between batch drains), so a
#: fast-vs-slow EWMA ratio reads every load burst as a "leak"
#: (observed: a clean 4-worker soak fired 15 false queue-depth
#: anomalies). Queue *growth* is still covered: the latency SLO burns,
#: the rollup ring keeps per-window ``queue_depth_max``, and the gauge
#: is exported per worker; opt a queue back into trending via
#: ``VitalsTrend(metrics=...)`` if a deployment's arrivals are smooth.
TREND_METRICS = ("rss_bytes", "open_fds", "threads")

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _rss_bytes() -> Optional[int]:
    """Resident set size via ``/proc/self/statm`` (second field, in
    pages); falls back to ``resource.getrusage`` off Linux."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        # ru_maxrss is KiB on Linux, bytes on macOS; either way it is
        # a high-water mark, not current RSS — good enough as a
        # fallback signal, and the trend detector only compares a
        # metric against its own history.
        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024
    except Exception:  # noqa: BLE001 - vitals must never fail a caller
        return None


def _open_fds() -> Optional[int]:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def process_vitals(queue_depth: Optional[int] = None) -> Dict[str, Any]:
    """One vitals sample for THIS process. Cheap (two procfs reads)
    and never raises: a metric that cannot be read is simply absent.
    ``queue_depth`` is caller-supplied (the process knows its own
    queues; procfs does not)."""
    out: Dict[str, Any] = {"t": time.time()}
    rss = _rss_bytes()
    if rss is not None:
        out["rss_bytes"] = rss
    fds = _open_fds()
    if fds is not None:
        out["open_fds"] = fds
    out["threads"] = threading.active_count()
    if queue_depth is not None:
        out["queue_depth"] = int(queue_depth)
    return out


class _TrendState:
    """Per-(worker, metric) EWMA pair (guarded by the trend lock)."""

    __slots__ = ("n", "fast", "slow", "anomalous")

    def __init__(self) -> None:
        self.n = 0
        self.fast = 0.0
        self.slow = 0.0
        self.anomalous = False


class VitalsTrend:
    """Two-rate EWMA leak/trend detector over vitals samples.

    ``observe(worker, vitals)`` folds one sample per metric into a
    fast (``alpha_fast``) and a slow (``alpha_slow``) EWMA. A steady
    process keeps the two averages together; a leak keeps the fast one
    persistently above the slow one. When ``fast > slow * (1 +
    grow_margin)`` after ``min_samples`` samples, ONE ``vitals_anomaly``
    event fires (``state="firing"``, a flight-recorder trigger);
    it resolves with hysteresis once the ratio falls back under
    ``1 + grow_margin * clear_fraction``. Metrics are judged
    independently per worker, so one leaking shard names itself.

    Thread-safety: ``observe`` runs on the collector's drain loop (or
    a single service's scrape thread), ``status``/``counters`` on
    whichever thread polls; state is guarded by the instance lock and
    events are emitted OUTSIDE it (the flight recorder's dump path
    reads ``status()`` from an event listener).
    """

    def __init__(self,
                 alpha_fast: float = 0.3,
                 alpha_slow: float = 0.03,
                 grow_margin: float = 0.25,
                 clear_fraction: float = 0.5,
                 min_samples: int = 20,
                 metrics: Tuple[str, ...] = TREND_METRICS,
                 events=None) -> None:
        if not 0.0 < alpha_slow < alpha_fast <= 1.0:
            raise ValueError("need 0 < alpha_slow < alpha_fast <= 1 "
                             "(the fast EWMA must actually be faster)")
        if grow_margin <= 0.0:
            raise ValueError("grow_margin must be positive")
        self.alpha_fast = float(alpha_fast)
        self.alpha_slow = float(alpha_slow)
        self.grow_margin = float(grow_margin)
        self.clear_fraction = float(clear_fraction)
        self.min_samples = int(min_samples)
        self.metrics = tuple(metrics)
        self.events = events
        self._lock = tsan.lock("VitalsTrend")
        # guarded-by: self._lock
        self._states: Dict[Tuple[str, str], _TrendState] = {}
        self._fired = 0            # guarded-by: self._lock
        self._resolved = 0         # guarded-by: self._lock
        self._observed = 0         # guarded-by: self._lock

    def observe(self, worker: str,
                vitals: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Fold one vitals sample; returns the transition events
        emitted (usually empty)."""
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            self._observed += 1
            for metric in self.metrics:
                value = vitals.get(metric)
                if value is None:
                    continue
                value = float(value)
                st = self._states.setdefault((str(worker), metric),
                                             _TrendState())
                if st.n == 0:
                    st.fast = st.slow = value
                else:
                    st.fast += self.alpha_fast * (value - st.fast)
                    st.slow += self.alpha_slow * (value - st.slow)
                st.n += 1
                denom = abs(st.slow) or 1.0
                ratio = st.fast / denom
                breach = (st.n >= self.min_samples
                          and ratio > 1.0 + self.grow_margin)
                clear = ratio <= 1.0 + self.grow_margin * self.clear_fraction
                if breach and not st.anomalous:
                    st.anomalous = True
                    self._fired += 1
                    transitions.append(self._event(
                        "firing", "warn", worker, metric, st, ratio))
                elif st.anomalous and clear:
                    st.anomalous = False
                    self._resolved += 1
                    transitions.append(self._event(
                        "resolved", "info", worker, metric, st, ratio))
        for ev in transitions:
            if self.events is not None:
                self.events.emit(**ev)
        return transitions

    @staticmethod
    def _event(state: str, severity: str, worker: str, metric: str,  # guarded-by: self._lock
               st: _TrendState, ratio: float) -> Dict[str, Any]:
        return dict(
            kind="vitals_anomaly", severity=severity, state=state,
            worker=str(worker), metric=metric,
            ewma_fast=round(st.fast, 2), ewma_slow=round(st.slow, 2),
            ratio=round(ratio, 4), n=st.n)

    # -- readers ------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """Per-(worker, metric) EWMA state + the anomalous set."""
        with self._lock:
            groups: Dict[str, Any] = {}
            anomalous: List[str] = []
            for (worker, metric), st in sorted(self._states.items()):
                label = f"{worker}/{metric}"
                denom = abs(st.slow) or 1.0
                groups[label] = {
                    "n": st.n,
                    "ewma_fast": round(st.fast, 2),
                    "ewma_slow": round(st.slow, 2),
                    "ratio": round(st.fast / denom, 4),
                    "anomalous": st.anomalous,
                }
                if st.anomalous:
                    anomalous.append(label)
            return {"groups": groups, "anomalous": anomalous,
                    "fired": self._fired, "resolved": self._resolved,
                    "observed": self._observed}

    def counters(self) -> Dict[str, int]:
        """Exposition counters (``/metrics`` extra_counters path)."""
        with self._lock:
            return {"vitals_anomalies_fired": self._fired,
                    "vitals_samples": self._observed}
