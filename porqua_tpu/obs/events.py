"""Structured JSON-lines event bus for the serve stack.

Metrics answer "how much"; the event bus answers "what happened, when,
to which request": compile events from the executable cache,
circuit-breaker transitions from the device-health manager, sanitizer
violations, backpressure rejections, deadline expiries, and the
calibration plane's route-table lifecycle (``route_reseed`` on every
candidate/promoted/abandoned/settled transition with the evidence
diff; ``route_rollback`` when the post-promotion guard reverts a
table — a flight-recorder trigger) are each
one structured record stamped with a severity and (where one exists)
the request's trace id, so a latency outlier in the span timeline
cross-references to the compile or breaker flip that caused it.

Events are plain dicts — host-side, lock-protected, bounded (a serving
process must not grow its event buffer without limit), with an
optional streaming JSON-lines sink so a crash loses at most the last
buffered line. Event schema: README "Observability".
"""

from __future__ import annotations

import collections
import json
import time
from typing import Any, Dict, List, Optional

from porqua_tpu.analysis import tsan

#: Severity order, least to most severe.
SEVERITIES = ("debug", "info", "warn", "error")


class EventBus:
    """Thread-safe bounded event sink with optional JSONL streaming.

    ``emit`` never raises and never blocks on anything but the lock —
    it is called from hot serving paths (dispatch thread, submitter
    threads), so a broken sink file degrades to counting drops rather
    than failing a batch. The buffer is a ring keeping the NEWEST
    ``capacity`` events (evictions are counted in ``dropped``): the
    recent tail — the breaker flip that just happened — is what a
    diagnostic read needs; use the streaming ``path`` sink to keep the
    complete history.
    """

    def __init__(self, capacity: int = 65536,
                 path: Optional[str] = None) -> None:
        self.capacity = int(capacity)
        self._lock = tsan.lock("EventBus")
        # guarded-by: self._lock
        self._events: "collections.deque[Dict[str, Any]]" = (
            collections.deque(maxlen=self.capacity))
        self._dropped = 0                        # guarded-by: self._lock
        self._sink_failures = 0                  # guarded-by: self._lock
        self._listeners: List = []               # guarded-by: self._lock
        self._listener_failures = 0              # guarded-by: self._lock
        self._sink = open(path, "a") if path else None

    def emit(self, kind: str, severity: str = "info",
             trace_id: Optional[str] = None, **fields) -> Dict[str, Any]:
        """Record one event; returns the record (for tests/logging)."""
        if severity not in SEVERITIES:
            severity = "info"
        event: Dict[str, Any] = {
            "t": time.time(),
            "kind": kind,
            "severity": severity,
        }
        if trace_id is not None:
            event["trace_id"] = trace_id
        event.update(fields)
        # Serialize OUTSIDE the sink try-block (same as HarvestSink):
        # a json.dumps ValueError is a caller bug in the event fields,
        # not a dead sink, and must not permanently disable a healthy
        # stream. Only when a sink exists at all — the common
        # sink-less bus must not pay per-emit serialization on the
        # dispatch hot path (the unlocked read is a one-way race:
        # _sink only ever transitions to None).
        line = (json.dumps(event, default=str)
                if self._sink is not None else None)
        with self._lock:
            if len(self._events) == self.capacity:
                self._dropped += 1  # deque evicts the oldest
            self._events.append(event)
            if self._sink is not None and line is not None:
                try:
                    self._sink.write(line + "\n")
                    self._sink.flush()
                except (OSError, ValueError):
                    # ValueError: write on a file something already
                    # closed (shutdown races included) — same posture.
                    # Dead sink: keep serving, but COUNT the failure —
                    # from the scrape's point of view a silently-dead
                    # stream sink looks identical to a healthy idle one
                    # otherwise (the counter is exported via
                    # /metrics and /healthz by SolveService).
                    self._sink_failures += 1
                    self._sink = None
            listeners = list(self._listeners) if self._listeners else None
        if listeners is not None:
            # OUTSIDE the lock: a listener (the flight recorder's
            # trigger path) reads this bus and other obs surfaces back
            # — calling it under the bus lock would self-deadlock and
            # put every other emitter behind a bundle dump.
            for fn in listeners:
                try:
                    fn(event)
                except Exception:  # noqa: BLE001 - a broken listener
                    # must not fail the emitting hot path; count it
                    # (exported with the other loss counters).
                    with self._lock:
                        self._listener_failures += 1
        return event

    def add_listener(self, fn) -> None:
        """Register a callback invoked (outside the bus lock, on the
        emitting thread) with every event record — the flight
        recorder's trigger feed. Listeners must be fast and must not
        raise; exceptions are swallowed and counted."""
        with self._lock:
            self._listeners.append(fn)

    # -- readers -----------------------------------------------------

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    @property
    def sink_failures(self) -> int:
        with self._lock:
            return self._sink_failures

    @property
    def listener_failures(self) -> int:
        with self._lock:
            return self._listener_failures

    def events(self, kind: Optional[str] = None,
               min_severity: str = "debug") -> List[Dict[str, Any]]:
        """Buffered events, optionally filtered by kind and severity."""
        floor = SEVERITIES.index(min_severity)
        with self._lock:
            return [e for e in self._events
                    if (kind is None or e["kind"] == kind)
                    and SEVERITIES.index(e["severity"]) >= floor]

    def write_jsonl(self, path: str) -> int:
        """Dump every buffered event to ``path``; returns the count."""
        events = self.events()
        with open(path, "w") as f:
            for e in events:
                f.write(json.dumps(e, default=str) + "\n")
        return len(events)

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    """Read an event JSONL file back into a list of dicts (blank lines
    skipped) — the reader ``scripts/obs_report.py`` uses."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
