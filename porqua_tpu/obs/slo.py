"""Live SLO engine: sliding windows, multi-window burn-rate alerts.

The obs stack could so far only explain a run after the fact; nothing
watched the service *live* against an objective. This module is the
declarative half of the live operational plane (README "SLOs, alerting
& incident response"):

* :class:`SLO` — one service-level objective over the serve stack's
  own counters: ``availability`` (completed / (completed + failed +
  expired + retry give-ups) — attempt-level, a deadline expiry is a
  failed request from the caller's view), ``latency`` (share of
  requests under a latency
  target, read from the ``solve_latency_seconds`` histogram so SLO
  targets and histogram edges align — see ``ServeMetrics(
  latency_buckets=)``), and ``wrong_answers`` (validation failures
  against a zero budget).
* :class:`BurnRateRule` — one Google-SRE-style multi-window
  multi-burn-rate alert rule: the alert condition is an AND over a
  short and a long window both burning error budget faster than
  ``burn_rate`` (the short window makes alerts reset quickly once the
  bleeding stops; the long window keeps a blip from paging).
* :class:`SLOEngine` — feeds sliding windows from
  :meth:`porqua_tpu.serve.metrics.ServeMetrics.slo_sample` cumulative
  counters, computes per-(SLO, rule) burn rates, and drives the alert
  state machine ``inactive -> pending -> firing -> resolved`` with a
  ``for_s`` dwell before firing and a ``resolve_s`` clear dwell (flap
  debounce) before resolving. Transitions emit ``slo_alert`` events on
  the :class:`~porqua_tpu.obs.events.EventBus` — a firing alert is a
  flight-recorder trigger (:mod:`porqua_tpu.obs.flight`) — and the
  current burn rates / alert states export as ``slo_burn_rate`` /
  ``slo_alert_state`` gauges through ``prometheus_text(extra_gauges=)``
  plus the ``/healthz`` payload.

Everything is clocked on an injectable monotonic clock (any zero-arg
float callable — :class:`porqua_tpu.resilience.FaultClock` included),
so burn-rate tests step time deterministically with no wall-clock
sleeps. The engine is pure host code fed by counters the serve stack
already maintains: the GC106 jaxpr-identity contract
(:func:`porqua_tpu.analysis.contracts.check_observability_identity`)
machine-checks that a live engine changes no traced program.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from porqua_tpu.analysis import tsan

__all__ = [
    "SLO",
    "BurnRateRule",
    "DEFAULT_RULES",
    "SLOEngine",
    "TenantSLOSet",
    "default_slos",
]

#: SLO kinds the engine can evaluate (each maps to one good/bad counter
#: extraction from ``ServeMetrics.slo_sample``).
KINDS = ("availability", "latency", "wrong_answers")

#: Alert states, in escalation order (the ``slo_alert_state`` gauge
#: exports the index: 0 inactive, 1 pending, 2 firing).
STATES = ("inactive", "pending", "firing")


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declarative service-level objective.

    ``objective`` is the good-events fraction the service promises
    (e.g. 0.999 = three nines); the error budget is ``1 - objective``.
    ``latency_target_s`` applies to ``kind="latency"`` only and should
    sit on a histogram bucket edge (``ServeMetrics(latency_buckets=)``)
    — the engine snaps it to the largest edge <= the target otherwise
    (conservative: borderline requests count as slow) and reports the
    effective target in ``status()``.
    """

    name: str
    kind: str
    objective: float = 0.999
    latency_target_s: float = 0.25
    description: str = ""

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(f"unknown SLO kind {self.kind!r}; "
                             f"expected one of {KINDS}")
        if not (0.0 < self.objective <= 1.0):
            raise ValueError("objective must be in (0, 1]")


@dataclasses.dataclass(frozen=True)
class BurnRateRule:
    """One multi-window burn-rate alert rule (Google SRE workbook ch.5).

    Fires when BOTH the ``short_s`` and ``long_s`` windows burn error
    budget at >= ``burn_rate`` x the sustainable rate. ``for_s`` is the
    pending dwell before firing; ``resolve_s`` is how long the
    condition must stay clear before a firing alert resolves (the flap
    debounce — a condition flickering inside ``resolve_s`` keeps ONE
    firing alert instead of a resolve/fire storm).
    """

    name: str
    long_s: float
    short_s: float
    burn_rate: float
    for_s: float = 0.0
    resolve_s: float = 60.0
    severity: str = "page"

    def __post_init__(self) -> None:
        if self.short_s <= 0 or self.long_s <= 0:
            raise ValueError("window lengths must be positive")
        if self.short_s > self.long_s:
            raise ValueError("short_s must be <= long_s (the short "
                             "window is the fast-reset gate)")


#: The canonical two-rule ladder: a fast page (5 m + 1 h at 14.4x —
#: 2% of a 30-day budget in one hour) and a slow ticket (30 m + 6 h at
#: 6x — 5% in six hours).
DEFAULT_RULES = (
    BurnRateRule("fast", long_s=3600.0, short_s=300.0, burn_rate=14.4,
                 for_s=0.0, resolve_s=300.0, severity="page"),
    BurnRateRule("slow", long_s=21600.0, short_s=1800.0, burn_rate=6.0,
                 for_s=0.0, resolve_s=900.0, severity="ticket"),
)


def default_slos(latency_target_s: float = 0.25,
                 availability_objective: float = 0.999,
                 latency_objective: float = 0.99) -> Tuple[SLO, ...]:
    """The serve stack's standard SLO set: availability, latency-p99
    (objective 0.99 under the target == "p99 <= target"), and
    zero-wrong-answers (objective 1.0 — any validation failure burns
    an empty budget, so a single wrong answer alerts immediately)."""
    return (
        SLO("availability", "availability",
            objective=availability_objective,
            description="completed / (completed + failed + expired "
                        "+ giveups)"),
        SLO("latency", "latency", objective=latency_objective,
            latency_target_s=latency_target_s,
            description=f"share of requests under "
                        f"{latency_target_s * 1e3:g} ms"),
        SLO("wrong_answers", "wrong_answers", objective=1.0,
            description="validation failures against a zero budget"),
    )


#: Floor for the error budget: an objective of exactly 1.0 (the
#: zero-wrong-answers SLO) would otherwise divide by zero; the floor
#: keeps burn rates finite (and JSON-serializable) while still making
#: any bad event an effectively-infinite burn.
_BUDGET_FLOOR = 1e-9


class _AlertState:
    """Mutable per-(SLO, rule) alert state (guarded by the engine lock)."""

    __slots__ = ("state", "pending_since", "clear_since",
                 "burn_short", "burn_long")

    def __init__(self) -> None:
        self.state = "inactive"
        self.pending_since = 0.0
        self.clear_since: Optional[float] = None
        self.burn_short = 0.0
        self.burn_long = 0.0


class SLOEngine:
    """Sliding-window burn-rate evaluation + the alert state machine.

    Thread-safety: ``evaluate``/``maybe_evaluate`` run on the dispatch
    thread (via ``MicroBatcher._finish_request``) and on scrape threads
    (``/metrics`` and ``/healthz`` evaluate before reading); ``status``
    / ``gauges`` read from whichever thread polls. All engine state is
    guarded by the instance lock; metric sampling and event emission
    happen OUTSIDE it (the flight recorder's dump path reads
    ``status()`` from inside an event listener, and emitting under the
    engine lock would re-enter it).
    """

    def __init__(self,
                 slos: Optional[Sequence[SLO]] = None,
                 rules: Sequence[BurnRateRule] = DEFAULT_RULES,
                 clock: Optional[Callable[[], float]] = None,
                 min_eval_interval_s: float = 1.0,
                 max_samples: int = 4096,
                 labels: Optional[Dict[str, str]] = None) -> None:
        # Static labels merged into every emitted slo_alert event —
        # the per-tenant engines (TenantSLOSet) ride this to stamp
        # their alerts with {"tenant": ...} so a flight bundle's
        # trigger names the tenant that burned its budget.
        self.labels: Dict[str, str] = dict(labels or {})
        self.slos: Tuple[SLO, ...] = tuple(
            default_slos() if slos is None else slos)
        if not self.slos:
            raise ValueError("SLOEngine needs at least one SLO")
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.rules: Tuple[BurnRateRule, ...] = tuple(rules)
        if not self.rules:
            raise ValueError("SLOEngine needs at least one BurnRateRule")
        self.clock = time.monotonic if clock is None else clock
        self.min_eval_interval_s = float(min_eval_interval_s)
        self._max_samples = int(max_samples)
        self._max_window = max(r.long_s for r in self.rules)
        # Samples closer together than this replace their predecessor
        # instead of appending: the bounded sample buffer then always
        # spans the longest rule window (a 1 s eval cadence would
        # otherwise cap retained history at max_samples seconds and
        # silently truncate a 6 h long window to a partial one).
        self._min_spacing = (self._max_window * 1.5
                             / max(self._max_samples - 2, 1))
        self.metrics = None
        self.events = None
        self._lock = tsan.lock("SLOEngine")
        # (t, {slo_name: (good, bad)}) cumulative samples, oldest
        # first.                              guarded-by: self._lock
        self._samples: List[Tuple[float, Dict[str, Tuple[int, int]]]] = []
        # guarded-by: self._lock
        self._alerts: Dict[Tuple[str, str], _AlertState] = {
            (s.name, r.name): _AlertState()
            for s in self.slos for r in self.rules}
        self._compliance: Dict[str, float] = {
            s.name: 1.0 for s in self.slos}       # guarded-by: self._lock
        self._effective_latency_target: Dict[str, float] = {}  # guarded-by: self._lock
        self._last_eval = float("-inf")           # guarded-by: self._lock
        self._alerts_fired = 0                    # guarded-by: self._lock
        self._evaluations = 0                     # guarded-by: self._lock

    # -- wiring -------------------------------------------------------

    def bind(self, metrics, events=None) -> "SLOEngine":
        """Point the engine at a :class:`ServeMetrics` (the sample
        source) and optionally an :class:`EventBus` (where
        ``slo_alert`` transitions land). ``SolveService`` calls this."""
        self.metrics = metrics
        if events is not None:
            self.events = events
        return self

    # -- sampling -----------------------------------------------------

    def _extract(self, sample: Dict[str, Any]
                 ) -> Tuple[Dict[str, Tuple[int, int]],
                            Dict[str, float]]:
        """Cumulative (good, bad) per SLO from one
        ``ServeMetrics.slo_sample`` reading, plus the effective
        (snapped) latency targets. Pure — runs outside the engine
        lock; the caller stores the results under it."""
        out: Dict[str, Tuple[int, int]] = {}
        eff_targets: Dict[str, float] = {}
        for slo in self.slos:
            if slo.kind == "availability":
                good = int(sample["completed"])
                # Attempt-level accounting, like the counters it reads:
                # a deadline expiry is a failed request from the
                # caller's view (the "deadline storm" case), and with a
                # retry layer an expired attempt that later gives up
                # counts once per stage — slightly overstating burn,
                # never hiding it.
                bad = (int(sample["failed"]) + int(sample["expired"])
                       + int(sample["retry_giveups"]))
            elif slo.kind == "wrong_answers":
                good = int(sample["completed"])
                bad = int(sample["validation_failures"])
            else:  # latency
                le = sample["latency_le"]
                counts = sample["latency_counts"]
                idx = -1
                for i, bound in enumerate(le):
                    if bound <= slo.latency_target_s:
                        idx = i
                    else:
                        break
                if idx < 0:
                    # No edge at or under the target: snap UP to the
                    # smallest edge (optimistic there is no conservative
                    # choice left) — align the ladder via
                    # ServeMetrics(latency_buckets=) instead.
                    idx = 0
                eff_targets[slo.name] = float(le[idx])
                good = int(sum(counts[:idx + 1]))
                bad = int(sample["latency_count"]) - good
            out[slo.name] = (good, bad)
        return out, eff_targets

    @staticmethod
    def _window_delta(samples, latest, name: str, now: float,
                      window_s: float) -> Tuple[int, int]:
        """(good, bad) accumulated inside the trailing window: latest
        minus the newest sample at or before ``now - window_s`` (or the
        oldest sample while the window is still filling — partial-
        window burn, the standard practical choice)."""
        cutoff = now - window_s
        base = samples[0][1]
        for t, vals in samples:
            if t <= cutoff:
                base = vals
            else:
                break
        g0, b0 = base.get(name, (0, 0))
        g1, b1 = latest.get(name, (0, 0))
        return max(g1 - g0, 0), max(b1 - b0, 0)

    # -- evaluation ---------------------------------------------------

    def maybe_evaluate(self) -> List[Dict[str, Any]]:
        """Clock-gated :meth:`evaluate` — safe to call per dispatch.
        The gate's clock read is advisory only; evaluate re-reads the
        clock under the engine lock, so a thread preempted between the
        gate and the evaluation cannot append an older-timestamped
        sample after a fresher one (explicit ``evaluate(now=...)`` is
        the single-threaded test path)."""
        with self._lock:
            if self.clock() - self._last_eval < self.min_eval_interval_s:
                return []
        return self.evaluate()

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Take one sample, recompute burn rates, and step every alert
        state machine. Returns the transition events emitted (also
        emitted on the bound event bus). Deterministic under an
        injected clock: time only moves when the caller's clock does.
        """
        if self.metrics is None:
            raise RuntimeError("SLOEngine.bind(metrics) first")
        transitions: List[Dict[str, Any]] = []
        with self._lock:
            # Clock read AND metrics sample under the engine lock: a
            # dispatch-thread sample taken outside it could be
            # preempted, land AFTER a fresher scrape-thread sample,
            # and masquerade as a metrics-window reset — wiping the
            # burn history. The engine->metrics lock edge is one-way
            # (metrics never calls back into the engine).
            now = self.clock() if now is None else float(now)
            vals, eff_targets = self._extract(self.metrics.slo_sample())
            self._last_eval = now
            self._evaluations += 1
            self._effective_latency_target.update(eff_targets)
            if self._samples:
                prev = self._samples[-1][1]
                if any(sum(vals[n]) < sum(prev.get(n, (0, 0)))
                       for n in vals):
                    # A cumulative counter moved backwards: the metrics
                    # window was reset (loadgen does this after
                    # prewarm). Old deltas are meaningless — restart.
                    self._samples.clear()
            if (len(self._samples) >= 2
                    and now - self._samples[-2][0] < self._min_spacing):
                # Thin by replacement: keep the freshest sample per
                # spacing slot so max_samples always spans the longest
                # window, however fast evaluations arrive.
                self._samples[-1] = (now, vals)
            else:
                self._samples.append((now, vals))
            cutoff = now - self._max_window * 1.5
            while (len(self._samples) > 2
                   and (self._samples[1][0] <= cutoff
                        or len(self._samples) > self._max_samples)):
                self._samples.pop(0)

            for slo in self.slos:
                budget = max(1.0 - slo.objective, _BUDGET_FLOOR)
                g, b = self._window_delta(self._samples, vals, slo.name,
                                          now, self._max_window)
                total = g + b
                self._compliance[slo.name] = (
                    1.0 - b / total if total else 1.0)
                for rule in self.rules:
                    st = self._alerts[(slo.name, rule.name)]
                    burns = []
                    for w in (rule.short_s, rule.long_s):
                        gw, bw = self._window_delta(
                            self._samples, vals, slo.name, now, w)
                        tw = gw + bw
                        rate = bw / tw if tw else 0.0
                        burns.append(rate / budget)
                    st.burn_short, st.burn_long = burns
                    cond = (st.burn_short >= rule.burn_rate
                            and st.burn_long >= rule.burn_rate)
                    ev = self._step_alert(st, slo, rule, cond, now)
                    if ev is not None:
                        transitions.append(ev)
        for ev in transitions:
            if self.events is not None:
                self.events.emit(**ev)
        return transitions

    def _step_alert(self, st: _AlertState, slo: SLO,  # guarded-by: self._lock
                    rule: BurnRateRule, cond: bool,
                    now: float) -> Optional[Dict[str, Any]]:
        """One state-machine step; returns the ``slo_alert`` event to
        emit (outside the lock) on a reportable transition."""
        def event(state: str, severity: str) -> Dict[str, Any]:
            return dict(
                kind="slo_alert", severity=severity, slo=slo.name,
                rule=rule.name, state=state,
                burn_short=round(st.burn_short, 4),
                burn_long=round(st.burn_long, 4),
                threshold=rule.burn_rate,
                short_s=rule.short_s, long_s=rule.long_s,
                rule_severity=rule.severity, **self.labels)

        if st.state == "inactive":
            if cond:
                st.pending_since = now
                if now - st.pending_since >= rule.for_s:
                    st.state = "firing"
                    st.clear_since = None
                    self._alerts_fired += 1
                    return event("firing", "error")
                st.state = "pending"
                return event("pending", "warn")
            return None
        if st.state == "pending":
            if not cond:
                st.state = "inactive"  # silent cancel, Prometheus-style
                return None
            if now - st.pending_since >= rule.for_s:
                st.state = "firing"
                st.clear_since = None
                self._alerts_fired += 1
                return event("firing", "error")
            return None
        # firing
        if cond:
            st.clear_since = None  # flap: the bleeding resumed
            return None
        if st.clear_since is None:
            st.clear_since = now
        if now - st.clear_since >= rule.resolve_s:
            st.state = "inactive"
            st.clear_since = None
            return event("resolved", "info")
        return None

    # -- readers ------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The ``/healthz`` payload section: per-SLO compliance over
        the longest rule window, current burn rates per rule, and any
        firing alerts. Pure read of the last evaluation — safe to call
        from the flight recorder's dump path."""
        with self._lock:
            slos: Dict[str, Any] = {}
            firing: List[str] = []
            for slo in self.slos:
                alerts: Dict[str, Any] = {}
                for rule in self.rules:
                    st = self._alerts[(slo.name, rule.name)]
                    alerts[rule.name] = {
                        "state": st.state,
                        "burn_short": round(st.burn_short, 4),
                        "burn_long": round(st.burn_long, 4),
                        "threshold": rule.burn_rate,
                    }
                    if st.state == "firing":
                        firing.append(f"{slo.name}/{rule.name}")
                entry: Dict[str, Any] = {
                    "kind": slo.kind,
                    "objective": slo.objective,
                    "compliance": round(self._compliance[slo.name], 6),
                    "alerts": alerts,
                }
                if slo.kind == "latency":
                    entry["latency_target_s"] = slo.latency_target_s
                    eff = self._effective_latency_target.get(slo.name)
                    if eff is not None:
                        entry["effective_target_s"] = eff
                slos[slo.name] = entry
            return {
                "slos": slos,
                "firing": firing,
                "alerts_fired": self._alerts_fired,
                "evaluations": self._evaluations,
            }

    def gauges(self) -> Dict[str, float]:
        """Flat gauge dict for ``prometheus_text(extra_gauges=)``:
        ``slo_compliance_<slo>``, ``slo_burn_rate_<slo>_<rule>_short``
        / ``_long``, and ``slo_alert_state_<slo>_<rule>`` (0 inactive,
        1 pending, 2 firing)."""
        with self._lock:
            out: Dict[str, float] = {}
            for slo in self.slos:
                out[f"slo_compliance_{slo.name}"] = round(
                    self._compliance[slo.name], 6)
                for rule in self.rules:
                    st = self._alerts[(slo.name, rule.name)]
                    key = f"{slo.name}_{rule.name}"
                    out[f"slo_burn_rate_{key}_short"] = round(
                        st.burn_short, 4)
                    out[f"slo_burn_rate_{key}_long"] = round(
                        st.burn_long, 4)
                    out[f"slo_alert_state_{key}"] = float(
                        STATES.index(st.state))
            return out

    def counters(self) -> Dict[str, int]:
        """Exposition counters (``/metrics`` extra_counters path)."""
        with self._lock:
            return {"slo_alerts_fired": self._alerts_fired,
                    "slo_evaluations": self._evaluations}


class TenantSLOSet:
    """Per-tenant SLO engines over one :class:`ServeMetrics`.

    The tenant axis of the live SLO plane, built by *reusing* the
    engine rather than duplicating it: one unmodified
    :class:`SLOEngine` per observed tenant, each bound to that
    tenant's :meth:`~porqua_tpu.serve.metrics.ServeMetrics.
    tenant_view` (the same reader-surface adapter the fleet collector
    uses) and stamped with ``labels={"tenant": <id>}`` so its
    ``slo_alert`` events — and therefore any flight-recorder bundle
    they trigger — carry the tenant id. Engines are created lazily as
    tenants appear, bounded by ``max_tenants`` (beyond it new tenants
    are counted, not judged — same posture as the anomaly detector's
    unknown groups).

    Semantics note: a tenant's availability counts its quota sheds as
    bad events (:meth:`ServeMetrics.tenant_slo_sample`) — a
    noisy-neighbor burst therefore burns ONLY the offender's budget;
    the victims' engines see their own clean counters.

    Thread-safety: ``maybe_evaluate`` runs on the dispatch thread via
    ``MicroBatcher._plane_tick`` and on scrape threads; the set's own
    lock guards only the engine registry — each engine keeps its own
    lock and evaluates outside ours.
    """

    def __init__(self,
                 slos: Optional[Sequence[SLO]] = None,
                 rules: Sequence[BurnRateRule] = DEFAULT_RULES,
                 clock: Optional[Callable[[], float]] = None,
                 min_eval_interval_s: float = 1.0,
                 max_tenants: int = 64) -> None:
        self._slos = tuple(default_slos() if slos is None else slos)
        self._rules = tuple(rules)
        self._clock = clock
        self._min_eval_interval_s = float(min_eval_interval_s)
        self._max_tenants = int(max_tenants)
        self.metrics = None
        self.events = None
        self._lock = tsan.lock("TenantSLOSet")
        self._engines: Dict[str, SLOEngine] = {}  # guarded-by: self._lock
        self._overflow = 0                        # guarded-by: self._lock

    def bind(self, metrics, events=None) -> "TenantSLOSet":
        """Point the set at the serve stack's :class:`ServeMetrics`
        (``SolveService`` calls this)."""
        self.metrics = metrics
        if events is not None:
            self.events = events
        return self

    def _engines_for(self, tenants) -> List[SLOEngine]:
        # Missing engines are constructed AND bound before they are
        # published into the registry: a concurrent evaluator (the
        # dispatch thread's _plane_tick vs a /metrics scrape) that
        # sees a registered engine must be able to evaluate it — an
        # unbound one would raise "SLOEngine.bind(metrics) first" into
        # whichever thread lost the race. The double-read is benign:
        # two racers may both build an engine for a new tenant; the
        # second insert defers to the first (setdefault), and the
        # loser's unbound engine is simply dropped.
        with self._lock:
            missing = [t for t in tenants if t not in self._engines]
        for t in missing:
            engine = SLOEngine(
                self._slos, rules=self._rules, clock=self._clock,
                min_eval_interval_s=self._min_eval_interval_s,
                labels={"tenant": t})
            engine.bind(self.metrics.tenant_view(t), events=self.events)
            with self._lock:
                if t in self._engines:
                    continue
                if len(self._engines) >= self._max_tenants:
                    self._overflow += 1
                    continue
                self._engines[t] = engine
        with self._lock:
            return list(self._engines.values())

    def maybe_evaluate(self) -> None:
        """Clock-gated evaluation of every tenant's engine (each
        engine gates itself, so this is one lock hop + N cheap clock
        reads per dispatch)."""
        if self.metrics is None:
            return
        for engine in self._engines_for(self.metrics.tenant_ids()):
            engine.maybe_evaluate()

    def evaluate(self) -> None:
        """Force one evaluation per tenant engine (run-end closing
        evaluation, same role as ``SLOEngine.evaluate``)."""
        if self.metrics is None:
            return
        for engine in self._engines_for(self.metrics.tenant_ids()):
            engine.evaluate()

    def engine(self, tenant: str) -> Optional[SLOEngine]:
        with self._lock:
            return self._engines.get(str(tenant))

    def status(self) -> Dict[str, Any]:
        """Per-tenant ``SLOEngine.status()`` payloads (the
        ``/healthz`` tenancy section + the loadgen report)."""
        with self._lock:
            engines = dict(self._engines)
        return {t: e.status() for t, e in sorted(engines.items())}

    def alerts_fired(self) -> Dict[str, int]:
        """Per-tenant fired-alert totals — the fairness/isolation
        figure (offender fires, nobody else does)."""
        with self._lock:
            engines = dict(self._engines)
        return {t: e.status()["alerts_fired"]
                for t, e in sorted(engines.items())}

    def labeled_gauges(self) -> Dict[str, list]:
        """Per-tenant labeled series for
        ``prometheus_text(labeled_gauges=)``: each engine's flat
        gauges re-shaped as ``tenant_slo_*{tenant=...}``."""
        with self._lock:
            engines = dict(self._engines)
        out: Dict[str, list] = {}
        for t, engine in sorted(engines.items()):
            lbl = {"tenant": t}
            for key, value in engine.gauges().items():
                out.setdefault(f"tenant_{key}", []).append((lbl, value))
        return out

    def counters(self) -> Dict[str, int]:
        with self._lock:
            engines = dict(self._engines)
            overflow = self._overflow
        return {
            "tenant_slo_engines": len(engines),
            "tenant_slo_overflow": overflow,
            "tenant_slo_alerts_fired": sum(
                e.counters()["slo_alerts_fired"]
                for e in engines.values()),
        }
