"""Closed-loop route calibration: live re-seeding with guarded promotion.

Every passive plane is already in place — the harvest warehouse
records per-solve outcomes, the shadow-compare stream records how the
*losing* backend would have done on served traffic, the SLO engine
tracks burn rates and the anomaly detector tracks convergence EWMAs.
This module closes the telemetry→action loop (the open half of the
multi-backend ROADMAP item, and the template the learned-policy work
will reuse): a :class:`Calibrator` folds the live shadow/harvest
stream into bounded per-``(bucket, eps)`` rolling evidence and drives
a staged promotion state machine over the
:class:`~porqua_tpu.serve.routing.SolverRouter`'s versioned route
table:

``idle`` → (candidate computed, gates pass) → ``canary`` (dwell;
evidence must *hold* ``min_samples`` per changed cell and a
``win_rate`` threshold on the shadow comparisons) → **promoted**
(:meth:`SolverRouter.set_table` — a version bump, 0 recompiles thanks
to the prewarmed-every-ladder invariant) → ``guard`` (a window
watching the EXISTING :class:`~porqua_tpu.obs.anomaly.AnomalyDetector`
fired count and :class:`~porqua_tpu.obs.slo.SLOEngine` firing alerts
for policy-induced drift) → ``idle``; a guard breach auto-reverts to
the prior table (another version bump — versions are never reused)
and emits one ``route_rollback`` event, which the flight recorder
turns into exactly one incident bundle.

Every transition emits a ``route_reseed`` event carrying the full
evidence diff (old/new route, per-cell iteration / latency deltas,
sample counts) and lands a **versioned audit record** in the harvest
warehouse (``source="calibration.audit"``): :func:`replay_audit`
rebuilds the active table from the audit chain alone, which is the
regression bar for version monotonicity.

Contract GC111 pins the whole plane host-side: a live calibrator
caught mid-promotion leaves every solve/serve jaxpr string-identical —
calibration only ever picks which already-compiled executable runs.

Pure host code: stdlib + the tsan lock factory, no JAX import (the
package promise), zero wall-clock sleeps — ticking is driven by the
batchers' ``_plane_tick`` through an injectable clock
(:class:`~porqua_tpu.resilience.faults.FaultClock` in tests).
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from porqua_tpu.analysis import tsan

__all__ = ["CALIBRATION_AUDIT_SOURCE", "Calibrator", "replay_audit"]

#: ``source`` field of audit records in the harvest warehouse. The
#: aggregator treats these as annotations (no solve fields), readers
#: like ``harvest_report`` render them as the calibration table.
CALIBRATION_AUDIT_SOURCE = "calibration.audit"

#: Audit-record schema version (bump when a field changes meaning).
AUDIT_SCHEMA_VERSION = 1

#: Mirrors ``porqua_tpu.serve.routing.METHODS`` — restated host-side
#: so importing this module initializes no JAX backend (the obs
#: package promise; the router re-validates methods on every swap).
_METHODS = ("admm", "pdhg", "napg")

#: ``int(porqua_tpu.qp.admm.Status.SOLVED)`` restated for the same
#: reason; harvest records carry the status as this integer.
_SOLVED = 1

#: Numeric encoding of the state machine for /metrics gauges.
_STATE_GAUGE = {"idle": 0.0, "canary": 1.0, "guard": 2.0}

Cell = Tuple[str, float]


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(float(x))


def _cell_str(cell: Cell) -> str:
    # The router-snapshot key format, so audit tables compare 1:1
    # against ``SolverRouter.snapshot()["table"]``.
    return f"{cell[0]}@{cell[1]:.0e}"


class Calibrator:
    """Live route-table calibration over a :class:`SolverRouter`.

    Wire as ``SolveService(calibrator=...)`` — the service binds the
    router / harvest sink / event bus / anomaly detector / SLO engine
    and the batchers feed every retired harvest record (and every
    shadow-compare record) through :meth:`observe`, then call
    :meth:`maybe_tick` from ``_plane_tick`` after each dispatch.

    Knobs (the README's calibration table):

    ``min_interval_s``
        clock gate between ticks (evidence folds continuously; the
        state machine advances at most this often).
    ``min_samples``
        evidence-maturity bar: per cell, only backends with at least
        this many valid evidence records are scored as contenders (at
        least two must mature for any comparison), AND the incoming
        winner needs this many shadow comparisons before a candidate
        may enter canary.
    ``win_rate``
        fraction of the winner's shadow comparisons that must be wins
        (served answer agreed AND the shadow was strictly faster —
        dispatch latency when recorded, iterations otherwise).
    ``canary_dwell_s``
        how long a candidate must keep its gates green before the
        table is swapped.
    ``guard_window_s``
        post-promotion watch: any NEW anomaly-detector firing or any
        NEWLY-firing SLO alert inside the window is a breach →
        auto-rollback.
    ``cooldown_s``
        no new candidate until this long after a rollback (the
        discredited cells' evidence is also dropped, so the same bad
        table cannot ping-pong back in).
    ``max_records_per_cell``
        bound on each (cell, backend) evidence deque — the rolling
        window live reseeding judges on.
    """

    def __init__(self,
                 router=None,
                 harvest=None,
                 events=None,
                 anomaly=None,
                 slo=None,
                 min_interval_s: float = 5.0,
                 min_samples: int = 8,
                 win_rate: float = 0.6,
                 canary_dwell_s: float = 10.0,
                 guard_window_s: float = 30.0,
                 cooldown_s: Optional[float] = None,
                 max_records_per_cell: int = 256,
                 clock: Optional[Callable[[], float]] = None) -> None:
        if not 0.0 <= float(win_rate) <= 1.0:
            raise ValueError("win_rate must be in [0, 1]")
        if int(min_samples) < 1:
            raise ValueError("min_samples must be >= 1")
        if int(max_records_per_cell) < 1:
            raise ValueError("max_records_per_cell must be >= 1")
        self.router = router
        self.harvest = harvest
        self.events = events
        self.anomaly = anomaly
        self.slo = slo
        self.min_interval_s = float(min_interval_s)
        self.min_samples = int(min_samples)
        self.win_rate = float(win_rate)
        self.canary_dwell_s = float(canary_dwell_s)
        self.guard_window_s = float(guard_window_s)
        self.cooldown_s = (float(guard_window_s) if cooldown_s is None
                           else float(cooldown_s))
        self.max_records_per_cell = int(max_records_per_cell)
        self._clock = clock if clock is not None else time.monotonic
        self._lock = tsan.lock("Calibrator")
        # guarded-by: self._lock
        # (cell -> method -> deque of (ok, iters, solve_s|None)): ALL
        # valid solve evidence (routed + shadow), the scoring input.
        self._evidence: Dict[Cell, Dict[str, deque]] = {}
        # (cell -> method -> deque of (win, agree, d_iters, d_solve_s)):
        # the shadow comparisons only — the promotion gate's input.
        self._shadow: Dict[Cell, Dict[str, deque]] = {}
        self._state = "idle"
        self._candidate: Optional[Dict[Cell, str]] = None
        self._candidate_diff: Dict[str, Dict[str, Any]] = {}
        self._canary_since = 0.0
        self._prior_table: Optional[Dict[Cell, str]] = None
        self._promoted_at = 0.0
        self._guard_base_anomaly = 0
        self._guard_base_slo: set = set()
        self._cooldown_until = 0.0
        self._last_tick = float(self._clock())
        self._last_reseed_t: Optional[float] = None
        self._audit: List[Dict[str, Any]] = []
        self._counters = {
            "ticks": 0, "tick_errors": 0, "observed": 0,
            "rejected": 0, "candidates": 0, "promotions": 0,
            "rollbacks": 0, "abandoned": 0, "settled": 0,
        }

    # -- wiring ------------------------------------------------------

    def bind(self, router=None, harvest=None, events=None,
             anomaly=None, slo=None) -> None:
        """Late wiring from ``SolveService`` — fills only the planes
        the constructor left unset, so a pre-configured calibrator
        keeps its own sinks."""
        if self.router is None:
            self.router = router
        if self.harvest is None:
            self.harvest = harvest
        if self.events is None:
            self.events = events
        if self.anomaly is None:
            self.anomaly = anomaly
        if self.slo is None:
            self.slo = slo

    # -- evidence ingestion ------------------------------------------

    def observe(self, rec: Dict[str, Any]) -> bool:
        """Fold one harvest record into the rolling evidence. Accepts
        the full live stream — routed serve records and
        ``serve.shadow`` comparisons alike — and REJECTS (counted,
        never raised) anything that cannot be trusted as evidence:
        missing cell coordinates, unknown backend, non-finite
        outcome fields. A poisoned feed (chaos ``data.feed`` seam)
        produces exactly such records, and rejecting them here is what
        keeps corrupted evidence from ever driving a promotion.

        Tenancy: the ``tenant`` attribution field is deliberately
        ignored — compiled programs are tenant-blind, so evidence
        pools across tenants and the calibrator can never build a
        per-tenant route table.
        """
        bucket = rec.get("bucket")
        eps = rec.get("eps_abs")
        method = rec.get("solver")
        status = rec.get("status")
        iters = rec.get("iters")
        solve_s = rec.get("solve_s")
        obj = rec.get("obj_val", rec.get("obj"))
        is_shadow = (rec.get("shadow_of") is not None
                     or rec.get("source") == "serve.shadow")
        ok_fields = (
            isinstance(bucket, str) and bucket
            and _finite(eps)
            and method in _METHODS
            and isinstance(status, int)
            and isinstance(iters, int) and iters >= 0
            and (solve_s is None or (_finite(solve_s) and solve_s >= 0))
            and (obj is None or _finite(obj)))
        d_iters = rec.get("delta_iters")
        d_solve = rec.get("delta_solve_s")
        if ok_fields and is_shadow:
            ok_fields = (_finite(d_iters)
                         and (d_solve is None or _finite(d_solve))
                         and isinstance(rec.get("agree"), bool))
        if not ok_fields:
            with self._lock:
                self._counters["rejected"] += 1
            return False
        cell: Cell = (bucket, float(eps))
        solved = int(status) == _SOLVED
        with self._lock:
            self._counters["observed"] += 1
            dq = self._evidence.setdefault(cell, {}).setdefault(
                method, deque(maxlen=self.max_records_per_cell))
            dq.append((solved, int(iters),
                       None if solve_s is None else float(solve_s)))
            if is_shadow:
                agree = bool(rec["agree"])
                # A "win" is the promotion currency: the served answer
                # agreed AND the shadow backend was strictly better —
                # dispatch latency when both sides recorded it,
                # iterations otherwise.
                if d_solve is not None:
                    better = float(d_solve) < 0.0
                else:
                    better = int(d_iters) < 0
                win = agree and solved and better
                sdq = self._shadow.setdefault(cell, {}).setdefault(
                    method, deque(maxlen=self.max_records_per_cell))
                sdq.append((win, agree,
                            int(d_iters),
                            None if d_solve is None else float(d_solve)))
        return True

    # -- candidate computation ---------------------------------------

    def _active_route(self, table: Dict[Cell, str], cell: Cell) -> str:
        default = (self.router.default_method
                   if self.router is not None else _METHODS[0])
        return table.get(cell, default)

    def _cell_stats(self, cell: Cell) -> Dict[str, Dict[str, Any]]:
        # caller holds self._lock
        out: Dict[str, Dict[str, Any]] = {}
        for method, dq in self._evidence.get(cell, {}).items():
            n = len(dq)
            if not n:
                continue
            lats = [s for (_, _, s) in dq if s is not None]
            out[method] = {
                "count": n,
                "solved_share": sum(1 for (ok, _, _) in dq if ok) / n,
                "iters_mean": sum(it for (_, it, _) in dq) / n,
                "solve_s_mean": (sum(lats) / len(lats)) if lats else None,
            }
        return out

    def _shadow_stats(self, cell: Cell,
                      method: str) -> Optional[Dict[str, Any]]:
        # caller holds self._lock
        sdq = self._shadow.get(cell, {}).get(method)
        if not sdq:
            return None
        n = len(sdq)
        d_solves = [d for (_, _, _, d) in sdq if d is not None]
        return {
            "samples": n,
            "wins": sum(1 for (w, _, _, _) in sdq if w),
            "win_rate": sum(1 for (w, _, _, _) in sdq if w) / n,
            "agree_rate": sum(1 for (_, a, _, _) in sdq if a) / n,
            "delta_iters_mean": sum(d for (_, _, d, _) in sdq) / n,
            "delta_solve_s_mean": (sum(d_solves) / len(d_solves)
                                   if d_solves else None),
        }

    def _compute_candidate(self) -> Tuple[Dict[Cell, str],
                                          Dict[str, Dict[str, Any]]]:
        """The would-be next table plus the gated evidence diff.
        Scoring per cell matches ``seed_from_aggregate`` (solved share
        first, then mean dispatch latency when every contender has
        one, then mean iterations, then name) over every backend with
        ``min_samples`` evidence records — with three backends a cell
        is scored across all contenders that have matured, and a
        still-thin third stream cannot block the two thick ones from
        comparing (it simply is not a contender yet); a changed cell
        enters the diff only when the incoming winner's shadow
        comparisons also clear the ``win_rate`` bar on at least
        ``min_samples`` samples — the staged-promotion gate."""
        active = (self.router.table() if self.router is not None else {})
        candidate = dict(active)
        diff: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for cell in sorted(self._evidence):
                # Only matured contenders score: a method below
                # min_samples has no seat at the table this tick.
                stats = {m: e for m, e in self._cell_stats(cell).items()
                         if e["count"] >= self.min_samples}
                if len(stats) < 2:
                    continue
                have_lat = all(e["solve_s_mean"] is not None
                               for e in stats.values())

                def score(item):
                    m, e = item
                    primary = (e["solve_s_mean"] if have_lat
                               else e["iters_mean"])
                    return (-e["solved_share"], primary,
                            e["iters_mean"], m)

                winner = min(stats.items(), key=score)[0]
                incumbent = self._active_route(active, cell)
                if winner == incumbent:
                    continue
                shadow = self._shadow_stats(cell, winner)
                if (shadow is None
                        or shadow["samples"] < self.min_samples
                        or shadow["win_rate"] < self.win_rate):
                    continue
                candidate[cell] = winner
                diff[_cell_str(cell)] = {
                    "old": incumbent, "new": winner,
                    "evidence": {"per_method": stats, "shadow": shadow},
                }
        return candidate, diff

    # -- state machine -----------------------------------------------

    def maybe_tick(self) -> bool:
        """The ``_plane_tick`` entry: advance the state machine at
        most every ``min_interval_s`` on the injected clock. Returns
        whether a tick ran. Never raises — a broken calibration plane
        must not fail served traffic (same bar as every obs plane)."""
        now = float(self._clock())
        with self._lock:
            if now - self._last_tick < self.min_interval_s:
                return False
            self._last_tick = now
        try:
            self.tick(now)
        except Exception:  # noqa: BLE001 - plane must not fail serving
            with self._lock:
                self._counters["tick_errors"] += 1
            return False
        return True

    def tick(self, now: Optional[float] = None) -> None:
        """One state-machine step (the gate-free entry tests drive
        directly). Also opens a fresh shadow-budget window on the
        router — evidence-gathering cost is bounded per tick."""
        now = float(self._clock()) if now is None else float(now)
        with self._lock:
            self._counters["ticks"] += 1
            state = self._state
        if self.router is not None:
            self.router.reset_shadow_budget()
        if self.router is None:
            return
        if state == "guard":
            self._tick_guard(now)
        elif state == "canary":
            self._tick_canary(now)
        else:
            self._tick_idle(now)

    def _tick_idle(self, now: float) -> None:
        with self._lock:
            if now < self._cooldown_until:
                return
        candidate, diff = self._compute_candidate()
        if not diff:
            return
        with self._lock:
            self._state = "canary"
            self._candidate = candidate
            self._candidate_diff = diff
            self._canary_since = now
            self._counters["candidates"] += 1
        self._emit_reseed("candidate", now, diff,
                          table=candidate, action="candidate")

    def _tick_canary(self, now: float) -> None:
        candidate, diff = self._compute_candidate()
        with self._lock:
            held = (self._candidate is not None
                    and diff
                    and all(k in diff
                            and diff[k]["new"] == d["new"]
                            for k, d in self._candidate_diff.items()))
            if held:
                # Evidence may have sharpened mid-dwell; promote the
                # freshest view of the same decision.
                self._candidate = candidate
                self._candidate_diff = {
                    k: diff[k] for k in self._candidate_diff}
            dwelled = now - self._canary_since >= self.canary_dwell_s
        if not held:
            with self._lock:
                dropped = self._candidate_diff
                self._state = "idle"
                self._candidate = None
                self._candidate_diff = {}
                self._counters["abandoned"] += 1
            self._emit_reseed("abandoned", now, dropped)
            return
        if dwelled:
            self._promote(now)

    def _promote(self, now: float) -> None:
        with self._lock:
            candidate = dict(self._candidate or {})
            diff = self._candidate_diff
        prior = self.router.table()
        version = self.router.set_table(candidate)
        anomaly_fired = 0
        if self.anomaly is not None:
            anomaly_fired = int(
                self.anomaly.counters().get("anomalies_fired", 0))
        slo_firing: set = set()
        if self.slo is not None:
            slo_firing = set(self.slo.status().get("firing", ()))
        with self._lock:
            self._state = "guard"
            self._prior_table = prior
            self._promoted_at = now
            self._guard_base_anomaly = anomaly_fired
            self._guard_base_slo = slo_firing
            self._candidate = None
            self._last_reseed_t = now
            self._counters["promotions"] += 1
        self._emit_reseed("promoted", now, diff, table=candidate,
                          prior_table=prior, version=version,
                          action="promote")

    def _guard_breaches(self) -> List[str]:
        reasons: List[str] = []
        if self.anomaly is not None:
            fired = int(
                self.anomaly.counters().get("anomalies_fired", 0))
            with self._lock:
                base = self._guard_base_anomaly
            if fired > base:
                reasons.append(
                    f"anomaly_fired +{fired - base} since promotion")
        if self.slo is not None:
            firing = set(self.slo.status().get("firing", ()))
            with self._lock:
                fresh = sorted(firing - self._guard_base_slo)
            if fresh:
                reasons.append("slo_firing " + ",".join(fresh))
        return reasons

    def _tick_guard(self, now: float) -> None:
        reasons = self._guard_breaches()
        if reasons:
            self._rollback(now, reasons)
            return
        with self._lock:
            expired = now - self._promoted_at >= self.guard_window_s
            if expired:
                self._state = "idle"
                self._prior_table = None
                diff = self._candidate_diff
                self._candidate_diff = {}
                self._counters["settled"] += 1
        if expired:
            self._emit_reseed("settled", now, diff)

    def _rollback(self, now: float, reasons: List[str]) -> None:
        with self._lock:
            prior = dict(self._prior_table or {})
            diff = self._candidate_diff
        promoted = self.router.table()
        version = self.router.set_table(prior)
        with self._lock:
            self._state = "idle"
            self._prior_table = None
            self._candidate_diff = {}
            self._cooldown_until = now + self.cooldown_s
            self._counters["rollbacks"] += 1
            # Evidence that promoted a table the guard then shot down
            # is discredited: drop it so the same candidate must earn
            # a whole fresh window before it can come back.
            for key in diff:
                for cell in list(self._evidence):
                    if _cell_str(cell) == key:
                        self._evidence.pop(cell, None)
                        self._shadow.pop(cell, None)
        reason = "; ".join(reasons)
        audit = self._audit_record("rollback", now, version,
                                   table=prior, prior_table=promoted,
                                   diff=diff, reason=reason)
        if self.events is not None:
            # severity "error": this is the plane admitting a policy
            # it promoted degraded live traffic. The flight recorder
            # triggers on the kind — exactly one bundle per rollback
            # (debounce handles event-storm multiplicity).
            self.events.emit(
                "route_rollback", "error", reason=reason,
                table_version=version,
                restored_table={_cell_str(c): m
                                for c, m in sorted(prior.items())},
                diff=diff)

    # -- eventing / audit --------------------------------------------

    def _emit_reseed(self, state: str, now: float,
                     diff: Dict[str, Dict[str, Any]],
                     table: Optional[Dict[Cell, str]] = None,
                     prior_table: Optional[Dict[Cell, str]] = None,
                     version: Optional[int] = None,
                     action: Optional[str] = None) -> None:
        if version is None and self.router is not None:
            version = self.router.table_version
        if action is not None:
            self._audit_record(action, now, int(version or 0),
                               table=table or {},
                               prior_table=prior_table, diff=diff)
        if self.events is not None:
            fields: Dict[str, Any] = {
                "state": state,
                "table_version": int(version or 0),
                "n_cells": len(diff),
                "diff": diff,
            }
            if table is not None:
                fields["table"] = {_cell_str(c): m
                                   for c, m in sorted(table.items())}
            self.events.emit("route_reseed", "info", **fields)

    def _audit_record(self, action: str, now: float, version: int,
                      table: Dict[Cell, str],
                      prior_table: Optional[Dict[Cell, str]] = None,
                      diff: Optional[Dict[str, Any]] = None,
                      reason: Optional[str] = None) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "v": AUDIT_SCHEMA_VERSION,
            "source": CALIBRATION_AUDIT_SOURCE,
            "t": float(now),
            "action": action,
            "table_version": int(version),
            "table": {_cell_str(c): m
                      for c, m in sorted(table.items())},
            "diff": dict(diff or {}),
        }
        if prior_table is not None:
            rec["prior_table"] = {_cell_str(c): m
                                  for c, m in sorted(prior_table.items())}
        if reason is not None:
            rec["reason"] = reason
        with self._lock:
            self._audit.append(rec)
        if self.harvest is not None:
            self.harvest.emit(rec)
        return rec

    # -- readers -----------------------------------------------------

    def audit_records(self) -> List[Dict[str, Any]]:
        """Copies of every audit record this calibrator produced (the
        same records landed in the harvest warehouse)."""
        with self._lock:
            return [dict(r) for r in self._audit]

    def evidence(self) -> Dict[str, Dict[str, Any]]:
        """Per-cell rolling-evidence summary (JSON-able): per-backend
        sample counts / solved share / means plus the shadow win-rate
        table — what the bench payload and ``harvest_report`` render."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for cell in sorted(self._evidence):
                entry: Dict[str, Any] = {
                    "per_method": self._cell_stats(cell)}
                shadows = {m: self._shadow_stats(cell, m)
                           for m in self._shadow.get(cell, {})}
                shadows = {m: s for m, s in shadows.items()
                           if s is not None}
                if shadows:
                    entry["shadow"] = shadows
                out[_cell_str(cell)] = entry
        return out

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {f"calibration_{k}": int(v)
                    for k, v in self._counters.items()}

    def gauges(self) -> Dict[str, float]:
        """/metrics calibration gauges: table version, last-reseed
        age, promotion/rollback totals, the state-machine position."""
        now = float(self._clock())
        with self._lock:
            last = self._last_reseed_t
            state = self._state
            promotions = self._counters["promotions"]
            rollbacks = self._counters["rollbacks"]
        out = {
            "calibration_route_table_version": float(
                self.router.table_version if self.router is not None
                else 0),
            "calibration_promotions_total": float(promotions),
            "calibration_rollbacks_total": float(rollbacks),
            "calibration_state": _STATE_GAUGE.get(state, -1.0),
        }
        if last is not None:
            out["calibration_last_reseed_age_s"] = max(0.0, now - last)
        return out

    def status(self) -> Dict[str, Any]:
        """The ``/healthz`` calibration section: state, versioning,
        counters, the live candidate diff, and knob settings."""
        now = float(self._clock())
        with self._lock:
            last = self._last_reseed_t
            payload: Dict[str, Any] = {
                "state": self._state,
                "candidate_cells": sorted(self._candidate_diff),
                "cooldown_remaining_s": max(
                    0.0, self._cooldown_until - now),
                "evidence_cells": len(self._evidence),
                "counters": {k: int(v)
                             for k, v in self._counters.items()},
            }
        payload["table_version"] = (
            self.router.table_version if self.router is not None else 0)
        payload["last_reseed_age_s"] = (
            None if last is None else max(0.0, now - last))
        payload["knobs"] = {
            "min_interval_s": self.min_interval_s,
            "min_samples": self.min_samples,
            "win_rate": self.win_rate,
            "canary_dwell_s": self.canary_dwell_s,
            "guard_window_s": self.guard_window_s,
            "cooldown_s": self.cooldown_s,
            "max_records_per_cell": self.max_records_per_cell,
        }
        return payload


def replay_audit(records: Iterable[Dict[str, Any]]
                 ) -> Tuple[Dict[str, str], int]:
    """Rebuild ``(active_table, version)`` from an audit chain — the
    warehouse is the source of truth for what the router served with,
    and this is the machine check that versions are monotonic and
    never reused. Non-audit records are skipped (pass a whole harvest
    dataset); ``candidate`` entries annotate but do not swap. Raises
    ``ValueError`` on a non-monotonic version sequence."""
    table: Dict[str, str] = {}
    version = 0
    chain = sorted(
        (r for r in records
         if r.get("source") == CALIBRATION_AUDIT_SOURCE),
        key=lambda r: (int(r.get("table_version", 0)),
                       float(r.get("t", 0.0))))
    for rec in chain:
        if rec.get("action") not in ("promote", "rollback"):
            continue
        v = int(rec.get("table_version", 0))
        if v <= version:
            raise ValueError(
                f"audit chain not monotonic: version {v} after "
                f"{version} (action {rec.get('action')!r})")
        version = v
        table = dict(rec.get("table", {}))
    return table, version
