"""Incident flight recorder: always-on rings, triggered evidence dumps.

When the breaker opens or a deadline storm hits, the evidence a
post-mortem needs — the events, spans, and metric trajectory leading
INTO the incident — is exactly what the bounded obs buffers are about
to evict. The :class:`FlightRecorder` is the black box: it rides along
holding bounded rings of recent history, and on a **trigger** dumps
one debounced, disk-bounded, self-contained incident bundle.

Triggers (:data:`DEFAULT_TRIGGERS`) are event kinds observed through
an :meth:`~porqua_tpu.obs.events.EventBus.add_listener` hook: breaker
opens, retry give-ups, validation failures, sanitizer/TSAN errors,
harvest-sink death, firing SLO alerts (:mod:`porqua_tpu.obs.slo`), and
convergence anomalies (:mod:`porqua_tpu.obs.anomaly`). ``slo_alert``
and ``convergence_anomaly`` events trigger only in their ``firing``
state — resolutions are history, not incidents.

One bundle (``incident-<seq>-<kind>.json.gz``) is self-contained:
the trigger event, a config fingerprint, the full metrics snapshot,
the recent metric-snapshot ring, the event/span tails, recent
SolveRecords, per-device breaker history, and the SLO/anomaly status
at dump time — renderable offline by ``scripts/incident_report.py``.
Dumps are debounced (``debounce_s`` on an injectable monotonic clock:
one bundle per window however many triggers fire inside it) and
disk-bounded (``max_bundles`` newest kept, oldest deleted).

The recorder is pure host bookkeeping around buffers the serve stack
already fills: the GC106 contract (:func:`porqua_tpu.analysis.
contracts.check_observability_identity`) machine-checks that a live,
dumping recorder changes no traced program.
"""

from __future__ import annotations

import collections
import gzip
import hashlib
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from porqua_tpu.analysis import tsan

__all__ = [
    "BUNDLE_VERSION",
    "DEFAULT_TRIGGERS",
    "FlightRecorder",
    "load_bundle",
]

BUNDLE_VERSION = 1

#: Event kinds that open an incident (the trigger inventory — README
#: "SLOs, alerting & incident response" documents each). Stateful
#: kinds (``slo_alert``, ``convergence_anomaly``, ``vitals_anomaly``)
#: trigger only when their ``state`` field is ``firing``.
#: ``worker_lost`` and ``vitals_anomaly`` come from the fleet plane
#: (:mod:`porqua_tpu.obs.federation` / :mod:`porqua_tpu.obs.vitals`):
#: a crashed loadgen shard or a leaking worker must land an incident
#: bundle, not a silent throughput dip. ``route_rollback`` comes from
#: the calibration plane (:mod:`porqua_tpu.obs.calibrate`): a promoted
#: route table the guard window had to revert is an incident — the
#: bundle carries the evidence diff that promoted it and the breach
#: that shot it down.
DEFAULT_TRIGGERS = (
    "breaker_open",
    "retry_giveup",
    "validation_failed",
    "sanitizer_violation",
    "harvest_sink_failed",
    "slo_alert",
    "convergence_anomaly",
    "worker_lost",
    "vitals_anomaly",
    "route_rollback",
)

#: Kinds whose events carry an alert ``state`` — only the firing edge
#: is an incident.
_STATEFUL_TRIGGERS = ("slo_alert", "convergence_anomaly",
                      "vitals_anomaly")

#: Event kinds folded into the bundle's per-device breaker history.
_BREAKER_KINDS = ("breaker_open", "breaker_close", "probe_failure")


def load_bundle(path: str) -> Dict[str, Any]:
    """Read one incident bundle back (``.json.gz`` or plain JSON)."""
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


class FlightRecorder:
    """The always-on incident black box (see module docstring).

    ``out_dir=None`` keeps bundles as in-memory dicts (tests, the
    chaos suite's per-cell assertions still parse real written files
    when a directory is given). ``armed=False`` starts the recorder
    observing but not dumping — ``arm()`` when the window of interest
    opens (the chaos suite arms after prewarm so warmup compiles don't
    spend the debounce budget).

    Thread-safety: ``on_event`` runs on whatever thread emits the
    trigger (dispatch thread, health-manager threads, retry timer);
    ``record_solve``/``maybe_snapshot`` on the dispatch thread;
    readers anywhere. The recorder lock guards only recorder state —
    ring gathering at dump time reads the bus/spans/metrics through
    their own locks with the recorder lock RELEASED, so the lock graph
    stays acyclic.
    """

    def __init__(self,
                 out_dir: Optional[str] = None,
                 triggers: Tuple[str, ...] = DEFAULT_TRIGGERS,
                 debounce_s: float = 30.0,
                 max_bundles: int = 16,
                 armed: bool = True,
                 solve_capacity: int = 256,
                 snapshot_capacity: int = 64,
                 events_tail: int = 2048,
                 spans_tail: int = 1024,
                 snapshot_interval_s: float = 5.0,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.out_dir = out_dir
        self.triggers = frozenset(triggers)
        self.debounce_s = float(debounce_s)
        self.max_bundles = int(max_bundles)
        self.events_tail = int(events_tail)
        self.spans_tail = int(spans_tail)
        self.snapshot_interval_s = float(snapshot_interval_s)
        self.clock = time.monotonic if clock is None else clock
        self.metrics = None
        self.obs = None
        self.slo = None
        self.anomaly = None
        self.cache = None
        self._params_repr: Optional[str] = None
        self._extra_config: Dict[str, Any] = {}
        self._lock = tsan.lock("FlightRecorder")
        self._armed = bool(armed)          # guarded-by: self._lock
        self._seq = 0                      # guarded-by: self._lock
        self._last_dump = float("-inf")    # guarded-by: self._lock
        self._last_snapshot = float("-inf")  # guarded-by: self._lock
        self._suppressed = 0               # guarded-by: self._lock
        self._write_failures = 0           # guarded-by: self._lock
        # guarded-by: self._lock
        self._solves: "collections.deque[Dict[str, Any]]" = (
            collections.deque(maxlen=int(solve_capacity)))
        # guarded-by: self._lock
        self._snapshots: "collections.deque[Dict[str, Any]]" = (
            collections.deque(maxlen=int(snapshot_capacity)))
        # Written paths (file mode) or bundle dicts (memory mode),
        # oldest first.                      guarded-by: self._lock
        self._bundles: List[Any] = []
        if out_dir is not None:
            os.makedirs(out_dir, exist_ok=True)

    # -- wiring -------------------------------------------------------

    def attach(self, metrics=None, obs=None, params=None, slo=None,
               anomaly=None, cache=None,
               extra_config: Optional[Dict[str, Any]] = None
               ) -> "FlightRecorder":
        """Point the recorder at the serve stack's obs surfaces
        (``SolveService`` calls this and registers :meth:`on_event` as
        an event-bus listener). ``params`` feeds the bundle's config
        fingerprint; ``extra_config`` rides along verbatim. ``cache``
        (an :class:`~porqua_tpu.serve.bucketing.ExecutableCache`)
        makes each bundle carry the harvested CostRecords of the
        implicated bucket's executables — the post-mortem sees what
        XLA thought the failing program cost without rerunning a
        compile."""
        if metrics is not None:
            self.metrics = metrics
        if obs is not None:
            self.obs = obs
        if slo is not None:
            self.slo = slo
        if anomaly is not None:
            self.anomaly = anomaly
        if cache is not None:
            self.cache = cache
        if params is not None:
            self._params_repr = repr(params)
        if extra_config:
            self._extra_config.update(extra_config)
        return self

    def arm(self) -> None:
        with self._lock:
            self._armed = True

    def disarm(self) -> None:
        with self._lock:
            self._armed = False

    # -- feeds --------------------------------------------------------

    def record_solve(self, record: Dict[str, Any]) -> None:
        """One resolved request's SolveRecord into the bounded ring
        (the batchers call this per retirement when a recorder is
        wired — same record the harvest sink receives)."""
        with self._lock:
            self._solves.append(record)

    def record_snapshot(self, snapshot: Dict[str, Any]) -> None:
        with self._lock:
            self._snapshots.append(snapshot)

    def maybe_snapshot(self) -> None:
        """Clock-gated metrics-snapshot sampling (called per request
        retirement; one snapshot per ``snapshot_interval_s``), so the
        bundle carries the metric TRAJECTORY into the incident, not
        just the end state."""
        if self.metrics is None:
            return
        now = self.clock()
        with self._lock:
            if now - self._last_snapshot < self.snapshot_interval_s:
                return
            self._last_snapshot = now
        snap = self.metrics.snapshot()
        self.record_snapshot(snap)

    # -- triggering ---------------------------------------------------

    def on_event(self, event: Dict[str, Any]) -> None:
        """EventBus listener: dump on a trigger kind. Never raises
        (the bus already shields listeners, but a recorder failure
        must degrade to a counter either way)."""
        kind = event.get("kind")
        if kind not in self.triggers:
            return
        if kind in _STATEFUL_TRIGGERS and event.get("state") != "firing":
            return
        self._trigger(event)

    def dump(self, kind: str = "manual", **fields) -> Optional[Any]:
        """Programmatic trigger (operator tooling, tests): dump now,
        subject to the same arming and debounce as event triggers."""
        event = {"t": time.time(), "kind": kind, "severity": "info"}
        event.update(fields)
        return self._trigger(event)

    def _trigger(self, event: Dict[str, Any]) -> Optional[Any]:
        now = self.clock()
        with self._lock:
            if not self._armed:
                return None
            if now - self._last_dump < self.debounce_s:
                self._suppressed += 1
                return None
            # Reserve the debounce window BEFORE building: concurrent
            # triggers on other threads debounce against this dump.
            prev_dump = self._last_dump
            self._last_dump = now
            self._seq += 1
            seq = self._seq
        try:
            bundle = self._build(event, seq)
            out = self._store(bundle, seq, str(event.get("kind", "?")))
        except Exception:  # noqa: BLE001 - the recorder must never
            # take down the path that triggered it (often the breaker's
            # own trip path); a failed dump is a counted loss — and it
            # must NOT consume the debounce window: a transient disk
            # error at the first trigger would otherwise suppress every
            # retrigger for the whole incident, capturing nothing.
            with self._lock:
                self._write_failures += 1
                if self._last_dump == now:
                    self._last_dump = prev_dump
            return None
        return out

    # -- bundle assembly ---------------------------------------------

    def _config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = dict(self._extra_config)
        cfg["pid"] = os.getpid()
        if self._params_repr is not None:
            cfg["params"] = self._params_repr
            cfg["fingerprint"] = hashlib.blake2b(
                self._params_repr.encode(), digest_size=8).hexdigest()
        return cfg

    @staticmethod
    def _breaker_history(events: List[Dict[str, Any]]
                         ) -> Dict[str, List[Dict[str, Any]]]:
        """Per-device breaker/probe timeline from the event tail."""
        out: Dict[str, List[Dict[str, Any]]] = {}
        for e in events:
            kind = e.get("kind")
            if kind not in _BREAKER_KINDS:
                continue
            device = str(e.get("device") or e.get("primary") or "?")
            out.setdefault(device, []).append(
                {"t": e.get("t"), "kind": kind,
                 **{k: v for k, v in e.items()
                    if k not in ("t", "kind", "severity")}})
        return out

    def _build(self, trigger: Dict[str, Any], seq: int) -> Dict[str, Any]:
        """Assemble one bundle. Reads the bus/spans/metrics through
        their own locks with the recorder lock released."""
        events: List[Dict[str, Any]] = []
        spans: List[Dict[str, Any]] = []
        if self.obs is not None:
            events = self.obs.events.events()[-self.events_tail:]
            spans = [
                {"name": s.name, "t_start": s.t_start, "t_end": s.t_end,
                 "trace_id": s.trace_id, "args": s.args}
                for s in self.obs.spans.spans()[-self.spans_tail:]]
        bundle: Dict[str, Any] = {
            "v": BUNDLE_VERSION,
            "t": time.time(),
            "seq": seq,
            "trigger": dict(trigger),
            "config": self._config(),
            "events": events,
            "spans": spans,
            "breaker_history": self._breaker_history(events),
        }
        if self.metrics is not None:
            bundle["counters"] = self.metrics.snapshot()
        with self._lock:
            bundle["snapshots"] = list(self._snapshots)
            bundle["solves"] = list(self._solves)
        if self.slo is not None:
            bundle["slo"] = self.slo.status()
        if self.anomaly is not None:
            bundle["anomaly"] = self.anomaly.status()
        if self.cache is not None:
            # Device-truth cost evidence: the CostRecords of the
            # implicated bucket's executables (triggers that carry a
            # `bucket` field — dispatch failures, sanitizer refusals,
            # anomalies), falling back to the whole harvested set
            # when the trigger names none. Bounded: a cache holds a
            # handful of executables per bucket, not per request.
            try:
                records = self.cache.cost_records()
            except Exception:  # noqa: BLE001 - evidence, not dependency
                records = []
            implicated = trigger.get("bucket")
            if implicated is not None:
                # Exact bucket, or its factored variants ("NxM" events
                # cover "NxMxfR" labels) — a bare prefix would also
                # swallow unrelated buckets ("32x8" matching "32x80").
                b = str(implicated)
                matched = [r for r in records
                           if str(r.get("bucket", "")) == b
                           or str(r.get("bucket", "")).startswith(
                               b + "xf")]
                if matched:
                    records = matched
                bundle["implicated_bucket"] = str(implicated)
            bundle["cost_records"] = records[:64]
        return bundle

    def _store(self, bundle: Dict[str, Any], seq: int, kind: str):
        if self.out_dir is None:
            with self._lock:
                self._bundles.append(bundle)
                while len(self._bundles) > self.max_bundles:
                    self._bundles.pop(0)
            return bundle
        safe_kind = "".join(c if c.isalnum() or c in "-_" else "_"
                            for c in kind)
        path = os.path.join(self.out_dir,
                            f"incident-{seq:04d}-{safe_kind}.json.gz")
        with gzip.open(path, "wt") as f:
            json.dump(bundle, f, default=str)
        evict: List[str] = []
        with self._lock:
            self._bundles.append(path)
            while len(self._bundles) > self.max_bundles:
                evict.append(self._bundles.pop(0))
        for old in evict:  # disk-bounded: newest max_bundles kept
            try:
                os.remove(old)
            except OSError:
                pass
        return path

    # -- readers ------------------------------------------------------

    def bundles(self) -> List[Any]:
        """Written bundle paths (file mode) or bundle dicts (memory
        mode), oldest first."""
        with self._lock:
            return list(self._bundles)

    @property
    def suppressed(self) -> int:
        with self._lock:
            return self._suppressed

    def counters(self) -> Dict[str, int]:
        """Recorder health counters for ``/metrics`` + ``/healthz``."""
        with self._lock:
            # _seq counts reserved dumps; the ones that failed to
            # build/write are the write_failures — the rest landed
            # (retention may have evicted old files, but they existed).
            return {"flight_bundles": self._seq - self._write_failures,
                    "flight_dumps_suppressed": self._suppressed,
                    "flight_write_failures": self._write_failures}
