"""Harvest-calibrated online convergence anomaly detection.

The telemetry warehouse (:mod:`porqua_tpu.obs.harvest`) turned every
solve into a record and ``scripts/harvest_report.py`` rolls them into
per-(bucket, eps) iteration quantiles — an offline picture of what
"normal" convergence looks like. This module closes the loop online:
:class:`AnomalyDetector` loads those aggregates as **baselines** and,
at every request retirement in both batchers, folds the lane's final
iteration count and wasted-iteration fraction into per-group EWMAs.
When a group's EWMA drifts past its baseline quantile band (iters EWMA
above ``iters_factor`` x the baseline p95, or waste EWMA above the
baseline waste + ``waste_margin``), the detector fires ONE
``convergence_anomaly`` event (``state="firing"``) — a flight-recorder
trigger — and resolves it with hysteresis once the EWMA falls back
under ``clear_fraction`` of the band.

This is exactly the detection the ROADMAP's learned-adaptive-policy
item presupposes: a policy that adapts per problem ("Learning
context-aware adaptive solvers to accelerate quadratic programming",
PAPERS.md) first needs to know, live, when convergence has left the
distribution it was fitted on (HARVEST_r07-style datasets).

Pure host arithmetic on integers the batchers already fetched: the
GC106 contract (:func:`porqua_tpu.analysis.contracts.
check_observability_identity`) machine-checks a live detector changes
no traced program.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

from porqua_tpu.analysis import tsan

__all__ = ["AnomalyDetector"]


class _GroupState:
    """Per-(bucket, eps) online state (guarded by the detector lock)."""

    __slots__ = ("n", "ewma_iters", "ewma_waste", "anomalous")

    def __init__(self) -> None:
        self.n = 0
        self.ewma_iters = 0.0
        self.ewma_waste = 0.0
        self.anomalous = False


def _eps_key(eps) -> Optional[float]:
    """Normalize an eps value into a stable group key (floats from
    params and from a JSON round-trip of the same params compare
    equal; ``None`` stays ``None``)."""
    return None if eps is None else float(eps)


class AnomalyDetector:
    """Online EWMA-vs-baseline convergence monitor (module docstring).

    ``baseline`` maps ``(bucket, eps_abs)`` to quantile bands — build
    it from a harvest dataset via :meth:`from_harvest` (the
    ``--anomaly-baseline`` path) or from a precomputed
    :func:`porqua_tpu.obs.harvest.aggregate` payload via
    :meth:`from_aggregate`. Groups the baseline has never seen are
    counted (``anomaly_unknown_group``) but never judged — an unknown
    workload is not evidence of drift.

    Thread-safety: ``observe`` runs on the dispatch thread,
    ``status``/``counters`` on scrape threads; state is guarded by the
    instance lock and events are emitted OUTSIDE it (the flight
    recorder's dump path reads ``status()`` from an event listener).
    """

    def __init__(self,
                 baseline: Dict[Tuple[str, Optional[float]],
                                Dict[str, float]],
                 alpha: float = 0.2,
                 iters_factor: float = 1.5,
                 waste_margin: float = 0.25,
                 clear_fraction: float = 0.9,
                 min_samples: int = 8,
                 events=None) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        self.baseline = {(str(b), _eps_key(e)): dict(v)
                         for (b, e), v in baseline.items()}
        self.alpha = float(alpha)
        self.iters_factor = float(iters_factor)
        self.waste_margin = float(waste_margin)
        self.clear_fraction = float(clear_fraction)
        self.min_samples = int(min_samples)
        self.events = events
        self._lock = tsan.lock("AnomalyDetector")
        # (tenant, bucket, eps) online groups against (bucket, eps)
        # baselines.                            guarded-by: self._lock
        self._groups: Dict[Tuple[Optional[str], str, Optional[float]],
                           _GroupState] = {}
        self._fired = 0            # guarded-by: self._lock
        self._resolved = 0         # guarded-by: self._lock
        self._unknown = 0          # guarded-by: self._lock
        self._observed = 0         # guarded-by: self._lock

    # -- constructors -------------------------------------------------

    @classmethod
    def from_aggregate(cls, agg: Dict[str, Any],
                       **kwargs) -> "AnomalyDetector":
        """Baselines from one :func:`porqua_tpu.obs.harvest.aggregate`
        payload (``scripts/harvest_report.py``'s table).

        Aggregates are per ``(tenant, bucket, eps)`` since harvest
        schema v2; the BASELINE stays per ``(bucket, eps)`` — solver
        convergence is physics of the problem class, not of who
        submitted it — so tenant rows of the same (bucket, eps) merge:
        counts sum, the p95/max band takes the widest tenant's value
        (conservative: the band only ever loosens), the waste
        attribution count-weights. Online EWMAs are still tracked per
        (tenant, bucket, eps), so a single tenant's drift fires an
        event naming that tenant."""
        merged: Dict[tuple, Dict[str, float]] = {}
        for g in agg.get("groups", ()):
            key = (str(g["bucket"]), _eps_key(g.get("eps_abs")))
            count = int(g.get("count", 0))
            row = {
                "iters_p50": float(g["iters"]["p50"]),
                "iters_p95": float(g["iters"]["p95"]),
                "iters_max": float(g["iters"]["max"]),
                "wasted": float(g.get("wasted_iteration_fraction", 0.0)),
                "count": count,
            }
            base = merged.get(key)
            if base is None:
                merged[key] = row
                continue
            total = base["count"] + count
            if total > 0:
                base["iters_p50"] = (
                    base["iters_p50"] * base["count"]
                    + row["iters_p50"] * count) / total
                base["wasted"] = (base["wasted"] * base["count"]
                                  + row["wasted"] * count) / total
            base["iters_p95"] = max(base["iters_p95"], row["iters_p95"])
            base["iters_max"] = max(base["iters_max"], row["iters_max"])
            base["count"] = total
        return cls(merged, **kwargs)

    @classmethod
    def from_harvest(cls, path: str, **kwargs) -> "AnomalyDetector":
        """Baselines straight from a harvest dataset (JSONL/.gz) —
        ``HARVEST_r07.json``-era datasets load unchanged."""
        from porqua_tpu.obs.harvest import aggregate, load_harvest

        return cls.from_aggregate(aggregate(load_harvest(path)), **kwargs)

    # -- online path --------------------------------------------------

    def _bands(self, base: Dict[str, float]) -> Tuple[float, float]:
        iters_band = max(base.get("iters_p95", 0.0), 1.0) * self.iters_factor
        waste_band = base.get("wasted", 0.0) + self.waste_margin
        return iters_band, waste_band

    def observe(self, bucket: str, eps, iters: int,
                segments: Optional[int] = None,
                check_interval: int = 1,
                tenant: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Fold one retired lane into its group's EWMAs and step the
        anomaly state machine; returns the transition event emitted
        (``None`` almost always). ``segments`` is the executed segment
        count where the caller knows it (continuous/compacted modes);
        classic mode derives ``ceil(iters / check_interval)`` — the
        same convention :func:`porqua_tpu.obs.harvest.solve_record`
        uses, so online waste matches the baseline's attribution.
        ``tenant`` splits the online EWMA per tenant against the
        shared (bucket, eps) baseline, so one tenant's corrupt feed or
        pathological stream fires an event carrying that tenant while
        the others' groups stay clean."""
        base_key = (str(bucket), _eps_key(eps))
        key = (tenant, str(bucket), _eps_key(eps))
        base = self.baseline.get(base_key)
        iters = int(iters)
        ci = max(int(check_interval), 1)
        segs = int(segments) if segments else max(-(-iters // ci), 1)
        waste = 1.0 - iters / max(segs * ci, 1)
        waste = min(max(waste, 0.0), 1.0)
        event: Optional[Dict[str, Any]] = None
        with self._lock:
            self._observed += 1
            if base is None:
                self._unknown += 1
                return None
            g = self._groups.setdefault(key, _GroupState())
            if g.n == 0:
                g.ewma_iters = float(iters)
                g.ewma_waste = waste
            else:
                a = self.alpha
                g.ewma_iters += a * (iters - g.ewma_iters)
                g.ewma_waste += a * (waste - g.ewma_waste)
            g.n += 1
            iters_band, waste_band = self._bands(base)
            breach = g.n >= self.min_samples and (
                g.ewma_iters > iters_band or g.ewma_waste > waste_band)
            clear = (g.ewma_iters <= iters_band * self.clear_fraction
                     and g.ewma_waste
                     <= waste_band * self.clear_fraction)
            if breach and not g.anomalous:
                g.anomalous = True
                self._fired += 1
                event = self._event("firing", "warn", key, g, base)
            elif g.anomalous and clear:
                g.anomalous = False
                self._resolved += 1
                event = self._event("resolved", "info", key, g, base)
        if event is not None and self.events is not None:
            self.events.emit(**event)
        return event

    def _event(self, state: str, severity: str, key, g: _GroupState,  # guarded-by: self._lock
               base: Dict[str, float]) -> Dict[str, Any]:
        iters_band, waste_band = self._bands(base)
        extra = {} if key[0] is None else {"tenant": key[0]}
        return dict(
            kind="convergence_anomaly", severity=severity,
            state=state, bucket=key[1], eps=key[2], **extra,
            ewma_iters=round(g.ewma_iters, 2),
            ewma_waste=round(g.ewma_waste, 4),
            iters_band=round(iters_band, 2),
            waste_band=round(waste_band, 4),
            baseline_iters_p95=base.get("iters_p95"),
            baseline_wasted=base.get("wasted"),
            n=g.n)

    # -- readers ------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """Per-group EWMA-vs-band state (the flight bundle's
        ``anomaly`` section and the ``/healthz`` surface)."""
        with self._lock:
            groups = {}
            anomalous: List[str] = []
            for (tenant, bucket, eps), g in self._groups.items():
                base = self.baseline[(bucket, eps)]
                iters_band, waste_band = self._bands(base)
                label = (f"{bucket}@{eps:.0e}" if eps is not None
                         and math.isfinite(eps) else f"{bucket}@-")
                if tenant is not None:
                    label = f"{tenant}/{label}"
                groups[label] = {
                    "n": g.n,
                    "ewma_iters": round(g.ewma_iters, 2),
                    "ewma_waste": round(g.ewma_waste, 4),
                    "iters_band": round(iters_band, 2),
                    "waste_band": round(waste_band, 4),
                    "anomalous": g.anomalous,
                }
                if g.anomalous:
                    anomalous.append(label)
            return {
                "groups": groups,
                "anomalous": anomalous,
                "fired": self._fired,
                "resolved": self._resolved,
                "observed": self._observed,
                "unknown_group": self._unknown,
                "baseline_groups": len(self.baseline),
            }

    def counters(self) -> Dict[str, int]:
        """Exposition counters (``/metrics`` extra_counters path)."""
        with self._lock:
            return {"anomalies_fired": self._fired,
                    "anomaly_unknown_group": self._unknown}
