"""Unified observability for the online serve stack.

Three pillars, one package (README "Observability" has the schemas):

* **Request span tracing** (:mod:`porqua_tpu.obs.trace`) — trace/span
  ids minted at ``SolveService.submit`` and recorded at every pipeline
  transition (pad → queue wait → batch assembly → device dispatch →
  resolve), exported as Chrome-trace-event JSON that Perfetto loads
  next to ``jax.profiler`` device traces.
* **On-device convergence rings** (:mod:`porqua_tpu.obs.rings`, data
  produced by ``qp/admm.py`` under ``SolverParams(ring_size=K)``) —
  per-problem ``(prim_res, dual_res, rho)`` sampled at each residual
  check *inside* the jitted program, zero host syncs; this module
  decodes them chronologically.
* **Event log + exposition** (:mod:`porqua_tpu.obs.events`,
  :mod:`porqua_tpu.obs.exposition`) — a structured JSON-lines event
  bus (compiles, circuit-breaker transitions, sanitizer violations,
  backpressure rejections, deadline expiries; severity + trace id),
  Prometheus text exposition of ``ServeMetrics``, and an optional
  stdlib-HTTP ``/metrics`` + ``/healthz`` endpoint.

On top of the pillars sits the **live operational plane** (README
"SLOs, alerting & incident response"): :mod:`porqua_tpu.obs.slo`
(declarative SLOs + multi-window burn-rate alerting),
:mod:`porqua_tpu.obs.flight` (the incident flight recorder dumping
debounced, self-contained evidence bundles on triggers), and
:mod:`porqua_tpu.obs.anomaly` (harvest-calibrated online convergence
anomaly detection) — wired through ``SolveService(slo=..., flight=...,
anomaly=...)`` and machine-checked invisible to XLA by contract GC106.

The **device-truth profiling plane** (README "Device-truth
profiling") grounds the perf claims in the compiler's own numbers:
:mod:`porqua_tpu.obs.devprof` harvests every AOT executable's XLA
``cost_analysis``/``memory_analysis`` into CostRecords (``CostLog``),
``qp_solve_profile`` switches its MFU/bandwidth numerators to those
measured figures where available, and ``roofline_verdict`` /
``scripts/roofline_report.py`` rank executables by measured bytes
into the fusion-candidate verdict — contract GC107 pins the plane
invisible to XLA.

The **fleet federation plane** (README "Fleet observability & soak
testing") scales all of it past one process:
:mod:`porqua_tpu.obs.federation` (per-worker ``WorkerStream`` JSONL
emitters drained by a ``FleetCollector`` that merges counters and RAW
latency histograms, runs fleet SLOs through the same ``SLOEngine``,
tracks worker liveness into ``worker_lost`` incidents, and keeps
bounded soak rollups), :mod:`porqua_tpu.obs.vitals` (process vitals +
EWMA leak trending), and :mod:`porqua_tpu.obs.ledger` (the
longitudinal run ledger ``bench_gate --trend`` gates against) —
contract GC108 pins the whole plane invisible to XLA.

:class:`Observability` bundles one span recorder and one event bus;
pass it to ``SolveService(obs=...)`` and every layer (batcher,
executable cache, device health) records through it. The package is
pure host code — importing it initializes no JAX backend, and nothing
in it runs on the request hot path beyond lock-bounded appends.
"""

from porqua_tpu.obs.anomaly import AnomalyDetector
from porqua_tpu.obs.calibrate import Calibrator, replay_audit
from porqua_tpu.obs.devprof import (
    CostLog,
    ProfileWindow,
    cost_record,
    load_cost_records,
    roofline_verdict,
)
from porqua_tpu.obs.events import EventBus, load_jsonl
from porqua_tpu.obs.exposition import ObsHTTPServer, prometheus_text
from porqua_tpu.obs.federation import FleetCollector, WorkerStream
from porqua_tpu.obs.flight import FlightRecorder, load_bundle
from porqua_tpu.obs.harvest import (
    HarvestSink,
    harvest_solution,
    load_harvest,
    solve_record,
)
from porqua_tpu.obs.ledger import (
    append_row,
    ledger_row,
    load_ledger,
    rolling_median,
)
from porqua_tpu.obs.profile import StageProfiler, qp_solve_profile
from porqua_tpu.obs.report import render_report
from porqua_tpu.obs.rings import ring_history, solution_ring_history
from porqua_tpu.obs.slo import (
    SLO,
    BurnRateRule,
    SLOEngine,
    TenantSLOSet,
    default_slos,
)
from porqua_tpu.obs.trace import Span, SpanRecorder
from porqua_tpu.obs.vitals import VitalsTrend, process_vitals


class Observability:
    """One span recorder + one event bus, shared by a serve stack."""

    def __init__(self, span_capacity: int = 262144,
                 event_capacity: int = 65536,
                 event_path=None) -> None:
        self.spans = SpanRecorder(capacity=span_capacity)
        self.events = EventBus(capacity=event_capacity, path=event_path)

    def write(self, trace_path=None, events_path=None) -> None:
        """Dump whichever artifacts were requested."""
        if trace_path:
            self.spans.write(trace_path)
        if events_path:
            self.events.write_jsonl(events_path)


__all__ = [
    "AnomalyDetector",
    "BurnRateRule",
    "CostLog",
    "EventBus",
    "FleetCollector",
    "FlightRecorder",
    "HarvestSink",
    "Observability",
    "ObsHTTPServer",
    "ProfileWindow",
    "SLO",
    "SLOEngine",
    "Span",
    "SpanRecorder",
    "StageProfiler",
    "TenantSLOSet",
    "VitalsTrend",
    "WorkerStream",
    "append_row",
    "cost_record",
    "default_slos",
    "harvest_solution",
    "ledger_row",
    "load_bundle",
    "load_cost_records",
    "load_harvest",
    "load_jsonl",
    "load_ledger",
    "process_vitals",
    "prometheus_text",
    "qp_solve_profile",
    "render_report",
    "ring_history",
    "rolling_median",
    "roofline_verdict",
    "solution_ring_history",
    "solve_record",
]
