"""Prometheus text exposition + optional stdlib-HTTP scrape endpoint.

:func:`prometheus_text` renders a :class:`porqua_tpu.serve.metrics.
ServeMetrics` snapshot in the Prometheus text exposition format
(version 0.0.4): window counters as ``counter`` metrics, derived
rates/percentiles/gauges as ``gauge``, the current device identity as
an info-style labeled gauge. :class:`ObsHTTPServer` is the zero-
dependency scrape endpoint — ``http.server.ThreadingHTTPServer`` on a
daemon thread serving ``/metrics`` (exposition) and ``/healthz``
(JSON liveness + degradation) — started via
``SolveService.start_http()``. Plane gauges ride the same snapshot:
a wired :class:`~porqua_tpu.obs.calibrate.Calibrator` surfaces its
``calibration_*`` counters and gauges (route-table version, state-
machine position, promotion/rollback totals, last-reseed age) here
and its full status section on ``/healthz``. Metric names: README
"Observability".
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

#: Snapshot keys that are free-form metadata, not metrics.
_NON_METRIC_KEYS = ("device", "t")

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(prefix: str, key: str) -> str:
    return f"{prefix}_{_NAME_RE.sub('_', key)}"


def _escape_label(value) -> str:
    """Label-value escaping per the exposition format spec: an
    unescaped ``"``/``\\``/newline in any label would invalidate the
    WHOLE scrape, not just its line."""
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _histogram_lines(name: str, hist: Dict[str, Any]) -> list:
    """Render one histogram as cumulative ``_bucket``/``_sum``/
    ``_count`` series (Prometheus histogram semantics: each ``le``
    bucket counts every observation <= its bound, ``+Inf`` == count).
    ``hist`` is :meth:`porqua_tpu.serve.metrics.ServeMetrics.
    histograms` state — per-bucket (non-cumulative) counts with the
    overflow bucket last."""
    lines = [f"# TYPE {name} histogram"]
    cum = 0
    for le, count in zip(hist["le"], hist["counts"]):
        cum += int(count)
        le_s = f"{float(le):g}"
        lines.append(f'{name}_bucket{{le="{le_s}"}} {cum}')
    cum += int(hist["counts"][-1])
    lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
    lines.append(f"{name}_sum {float(hist['sum'])}")
    lines.append(f"{name}_count {int(hist['count'])}")
    return lines


def prometheus_text(snapshot: Dict[str, Any],
                    prefix: str = "porqua_serve",
                    histograms: Optional[Dict[str, Dict[str, Any]]] = None,
                    extra_counters: Optional[Dict[str, Any]] = None,
                    extra_gauges: Optional[Dict[str, Any]] = None,
                    labeled_gauges: Optional[Dict[str, Any]] = None) -> str:
    """Render one metrics snapshot as Prometheus exposition text.

    Every numeric snapshot key is exported; keys in the window-counter
    set (:data:`porqua_tpu.serve.metrics.COUNTERS`) are typed
    ``counter`` (they reset with the measurement window — scrapers
    should treat window resets like process restarts), everything else
    ``gauge``. ``degraded`` exports as 0/1 and ``device`` as a labeled
    ``_device_info`` gauge.

    ``histograms`` renders real cumulative-histogram series
    (``<prefix>_<name>_bucket{le=...}`` / ``_sum`` / ``_count`` —
    :meth:`ServeMetrics.histograms` state) next to the percentile
    gauges, which stay for backward compatibility. ``extra_counters``
    exports observability-plane counters that live outside the
    snapshot (``EventBus.dropped``, harvest sink failures, span
    drops) as ``counter`` series — a saturated bounded bus is
    invisible to a scraper otherwise. ``extra_gauges`` does the same
    with ``gauge`` typing — the SLO engine's ``slo_burn_rate`` /
    ``slo_alert_state`` / ``slo_compliance`` series ride this path
    (:meth:`porqua_tpu.obs.slo.SLOEngine.gauges`).

    ``labeled_gauges`` renders label-carrying gauge series:
    ``{name: [(labels_dict, value), ...]}`` becomes one ``# TYPE``
    header plus ``<prefix>_<name>{k="v",...} value`` per entry — the
    executable cache's per-bucket compile-seconds / hit / peak-memory
    series (:meth:`porqua_tpu.serve.bucketing.ExecutableCache.
    prometheus_gauges`) ride this path.
    """
    # Imported lazily: serve imports obs, so a module-level import here
    # would be circular; at call time both modules are initialized.
    from porqua_tpu.serve.metrics import COUNTERS

    counters = set(COUNTERS)
    lines = []
    for key, value in snapshot.items():
        if key in _NON_METRIC_KEYS:
            continue
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, float)):
            continue
        name = _metric_name(prefix, key)
        kind = "counter" if key in counters else "gauge"
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {value}")
    for key, hist in (histograms or {}).items():
        lines.extend(_histogram_lines(_metric_name(prefix, key), hist))
    for kind, extras in (("counter", extra_counters),
                         ("gauge", extra_gauges)):
        for key, value in (extras or {}).items():
            if isinstance(value, bool):
                value = int(value)
            if not isinstance(value, (int, float)):
                continue
            name = _metric_name(prefix, key)
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {value}")
    for key, series in (labeled_gauges or {}).items():
        rendered = []
        for labels, value in series:
            if isinstance(value, bool):
                value = int(value)
            if not isinstance(value, (int, float)):
                continue
            lbl = ",".join(f'{k}="{_escape_label(v)}"'
                           for k, v in sorted(labels.items()))
            rendered.append((lbl, value))
        if not rendered:
            continue
        name = _metric_name(prefix, key)
        lines.append(f"# TYPE {name} gauge")
        for lbl, value in rendered:
            lines.append(f"{name}{{{lbl}}} {value}")
    device = snapshot.get("device")
    if device:
        name = _metric_name(prefix, "device_info")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f'{name}{{device="{_escape_label(device)}"}} 1')
    return "\n".join(lines) + "\n"


class ObsHTTPServer:
    """``/metrics`` + ``/healthz`` on a daemon thread; stdlib only.

    ``metrics_fn`` returns the exposition text; ``health_fn`` returns a
    JSON-able dict (must carry ``ok``: a falsy ``ok`` answers 503 so
    load balancers can eject a degraded-and-drowning instance while
    scrapers keep reading ``/metrics``).
    """

    def __init__(self, metrics_fn: Callable[[], str],
                 health_fn: Callable[[], Dict[str, Any]],
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self._metrics_fn = metrics_fn
        self._health_fn = health_fn
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 - stdlib API
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = outer._metrics_fn().encode()
                        self._reply(200, body,
                                    "text/plain; version=0.0.4")
                    elif self.path.split("?")[0] == "/healthz":
                        health = outer._health_fn()
                        body = json.dumps(health).encode()
                        code = 200 if health.get("ok", True) else 503
                        self._reply(code, body, "application/json")
                    else:
                        self._reply(404, b"not found\n", "text/plain")
                except Exception as exc:  # noqa: BLE001 - never kill the server
                    self._reply(500, f"{exc!r}\n".encode(), "text/plain")

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass  # scrapes must not spam the serving process's stderr

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> int:
        """Begin serving; returns the bound port (useful with port=0)."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="porqua-obs-http", daemon=True)
            self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
            self._server.server_close()
            self._thread = None
