"""Device-truth profiling: XLA cost/memory warehouse + measured roofline.

Every MFU / bandwidth figure the stack reported before this module was
an *analytic estimate* — ``profiling.admm_flop_model`` multiplied by a
wall-clock. The compiler knows better: every AOT executable carries
``compiled.cost_analysis()`` (XLA-counted flops and bytes accessed)
and ``compiled.memory_analysis()`` (argument / output / temp / peak
buffer bytes). This module harvests that device truth once per compile
and makes it a first-class artifact:

* :func:`cost_record` — ONE schema (``COST_SCHEMA_VERSION``) for what
  XLA says one compiled executable costs: flops, bytes accessed,
  argument/output/temp/peak memory, generated-code size, compile
  seconds, and an HLO-module fingerprint — keyed by (kind, entry,
  bucket, slots, dtype, device). Harvesting is version-tolerant and
  NEVER raises: a backend that refuses an analysis yields ``None``
  fields, not a failed compile.
* :class:`CostLog` — the append-only JSONL(.gz) CostRecord warehouse,
  mirror of :class:`~porqua_tpu.obs.harvest.HarvestSink` (thread-safe,
  ``emit`` never raises, dead disks degrade to counters). The serve
  stack's :class:`~porqua_tpu.serve.bucketing.ExecutableCache` emits
  one record per executable it compiles.
* :func:`roofline_verdict` — the reader half: join CostRecords with
  measured stage seconds, rank executables by *measured* bytes, and
  emit the top fusion candidates as a machine-readable verdict — the
  evidence artifact the ROADMAP fusion item consumes
  (``scripts/roofline_report.py`` is the CLI).
* :class:`ProfileWindow` — a bounded programmatic ``jax.profiler``
  trace (started mid-steady-state, stopped by a timer), the
  ``--profile-window`` knob on ``serve_loadgen.py`` / ``bench.py``.

Everything here is host post-processing of objects the compile path
already produced: contract GC107 (:func:`porqua_tpu.analysis.
contracts.check_devprof_identity`) machine-checks that a live cost
plane — records harvested, log emitting, measured profile computed —
changes no traced program, and the disabled mode is pinned
bit-identical by ``tests/test_devprof.py``.
"""

from __future__ import annotations

import gzip
import hashlib
import json
import re
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

from porqua_tpu.analysis import tsan

__all__ = [
    "COST_SCHEMA_VERSION",
    "CostLog",
    "ProfileWindow",
    "cost_record",
    "executable_cost",
    "executable_memory",
    "hlo_fingerprint",
    "load_cost_records",
    "measured_rates",
    "roofline_verdict",
    "write_cost_records",
]

#: Bump when a field changes meaning; additive fields don't need it.
COST_SCHEMA_VERSION = 1


def executable_cost(compiled) -> Dict[str, Optional[float]]:
    """XLA-counted flops / bytes of one compiled executable.

    ``cost_analysis()`` returns a dict on current jax and a one-dict
    list on older versions; either way the totals live under
    ``"flops"`` and ``"bytes accessed"``. Returns ``None`` values when
    the backend refuses the analysis (some plugin backends do) — the
    caller records the refusal instead of failing the compile.
    """
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = ca.get("flops")
        bytes_accessed = ca.get("bytes accessed")
        return {
            "flops": None if flops is None else float(flops),
            "bytes_accessed": (None if bytes_accessed is None
                               else float(bytes_accessed)),
        }
    except Exception:  # noqa: BLE001 - analysis must never fail a compile
        return {"flops": None, "bytes_accessed": None}


def executable_memory(compiled) -> Dict[str, Optional[float]]:
    """``memory_analysis()`` flattened: argument / output / temp /
    alias / generated-code bytes plus the derived ``peak_bytes``
    (argument + output + temp − alias; the backend's own
    ``peak_memory_in_bytes`` is preferred where the jaxlib exposes
    it). ``None`` values when the backend refuses."""
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return {"peak_bytes": None}
        get = (ma.get if isinstance(ma, dict)
               else lambda k, d=None: getattr(ma, k, d))
        out: Dict[str, Optional[float]] = {}
        for field in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "alias_size_in_bytes",
                      "generated_code_size_in_bytes"):
            v = get(field)
            short = field.replace("_size_in_bytes", "_bytes")
            out[short] = None if v is None else float(v)
        peak = get("peak_memory_in_bytes")
        if peak is None:
            parts = [out.get("argument_bytes"), out.get("output_bytes"),
                     out.get("temp_bytes")]
            if any(p is not None for p in parts):
                peak = (sum(p or 0.0 for p in parts)
                        - (out.get("alias_bytes") or 0.0))
        out["peak_bytes"] = None if peak is None else float(peak)
        return out
    except Exception:  # noqa: BLE001 - analysis must never fail a compile
        return {"peak_bytes": None}


#: Non-semantic HLO decoration stripped before fingerprinting:
#: ``metadata={op_name=... source_file=... source_line=N}`` clauses
#: change with source position (two compiles of the same program from
#: different call sites would otherwise hash differently).
_HLO_METADATA_RE = re.compile(r", metadata=\{[^{}]*\}")


def hlo_fingerprint(compiled) -> Optional[str]:
    """A short blake2b digest of the optimized HLO module text — the
    identity that says whether two rounds compiled the *same program*
    (a cost drift with an unchanged fingerprint is an XLA/runtime
    change; with a changed one, a program change). Source-location
    metadata is stripped first: it is call-site decoration, not
    program."""
    try:
        text = compiled.as_text()
        if not text:
            return None
        text = _HLO_METADATA_RE.sub("", text)
        return hashlib.blake2b(text.encode(), digest_size=8).hexdigest()
    except Exception:  # noqa: BLE001 - fingerprinting is best-effort
        return None


def cost_record(compiled,
                entry: str,
                kind: str,
                bucket: Optional[str] = None,
                slots: Optional[int] = None,
                dtype: Optional[str] = None,
                device: Optional[str] = None,
                compile_s: Optional[float] = None,
                **extra) -> Dict[str, Any]:
    """Build one CostRecord dict from a compiled executable (the
    schema's single constructor — every harvester goes through here so
    fields cannot drift apart). Never raises: analysis refusals land
    as ``None`` fields."""
    rec: Dict[str, Any] = {
        "v": COST_SCHEMA_VERSION,
        "t": time.time(),
        "kind": str(kind),
        "entry": str(entry),
    }
    if bucket is not None:
        rec["bucket"] = str(bucket)
    if slots is not None:
        rec["slots"] = int(slots)
    if dtype is not None:
        rec["dtype"] = str(dtype)
    if device is not None:
        rec["device"] = str(device)
    if compile_s is not None:
        rec["compile_s"] = float(compile_s)
    rec.update(executable_cost(compiled))
    rec.update(executable_memory(compiled))
    rec["hlo_hash"] = hlo_fingerprint(compiled)
    rec.update(extra)
    return rec


class CostLog:
    """Thread-safe append-only CostRecord warehouse (JSONL, ``.gz``
    transparently gzipped; ``path=None`` keeps a bounded in-memory
    buffer). ``emit`` never raises — it runs on the compile path, and
    a dead disk degrades to ``write_failures``, not failed compiles.
    Same posture as :class:`~porqua_tpu.obs.harvest.HarvestSink`,
    kept separate because cost records are per-*compile* (a handful
    per process), not per-solve. ``append=False`` truncates an
    existing file (one-shot exports; the default appends, the
    long-lived-warehouse contract)."""

    def __init__(self, path: Optional[str] = None,
                 buffer_capacity: int = 4096,
                 append: bool = True) -> None:
        self.path = path
        self._lock = tsan.lock("CostLog")
        self._records = 0                 # guarded-by: self._lock
        self._write_failures = 0          # guarded-by: self._lock
        self._buffer_capacity = int(buffer_capacity)
        self._buffer: List[Dict[str, Any]] = []  # guarded-by: self._lock
        self._sink = None                 # guarded-by: self._lock
        if path is not None:
            mode = "at" if append else "wt"
            try:
                self._sink = (gzip.open(path, mode)
                              if str(path).endswith(".gz")
                              else open(path, mode[0]))
            except OSError:
                self._write_failures += 1

    def emit(self, record: Dict[str, Any]) -> None:
        """Append one record; never raises (see class docstring)."""
        line = (json.dumps(record, default=str)
                if self._sink is not None else None)
        with self._lock:
            self._records += 1
            if self._sink is not None and line is not None:
                try:
                    self._sink.write(line + "\n")
                except (OSError, ValueError):
                    self._write_failures += 1
                    self._sink = None  # dead sink: keep compiling
            elif len(self._buffer) < self._buffer_capacity:
                self._buffer.append(record)

    # -- readers -----------------------------------------------------

    @property
    def records(self) -> int:
        with self._lock:
            return self._records

    @property
    def write_failures(self) -> int:
        with self._lock:
            return self._write_failures

    def buffered(self) -> List[Dict[str, Any]]:
        """In-memory records (``path=None`` logs only)."""
        with self._lock:
            return list(self._buffer)

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {"cost_records": self._records,
                    "cost_write_failures": self._write_failures}

    def flush(self) -> None:
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.flush()
                except OSError:
                    self._write_failures += 1
                    self._sink = None

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    self._write_failures += 1
                self._sink = None

    def __enter__(self) -> "CostLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def load_cost_records(path: str) -> List[Dict[str, Any]]:
    """Read a CostRecord dataset back (JSONL, ``.gz`` transparently)."""
    opener = gzip.open if str(path).endswith(".gz") else open
    out: List[Dict[str, Any]] = []
    with opener(path, "rt") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def write_cost_records(path: str,
                       records: Iterable[Dict[str, Any]]) -> int:
    """Dump an iterable of CostRecords as JSONL(.gz); returns the
    count. A one-shot export (``run_loadgen(cost_out=...)``), so the
    file is TRUNCATED: re-running a loadgen with the same ``--cost-out``
    must describe that run, not accumulate stale executables from the
    last one into the roofline verdict."""
    n = 0
    with CostLog(path, append=False) as log:
        for rec in records:
            log.emit(rec)
            n += 1
    return n


def measured_rates(record: Dict[str, Any],
                   seconds: Optional[float] = None,
                   model_flops: Optional[float] = None,
                   model_bytes: Optional[float] = None
                   ) -> Dict[str, float]:
    """Achieved rates + model-drift ratios for one CostRecord — the
    ONE home of the measured-roofline arithmetic, shared by
    ``bench.py``'s ``xla_cost`` block and
    :func:`porqua_tpu.obs.profile.qp_solve_profile` so the two cannot
    drift apart. ``seconds`` (measured wall of the program) enables
    ``achieved_tflops``/``achieved_hbm_gbps``; ``model_flops``/
    ``model_bytes`` (the analytic figures) enable the
    ``*_model_ratio`` drift metrics. Fields appear only when both
    sides of their division exist."""
    out: Dict[str, float] = {}
    flops = record.get("flops")
    bytes_acc = record.get("bytes_accessed")
    if seconds and seconds > 0:
        if flops:
            out["achieved_tflops"] = flops / seconds / 1e12
        if bytes_acc:
            out["achieved_hbm_gbps"] = bytes_acc / seconds / 1e9
    if flops and model_flops is not None:
        out["flops_model_ratio"] = float(model_flops) / flops
    if bytes_acc and model_bytes is not None:
        out["bytes_model_ratio"] = float(model_bytes) / bytes_acc
    return out


# ---------------------------------------------------------------------------
# bounded programmatic profiler window
# ---------------------------------------------------------------------------

class ProfileWindow:
    """A bounded ``jax.profiler`` trace: :meth:`start` opens the trace
    and arms a daemon timer that stops it after ``window_s`` seconds;
    :meth:`stop` is idempotent (the run's teardown calls it
    unconditionally — whichever of the timer and the teardown fires
    second is a no-op). Failures never propagate: profiling a run must
    not fail it (``error`` carries the first failure for the report).
    """

    def __init__(self, logdir: str, window_s: Optional[float] = None) -> None:
        self.logdir = str(logdir)
        self.window_s = None if window_s is None else float(window_s)
        self._lock = tsan.lock("ProfileWindow")
        self._state = "idle"              # guarded-by: self._lock
        self._timer: Optional[threading.Timer] = None  # guarded-by: self._lock
        self._error: Optional[str] = None  # guarded-by: self._lock

    def _note_error(self, exc: BaseException) -> None:
        with self._lock:
            if self._error is None:
                self._error = f"{type(exc).__name__}: {exc}"

    def start(self) -> bool:
        with self._lock:
            if self._state != "idle":
                return False
            self._state = "tracing"
        try:
            import jax

            jax.profiler.start_trace(self.logdir)
        except Exception as exc:  # noqa: BLE001 - best-effort capture
            self._note_error(exc)
            with self._lock:
                self._state = "failed"
            return False
        if self.window_s is not None:
            t = threading.Timer(self.window_s, self.stop)
            t.daemon = True
            with self._lock:
                self._timer = t
            t.start()
        return True

    def stop(self) -> bool:
        with self._lock:
            if self._state != "tracing":
                return False
            self._state = "stopped"
            timer, self._timer = self._timer, None
        if timer is not None:
            timer.cancel()
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001 - best-effort capture
            self._note_error(exc)
            return False
        return True

    @property
    def error(self) -> Optional[str]:
        with self._lock:
            return self._error

    @property
    def state(self) -> str:
        with self._lock:
            return self._state


# ---------------------------------------------------------------------------
# the roofline verdict (scripts/roofline_report.py renders it)
# ---------------------------------------------------------------------------

#: CostRecord ``entry`` -> the StageProfiler stage(s) whose measured
#: seconds describe dispatches of that executable (the join key between
#: the cost warehouse and a loadgen/bench run's ``profile_stages``).
ENTRY_STAGES = {
    "solve": ("serve/solve_batch",),
    "admit": ("serve/admit",),
    "step": ("serve/segment_step", "segment_step"),
    "finalize": ("serve/finalize", "finalize"),
    "init": ("init",),
    "tracking_step": ("solve",),
}


def _identity(rec: Dict[str, Any]) -> tuple:
    return (rec.get("kind"), rec.get("entry"), rec.get("bucket"),
            rec.get("slots"), rec.get("dtype"), rec.get("device"))


def roofline_verdict(records: Iterable[Dict[str, Any]],
                     stage_seconds: Optional[Dict[str, float]] = None,
                     top: int = 5,
                     device_kind: str = "") -> Dict[str, Any]:
    """Rank executables by XLA-measured bytes and emit fusion targets.

    ``records`` is a CostRecord stream (append-only: the LATEST record
    per (kind, entry, bucket, slots, dtype, device) identity wins);
    ``stage_seconds`` is a run's measured per-stage host seconds
    (loadgen/bench ``profile_stages``), joined per entry through
    :data:`ENTRY_STAGES`. Each ranked row carries arithmetic intensity
    (flops per byte accessed); with a known ``device_kind`` the row is
    classified against the chip's ridge point (peak flops / peak
    bandwidth — below it the executable cannot be compute-bound no
    matter how well it schedules), otherwise intensity alone is
    reported. The verdict's ``fusion_candidates`` are the ``top``
    rows by measured bytes — the executables where fusing away
    intermediate traffic buys the most — which is exactly the
    machine-readable input the ROADMAP's Pallas-fusion item consumes.
    """
    from porqua_tpu.profiling import device_peaks

    latest: Dict[tuple, Dict[str, Any]] = {}
    total_in = 0
    for rec in records:
        total_in += 1
        latest[_identity(rec)] = rec

    peak_flops, peak_bw = device_peaks(device_kind)
    ridge = (peak_flops / peak_bw) if peak_flops and peak_bw else None

    rows: List[Dict[str, Any]] = []
    for rec in latest.values():
        flops = rec.get("flops")
        bytes_acc = rec.get("bytes_accessed")
        row: Dict[str, Any] = {
            "kind": rec.get("kind"),
            "entry": rec.get("entry"),
            "bucket": rec.get("bucket"),
            "slots": rec.get("slots"),
            "device": rec.get("device"),
            "flops": flops,
            "bytes_accessed": bytes_acc,
            "peak_bytes": rec.get("peak_bytes"),
            "hlo_hash": rec.get("hlo_hash"),
        }
        if flops and bytes_acc:
            row["arithmetic_intensity"] = flops / bytes_acc
            if ridge is not None:
                row["bound"] = ("memory"
                                if row["arithmetic_intensity"] < ridge
                                else "compute")
        stages = {}
        for stage in ENTRY_STAGES.get(str(rec.get("entry")), ()):
            if stage_seconds and stage in stage_seconds:
                stages[stage] = float(stage_seconds[stage])
        if stages:
            row["stage_seconds"] = stages
            secs = sum(stages.values())
            if bytes_acc and secs > 0:
                # A floor, not a rate: one dispatch's bytes over the
                # stage's TOTAL seconds (the stage covers every
                # dispatch of the entry; without per-entry dispatch
                # counts the honest derived figure is "at least").
                row["min_achieved_gbps"] = bytes_acc / secs / 1e9
        rows.append(row)

    rows.sort(key=lambda r: (r.get("bytes_accessed") or 0.0),
              reverse=True)
    for i, row in enumerate(rows):
        row["rank"] = i + 1

    candidates = [r for r in rows if r.get("bytes_accessed")]
    if ridge is not None:
        mem_bound = [r for r in candidates if r.get("bound") == "memory"]
        if mem_bound:
            candidates = mem_bound
    candidates = candidates[:max(int(top), 0)]

    stages_ranked = []
    if stage_seconds:
        stages_ranked = sorted(
            ({"stage": k, "seconds": float(v)}
             for k, v in stage_seconds.items()),
            key=lambda s: s["seconds"], reverse=True)

    verdict: Dict[str, Any] = {
        "v": COST_SCHEMA_VERSION,
        "t": time.time(),
        "records_in": total_in,
        "executables": len(rows),
        "device_kind": device_kind or None,
        "ridge_flops_per_byte": ridge,
        "ranked": rows,
        "stages_ranked": stages_ranked,
        "fusion_candidates": [
            {"kind": r.get("kind"), "entry": r.get("entry"),
             "bucket": r.get("bucket"), "slots": r.get("slots"),
             "bytes_accessed": r.get("bytes_accessed"),
             "arithmetic_intensity": r.get("arithmetic_intensity"),
             "bound": r.get("bound"),
             "reason": ("largest measured byte traffic"
                        + ("" if r.get("bound") != "memory"
                           else " and memory-bound at this chip's "
                                "ridge point"))}
            for r in candidates],
    }
    verdict["verdict"] = (
        "no executables with measured bytes — harvest CostRecords first"
        if not candidates else
        f"top fusion target: {candidates[0].get('entry')} "
        f"{candidates[0].get('bucket')} x{candidates[0].get('slots')} "
        f"({(candidates[0].get('bytes_accessed') or 0) / 1e6:.1f} MB "
        f"accessed per dispatch)")
    return verdict
