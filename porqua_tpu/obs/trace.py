"""Request span tracing with a Chrome-trace-event (Perfetto) exporter.

A *span* is one named interval of one request's life — ``submit``
(pad + enqueue), ``queue_wait``, ``assemble`` (batch formation),
``solve`` (device dispatch), ``resolve`` (future fan-out) — stamped
with the request's ``trace_id`` so a p99 outlier can be read as "this
request spent 48 ms waiting for its bucket's age trigger", not just
"p99 is 50 ms". The serve stack records spans through one shared
:class:`SpanRecorder`; nothing here touches JAX or the device — span
timestamps come from ``time.monotonic()`` on whatever host thread
observed the transition, which is exactly the layer the on-device
profiler (``jax.profiler`` / :func:`porqua_tpu.profiling.device_trace`)
cannot see.

The export format is the Chrome trace-event JSON (``"X"`` complete
events with microsecond ``ts``/``dur``), which Perfetto and
``chrome://tracing`` load directly — so a serving timeline renders in
the same UI, and on the same time axis style, as an XLA device trace.
Span schema: README "Observability".
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import time
from typing import Any, Dict, List, Optional

from porqua_tpu.analysis import tsan


@dataclasses.dataclass(frozen=True)
class Span:
    """One closed interval on the serving timeline.

    ``t_start``/``t_end`` are ``time.monotonic()`` seconds — the same
    clock the serve stack stamps ``SolveRequest.submitted`` with, so
    spans and request latencies subtract cleanly.
    """

    name: str
    t_start: float
    t_end: float
    trace_id: Optional[str] = None
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def duration(self) -> float:
        return self.t_end - self.t_start


class SpanRecorder:
    """Thread-safe bounded span sink shared by the whole serve stack.

    Bounded on purpose: a long-lived serving process must not grow its
    trace buffer without limit — past ``capacity`` the recorder drops
    new spans and counts them (``dropped``), the same posture as the
    metrics latency reservoir. Trace ids are minted here
    (:meth:`new_trace`) so they are unique per recorder without any
    global state.
    """

    def __init__(self, capacity: int = 262144) -> None:
        self.capacity = int(capacity)
        self._lock = tsan.lock("SpanRecorder")
        self._spans: List[Span] = []      # guarded-by: self._lock
        self._dropped = 0                 # guarded-by: self._lock
        self._ids = itertools.count()
        # Anchor pair: monotonic spans export against a wall-clock
        # epoch so two artifacts from one run line up.
        self._anchor_mono = time.monotonic()
        self._anchor_wall = time.time()

    def new_trace(self) -> str:
        """Mint a per-request trace id (unique within this recorder)."""
        return f"{os.getpid():x}-{next(self._ids):08x}"

    # -- recording ---------------------------------------------------

    def record(self, name: str, t_start: float, t_end: float,
               trace_id: Optional[str] = None, **args) -> None:
        span = Span(name, float(t_start), float(t_end), trace_id,
                    dict(args))
        with self._lock:
            if len(self._spans) >= self.capacity:
                self._dropped += 1
                return
            self._spans.append(span)

    def span(self, name: str, trace_id: Optional[str] = None, **args):
        """Context manager: time the block as one span."""
        return _SpanCtx(self, name, trace_id, args)

    # -- readers -----------------------------------------------------

    @property
    def anchor_mono(self) -> float:
        """The monotonic instant ``ts=0`` of this recorder's Chrome
        export maps to — other producers (the stage profiler's counter
        tracks) export against it so one trace file lines up."""
        return self._anchor_mono

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def by_trace(self) -> Dict[str, List[Span]]:
        """Spans grouped per trace id (anonymous spans excluded),
        chronological within each trace."""
        out: Dict[str, List[Span]] = {}
        for s in self.spans():
            if s.trace_id is not None:
                out.setdefault(s.trace_id, []).append(s)
        for spans in out.values():
            spans.sort(key=lambda s: s.t_start)
        return out

    # -- export ------------------------------------------------------

    def chrome_trace(self) -> Dict[str, Any]:
        """The Chrome trace-event JSON object (Perfetto-loadable).

        Each span becomes one ``"X"`` (complete) event; each trace id
        gets its own ``tid`` so Perfetto renders one lane per request.
        ``ts`` is microseconds since the recorder's anchor; the anchor's
        wall-clock epoch rides in ``metadata`` so device traces captured
        in the same run can be aligned by hand.
        """
        tids: Dict[Optional[str], int] = {None: 0}
        events: List[Dict[str, Any]] = []
        for s in self.spans():
            tid = tids.setdefault(s.trace_id, len(tids))
            args = dict(s.args)
            if s.trace_id is not None:
                args["trace_id"] = s.trace_id
            events.append({
                "name": s.name,
                "cat": "serve",
                "ph": "X",
                "ts": (s.t_start - self._anchor_mono) * 1e6,
                "dur": s.duration * 1e6,
                "pid": os.getpid(),
                "tid": tid,
                "args": args,
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "anchor_unix_time": self._anchor_wall,
                "dropped_spans": self.dropped,
            },
        }

    def write(self, path: str) -> Dict[str, Any]:
        """Write the Chrome trace JSON to ``path``; returns the object."""
        trace = self.chrome_trace()
        with open(path, "w") as f:
            json.dump(trace, f)
        return trace


class _SpanCtx:
    """Context manager returned by :meth:`SpanRecorder.span`."""

    def __init__(self, recorder: SpanRecorder, name: str,
                 trace_id: Optional[str], args: Dict[str, Any]) -> None:
        self._recorder = recorder
        self._name = name
        self._trace_id = trace_id
        self._args = args
        self._t0 = 0.0

    def __enter__(self) -> "_SpanCtx":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._recorder.record(self._name, self._t0, time.monotonic(),
                              trace_id=self._trace_id, **self._args)
