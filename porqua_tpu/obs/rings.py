"""Host-side unrolling of the on-device convergence rings.

With ``SolverParams(ring_size=K)`` the ADMM segment loop records
``(prim_res, dual_res, rho)`` into a K-slot circular buffer at every
residual check — *inside* the jitted program, zero host syncs (the
rings are just three more ``Solution`` output leaves). Slot layout:
segment ``j`` (0-based) writes slot ``j % K``, so once the solve runs
more than K segments the ring holds the **last K** checks. This module
is the host-side decoder: given the rings plus the device-reported
iteration count it reconstructs the chronological residual trajectory
and the iteration number of each sample.

First-order QP methods are diagnosed by exactly these trajectories
(restart behavior, rho adaptation, stall-vs-converge) — see PDQP
(arXiv:2311.07710) and GPU-ADMM (arXiv:1912.04263); the rings make
them observable without re-running the solve with host polling.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def ring_history(ring_prim, ring_dual, ring_rho, iters: int,
                 check_interval: int) -> Dict[str, Any]:
    """Decode one problem's rings into a chronological trajectory.

    Returns ``{"iters": [...], "prim_res": [...], "dual_res": [...],
    "rho": [...]}`` where ``iters[j]`` is the iteration count at which
    sample ``j`` was taken (the end of its segment). When the solve ran
    more than ``ring_size`` segments, the earliest samples have been
    overwritten and the arrays cover only the trailing window.
    """
    prim = np.asarray(ring_prim)
    dual = np.asarray(ring_dual)
    rho = np.asarray(ring_rho)
    ring_size = int(prim.shape[-1])
    segments = int(iters) // int(check_interval)
    k = min(segments, ring_size)
    start = segments - k  # first surviving segment index
    idx = [(start + j) % ring_size for j in range(k)]
    return {
        "iters": [(start + j + 1) * int(check_interval) for j in range(k)],
        "prim_res": [float(prim[i]) for i in idx],
        "dual_res": [float(dual[i]) for i in idx],
        "rho": [float(rho[i]) for i in idx],
    }


def solution_ring_history(solution, check_interval: int,
                          index: Optional[int] = None) -> Optional[Dict]:
    """Decode the rings off a :class:`porqua_tpu.qp.solve.QPSolution`
    (or a serve :class:`SolveResult`). ``index`` selects one problem of
    a batched solution; ``None`` for an unbatched one. Returns ``None``
    when the solve ran without rings (``ring_size=0``)."""
    rp = getattr(solution, "ring_prim", None)
    if rp is None:
        return None
    rd, rr = solution.ring_dual, solution.ring_rho
    iters = solution.iters
    if index is not None:
        rp, rd, rr = rp[index], rd[index], rr[index]
        iters = np.asarray(iters)[index]
    return ring_history(rp, rd, rr, int(np.asarray(iters)),
                        check_interval)
