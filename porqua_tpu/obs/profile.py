"""Per-solve stage profiling: timing hooks + roofline estimates.

The round-5 finding that the whole serving plane runs memory-bound at
MFU < 3% came from one hand-run roofline; this module makes the same
accounting continuous. Two halves:

* :class:`StageProfiler` — host-side stage accounting around the
  solver's device dispatches (``init`` / ``segment_step`` / ``repack``
  / ``finalize`` in the compacting driver, ``admit`` / ``segment_step``
  / ``finalize`` in the continuous batcher, ``solve_batch`` in the
  classic one). Each bracketed region also enters a
  ``jax.profiler.TraceAnnotation``, so an XLA device trace captured in
  the same run (:func:`porqua_tpu.profiling.device_trace`) carries
  matching ``porqua/<stage>`` annotations, and
  :func:`chrome_counter_events` exports the accumulated stage seconds
  as Chrome-trace **counter tracks** that render alongside the request
  spans of :mod:`porqua_tpu.obs.trace` (same anchor, same file).
  Stage seconds are honest only up to dispatch asynchrony: the
  bracketed drivers sync at every segment boundary (the compaction
  active-count readout / the continuous status fetch), so in practice
  the brackets cover dispatch + completion.

* :func:`qp_solve_profile` — the per-solve MFU / HBM-bandwidth
  estimate: the analytic cost of the dispatched program from
  :func:`porqua_tpu.profiling.admm_flop_model` (``window=0`` drops
  the Gram/TE stages a pure QP solve never runs; a factored objective
  passes its row count as the window, which is exactly T for tracking
  problems) against measured seconds and the chip's public peaks.
  Exported into SolveRecords (``profile`` field) by the harvest
  producers.

Everything here is host code around already-dispatched programs — the
GC105 contract (:func:`porqua_tpu.analysis.contracts.
check_telemetry_identity`) pins that a live profiler changes no traced
program.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, List, Optional, Tuple

from porqua_tpu.analysis import tsan

__all__ = [
    "StageProfiler",
    "annotate",
    "chrome_counter_events",
    "profiled_stage",
    "qp_solve_profile",
]


@contextlib.contextmanager
def annotate(name: str):
    """``jax.profiler.TraceAnnotation`` when jax is importable (a no-op
    unless a profiler trace is actually being captured), nullcontext
    otherwise — so pure-host consumers (tests, report tooling) can use
    the same brackets without initializing a backend."""
    try:
        import jax

        cm = jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - jax-version dependent
        cm = contextlib.nullcontext()
    with cm:
        yield


@contextlib.contextmanager
def profiled_stage(profiler, name: str, annotation: str):
    """The ONE dispatch bracket every driver uses: enter the
    ``porqua/<annotation>`` jax-profiler annotation, time the block,
    feed ``profiler`` (a :class:`StageProfiler`, or ``None`` for
    annotation-only), and expose the elapsed seconds to the caller —
    ``with profiled_stage(p, "serve/solve_batch", "solve_batch") as h:
    ...; solve_s = h["seconds"]``. Centralized so the stage name, the
    annotation, the clock, and the observe call cannot drift apart
    across the compaction / classic / continuous drivers."""
    holder = {"seconds": 0.0}
    t0 = time.monotonic()
    with annotate(f"porqua/{annotation}"):
        try:
            yield holder
        except BaseException:
            # A raising dispatch (device fault, sanitizer trip) still
            # reports its elapsed time to the caller but is NOT a
            # stage sample — failed dispatches would skew the
            # per-stage device-seconds the counter tracks render.
            holder["seconds"] = time.monotonic() - t0
            raise
        t1 = time.monotonic()
        holder["seconds"] = t1 - t0
        if profiler is not None:
            profiler.observe(name, holder["seconds"], t_end=t1)


class StageProfiler:
    """Thread-safe per-stage seconds/counts accumulator.

    One instance is shared by a serve stack or a driver; stages are
    cheap (one monotonic pair + a lock-bounded add), and the sample
    log (for counter tracks) is bounded like every other obs buffer.
    """

    def __init__(self, sample_capacity: int = 65536) -> None:
        self._lock = tsan.lock("StageProfiler")
        # guarded-by: self._lock
        self._stages: Dict[str, Dict[str, float]] = {}
        # (t_mono_end, stage, cumulative_seconds); guarded-by: self._lock
        self._samples: List[Tuple[float, str, float]] = []
        self._sample_capacity = int(sample_capacity)
        self._samples_dropped = 0          # guarded-by: self._lock

    def observe(self, name: str, seconds: float,
                t_end: Optional[float] = None) -> None:
        with self._lock:
            slot = self._stages.setdefault(
                name, {"seconds": 0.0, "count": 0.0})
            slot["seconds"] += float(seconds)
            slot["count"] += 1.0
            if len(self._samples) < self._sample_capacity:
                self._samples.append(
                    (time.monotonic() if t_end is None else float(t_end),
                     name, slot["seconds"]))
            else:
                self._samples_dropped += 1

    @contextlib.contextmanager
    def stage(self, name: str):
        """Bracket one device dispatch: times the block and enters the
        matching ``porqua/<name>`` jax profiler annotation."""
        t0 = time.monotonic()
        with annotate(f"porqua/{name}"):
            try:
                yield
            finally:
                t1 = time.monotonic()
                self.observe(name, t1 - t0, t_end=t1)

    # -- readers -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "stages": {k: dict(v) for k, v in self._stages.items()},
                "samples": len(self._samples),
                "samples_dropped": self._samples_dropped,
            }

    def stage_seconds(self) -> Dict[str, float]:
        with self._lock:
            return {k: v["seconds"] for k, v in self._stages.items()}

    def samples(self) -> List[Tuple[float, str, float]]:
        with self._lock:
            return list(self._samples)


def chrome_counter_events(profiler: StageProfiler,
                          anchor_mono: float,
                          pid: Optional[int] = None) -> List[Dict]:
    """Export the profiler's sample log as Chrome-trace ``"C"``
    (counter) events on the SAME time anchor as a
    :class:`~porqua_tpu.obs.trace.SpanRecorder` export — append them
    to that recorder's ``traceEvents`` and Perfetto renders cumulative
    per-stage device-seconds tracks under the request spans."""
    import os

    pid = os.getpid() if pid is None else pid
    return [{
        "name": f"porqua/profile/{name}",
        "cat": "profile",
        "ph": "C",
        "ts": (t - anchor_mono) * 1e6,
        "pid": pid,
        "args": {"seconds": round(cum, 6)},
    } for t, name, cum in profiler.samples()]


def qp_solve_profile(n: int, m: int, iters: float, seconds: float,
                     params=None,
                     batch: int = 1,
                     factor_rows: Optional[int] = None,
                     window: Optional[int] = None,
                     device_kind: str = "",
                     stage_seconds: Optional[Dict[str, float]] = None,
                     cost: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """FLOPs/bytes of the dispatched batch + achieved rates.

    ``seconds`` is the measured wall of the WHOLE ``batch``-lane
    dispatch; the model multiplies per-lane cost by ``batch``
    (``admm_flop_model(n_dates=batch)``), so achieved figures describe
    the dispatch, which every lane's record shares. ``window`` (or a
    factored objective's ``factor_rows``, which equals T for tracking
    problems) re-enables the Gram-assembly accounting; the default 0
    counts only what a pure QP solve runs. MFU fields appear only when
    the device kind maps to known peaks (TPUs) — on XLA-CPU the record
    carries the cost and achieved rates alone, which is exactly what a
    later chip window needs for comparison.

    ``cost`` is the dispatched executable's CostRecord
    (:func:`porqua_tpu.obs.devprof.cost_record`, looked up via
    :meth:`~porqua_tpu.serve.bucketing.ExecutableCache.
    cost_record_for`). When it carries XLA-measured flops/bytes, the
    MFU/bandwidth numerators switch to the compiler's own accounting
    (``cost_source: "xla"``; ``flops_xla``/``bytes_xla``/
    ``peak_bytes`` recorded) and the analytic figures stay side by
    side as ``model_flops``/``model_bytes`` with their
    ``flops_model_ratio``/``bytes_model_ratio`` — so drift between the
    hand model and the compiler is itself a tracked metric. Without
    ``cost``, the analytic model remains the numerator
    (``cost_source: "model"``) — the pre-device-truth behavior."""
    from porqua_tpu.profiling import admm_flop_model, roofline_report
    from porqua_tpu.qp.solve import SolverParams

    params = SolverParams() if params is None else params
    T = int(window if window is not None
            else (factor_rows if factor_rows is not None else 0))
    model = admm_flop_model(
        int(n), int(m), T, float(max(iters, 1.0)), int(batch),
        check_interval=params.check_interval,
        scaling_iters=params.scaling_iters,
        scaling_mode=params.scaling_mode,
        polish_passes=params.polish_passes if params.polish else 0,
        linsolve="trinv" if params.linsolve == "auto" else params.linsolve,
        woodbury_refine=params.woodbury_refine,
    )
    num_flops = model["flops_total"]
    num_bytes = model["bytes_total"]
    out: Dict[str, Any] = {
        "seconds": float(seconds),
        "batch": int(batch),
        "cost_source": "model",
    }
    xla_flops = None if cost is None else cost.get("flops")
    xla_bytes = None if cost is None else cost.get("bytes_accessed")
    if xla_flops or xla_bytes:
        # Device truth: the executable's own cost analysis becomes the
        # numerator; the analytic model rides along as the drift probe
        # (ratio formula shared with bench.py via measured_rates).
        from porqua_tpu.obs.devprof import measured_rates

        out["cost_source"] = "xla"
        out["model_flops"] = model["flops_total"]
        out["model_bytes"] = model["bytes_total"]
        out.update(measured_rates(cost,
                                  model_flops=model["flops_total"],
                                  model_bytes=model["bytes_total"]))
        if xla_flops:
            num_flops = float(xla_flops)
            out["flops_xla"] = num_flops
        if xla_bytes:
            num_bytes = float(xla_bytes)
            out["bytes_xla"] = num_bytes
        if cost.get("peak_bytes") is not None:
            out["peak_bytes"] = float(cost["peak_bytes"])
    out["flops_est"] = num_flops
    out["bytes_est"] = num_bytes
    if seconds > 0:
        roof = roofline_report(
            {"flops_total": num_flops, "bytes_total": num_bytes},
            float(seconds), device_kind)
        out["achieved_tflops"] = roof["achieved_tflops"]
        out["achieved_hbm_gbps"] = roof["achieved_hbm_gbps"]
        for key in ("mfu_bf16_peak", "mfu_f32_est", "hbm_utilization",
                    "roofline_bound"):
            if key in roof:
                out[key] = roof[key]
    if stage_seconds:
        out["stage_seconds"] = {k: round(v, 6)
                                for k, v in stage_seconds.items()}
    return out
