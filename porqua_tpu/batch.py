"""Fully-batched device backtest: the whole rebalance loop as one XLA program.

The reference iterates rebalance dates in a serial Python loop and hands
each date's QP to a CPU solver (reference ``src/backtest.py:203-222`` ->
``src/qp_problems.py:211``). Here the loop is *inverted*:

* **Pass 1 (host)** — run every selection / optimization item builder for
  every rebalance date (the same plug-in bibfn API as the serial engine),
  lower each date to unpadded canonical parts, find the maximum variable
  and row counts across dates, and pad everything to one static shape.
* **Pass 2 (device)** — stack the padded problems along a leading dates
  axis and solve them all in one jitted program: ``vmap`` of the ADMM
  solver when dates are independent, ``lax.scan`` with warm starts when a
  turnover constraint couples consecutive dates through x0 (reference
  ``optimization.py:126-137``).

The result converts back into the same ``Strategy``/``Portfolio`` objects
the serial engine produces, so downstream accounting and reporting is
identical.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from porqua_tpu.analysis import sanitize
from porqua_tpu.backtest import Backtest, BacktestService
from porqua_tpu.portfolio import Portfolio, Strategy
from porqua_tpu.qp.canonical import CanonicalQP, stack_qps
from porqua_tpu.qp.solve import (
    QPSolution,
    SolverParams,
    Status,
    solve_qp_batch,
    _solve_impl,
)


@dataclasses.dataclass
class BatchProblems:
    """Host-built, device-ready batch of per-date problems."""

    qp: CanonicalQP                 # stacked, leading axis = dates
    rebdates: List[str]
    universes: List[List[str]]      # per-date asset names (len <= n_assets_max)
    n_assets_max: int               # weights live in x[:n_assets_max]
    turnover_rows: Optional[slice] = None   # rows of C holding the x0 bounds
    constants: Optional[np.ndarray] = None
    l1_weight: Optional[jax.Array] = None   # (dates, n) native L1 term weights
    l1_center: Optional[jax.Array] = None   # (dates, n) native L1 term centers

    @property
    def n_dates(self) -> int:
        return len(self.rebdates)


def build_problems(bs: BacktestService,
                   rebdates: Optional[Sequence[str]] = None,
                   dtype=jnp.float32) -> BatchProblems:
    """Pass 1: run builders for every date, pad to one static shape.

    Mirrors the per-date orchestration of the serial engine
    (``BacktestService.prepare_rebalancing`` + ``set_objective`` +
    canonical lowering) but defers padding until all dates are known.
    """
    rebdates = list(bs.settings["rebdates"] if rebdates is None else rebdates)

    parts_list, universes = [], []
    for date in rebdates:
        bs.prepare_rebalancing(rebalancing_date=date)
        bs.optimization.set_objective(optimization_data=bs.optimization_data)
        parts = bs.optimization.canonical_parts()
        parts_list.append(parts)
        universes.append(list(bs.optimization.constraints.selection))

    n_max = max(len(p["q"]) for p in parts_list)
    m_max = max(p["C"].shape[0] for p in parts_list)
    n_assets_max = max(len(u) for u in universes)

    # Carry the objective factor (P == 2 Pf' Pf + diag(Pdiag)) into the
    # batch only when every date has one with the same row count —
    # stacking requires a single static factor shape. A mixed batch
    # (e.g. one date's problem lifted) falls back to dense P.
    use_pf = (all("Pf" in p for p in parts_list)
              and len({p["Pf"].shape[0] for p in parts_list}) == 1)
    qps = [
        CanonicalQP.build(
            p["P"], p["q"], C=p["C"], l=p["l"], u=p["u"],
            lb=p["lb"], ub=p["ub"], constant=p.get("constant", 0.0),
            n_max=n_max, m_max=m_max, dtype=dtype,
            Pf=p["Pf"] if use_pf else None,
            Pdiag=p.get("Pdiag") if use_pf else None,
        )
        for p in parts_list
    ]
    # build() itself may have degraded individual dates to the dense
    # path (rounding-grade factor drift, see CanonicalQP.build); a
    # mixed batch cannot stack (None is an empty pytree subtree), so
    # the whole batch falls back to dense together.
    if use_pf and any(qp.Pf is None for qp in qps):
        qps = [qp._replace(Pf=None, Pdiag=None) for qp in qps]
    l1_weight = l1_center = None
    if any("l1_weight" in p for p in parts_list):
        def pad_n(v):
            return np.pad(np.asarray(v, float), (0, n_max - len(v)))

        l1_weight = jnp.asarray(np.stack([
            pad_n(p["l1_weight"]) if "l1_weight" in p else np.zeros(n_max)
            for p in parts_list
        ]), dtype=dtype)
        l1_center = jnp.asarray(np.stack([
            pad_n(p["l1_center"]) if "l1_center" in p else np.zeros(n_max)
            for p in parts_list
        ]), dtype=dtype)

    return BatchProblems(
        qp=stack_qps(qps),
        rebdates=rebdates,
        universes=universes,
        n_assets_max=n_assets_max,
        constants=np.array([p.get("constant", 0.0) for p in parts_list]),
        l1_weight=l1_weight,
        l1_center=l1_center,
    )


def solve_batch(problems: BatchProblems,
                params: SolverParams = SolverParams(),
                harvest=None) -> QPSolution:
    """Pass 2, independent dates: one vmapped device solve.

    Under ``PORQUA_SANITIZE=1`` the dispatch runs inside
    ``jax.transfer_guard("disallow")``: the problems were placed on
    device by :func:`build_problems` (``stack_qps``), so any implicit
    host transfer the solve path picks up is a discipline bug and
    raises instead of silently round-tripping.

    ``harvest`` (a :class:`porqua_tpu.obs.HarvestSink`) appends one
    telemetry-warehouse SolveRecord per date AFTER the dispatch —
    pure host post-processing of the returned arrays (it forces
    completion, so the recorded wall seconds are honest); ``None``
    leaves the solve byte-for-byte untouched, including its async
    return.
    """
    import time as _time

    t0 = _time.perf_counter()
    with sanitize.transfer_guard():
        sol = solve_qp_batch(problems.qp, params,
                             l1_weight=problems.l1_weight,
                             l1_center=problems.l1_center)
    if harvest is not None:
        from porqua_tpu.obs.harvest import device_label_of, harvest_solution

        np.asarray(sol.status)  # force completion: honest wall seconds
        wall = _time.perf_counter() - t0
        harvest_solution(harvest, sol, params, "batch",
                         wall_s=wall, solve_s=wall,
                         device=device_label_of(sol))
    return sol


def solve_batch_compacted(problems: BatchProblems,
                          params: SolverParams = SolverParams(),
                          segment_budget: Optional[int] = None,
                          compact: bool = True,
                          driver=None,
                          harvest=None):
    """Pass 2 with segment-level batch compaction: wall-clock tracks
    total useful work instead of the slowest lane.

    The segment loop runs on the host over the steppable solver API
    (:mod:`porqua_tpu.compaction`): after every residual-check segment,
    still-``RUNNING`` lanes are repacked to the front on device and the
    dispatch width drops down the serving slot ladder, so converged
    dates stop paying for stragglers. Converged lanes' solutions are
    bit-identical to :func:`solve_batch`'s; a lane exceeding
    ``segment_budget`` segments retires as ``MAX_ITER`` with the polish
    fallback. Returns ``(QPSolution, CompactionReport)`` — the report
    carries the executed-vs-dense lane-segment accounting ``bench.py``
    pins the win with. Pass a shared ``driver``
    (:class:`porqua_tpu.compaction.CompactingDriver`) to reuse compiled
    executables across calls — its SolverParams must match ``params``
    (a mismatch raises rather than silently solving at the driver's
    tolerance); ``segment_budget`` is forwarded per call either way.
    Sanitizer semantics match :func:`solve_batch` (the driver runs its
    dispatch loop inside the transfer guard itself). ``harvest``
    appends one SolveRecord per date with the compaction accounting
    and stage profile attached (source ``batch.compacted``).
    """
    from porqua_tpu.compaction import solve_batch_compacted as _solve

    return _solve(problems.qp, params, segment_budget=segment_budget,
                  l1_weight=problems.l1_weight,
                  l1_center=problems.l1_center,
                  compact=compact, driver=driver, harvest=harvest)


# Sentinel for scan-coupled entry points: the caller attests that every
# date's problem was built over one identically-ordered asset universe
# (e.g. synthetic batches built by construction). Use the real per-date
# universe lists (``BatchProblems.universes``) whenever they exist.
FIXED_UNIVERSE = "attested-fixed"


def _require_fixed_universe(universes) -> None:
    """Both scan paths carry holdings positionally: variable j must mean
    the same asset on every date, or costs/bounds bind across unrelated
    assets. Raise when per-date universes differ — and raise on None:
    the check is non-optional (round-2 verdict: the footgun was
    reachable by the natural call). Pass :data:`FIXED_UNIVERSE` to
    attest a by-construction fixed universe."""
    if universes is None:
        raise ValueError(
            "scan-coupled solves carry holdings positionally, so they "
            "require the per-date asset universes to verify variable j "
            "means the same asset on every date. Pass "
            "universes=problems.universes (from BatchProblems), or "
            "porqua_tpu.batch.FIXED_UNIVERSE to attest the batch was "
            "built over one identically-ordered universe.")
    if isinstance(universes, str):
        if universes == FIXED_UNIVERSE:
            return
        raise ValueError(
            f"unknown universes attestation {universes!r}; expected "
            f"per-date asset lists or porqua_tpu.batch.FIXED_UNIVERSE")
    first = list(universes[0])
    for i, uni in enumerate(universes):
        if list(uni) != first:
            raise ValueError(
                f"scan-coupled solves require one fixed asset universe "
                f"across dates (the scan carry is positional); date {i} "
                f"differs from date 0. Mask exits with lb = ub = 0 "
                f"instead of shrinking the selection.")


def solve_scan_turnover(qp: CanonicalQP,
                        n_assets: int,
                        row_start: int,
                        w_init: jax.Array,
                        params: SolverParams = SolverParams(),
                        *,
                        universes: Sequence[Sequence[str]]) -> QPSolution:
    """Pass 2, turnover-coupled dates: ``lax.scan`` with warm starts.

    When a turnover constraint chains dates through the previous
    solution x0 (reference ``optimization.py:126-137``), the lifted
    problem's constraint rows ``[row_start, row_start+n)`` carry upper
    bound ``x0`` and rows ``[row_start+n, row_start+2n)`` carry ``-x0``
    (:func:`porqua_tpu.qp.lift.lift_turnover_constraint`). Shapes are
    identical across dates, so the scan body updates only those bounds
    and warm-starts each solve from the previous primal/dual point —
    the on-device analog of the reference's ``initvals`` warm start
    (``qp_problems.py:213``).

    ``qp`` is a stacked batch (leading axis = dates) built with
    placeholder x0 = 0; ``w_init`` is the pre-backtest holdings vector
    (zeros for a cash start). ``universes`` (required): the per-date
    asset lists, or :data:`FIXED_UNIVERSE` to attest a by-construction
    fixed universe — the positional-carry precondition is checked, not
    optional.
    """
    _require_fixed_universe(universes)
    n = n_assets
    dtype = qp.P.dtype
    nvar, m = qp.P.shape[-1], qp.C.shape[-2]

    def step(carry, qp_t):
        w_prev, x_prev, y_prev = carry
        u = qp_t.u
        u = jax.lax.dynamic_update_slice(u, w_prev, (row_start,))
        u = jax.lax.dynamic_update_slice(u, -w_prev, (row_start + n,))
        qp_t = qp_t._replace(u=u)
        sol = _solve_impl(qp_t, params, x_prev, y_prev)
        w_new = sol.x[:n]
        # Only advance holdings on a successful solve (the reference keeps
        # the previous portfolio when a date fails, backtest.py:212-214).
        ok = sol.status == Status.SOLVED
        w_carry = jnp.where(ok, w_new, w_prev)
        return (w_carry, sol.x, sol.y), sol

    init = (
        jnp.asarray(w_init, dtype),
        jnp.zeros(nvar, dtype),
        jnp.zeros(m, dtype),
    )
    _, sols = jax.lax.scan(step, init, qp)
    return sols


def solve_scan_l1(qp: CanonicalQP,
                  n_assets: int,
                  w_init: jax.Array,
                  transaction_cost: float,
                  params: SolverParams = SolverParams(),
                  *,
                  universes: Sequence[Sequence[str]]) -> QPSolution:
    """Turnover-cost-coupled dates via ``lax.scan`` with the native prox.

    The sequential analog of :func:`solve_scan_turnover` for the
    *objective* cost term: each date pays
    ``transaction_cost * |w - w_prev|_1`` against the previous date's
    *solved* weights, handled by the solver's L1 prox at n variables
    (no lifted aux block, so the scan carries only the l1 center and the
    warm-start vectors). This is the fully-on-device version of the
    reference's date-chained ``x0`` transaction-cost backtest
    (reference ``optimization.py:126-137`` + ``qp_problems.py:120-157``).

    ``qp`` is a stacked batch (leading axis = dates) of problems over
    the SAME, identically-ordered asset universe: the carry is
    positional, so variable j must mean the same asset on every date —
    a date-varying selection would charge costs between unrelated
    assets. ``universes`` (required): the per-date asset lists from
    :class:`BatchProblems`, or :data:`FIXED_UNIVERSE` to attest a
    by-construction fixed universe; build with a fixed universe,
    masking exits via lb = ub = 0, when chaining costs. ``w_init`` is
    the pre-backtest holdings vector (zeros for a cash start), padded
    to the problem's n.
    """
    _require_fixed_universe(universes)
    dtype = qp.P.dtype
    nvar = qp.P.shape[-1]
    tc = jnp.asarray(transaction_cost, dtype)
    l1w = jnp.where(jnp.arange(nvar) < n_assets, tc, jnp.asarray(0.0, dtype))
    w0 = jnp.zeros(nvar, dtype).at[:n_assets].set(
        jnp.asarray(w_init, dtype)[:n_assets]
    )
    return _scan_l1_core(qp, w0, l1w, params)


def _scan_l1_core(qp: CanonicalQP, w0, l1w,
                  params: SolverParams,
                  x_init=None, y_init=None,
                  return_carry: bool = False):
    """One column of the chained-L1 backtest: the single scan body
    shared by :func:`solve_scan_l1` and (vmapped) by
    :func:`solve_scan_l1_grid`, so the carry/failed-date semantics
    cannot drift between the two.

    ``x_init``/``y_init`` seed the warm-start half of the carry
    (default zeros — a cold start) and ``return_carry=True`` also
    returns the final ``(w, x, y)`` carry: together they let
    :func:`porqua_tpu.checkpoint.solve_scan_l1_checkpointed` cut the
    date axis into segments whose chained execution is bit-identical
    to one uncut scan (the scan body is the same compiled program
    either way; only the host loop around it changes)."""
    dtype = qp.P.dtype
    nvar, m = qp.P.shape[-1], qp.C.shape[-2]

    def step(carry, qp_t):
        w_prev, x_prev, y_prev = carry
        sol = _solve_impl(qp_t, params, x_prev, y_prev,
                          l1_weight=l1w, l1_center=w_prev)
        # Only advance holdings on a successful solve (the reference
        # keeps the previous portfolio when a date fails,
        # backtest.py:212-214).
        ok = sol.status == Status.SOLVED
        w_carry = jnp.where(ok, sol.x, w_prev)
        return (w_carry, sol.x, sol.y), sol

    init = (
        w0,
        jnp.zeros(nvar, dtype) if x_init is None
        else jnp.asarray(x_init, dtype),
        jnp.zeros(m, dtype) if y_init is None
        else jnp.asarray(y_init, dtype),
    )
    carry, sols = jax.lax.scan(step, init, qp)
    if return_carry:
        return sols, carry
    return sols


def solve_scan_l1_grid(qp_grid: CanonicalQP,
                       n_assets: int,
                       w_init: jax.Array,
                       transaction_cost: float,
                       params: SolverParams = SolverParams(),
                       mesh=None,
                       *,
                       universes: Sequence[Sequence[str]]) -> QPSolution:
    """Turnover-cost backtests for a whole benchmark/strategy grid:
    ``lax.scan`` over the coupled dates axis x ``vmap`` over benchmarks,
    optionally sharded over a device mesh.

    This is SURVEY.md §7's mitigation for the scan-vs-vmap tension:
    transaction costs chain consecutive dates (inherently sequential),
    but each benchmark/strategy column is independent, so the scan body
    solves all B benchmarks' date-t problems concurrently and the B
    axis rides the mesh over ICI — zero cross-benchmark collectives in
    the loop (each lane carries its own holdings/warm-start state).

    ``qp_grid`` is a stacked pytree with leading axes ``(B, T)``
    (benchmarks x dates) over one fixed, identically-ordered asset
    universe per column (the :func:`solve_scan_l1` precondition;
    ``universes`` checks it). ``w_init``: (B, n) pre-backtest holdings.
    ``mesh``: a 1-D :class:`jax.sharding.Mesh`; when given, inputs are
    placed with the benchmark axis split across its devices and the
    scan is jitted with matching shardings.
    """
    _require_fixed_universe(universes)
    if qp_grid.P.ndim != 4:
        raise ValueError(
            f"qp_grid must have leading (benchmarks, dates) axes — "
            f"P of shape (B, T, n, n), got {qp_grid.P.shape}; for a "
            f"single column use solve_scan_l1")
    dtype = qp_grid.P.dtype
    B = qp_grid.P.shape[0]
    nvar = qp_grid.P.shape[-1]
    tc = jnp.asarray(transaction_cost, dtype)
    l1w = jnp.where(jnp.arange(nvar) < n_assets, tc, jnp.asarray(0.0, dtype))
    w0 = jnp.zeros((B, nvar), dtype).at[:, :n_assets].set(
        jnp.asarray(w_init, dtype)[:, :n_assets])

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh_size = int(np.prod(mesh.devices.shape))
        if B % mesh_size:
            raise ValueError(
                f"benchmark axis ({B}) must divide evenly over the mesh "
                f"({mesh_size} devices); pad the grid with repeated "
                f"columns (their results are identical and can be "
                f"dropped)")
        axis = mesh.axis_names[0]

        def shard(a):
            spec = (axis,) + (None,) * (a.ndim - 1)
            return jax.device_put(a, NamedSharding(mesh, P(*spec)))

        qp_grid = jax.tree.map(shard, qp_grid)
        w0 = shard(w0)
    return _scan_l1_grid_jit(qp_grid, w0, l1w, params)


@functools.partial(jax.jit, static_argnames=("params",))
def _scan_l1_grid_jit(qp_grid: CanonicalQP, w0, l1w,
                      params: SolverParams) -> QPSolution:
    # vmap over the leading benchmark axis of the shared single-column
    # scan: XLA commutes the vmap into the scan body, yielding the
    # scan-of-vmapped-solves program with no explicit transposes, and
    # the module-level jit caches the compilation across calls.
    return jax.vmap(
        lambda q, w: _scan_l1_core(q, w, l1w, params)
    )(qp_grid, w0)


def as_requests(problems: BatchProblems) -> List[CanonicalQP]:
    """Unstack a host-built batch into per-date single problems — the
    bridge from the one-shot batched backtest to the online solve
    service (:mod:`porqua_tpu.serve`): each date becomes an independent
    request the micro-batcher re-coalesces with whatever else is in
    flight. Fields are numpy views into the stacked arrays (no copy);
    the serve bucketizer re-pads them to its own shape ladder.

    Batches carrying a native L1 objective term are rejected: the term
    lives outside the :class:`CanonicalQP` pytree and the serve entry
    point ``(qp, x0, y0)`` cannot express it — dropping it silently
    would hand the service a *different* optimization problem per date.
    """
    if problems.l1_weight is not None or problems.l1_center is not None:
        raise ValueError(
            "as_requests cannot bridge a batch with a native L1 "
            "objective term (l1_weight/l1_center): the serve executable "
            "signature has no L1 inputs, so the requests would silently "
            "solve a different problem. Lower the cost term into the "
            "constraint rows (qp.lift) before bridging.")
    leaves = jax.tree.map(np.asarray, problems.qp)
    return [
        jax.tree.map(lambda a: a[i], leaves)
        for i in range(problems.n_dates)
    ]


def to_strategy(problems: BatchProblems, solution: QPSolution) -> Strategy:
    """Convert batched device results into the host ``Strategy`` object."""
    xs = np.asarray(solution.x)
    status = np.asarray(solution.status)
    strategy = Strategy([])
    for i, date in enumerate(problems.rebdates):
        uni = problems.universes[i]
        if status[i] == Status.SOLVED:
            weights = {a: float(xs[i, j]) for j, a in enumerate(uni)}
        else:
            weights = {a: None for a in uni}
        strategy.portfolios.append(Portfolio(rebalancing_date=date, weights=weights))
    return strategy


def assemble_backtest(problems: BatchProblems,
                      solution: QPSolution) -> Backtest:
    """Wrap a batched device solution in the serial engine's output type
    (``Backtest`` + ``Strategy``), with the per-date device counters in
    ``output['batch']``."""
    backtest = Backtest()
    backtest._strategy = to_strategy(problems, solution)
    backtest.output["batch"] = {
        "status": np.asarray(solution.status),
        "iters": np.asarray(solution.iters),
        "prim_res": np.asarray(solution.prim_res),
        "dual_res": np.asarray(solution.dual_res),
        "obj_val": np.asarray(solution.obj_val),
    }
    return backtest


def run_batch(bs: BacktestService,
              params: Optional[SolverParams] = None,
              dtype=jnp.float32,
              harvest=None) -> Backtest:
    """End-to-end batched backtest with the serial engine's output type.

    Equivalent to ``Backtest.run(bs)`` (reference ``backtest.py:201-224``)
    for date-independent strategies, but every date solves concurrently
    in one XLA program. ``harvest`` appends one telemetry-warehouse
    record per rebalance date (see :func:`solve_batch`).
    """
    # Build the problems FIRST, then default to the strategy's OWN
    # resolved solver configuration, like the serial engine does.
    # solver_params() is lowering-aware (LAD merges its fixed-LP-step
    # overlay iff the prox form is the active lowering) and pure, but
    # deriving it after the build keeps this robust to any future
    # lowering that is decided during canonical_parts.
    problems = build_problems(bs, dtype=dtype)
    if params is None:
        # Pass the BATCH dtype: the problems were just cast to it, and
        # dtype-sensitive strategy defaults (LAD's f32 eps floor) must
        # key on the dtype actually being solved, not the strategy's
        # declaration.
        params = bs.optimization.solver_params(solve_dtype=dtype)
    solution = solve_batch(problems, params, harvest=harvest)
    return assemble_backtest(problems, solution)
