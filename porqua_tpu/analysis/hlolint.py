"""hlolint: post-lowering static analysis over optimized HLO text.

graftcheck's jaxpr contracts (GC101-GC110, :mod:`.contracts`) stop at
the trace: what XLA actually *emits* — fusions, layouts, temporaries,
padding, post-lowering dtype changes — is invisible to them, so a
refactor or an XLA upgrade can silently unfuse the segment stepper and
the first evidence is a burned chip window. This module closes that
gap: it parses the optimized-HLO ``as_text()`` of a compiled
executable (harvested by :mod:`.hlo` from every
``contracts.check_entry_points`` program) into a light
instruction/fusion graph and runs typed rules over it:

* **GC201 fusion miss** — an unfused elementwise/reduce chain whose
  materialized intermediate clears a ridge-point byte threshold (the
  same measured-bytes axis ``roofline_report`` ranks fusion candidates
  on, so the lint and the verdict agree on targets).
* **GC202 redundant materialization** — the same subcomputation
  (canonicalized fusion body, or a duplicate dot/convolution with
  identical operands) emitted >= 2x in one module: the Gram build or a
  residual norm computed twice.
* **GC203 layout churn** — chained data-movement pairs
  (copy/transpose/bitcast-convert feeding each other): the same bytes
  moved twice for layout's sake on the hot path.
* **GC204 padding waste** — a bucket-ladder padded shape whose
  dead-lane byte share exceeds its per-bucket budget.
* **GC205 temporary-peak budget** — ``memory_analysis()`` peak bytes
  over the committed per-program bound.
* **GC206 post-lowering dtype drift** — f64/c128 (or an explicit
  widening convert) emitted by XLA inside a program whose jaxpr was
  f32-clean: exactly what GC101 cannot see after lowering.

Findings reuse :class:`porqua_tpu.analysis.lint.Finding`; the ``path``
is the virtual ``<hlo:PROGRAM>`` anchor (there is no source file — the
line number indexes the harvested HLO text, which
``scripts/hlolint_report.py`` can print around a finding). Rule ids
live in ``lint.RULE_DOCS`` next to the AST and jaxpr rules so
``run_checks.py --select`` / ``--stats`` treat all three planes
uniformly. Suppressions are per-(program, rule) entries in the
committed baseline artifact (``HLO_BASELINE.json`` — see
:func:`apply_suppressions`), not source comments: HLO has no source
lines to annotate, and the baseline file is already the per-program
contract surface. The shipped baseline carries ZERO suppressions —
same bar as the AST plane.

Pure stdlib on purpose (no jax/numpy): the parser and every rule run
on captured text, so the seeded-violation tests and the CI selftest
(``hlolint_report.py --selftest``) cost no backend compile.
"""

from __future__ import annotations

import dataclasses
import re
from typing import (Any, Dict, Iterable, List, Mapping, Optional,
                    Sequence, Set, Tuple)

from porqua_tpu.analysis.lint import Finding

__all__ = [
    "HLO_RULES",
    "HloComputation",
    "HloInstruction",
    "HloModule",
    "LintConfig",
    "apply_suppressions",
    "check_dtype_drift",
    "check_fusion_miss",
    "check_layout_churn",
    "check_padding_waste",
    "check_redundant_materialization",
    "check_temp_peak",
    "hlo_path",
    "lint_module",
    "parse_hlo",
    "path_program",
    "shape_bytes",
]

#: The post-lowering rule ids this module owns (documented in
#: ``lint.RULE_DOCS``; ``run_checks.py --select`` matches against it).
HLO_RULES = ("GC201", "GC202", "GC203", "GC204", "GC205", "GC206")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
}

_ARRAY_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


def shape_bytes(shape: str) -> int:
    """Total buffer bytes of an HLO shape string — a plain array
    (``f32[4,16]{1,0}``), a scalar (``f32[]``), or a tuple (sum of the
    elements). Layout braces are ignored; unknown dtypes count 4."""
    total = 0
    for dtype, dims in _ARRAY_RE.findall(shape):
        count = 1
        for d in dims.split(","):
            if d:
                count *= int(d)
        total += count * _DTYPE_BYTES.get(dtype, 4)
    return total


def shape_dtypes(shape: str) -> Set[str]:
    """The element dtypes an HLO shape string mentions."""
    return {dtype for dtype, _ in _ARRAY_RE.findall(shape)}


@dataclasses.dataclass
class HloInstruction:
    """One parsed HLO instruction line."""

    name: str             #: SSA name without the leading ``%``
    shape: str            #: result shape string (layout included)
    opcode: str
    operands: Tuple[str, ...]  #: referenced ``%names`` in the operand list
    line: int             #: 1-based line in the module text
    attrs: str            #: raw text after the operand list
    is_root: bool = False

    @property
    def bytes(self) -> int:
        return shape_bytes(self.shape)

    @property
    def called(self) -> Tuple[str, ...]:
        """Computations this instruction calls (fusion bodies, reducer
        lambdas, while bodies/conditions, conditional branches)."""
        return tuple(m.group(2) for m in _CALL_RE.finditer(self.attrs))


@dataclasses.dataclass
class HloComputation:
    """One computation block: the ENTRY, a fusion body, a while
    body/condition, or a reducer lambda."""

    name: str
    line: int                     #: header line number
    params: List[Tuple[str, str]]  #: (name, shape) in signature order
    instructions: List[HloInstruction]
    is_entry: bool = False

    def __post_init__(self) -> None:
        self.by_name: Dict[str, HloInstruction] = {
            i.name: i for i in self.instructions}

    @property
    def root(self) -> Optional[HloInstruction]:
        for i in self.instructions:
            if i.is_root:
                return i
        return self.instructions[-1] if self.instructions else None


@dataclasses.dataclass
class HloModule:
    """A parsed HLO module: computations by name plus the raw text."""

    name: str
    text: str
    computations: Dict[str, HloComputation]
    entry: Optional[HloComputation]

    def fusion_bodies(self) -> Dict[str, HloComputation]:
        """Computations reached through a ``fusion`` op's ``calls=`` —
        the subcomputations XLA actually fused (reducer lambdas and
        while bodies are *not* fusion bodies)."""
        called: Dict[str, HloComputation] = {}
        for comp in self.computations.values():
            for instr in comp.instructions:
                if instr.opcode == "fusion":
                    for target in instr.called:
                        if target in self.computations:
                            called[target] = self.computations[target]
        return called

    def scheduled_computations(self) -> List[HloComputation]:
        """Computations whose instructions execute as emitted (ENTRY +
        while bodies + conditional branches) — everything except fusion
        bodies (fused away) and reducer lambdas (per-element)."""
        fused = set(self.fusion_bodies())
        small = {t for comp in self.computations.values()
                 for instr in comp.instructions
                 if instr.opcode in ("reduce", "reduce-window", "scatter",
                                     "sort", "map", "all-reduce",
                                     "select-and-scatter")
                 for t in instr.called}
        return [c for c in self.computations.values()
                if c.name not in fused and c.name not in small]


_MODULE_RE = re.compile(r"^HloModule\s+([\w.\-]+)")
_HEADER_RE = re.compile(
    r"^\s*(ENTRY\s+)?%?([\w.\-]+)\s*\((.*)\)\s*->\s*.+?\s*\{\s*$")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")
_CALL_RE = re.compile(
    r"(calls|to_apply|body|condition|branch_computations)="
    r"\{?%?([\w.\-]+)")


def _split_shape(rest: str) -> Tuple[str, str]:
    """Split ``rest`` into (result shape, remainder) — the shape is
    either a parenthesized tuple or a single space-free token."""
    if rest.startswith("("):
        depth = 0
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    return rest[:i + 1], rest[i + 1:].lstrip()
        return rest, ""
    cut = rest.find(" ")
    if cut < 0:
        return rest, ""
    return rest[:cut], rest[cut + 1:].lstrip()


def _split_operands(body: str) -> Tuple[str, str]:
    """Split ``opcode(...)...`` tail after the opening paren into
    (operand segment, attrs) by matching the close paren."""
    depth = 1
    for i, ch in enumerate(body):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return body[:i], body[i + 1:].lstrip(", ").strip()
    return body, ""


def _parse_params(seg: str) -> List[Tuple[str, str]]:
    """Signature parameters ``name: shape`` split on top-level commas."""
    params: List[Tuple[str, str]] = []
    depth = 0
    start = 0
    parts: List[str] = []
    for i, ch in enumerate(seg):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(seg[start:i])
            start = i + 1
    if seg[start:].strip():
        parts.append(seg[start:])
    for part in parts:
        if ":" not in part:
            continue
        name, shape = part.split(":", 1)
        params.append((name.strip().lstrip("%"), shape.strip()))
    return params


def parse_hlo(text: str) -> HloModule:
    """Parse optimized-HLO module text into the light graph the rules
    walk. Tolerant by construction: lines that match neither a
    computation header nor an instruction are skipped, so schedule
    annotations, buffer-assignment dumps, and future decoration do not
    break the lint."""
    module_name = ""
    computations: Dict[str, HloComputation] = {}
    entry: Optional[HloComputation] = None

    current: Optional[HloComputation] = None
    for lineno, line in enumerate(text.splitlines(), start=1):
        stripped = line.strip()
        if not stripped:
            continue
        m = _MODULE_RE.match(stripped)
        if m:
            module_name = m.group(1)
            continue
        if current is None:
            h = _HEADER_RE.match(line)
            if h and "=" not in line.split("(")[0]:
                current = HloComputation(
                    name=h.group(2), line=lineno,
                    params=_parse_params(h.group(3)),
                    instructions=[], is_entry=bool(h.group(1)))
            continue
        if stripped == "}":
            current.by_name = {i.name: i for i in current.instructions}
            computations[current.name] = current
            if current.is_entry:
                entry = current
            current = None
            continue
        root = stripped.startswith("ROOT ")
        body = stripped[5:] if root else stripped
        if not body.startswith("%") or "=" not in body:
            continue
        name, _, rest = body.partition("=")
        name = name.strip().lstrip("%")
        rest = rest.strip()
        shape, rest = _split_shape(rest)
        paren = rest.find("(")
        if paren < 0:
            continue
        opcode = rest[:paren].strip()
        operand_seg, attrs = _split_operands(rest[paren + 1:])
        current.instructions.append(HloInstruction(
            name=name, shape=shape, opcode=opcode,
            operands=tuple(_OPERAND_NAME_RE.findall(operand_seg)),
            line=lineno, attrs=attrs, is_root=root))
    if current is not None:  # unterminated block: keep what parsed
        current.by_name = {i.name: i for i in current.instructions}
        computations[current.name] = current
        if current.is_entry:
            entry = current

    return HloModule(name=module_name, text=text,
                     computations=computations, entry=entry)


# ---------------------------------------------------------------------------
# finding anchors
# ---------------------------------------------------------------------------

_HLO_PATH_RE = re.compile(r"^<hlo:(.+)>$")


def hlo_path(program: str) -> str:
    """The virtual path findings on ``program``'s HLO anchor to."""
    return f"<hlo:{program}>"


def path_program(path: str) -> Optional[str]:
    """Inverse of :func:`hlo_path`; ``None`` for ordinary file paths."""
    m = _HLO_PATH_RE.match(path)
    return m.group(1) if m else None


@dataclasses.dataclass
class LintConfig:
    """Thresholds the rules judge against. The defaults are the
    committed-tree contract (HLO_BASELINE.json records the config it
    was built with); the report CLI can override per run."""

    #: GC201: minimum bytes a materialized intermediate must reach to
    #: count as a fusion miss — the ridge-point threshold. At the
    #: harvest shapes everything XLA leaves unfused is small; a real
    #: miss on a production shape clears 64 KiB easily.
    fusion_miss_min_bytes: float = 65536.0
    #: GC203: minimum bytes moved twice before churn is worth a finding.
    churn_min_bytes: float = 16384.0
    #: GC202: fusion bodies smaller than this many ops are ignored
    #: (XLA legitimately duplicates tiny ones instead of materializing).
    dup_min_ops: int = 4
    #: GC202: minimum bytes a duplicated result must materialize before
    #: the pair is a finding rather than an XLA-CSE rounding error.
    dup_min_bytes: float = 4096.0
    #: GC204: default dead-lane byte share budget per bucket.
    padding_budget: float = 0.25
    #: GC206: the widest float the program is allowed to emit.
    expect_float: str = "f32"


# ---------------------------------------------------------------------------
# GC201 — fusion miss
# ---------------------------------------------------------------------------

_ELEMENTWISE = {
    "abs", "add", "and", "atan2", "ceil", "clamp", "compare", "cosine",
    "divide", "exponential", "exponential-minus-one", "floor", "log",
    "log-plus-one", "maximum", "minimum", "multiply", "negate", "not",
    "or", "power", "remainder", "round-nearest-afz", "rsqrt", "select",
    "sign", "sine", "sqrt", "subtract", "tanh", "xor",
}
_REDUCERS = {"reduce", "reduce-window"}


def check_fusion_miss(module: HloModule, program: str,
                      min_bytes: float = 65536.0) -> List[Finding]:
    """GC201: an elementwise producer feeding an elementwise/reduce
    consumer as two *scheduled* instructions — the intermediate is
    materialized to memory where a fusion would have kept it in
    registers. Only intermediates at least ``min_bytes`` wide count
    (the ridge-point threshold: below it the roundtrip is latency
    noise, above it the program is provably bandwidth-bound on bytes
    a fusion removes). Findings are ranked widest-first, the same
    measured-bytes ordering ``roofline_report`` ranks its fusion
    candidates by."""
    ranked: List[Tuple[int, Finding]] = []
    for comp in module.scheduled_computations():
        flagged: Set[str] = set()
        for instr in comp.instructions:
            if instr.opcode not in (_ELEMENTWISE | _REDUCERS):
                continue
            for op_name in instr.operands:
                prod = comp.by_name.get(op_name)
                if prod is None or prod.name in flagged:
                    continue
                if prod.opcode not in _ELEMENTWISE:
                    continue
                nbytes = prod.bytes
                if nbytes < min_bytes:
                    continue
                flagged.add(prod.name)
                ranked.append((nbytes, Finding(
                    "GC201", hlo_path(program), prod.line, 1,
                    f"fusion miss: {prod.opcode} -> {instr.opcode} left "
                    f"unfused in {comp.name}; the {prod.shape} "
                    f"intermediate materializes {nbytes} B per dispatch "
                    f"(ridge threshold {int(min_bytes)} B)")))
    ranked.sort(key=lambda pair: (-pair[0], pair[1].line))
    return [f for _, f in ranked]


# ---------------------------------------------------------------------------
# GC202 — redundant materialization
# ---------------------------------------------------------------------------

def _canonical_body(comp: HloComputation) -> Tuple:
    """A rename-invariant signature of a computation body: opcodes,
    shapes, and operand references rewritten to local positions."""
    local = {name: f"i{idx}" for idx, name in
             enumerate(i.name for i in comp.instructions)}
    for idx, (pname, _) in enumerate(comp.params):
        local.setdefault(pname, f"p{idx}")
    rows = []
    for instr in comp.instructions:
        rows.append((instr.opcode, instr.shape,
                     tuple(local.get(op, "?") for op in instr.operands)))
    return (tuple(s for _, s in comp.params), tuple(rows))


def check_redundant_materialization(module: HloModule, program: str,
                                    min_ops: int = 4,
                                    min_bytes: float = 4096.0,
                                    ) -> List[Finding]:
    """GC202: the same subcomputation materialized >= 2x in one module
    — two fusion *call sites* whose bodies are canonically identical
    AND whose operands are identical (the Gram build or a residual
    norm computed twice instead of reused), or a duplicated expensive
    op (dot/convolution with identical operands and shape surviving in
    one computation). Cloned fusion bodies alone are NOT findings: XLA
    clones one body per call site by design (unrolled segment steps
    each call their own copy with different state), and only an
    identical-operand pair recomputes anything. Duplicates whose
    result is under ``min_bytes`` are noise, not bandwidth (XLA's own
    CSE misses the occasional tiny constant-fed pair — see the README
    triage table)."""
    findings: List[Finding] = []

    bodies = module.fusion_bodies()
    body_sig: Dict[str, Tuple] = {}
    for name, comp in bodies.items():
        if len(comp.instructions) >= min_ops:
            body_sig[name] = _canonical_body(comp)

    for comp in module.scheduled_computations():
        seen_calls: Dict[Tuple, HloInstruction] = {}
        for instr in comp.instructions:
            if instr.opcode != "fusion":
                continue
            sigs = tuple(body_sig.get(t) for t in instr.called
                         if t in bodies)
            if not sigs or any(s is None for s in sigs):
                continue
            key = (sigs, instr.shape, instr.operands)
            prev = seen_calls.get(key)
            if prev is None:
                seen_calls[key] = instr
                continue
            if instr.bytes < min_bytes:
                continue
            body = next(t for t in instr.called if t in bodies)
            findings.append(Finding(
                "GC202", hlo_path(program), instr.line, 1,
                f"redundant materialization: fusion {instr.name} "
                f"({body}, {instr.shape}) in {comp.name} recomputes "
                f"{prev.name} (line {prev.line}) over identical "
                "operands — the same subcomputation is emitted and "
                "materialized twice in one module"))

    for comp in module.scheduled_computations():
        seen: Dict[Tuple, HloInstruction] = {}
        for instr in comp.instructions:
            if instr.opcode not in ("dot", "convolution"):
                continue
            key = (instr.opcode, instr.shape, instr.operands)
            prev = seen.get(key)
            if prev is None:
                seen[key] = instr
                continue
            findings.append(Finding(
                "GC202", hlo_path(program), instr.line, 1,
                f"redundant materialization: {instr.opcode} "
                f"{instr.shape} over {', '.join(instr.operands)} in "
                f"{comp.name} repeats {prev.name} (line {prev.line}) "
                "with identical operands — CSE left the contraction "
                "computed twice"))
    return findings


# ---------------------------------------------------------------------------
# GC203 — layout churn
# ---------------------------------------------------------------------------

_MOVERS = {"copy", "transpose", "bitcast-convert"}


def check_layout_churn(module: HloModule, program: str,
                       min_bytes: float = 16384.0) -> List[Finding]:
    """GC203: a copy/transpose/bitcast-convert whose operand is itself
    one — the same buffer moved twice for layout's sake. Plain
    ``bitcast`` is exempt (metadata-only, no data movement); pairs
    under ``min_bytes`` are latency noise, not bandwidth."""
    findings: List[Finding] = []
    for comp in module.scheduled_computations():
        for instr in comp.instructions:
            if instr.opcode not in _MOVERS:
                continue
            for op_name in instr.operands:
                prod = comp.by_name.get(op_name)
                if prod is None or prod.opcode not in _MOVERS:
                    continue
                nbytes = max(instr.bytes, prod.bytes)
                if nbytes < min_bytes:
                    continue
                findings.append(Finding(
                    "GC203", hlo_path(program), instr.line, 1,
                    f"layout churn: {prod.opcode} (line {prod.line}) -> "
                    f"{instr.opcode} in {comp.name} moves {nbytes} B "
                    "twice for layout — fold the transposition into "
                    "the producer or pin the layout"))
    return findings


# ---------------------------------------------------------------------------
# GC204 — padding waste
# ---------------------------------------------------------------------------

def check_padding_waste(program: str,
                        natural_bytes: float,
                        padded_bytes: Optional[float] = None,
                        budget: float = 0.25,
                        module: Optional[HloModule] = None,
                        bucket: Optional[str] = None,
                        line: int = 1) -> List[Finding]:
    """GC204: the dead-lane byte share of a bucket-padded program —
    ``1 - natural/padded`` — exceeds its per-bucket budget. The padded
    bytes come from the lowered entry signature when a ``module`` is
    given (the shapes XLA actually allocated), or are passed directly
    (the bucket-ladder arithmetic ``hlo.bucket_padding_cells``
    computes)."""
    if module is not None and module.entry is not None:
        padded_bytes = float(sum(shape_bytes(s)
                                 for _, s in module.entry.params))
        line = module.entry.line
    if not padded_bytes or natural_bytes is None:
        return []
    share = 1.0 - float(natural_bytes) / float(padded_bytes)
    if share <= budget:
        return []
    where = f" (bucket {bucket})" if bucket else ""
    return [Finding(
        "GC204", hlo_path(program), line, 1,
        f"padding waste{where}: dead-lane byte share {share:.3f} over "
        f"budget {budget:.3f} — {int(padded_bytes - natural_bytes)} of "
        f"{int(padded_bytes)} padded input bytes are dead lanes")]


# ---------------------------------------------------------------------------
# GC205 — temporary-peak budget
# ---------------------------------------------------------------------------

def check_temp_peak(program: str,
                    peak_bytes: Optional[float],
                    budget_bytes: Optional[float],
                    line: int = 1) -> List[Finding]:
    """GC205: ``memory_analysis()`` peak bytes over the committed
    per-program bound. No bound (a program the baseline has not seen)
    or no measurement (a backend that refuses the analysis) checks
    nothing — absence is handled by the coverage rules in bench_gate,
    not by a fake pass here."""
    if peak_bytes is None or budget_bytes is None:
        return []
    if float(peak_bytes) <= float(budget_bytes):
        return []
    return [Finding(
        "GC205", hlo_path(program), line, 1,
        f"temporary-peak budget: memory_analysis peak {int(peak_bytes)} B "
        f"exceeds the committed bound {int(budget_bytes)} B — a bigger "
        "live range (lost fusion, new temporary) lands here before it "
        "OOMs a chip window")]


# ---------------------------------------------------------------------------
# GC206 — post-lowering dtype drift
# ---------------------------------------------------------------------------

_WIDER_THAN = {
    "f16": {"f32", "f64", "c64", "c128"},
    "bf16": {"f32", "f64", "c64", "c128"},
    "f32": {"f64", "c128"},
    "f64": set(),
}


def check_dtype_drift(module: HloModule, program: str,
                      expect_float: str = "f32") -> List[Finding]:
    """GC206: an instruction whose result is wider than the program's
    float policy (f64/c128 in an f32 program) after lowering — the
    drift GC101 cannot see because it appears in XLA's output, not the
    jaxpr. One finding per (computation, opcode): the first occurrence
    anchors it, the rest are the same root cause."""
    wide = _WIDER_THAN.get(expect_float, {"f64", "c128"})
    if not wide:
        return []
    findings: List[Finding] = []
    for comp in module.computations.values():
        seen: Set[str] = set()
        for instr in comp.instructions:
            hit = shape_dtypes(instr.shape) & wide
            if not hit or instr.opcode in seen:
                continue
            seen.add(instr.opcode)
            findings.append(Finding(
                "GC206", hlo_path(program), instr.line, 1,
                f"post-lowering dtype drift: {instr.opcode} emits "
                f"{'/'.join(sorted(hit))} in {comp.name} of a "
                f"{expect_float} program — widening XLA introduced "
                "after the jaxpr (GC101) was checked"))
    return findings


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def lint_module(module: HloModule,
                program: str,
                config: Optional[LintConfig] = None,
                peak_bytes: Optional[float] = None,
                peak_budget: Optional[float] = None,
                rules: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run every module-scoped rule (GC201/202/203/206 — plus GC205
    when a peak and its budget are supplied) over one parsed program.
    GC204 is ladder-scoped, not module-scoped: drive
    :func:`check_padding_waste` from the bucket cells directly."""
    cfg = config or LintConfig()
    selected = set(rules) if rules is not None else set(HLO_RULES)
    findings: List[Finding] = []
    if "GC201" in selected:
        findings += check_fusion_miss(module, program,
                                      cfg.fusion_miss_min_bytes)
    if "GC202" in selected:
        findings += check_redundant_materialization(module, program,
                                                    cfg.dup_min_ops,
                                                    cfg.dup_min_bytes)
    if "GC203" in selected:
        findings += check_layout_churn(module, program,
                                       cfg.churn_min_bytes)
    if "GC205" in selected:
        findings += check_temp_peak(program, peak_bytes, peak_budget)
    if "GC206" in selected:
        findings += check_dtype_drift(module, program, cfg.expect_float)
    return findings


def apply_suppressions(
        findings: Sequence[Finding],
        suppressions: Iterable[Mapping[str, Any]],
) -> Tuple[List[Finding], Dict[str, int]]:
    """Filter findings against baseline suppression entries
    (``{"program": <label or "*">, "rule": "GC2xx", "reason": ...}``)
    and count what was suppressed per rule — the counts feed
    ``run_checks.py --stats`` so HLO suppression creep is as visible
    as source-comment creep. Entries without a reason are ignored: an
    unexplained suppression is a finding, not a policy."""
    table: Set[Tuple[str, str]] = set()
    for entry in suppressions:
        rule = str(entry.get("rule", ""))
        prog = str(entry.get("program", "*"))
        if rule and entry.get("reason"):
            table.add((prog, rule))
    kept: List[Finding] = []
    counts: Dict[str, int] = {}
    for f in findings:
        prog = path_program(f.path) or f.path
        if (prog, f.rule) in table or ("*", f.rule) in table:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        else:
            kept.append(f)
    return kept, counts
