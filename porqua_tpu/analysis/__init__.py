"""graftcheck: static analysis + sanitizers for the device discipline.

Four enforcement layers (see each submodule's docstring):

* :mod:`porqua_tpu.analysis.lint` — AST rules GC001-GC005 (precision
  pins, host-sync hazards, recompile hazards, debug hooks, import-time
  backend init). Pure stdlib on its own (no JAX work), though the
  parent ``porqua_tpu`` package import still runs first.
* :mod:`porqua_tpu.analysis.guards` — GC006, the ``# guarded-by:``
  thread-safety lint for the serving stack.
* :mod:`porqua_tpu.analysis.concurrency` — GC008-GC010, the
  concurrency plane: inferred lock discipline over a thread-root
  reachability graph, static lock-order deadlock detection, and the
  blocking-call-under-lock lint.
* :mod:`porqua_tpu.analysis.contracts` — GC101-GC103, trace-time jaxpr
  contracts on the public batch entry points (imports JAX; loaded
  lazily so the lint path stays light).
* :mod:`porqua_tpu.analysis.hlolint` — GC201-GC206, post-lowering
  rules over optimized HLO text (fusion miss, redundant
  materialization, layout churn, padding waste, temp-peak budget,
  dtype drift). Pure stdlib; the companion harvester
  :mod:`porqua_tpu.analysis.hlo` compiles every entry-point program
  via ``jit(...).lower(...).compile()`` and is loaded lazily (it
  needs JAX and real compile time).
* :mod:`porqua_tpu.analysis.sanitize` — the ``PORQUA_SANITIZE=1``
  runtime mode: ``jax.transfer_guard`` around solver dispatches and a
  hard zero-recompiles-after-warmup assertion in serving.
* :mod:`porqua_tpu.analysis.tsan` — the ``PORQUA_TSAN=1`` runtime
  lock-order sanitizer: instrumented locks maintaining per-thread
  held-lock sets and the process-wide acquisition-order graph,
  raising ``SanitizerError`` on order inversions, hold-time budget
  breaches, and live wait-for deadlocks.

CLI: ``python scripts/run_checks.py porqua_tpu/`` (wired into
``scripts/run_tests.sh``). Suppressions: ``# graftcheck:
disable=GC00x`` per line, ``# graftcheck: disable-file=GC00x`` per
file. See README "Static analysis & sanitizers".
"""

from porqua_tpu.analysis.lint import (  # noqa: F401
    Finding,
    RULE_DOCS,
    scan_paths,
    suppression_stats,
)
from porqua_tpu.analysis.guards import check_guarded_by  # noqa: F401
from porqua_tpu.analysis.concurrency import check_concurrency  # noqa: F401
from porqua_tpu.analysis import sanitize  # noqa: F401
from porqua_tpu.analysis import tsan  # noqa: F401

__all__ = [
    "Finding",
    "RULE_DOCS",
    "scan_paths",
    "suppression_stats",
    "check_guarded_by",
    "check_concurrency",
    "sanitize",
    "tsan",
    "contracts",
    "hlo",
    "hlolint",
]


def __getattr__(name):
    # `contracts` and `hlo` import porqua_tpu.qp/batch at call time;
    # loading them lazily keeps this package free of import cycles with
    # porqua_tpu.batch (which imports `sanitize` from here) and skips
    # the tracer/harvester machinery when only the AST rules are
    # wanted. `hlolint` is stdlib-light but pulled lazily for symmetry.
    if name in ("contracts", "hlo", "hlolint"):
        import importlib

        return importlib.import_module(f"porqua_tpu.analysis.{name}")
    raise AttributeError(name)
