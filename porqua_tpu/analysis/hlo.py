"""Post-lowering HLO harvester: every entry-point program, compiled.

The jaxpr contracts (:mod:`.contracts`) enumerate the programs this
repo serves — solve/serve AOT entries (both solver backends, i.e. the
routed dispatch pair), the factored and ring-telemetry variants, the
tracking step, the compaction step-and-repack, and the
continuous-batching admit/step/finalize triple. This module lowers the
*same* closures (the ``*_program`` builders in :mod:`.contracts`, so
there is one definition of "the program") through
``jit(...).lower(...).compile()`` and captures what XLA actually
emitted: the optimized-HLO ``as_text()``, ``cost_analysis()`` flops /
bytes, ``memory_analysis()`` peak, and the stable per-program HLO
fingerprint — all through :mod:`porqua_tpu.obs.devprof`'s CostRecord
constructor, so the harvest lands in the same warehouse schema the
roofline verdict reads.

On top of the harvest sit the post-lowering lint harness
(:func:`lint_harvest` — drives :mod:`.hlolint`'s GC201-GC206 rules
with the committed per-program budgets) and the baseline plumbing
(``HLO_BASELINE.json``: fingerprints, measured cost, peak budgets,
padding cells, and the — empty — suppression table). A fingerprint
flip against the baseline on an unchanged source tree names the
program that re-lowered differently; ``scripts/hlolint_report.py``
renders that join and ``bench_gate.py``'s hlo rule class holds the
finding counts and top-target bytes against the baseline and the
ledger trend.

Harvesting compiles every program (~seconds each on XLA-CPU), so it is
opt-in everywhere: ``run_checks.py --hlo``, ``hlolint_report.py``, and
the ``config_hlo`` bench part guard it behind explicit flags/budgets.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from porqua_tpu.analysis import hlolint
from porqua_tpu.analysis.lint import Finding

__all__ = [
    "BASELINE_SCHEMA_VERSION",
    "DEFAULT_BASELINE_PATH",
    "HarvestedProgram",
    "bench_hlo_part",
    "bucket_padding_cells",
    "build_baseline",
    "compare_fingerprints",
    "entry_point_programs",
    "harvest_entry_points",
    "lint_harvest",
    "load_baseline",
    "lower_program",
    "padding_findings",
]

#: Bump when a baseline field changes meaning.
BASELINE_SCHEMA_VERSION = 1

#: The committed fingerprint/budget artifact, repo root.
DEFAULT_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "HLO_BASELINE.json")

#: Peak-memory headroom the baseline grants each program: the GC205
#: bound is ``peak_bytes * PEAK_HEADROOM`` at baseline-build time, so
#: jitter passes and a real live-range regression (lost fusion, new
#: temporary) fails.
PEAK_HEADROOM = 1.25


def entry_point_programs(dtype=np.float32,
                         factor_rows: int = 8,
                         ring_size: int = 8):
    """Every lowerable program ``contracts.check_entry_points`` sweeps,
    as ``[(label, fn, example_args)]`` — the identity checks
    (GC104-GC110) are properties of *these* programs, not extra ones.
    The labels match the contract sweep's so a finding, a CostRecord,
    and a jaxpr contract all name the same program. ``serve_entry`` /
    ``serve_entry[pdhg]`` / ``serve_entry[napg]`` are the routed
    dispatch set — the executables
    :class:`porqua_tpu.serve.routing.SolverRouter` picks between."""
    from porqua_tpu.analysis import contracts
    from porqua_tpu.qp.solve import SolverParams

    progs: List[Tuple[str, Any, tuple]] = []

    def add(label: str, pair) -> None:
        fn, args = pair
        progs.append((label, fn, args))

    add("solve_batch", contracts.solve_batch_program(dtype=dtype))
    add("solve_batch[factored]", contracts.solve_batch_program(
        factor_rows=factor_rows, dtype=dtype))
    add("serve_entry", contracts.serve_entry_program(dtype=dtype))
    add("serve_entry[factored]", contracts.serve_entry_program(
        factor_rows=factor_rows, dtype=dtype))
    add("tracking_step", contracts.tracking_program(dtype=dtype))
    if ring_size:
        rings = SolverParams(ring_size=ring_size)
        add("solve_batch[rings]", contracts.solve_batch_program(
            params=rings, dtype=dtype))
        add("serve_entry[rings]", contracts.serve_entry_program(
            params=rings, dtype=dtype))
    add("compaction_step", contracts.compaction_step_program(dtype=dtype))
    add("compaction_step[factored]", contracts.compaction_step_program(
        factor_rows=factor_rows, dtype=dtype))
    for label, fn, args in contracts.continuous_programs(dtype=dtype):
        progs.append((label, fn, args))
    pdhg = SolverParams(method="pdhg")
    add("solve_batch[pdhg]", contracts.solve_batch_program(
        params=pdhg, dtype=dtype))
    add("serve_entry[pdhg]", contracts.serve_entry_program(
        params=pdhg, dtype=dtype))
    if ring_size:
        add("solve_batch[pdhg,rings]", contracts.solve_batch_program(
            params=SolverParams(method="pdhg", ring_size=ring_size),
            dtype=dtype))
    add("compaction_step[pdhg]", contracts.compaction_step_program(
        params=pdhg, dtype=dtype))
    for label, fn, args in contracts.continuous_programs(
            params=pdhg, dtype=dtype):
        progs.append((f"{label}[pdhg]", fn, args))
    napg = SolverParams(method="napg")
    add("solve_batch[napg]", contracts.solve_batch_program(
        params=napg, dtype=dtype))
    add("serve_entry[napg]", contracts.serve_entry_program(
        params=napg, dtype=dtype))
    if ring_size:
        add("solve_batch[napg,rings]", contracts.solve_batch_program(
            params=SolverParams(method="napg", ring_size=ring_size),
            dtype=dtype))
    add("compaction_step[napg]", contracts.compaction_step_program(
        params=napg, dtype=dtype))
    for label, fn, args in contracts.continuous_programs(
            params=napg, dtype=dtype):
        progs.append((f"{label}[napg]", fn, args))
    # The sketch-fed tracking path is its own executable (sketch_dim is
    # a static jit key): the count-sketch Gram embedding must lint and
    # fingerprint like any other routed program. window=8 -> dim 4
    # compresses, exercising the enabled branch.
    add("tracking_step[sketch]", contracts.tracking_program(
        params=SolverParams(sketch_dim=4), dtype=dtype))
    return progs


@dataclasses.dataclass
class HarvestedProgram:
    """One lowered entry point and everything the lint reads off it."""

    label: str
    hlo_text: str
    fingerprint: Optional[str]
    flops: Optional[float]
    bytes_accessed: Optional[float]
    memory: Dict[str, Optional[float]]
    compile_s: float
    record: Dict[str, Any]  #: the devprof CostRecord (warehouse schema)

    @property
    def peak_bytes(self) -> Optional[float]:
        return self.memory.get("peak_bytes")

    def parse(self) -> "hlolint.HloModule":
        return hlolint.parse_hlo(self.hlo_text)


def lower_program(label: str, fn, args,
                  cost_log=None) -> HarvestedProgram:
    """Lower + compile one program and capture the device truth. The
    CostRecord goes through :func:`porqua_tpu.obs.devprof.cost_record`
    (kind ``"hlolint"``) so the harvest shares the warehouse schema —
    and optionally lands in a live :class:`~porqua_tpu.obs.devprof.CostLog`."""
    import jax

    from porqua_tpu.obs.devprof import (
        cost_record, executable_cost, executable_memory)

    t0 = time.perf_counter()
    # Pin x64 off for the lowering: the committed fingerprints must be
    # invariant to ambient config (the test suite flips jax_enable_x64
    # globally, which re-lowers weak-typed scalars as f64 and flips
    # every hash).
    with jax.experimental.disable_x64():
        compiled = jax.jit(fn).lower(*args).compile()
    compile_s = time.perf_counter() - t0
    device = jax.devices()[0].platform
    rec = cost_record(compiled, entry=label, kind="hlolint",
                      device=device, compile_s=compile_s)
    if cost_log is not None:
        cost_log.emit(rec)
    try:
        text = compiled.as_text() or ""
    except Exception:  # noqa: BLE001 - text capture is best-effort
        text = ""
    cost = executable_cost(compiled)
    return HarvestedProgram(
        label=label, hlo_text=text, fingerprint=rec.get("hlo_hash"),
        flops=cost.get("flops"),
        bytes_accessed=cost.get("bytes_accessed"),
        memory=executable_memory(compiled), compile_s=compile_s,
        record=rec)


def harvest_entry_points(dtype=np.float32,
                         factor_rows: int = 8,
                         ring_size: int = 8,
                         labels: Optional[Iterable[str]] = None,
                         cost_log=None,
                         progress=None) -> List[HarvestedProgram]:
    """Lower every entry-point program (optionally restricted to
    ``labels``) and return the harvest. ``progress`` is an optional
    ``callable(label, seconds)`` hook for CLIs — a full sweep is ~20
    compiles and minutes of XLA-CPU time, silence reads as a hang."""
    wanted = set(labels) if labels is not None else None
    out: List[HarvestedProgram] = []
    for label, fn, args in entry_point_programs(
            dtype=dtype, factor_rows=factor_rows, ring_size=ring_size):
        if wanted is not None and label not in wanted:
            continue
        hp = lower_program(label, fn, args, cost_log=cost_log)
        if progress is not None:
            progress(label, hp.compile_s)
        out.append(hp)
    return out


# ---------------------------------------------------------------------------
# GC204 — bucket-ladder padding cells
# ---------------------------------------------------------------------------

def _qp_lane_bytes(n: int, m: int, dtype=np.float32) -> int:
    """Input bytes of ONE lane of the batched QP at shape (n, m) — from
    the solver's own ``batch_shape_struct`` leaves, so the arithmetic
    cannot fork from what the serve plane actually allocates."""
    import jax

    from porqua_tpu.qp.solve import batch_shape_struct

    struct = batch_shape_struct(1, n, m, dtype=dtype)
    return int(sum(int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
                   for leaf in jax.tree.leaves(struct)))


def bucket_padding_cells(n_rungs: Optional[Sequence[int]] = None,
                         m_rungs: Optional[Sequence[int]] = None,
                         dtype=np.float32) -> List[Dict[str, Any]]:
    """The worst-case dead-lane byte share per bucket of the serving
    ladder: for each ``(n, m)`` rung pair, the natural shape that pads
    worst is one past the previous rung on both axes. These are the
    GC204 cells: the committed baseline records each cell's share, and
    a ladder change that worsens a cell past its budget is a finding."""
    from porqua_tpu.serve.bucketing import DEFAULT_M_RUNGS, DEFAULT_N_RUNGS

    n_rungs = tuple(n_rungs or DEFAULT_N_RUNGS)
    m_rungs = tuple(m_rungs or DEFAULT_M_RUNGS)
    cells: List[Dict[str, Any]] = []
    for i, n in enumerate(n_rungs):
        nat_n = (n_rungs[i - 1] + 1) if i else 1
        for j, m in enumerate(m_rungs):
            nat_m = (m_rungs[j - 1] + 1) if j else 1
            padded = _qp_lane_bytes(n, m, dtype=dtype)
            natural = _qp_lane_bytes(nat_n, nat_m, dtype=dtype)
            cells.append({
                "bucket": f"{n}x{m}",
                "natural": f"{nat_n}x{nat_m}",
                "padded_bytes": padded,
                "natural_bytes": natural,
                "share": 1.0 - natural / padded,
            })
    return cells


def padding_findings(cells: Iterable[Dict[str, Any]],
                     budgets: Optional[Dict[str, float]] = None,
                     default_budget: float = 0.25) -> List[Finding]:
    """GC204 over ladder cells: each cell's worst-case share vs its
    per-bucket budget (``budgets[bucket]``, falling back to the
    default). Program anchor is ``bucket_ladder[<bucket>]``."""
    findings: List[Finding] = []
    budgets = budgets or {}
    for idx, cell in enumerate(cells):
        bucket = cell["bucket"]
        findings += hlolint.check_padding_waste(
            f"bucket_ladder[{bucket}]",
            natural_bytes=cell["natural_bytes"],
            padded_bytes=cell["padded_bytes"],
            budget=float(budgets.get(bucket, default_budget)),
            bucket=bucket, line=idx + 1)
    return findings


# ---------------------------------------------------------------------------
# lint harness + baseline
# ---------------------------------------------------------------------------

def lint_harvest(programs: Sequence[HarvestedProgram],
                 baseline: Optional[Dict[str, Any]] = None,
                 config: Optional[hlolint.LintConfig] = None,
                 rules: Optional[Iterable[str]] = None,
                 include_padding: bool = True,
                 stats_out: Optional[Dict[str, Any]] = None,
                 ) -> List[Finding]:
    """Run GC201-GC206 over a harvest. The committed baseline supplies
    the per-program GC205 peak budgets, the per-bucket GC204 budgets,
    and the suppression table; without one, GC205 has no bounds to
    check and GC204 falls back to each cell's recorded-share-free
    default budget. ``stats_out`` (when given) receives
    ``hlo_programs`` / ``hlo_suppressions_by_rule`` for
    ``run_checks.py --stats``."""
    base_programs = (baseline or {}).get("programs", {})
    base_padding = (baseline or {}).get("padding", {})
    findings: List[Finding] = []
    for hp in programs:
        module = hp.parse()
        entry = base_programs.get(hp.label, {})
        findings += hlolint.lint_module(
            module, hp.label, config=config,
            peak_bytes=hp.peak_bytes,
            peak_budget=entry.get("peak_budget"),
            rules=rules)
    selected = set(rules) if rules is not None else set(hlolint.HLO_RULES)
    if include_padding and "GC204" in selected:
        findings += padding_findings(
            bucket_padding_cells(),
            budgets=base_padding.get("budgets"),
            default_budget=float(base_padding.get("default_budget", 0.25)))
    findings, suppressed = hlolint.apply_suppressions(
        findings, (baseline or {}).get("suppressions", ()))
    if stats_out is not None:
        stats_out["hlo_programs"] = len(programs)
        stats_out["hlo_suppressions_by_rule"] = suppressed
    return findings


def load_baseline(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Read the committed baseline; ``None`` when absent (a fresh tree
    that has not built one yet — callers degrade, not crash)."""
    path = path or DEFAULT_BASELINE_PATH
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def build_baseline(programs: Sequence[HarvestedProgram],
                   config: Optional[hlolint.LintConfig] = None,
                   padding_margin: float = 0.02) -> Dict[str, Any]:
    """The committed artifact: per-program fingerprints + measured
    cost + GC205 peak budgets (measured peak x headroom), the GC204
    ladder cells with per-bucket budgets (current worst-case share +
    margin — a ladder change that worsens a cell fails), the finding
    counts at build time (the bench gate's regression floor), and the
    — empty — suppression table."""
    cfg = config or hlolint.LintConfig()
    entries: Dict[str, Any] = {}
    for hp in programs:
        module = hp.parse()
        found = hlolint.lint_module(module, hp.label, config=cfg,
                                    peak_bytes=hp.peak_bytes,
                                    peak_budget=None)
        by_rule: Dict[str, int] = {}
        for f in found:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        peak = hp.peak_bytes
        entries[hp.label] = {
            "fingerprint": hp.fingerprint,
            "flops": hp.flops,
            "bytes_accessed": hp.bytes_accessed,
            "peak_bytes": peak,
            "peak_budget": (None if peak is None
                            else float(int(peak * PEAK_HEADROOM))),
            "hlo_lines": hp.hlo_text.count("\n") + 1,
            "compile_s": round(hp.compile_s, 3),
            "findings_by_rule": by_rule,
        }
    cells = bucket_padding_cells()
    budgets = {c["bucket"]: round(c["share"] + padding_margin, 4)
               for c in cells}
    return {
        "schema": BASELINE_SCHEMA_VERSION,
        "built_t": time.time(),
        "dtype": "float32",
        "config": dataclasses.asdict(cfg),
        "programs": entries,
        "padding": {"default_budget": cfg.padding_budget,
                    "budgets": budgets, "cells": cells},
        "suppressions": [],
    }


def compare_fingerprints(baseline: Dict[str, Any],
                         programs: Sequence[HarvestedProgram],
                         ) -> Dict[str, List[str]]:
    """Diff a fresh harvest's fingerprints against the baseline's.
    ``flipped`` names programs that re-lowered differently on an
    unchanged source tree (an XLA/runtime change, or a silent program
    change); ``missing`` are baseline programs the harvest lost
    (coverage regression); ``new`` are programs the baseline predates."""
    base = baseline.get("programs", {})
    fresh = {hp.label: hp.fingerprint for hp in programs}
    flipped = sorted(
        label for label, fp in fresh.items()
        if label in base and base[label].get("fingerprint")
        and fp and fp != base[label]["fingerprint"])
    missing = sorted(set(base) - set(fresh))
    new = sorted(set(fresh) - set(base))
    return {"flipped": flipped, "missing": missing, "new": new}


def bench_hlo_part(baseline: Optional[Dict[str, Any]] = None,
                   programs: Optional[Sequence[HarvestedProgram]] = None,
                   dtype=np.float32) -> Dict[str, Any]:
    """The ``config_hlo`` bench part: a fresh harvest linted against
    the committed baseline, summarized to what the gate's hlo rule
    class holds — program coverage, total and per-program-max finding
    counts, fingerprint flips, and the top fusion target's measured
    bytes (the number a fusion win must move and a regression must not
    grow)."""
    if baseline is None:
        baseline = load_baseline()
    if programs is None:
        programs = harvest_entry_points(dtype=dtype)
    findings = lint_harvest(programs, baseline=baseline)
    by_program: Dict[str, int] = {}
    for f in findings:
        prog = hlolint.path_program(f.path) or f.path
        by_program[prog] = by_program.get(prog, 0) + 1
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    top = max(programs, key=lambda hp: hp.bytes_accessed or 0.0,
              default=None)
    flips = (compare_fingerprints(baseline, programs)["flipped"]
             if baseline else [])
    part: Dict[str, Any] = {
        "programs": len(programs),
        "findings_total": len(findings),
        "findings_by_rule": by_rule,
        "findings_by_program": by_program,
        "findings_max_per_program": max(by_program.values(), default=0),
        "fingerprint_flips": len(flips),
        "flipped_programs": flips,
        "compile_s_total": round(sum(hp.compile_s for hp in programs), 3),
    }
    if top is not None:
        part["top_target"] = top.label
        part["top_target_bytes"] = top.bytes_accessed
    return part
