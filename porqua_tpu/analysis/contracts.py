"""Trace-time jaxpr contracts for the public batch entry points.

The AST rules see source; these checks see what XLA will actually be
asked to run. Each public batch entry point — ``solve_batch`` (dense
and factored), the serving AOT executable body, ``tracking_step``, and
``run_batch``'s device core — is traced with abstract f32 inputs via
``jax.make_jaxpr`` and the resulting program is asserted to satisfy:

GC101  **No float64 anywhere.** The TPU has no native f64; a stray
       ``convert_element_type`` to f64 (a numpy scalar leaking into
       the trace, an unpinned literal under x64) silently doubles
       memory traffic on CPU and fails or emulates on TPU.
GC102  **No callback / transfer primitives.** ``pure_callback``,
       ``io_callback``, ``debug_callback``, infeed/outfeed and
       ``device_put`` inside the program mean a host round-trip per
       dispatch — exactly the per-date sync the one-XLA-program design
       exists to eliminate (PDQP / GPU-ADMM both attribute their
       throughput to a sync-free iteration loop).
GC103  **Stable output dtypes.** Every output leaf is the input float
       dtype or int32/bool — so executables cached per shape bucket
       can never disagree about result buffers.

All tracing is abstract: nothing executes, no backend kernel runs, so
the checks are a few hundred milliseconds on CPU and safe for tier-1.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

import jax

from porqua_tpu.analysis.lint import Finding

try:  # jax >= 0.5 moves the jaxpr types to jax.extend.core
    from jax.core import ClosedJaxpr, Jaxpr  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - version dependent
    from jax.extend.core import ClosedJaxpr, Jaxpr  # type: ignore

__all__ = [
    "check_closed_jaxpr",
    "check_devprof_identity",
    "check_entry_points",
    "check_observability_identity",
    "check_resilience_identity",
    "check_routing_identity",
    "check_run_batch",
    "check_telemetry_identity",
    "check_tenancy_identity",
    "compaction_step_jaxpr",
    "compaction_step_program",
    "continuous_jaxprs",
    "continuous_programs",
    "solve_batch_jaxpr",
    "solve_batch_program",
    "serve_entry_jaxpr",
    "serve_entry_program",
    "tracking_jaxpr",
    "tracking_program",
]

#: primitive names that imply a host round-trip or transfer
_BANNED_EXACT = {"device_put"}
_BANNED_SUBSTR = ("callback", "infeed", "outfeed")


def _iter_eqns(jaxpr: Jaxpr) -> Iterable:
    for eqn in jaxpr.eqns:
        yield eqn
        for param in eqn.params.values():
            for sub in _sub_jaxprs(param):
                yield from _iter_eqns(sub)


def _sub_jaxprs(param) -> Iterable[Jaxpr]:
    if isinstance(param, ClosedJaxpr):
        yield param.jaxpr
    elif isinstance(param, Jaxpr):
        yield param
    elif isinstance(param, (tuple, list)):
        for item in param:
            yield from _sub_jaxprs(item)


def _is_f64(aval) -> bool:
    dtype = getattr(aval, "dtype", None)
    return dtype is not None and dtype == np.float64


def check_closed_jaxpr(closed: ClosedJaxpr, label: str,
                       expect_float=np.float32) -> List[Finding]:
    """Assert the GC101/GC102/GC103 contracts on one traced program."""
    findings: List[Finding] = []
    path = f"<jaxpr:{label}>"

    def emit(rule: str, message: str) -> None:
        findings.append(Finding(rule, path, 0, 0, message))

    inputs_f64 = any(_is_f64(v.aval) for v in closed.jaxpr.invars)

    seen_f64: set = set()
    seen_banned: set = set()
    for eqn in _iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in _BANNED_EXACT or any(s in name for s in _BANNED_SUBSTR):
            if name not in seen_banned:
                seen_banned.add(name)
                emit("GC102", f"callback/transfer primitive {name!r} inside "
                              "the traced program: a host round-trip per "
                              "dispatch")
        if inputs_f64:
            continue  # an f64 caller opted in; dtype policing is moot
        if name == "convert_element_type" \
                and eqn.params.get("new_dtype") == np.float64 \
                and "convert" not in seen_f64:
            seen_f64.add("convert")
            emit("GC101", "convert_element_type to float64 inside a "
                          "float32 program (numpy scalar or x64 literal "
                          "leaking into the trace)")
        for ov in eqn.outvars:
            if _is_f64(getattr(ov, "aval", None)) and name not in seen_f64:
                seen_f64.add(name)
                emit("GC101", f"primitive {name!r} produces float64 inside "
                              "a float32 program")

    for i, ov in enumerate(closed.jaxpr.outvars):
        aval = getattr(ov, "aval", None)
        dtype = getattr(aval, "dtype", None)
        if dtype is None:
            continue
        if dtype == np.dtype(expect_float) or dtype == np.int32 \
                or dtype == np.bool_:
            continue
        if inputs_f64 and dtype == np.float64:
            continue
        emit("GC103", f"output {i} has dtype {dtype} (expected "
                      f"{np.dtype(expect_float).name}/int32/bool): shape-"
                      "bucketed executables must agree on result buffers")
    return findings


# ---------------------------------------------------------------------------
# entry-point tracers
# ---------------------------------------------------------------------------

def solve_batch_program(batch: int = 4, n: int = 16, m: int = 4,
                        factor_rows: Optional[int] = None,
                        params=None, dtype=np.float32):
    """The ``(fn, example_args)`` pair behind the batched solve —
    exactly what ``solve_qp_batch`` / ``solve_batch`` run (shared
    ``_solve_batch_impl``). The jaxpr contracts trace it; the HLO
    harvester (:mod:`porqua_tpu.analysis.hlo`) lowers the same closure
    through ``jit(...).lower(...).compile()`` so both planes check one
    program, not two reconstructions of it."""
    from porqua_tpu.qp.solve import (
        SolverParams, _solve_batch_impl, batch_shape_struct)

    params = SolverParams() if params is None else params
    struct = batch_shape_struct(batch, n, m, dtype=dtype,
                                factor_rows=factor_rows)
    return (lambda qp: _solve_batch_impl(qp, params)), (struct,)


def solve_batch_jaxpr(batch: int = 4, n: int = 16, m: int = 4,
                      factor_rows: Optional[int] = None,
                      params=None, dtype=np.float32) -> ClosedJaxpr:
    """Trace the batched solve exactly as ``solve_qp_batch`` /
    ``solve_batch`` run it (shared ``_solve_batch_impl``)."""
    fn, args = solve_batch_program(batch, n, m, factor_rows=factor_rows,
                                   params=params, dtype=dtype)
    return jax.make_jaxpr(fn)(*args)


def serve_entry_program(batch: int = 4, n: int = 16, m: int = 4,
                        factor_rows: Optional[int] = None,
                        params=None, dtype=np.float32):
    """``(fn, example_args)`` for the serving AOT executable body (the
    ``entry`` that ``aot_compile_batch`` lowers: batch solve +
    warm-start inputs)."""
    from porqua_tpu.qp.solve import (
        SolverParams, _solve_batch_impl, batch_shape_struct)

    params = SolverParams() if params is None else params
    struct = batch_shape_struct(batch, n, m, dtype=dtype,
                                factor_rows=factor_rows)
    x0 = jax.ShapeDtypeStruct((batch, n), dtype)
    y0 = jax.ShapeDtypeStruct((batch, m), dtype)
    return (lambda qp, xx, yy: _solve_batch_impl(qp, params, xx, yy)), \
        (struct, x0, y0)


def serve_entry_jaxpr(batch: int = 4, n: int = 16, m: int = 4,
                      factor_rows: Optional[int] = None,
                      params=None, dtype=np.float32) -> ClosedJaxpr:
    """Trace the serving AOT executable body (the ``entry`` that
    ``aot_compile_batch`` lowers: batch solve + warm-start inputs)."""
    fn, args = serve_entry_program(batch, n, m, factor_rows=factor_rows,
                                   params=params, dtype=dtype)
    return jax.make_jaxpr(fn)(*args)


def tracking_program(batch: int = 2, window: int = 8, n_assets: int = 6,
                     params=None, dtype=np.float32):
    """``(fn, example_args)`` for the flagship tracking backtest step
    (build + solve + evaluate in one program)."""
    from porqua_tpu.qp.solve import SolverParams
    from porqua_tpu.tracking import tracking_step

    params = SolverParams() if params is None else params
    Xs = jax.ShapeDtypeStruct((batch, window, n_assets), dtype)
    ys = jax.ShapeDtypeStruct((batch, window), dtype)
    return (lambda X, y: tracking_step(X, y, params)), (Xs, ys)


def tracking_jaxpr(batch: int = 2, window: int = 8, n_assets: int = 6,
                   params=None, dtype=np.float32) -> ClosedJaxpr:
    """Trace the flagship tracking backtest step (build + solve +
    evaluate in one program)."""
    fn, args = tracking_program(batch, window, n_assets,
                                params=params, dtype=dtype)
    return jax.make_jaxpr(fn)(*args)


def compaction_step_program(batch: int = 6, group: int = 4,
                            n: int = 16, m: int = 4,
                            factor_rows: Optional[int] = None,
                            params=None, dtype=np.float32):
    """``(fn, example_args)`` for the compaction driver's
    step-and-repack program exactly as
    :class:`porqua_tpu.compaction.CompactingDriver` compiles it: one
    segment over a ``group``-wide compacted lane set, the per-lane
    freeze/select, the scatter-back into the ``batch``-wide result
    buffer, and the stable active-first repack."""
    from porqua_tpu.compaction import step_and_repack
    from porqua_tpu.qp.solve import (
        SolverParams, batch_shape_struct, prepare_batch)

    params = SolverParams() if params is None else params
    qp_s = batch_shape_struct(batch, n, m, dtype=dtype,
                              factor_rows=factor_rows)
    scaled_s, scaling_s, carry_s, _, _ = jax.eval_shape(
        lambda q: prepare_batch(q, params), qp_s)
    take = lambda t: jax.tree.map(
        lambda s: jax.ShapeDtypeStruct((group,) + s.shape[1:], s.dtype), t)
    buf_s = carry_s.state
    idx_s = jax.ShapeDtypeStruct((group,), np.int32)
    segl_s = jax.ShapeDtypeStruct((group,), np.int32)
    group_s = (take(scaled_s), take(scaling_s), take(carry_s),
               None, None, idx_s, segl_s)
    return (lambda buf, grp: step_and_repack(buf, grp, params)), \
        (buf_s, group_s)


def compaction_step_jaxpr(batch: int = 6, group: int = 4,
                          n: int = 16, m: int = 4,
                          factor_rows: Optional[int] = None,
                          params=None, dtype=np.float32) -> ClosedJaxpr:
    """Trace the compaction driver's step-and-repack program. GC102 on
    this program is the machine-checked form of "the repack introduces
    no host syncs or transfers"."""
    fn, args = compaction_step_program(batch, group, n, m,
                                       factor_rows=factor_rows,
                                       params=params, dtype=dtype)
    return jax.make_jaxpr(fn)(*args)


def continuous_programs(batch: int = 4, n: int = 16, m: int = 4,
                        factor_rows: Optional[int] = None,
                        params=None, dtype=np.float32):
    """The continuous-batching executable triple (admit / step /
    finalize) — the same closures ``aot_compile_continuous`` lowers —
    as ``[(label, fn, example_args)]``."""
    from porqua_tpu.qp.solve import (
        SolverParams, batch_shape_struct, continuous_entries,
        prepare_batch)

    params = SolverParams() if params is None else params
    qp_s = batch_shape_struct(batch, n, m, dtype=dtype,
                              factor_rows=factor_rows)
    x0_s = jax.ShapeDtypeStruct((batch, n), dtype)
    y0_s = jax.ShapeDtypeStruct((batch, m), dtype)
    mask_s = jax.ShapeDtypeStruct((batch,), np.bool_)
    scaled_s, scaling_s, carry_s = jax.eval_shape(
        lambda q, x, y: prepare_batch(q, params, x, y)[:3],
        qp_s, x0_s, y0_s)
    admit, step, fin = continuous_entries(params)
    return [
        ("continuous_admit", admit,
         (qp_s, x0_s, y0_s, mask_s, scaled_s, scaling_s, carry_s)),
        ("continuous_step", step, (scaled_s, scaling_s, carry_s, mask_s)),
        ("continuous_finalize", fin,
         (qp_s, scaled_s, scaling_s, carry_s.state)),
    ]


def continuous_jaxprs(batch: int = 4, n: int = 16, m: int = 4,
                      factor_rows: Optional[int] = None,
                      params=None, dtype=np.float32):
    """Trace the continuous-batching executable triple (admit / step /
    finalize) as ``[(label, ClosedJaxpr)]``."""
    return [(label, jax.make_jaxpr(fn)(*args))
            for label, fn, args in continuous_programs(
                batch, n, m, factor_rows=factor_rows,
                params=params, dtype=dtype)]


def check_resilience_identity(dtype=np.float32) -> List[Finding]:
    """GC104: fault injection must be invisible to XLA.

    The resilience plane (:mod:`porqua_tpu.resilience`) promises its
    seams live strictly in host dispatch code — with the injector
    disabled the solve/serve programs are the pre-resilience ones,
    bit for bit. Source review can't prove that (a seam smuggled into
    a traced function behind ``jax.debug.callback`` or a trace-time
    host branch would *look* guarded); the jaxpr can. This check
    traces the solve-batch and serve entry points twice — once bare,
    once with a live injector installed whose scenario covers **every
    seam and fault kind** — and requires the two jaxprs to be
    string-identical. Any seam reachable from tracing would fire
    (raising kinds abort the trace, directive kinds perturb it), so
    identity is exactly the "no new primitives, no callbacks,
    bit-identical when disabled" contract, machine-checked.

    Requires no injector to be active (it installs its own); an
    installed one raises, which ``run_checks.py`` surfaces as an
    internal error rather than a clean pass.
    """
    from porqua_tpu.resilience import faults

    def trace_all():
        return [("solve_batch", str(solve_batch_jaxpr(dtype=dtype))),
                ("serve_entry", str(serve_entry_jaxpr(dtype=dtype)))]

    findings: List[Finding] = []
    baseline = trace_all()
    mk = faults.FaultSpec.make
    scenario = faults.Scenario("gc104-contract", (
        mk("serve.dispatch", "device_lost", count=1_000_000),
        mk("serve.continuous", "device_lost", count=1_000_000),
        mk("serve.result", "nan_lanes", count=1_000_000, lanes=1),
        mk("serve.admission", "clock_skew", count=1_000_000, skew_s=1.0),
        mk("health.probe", "probe_fail", count=1_000_000),
        mk("cache.get", "compile_storm", count=1_000_000),
        mk("data.feed", "feed_corrupt", count=1_000_000),
        mk("backtest.chunk", "crash", count=1_000_000),
    ))
    # Install OUTSIDE the trace try-block: a pre-installed injector is
    # a usage error (install raises RuntimeError) and must propagate as
    # such, not be misreported as a seam reachable from tracing.
    inj = faults.install(faults.FaultInjector(scenario))
    try:
        try:
            injected = trace_all()
            fired = inj.fires()
        except BaseException as exc:  # noqa: BLE001 - seam fired mid-trace
            return [Finding(
                "GC104", "<jaxpr:resilience_identity>", 0, 0,
                f"tracing with a live injector raised "
                f"{type(exc).__name__}: {exc} — a fault seam is "
                f"reachable from a traced program")]
    finally:
        faults.uninstall()
    if fired:
        findings.append(Finding(
            "GC104", "<jaxpr:resilience_identity>", 0, 0,
            f"{fired} fault seam hit(s) fired during tracing — seams "
            "must live strictly in host dispatch code"))
    for (label, base), (_, inj_str) in zip(baseline, injected):
        if base != inj_str:
            findings.append(Finding(
                "GC104", f"<jaxpr:{label}>", 0, 0,
                "traced program differs with a fault injector "
                "installed: the injector-disabled program is no longer "
                "the pre-resilience one (bit-identical-when-disabled "
                "contract broken)"))
    return findings


def check_telemetry_identity(dtype=np.float32) -> List[Finding]:
    """GC105: the telemetry warehouse must be invisible to XLA.

    The harvest/profiling plane (:mod:`porqua_tpu.obs.harvest`,
    :mod:`porqua_tpu.obs.profile`) promises it is pure host
    post-processing: records are built from arrays the producers
    already fetched, and stage brackets wrap dispatches from the
    OUTSIDE (``jax.profiler.TraceAnnotation`` — metadata, not
    program). "Harvest disabled = bit-identical program" is the
    acceptance bar; this check machine-verifies the enabled half:
    the solve/serve/compaction-step entry points are traced once
    bare, then again INSIDE a live :class:`StageProfiler` stage (its
    trace annotation active) with a live :class:`HarvestSink` in
    scope, and the jaxprs are required to be string-identical — no
    new primitives, no callbacks, no dtype drift.
    """
    from porqua_tpu.obs.harvest import HarvestSink, solve_record
    from porqua_tpu.obs.profile import StageProfiler
    from porqua_tpu.qp.solve import SolverParams

    ring_params = SolverParams(ring_size=4)

    def trace_all():
        return [
            ("solve_batch", str(solve_batch_jaxpr(dtype=dtype))),
            ("solve_batch[rings]", str(solve_batch_jaxpr(
                params=ring_params, dtype=dtype))),
            ("serve_entry", str(serve_entry_jaxpr(dtype=dtype))),
            ("compaction_step", str(compaction_step_jaxpr(dtype=dtype))),
        ]

    findings: List[Finding] = []
    baseline = trace_all()
    profiler = StageProfiler()
    sink = HarvestSink(path=None)
    with profiler.stage("gc105-contract"):
        telemetered = trace_all()
    # The sink must also demonstrably be pure host code: emitting a
    # record between traces cannot perturb the next trace.
    sink.emit(solve_record("batch", 4, 2, 1, 10, 0.0, 0.0, 0.0))
    post_emit = str(solve_batch_jaxpr(dtype=dtype))
    for (label, base), (_, tele) in zip(baseline, telemetered):
        if base != tele:
            findings.append(Finding(
                "GC105", f"<jaxpr:{label}>", 0, 0,
                "traced program differs inside an active StageProfiler "
                "stage: the telemetry plane is no longer invisible to "
                "XLA (harvest-disabled bit-identity contract broken)"))
    if post_emit != baseline[0][1]:
        findings.append(Finding(
            "GC105", "<jaxpr:solve_batch>", 0, 0,
            "traced program differs after a HarvestSink.emit — the "
            "sink leaked state into tracing"))
    if sink.records != 1 or sink.write_failures:
        findings.append(Finding(
            "GC105", "<jaxpr:telemetry_identity>", 0, 0,
            "in-memory HarvestSink did not record the probe emit"))
    return findings


def check_observability_identity(dtype=np.float32) -> List[Finding]:
    """GC106: the live SLO/flight/anomaly plane must be invisible to XLA.

    The live operational plane (:mod:`porqua_tpu.obs.slo`,
    :mod:`porqua_tpu.obs.flight`, :mod:`porqua_tpu.obs.anomaly`)
    promises it is pure host bookkeeping over counters and buffers the
    serve stack already maintains: burn rates from counter deltas,
    incident bundles from bounded rings, anomaly EWMAs from fetched
    integers — zero callbacks, zero transfers, zero program edits.
    This check machine-verifies the enabled half of "disabled ==
    bit-identical" (the runtime half is pinned by test): the
    solve / serve / compaction-step entry points are traced bare, then
    a FULLY LIVE plane is exercised — an SLO engine bound to real
    metrics fires a burn-rate alert on a stepped clock, the alert's
    ``slo_alert`` event trips a flight-recorder dump through a real
    event-bus listener, and an anomaly detector crosses its baseline
    band and fires — and the entry points are re-traced. The jaxprs
    must be string-identical.
    """
    from porqua_tpu.obs.anomaly import AnomalyDetector
    from porqua_tpu.obs.events import EventBus
    from porqua_tpu.obs.flight import FlightRecorder
    from porqua_tpu.obs.slo import SLOEngine, default_slos
    from porqua_tpu.resilience.faults import FaultClock
    from porqua_tpu.serve.metrics import ServeMetrics

    def trace_all():
        return [
            ("solve_batch", str(solve_batch_jaxpr(dtype=dtype))),
            ("serve_entry", str(serve_entry_jaxpr(dtype=dtype))),
            ("compaction_step", str(compaction_step_jaxpr(dtype=dtype))),
        ]

    findings: List[Finding] = []
    baseline = trace_all()

    clock = FaultClock()
    metrics = ServeMetrics()
    events = EventBus(capacity=1024)
    engine = SLOEngine(default_slos(), clock=clock,
                       min_eval_interval_s=0.0).bind(metrics,
                                                     events=events)
    flight = FlightRecorder(out_dir=None, debounce_s=0.0, clock=clock)
    flight.attach(metrics=metrics, slo=engine)
    events.add_listener(flight.on_event)
    detector = AnomalyDetector.from_aggregate(
        {"groups": [{"bucket": "16x4", "eps_abs": 1e-3,
                     "iters": {"p50": 50.0, "p95": 100.0, "max": 150.0},
                     "wasted_iteration_fraction": 0.1, "count": 64}]},
        min_samples=4, events=events)
    # Drive the plane hot: a hard availability breach across two
    # evaluations (so the windows have a real delta), plus anomaly
    # observations far past the baseline band.
    engine.evaluate()
    metrics.inc("completed", 5)
    metrics.inc("failed", 95)
    clock.advance(10.0)
    engine.evaluate()
    for _ in range(8):
        detector.observe("16x4", 1e-3, iters=5000, segments=200,
                         check_interval=25)
    live = trace_all()
    post = str(solve_batch_jaxpr(dtype=dtype))

    if engine.status()["alerts_fired"] < 1 or not flight.bundles():
        findings.append(Finding(
            "GC106", "<jaxpr:observability_identity>", 0, 0,
            "the live-plane probe did not exercise itself (no alert "
            "fired or no bundle dumped) — the identity check proved "
            "nothing"))
    if detector.status()["fired"] < 1:
        findings.append(Finding(
            "GC106", "<jaxpr:observability_identity>", 0, 0,
            "the anomaly-detector probe never crossed its baseline "
            "band — the identity check proved nothing"))
    for (label, base), (_, lv) in zip(baseline, live):
        if base != lv:
            findings.append(Finding(
                "GC106", f"<jaxpr:{label}>", 0, 0,
                "traced program differs with the live SLO/flight/"
                "anomaly plane active: the plane is no longer "
                "invisible to XLA (disabled-bit-identity contract "
                "broken)"))
    if post != baseline[0][1]:
        findings.append(Finding(
            "GC106", "<jaxpr:solve_batch>", 0, 0,
            "traced program differs after a flight-recorder dump — "
            "the incident plane leaked state into tracing"))
    return findings


def check_devprof_identity(dtype=np.float32) -> List[Finding]:
    """GC107: the device-truth cost plane must be invisible to XLA.

    The cost warehouse (:mod:`porqua_tpu.obs.devprof`) promises it is
    strictly post-compile host bookkeeping: ``cost_analysis()`` /
    ``memory_analysis()`` / ``as_text()`` are read off an
    already-compiled executable, the CostRecord is a dict, and the
    measured profile (:func:`porqua_tpu.obs.profile.qp_solve_profile`
    with ``cost=``) is float arithmetic — zero callbacks, zero
    transfers, zero program edits on any jitted entry. This check
    machine-verifies the enabled half of "disabled == bit-identical"
    (the runtime half is pinned by ``tests/test_devprof.py``): the
    solve/serve entry points are traced bare, then the plane is
    exercised FOR REAL — a probe program is AOT-compiled, its cost and
    memory analyses harvested into a CostRecord, the record emitted
    through a live :class:`CostLog`, and a measured (``cost_source:
    "xla"``) profile computed from it — and the entry points are
    re-traced. The jaxprs must be string-identical, and the probe must
    actually have harvested (an empty record would prove nothing).
    """
    import jax.numpy as jnp

    from porqua_tpu.obs.devprof import CostLog, cost_record
    from porqua_tpu.obs.profile import qp_solve_profile

    def trace_all():
        return [("solve_batch", str(solve_batch_jaxpr(dtype=dtype))),
                ("serve_entry", str(serve_entry_jaxpr(dtype=dtype)))]

    findings: List[Finding] = []
    baseline = trace_all()

    # Drive the plane hot: a real AOT compile -> harvest -> log ->
    # measured profile. The probe program is tiny (one 8x8 matmul) so
    # the contract stays CI-cheap; the harvesting path it exercises is
    # exactly the one ExecutableCache._build runs per executable.
    probe = jax.jit(lambda a: a @ a).lower(
        jax.ShapeDtypeStruct((8, 8), jnp.dtype(dtype))).compile()
    log = CostLog(path=None)
    rec = cost_record(probe, entry="gc107-probe", kind="contract",
                      bucket="8x8", slots=1,
                      dtype=np.dtype(dtype).str, compile_s=0.0)
    log.emit(rec)
    prof = qp_solve_profile(8, 8, 10.0, 0.01, cost=rec)
    live = trace_all()

    if rec.get("flops") is None and rec.get("bytes_accessed") is None:
        findings.append(Finding(
            "GC107", "<jaxpr:devprof_identity>", 0, 0,
            "the probe executable yielded no cost analysis on this "
            "backend — the identity check exercised nothing"))
    if log.records != 1 or prof.get("cost_source") != "xla":
        findings.append(Finding(
            "GC107", "<jaxpr:devprof_identity>", 0, 0,
            "the cost-plane probe did not run end to end (no record "
            "logged or the profile never switched to XLA numerators) "
            "— the identity check proved nothing"))
    for (label, base), (_, lv) in zip(baseline, live):
        if base != lv:
            findings.append(Finding(
                "GC107", f"<jaxpr:{label}>", 0, 0,
                "traced program differs with the device-truth cost "
                "plane active: cost harvesting is no longer invisible "
                "to XLA (disabled-bit-identity contract broken)"))
    return findings


def check_federation_identity(dtype=np.float32) -> List[Finding]:
    """GC108: the fleet federation plane must be invisible to XLA.

    The federation plane (:mod:`porqua_tpu.obs.federation`,
    :mod:`porqua_tpu.obs.vitals`, :mod:`porqua_tpu.obs.ledger`)
    promises it is pure host file/dict bookkeeping: worker emitters
    write JSONL, the collector merges counters and raw histograms,
    liveness and vitals trends are float arithmetic — zero callbacks,
    zero transfers, zero program edits on any jitted entry. This check
    machine-verifies the enabled half of "disabled == bit-identical"
    (the runtime half is pinned by ``tests/test_federation.py``): the
    solve/serve entry points are traced bare, then the plane is
    exercised FOR REAL — two worker streams written and drained, fleet
    counters and raw histograms merged, the fleet SLO engine evaluated
    on a stepped clock, a vitals leak trended to firing, one worker's
    stream left to go stale so ``worker_lost`` fires and dumps a fleet
    incident bundle through a real event-bus listener — and the entry
    points are re-traced. The jaxprs must be string-identical, and the
    probe self-verifies it actually exercised the plane (a collector
    that never lost the worker or never dumped proves nothing).
    """
    import os
    import tempfile

    from porqua_tpu.obs.federation import FleetCollector, WorkerStream
    from porqua_tpu.obs.flight import FlightRecorder
    from porqua_tpu.obs.ledger import ledger_row, rolling_median
    from porqua_tpu.obs.slo import SLOEngine, default_slos
    from porqua_tpu.obs.vitals import VitalsTrend
    from porqua_tpu.resilience.faults import FaultClock

    def trace_all():
        return [("solve_batch", str(solve_batch_jaxpr(dtype=dtype))),
                ("serve_entry", str(serve_entry_jaxpr(dtype=dtype)))]

    findings: List[Finding] = []
    baseline = trace_all()

    with tempfile.TemporaryDirectory() as td:
        clock = FaultClock()
        flight = FlightRecorder(out_dir=None, debounce_s=0.0,
                                clock=clock)
        engine = SLOEngine(default_slos(), clock=clock,
                           min_eval_interval_s=0.0)
        trend = VitalsTrend(min_samples=4, alpha_fast=0.6,
                            alpha_slow=0.05)
        collector = FleetCollector(
            heartbeat_timeout_s=2.0, rollup_window_s=1.0,
            slo=engine, flight=flight, vitals_trend=trend, clock=clock)
        streams = {}
        for wid in ("w0", "w1"):
            path = os.path.join(td, f"{wid}.jsonl")
            collector.add_worker(wid, path)
            streams[wid] = WorkerStream(path, wid)
            streams[wid].hello(latency_le=[0.01, 0.1])

        def sample(completed, failed, counts, rss):
            return dict(
                slo={"completed": completed, "failed": failed,
                     "expired": 0, "retry_giveups": 0,
                     "validation_failures": 0,
                     "latency_le": (0.01, 0.1),
                     "latency_counts": tuple(counts),
                     "latency_count": sum(counts)},
                vitals={"rss_bytes": rss, "threads": 4})

        streams["w0"].sample(**sample(5, 0, [3, 2, 0], 1000))
        for i in range(8):
            # w1 keeps heartbeating with a leaking RSS while w0 goes
            # silent — the liveness deadline and the vitals trend both
            # cross inside this loop.
            clock.advance(1.0)
            streams["w1"].sample(**sample(10 + i, 0, [6, 4, i],
                                          1000 * (1.4 ** i)))
            collector.drain()
        merged = collector.slo_sample()
        row = ledger_row("fleet_loadgen",
                         {"fleet.completed": merged["completed"]})
        med = rolling_median([row], "fleet.completed", window=3)
    live = trace_all()

    rows = {r["worker"]: r for r in collector.worker_rows()}
    bundle_kinds = [b["trigger"]["kind"] for b in flight.bundles()]
    if (not rows.get("w0", {}).get("status") == "lost"
            or "worker_lost" not in bundle_kinds):
        findings.append(Finding(
            "GC108", "<jaxpr:federation_identity>", 0, 0,
            "the federation probe never lost its stale worker or "
            "never dumped the worker_lost bundle — the identity check "
            "proved nothing"))
    if merged["completed"] != 22 or merged["latency_counts"][0] != 9:
        findings.append(Finding(
            "GC108", "<jaxpr:federation_identity>", 0, 0,
            "the collector merge produced wrong fleet counters — the "
            "identity check exercised a broken plane"))
    if trend.status()["fired"] < 1:
        findings.append(Finding(
            "GC108", "<jaxpr:federation_identity>", 0, 0,
            "the vitals-trend probe never crossed its leak band — the "
            "identity check proved nothing"))
    if med != float(merged["completed"]):
        findings.append(Finding(
            "GC108", "<jaxpr:federation_identity>", 0, 0,
            "the ledger probe did not round-trip its row — the "
            "identity check exercised a broken plane"))
    for (label, base), (_, lv) in zip(baseline, live):
        if base != lv:
            findings.append(Finding(
                "GC108", f"<jaxpr:{label}>", 0, 0,
                "traced program differs with the fleet federation "
                "plane active: the plane is no longer invisible to "
                "XLA (disabled-bit-identity contract broken)"))
    return findings


def check_tenancy_identity(dtype=np.float32) -> List[Finding]:
    """GC109: the tenant plane must be invisible to XLA.

    Tenancy (:mod:`porqua_tpu.serve.tenancy`, the per-tenant metrics
    axis, :class:`porqua_tpu.obs.slo.TenantSLOSet`, the workload
    library :mod:`porqua_tpu.serve.workloads`) promises it is
    host-side scheduling + attribution ONLY: quotas shed at submit,
    DRR reorders host deques, per-tenant counters/histograms/engines
    are dict arithmetic, and workload blends are numpy built before
    the clock starts — requests from different tenants coalesce into
    the same compiled batches, and no program carries a tenant. This
    check machine-verifies the enabled half of "tenancy disabled ==
    bit-identical" (the runtime half is pinned by
    ``tests/test_tenancy.py``): the solve/serve entry points are
    traced bare, then the tenant plane is exercised FOR REAL — a
    quota shed, a DRR interleave across a 10:1 backlog imbalance, a
    per-tenant burn-rate alert fired on a stepped clock (its event
    carrying the tenant label), a tenant-tagged SolveRecord, and a
    seeded three-tenant workload blend — and the entry points are
    re-traced. The jaxprs must be string-identical, and every probe
    self-verifies it actually exercised its path (a shed that never
    shed proves nothing).
    """
    from porqua_tpu.obs.events import EventBus
    from porqua_tpu.obs.harvest import solve_record
    from porqua_tpu.obs.slo import TenantSLOSet
    from porqua_tpu.resilience.faults import FaultClock
    from porqua_tpu.serve.metrics import ServeMetrics
    from porqua_tpu.serve.tenancy import FairPendingQueue, TenantAdmission
    from porqua_tpu.serve.workloads import (
        build_blend, parse_tenant_specs)

    def trace_all():
        return [("solve_batch", str(solve_batch_jaxpr(dtype=dtype))),
                ("serve_entry", str(serve_entry_jaxpr(dtype=dtype)))]

    findings: List[Finding] = []
    baseline = trace_all()

    def probe_fail(msg: str) -> None:
        findings.append(Finding(
            "GC109", "<jaxpr:tenancy_identity>", 0, 0, msg))

    # Quota shed: the offender hits its bound, the victim never does.
    admission = TenantAdmission(quota={"noisy": 2})
    sheds = sum(not admission.try_admit("noisy") for _ in range(5))
    victim_ok = all(admission.try_admit("quiet") for _ in range(5))
    if sheds != 3 or not victim_ok:
        probe_fail("the quota probe did not shed exactly the "
                   "offender's overflow — the identity check "
                   "exercised a broken admission plane")

    # DRR interleave: a 10:1 backlog imbalance still alternates
    # tenants 1:1 at equal weights.
    class _Req:
        def __init__(self, tenant, i):
            self.tenant, self.submitted = tenant, float(i)

    fq = FairPendingQueue()
    for i in range(10):
        fq.append(_Req("noisy", i))
    fq.append(_Req("quiet", 100.0))
    first_four = [fq.popleft().tenant for _ in range(4)]
    if "quiet" not in first_four[:2]:
        probe_fail(f"DRR probe served {first_four} — the quiet "
                   "tenant waited behind the burst backlog")

    # Per-tenant SLO engines on a stepped clock: the offender's
    # availability alert fires WITH its tenant label; the victim's
    # engine stays quiet.
    clock = FaultClock()
    metrics = ServeMetrics()
    events = EventBus(capacity=256)
    tset = TenantSLOSet(clock=clock, min_eval_interval_s=0.0)
    tset.bind(metrics, events=events)
    for t in ("noisy", "quiet"):
        metrics.inc_tenant(t, "completed")
    tset.evaluate()
    metrics.inc_tenant("noisy", "completed", 2)
    metrics.inc_tenant("noisy", "rejected", 98)
    metrics.inc_tenant("quiet", "completed", 100)
    metrics.observe_tenant_latency("quiet", 0.004)
    clock.advance(10.0)
    tset.evaluate()
    fired = tset.alerts_fired()
    alert_tenants = {e.get("tenant")
                     for e in events.events("slo_alert")
                     if e.get("state") == "firing"}
    if fired.get("noisy", 0) < 1 or fired.get("quiet", 0) != 0 \
            or alert_tenants != {"noisy"}:
        probe_fail("the per-tenant SLO probe did not fire exactly "
                   "the offender's tenant-labeled alert "
                   f"(fired={fired}, labels={alert_tenants})")

    # Tenant-tagged SolveRecord + a seeded workload blend (numpy).
    rec = solve_record("serve", 4, 2, 1, 10, 0.0, 0.0, 0.0,
                       tenant="noisy")
    blend = build_blend(parse_tenant_specs(
        "a:tracking:steady:rate=20,n_assets=4,window=8,pool=2;"
        "b:lad:heavy_tailed:rate=10,n_assets=4,window=8,pool=2;"
        "c:turnover:bursty:rate=5,n_assets=4,window=8,pool=2"),
        duration_s=2.0, seed=1)
    if rec.get("tenant") != "noisy" or len(blend) < 3 \
            or len(blend.shares()) != 3:
        probe_fail("the harvest/workload probe did not produce a "
                   "tenant-tagged record and a three-tenant blend")

    live = trace_all()
    for (label, base), (_, lv) in zip(baseline, live):
        if base != lv:
            findings.append(Finding(
                "GC109", f"<jaxpr:{label}>", 0, 0,
                "traced program differs with the tenant plane "
                "exercised: tenancy is no longer host-side "
                "scheduling + attribution only (disabled-bit-identity "
                "contract broken)"))
    return findings


def check_routing_identity(dtype=np.float32) -> List[Finding]:
    """GC110: solver routing must be invisible to XLA.

    The :class:`porqua_tpu.serve.routing.SolverRouter` promises it is
    host-side dispatch selection ONLY: it picks WHICH pre-compiled
    executable a batch runs (per-(bucket, eps) table, harvest-seeded,
    force-pinnable), it never changes what any executable computes.
    This check machine-verifies the enabled half of "routing disabled
    == bit-identical": the solve/serve entry points are traced bare
    (for EVERY backend — the routed programs), then a live router is
    exercised for real — per-bucket decisions taken against a seeded
    table, a winner seeded from a harvest aggregate, a
    force() flip, a snapshot — and the entry points are re-traced.
    The jaxprs must be string-identical, and the probe self-verifies
    it actually routed (a table that seeded nothing, or decisions
    that never consulted it, prove nothing).
    """
    import dataclasses

    from porqua_tpu.qp.solve import SolverParams
    from porqua_tpu.serve.bucketing import Bucket
    from porqua_tpu.serve.routing import SolverRouter

    params = SolverParams()

    def trace_all():
        out = []
        for method in ("admm", "pdhg", "napg"):
            p = dataclasses.replace(params, method=method)
            out.append((f"solve_batch[{method}]",
                        str(solve_batch_jaxpr(params=p, dtype=dtype))))
            out.append((f"serve_entry[{method}]",
                        str(serve_entry_jaxpr(params=p, dtype=dtype))))
        return out

    findings: List[Finding] = []
    baseline = trace_all()

    def probe_fail(msg: str) -> None:
        findings.append(Finding(
            "GC110", "<jaxpr:routing_identity>", 0, 0, msg))

    # A live router, exercised end to end on the host: seed a route
    # table from a two-backend aggregate (PDHG the clear winner at
    # 16x4), take decisions on the seeded cell AND an unseeded one,
    # flip the force pin both ways.
    router = SolverRouter(params)
    eps = float(params.eps_abs)
    agg = {"groups": [{
        "bucket": "16x4", "eps_abs": eps,
        "by_solver": {
            "admm": {"count": 8, "iters": {"p95": 900.0},
                     "status_counts": {"1": 8}, "solve_s_mean": 4e-3},
            "pdhg": {"count": 8, "iters": {"p95": 200.0},
                     "status_counts": {"1": 8}, "solve_s_mean": 1e-3},
        }}]}
    seeded = router.seed_from_aggregate(agg)
    routed = router.route(Bucket(16, 4))
    default = router.route(Bucket(32, 8))
    router.force("admm")
    forced = router.route(Bucket(16, 4))
    router.force(None)
    unpinned = router.route(Bucket(16, 4))
    snap = router.snapshot()
    if seeded != {f"16x4@{eps:.0e}": "pdhg"} or routed != "pdhg" \
            or default != "admm" or forced != "admm" \
            or unpinned != "pdhg" or snap["decisions"]["pdhg"] != 2:
        probe_fail("the routing probe did not seed and take the "
                   "expected decisions — the identity check exercised "
                   f"a broken router (seeded={seeded}, snap={snap})")

    live = trace_all()
    for (label, base), (_, lv) in zip(baseline, live):
        if base != lv:
            findings.append(Finding(
                "GC110", f"<jaxpr:{label}>", 0, 0,
                "traced program differs with a live SolverRouter "
                "exercised: routing is no longer host-side dispatch "
                "selection only (disabled-bit-identity contract "
                "broken)"))
    return findings


def check_calibration_identity(dtype=np.float32) -> List[Finding]:
    """GC111: the closed calibration loop must be invisible to XLA.

    The :class:`porqua_tpu.obs.calibrate.Calibrator` closes the
    telemetry→action loop — live shadow evidence folded into rolling
    per-cell statistics, a staged promotion swapping the router's
    versioned route table, a guard window auto-reverting on drift.
    All of it is host-side dispatch SELECTION: it may only ever change
    which prewarmed executable a batch runs on. This check traces
    every backend's solve/serve entry points bare, then drives a live
    calibrator through the ENTIRE lifecycle on a stepped clock —
    evidence ingested (valid + rejected records), a candidate gated
    into canary, a promotion (version bump), a guard breach, the
    auto-rollback (another version bump), the audit chain replayed —
    and re-traces MID-LIFECYCLE (canary held) and after. Every jaxpr
    must be string-identical, and the probe self-verifies each
    transition actually happened (a calibrator that never promoted
    proves nothing).
    """
    import dataclasses

    from porqua_tpu.obs.calibrate import Calibrator, replay_audit
    from porqua_tpu.obs.events import EventBus
    from porqua_tpu.obs.harvest import HarvestSink, solve_record
    from porqua_tpu.qp.solve import SolverParams
    from porqua_tpu.resilience.faults import FaultClock
    from porqua_tpu.serve.routing import SolverRouter

    params = SolverParams()

    def trace_all():
        out = []
        for method in ("admm", "pdhg", "napg"):
            p = dataclasses.replace(params, method=method)
            out.append((f"solve_batch[{method}]",
                        str(solve_batch_jaxpr(params=p, dtype=dtype))))
            out.append((f"serve_entry[{method}]",
                        str(serve_entry_jaxpr(params=p, dtype=dtype))))
        return out

    findings: List[Finding] = []
    baseline = trace_all()

    def probe_fail(msg: str) -> None:
        findings.append(Finding(
            "GC111", "<jaxpr:calibration_identity>", 0, 0, msg))

    class _GuardAnomaly:
        """Anomaly-counter stand-in the probe flips to breach the
        guard window deterministically (the real detector's counters()
        shape)."""

        fired = 0

        def counters(self):
            return {"anomalies_fired": self.fired}

    clock = FaultClock()
    router = SolverRouter(params)
    events = EventBus()
    sink = HarvestSink()
    guard = _GuardAnomaly()
    cal = Calibrator(router=router, harvest=sink, events=events,
                     anomaly=guard, min_interval_s=1.0, min_samples=4,
                     win_rate=0.6, canary_dwell_s=2.0,
                     guard_window_s=30.0, clock=clock)
    eps = float(params.eps_abs)
    p_admm = dataclasses.replace(params, method="admm")
    p_pdhg = dataclasses.replace(params, method="pdhg")
    for _ in range(6):
        cal.observe(solve_record(
            "serve", 16, 4, 1, 40, 1e-6, 1e-6, -1.0, params=p_admm,
            bucket="16x4", solve_s=4e-3))
        cal.observe(solve_record(
            "serve.shadow", 16, 4, 1, 12, 1e-6, 1e-6, -1.0,
            params=p_pdhg, bucket="16x4", solve_s=1e-3,
            shadow_of="admm", delta_iters=-28, delta_obj=0.0,
            agree=True, delta_solve_s=-3e-3))
    rejected = cal.observe(solve_record(
        "serve", 16, 4, 1, 40, 1e-6, 1e-6, float("nan"),
        params=p_admm, bucket="16x4"))
    clock.advance(1.5)
    cal.maybe_tick()
    if rejected is not False or cal.status()["state"] != "canary":
        probe_fail("the calibration probe did not reach canary with "
                   "the poison record rejected — the identity check "
                   f"exercised a broken loop (status={cal.status()})")

    # Mid-promotion: the candidate is live, the dwell is running.
    mid = trace_all()

    clock.advance(2.5)
    cal.maybe_tick()   # dwell held -> promoted, guard window opens
    promoted = router.snapshot()
    guard.fired = 1    # policy-induced drift: breach the guard
    clock.advance(1.5)
    cal.maybe_tick()   # breach -> auto-rollback
    snap = router.snapshot()
    table, version = replay_audit(cal.audit_records())
    counters = cal.counters()
    if (promoted["table"] != {f"16x4@{eps:.0e}": "pdhg"}
            or promoted["table_version"] != 1
            or snap["table"] != {} or snap["table_version"] != 2
            or (table, version) != (snap["table"], 2)
            or counters["calibration_promotions"] != 1
            or counters["calibration_rollbacks"] != 1):
        probe_fail("the calibration probe did not promote, roll back "
                   "and replay its audit chain as expected — the "
                   "identity check exercised a broken loop "
                   f"(promoted={promoted}, snap={snap}, "
                   f"counters={counters})")

    live = trace_all()
    for traced in (mid, live):
        for (label, base), (_, lv) in zip(baseline, traced):
            if base != lv:
                findings.append(Finding(
                    "GC111", f"<jaxpr:{label}>", 0, 0,
                    "traced program differs with a live Calibrator "
                    "mid-promotion: calibration is no longer "
                    "host-side dispatch selection only (disabled-"
                    "bit-identity contract broken)"))
                break
    return findings


def run_batch_jaxpr(bs, params=None, dtype=np.float32) -> ClosedJaxpr:
    """Trace ``run_batch``'s device core against a *real*
    ``BacktestService``: the host pass (``build_problems``) runs for
    real, then the device pass (``solve_batch``) is traced abstractly
    over the resulting problem shapes."""
    import dataclasses

    from porqua_tpu.batch import build_problems, solve_batch

    problems = build_problems(bs, dtype=dtype)
    if params is None:
        params = bs.optimization.solver_params(solve_dtype=dtype)
    abstract_qp = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), problems.qp)
    return jax.make_jaxpr(
        lambda qp: solve_batch(dataclasses.replace(problems, qp=qp), params)
    )(abstract_qp)


def check_run_batch(bs, params=None, dtype=np.float32) -> List[Finding]:
    return check_closed_jaxpr(run_batch_jaxpr(bs, params, dtype),
                              "run_batch", expect_float=dtype)


def check_entry_points(dtype=np.float32,
                       factor_rows: int = 8,
                       ring_size: int = 8) -> List[Finding]:
    """The CI sweep: every entry point reachable without market data.

    Each batch entry is traced twice — default params AND with the
    convergence rings enabled (``SolverParams(ring_size=...)``) — so
    the telemetry-enabled program carries the same proofs as the
    default one: no host callbacks/transfers (GC102 — the rings are
    recorded with zero host syncs, and this is where that claim is
    machine-checked), no f64 leaks, stable output dtypes.
    """
    from porqua_tpu.qp.solve import SolverParams

    findings: List[Finding] = []
    findings += check_closed_jaxpr(
        solve_batch_jaxpr(dtype=dtype), "solve_batch", expect_float=dtype)
    findings += check_closed_jaxpr(
        solve_batch_jaxpr(factor_rows=factor_rows, dtype=dtype),
        "solve_batch[factored]", expect_float=dtype)
    findings += check_closed_jaxpr(
        serve_entry_jaxpr(dtype=dtype), "serve_entry", expect_float=dtype)
    findings += check_closed_jaxpr(
        serve_entry_jaxpr(factor_rows=factor_rows, dtype=dtype),
        "serve_entry[factored]", expect_float=dtype)
    findings += check_closed_jaxpr(
        tracking_jaxpr(dtype=dtype), "tracking_step", expect_float=dtype)
    if ring_size:
        ring_params = SolverParams(ring_size=ring_size)
        findings += check_closed_jaxpr(
            solve_batch_jaxpr(params=ring_params, dtype=dtype),
            "solve_batch[rings]", expect_float=dtype)
        findings += check_closed_jaxpr(
            serve_entry_jaxpr(params=ring_params, dtype=dtype),
            "serve_entry[rings]", expect_float=dtype)
    # Compaction / continuous-batching entry points: the segment step,
    # the device-side repack + scatter-back, and the admit/finalize
    # programs must stay free of host callbacks/transfers (GC102 here
    # is the machine-checked form of "the repack introduces no host
    # syncs") with stable dtypes across compacted widths.
    findings += check_closed_jaxpr(
        compaction_step_jaxpr(dtype=dtype), "compaction_step",
        expect_float=dtype)
    findings += check_closed_jaxpr(
        compaction_step_jaxpr(factor_rows=factor_rows, dtype=dtype),
        "compaction_step[factored]", expect_float=dtype)
    for label, jaxpr in continuous_jaxprs(dtype=dtype):
        findings += check_closed_jaxpr(jaxpr, label, expect_float=dtype)
    # GC104: the fault-injection plane must be invisible to XLA — the
    # solve/serve jaxprs with an injector installed are required to be
    # string-identical to the bare ones (no new primitives, no
    # callbacks, bit-identical when disabled).
    findings += check_resilience_identity(dtype=dtype)
    # GC105: same identity bar for the telemetry warehouse — tracing
    # inside a live StageProfiler stage with a HarvestSink in scope
    # must produce string-identical programs (harvest/profiling is
    # host post-processing, never traced work).
    findings += check_telemetry_identity(dtype=dtype)
    # GC106: and for the live operational plane — a firing SLO alert,
    # a flight-recorder dump, and an anomaly-detector breach must all
    # leave the traced solve/serve/compaction programs string-
    # identical (the whole plane is counters-and-rings host code).
    findings += check_observability_identity(dtype=dtype)
    # GC107: and for the device-truth cost plane — harvesting a real
    # executable's cost/memory analysis into a CostRecord, logging it,
    # and computing a measured profile from it must leave the traced
    # solve/serve programs string-identical (the plane reads compiled
    # objects, never traced ones).
    findings += check_devprof_identity(dtype=dtype)
    # GC108: and for the fleet federation plane — worker streams
    # written and drained, counters/raw-histograms merged, a worker
    # lost to the liveness deadline, a fleet incident bundle dumped,
    # a vitals leak trended to firing, a ledger row round-tripped —
    # all of it must leave the traced solve/serve programs string-
    # identical (the plane is host file/dict code end to end).
    findings += check_federation_identity(dtype=dtype)
    # GC109: and for the tenant plane — a quota shed, a DRR
    # interleave, a tenant-labeled burn-rate alert, a tenant-tagged
    # harvest record, and a seeded workload blend must all leave the
    # traced solve/serve programs string-identical (tenancy is
    # host-side scheduling + attribution only).
    findings += check_tenancy_identity(dtype=dtype)
    # The PDHG backend's programs carry the same GC101-103 proofs as
    # ADMM's — the routed executables are peers, not exceptions: the
    # restarted segment stepper is sync-free, f64-free, and lands the
    # same output dtypes through the shared finalize/compaction/
    # continuous plumbing.
    pdhg = SolverParams(method="pdhg")
    findings += check_closed_jaxpr(
        solve_batch_jaxpr(params=pdhg, dtype=dtype),
        "solve_batch[pdhg]", expect_float=dtype)
    findings += check_closed_jaxpr(
        serve_entry_jaxpr(params=pdhg, dtype=dtype),
        "serve_entry[pdhg]", expect_float=dtype)
    if ring_size:
        findings += check_closed_jaxpr(
            solve_batch_jaxpr(
                params=SolverParams(method="pdhg", ring_size=ring_size),
                dtype=dtype),
            "solve_batch[pdhg,rings]", expect_float=dtype)
    findings += check_closed_jaxpr(
        compaction_step_jaxpr(params=pdhg, dtype=dtype),
        "compaction_step[pdhg]", expect_float=dtype)
    for label, jaxpr in continuous_jaxprs(params=pdhg, dtype=dtype):
        findings += check_closed_jaxpr(
            jaxpr, f"{label}[pdhg]", expect_float=dtype)
    # And the NAPG backend's — the third routed peer: the accelerated
    # projected-gradient stepper (one P-apply + the row-prox bisection
    # per iteration) must clear the same sync-free/f64-free/dtype bars
    # through the same shared plumbing.
    napg = SolverParams(method="napg")
    findings += check_closed_jaxpr(
        solve_batch_jaxpr(params=napg, dtype=dtype),
        "solve_batch[napg]", expect_float=dtype)
    findings += check_closed_jaxpr(
        serve_entry_jaxpr(params=napg, dtype=dtype),
        "serve_entry[napg]", expect_float=dtype)
    if ring_size:
        findings += check_closed_jaxpr(
            solve_batch_jaxpr(
                params=SolverParams(method="napg", ring_size=ring_size),
                dtype=dtype),
            "solve_batch[napg,rings]", expect_float=dtype)
    findings += check_closed_jaxpr(
        compaction_step_jaxpr(params=napg, dtype=dtype),
        "compaction_step[napg]", expect_float=dtype)
    for label, jaxpr in continuous_jaxprs(params=napg, dtype=dtype):
        findings += check_closed_jaxpr(
            jaxpr, f"{label}[napg]", expect_float=dtype)
    # GC110: and for solver routing — a harvest-seeded route table
    # consulted per bucket, a force() flip, a snapshot — all of it
    # must leave every backend's traced solve/serve programs string-
    # identical (routing picks which compiled program runs, it never
    # touches a traced one).
    findings += check_routing_identity(dtype=dtype)
    # GC111: and for the closed calibration loop — evidence folded,
    # a candidate promoted through canary, a guard breach rolled back,
    # the audit chain replayed — all of it must leave every backend's
    # traced solve/serve programs string-identical (calibration only
    # ever picks which prewarmed executable runs).
    findings += check_calibration_identity(dtype=dtype)
    return findings
