"""graftcheck: JAX-aware AST lint rules for the device-discipline invariants.

The whole TPU rebuild rests on conventions no Python runtime enforces:
every objective-bearing contraction pinned to ``Precision.HIGHEST``
(the MXU computes f32 matmuls in bf16 passes by default — README,
round-4 chip findings), zero host<->device syncs inside jit-reachable
code, zero steady-state recompiles in the serving path, and no
backend-initializing work at import time. Round-5 review found three
fresh precision-pin violations in freshly written code — human review
does not scale, so this module makes the conventions machine-checked.

Rules (each suppressible per line with ``# graftcheck: disable=GC00x``
or per file with ``# graftcheck: disable-file=GC00x``):

GC001  Unpinned contraction (``jnp.dot``/``einsum``/``matmul``/
       ``tensordot``/``inner``/``vdot`` without ``precision=``, or the
       ``@`` operator on jnp-derived operands) inside the precision-
       policy modules: ``qp/``, ``tracking.py``, ``estimators/``,
       ``accounting.py``. Host numpy contractions are exempt (numpy
       computes f32 at full precision; the rule tracks jnp taint).
GC002  Host-sync hazard inside jit-reachable code: ``.item()``,
       ``.block_until_ready()``, ``float()/int()/bool()`` on non-
       literals, host ``np.*`` calls, ``jax.device_get``. Jit-reachable
       is computed, not guessed: functions decorated with / passed to
       ``jax.jit``/``vmap``/``pmap``/``grad``/``lax.scan`` etc. are
       roots, and the rule walks the call graph (same-module names,
       ``from x import y`` bindings, module-alias attributes) across
       every file in the scan.
GC003  Recompile hazard: ``jax.jit`` constructed inside a loop
       (anywhere), or inside a function body in a steady-state module
       (``qp/``, ``serve/``, ``ops/``, ``tracking.py``, ``batch.py``,
       ``backtest.py``, ``accounting.py``) without a caching idiom —
       immediate ``.lower(...)`` (the AOT path) and assignment to a
       ``self.`` attribute are exempt; ``static_argnames`` naming a
       parameter whose default is an unhashable literal; f-strings
       interpolating ``.shape`` inside jitted code (outside
       ``raise``/``assert``).
GC004  Stray debug hooks in library code: ``jax.debug.print``,
       ``jax.debug.breakpoint``, builtin ``breakpoint()``.
GC005  Module-level calls that initialize a JAX backend at import time:
       any ``jnp.*`` call, ``jax.devices``/``device_put``/
       ``device_count``/``default_backend``, ``jax.random.*`` executed
       at module scope (including class bodies, decorator expressions
       and default-argument values). ``jax.jit``/``vmap`` at module
       scope stay exempt — they are lazy and are the *recommended*
       caching pattern.

GC007   Fault-injection seam (``porqua_tpu.resilience.faults.fire``)
        not lexically inside an ``if faults.enabled():`` guard — the
        pattern that keeps the disabled production path one
        module-global predicate and provably bit-identical (see the
        GC104 jaxpr-identity contract).

GC006 (the ``# guarded-by:`` thread-safety lint) lives in
:mod:`porqua_tpu.analysis.guards`; GC008-GC010 (the concurrency plane:
inferred lock discipline, static deadlock detection, blocking-call-
under-lock) live in :mod:`porqua_tpu.analysis.concurrency`;
GC101-GC104 (trace-time jaxpr contracts) live in
:mod:`porqua_tpu.analysis.contracts`. This module's own code is pure
stdlib ``ast`` — it adds no JAX work of its own, though reaching it
through the package path still executes ``porqua_tpu/__init__``
(which imports the solver stack).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "ModuleInfo",
    "RULE_DOCS",
    "iter_py_files",
    "load_module",
    "scan_paths",
    "suppression_stats",
]

RULE_DOCS = {
    "GC001": "unpinned contraction in a precision-policy module",
    "GC002": "host-device sync hazard in jit-reachable code",
    "GC003": "recompile hazard",
    "GC004": "stray debug hook in library code",
    "GC005": "backend-initializing work at module import time",
    "GC006": "guarded-by attribute mutated without its lock",
    "GC007": "fault seam not guarded by the injector-enabled check",
    "GC008": "unannotated shared state mutated from multiple thread roots",
    "GC009": "lock-order cycle (potential deadlock)",
    "GC010": "blocking call while holding a lock",
    "GC101": "float64 leaked into a traced program",
    "GC102": "callback/transfer primitive inside a traced program",
    "GC103": "unstable output dtype in a traced program",
    "GC104": "fault injection perturbs a traced program",
    "GC105": "telemetry (harvest/profiling) perturbs a traced program",
    "GC106": "live plane (SLO/flight/anomaly) perturbs a traced program",
    "GC107": "device-truth cost plane perturbs a traced program",
    "GC108": "fleet federation plane perturbs a traced program",
    "GC109": "tenant plane perturbs a traced program",
    "GC110": "solver routing perturbs a traced program",
    "GC111": "calibration loop perturbs a traced program",
    # Post-lowering HLO rules (porqua_tpu/analysis/hlolint.py): run
    # over the optimized HLO harvested from every entry-point program
    # (analysis/hlo.py), not over source text — what XLA emitted, not
    # what we traced.
    "GC201": "fusion miss: unfused elementwise/reduce chain past the "
             "ridge-point byte threshold",
    "GC202": "redundant materialization: same subcomputation emitted "
             ">=2x in one HLO module",
    "GC203": "layout churn: chained copy/transpose/bitcast-convert "
             "data movement",
    "GC204": "padding waste: bucket dead-lane byte share over the "
             "per-bucket budget",
    "GC205": "temporary-peak budget: memory_analysis peak over the "
             "committed per-program bound",
    "GC206": "post-lowering dtype drift: f64/widening emitted by XLA "
             "in an f32 program",
}

_CONTRACTIONS = {"dot", "einsum", "matmul", "tensordot", "inner", "vdot"}
_JIT_WRAPPERS = {"jit", "vmap", "pmap", "grad", "value_and_grad",
                 "checkpoint", "remat"}
_LAX_CONTROL = {"scan", "while_loop", "fori_loop", "cond", "switch", "map",
                "associative_scan"}
_CAST_BUILTINS = {"float", "int", "bool"}
# numpy attribute calls that only *name* a dtype are still host
# conversions when called — no exemptions; attribute references
# (``np.float32`` as a dtype argument) are not calls and never flagged.

_SUPPRESS_LINE_RE = re.compile(
    r"#\s*graftcheck:\s*disable\s*=\s*([A-Za-z0-9_,\s]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*graftcheck:\s*disable-file\s*=\s*([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a file line."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class ModuleInfo:
    """One parsed file plus everything the rules need: import aliases,
    suppression tables, and parent links on every AST node."""

    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.posix = "/" + path.replace(os.sep, "/").lstrip("/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._gc_parent = node  # type: ignore[attr-defined]

        self.jnp_aliases: Set[str] = set()
        self.np_aliases: Set[str] = set()
        self.jax_aliases: Set[str] = set()
        self.partial_names: Set[str] = set()
        self.functools_aliases: Set[str] = set()
        #: ``from pkg.mod import name as alias`` -> alias: (pkg.mod, name)
        self.imported_from: Dict[str, Tuple[str, str]] = {}
        #: ``import pkg.mod as alias`` -> alias: pkg.mod
        self.module_aliases: Dict[str, str] = {}
        self._collect_imports()

        self.file_suppress: Set[str] = set()
        self.line_suppress: Dict[int, Set[str]] = {}
        self._collect_suppressions()

        #: name -> function/async defs bound to it anywhere in the file
        self.defs_by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)

    # -- imports -----------------------------------------------------

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name, bound = alias.name, alias.asname or alias.name
                    if name == "jax.numpy":
                        self.jnp_aliases.add(bound)
                    elif name == "numpy":
                        self.np_aliases.add(bound)
                    elif name == "jax":
                        self.jax_aliases.add(bound)
                    elif name == "functools":
                        self.functools_aliases.add(bound)
                    if "." in name and alias.asname:
                        self.module_aliases[bound] = name
                    elif "." not in name:
                        self.module_aliases.setdefault(bound, name)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    bound = alias.asname or alias.name
                    if node.module == "jax" and alias.name == "numpy":
                        self.jnp_aliases.add(bound)
                    elif node.module == "functools" and alias.name == "partial":
                        self.partial_names.add(bound)
                    self.imported_from[bound] = (node.module, alias.name)

    # -- suppressions ------------------------------------------------

    def _collect_suppressions(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                self.file_suppress |= _parse_rule_list(m.group(1))
            m = _SUPPRESS_LINE_RE.search(line)
            if m:
                self.line_suppress.setdefault(i, set()).update(
                    _parse_rule_list(m.group(1)))

    def suppressed(self, rule: str, line: int) -> bool:
        for pool in (self.file_suppress, self.line_suppress.get(line, ())):
            if "all" in pool or rule in pool:
                return True
        return False

    # -- chain helpers -----------------------------------------------

    def attr_chain(self, node: ast.AST) -> Optional[List[str]]:
        """``jax.lax.scan`` -> ['jax', 'lax', 'scan']; None when the
        expression is not a pure Name/Attribute chain."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return parts[::-1]
        return None

    def is_jnp_attr(self, node: ast.AST,
                    attrs: Optional[Set[str]] = None) -> bool:
        """True for ``jnp.X`` / ``jax.numpy.X`` (X restricted to
        ``attrs`` when given)."""
        chain = self.attr_chain(node)
        if not chain or len(chain) < 2:
            return False
        head, tail = chain[:-1], chain[-1]
        if attrs is not None and tail not in attrs:
            return False
        if len(head) == 1 and head[0] in self.jnp_aliases:
            return True
        return (len(head) == 2 and head[0] in self.jax_aliases
                and head[1] == "numpy")

    def mentions_jnp(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and (
                    sub.id in self.jnp_aliases):
                return True
            if isinstance(sub, ast.Attribute):
                chain = self.attr_chain(sub)
                if chain and len(chain) >= 2 and chain[0] in self.jax_aliases \
                        and chain[1] == "numpy":
                    return True
        return False

    def _chain_is_jax_name(self, chain: Optional[List[str]],
                           names: Set[str],
                           lax_names: Optional[Set[str]] = None) -> bool:
        """Does ``chain`` denote ``jax.<name>`` for ``name in names``
        (or ``jax.lax.<name>`` for ``lax_names``), under any import
        style — ``jax.jit``, ``from jax import jit [as j]``,
        ``from jax import lax; lax.scan``, ``from jax.lax import
        scan``?"""
        if not chain:
            return False
        head = self.imported_from.get(chain[0])
        if head is not None:
            src, orig = head
            chain = src.split(".") + [orig] + chain[1:]
        elif chain[0] in self.jax_aliases:
            chain = ["jax"] + chain[1:]
        if chain[0] != "jax":
            return False
        if len(chain) == 2 and chain[1] in names:
            return True
        return bool(lax_names and len(chain) == 3 and chain[1] == "lax"
                    and chain[2] in lax_names)

    def is_jit_constructor(self, call: ast.Call) -> bool:
        """``jax.jit(...)`` / ``jit(...)`` (from-import) or
        ``partial(jax.jit, ...)`` / ``functools.partial(jax.jit, ...)``."""
        if self._chain_is_jax_name(self.attr_chain(call.func), {"jit"}):
            return True
        if self._is_partial(call) and call.args:
            return self._chain_is_jax_name(
                self.attr_chain(call.args[0]), {"jit"})
        return False

    def _is_partial(self, call: ast.Call) -> bool:
        chain = self.attr_chain(call.func)
        if not chain:
            return False
        if len(chain) == 1 and chain[0] in self.partial_names:
            return True
        return (len(chain) == 2 and chain[0] in self.functools_aliases
                and chain[1] == "partial")


def _parse_rule_list(raw: str) -> Set[str]:
    return {tok.strip() for tok in raw.split(",") if tok.strip()}


def _ancestors(node: ast.AST) -> Iterable[ast.AST]:
    node = getattr(node, "_gc_parent", None)
    while node is not None:
        yield node
        node = getattr(node, "_gc_parent", None)


# ---------------------------------------------------------------------------
# path scoping
# ---------------------------------------------------------------------------

def in_precision_scope(posix_path: str) -> bool:
    p = posix_path
    return ("/qp/" in p or "/estimators/" in p
            or p.endswith("/tracking.py") or p.endswith("/accounting.py"))


def in_steady_state_scope(posix_path: str) -> bool:
    p = posix_path
    return ("/qp/" in p or "/serve/" in p or "/ops/" in p
            or p.endswith("/tracking.py") or p.endswith("/batch.py")
            or p.endswith("/backtest.py") or p.endswith("/accounting.py"))


def in_library_scope(posix_path: str) -> bool:
    p = posix_path
    return not ("/tests/" in p or "/scripts/" in p or "/examples/" in p)


# ---------------------------------------------------------------------------
# GC001 — unpinned contractions
# ---------------------------------------------------------------------------

def _check_gc001(mod: ModuleInfo,
                 reachable_ids: Optional[Set[int]] = None) -> List[Finding]:
    if not in_precision_scope(mod.posix):
        return []
    reachable_ids = reachable_ids or set()
    out: List[Finding] = []

    def emit(node: ast.AST, what: str) -> None:
        if not mod.suppressed("GC001", node.lineno):
            out.append(Finding(
                "GC001", mod.path, node.lineno, node.col_offset,
                f"{what} without precision= in a precision-policy module; "
                "pin to jax.lax.Precision.HIGHEST (policy: qp/canonical.HP)"))

    # Unpinned jnp contraction calls.
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and mod.is_jnp_attr(
                node.func, _CONTRACTIONS):
            if not any(kw.arg == "precision" for kw in node.keywords):
                name = mod.attr_chain(node.func)[-1]
                emit(node, f"jnp.{name}()")

    # `@` on jnp-derived operands: taint names assigned from jnp
    # expressions within their enclosing function scope, then flag
    # MatMult whose operand is tainted or directly mentions jnp. Host
    # numpy `@` (e.g. qp/ipm.py, CanonicalQP.build) stays exempt by
    # construction.
    def scope_of(node: ast.AST) -> ast.AST:
        for a in _ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return a
        return mod.tree

    taint_cache: Dict[int, Set[str]] = {}

    def tainted_names(scope: ast.AST) -> Set[str]:
        cached = taint_cache.get(id(scope))
        if cached is not None:
            return cached
        tainted: Set[str] = set()
        for node in ast.walk(scope):
            if not isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                continue
            if scope_of(node) is not scope:
                continue
            value = node.value
            if value is None or not mod.mentions_jnp(value):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name):
                        tainted.add(leaf.id)
        taint_cache[id(scope)] = tainted
        return tainted

    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.MatMult)):
            continue
        scope = scope_of(node)
        tainted = tainted_names(scope)

        def is_jnp_operand(op: ast.AST) -> bool:
            if isinstance(op, ast.Name) and op.id in tainted:
                return True
            return mod.mentions_jnp(op)

        # Inside a jit-reachable function every operand is traced (a
        # numpy constant operand still lowers to a device matmul), so
        # `@` on plain parameters is flagged too — the taint heuristic
        # alone would miss exactly the hot-path case the rule exists
        # for.
        if id(scope) in reachable_ids \
                or is_jnp_operand(node.left) or is_jnp_operand(node.right):
            emit(node, "the @ operator on a jnp array")
    return out


# ---------------------------------------------------------------------------
# GC002 — host-sync hazards in jit-reachable code
# ---------------------------------------------------------------------------

class _Reachability:
    """Cross-module jit-reachability: roots are functions decorated
    with / passed to JAX tracing wrappers; edges follow plain-name
    calls (same module + ``from x import y`` bindings + module-alias
    attributes) and bare-method calls (same module)."""

    def __init__(self, mods: Sequence[ModuleInfo]) -> None:
        self.mods = mods
        self.by_modname: Dict[str, ModuleInfo] = {}
        for m in mods:
            dotted = m.posix.lstrip("/").removesuffix(".py").replace("/", ".")
            self.by_modname[dotted] = m
        #: reachable (mod, function-or-lambda node) pairs
        self.reached: Set[Tuple[int, int]] = set()
        self.work: List[Tuple[ModuleInfo, ast.AST]] = []

    def _module_for(self, dotted: str) -> Optional[ModuleInfo]:
        if dotted in self.by_modname:
            return self.by_modname[dotted]
        # Tolerate roots scanned from a subdirectory: match on suffix.
        for name, m in self.by_modname.items():
            if name.endswith("." + dotted) or dotted.endswith("." + name):
                return m
        return None

    def _add(self, mod: ModuleInfo, node: ast.AST) -> None:
        key = (id(mod), id(node))
        if key not in self.reached:
            self.reached.add(key)
            self.work.append((mod, node))

    def _add_callable_expr(self, mod: ModuleInfo, expr: ast.AST) -> None:
        if isinstance(expr, ast.Lambda):
            self._add(mod, expr)
        elif isinstance(expr, ast.Name):
            self._resolve_name(mod, expr.id)
        elif isinstance(expr, ast.Call):
            # partial(f, ...) / jax.tree_util wrappers: dig into args.
            for a in expr.args:
                self._add_callable_expr(mod, a)

    def _resolve_name(self, mod: ModuleInfo, name: str) -> None:
        for node in mod.defs_by_name.get(name, ()):
            self._add(mod, node)
        if name in mod.imported_from:
            src_mod, orig = mod.imported_from[name]
            target = self._module_for(src_mod)
            if target is not None:
                for node in target.defs_by_name.get(orig, ()):
                    self._add(target, node)

    def _resolve_call(self, mod: ModuleInfo, call: ast.Call) -> None:
        func = call.func
        if isinstance(func, ast.Name):
            self._resolve_name(mod, func.id)
            return
        chain = mod.attr_chain(func)
        if chain and len(chain) == 2 and chain[0] in mod.module_aliases:
            target = self._module_for(mod.module_aliases[chain[0]])
            if target is not None:
                for node in target.defs_by_name.get(chain[1], ()):
                    self._add(target, node)
                return
        if isinstance(func, ast.Attribute):
            # Bare-method call (self.foo(...), qp.foo(...)): resolve to
            # same-module defs only — cross-module method resolution by
            # bare name would be collision-prone.
            for node in mod.defs_by_name.get(func.attr, ()):
                self._add(mod, node)

    def collect_roots(self) -> None:
        for mod in self.mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if self._is_tracing_wrapper(mod, dec):
                            self._add(mod, node)
                elif isinstance(node, ast.Call) \
                        and self._is_tracing_wrapper(mod, node.func,
                                                     call=node):
                    for arg in list(node.args) + [
                            kw.value for kw in node.keywords]:
                        self._add_callable_expr(mod, arg)

    def _is_tracing_wrapper(self, mod: ModuleInfo, node: ast.AST,
                            call: Optional[ast.Call] = None) -> bool:
        if isinstance(node, ast.Call):
            # @functools.partial(jax.jit, ...) decorator form
            if mod.is_jit_constructor(node):
                return True
            return self._is_tracing_wrapper(mod, node.func, call=node)
        return mod._chain_is_jax_name(
            mod.attr_chain(node), _JIT_WRAPPERS, _LAX_CONTROL)

    def run(self) -> Dict[int, Set[int]]:
        self.collect_roots()
        while self.work:
            mod, node = self.work.pop()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    self._resolve_call(mod, sub)
        per_mod: Dict[int, Set[int]] = {}
        for mod_id, node_id in self.reached:
            per_mod.setdefault(mod_id, set()).add(node_id)
        return per_mod


def _check_gc002(mods: Sequence[ModuleInfo],
                 reached: Dict[int, Set[int]]) -> List[Finding]:
    out: List[Finding] = []
    for mod in mods:
        node_ids = reached.get(id(mod), set())
        if not node_ids:
            continue
        nodes = [n for n in ast.walk(mod.tree) if id(n) in node_ids]
        seen_lines: Set[Tuple[str, int]] = set()

        def emit(node: ast.AST, what: str) -> None:
            key = (what, node.lineno)
            if key in seen_lines or mod.suppressed("GC002", node.lineno):
                return
            seen_lines.add(key)
            out.append(Finding(
                "GC002", mod.path, node.lineno, node.col_offset,
                f"{what} in jit-reachable code forces a host-device sync "
                "(or fails at trace time); keep the hot path device-only"))

        for fn in nodes:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if isinstance(func, ast.Attribute):
                    if func.attr == "item" and not node.args:
                        emit(node, ".item()")
                    elif func.attr == "block_until_ready":
                        emit(node, ".block_until_ready()")
                chain = mod.attr_chain(func)
                if chain:
                    if chain[0] in mod.np_aliases:
                        emit(node, f"host numpy call np.{'.'.join(chain[1:])}()")
                    elif len(chain) == 2 and chain[0] in mod.jax_aliases \
                            and chain[1] == "device_get":
                        emit(node, "jax.device_get()")
                if isinstance(func, ast.Name) and func.id in _CAST_BUILTINS \
                        and len(node.args) == 1 \
                        and not isinstance(node.args[0], ast.Constant):
                    emit(node, f"{func.id}() on a (possibly traced) array")
    return out


# ---------------------------------------------------------------------------
# GC003 — recompile hazards
# ---------------------------------------------------------------------------

def _check_gc003(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    steady = in_steady_state_scope(mod.posix)

    def emit(node: ast.AST, msg: str) -> None:
        if not mod.suppressed("GC003", node.lineno):
            out.append(Finding("GC003", mod.path, node.lineno,
                               node.col_offset, msg))

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and mod.is_jit_constructor(node):
            in_loop = any(isinstance(a, (ast.For, ast.While))
                          for a in _ancestors(node))
            enclosing_fn = next(
                (a for a in _ancestors(node)
                 if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda))), None)
            is_decorator = any(
                isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node in getattr(a, "decorator_list", ())
                for a in [getattr(node, "_gc_parent", None)] if a is not None)
            parent = getattr(node, "_gc_parent", None)
            lowered = (isinstance(parent, ast.Attribute)
                       and parent.attr == "lower")
            cached_on_self = (
                isinstance(parent, ast.Assign)
                and any(isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        for t in parent.targets))
            if in_loop:
                emit(node, "jax.jit constructed inside a loop recompiles "
                           "every iteration; hoist it to module scope")
            elif steady and enclosing_fn is not None and not is_decorator \
                    and not lowered and not cached_on_self:
                emit(node, "jax.jit constructed inside a function in a "
                           "steady-state module recompiles on every call; "
                           "cache it at module scope, on self, or use the "
                           "AOT .lower(...).compile() path")

            # Unhashable defaults behind static_argnames.
            static_names: Set[str] = set()
            for kw in node.keywords:
                if kw.arg == "static_argnames":
                    for leaf in ast.walk(kw.value):
                        if isinstance(leaf, ast.Constant) \
                                and isinstance(leaf.value, str):
                            static_names.add(leaf.value)
            target_fn = None
            grandparent = getattr(node, "_gc_parent", None)
            if isinstance(grandparent, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) \
                    and node in grandparent.decorator_list:
                target_fn = grandparent
            elif node.args and isinstance(node.args[0], ast.Name):
                defs = mod.defs_by_name.get(node.args[0].id)
                target_fn = defs[0] if defs else None
            elif node.args and isinstance(node.args[0], ast.Attribute):
                pass  # jax.jit(partial) of foreign callables: unknowable
            if static_names and target_fn is not None:
                args = target_fn.args
                pos = args.posonlyargs + args.args
                defaults = args.defaults
                offset = len(pos) - len(defaults)
                pairs = [(a.arg, d) for a, d in zip(pos[offset:], defaults)]
                pairs += [(a.arg, d) for a, d in
                          zip(args.kwonlyargs, args.kw_defaults) if d]
                for name, default in pairs:
                    if name in static_names and isinstance(
                            default, (ast.List, ast.Dict, ast.Set,
                                      ast.ListComp, ast.DictComp,
                                      ast.SetComp)):
                        emit(default,
                             f"static arg {name!r} has an unhashable "
                             f"default ({type(default).__name__.lower()}); "
                             "jit will raise or recompile per call")

    # f-strings interpolating .shape inside jit-decorated functions.
    jitted_fns = [
        n for n in ast.walk(mod.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and any(_is_jit_decorator(mod, d) for d in n.decorator_list)]
    for fn in jitted_fns:
        for node in ast.walk(fn):
            if not isinstance(node, ast.JoinedStr):
                continue
            if any(isinstance(a, (ast.Raise, ast.Assert))
                   for a in _ancestors(node)):
                continue
            for val in node.values:
                if isinstance(val, ast.FormattedValue) and ".shape" in \
                        ast.unparse(val.value):
                    emit(node, "f-string interpolating .shape inside a "
                               "jitted function bakes the shape into a "
                               "Python string at trace time — a silent "
                               "per-shape recompile anchor")
                    break
    return out


def _is_jit_decorator(mod: ModuleInfo, dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        return mod.is_jit_constructor(dec)
    return mod._chain_is_jax_name(mod.attr_chain(dec), {"jit"})


# ---------------------------------------------------------------------------
# GC004 — stray debug hooks
# ---------------------------------------------------------------------------

def _check_gc004(mod: ModuleInfo) -> List[Finding]:
    if not in_library_scope(mod.posix):
        return []
    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = mod.attr_chain(node.func)
        msg = None
        if chain and len(chain) == 3 and chain[0] in mod.jax_aliases \
                and chain[1] == "debug" and chain[2] in ("print",
                                                         "breakpoint"):
            msg = f"jax.debug.{chain[2]}() left in library code"
        elif isinstance(node.func, ast.Name) and node.func.id == "breakpoint":
            msg = "breakpoint() left in library code"
        if msg and not mod.suppressed("GC004", node.lineno):
            out.append(Finding("GC004", mod.path, node.lineno,
                               node.col_offset, msg))
    return out


# ---------------------------------------------------------------------------
# GC007 — fault seams guarded by the injector-enabled predicate
# ---------------------------------------------------------------------------

def _faults_bindings(mod: ModuleInfo) -> Tuple[Set[str], Set[str], Set[str]]:
    """Names bound to the fault-injection module / its entry points:
    ``(module_aliases, bare_fire_names, bare_enabled_names)`` —
    covering ``import porqua_tpu.resilience.faults as _faults``,
    ``from porqua_tpu.resilience import faults``, and
    ``from porqua_tpu.resilience.faults import fire, enabled``."""
    mod_aliases: Set[str] = set()
    for alias, target in mod.module_aliases.items():
        if target.endswith("resilience.faults"):
            mod_aliases.add(alias)
    bare_fire: Set[str] = set()
    bare_enabled: Set[str] = set()
    for alias, (src, orig) in mod.imported_from.items():
        if orig == "faults" and src.endswith("resilience"):
            mod_aliases.add(alias)
        elif src.endswith("resilience.faults"):
            if orig == "fire":
                bare_fire.add(alias)
            elif orig == "enabled":
                bare_enabled.add(alias)
    return mod_aliases, bare_fire, bare_enabled


def _check_gc007(mod: ModuleInfo) -> List[Finding]:
    """Every ``faults.fire(...)`` seam must sit lexically inside an
    ``if`` whose test calls ``faults.enabled()``. The guard is what
    makes the disabled path one module-global predicate (no injector
    lookup, no RNG, no allocation) — an unguarded seam silently turns
    the production hot path into a per-call function boundary AND
    breaks the bit-identical-when-disabled promise the chaos suite's
    A/B leans on. The resilience package itself is exempt (it IS the
    plane), as are tests/scripts/examples."""
    if not in_library_scope(mod.posix) or "/resilience/" in mod.posix:
        return []
    mod_aliases, bare_fire, bare_enabled = _faults_bindings(mod)
    if not mod_aliases and not bare_fire:
        return []

    def is_enabled_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        chain = mod.attr_chain(node.func)
        if not chain:
            return False
        if len(chain) == 1 and chain[0] in bare_enabled:
            return True
        return (len(chain) == 2 and chain[0] in mod_aliases
                and chain[1] == "enabled")

    def positively_tests_enabled(test: ast.AST) -> bool:
        # enabled() must appear in the test OUTSIDE any `not`:
        # `if not faults.enabled():` selects exactly the disabled path
        # the rule exists to keep seam-free.
        negated: Set[ast.AST] = set()
        for sub in ast.walk(test):
            if isinstance(sub, ast.UnaryOp) and isinstance(sub.op, ast.Not):
                negated.update(ast.walk(sub.operand))
        return any(is_enabled_call(sub) and sub not in negated
                   for sub in ast.walk(test))

    def guarded(node: ast.AST) -> bool:
        # The fire() must sit in the If's BODY (not the orelse — a
        # seam in the else branch of an enabled() check is precisely
        # the unguarded/disabled-path placement being linted for).
        child: ast.AST = node
        for anc in _ancestors(node):
            if (isinstance(anc, ast.If) and child in anc.body
                    and positively_tests_enabled(anc.test)):
                return True
            child = anc
        return False

    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        chain = mod.attr_chain(node.func)
        if not chain:
            continue
        is_fire = ((len(chain) == 1 and chain[0] in bare_fire)
                   or (len(chain) == 2 and chain[0] in mod_aliases
                       and chain[1] == "fire"))
        if not is_fire or guarded(node):
            continue
        if not mod.suppressed("GC007", node.lineno):
            out.append(Finding(
                "GC007", mod.path, node.lineno, node.col_offset,
                "fault seam fired without the enabled() guard; wrap in "
                "`if faults.enabled():` so the disabled path stays one "
                "module-global predicate (and bit-identical)"))
    return out


# ---------------------------------------------------------------------------
# GC005 — backend init at import time
# ---------------------------------------------------------------------------

_JAX_EAGER = {"devices", "local_devices", "device_count",
              "local_device_count", "device_put", "default_backend"}


def _module_level_exprs(tree: ast.Module) -> Iterable[ast.AST]:
    """Every expression evaluated at import time: module/class-body
    statements, plus decorator lists and default-argument values of
    module-level defs (their *bodies* are not executed at import)."""
    stack: List[ast.AST] = [tree]
    while stack:
        scope = stack.pop()
        for stmt in scope.body:
            if isinstance(stmt, ast.ClassDef):
                yield from stmt.decorator_list
                yield from stmt.bases
                stack.append(stmt)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from stmt.decorator_list
                yield from (d for d in stmt.args.defaults)
                yield from (d for d in stmt.args.kw_defaults if d)
            else:
                yield stmt


def _runs_later(node: ast.AST) -> bool:
    """True when ``node`` sits in a function or lambda *body* (runs at
    call time), even if the enclosing def is itself nested inside a
    module-level compound statement (``try:``/``if:`` fallbacks).
    Decorator expressions and default-argument values are NOT bodies —
    they execute when the def is, so they stay import-time when the
    def is at module level."""
    child = node
    for anc in _ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and child in anc.body:
            return True
        if isinstance(anc, ast.Lambda) and child is anc.body:
            return True
        child = anc
    return False


def _check_gc005(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for expr in _module_level_exprs(mod.tree):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            if _runs_later(node):
                continue
            chain = mod.attr_chain(node.func)
            if not chain:
                continue
            msg = None
            if mod.is_jnp_attr(node.func):
                msg = (f"module-level jnp.{chain[-1]}() initializes a JAX "
                       "backend at import time; build arrays lazily")
            elif chain[0] in mod.jax_aliases and len(chain) == 2 \
                    and chain[1] in _JAX_EAGER:
                msg = (f"module-level jax.{chain[1]}() initializes a JAX "
                       "backend at import time")
            elif chain[0] in mod.jax_aliases and len(chain) >= 3 \
                    and chain[1] == "random":
                msg = ("module-level jax.random call initializes a JAX "
                       "backend at import time")
            if msg and not mod.suppressed("GC005", node.lineno):
                out.append(Finding("GC005", mod.path, node.lineno,
                                   node.col_offset, msg))
    return out


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

def iter_py_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                files.extend(os.path.join(root, n)
                             for n in sorted(names) if n.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    return files


def load_module(path: str) -> ModuleInfo:
    with open(path, "r", encoding="utf-8") as fh:
        return ModuleInfo(path, fh.read())


def suppression_stats(mods: Sequence[ModuleInfo]) -> Dict[str, int]:
    """Per-rule suppression-directive counts across ``mods`` (each
    file-level directive counts 1 per rule, each line directive 1 per
    (line, rule)). The CLI's ``--stats`` surfaces these so suppression
    creep is visible in CI output — the shipped tree's bar is zero.
    Only recognized rule ids (and ``all``) are counted: a directive
    naming a rule that does not exist suppresses nothing real (doc
    examples spelling ``GC00x`` would otherwise read as creep)."""
    known = set(RULE_DOCS) | {"all"}
    out: Dict[str, int] = {}
    for mod in mods:
        for rule in mod.file_suppress:
            if rule in known:
                out[rule] = out.get(rule, 0) + 1
        for rules in mod.line_suppress.values():
            for rule in rules:
                if rule in known:
                    out[rule] = out.get(rule, 0) + 1
    return out


def scan_paths(paths: Sequence[str],
               rules: Optional[Set[str]] = None,
               stats_out: Optional[dict] = None) -> List[Finding]:
    """Run every AST rule (GC001-GC010) over ``paths`` (files or
    directory trees). ``rules`` restricts to a subset of rule ids.
    ``stats_out``, when given, is populated with per-rule finding and
    suppression counts plus the scanned-file count."""
    mods: List[ModuleInfo] = []
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        try:
            mods.append(load_module(path))
        except SyntaxError as exc:
            findings.append(Finding(
                "GC000", path, exc.lineno or 0, exc.offset or 0,
                f"file does not parse: {exc.msg}"))

    def want(rule: str) -> bool:
        return rules is None or rule in rules

    # GC001 (the `@`-in-jit-reachable-code case) and GC002 share the
    # cross-module reachability pass.
    reached: Dict[int, Set[int]] = {}
    if want("GC001") or want("GC002"):
        reached = _Reachability(mods).run()

    for mod in mods:
        if want("GC001"):
            findings.extend(_check_gc001(mod, reached.get(id(mod))))
        if want("GC003"):
            findings.extend(_check_gc003(mod))
        if want("GC004"):
            findings.extend(_check_gc004(mod))
        if want("GC005"):
            findings.extend(_check_gc005(mod))
        if want("GC007"):
            findings.extend(_check_gc007(mod))
    if want("GC002"):
        findings.extend(_check_gc002(mods, reached))
    if want("GC006"):
        from porqua_tpu.analysis.guards import check_guarded_by
        for mod in mods:
            findings.extend(check_guarded_by(mod))
    if want("GC008") or want("GC009") or want("GC010"):
        from porqua_tpu.analysis.concurrency import check_concurrency
        findings.extend(check_concurrency(mods, rules=rules))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if stats_out is not None:
        by_rule: Dict[str, int] = {}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        stats_out["files"] = len(mods)
        stats_out["findings_by_rule"] = by_rule
        stats_out["suppressions_by_rule"] = suppression_stats(mods)
    return findings
