"""Runtime lock-order sanitizer (``PORQUA_TSAN=1``).

The static concurrency rules (:mod:`porqua_tpu.analysis.concurrency`,
GC008-GC010) see what is visible in source; lock *ordering* across
dynamic dispatch — callbacks, timer-wheel lambdas, cross-object call
chains the resolver cannot follow — is only observable at runtime.
This module is the lockdep-style dynamic half:

* :func:`lock` is the drop-in factory the serve stack's classes use
  for their instance locks (``self._lock = tsan.lock("EventBus")``).
  Disabled (the default), it returns a plain ``threading.Lock`` — the
  production path pays one function call at construction and nothing
  per acquire. Under ``PORQUA_TSAN=1`` it returns a
  :class:`TSanLock`, which on every acquire/release maintains:

  - the calling thread's **held-lock set** (a ``threading.local``
    stack), and
  - the process-wide **acquisition-order graph** over lock *names*
    (instances of one class share a name — lockdep semantics: the
    discipline is per lock class, not per object).

* **Order-inversion detection**: acquiring ``B`` while holding ``A``
  records the edge ``A -> B``; a later acquire of ``A`` under ``B``
  finds the ``A ->* B`` path already in the graph and raises
  :class:`LockOrderError` (a :class:`~porqua_tpu.analysis.sanitize.
  SanitizerError`) *before blocking* — the inversion is caught even
  when the interleaving this run happened to take would not have
  deadlocked. Re-acquiring a held name (same lock, or a sibling
  instance of the same class) raises immediately: with
  non-reentrant ``threading.Lock`` that is a guaranteed self-deadlock
  or an unordered same-class pair.

* **Hold-time budget**: ``release`` measures the critical section;
  longer than ``PORQUA_TSAN_HOLD_BUDGET_S`` (default 5.0) raises
  :class:`LockHoldError` *after* releasing (the violation must not
  wedge other threads behind a lock held by a raising frame). The
  blocking-work-under-a-lock discipline GC010 lints statically,
  enforced on the real interleaving.

* **Deadlock watchdog**: a blocking acquire runs as a bounded-timeout
  poll loop; on every timeout the watchdog walks the wait-for graph
  (thread -> lock it waits on -> owning thread -> ...) and raises
  :class:`DeadlockError` naming the cycle if one closed — so even an
  inversion the order graph could not predict (e.g. locks acquired
  through uninstrumented paths) surfaces as a raised error, not a
  hung process. ``PORQUA_TSAN_MAX_WAIT_S`` (default off) additionally
  bounds any single acquire, for stress harnesses that prefer a hard
  failure over unbounded contention.

:class:`TSanLock` supports the full lock protocol (``with``,
``acquire(blocking, timeout)``, ``release``) and is a valid
``threading.Condition`` base lock (``RetryManager`` wraps its lock in
a Condition; ``Condition.wait`` releases and re-acquires through the
instrumented path, so held-set bookkeeping stays exact).

Everything is exercised under real contention by the
``scripts/tsan_smoke.py`` loadgen pass and the chaos-suite selftest
(both run with ``PORQUA_TSAN=1`` in ``scripts/run_tests.sh``);
adopters: ``WarmStartCache``, ``ExecutableCache``, ``DeviceHealth``,
``RetryManager``, ``ServeMetrics``, ``EventBus``, ``SpanRecorder``,
``CompactingDriver`` — the locks guarding every piece of shared state
the ``MicroBatcher``/``ContinuousBatcher`` dispatch loops touch.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from porqua_tpu.analysis.sanitize import SanitizerError

__all__ = [
    "DeadlockError",
    "LockHoldError",
    "LockOrderError",
    "TSanLock",
    "enabled",
    "hold_budget_s",
    "lock",
    "order_graph",
    "reset",
    "violations",
]


class LockOrderError(SanitizerError):
    """Two locks were acquired in both orders (potential deadlock)."""


class LockHoldError(SanitizerError):
    """A lock was held longer than the configured budget."""


class DeadlockError(SanitizerError):
    """The wait-for graph closed a cycle (live deadlock)."""


def enabled() -> bool:
    """TSAN mode is on (checked at lock construction)."""
    return os.environ.get("PORQUA_TSAN") == "1"


def hold_budget_s() -> float:
    """Critical-section duration budget (seconds)."""
    return float(os.environ.get("PORQUA_TSAN_HOLD_BUDGET_S", "5.0"))


def max_wait_s() -> Optional[float]:
    """Optional hard bound on any single blocking acquire."""
    raw = os.environ.get("PORQUA_TSAN_MAX_WAIT_S")
    return float(raw) if raw else None


#: Watchdog poll interval for blocking acquires (seconds). Short
#: enough that a real deadlock is reported promptly, long enough that
#: a contended-but-live lock costs a handful of extra syscalls.
_POLL_S = 0.05

# The meta-lock guarding the order/wait-for graphs. A plain Lock on
# purpose (instrumenting it would recurse); every critical section
# under it is a few dict operations.
_graph_lock = threading.Lock()
#: name -> names acquired at least once while `name` was held
_order: Dict[str, Set[str]] = {}
#: (held name, acquired name) -> "file:line" of the first recording
_edge_sites: Dict[Tuple[str, str], str] = {}
#: id(TSanLock) -> owning thread ident (while held)
_owners: Dict[int, int] = {}
#: thread ident -> TSanLock it is currently blocked acquiring
_waiting: Dict[int, "TSanLock"] = {}
#: violations recorded (also raised) — readable by tests/reports
_violations: List[str] = []

_tls = threading.local()


def _held_stack() -> List["TSanLock"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def reset() -> None:
    """Clear the order graph, wait-for state, and violation log (test
    helper; live TSanLocks keep working against the fresh graph)."""
    with _graph_lock:
        _order.clear()
        _edge_sites.clear()
        _owners.clear()
        _waiting.clear()
        _violations.clear()


def order_graph() -> Dict[str, Set[str]]:
    """A copy of the acquisition-order edges recorded so far."""
    with _graph_lock:
        return {k: set(v) for k, v in _order.items()}


def violations() -> List[str]:
    """Messages of every violation raised so far (process-wide)."""
    with _graph_lock:
        return list(_violations)


def _from_stdlib_threading() -> bool:
    """Is the frame calling into this module threading.py itself
    (Condition._release_save / _acquire_restore)?"""
    import sys

    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename.endswith("tsan.py"):
        f = f.f_back
    return f is not None and f.f_code.co_filename == threading.__file__


def _call_site() -> str:
    """The acquiring frame outside this module (for edge messages)."""
    import sys

    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename.endswith("tsan.py"):
        f = f.f_back
    if f is None:  # pragma: no cover - interpreter teardown
        return "<unknown>"
    return f"{f.f_code.co_filename}:{f.f_lineno}"


def _path_exists(src: str, dst: str) -> bool:
    """Is there a path src ->* dst in the order graph? (called under
    ``_graph_lock``)"""
    if src == dst:
        return True
    seen = {src}
    stack = [src]
    while stack:
        node = stack.pop()
        for nxt in _order.get(node, ()):
            if nxt == dst:
                return True
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


def _record_violation(msg: str) -> None:
    with _graph_lock:
        _violations.append(msg)


class TSanLock:
    """An instrumented non-reentrant mutex (see module docstring)."""

    __slots__ = ("name", "_inner", "_acquired_at", "_acquire_site")

    def __init__(self, name: str) -> None:
        self.name = name
        self._inner = threading.Lock()
        self._acquired_at = 0.0
        self._acquire_site = ""

    # -- protocol -----------------------------------------------------

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        held = _held_stack()
        site = _call_site()
        me = threading.get_ident()
        if held:
            self._check_order(held, site)
        if blocking and timeout < 0:
            ok = self._acquire_watched(me)
        else:
            ok = self._inner.acquire(blocking, timeout)
        if ok:
            with _graph_lock:
                _owners[id(self)] = me
            self._acquired_at = time.monotonic()
            self._acquire_site = site
            held.append(self)
        return ok

    def release(self) -> None:
        held = _held_stack()
        if self not in held:
            # A thread releasing a lock it does not hold: threading.Lock
            # would let a FOREIGN release through silently (it is not
            # owner-checked), corrupting the owner table the watchdog
            # walks and setting the real owner up for a misattributed
            # "release unlocked lock". Refuse before touching any state.
            msg = (f"lock {self.name!r} released by thread "
                   f"{threading.get_ident()} which does not hold it "
                   f"(cross-thread or double release)")
            _record_violation(msg)
            raise SanitizerError(msg)
        duration = time.monotonic() - self._acquired_at
        # Snapshot the site BEFORE dropping the inner lock: the next
        # acquirer overwrites _acquire_site the instant it gets in, and
        # a violation naming the wrong critical section misdirects the
        # triage.
        site = self._acquire_site
        held.remove(self)
        with _graph_lock:
            _owners.pop(id(self), None)
        self._inner.release()
        budget = hold_budget_s()
        if duration > budget:
            msg = (f"lock {self.name!r} held {duration:.3f}s "
                   f"(budget {budget:.3f}s; acquired at "
                   f"{site}): blocking work does not "
                   f"belong inside this critical section")
            _record_violation(msg)
            # Raised AFTER the release: the violation must not wedge
            # every other thread behind a lock held by a raising frame.
            # EXCEPT when the release is Condition.wait's internal
            # _release_save — raising into threading's wait protocol
            # leaves the condition with a queued waiter and the lock
            # not re-acquired, so the enclosing `with cond:` exit then
            # masks this diagnostic with "release unlocked lock". The
            # violation is still recorded; tsan.violations() gates on
            # it in the smoke/stress passes.
            if not _from_stdlib_threading():
                raise LockHoldError(msg)

    def __enter__(self) -> "TSanLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, *exc) -> None:
        try:
            self.release()
        except LockHoldError:
            # An exception is already unwinding through this `with`
            # block: replacing it with the hold-budget violation would
            # misdiagnose the real failure (the original error would
            # survive only as __context__). The violation is recorded;
            # violations() still gates on it.
            if exc_type is None:
                raise

    def locked(self) -> bool:
        return self._inner.locked()

    def _is_owned(self) -> bool:
        """threading.Condition's ownership probe."""
        return self in _held_stack()

    # -- instrumentation ----------------------------------------------

    def _check_order(self, held: List["TSanLock"], site: str) -> None:
        with _graph_lock:
            for h in held:
                if h is self or h.name == self.name:
                    msg = (f"re-acquisition of lock {self.name!r} "
                           f"at {site} while already held (acquired "
                           f"at {h._acquire_site}): guaranteed "
                           f"self-deadlock / unordered same-class pair")
                    _violations.append(msg)
                    raise DeadlockError(msg)
                if _path_exists(self.name, h.name):
                    first = _edge_sites.get((self.name, h.name), "?")
                    msg = (f"lock-order inversion: acquiring "
                           f"{self.name!r} at {site} while holding "
                           f"{h.name!r} (acquired at "
                           f"{h._acquire_site}), but the opposite "
                           f"order {self.name!r} -> {h.name!r} was "
                           f"recorded at {first}; acquire these locks "
                           f"in one global order")
                    _violations.append(msg)
                    raise LockOrderError(msg)
            for h in held:
                after = _order.setdefault(h.name, set())
                if self.name not in after:
                    after.add(self.name)
                    _edge_sites[(h.name, self.name)] = site

    def _acquire_watched(self, me: int) -> bool:
        """Blocking acquire as a bounded poll loop with the deadlock
        watchdog: each timeout, walk the wait-for graph and raise on a
        closed cycle; ``PORQUA_TSAN_MAX_WAIT_S`` optionally bounds the
        total wait."""
        deadline = None
        cap = max_wait_s()
        if cap is not None:
            deadline = time.monotonic() + cap
        with _graph_lock:
            _waiting[me] = self
        try:
            while True:
                if self._inner.acquire(timeout=_POLL_S):
                    return True
                self._watchdog_check(me)
                if deadline is not None and time.monotonic() > deadline:
                    msg = (f"acquire of lock {self.name!r} exceeded "
                           f"PORQUA_TSAN_MAX_WAIT_S={cap}s "
                           f"(possible deadlock or runaway hold)")
                    _record_violation(msg)
                    raise DeadlockError(msg)
        finally:
            with _graph_lock:
                _waiting.pop(me, None)

    def _watchdog_check(self, me: int) -> None:
        with _graph_lock:
            cycle = [f"thread {me} waits for {self.name!r}"]
            lock: Optional[TSanLock] = self
            seen_threads = {me}
            while lock is not None:
                owner = _owners.get(id(lock))
                if owner is None:
                    return  # released between poll and check
                if owner == me:
                    msg = ("deadlock: " + " -> ".join(
                        cycle + [f"owned by thread {owner}"]))
                    _violations.append(msg)
                    raise DeadlockError(msg)
                if owner in seen_threads:
                    return  # cycle not through us; their watchdog fires
                seen_threads.add(owner)
                nxt = _waiting.get(owner)
                if nxt is not None:
                    cycle.append(f"thread {owner} holds {lock.name!r} "
                                 f"and waits for {nxt.name!r}")
                lock = nxt


def lock(name: str):
    """The drop-in lock factory: a :class:`TSanLock` under
    ``PORQUA_TSAN=1``, a plain ``threading.Lock`` otherwise. ``name``
    should identify the lock *class* (usually the owning class name) —
    instances share ordering state, lockdep-style."""
    if enabled():
        return TSanLock(name)
    return threading.Lock()
