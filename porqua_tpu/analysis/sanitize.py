"""Runtime sanitizer mode (``PORQUA_SANITIZE=1``).

Static rules catch what is visible in source; two device-discipline
invariants are only observable at runtime:

* **No implicit host<->device transfers in solver hot paths.** Under
  sanitize mode the batched-solve dispatch sites wrap the device call
  in ``jax.transfer_guard("disallow")`` — any *implicit* transfer
  (e.g. a stray numpy array reaching a compiled executable, or a
  hidden device->host fetch inside the dispatch path) raises instead
  of silently serializing the pipeline. Explicit ``jax.device_put``
  remains allowed, so the serving batcher's one intentional
  host->device batch transfer is made explicit and everything else is
  an error.

* **Zero steady-state recompiles.** The serving executable cache calls
  :func:`note_compile` on every AOT compile, passing its own per-cache
  warmed flag (closed by ``ExecutableCache.prewarm`` /
  ``SolveService.prewarm``); once closed, any further compile under
  sanitize mode raises :class:`SanitizerError` — the "compiles after
  warmup == 0" serving invariant (README "Online serving") enforced at
  the moment of violation, with the offending shape in the message,
  rather than discovered as a latency regression in a dashboard.
  Warmed state is scoped per cache so services cannot close each
  other's windows; the module-level counters aggregate process-wide
  for reporting.

The counters always run (they are two integer bumps); only the
*raising* behavior is gated on the environment variable, so tests can
assert on :func:`compile_count` / :func:`post_warmup_compiles` without
enabling enforcement.
"""

from __future__ import annotations

import contextlib
import os
import threading

__all__ = [
    "SanitizerError",
    "enabled",
    "note_compile",
    "warmup_complete",
    "warmed_up",
    "compile_count",
    "post_warmup_compiles",
    "transfer_guard",
    "no_recompile",
    "reset",
]
# NOTE: the *warmed* decision for the serving executable cache is
# scoped per cache and per device (ExecutableCache._warmed_devices,
# passed via note_compile's post_warmup argument); the globals here
# are the process-wide counters/flag for reporting and for
# integrations without their own lifecycle object.


class SanitizerError(RuntimeError):
    """A device-discipline invariant was violated at runtime."""


_lock = threading.Lock()
_compiles = 0
_post_warmup_compiles = 0
_warmed = False


def enabled() -> bool:
    """Sanitize mode is on (checked per call so tests can toggle)."""
    return os.environ.get("PORQUA_SANITIZE") == "1"


def reset() -> None:
    """Zero the counters and re-open the warmup window (test helper)."""
    global _compiles, _post_warmup_compiles, _warmed
    with _lock:
        _compiles = 0
        _post_warmup_compiles = 0
        _warmed = False


def note_compile(what: str = "",
                 post_warmup: "bool | None" = None) -> None:
    """Record one XLA compile *demand*; raise under sanitize mode
    post-warmup. Demands, not completions: a refused post-warmup
    compile (this function raising before the compile runs) and a
    compile that subsequently fails both count — the demand itself is
    the invariant violation the counters exist to surface.

    ``post_warmup`` lets the caller scope the warmup decision to its
    own lifecycle — the serving ``ExecutableCache`` passes its
    per-cache warmed flag, so two services in one process cannot close
    (or re-open) each other's warmup windows. ``None`` falls back to
    the process-global flag set by :func:`warmup_complete`.
    """
    global _compiles, _post_warmup_compiles
    with _lock:
        _compiles += 1
        post = _warmed if post_warmup is None else bool(post_warmup)
        if post:
            _post_warmup_compiles += 1
    if post and enabled():
        raise SanitizerError(
            f"XLA compile after warmup{f' ({what})' if what else ''}: the "
            "steady-state serving invariant is zero recompiles — prewarm "
            "the missing shape bucket, or widen the bucket ladder")


def warmup_complete() -> None:
    """Declare warmup over for callers relying on the process-global
    flag (integrations that own a cache pass ``post_warmup``
    explicitly instead)."""
    global _warmed
    with _lock:
        _warmed = True


def warmed_up() -> bool:
    with _lock:
        return _warmed


def compile_count() -> int:
    """Total compile demands recorded (see :func:`note_compile`)."""
    with _lock:
        return _compiles


def post_warmup_compiles() -> int:
    """Compile demands recorded after warmup (refusals included)."""
    with _lock:
        return _post_warmup_compiles


@contextlib.contextmanager
def transfer_guard(level: str = "disallow"):
    """``jax.transfer_guard(level)`` when sanitize mode is on, no-op
    otherwise. Imports jax lazily: the guard is only paid for (and jax
    only required at this point) when enforcement is actually on."""
    if not enabled():
        yield
        return
    import jax

    with jax.transfer_guard(level):
        yield


@contextlib.contextmanager
def no_recompile(what: str = ""):
    """Assert no compile was demanded inside the block (enforced only
    under sanitize mode; always measured)."""
    before = compile_count()
    yield
    delta = compile_count() - before
    if delta and enabled():
        raise SanitizerError(
            f"{delta} XLA compile demand(s) inside a no-recompile window"
            f"{f' ({what})' if what else ''}")
