"""GC006: ``# guarded-by:`` annotation-driven thread-safety lint.

The serving stack (``serve/batcher.py``, ``serve/service.py``,
``serve/bucketing.py``) shares mutable state between the caller
threads, the dispatch thread, and scrap probe threads. The lock
discipline is a convention: certain attributes may only be *mutated*
while holding a specific lock. This lint makes the convention
declarative and machine-checked:

* Annotate the attribute at its initialization site (same line, or a
  comment-only line directly above)::

      self._lock = threading.Lock()
      self._cache = {}  # guarded-by: self._lock

* Every later mutation of ``self._cache`` — assignment, augmented
  assignment, ``del``, subscript store, or a mutating method call
  (``append``/``pop``/``update``/``move_to_end``/...) — must occur
  lexically inside ``with self._lock:`` in the same method, or inside
  a method whose ``def`` line carries the same annotation (the
  "caller holds the lock" contract, for private helpers invoked under
  the lock)::

      def _trip(self) -> None:  # guarded-by: self._lock
          self._degraded = True

* ``__init__``/``__post_init__``/``__new__`` are exempt (the object is
  not yet shared), and *reads* are not checked — the discipline here
  is writer-side; racy reads that matter are the writer's bug to
  prevent by publishing consistent snapshots.

A ``with`` held-lock context does NOT propagate into nested ``def``s:
a nested function is typically a thread target or callback that runs
without the lock.

Scope: GC006 is **opt-in** — it enforces exactly the attributes
someone annotated. Its inference-side complement is GC008
(:mod:`porqua_tpu.analysis.concurrency`), which walks the thread-root
reachability graph and flags *unannotated* ``self`` attributes
mutated from two or more roots with no lock held; an attribute GC008
surfaces is fixed by adding the ``# guarded-by:`` annotation (plus
the lock, where the mutation was a true race), which moves it into
this rule's jurisdiction. The writer-side rules here and the
mutation detection in GC008 share one ``_MUTATORS`` vocabulary.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set

from porqua_tpu.analysis.lint import Finding, ModuleInfo

__all__ = ["check_guarded_by"]

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*self\.(\w+)")

#: method names whose call on a guarded attribute mutates it
#: (shared with GC008's mutation detection). ``__setitem__`` /
#: ``__delitem__`` cover the explicit dunder-call spelling of a
#: subscript/slice store (``self._data.__setitem__(slice(0, k), v)``)
#: — the operator forms are caught as Subscript targets; ``rotate``
#: is deque's in-place rotation.
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popitem", "popleft", "clear", "update",
    "setdefault", "move_to_end", "sort", "reverse", "rotate",
    "__setitem__", "__delitem__",
}

_CTOR_EXEMPT = {"__init__", "__post_init__", "__new__", "__del__"}


def _guard_on_line(mod: ModuleInfo, lineno: int) -> Set[str]:
    """Lock names annotated on ``lineno`` (1-based) or on a
    comment-only line directly above it."""
    locks: Set[str] = set()
    if 1 <= lineno <= len(mod.lines):
        locks.update(_GUARD_RE.findall(mod.lines[lineno - 1]))
    if lineno >= 2:
        above = mod.lines[lineno - 2].strip()
        if above.startswith("#"):
            locks.update(_GUARD_RE.findall(above))
    return locks


def _self_attr(node: ast.AST) -> str | None:
    """'attr' when ``node`` is exactly ``self.attr``."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _collect_guarded(mod: ModuleInfo, cls: ast.ClassDef) -> Dict[str, str]:
    """attr -> lock name, from annotated ``self.attr = ...`` sites."""
    guarded: Dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                for lock in _guard_on_line(mod, node.lineno):
                    guarded[attr] = lock
    return guarded


class _MethodWalker:
    """Walk one method body tracking which locks are lexically held."""

    def __init__(self, mod: ModuleInfo, cls_name: str, method_name: str,
                 guarded: Dict[str, str], findings: List[Finding]) -> None:
        self.mod = mod
        self.cls_name = cls_name
        self.method_name = method_name
        self.guarded = guarded
        self.findings = findings

    def _locks_in_with(self, node: ast.With) -> Set[str]:
        locks: Set[str] = set()
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None:
                locks.add(attr)
        return locks

    def _flag(self, node: ast.AST, attr: str, verb: str) -> None:
        if self.mod.suppressed("GC006", node.lineno):
            return
        lock = self.guarded[attr]
        self.findings.append(Finding(
            "GC006", self.mod.path, node.lineno, node.col_offset,
            f"{self.cls_name}.{attr} is guarded-by self.{lock} but is "
            f"{verb} in {self.method_name}() without holding it; wrap in "
            f"`with self.{lock}:` or annotate the def line if callers "
            f"hold the lock"))

    def _check_target(self, target: ast.AST, node: ast.AST,
                      held: Set[str], verb: str) -> None:
        attr = _self_attr(target)
        if attr is None and isinstance(target, ast.Subscript):
            attr = _self_attr(target.value)
        if attr is None and isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_target(elt, node, held, verb)
            return
        if attr is not None and attr in self.guarded \
                and self.guarded[attr] not in held:
            self._flag(node, attr, verb)

    def _check_exprs(self, node: ast.AST, held: Set[str]) -> None:
        """Expression-level checks over one simple statement (or one
        compound statement's header expression)."""
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                for t in targets:
                    self._check_target(t, sub, held, "assigned")
            elif isinstance(sub, ast.AugAssign):
                self._check_target(sub.target, sub, held, "updated")
            elif isinstance(sub, ast.Delete):
                for t in sub.targets:
                    self._check_target(t, sub, held, "deleted")
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _MUTATORS:
                attr = _self_attr(sub.func.value)
                if attr is not None and attr in self.guarded \
                        and self.guarded[attr] not in held:
                    self._flag(sub, attr, f"mutated via .{sub.func.attr}()")

    def walk(self, stmts, held: Set[str]) -> None:
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Nested def: runs later (thread target, callback) —
                # the lexically held lock does not apply; honor a
                # caller-holds annotation on its own def line.
                self.walk(node.body, _guard_on_line(self.mod, node.lineno))
            elif isinstance(node, ast.With):
                self.walk(node.body, held | self._locks_in_with(node))
            elif hasattr(node, "body"):
                # Compound statement (if/for/while/try/match): check
                # its header expressions, then recurse into each block
                # so nested `with self._lock:` contexts are honored.
                for field in ("test", "iter", "target", "subject"):
                    header = getattr(node, field, None)
                    if header is not None:
                        self._check_exprs(header, held)
                for field in ("body", "orelse", "finalbody"):
                    sub_stmts = getattr(node, field, None)
                    if sub_stmts:
                        self.walk(sub_stmts, held)
                for handler in getattr(node, "handlers", []) or []:
                    self.walk(handler.body, held)
            else:
                self._check_exprs(node, held)


def check_guarded_by(mod: ModuleInfo) -> List[Finding]:
    """Run the GC006 lint over every class in ``mod``."""
    findings: List[Finding] = []
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded = _collect_guarded(mod, cls)
        if not guarded:
            continue
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            if method.name in _CTOR_EXEMPT:
                continue
            held = _guard_on_line(mod, method.lineno)
            _MethodWalker(mod, cls.name, method.name, guarded,
                          findings).walk(method.body, held)
    return findings
