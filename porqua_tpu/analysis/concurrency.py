"""graftcheck concurrency plane: GC008-GC010 static thread-safety rules.

The serve stack runs at least six concurrent actors — caller threads,
the batcher dispatch loop, continuous-cohort steppers, the
RetryManager timer thread, the EventBus/HTTP exposition server, and
breaker probe threads — and every recent review round found real
threading bugs in it. GC006 (:mod:`porqua_tpu.analysis.guards`) only
checks attributes someone remembered to annotate with ``# guarded-by:``;
these rules close that opt-in blindness by *inferring* the lock
discipline from the code:

GC008  **Shared-state inference.** Build a thread-root reachability
       graph — roots are ``threading.Thread(target=...)`` /
       ``threading.Timer`` targets (each spawn site its own root),
       future/timer callbacks (any callable escaping into a call
       argument — ``add_done_callback``, retry-wheel lambdas — one
       root per escape site), ``http.server`` request-handler classes
       (the exposition daemon's threads), and the public API itself
       (every public method, the caller-thread root) — then walk the
       call graph (``self.m()``, attribute calls through inferred
       attribute types, same-module and ``from x import y`` names,
       subclass overrides of inherited thread targets) and flag any
       ``self._x`` *mutated* from two or more distinct roots when the
       mutation site is not inside ``with self.<lock>:``, the method
       does not carry a caller-holds ``# guarded-by:`` def-line
       annotation, and the attribute itself is not ``guarded-by``-
       annotated (annotated attributes are GC006's jurisdiction).
       ``__init__``/``__post_init__``/``__new__``/``__del__`` are
       exempt (the object is not yet / no longer shared), as are
       attributes holding intrinsically thread-safe stdlib objects
       (``threading.Lock``/``Event``/..., ``queue.Queue``/...).

GC009  **Static deadlock detection.** Extract the lock-acquisition-
       order graph: a node per ``(class, lock attribute)`` (lockdep-
       style — instances of one class share a node) or module-level
       lock; an edge ``A -> B`` whenever ``B`` is acquired while ``A``
       is lexically held — including *cross-object* acquisitions
       reached through the call graph (``with self._lock:`` calling a
       method of another class that takes its own lock). Any cycle is
       reported as a potential deadlock with every participating
       acquisition site in the message. ``threading.Condition(lock)``
       attributes alias to their underlying lock's node.

GC010  **Blocking call under a lock.** While a lock is held (lexically
       or transitively through the call graph), flag: untimed
       ``queue.put``/``queue.get`` (receiver inferred as a
       ``queue.Queue``-family object), ``future.result()`` without a
       timeout, ``.block_until_ready()``, AOT compilation
       (``aot_compile_*`` / ``jit(...).lower(...)`` /
       ``.lower(...).compile()``), ``time.sleep``, and socket/HTTP
       calls (``socket.*``, ``urllib.request.urlopen``,
       ``requests.*``). ``Condition.wait`` is exempt (it releases the
       lock), as is anything carrying an explicit timeout — the rule
       targets *unbounded* waits and multi-second work that wedge
       every other thread contending for the lock.

The runtime half of this plane is :mod:`porqua_tpu.analysis.tsan`
(``PORQUA_TSAN=1``): the same acquisition-order discipline enforced on
live lock operations, so an inversion the static pass cannot see
(dynamic dispatch, callbacks) still raises under the stress passes.

All three rules run over the same :class:`~porqua_tpu.analysis.lint.
ModuleInfo` set as GC001-GC007 (one parse per file) via
:func:`check_concurrency`; they are pure stdlib ``ast``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from porqua_tpu.analysis.lint import Finding, ModuleInfo

__all__ = ["check_concurrency"]

#: Constructors whose instances are intrinsically thread-safe: mutation
#: through their methods needs no external lock, so GC008 skips
#: attributes initialized to one of these.
_THREADSAFE_CTORS = {
    ("threading", "Lock"), ("threading", "RLock"), ("threading", "Event"),
    ("threading", "Condition"), ("threading", "Semaphore"),
    ("threading", "BoundedSemaphore"), ("threading", "Barrier"),
    ("queue", "Queue"), ("queue", "SimpleQueue"), ("queue", "LifoQueue"),
    ("queue", "PriorityQueue"),
}

#: Constructors marking an attribute/local as a queue for GC010's
#: untimed put/get check.
_QUEUE_CTORS = {("queue", "Queue"), ("queue", "SimpleQueue"),
                ("queue", "LifoQueue"), ("queue", "PriorityQueue")}

#: The tsan drop-in lock factory also mints lock objects (GC008's
#: thread-safe exemption and GC009's lock-attr detection both honor
#: it): ``self._lock = tsan.lock("...")``.
_TSAN_FACTORIES = {"lock"}

_CTOR_EXEMPT = {"__init__", "__post_init__", "__new__", "__del__"}

#: Call-argument callables passed to these heads run on the *calling*
#: thread (tracing/functional wrappers), not on a new root.
_SAME_THREAD_HEADS = {"jax", "jnp", "functools", "np", "numpy", "sorted",
                      "min", "max", "map", "filter"}

_API_ROOT = "api"


def _is_property_def(node: ast.AST) -> bool:
    """A @property / @cached_property / @x.setter-decorated def:
    ``self.name`` referencing it is an attribute ACCESS, not a bound
    method escaping as a callback."""
    for dec in getattr(node, "decorator_list", ()):
        if isinstance(dec, ast.Name) \
                and dec.id in ("property", "cached_property"):
            return True
        if isinstance(dec, ast.Attribute) \
                and dec.attr in ("setter", "getter", "deleter"):
            return True
    return False


def _is_public_entry(name: str) -> bool:
    """Methods reachable from arbitrary caller threads: the public
    surface plus the dunders callers invoke (``with svc:``, len,
    call)."""
    if not name.startswith("_"):
        return True
    return name in ("__call__", "__enter__", "__exit__", "__len__",
                    "__contains__", "__iter__", "__getitem__")


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------

class _Class:
    """One scanned class: methods, resolved bases, inferred attribute
    types, guarded-by map, and lock-alias table."""

    def __init__(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        self.mod = mod
        self.node = node
        self.name = node.name
        self.methods: Dict[str, ast.AST] = {}
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[stmt.name] = stmt
        self.bases: List["_Class"] = []          # resolved later
        self.base_names: List[str] = []
        for b in node.bases:
            chain = mod.attr_chain(b)
            if chain:
                self.base_names.append(chain[-1])
        self.attr_types: Dict[str, Set["_Class"]] = {}
        #: attrs initialized to thread-safe stdlib objects
        self.threadsafe_attrs: Set[str] = set()
        #: attrs initialized to queue.Queue-family objects
        self.queue_attrs: Set[str] = set()
        #: attrs that look like locks (Lock/RLock ctor or tsan.lock)
        self.lock_attrs: Set[str] = set()
        #: Condition attr -> underlying lock attr
        self.lock_aliases: Dict[str, str] = {}
        self.guarded: Dict[str, str] = {}        # attr -> lock (GC006 map)

    def mro(self) -> List["_Class"]:
        out, seen = [], set()
        stack = [self]
        while stack:
            c = stack.pop(0)
            if id(c) in seen:
                continue
            seen.add(id(c))
            out.append(c)
            stack.extend(c.bases)
        return out

    def find_method(self, name: str) -> List[Tuple["_Class", ast.AST]]:
        for c in self.mro():
            if name in c.methods:
                return [(c, c.methods[name])]
        return []


class _Analyzer:
    """Shared cross-module model for the three rules."""

    def __init__(self, mods: Sequence[ModuleInfo]) -> None:
        from porqua_tpu.analysis.guards import _collect_guarded

        self.mods = mods
        self.by_modname: Dict[str, ModuleInfo] = {}
        for m in mods:
            dotted = m.posix.lstrip("/").removesuffix(".py").replace("/", ".")
            self.by_modname[dotted] = m
        # class registry
        self.classes: List[_Class] = []
        self.class_of_node: Dict[int, _Class] = {}
        self.classes_by_name: Dict[str, List[_Class]] = {}
        for mod in mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    c = _Class(mod, node)
                    c.guarded = _collect_guarded(mod, node)
                    self.classes.append(c)
                    self.class_of_node[id(node)] = c
                    self.classes_by_name.setdefault(c.name, []).append(c)
        for c in self.classes:
            for bname in c.base_names:
                rc = self._resolve_class_name(c.mod, bname)
                if rc is not None:
                    c.bases.append(rc)
        self.subclasses: Dict[int, List[_Class]] = {}
        for c in self.classes:
            for anc in c.mro()[1:]:
                self.subclasses.setdefault(id(anc), []).append(c)
        #: function node -> enclosing class (methods and nested defs)
        self.owner: Dict[int, Optional[_Class]] = {}
        for mod in mods:
            self._map_owners(mod.tree, None)
        for c in self.classes:
            self._infer_attr_types(c)

    # -- registry helpers --------------------------------------------

    def _module_for(self, dotted: str) -> Optional[ModuleInfo]:
        if dotted in self.by_modname:
            return self.by_modname[dotted]
        for name, m in self.by_modname.items():
            if name.endswith("." + dotted) or dotted.endswith("." + name):
                return m
        return None

    def _resolve_class_name(self, mod: ModuleInfo,
                            name: str) -> Optional[_Class]:
        """``name`` used in ``mod``: a class defined there, or imported
        from a scanned module."""
        for c in self.classes_by_name.get(name, ()):
            if c.mod is mod:
                return c
        if name in mod.imported_from:
            src, orig = mod.imported_from[name]
            target = self._module_for(src)
            if target is not None:
                for c in self.classes_by_name.get(orig, ()):
                    if c.mod is target:
                        return c
        return None

    def _map_owners(self, node: ast.AST,
                    cls: Optional[_Class]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._map_owners(child, self.class_of_node[id(child)])
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                self.owner[id(child)] = cls
                # nested defs keep the method's class for `self`
                self._map_owners(child, cls)
            else:
                self._map_owners(child, cls)

    # -- attribute type inference ------------------------------------

    def _classes_in_expr(self, mod: ModuleInfo,
                         expr: ast.AST) -> Set[_Class]:
        out: Set[_Class] = set()
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                chain = mod.attr_chain(sub.func)
                if chain and len(chain) == 1:
                    rc = self._resolve_class_name(mod, chain[0])
                    if rc is not None:
                        out.add(rc)
        return out

    def _names_in_annotation(self, mod: ModuleInfo,
                             ann: ast.AST) -> Set[_Class]:
        out: Set[_Class] = set()
        for sub in ast.walk(ann):
            if isinstance(sub, ast.Name):
                rc = self._resolve_class_name(mod, sub.id)
                if rc is not None:
                    out.add(rc)
            elif isinstance(sub, ast.Constant) \
                    and isinstance(sub.value, str):
                # forward references: `owner: "Outer"`
                try:
                    parsed = ast.parse(sub.value, mode="eval")
                except SyntaxError:
                    continue
                for leaf in ast.walk(parsed):
                    if isinstance(leaf, ast.Name):
                        rc = self._resolve_class_name(mod, leaf.id)
                        if rc is not None:
                            out.add(rc)
        return out

    @staticmethod
    def _ctor_id(mod: ModuleInfo, expr: ast.AST) -> Optional[Tuple[str, str]]:
        """``(module, Name)`` for a stdlib constructor call like
        ``threading.Lock()`` / ``queue.Queue(...)`` under any import
        style."""
        if not isinstance(expr, ast.Call):
            return None
        chain = mod.attr_chain(expr.func)
        if not chain:
            return None
        if len(chain) == 1:
            imp = mod.imported_from.get(chain[0])
            return (imp[0], imp[1]) if imp else None
        head = mod.module_aliases.get(chain[0], chain[0])
        return (head, chain[-1])

    def _infer_attr_types(self, c: _Class) -> None:
        mod = c.mod
        # __init__ parameter annotations feeding `self.x = param`
        param_ann: Dict[str, Set[_Class]] = {}
        init = c.methods.get("__init__")
        if init is not None:
            args = init.args
            for a in args.posonlyargs + args.args + args.kwonlyargs:
                if a.annotation is not None:
                    param_ann[a.arg] = self._names_in_annotation(
                        mod, a.annotation)
        for node in ast.walk(c.node):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            value = node.value
            if value is None:
                continue
            for t in targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                attr = t.attr
                found = self._classes_in_expr(mod, value)
                if isinstance(value, ast.Name) and value.id in param_ann:
                    found |= param_ann[value.id]
                if found:
                    self.attr_union(c, attr, found)
                ctor = self._ctor_id(mod, value)
                # `cond_expr if x else y`: look inside for ctor calls
                ctors = {self._ctor_id(mod, sub)
                         for sub in ast.walk(value)
                         if isinstance(sub, ast.Call)}
                ctors.discard(None)
                if ctor is not None:
                    ctors.add(ctor)
                for cid in ctors:
                    if cid in _THREADSAFE_CTORS:
                        c.threadsafe_attrs.add(attr)
                    if cid in _QUEUE_CTORS:
                        c.queue_attrs.add(attr)
                    if cid in (("threading", "Lock"), ("threading", "RLock")):
                        c.lock_attrs.add(attr)
                    if cid is not None and cid[0].endswith("tsan") \
                            and cid[1] in _TSAN_FACTORIES:
                        c.lock_attrs.add(attr)
                        c.threadsafe_attrs.add(attr)
                    if cid == ("threading", "Condition"):
                        c.lock_attrs.add(attr)
                        # Condition(self._lock): alias to the real lock
                        for sub in ast.walk(value):
                            if isinstance(sub, ast.Call):
                                sid = self._ctor_id(mod, sub)
                                if sid == ("threading", "Condition") \
                                        and sub.args:
                                    a0 = sub.args[0]
                                    if isinstance(a0, ast.Attribute) \
                                            and isinstance(a0.value, ast.Name) \
                                            and a0.value.id == "self":
                                        c.lock_aliases[attr] = a0.attr

    @staticmethod
    def attr_union(c: _Class, attr: str, found: Set[_Class]) -> None:
        c.attr_types.setdefault(attr, set()).update(found)

    # -- call resolution ---------------------------------------------

    def resolve_call(self, mod: ModuleInfo, cls: Optional[_Class],
                     call: ast.Call
                     ) -> List[Tuple[ModuleInfo, Optional[_Class], ast.AST]]:
        """Callee candidates for one call site. Deliberately narrow:
        bare names (local defs + ``from x import y``), ``self.m()``
        (MRO + subclass overrides), ``self.attr.m()`` through inferred
        attribute types, ``module_alias.f()``. Unresolvable attribute
        calls resolve to nothing — cross-module resolution by bare
        method name would drown the rules in name-collision edges."""
        func = call.func
        out: List[Tuple[ModuleInfo, Optional[_Class], ast.AST]] = []
        if isinstance(func, ast.Name):
            name = func.id
            if name in mod.imported_from:
                src, orig = mod.imported_from[name]
                target = self._module_for(src)
                if target is not None:
                    for node in target.defs_by_name.get(orig, ()):
                        out.append((target, self.owner.get(id(node)), node))
                    return out
            for node in mod.defs_by_name.get(name, ()):
                owner = self.owner.get(id(node))
                # bare-name calls cannot reach methods of other classes
                if owner is None or owner is cls:
                    out.append((mod, owner, node))
            return out
        chain = mod.attr_chain(func)
        if chain is None:
            return out
        if len(chain) == 2 and chain[0] == "self" and cls is not None:
            for c, node in cls.find_method(chain[1]):
                out.append((c.mod, c, node))
            # a Thread target bound on a base may run a subclass
            # override — include them so inherited dispatch loops are
            # walked at the subclass too.
            for sub in self.subclasses.get(id(cls), ()):
                if chain[1] in sub.methods:
                    out.append((sub.mod, sub, sub.methods[chain[1]]))
            return out
        if len(chain) == 3 and chain[0] == "self" and cls is not None:
            for c in self.mro_attr_types(cls, chain[1]):
                for cc, node in c.find_method(chain[2]):
                    out.append((cc.mod, cc, node))
            return out
        if len(chain) == 2 and chain[0] in mod.module_aliases:
            target = self._module_for(mod.module_aliases[chain[0]])
            if target is not None:
                for node in target.defs_by_name.get(chain[1], ()):
                    if self.owner.get(id(node)) is None:
                        out.append((target, None, node))
        return out

    @staticmethod
    def mro_attr_types(cls: _Class, attr: str) -> Set[_Class]:
        out: Set[_Class] = set()
        for c in cls.mro():
            out |= c.attr_types.get(attr, set())
        return out

    def mro_flag(self, cls: Optional[_Class], attr: str,
                 field: str) -> bool:
        if cls is None:
            return False
        return any(attr in getattr(c, field) for c in cls.mro())

    def mro_guard(self, cls: Optional[_Class],
                  attr: str) -> Optional[str]:
        if cls is None:
            return None
        for c in cls.mro():
            if attr in c.guarded:
                return c.guarded[attr]
        return None

    def lock_node(self, cls: Optional[_Class], mod: ModuleInfo,
                  attr: str) -> str:
        """Lockdep-style node name for one acquisition: per class (the
        base-most class in the scanned hierarchy that inits the lock),
        aliases (Condition) folded onto the underlying lock."""
        if cls is not None:
            for c in cls.mro():
                if attr in c.lock_aliases:
                    attr = c.lock_aliases[attr]
                    break
            owner = cls
            for c in reversed(cls.mro()):
                if attr in c.lock_attrs or attr in c.guarded.values():
                    owner = c
                    break
            return f"{owner.name}.{attr}"
        base = mod.posix.rsplit("/", 1)[-1].removesuffix(".py")
        return f"{base}.{attr}"


# ---------------------------------------------------------------------------
# thread roots
# ---------------------------------------------------------------------------

def _thread_ctor_kind(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    cid = _Analyzer._ctor_id(mod, call)
    if cid == ("threading", "Thread"):
        return "thread"
    if cid == ("threading", "Timer"):
        return "timer"
    return None


class _Roots:
    """Root set + reachability: maps every (function node) to the set
    of thread roots that can execute it."""

    def __init__(self, an: _Analyzer) -> None:
        self.an = an
        #: (id(func node)) -> set of root ids
        self.roots_of: Dict[int, Set[str]] = {}
        self.work: List[Tuple[ModuleInfo, Optional[_Class], ast.AST, str]] = []

    def _add(self, mod: ModuleInfo, cls: Optional[_Class],
             node: ast.AST, root: str) -> None:
        pool = self.roots_of.setdefault(id(node), set())
        if root not in pool:
            pool.add(root)
            self.work.append((mod, cls, node, root))

    def _add_callable_expr(self, mod: ModuleInfo, cls: Optional[_Class],
                           expr: ast.AST, root: str) -> None:
        if isinstance(expr, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            self._add(mod, self.an.owner.get(id(expr), cls), expr, root)
            return
        if isinstance(expr, ast.Name):
            for node in mod.defs_by_name.get(expr.id, ()):
                owner = self.an.owner.get(id(node))
                if owner is None or owner is cls:
                    self._add(mod, owner, node, root)
            return
        chain = mod.attr_chain(expr)
        if chain and len(chain) == 2 and chain[0] == "self" \
                and cls is not None:
            for c, node in cls.find_method(chain[1]):
                if not _is_property_def(node):
                    self._add(c.mod, c, node, root)
            for sub in self.an.subclasses.get(id(cls), ()):
                if chain[1] in sub.methods \
                        and not _is_property_def(sub.methods[chain[1]]):
                    self._add(sub.mod, sub, sub.methods[chain[1]], root)

    def collect(self) -> None:
        an = self.an
        for mod in an.mods:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                cls = self._enclosing_class(mod, node)
                kind = _thread_ctor_kind(mod, node)
                if kind == "thread":
                    name = None
                    target = None
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = kw.value
                        elif kw.arg == "name" and isinstance(
                                kw.value, ast.Constant):
                            name = kw.value.value
                    # positional: Thread(group, target, name, ...) —
                    # the FIRST slot is group, not target.
                    if target is None and len(node.args) >= 2:
                        target = node.args[1]
                    if target is not None:
                        root = f"thread:{name or f'{mod.path}:{node.lineno}'}"
                        self._add_callable_expr(mod, cls, target, root)
                    continue
                if kind == "timer":
                    # Timer(interval, function, ...) — function may be
                    # positional or the `function=` keyword.
                    fn_expr = (node.args[1] if len(node.args) >= 2
                               else None)
                    if fn_expr is None:
                        for kw in node.keywords:
                            if kw.arg == "function":
                                fn_expr = kw.value
                    if fn_expr is not None:
                        self._add_callable_expr(
                            mod, cls, fn_expr,
                            f"timer:{mod.path}:{node.lineno}")
                    continue
                # escaping callables: a lambda/def handed into ANY call
                # runs on whatever thread the holder chooses (future
                # callbacks, timer wheels) — its own root per site.
                # Tracing/functional heads (jax.*, functools.partial)
                # run the callable on the calling thread; their args
                # are walked as part of the enclosing function instead.
                head_chain = mod.attr_chain(node.func)
                head = head_chain[0] if head_chain else None
                if head in _SAME_THREAD_HEADS \
                        or head in mod.jnp_aliases or head in mod.jax_aliases \
                        or head in mod.np_aliases \
                        or head in mod.functools_aliases \
                        or (head is not None and head in mod.partial_names):
                    continue
                # **spread keywords (kw.arg None) unpack DATA mappings
                # (`f(**self.arguments)`), never escape a callable.
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords
                                              if kw.arg is not None]:
                    if isinstance(arg, ast.Lambda):
                        self._add(mod, an.owner.get(id(arg), cls), arg,
                                  f"cb:{mod.path}:{arg.lineno}")
                    elif isinstance(arg, ast.Attribute):
                        # a BOUND METHOD escaping as a callback
                        # (fut.add_done_callback(self._on_done)) is a
                        # root exactly like a lambda; _add_callable_expr
                        # only roots names that resolve to methods, so
                        # data attributes passed as arguments add
                        # nothing.
                        self._add_callable_expr(
                            mod, cls, arg,
                            f"cb:{mod.path}:{arg.lineno}")
        # HTTP handler classes: every method runs on a server thread.
        for c in an.classes:
            if any(b == "BaseHTTPRequestHandler" for b in c.base_names):
                for name, meth in c.methods.items():
                    if name not in _CTOR_EXEMPT:
                        self._add(c.mod, c, meth, "http-handler")
        # the caller-thread root: the public API surface
        for c in an.classes:
            for name, meth in c.methods.items():
                if _is_public_entry(name):
                    self._add(c.mod, c, meth, _API_ROOT)
        for mod in an.mods:
            for name, nodes in mod.defs_by_name.items():
                for node in nodes:
                    if an.owner.get(id(node)) is None \
                            and not name.startswith("_"):
                        self._add(mod, None, node, _API_ROOT)

    def _enclosing_class(self, mod: ModuleInfo,
                         node: ast.AST) -> Optional[_Class]:
        n = getattr(node, "_gc_parent", None)
        while n is not None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return self.an.owner.get(id(n))
            if isinstance(n, ast.ClassDef):
                return self.an.class_of_node[id(n)]
            n = getattr(n, "_gc_parent", None)
        return None

    def run(self) -> None:
        self.collect()
        an = self.an
        while self.work:
            mod, cls, fn, root = self.work.pop()
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                if _thread_ctor_kind(mod, sub) is not None:
                    continue  # spawns a root, handled in collect()
                for (m2, c2, n2) in an.resolve_call(mod, cls, sub):
                    self._add(m2, c2, n2, root)


# ---------------------------------------------------------------------------
# GC008 — shared-state inference
# ---------------------------------------------------------------------------

def _held_locks_at(node: ast.AST) -> Set[str]:
    """Lock attrs lexically held at ``node`` via ``with self.X:``
    contexts inside the enclosing function (nested defs break the
    chain — they run later, without the lock)."""
    held: Set[str] = set()
    child: ast.AST = node
    anc = getattr(node, "_gc_parent", None)
    while anc is not None:
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            break
        if isinstance(anc, ast.With) and child in anc.body:
            for item in anc.items:
                ce = item.context_expr
                if isinstance(ce, ast.Attribute) \
                        and isinstance(ce.value, ast.Name) \
                        and ce.value.id == "self":
                    held.add(ce.attr)
        child = anc
        anc = getattr(anc, "_gc_parent", None)
    return held


def _enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    anc = getattr(node, "_gc_parent", None)
    while anc is not None:
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return anc
        anc = getattr(anc, "_gc_parent", None)
    return None


def _enclosing_method(node: ast.AST) -> Optional[ast.AST]:
    """The outermost enclosing function whose parent is a ClassDef."""
    fn = _enclosing_function(node)
    while fn is not None:
        parent = getattr(fn, "_gc_parent", None)
        if isinstance(parent, ast.ClassDef):
            return fn
        fn = _enclosing_function(fn)
    return None


def _iter_self_mutations(mod: ModuleInfo, fn: ast.AST
                         ) -> Iterable[Tuple[str, ast.AST, str]]:
    """(attr, site node, verb) for every ``self.attr`` mutation inside
    ``fn``'s own body (nested defs excluded — they are walked as their
    own functions)."""
    from porqua_tpu.analysis.guards import _MUTATORS, _self_attr

    def own(node: ast.AST) -> bool:
        return _enclosing_function(node) is fn

    def targets_of(t: ast.AST) -> Iterable[str]:
        attr = _self_attr(t)
        if attr is None and isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
        if attr is not None:
            yield attr
        elif isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                yield from targets_of(elt)

    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            continue
        if not own(node):
            continue
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in tgts:
                for attr in targets_of(t):
                    yield attr, node, "assigned"
        elif isinstance(node, ast.AugAssign):
            for attr in targets_of(node.target):
                yield attr, node, "updated"
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                for attr in targets_of(t):
                    yield attr, node, "deleted"
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            attr = _self_attr(node.func.value)
            if attr is not None:
                yield attr, node, f"mutated via .{node.func.attr}()"


def _check_gc008(an: _Analyzer, roots: _Roots) -> List[Finding]:
    from porqua_tpu.analysis.guards import _guard_on_line

    # (hierarchy-root class, attr) -> list of
    #   (cls, mod, site, verb, roots, protected)
    records: Dict[Tuple[int, str], List[tuple]] = {}
    anchor: Dict[int, _Class] = {}

    for c in an.classes:
        chain = c.mro()
        anchor[id(c)] = chain[-1] if chain else c

    for c in an.classes:
        mod = c.mod
        for mname, meth in c.methods.items():
            if mname in _CTOR_EXEMPT:
                continue
            fns = [meth] + [n for n in ast.walk(meth)
                            if isinstance(n, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.Lambda)) and n is not meth]
            for fn in fns:
                fn_roots = roots.roots_of.get(id(fn), set())
                if not fn_roots:
                    continue
                caller_holds = _guard_on_line(mod, fn.lineno) \
                    if not isinstance(fn, ast.Lambda) else set()
                for attr, site, verb in _iter_self_mutations(mod, fn):
                    if an.mro_guard(c, attr) is not None:
                        continue  # GC006's jurisdiction
                    if verb.startswith("mutated via") \
                            and an.mro_flag(c, attr, "threadsafe_attrs"):
                        continue  # Queue.put / Event.clear etc.
                    held = _held_locks_at(site) | caller_holds
                    protected = bool(held)
                    key = (id(anchor[id(c)]), attr)
                    records.setdefault(key, []).append(
                        (c, mod, site, verb, frozenset(fn_roots),
                         protected))

    out: List[Finding] = []
    # dedup per (path, line, ATTR): `self._a, self._b = f()` mutates
    # two attributes on one line — both must be reported, or one scan
    # understates the unguarded surface.
    seen: Set[Tuple[str, int, str]] = set()
    for (_, attr), recs in records.items():
        all_roots: Set[str] = set()
        for _, _, _, _, rts, _ in recs:
            all_roots |= rts
        if len(all_roots) < 2:
            continue
        for c, mod, site, verb, _, protected in recs:
            if protected:
                continue
            if mod.suppressed("GC008", site.lineno):
                continue
            key = (mod.path, site.lineno, attr)
            if key in seen:
                continue
            seen.add(key)
            roots_desc = ", ".join(sorted(all_roots))
            out.append(Finding(
                "GC008", mod.path, site.lineno, site.col_offset,
                f"{c.name}.{attr} is {verb} here but is written from "
                f"multiple thread roots ({roots_desc}) with no lock "
                f"held; wrap in `with self.<lock>:` or annotate the "
                f"attribute `# guarded-by: self.<lock>` (GC006 then "
                f"enforces it)"))
    return out


# ---------------------------------------------------------------------------
# lock regions (shared by GC009/GC010)
# ---------------------------------------------------------------------------

class _Region:
    """One lexically-held lock: the With node (or guarded-by method
    body) plus everything needed to attribute findings."""

    def __init__(self, node: ast.AST, mod: ModuleInfo,
                 cls: Optional[_Class], lock_attr: str,
                 body: List[ast.AST]) -> None:
        self.node = node
        self.mod = mod
        self.cls = cls
        self.lock_attr = lock_attr
        self.body = body

    def site(self) -> str:
        return f"{self.mod.path}:{self.node.lineno}"


def _module_lock_names(mod: ModuleInfo) -> Set[str]:
    """Module-level names bound to Lock/RLock constructors."""
    out: Set[str] = set()
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Call):
            cid = _Analyzer._ctor_id(mod, stmt.value)
            if cid in (("threading", "Lock"), ("threading", "RLock")):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _iter_regions(an: _Analyzer) -> Iterable[_Region]:
    from porqua_tpu.analysis.guards import _guard_on_line

    for mod in an.mods:
        mod_locks = _module_lock_names(mod)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.With):
                fn = _enclosing_function(node)
                cls = an.owner.get(id(fn)) if fn is not None else None
                for item in node.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Attribute) \
                            and isinstance(ce.value, ast.Name) \
                            and ce.value.id == "self" and cls is not None:
                        yield _Region(node, mod, cls, ce.attr, node.body)
                    elif isinstance(ce, ast.Name) and ce.id in mod_locks:
                        yield _Region(node, mod, None, ce.id, node.body)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls = an.owner.get(id(node))
                if cls is None:
                    continue
                for lock in _guard_on_line(mod, node.lineno):
                    # caller-holds methods: body runs under the lock
                    yield _Region(node, mod, cls, lock, node.body)


def _walk_region(an: _Analyzer, region: _Region, visit_fn) -> None:
    """Call ``visit_fn(mod, cls, fn_node_or_None, stmt_iterable,
    depth, path)`` for the region body and, transitively, every
    resolvable callee body (bounded)."""
    seen: Set[int] = set()

    # Direct body: all nodes excluding nested function bodies (those
    # run later, without the lock).
    def iter_nodes(body, owner_fn):
        for stmt in body:
            for sub in ast.walk(stmt):
                if _enclosing_function(sub) is owner_fn:
                    yield sub

    def recurse(mod, cls, body, owner_fn, depth, path):
        nodes = list(iter_nodes(body, owner_fn))
        visit_fn(mod, cls, nodes, depth, path)
        if depth >= 6:
            return
        for sub in nodes:
            if not isinstance(sub, ast.Call):
                continue
            if _thread_ctor_kind(mod, sub) is not None:
                continue  # spawning a thread is not calling its target
            for (m2, c2, n2) in an.resolve_call(mod, cls, sub):
                if id(n2) in seen or isinstance(n2, ast.Lambda):
                    continue
                seen.add(id(n2))
                name = getattr(n2, "name", "<fn>")
                recurse(m2, c2, n2.body, n2, depth + 1,
                        path + [f"{name}() at {m2.path}:{n2.lineno}"])

    owner = (region.node if isinstance(
        region.node, (ast.FunctionDef, ast.AsyncFunctionDef))
        else _enclosing_function(region.node))
    recurse(region.mod, region.cls, region.body, owner, 0, [])


# ---------------------------------------------------------------------------
# GC009 — static deadlock detection
# ---------------------------------------------------------------------------

def _check_gc009(an: _Analyzer) -> List[Finding]:
    #: edge (held lock node -> acquired lock node) ->
    #:   (outer-region mod/node, inner-acquisition mod/node)
    edges: Dict[Tuple[str, str],
                Tuple[ModuleInfo, ast.AST, ModuleInfo, ast.AST]] = {}

    for region in _iter_regions(an):
        held = an.lock_node(region.cls, region.mod, region.lock_attr)

        def visit(mod, cls, nodes, depth, path,
                  held=held, region=region):
            for sub in nodes:
                if not isinstance(sub, ast.With):
                    continue
                for item in sub.items:
                    ce = item.context_expr
                    acquired: Optional[str] = None
                    if isinstance(ce, ast.Attribute) \
                            and isinstance(ce.value, ast.Name) \
                            and ce.value.id == "self" \
                            and cls is not None \
                            and an.mro_flag(cls, ce.attr, "lock_attrs"):
                        acquired = an.lock_node(cls, mod, ce.attr)
                    elif isinstance(ce, ast.Name) \
                            and ce.id in _module_lock_names(mod):
                        acquired = an.lock_node(None, mod, ce.id)
                    if acquired is not None and acquired != held:
                        edges.setdefault(
                            (held, acquired),
                            (region.mod, region.node, mod, sub))

        _walk_region(an, region, visit)

    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    out: List[Finding] = []
    reported: Set[frozenset] = set()

    def dfs(start: str, node: str, path: List[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = frozenset(path)
                if cyc in reported:
                    continue
                reported.add(cyc)
                cycle_nodes = path + [start]
                sites = []
                for a, b in zip(cycle_nodes, cycle_nodes[1:]):
                    emod, enode, imod, isite = edges[(a, b)]
                    sites.append(
                        f"{a} -> {b} (held at {emod.path}:{enode.lineno}"
                        f", acquired at {imod.path}:{isite.lineno})")
                emod, enode, _, _ = edges[(cycle_nodes[0], cycle_nodes[1])]
                if not emod.suppressed("GC009", enode.lineno):
                    out.append(Finding(
                        "GC009", emod.path, enode.lineno, enode.col_offset,
                        "lock-order cycle (potential deadlock): "
                        + "; ".join(sites)
                        + " — acquire these locks in one global order"))
            elif nxt not in path and nxt > start:
                # Only walk nodes > start: each cycle is enumerated
                # exactly once, rooted at its smallest node.
                dfs(start, nxt, path + [nxt])

    for start in sorted(graph):
        dfs(start, start, [start])
    return out


# ---------------------------------------------------------------------------
# GC010 — blocking call under a lock
# ---------------------------------------------------------------------------

def _queue_typed(an: _Analyzer, mod: ModuleInfo, cls: Optional[_Class],
                 nodes_fn: ast.AST, recv: ast.AST) -> bool:
    """Is ``recv`` a queue.Queue-family object? self.attr via inferred
    attr kinds; bare local names via same-function ctor assignment."""
    if isinstance(recv, ast.Attribute) and isinstance(recv.value, ast.Name):
        if recv.value.id == "self":
            return an.mro_flag(cls, recv.attr, "queue_attrs")
        # two-level: self.batcher.queue — look through one typed hop
    chain = mod.attr_chain(recv)
    if chain and len(chain) == 3 and chain[0] == "self" and cls is not None:
        for c in an.mro_attr_types(cls, chain[1]):
            if an.mro_flag(c, chain[2], "queue_attrs"):
                return True
    if isinstance(recv, ast.Name):
        fn = nodes_fn
        if fn is not None:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign) \
                        and isinstance(sub.value, ast.Call) \
                        and _Analyzer._ctor_id(mod, sub.value) in _QUEUE_CTORS:
                    for t in sub.targets:
                        if isinstance(t, ast.Name) and t.id == recv.id:
                            return True
    return False


def _has_timeout(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return True
        # block=False is a NON-blocking call; block=True (or a
        # non-constant) leaves the wait unbounded and exempts nothing.
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    return False


def _queue_wait_unbounded(meth: str, call: ast.Call) -> bool:
    """Is this queue ``get``/``put`` an UNBOUNDED wait? Keyword and
    positional spellings both count — ``get(block, timeout)``,
    ``put(item, block, timeout)``: ``block=False`` (either spelling)
    is non-blocking, any timeout bounds the wait."""
    if _has_timeout(call):
        return False
    block_pos = 0 if meth == "get" else 1
    args = call.args
    if len(args) > block_pos:
        blk = args[block_pos]
        if isinstance(blk, ast.Constant) and blk.value is False:
            return False
    if len(args) > block_pos + 1:
        return False  # positional timeout present
    return True


def _condition_typed(an: _Analyzer, cls: Optional[_Class],
                     recv: ast.AST) -> bool:
    """Is ``recv`` a ``self.<attr>`` known to be a
    ``threading.Condition`` (recorded in the class's lock-alias
    table)? Condition.wait releases its lock while blocked — the one
    ``.wait`` that is correct under that lock."""
    if cls is None or not isinstance(recv, ast.Attribute) \
            or not isinstance(recv.value, ast.Name) \
            or recv.value.id != "self":
        return False
    return any(recv.attr in c.lock_aliases for c in cls.mro())


def _blocking_what(an: _Analyzer, mod: ModuleInfo, cls: Optional[_Class],
                   fn: Optional[ast.AST], call: ast.Call) -> Optional[str]:
    func = call.func
    chain = mod.attr_chain(func)
    if isinstance(func, ast.Attribute):
        meth = func.attr
        if meth == "block_until_ready":
            return ".block_until_ready()"
        if meth == "result" and not call.args and not _has_timeout(call):
            return "future.result() with no timeout"
        if meth in ("get", "put") and _queue_wait_unbounded(meth, call) \
                and _queue_typed(an, mod, cls, fn, func.value):
            return f"untimed queue.{meth}()"
        if meth == "compile":
            src = ast.unparse(func.value)
            if "lower(" in src or "jit(" in src:
                return "AOT compile (.lower(...).compile())"
        if meth == "lower":
            src = ast.unparse(func.value)
            if "jit(" in src:
                return "AOT trace (jit(...).lower(...))"
    if chain:
        head = mod.module_aliases.get(chain[0], chain[0])
        imp = mod.imported_from.get(chain[0])
        if len(chain) == 2 and head == "time" and chain[1] == "sleep":
            return "time.sleep()"
        if imp is not None and imp == ("time", "sleep"):
            return "time.sleep()"
        if head == "socket" and len(chain) >= 2:
            return f"socket call socket.{'.'.join(chain[1:])}()"
        if head == "requests" and len(chain) == 2:
            return f"HTTP call requests.{chain[1]}()"
        if chain[-1] == "urlopen":
            if (imp is not None and imp[0].startswith("urllib")) \
                    or head.startswith("urllib"):
                return "HTTP call urlopen()"
        if chain[-1].startswith("aot_compile"):
            return f"AOT compile ({chain[-1]})"
    return None


def _check_gc010(an: _Analyzer) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()

    for region in _iter_regions(an):
        lock_name = an.lock_node(region.cls, region.mod, region.lock_attr)
        # Condition.wait is the one blocking call that's CORRECT under
        # its own lock (it releases it); the region's lock aliases to
        # the condition's underlying lock, so exempt wait entirely.

        def visit(mod, cls, nodes, depth, path,
                  lock_name=lock_name, region=region):
            for sub in nodes:
                if not isinstance(sub, ast.Call):
                    continue
                if isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "wait":
                    # Exempt only Condition.wait (it RELEASES the lock
                    # while blocked) and timeout-bounded waits. An
                    # untimed Event.wait() under a lock is the
                    # unbounded-wait deadlock this rule exists for:
                    # the setter may need the very lock we hold.
                    if sub.args or _has_timeout(sub) \
                            or _condition_typed(an, cls, sub.func.value):
                        continue
                    what = "untimed .wait()"
                else:
                    owner_fn = _enclosing_function(sub)
                    what = _blocking_what(an, mod, cls, owner_fn, sub)
                if what is None:
                    continue
                key = (mod.path, sub.lineno, lock_name)
                if key in seen or mod.suppressed("GC010", sub.lineno):
                    continue
                seen.add(key)
                via = (f" (reached via {' -> '.join(path)})"
                       if path else "")
                out.append(Finding(
                    "GC010", mod.path, sub.lineno, sub.col_offset,
                    f"{what} while holding {lock_name} (acquired at "
                    f"{region.site()}){via}; blocking work under a "
                    f"lock wedges every thread contending for it — "
                    f"move it outside the critical section or bound "
                    f"it with a timeout"))

        _walk_region(an, region, visit)
    return out


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def check_concurrency(mods: Sequence[ModuleInfo],
                      rules: Optional[Set[str]] = None) -> List[Finding]:
    """Run GC008/GC009/GC010 over an already-parsed module set."""
    def want(rule: str) -> bool:
        return rules is None or rule in rules

    an = _Analyzer(mods)
    out: List[Finding] = []
    if want("GC008"):
        roots = _Roots(an)
        roots.run()
        out.extend(_check_gc008(an, roots))
    if want("GC009"):
        out.extend(_check_gc009(an))
    if want("GC010"):
        out.extend(_check_gc010(an))
    return out
