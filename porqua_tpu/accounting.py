"""Device-vectorized portfolio accounting: the whole P&L as one XLA program.

The reference's return engine (``src/portfolio.py:205-245``,
``Strategy.simulate``) loops over rebalance periods in Python, drifting
weights with a pandas ``cumprod`` per period and concatenating the
pieces. Here the entire simulation over (days x assets) is a handful of
fused array ops:

* one global ``cumprod`` of gross returns replaces all per-period
  cumprods — the drifted weight at day t under the segment that started
  at day s is ``w_s * G[t] / G[s]`` with ``G = cumprod(1 + R)``;
* each day is assigned to its rebalance segment with a ``searchsorted``
  (a day that *is* a rebalance date belongs to the *previous* segment,
  matching the pandas engine where the new weights seed that day's level
  and produce their first return the day after);
* margin / cash / loan sleeves, turnover, variable and fixed costs are
  computed per segment and broadcast.

Everything is jittable and ``vmap``-able over a strategies axis, so a
whole grid of backtests (dates x benchmarks) marks to market in one
program. The pandas engine remains the golden reference in tests.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd


def _renormalize_long_short(w: jax.Array) -> jax.Array:
    """Row-wise long/short renormalization: each row's long side is
    scaled to sum to +1 of its own gross and the short side to -1 of
    its (reference ``portfolio.py:283-286``). Rows with an empty side
    contribute zero for that side. The raw drift is computed first and
    each row renormalized independently, so renormalizing only the rows
    one consumes is equivalent to renormalizing the full path."""
    longs = jnp.maximum(w, 0.0)
    shorts = w - longs
    long_gross = jnp.sum(longs, axis=-1, keepdims=True)
    short_gross = jnp.sum(jnp.abs(shorts), axis=-1, keepdims=True)
    safe = lambda part, tot: jnp.where(
        tot > 0.0, part / jnp.maximum(tot, 1e-30), 0.0)
    return safe(longs, long_gross) + safe(shorts, short_gross)


def drift_weights(weights: jax.Array,
                  returns: jax.Array,
                  reb_idx: jax.Array,
                  rescale: bool = False) -> jax.Array:
    """Drifted weights for every day under its active segment.

    Device equivalent of the reference's ``floating_weights``
    (``portfolio.py:254-288``) over a whole backtest at once: one global
    cumulative product, segment assignment by ``searchsorted``, and —
    with ``rescale`` — the long/short renormalization applied row-wise.
    Days before the first rebalance hold the first segment's seed.
    """
    weights = jnp.asarray(weights, returns.dtype)
    reb_idx = jnp.asarray(reb_idx, jnp.int32)
    T = returns.shape[0]
    G = jnp.cumprod(1.0 + returns, axis=0)
    days = jnp.arange(T)
    seg = jnp.clip(jnp.searchsorted(reb_idx, days, side="left") - 1,
                   0, weights.shape[0] - 1)
    w_float = weights[seg] * G / G[reb_idx[seg]]
    return _renormalize_long_short(w_float) if rescale else w_float


class SimulationResult(NamedTuple):
    returns: jax.Array      # (T,) daily strategy returns; 0 before the first rebdate
    valid: jax.Array        # (T,) bool, True where a return is defined
    turnover: jax.Array     # (D,) two-sided turnover at each rebalance
    levels: jax.Array       # (T,) portfolio level under the active segment


def simulate(weights: jax.Array,
             returns: jax.Array,
             reb_idx: jax.Array,
             vc: float = 0.0,
             fc: float = 0.0,
             day_gaps: Optional[jax.Array] = None,
             n_days_per_year: int = 252,
             rescale_turnover: bool = False) -> SimulationResult:
    """Simulate a rebalanced strategy (reference ``portfolio.py:205-245``).

    Args:
      weights: (D, N) portfolio weights decided at each rebalance date.
      returns: (T, N) daily asset returns.
      reb_idx: (D,) int positions of the rebalance dates within the T axis
        (strictly increasing).
      vc: variable (turnover-proportional) cost rate.
      fc: fixed cost rate per year, compounded by calendar-day gaps.
      day_gaps: (T,) calendar days since the previous row (0 for the
        first); required when ``fc != 0``.
      rescale_turnover: measure turnover against the long/short
        renormalized drift of the previous portfolio (the reference's
        ``turnover(rescale=True)`` default, ``portfolio.py:109-121``)
        instead of the raw drift.
    """
    dtype = returns.dtype
    T, _ = returns.shape
    weights = jnp.asarray(weights, dtype)
    reb_idx = jnp.asarray(reb_idx, jnp.int32)

    # Global growth G[t] = prod_{s<=t} (1 + r_s); drifted weights under the
    # segment seeded at s are w_s * G[t] / G[s] (the seed row replaces the
    # rebalance day's own return, reference portfolio.py:278-281).
    G = jnp.cumprod(1.0 + returns, axis=0)

    days = jnp.arange(T)
    # Day t belongs to segment seg[t]: the last rebdate strictly before t,
    # so the return *on* a rebalance date still uses the old weights.
    seg = jnp.searchsorted(reb_idx, days, side="left") - 1
    seg_clip = jnp.clip(seg, 0, weights.shape[0] - 1)

    w_seg = weights[seg_clip]                       # (T, N)
    g_seed = G[reb_idx[seg_clip]]                   # (T, N) growth at seed day
    w_float = w_seg * G / g_seed                    # (T, N) drifted weights
    w_float_prev = w_seg * jnp.where(days[:, None] > 0, G[jnp.maximum(days - 1, 0)], 1.0) / g_seed

    # Margin / cash / loan sleeves per rebalance (reference
    # portfolio.py:220-227): constants within a segment.
    short_sum = jnp.sum(jnp.minimum(weights, 0.0), axis=1)        # (D,)
    long_sum = jnp.sum(jnp.maximum(weights, 0.0), axis=1)
    margin = jnp.abs(short_sum)
    cash = jnp.clip(1.0 - long_sum, 0.0, 1.0)
    loan = 1.0 - (long_sum + cash) - (short_sum + margin)
    sleeves = (margin + cash + loan)[seg_clip]                    # (T,)

    level = sleeves + jnp.sum(w_float, axis=1)
    level_prev = sleeves + jnp.sum(w_float_prev, axis=1)
    ret = level / level_prev - 1.0

    valid = (seg >= 0) & (days > reb_idx[0])
    ret = jnp.where(valid, ret, 0.0)

    # Turnover: drifted previous weights at the rebalance date vs the
    # new weights (reference portfolio.py:109-121, 194-203), with the
    # drift optionally long/short-renormalized first.
    prev_seg = jnp.maximum(jnp.arange(weights.shape[0]) - 1, 0)
    g_at_reb = G[reb_idx]                                          # (D, N)
    g_prev_seed = G[reb_idx[prev_seg]]
    w_drift_prev = weights[prev_seg] * g_at_reb / g_prev_seed      # (D, N)
    if rescale_turnover:
        w_drift_prev = _renormalize_long_short(w_drift_prev)
    to = jnp.sum(jnp.abs(w_drift_prev - weights), axis=1)
    to = to.at[0].set(jnp.sum(jnp.abs(weights[0])))

    if vc != 0.0:
        # Cost lands on the first defined return for the first rebalance
        # and on the rebalance-date return otherwise (portfolio.py:234-239).
        cost_t = jnp.zeros(T, dtype).at[reb_idx].add(to * vc)
        first_ret_day = reb_idx[0] + 1
        cost_t = cost_t.at[first_ret_day].add(cost_t[reb_idx[0]])
        cost_t = cost_t.at[reb_idx[0]].set(0.0)
        ret = ret - jnp.where(valid, cost_t, 0.0)

    if fc != 0.0:
        if day_gaps is None:
            raise ValueError("day_gaps is required when fc != 0")
        fixcost = (1.0 + fc) ** (jnp.asarray(day_gaps, dtype) / n_days_per_year) - 1.0
        # The pandas engine charges no fixed cost on the very first return
        # row (reference portfolio.py:240-243 slices [1:]).
        charge = valid & (days > reb_idx[0] + 1)
        ret = ret - jnp.where(charge, fixcost, 0.0)

    return SimulationResult(returns=ret, valid=valid, turnover=to,
                            levels=jnp.where(seg >= 0, level, 1.0))


_simulate_jit = jax.jit(simulate, static_argnames=(
    "vc", "fc", "n_days_per_year", "rescale_turnover"))


def simulate_strategy(strategy,
                      return_series: pd.DataFrame,
                      fc: float = 0.0,
                      vc: float = 0.0,
                      n_days_per_year: int = 252) -> pd.Series:
    """Pandas-friendly wrapper: a ``Strategy`` in, a return Series out.

    Drop-in accelerated replacement for ``Strategy.simulate`` (reference
    ``portfolio.py:205-245``) for the rescale=False path; asset universe
    may vary by date (weights are aligned to the full column set).
    """
    rebdates = strategy.get_rebalancing_dates()
    W = (
        strategy.get_weights_df()
        .reindex(columns=return_series.columns)
        .fillna(0.0)
        .to_numpy(dtype=float)
    )
    dates = pd.to_datetime(pd.Index(rebdates))
    reb_idx = return_series.index.get_indexer(dates, method="pad")
    if (reb_idx < 0).any():
        raise ValueError("all rebalance dates must fall inside the return series")

    day_gaps = np.zeros(len(return_series.index))
    day_gaps[1:] = (
        (return_series.index[1:] - return_series.index[:-1])
        .to_numpy().astype("timedelta64[D]").astype(float)
    )

    out = _simulate_jit(
        jnp.asarray(W),
        jnp.asarray(return_series.to_numpy(dtype=float)),
        jnp.asarray(reb_idx),
        vc=vc, fc=fc,
        day_gaps=jnp.asarray(day_gaps),
        n_days_per_year=n_days_per_year,
    )
    ret = np.asarray(out.returns)
    valid = np.asarray(out.valid)
    return pd.Series(ret[valid], index=return_series.index[valid])


def performance_summary(returns: pd.Series,
                        benchmark: Optional[pd.Series] = None,
                        n_days_per_year: int = 252) -> dict:
    """Per-strategy performance report: the quantstats-style metric set
    the reference notebooks print (Sharpe / VaR / drawdown,
    ``example/backtest.ipynb`` cell 2, ``index_replication.ipynb`` cell
    11) computed from first principles — no external dependency.

    Returns a dict with annualized return/volatility/Sharpe, max
    drawdown (on the compounded level path), daily 95% historical VaR,
    cumulative return, and — when a benchmark series is given —
    annualized tracking error, beta, and active (excess) return.
    """
    r = returns.dropna()
    ann = float(n_days_per_year)
    if r.empty:
        # A strategy with no valid days has no performance — report it
        # as NaN metrics, not an IndexError.
        nan = float("nan")
        out = {"n_days": 0, "annual_return": nan, "annual_volatility": nan,
               "sharpe": nan, "max_drawdown": nan, "var_95": nan,
               "cumulative_return": nan}
        if benchmark is not None:
            out.update(tracking_error=nan, beta=nan, active_return=nan)
        return out
    mean_d, std_d = float(r.mean()), float(r.std())
    levels = (1.0 + r).cumprod()
    final = float(levels.iloc[-1])
    out = {
        "n_days": int(r.size),
        # CAGR from the compounded level path (the quantstats
        # convention; round-3 advisor finding): consistent with
        # cumulative_return by construction, where compounding the
        # arithmetic daily mean overstates growth for volatile series.
        # A wiped-out path (level <= 0) annualizes to -100%.
        "annual_return": (float(final ** (ann / r.size) - 1.0)
                          if final > 0 else -1.0),
        "annual_volatility": std_d * float(np.sqrt(ann)),
        # A zero/undefined-variance series has no defined risk-adjusted
        # return; NaN, never +inf for a flat losing strategy.
        "sharpe": (mean_d / std_d * float(np.sqrt(ann))
                   if std_d > 0 else float("nan")),
        "max_drawdown": float((levels / levels.cummax() - 1.0).min()),
        "var_95": float(r.quantile(0.05)),
        "cumulative_return": float(levels.iloc[-1] - 1.0),
    }
    if benchmark is not None:
        # One aligned, pairwise-complete sample for every benchmark
        # metric: covariance and variance from different subsets would
        # bias beta whenever the two series' calendars differ.
        pair = pd.DataFrame(
            {"r": r, "b": benchmark.reindex(r.index).astype(float)}
        ).dropna()
        active = pair["r"] - pair["b"]
        bv = float(pair["b"].var())
        out["tracking_error"] = float(active.std() * np.sqrt(ann))
        out["beta"] = (float(pair["r"].cov(pair["b"]) / bv)
                       if bv > 0 else float("nan"))
        out["active_return"] = (
            float((1.0 + active.mean()) ** ann - 1.0)
            if len(active) else float("nan"))
    return out
