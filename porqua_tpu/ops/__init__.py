"""Pallas TPU kernels for the solver hot loop."""

from porqua_tpu.ops.admm_kernel import admm_segment

__all__ = ["admm_segment"]
