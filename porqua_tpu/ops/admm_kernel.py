"""Pallas TPU kernel: fused ADMM iteration segment with a VMEM-resident KKT inverse.

Why this kernel exists: the stock XLA path (``porqua_tpu.qp.admm``)
re-reads each problem's n x n KKT factor from HBM on *every* ADMM
iteration — for the north-star batch (252 dates x 500 assets, ~1 MB of
factor per problem) that is hundreds of MB of HBM traffic per iteration,
and the solve is purely HBM-bandwidth bound. This kernel instead runs a
whole ``check_interval``-iteration segment per grid program with the
explicit KKT inverse and the constraint matrix pinned in VMEM, so the
factor crosses HBM once per ~25 iterations instead of once per
iteration. With the batch as the grid axis, Pallas double-buffers the
next problem's DMA behind the current problem's iteration loop for free.

Status of the DENSE-operator forms (retired to exemplar after the
round-3 on-chip batch): opt-in (``backend="pallas"``), no measured
regime where they pay on this chip generation. At the north-star shape
(n=500) both dense forms time at parity with the XLA path (173 vs
176 ms, round 2 — the iteration stage there is latency-bound, so the
VMEM residency saves nothing XLA's pipelining had not already hidden).
In their claimed advantage regime (n>=1000, where the operator stops
fitting cache-adjacent HBM streams) they **fail to compile**:
``tpu_compile_helper`` dies with a kernel-VMEM-stack OOM at
``vmem_limit_mb=64`` for both the trinv and explicit-inverse forms
(round-3 measurement log, ``TPU_MEASURE_r03.txt``) — the n x n
resident operator is structurally too big for VMEM at large n.

Round 4 adds the **factored segment**
(:func:`admm_segment_factored`): the resident operator is the
capacitance pieces ``(inv_d, W, Y0, Ginv)`` of the
``linsolve="woodbury"`` path — ~((T+m) x n) instead of n x n, ~1 MB
per north-star problem — so the kernel keeps the fused-segment
residency win *in the regime the promoted TPU headline config actually
runs*. The XLA woodbury path re-reads W (0.5 MB/problem) twice per
iteration from HBM: at B=252, 35 iterations, that is ~9 GB of traffic
this kernel replaces with one W read per problem per segment. It also
scales where the dense kernel OOMed: at n=2000 the resident set is
~4 MB (vs the dense kernel's ~16 MB + stack). The production default
is still the XLA path pending on-chip measurement
(``scripts/tpu_jobs``); parity is pinned in interpret mode by
``tests/test_pallas_kernel.py``.

The dense production path keeps the factor-reuse idea in stock XLA:
``linsolve="trinv"`` inverts only the triangular factor once per
segment, and the round-3 capacitance path (``linsolve="woodbury"``)
shrinks the factorization itself to the (T+m)-dim dual space.

This replaces the hot loop of the external C solvers the reference
dispatches to through ``qpsolvers.solve_problem`` (reference
``src/qp_problems.py:211`` — OSQP's sparse LDL backsolve per iteration);
the dense VMEM-resident formulation is the TPU-idiomatic equivalent.

The iteration math is identical to ``porqua_tpu.qp.admm.admm_solve``'s
``one_iteration`` (OSQP splitting with an implicit box block); the only
algebraic difference is that the linear solve uses the precomputed
inverse (one (1,n)@(n,n) MXU matvec) instead of two triangular solves.
Parity between the two backends is pinned by ``tests/test_pallas_kernel.py``.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from porqua_tpu.qp.admm import l1_box_prox


def _round_up(x: int, mult: int) -> int:
    return ((x + mult - 1) // mult) * mult


_HP = jax.lax.Precision.HIGHEST


def _row_dot_t(v, M, dtype):
    """``v @ M'`` in row-vector form: contract ``v``'s lane axis with
    ``M``'s lane axis. precision=HIGHEST throughout this module: the
    MXU's default f32 handling drops to bf16 passes, far too coarse for
    ADMM fixed-point iteration (the iterates diverge)."""
    return jax.lax.dot_general(
        v, M, (((1,), (1,)), ((), ())),
        preferred_element_type=dtype, precision=_HP)


def _make_iteration(solve_fn, C, q, l, u, lb, ub, rho, rho_b, l1w, l1c,
                    sigma, alpha, dtype):
    """One OSQP iteration (rhs build -> ``solve_fn`` -> prox/dual
    updates), shared by every kernel form so the linear-solve operator
    is the ONLY thing that can differ between them."""
    inv_rho = 1.0 / rho
    inv_rhob = 1.0 / rho_b
    sig = jnp.asarray(sigma, dtype)
    al = jnp.asarray(alpha, dtype)
    one_m_al = jnp.asarray(1.0 - alpha, dtype)

    def one_iteration(carry):
        x, z, w, y, mu = carry
        # rhs = sigma x - q + C'(rho z - y) + (rho_b w - mu); row-vector form.
        rhs = (
            sig * x - q
            + jnp.dot(rho * z - y, C, preferred_element_type=dtype,
                      precision=_HP)
            + (rho_b * w - mu)
        )
        xt = solve_fn(rhs)
        zt = _row_dot_t(xt, C, dtype)  # zt = C @ xt

        x_new = al * xt + one_m_al * x
        z_pre = al * zt + one_m_al * z
        z_new = jnp.clip(z_pre + y * inv_rho, l, u)
        y_new = y + rho * (z_pre - z_new)
        w_pre = al * xt + one_m_al * w
        w_new = l1_box_prox(w_pre + mu * inv_rhob, lb, ub, l1w * inv_rhob, l1c)
        mu_new = mu + rho_b * (w_pre - w_new)
        return (x_new, z_new, w_new, y_new, mu_new)

    return one_iteration


def _run_segment(one_iteration, n_iters,
                 x_ref, z_ref, w_ref, y_ref, mu_ref,
                 x_out, z_out, w_out, y_out, mu_out,
                 dx_out, dy_out, dmu_out):
    """Drive ``n_iters`` iterations and write final state + the
    one-iteration increments the OSQP infeasibility certificates need."""
    carry0 = (x_ref[:], z_ref[:], w_ref[:], y_ref[:], mu_ref[:])
    carry = jax.lax.fori_loop(
        0, n_iters - 1, lambda _, c: one_iteration(c), carry0
    )
    x, z, w, y, mu = one_iteration(carry)

    x_out[:] = x
    z_out[:] = z
    w_out[:] = w
    y_out[:] = y
    mu_out[:] = mu
    dx_out[:] = x - carry[0]
    dy_out[:] = y - carry[3]
    dmu_out[:] = mu - carry[4]


def _segment_kernel(Kinv_ref, C_ref, q_ref, l_ref, u_ref, lb_ref, ub_ref,
                    rho_ref, rhob_ref, l1w_ref, l1c_ref,
                    x_ref, z_ref, w_ref, y_ref, mu_ref,
                    x_out, z_out, w_out, y_out, mu_out,
                    dx_out, dy_out, dmu_out,
                    *, sigma: float, alpha: float, n_iters: int,
                    triangular: bool = False):
    """One ADMM segment (``n_iters`` iterations) for one problem, all in VMEM.

    With ``triangular=True`` the resident matrix is the inverse
    Cholesky factor ``L^-1`` and the linear step applies
    ``K^-1 = L^-T L^-1`` as two dense matvecs — the accuracy story of
    ``SolverParams.linsolve="trinv"`` (error ``sqrt(cond(K))*eps``
    instead of the full inverse's ``cond(K)*eps``) with the kernel's
    VMEM residency.
    """
    dtype = x_ref.dtype
    Kinv = Kinv_ref[:]

    if triangular:
        # Kinv holds L^-1: xt = L^-T (L^-1 rhs). Row-vector form:
        # u = rhs @ L^-T (contract rhs lanes with L^-1's lanes),
        # then xt = u @ L^-1.
        def solve_fn(rhs):
            u_row = _row_dot_t(rhs, Kinv, dtype)
            return jnp.dot(u_row, Kinv, preferred_element_type=dtype,
                           precision=_HP)
    else:
        # K is symmetric, so Kinv is too: x~ = rhs @ Kinv == Kinv @ rhs.
        def solve_fn(rhs):
            return jnp.dot(rhs, Kinv, preferred_element_type=dtype,
                           precision=_HP)

    one_iteration = _make_iteration(
        solve_fn, C_ref[:], q_ref[:], l_ref[:], u_ref[:], lb_ref[:],
        ub_ref[:], rho_ref[:], rhob_ref[:], l1w_ref[:], l1c_ref[:],
        sigma, alpha, dtype)
    _run_segment(one_iteration, n_iters,
                 x_ref, z_ref, w_ref, y_ref, mu_ref,
                 x_out, z_out, w_out, y_out, mu_out,
                 dx_out, dy_out, dmu_out)


def _factored_segment_kernel(W_ref, invd_ref, Y0_ref, Ginv_ref, V_ref,
                             Dv_ref,
                             C_ref, q_ref, l_ref, u_ref, lb_ref, ub_ref,
                             rho_ref, rhob_ref, l1w_ref, l1c_ref,
                             x_ref, z_ref, w_ref, y_ref, mu_ref,
                             x_out, z_out, w_out, y_out, mu_out,
                             dx_out, dy_out, dmu_out,
                             *, sigma: float, alpha: float, n_iters: int,
                             refine_steps: int = 0):
    """Factored (capacitance/Woodbury) segment: resident state is
    ``W`` (k x n), ``inv_d`` (n), ``Y0`` (n x m), ``Ginv`` (m x m) —
    the exact operator pieces of the XLA ``linsolve="woodbury"`` path
    (``qp/admm.py``: ``factored_solve_pieces`` + the eq-row Schur
    split):

        base(r) = inv_d * r - (r W') W      (+ refine_steps rounds of
                  iterative refinement against K = diag(Dv) + V'V,
                  which additionally keeps V and Dv resident)
        xt = x0 - (Ginv (C x0)) Y0'
    """
    dtype = x_ref.dtype
    W = W_ref[:]
    inv_d = invd_ref[:]
    Y0 = Y0_ref[:]
    Ginv = Ginv_ref[:]
    C = C_ref[:]
    if refine_steps:
        V = V_ref[:]
        Dv = Dv_ref[:]

    def base(r):
        t = _row_dot_t(r, W, dtype)               # (1, k) = r @ W'
        return r * inv_d - jnp.dot(
            t, W, preferred_element_type=dtype, precision=_HP)

    def solve_fn(rhs):
        x0 = base(rhs)
        for _ in range(refine_steps):
            Kx = Dv * x0 + jnp.dot(
                _row_dot_t(x0, V, dtype), V,
                preferred_element_type=dtype, precision=_HP)
            x0 = x0 + base(rhs - Kx)
        s = _row_dot_t(x0, C, dtype)              # (1, m) = C @ x0
        # G is symmetric (diag(1/rho) + C K0^-1 C'), hence so is Ginv:
        # row-vector application s @ Ginv == (Ginv s)'.
        v = jnp.dot(s, Ginv, preferred_element_type=dtype, precision=_HP)
        return x0 - _row_dot_t(v, Y0, dtype)      # x0 - Y0 @ v

    one_iteration = _make_iteration(
        solve_fn, C, q_ref[:], l_ref[:], u_ref[:], lb_ref[:],
        ub_ref[:], rho_ref[:], rhob_ref[:], l1w_ref[:], l1c_ref[:],
        sigma, alpha, dtype)
    _run_segment(one_iteration, n_iters,
                 x_ref, z_ref, w_ref, y_ref, mu_ref,
                 x_out, z_out, w_out, y_out, mu_out,
                 dx_out, dy_out, dmu_out)


@functools.partial(
    jax.jit,
    static_argnames=("sigma", "alpha", "n_iters", "interpret", "triangular"),
)
def admm_segment(Kinv: jax.Array,
                 C: jax.Array,
                 q: jax.Array,
                 l: jax.Array,
                 u: jax.Array,
                 lb: jax.Array,
                 ub: jax.Array,
                 rho: jax.Array,
                 rho_b: jax.Array,
                 l1w: jax.Array,
                 l1c: jax.Array,
                 x: jax.Array,
                 z: jax.Array,
                 w: jax.Array,
                 y: jax.Array,
                 mu: jax.Array,
                 *,
                 sigma: float,
                 alpha: float,
                 n_iters: int,
                 interpret: bool = False,
                 triangular: bool = False) -> Tuple[jax.Array, ...]:
    """Run ``n_iters`` fused ADMM iterations on one problem.

    Inputs are the *scaled* problem data for a single QP (no batch axis —
    batching is ``jax.vmap``, which Pallas lowers to a grid axis so
    problems pipeline through VMEM). Returns
    ``(x, z, w, y, mu, dx, dy, dmu)`` with the same 1-D shapes as the
    inputs, where d* are the last-iteration increments.

    Padding: n is padded to a lane multiple (128) and m likewise; padded
    variables/rows carry zero matrix entries, ``[0, 0]`` / ``(-inf, inf)``
    bounds and unit step sizes, so they fix at exactly zero and cannot
    perturb the real entries (same neutrality argument as
    ``porqua_tpu.qp.canonical``).
    """
    dtype = x.dtype
    n = x.shape[-1]
    m = z.shape[-1]
    n_p = _round_up(max(n, 1), 128)
    m_p = _round_up(max(m, 1), 128)
    inf = jnp.asarray(jnp.inf, dtype)

    def pad_vec(v, size, value=0.0):
        pad = size - v.shape[-1]
        if pad == 0:
            return v[None, :]
        return jnp.concatenate(
            [v, jnp.full((pad,), value, dtype)], axis=-1
        )[None, :]

    Kinv_p = jnp.zeros((n_p, n_p), dtype).at[:n, :n].set(Kinv)
    C_p = jnp.zeros((m_p, n_p), dtype).at[:m, :n].set(C)
    args = (
        Kinv_p, C_p,
        pad_vec(q, n_p),
        pad_vec(l, m_p, -inf), pad_vec(u, m_p, inf),
        pad_vec(lb, n_p), pad_vec(ub, n_p),
        pad_vec(rho, m_p, 1.0), pad_vec(rho_b, n_p, 1.0),
        pad_vec(l1w, n_p), pad_vec(l1c, n_p),
        pad_vec(x, n_p), pad_vec(z, m_p), pad_vec(w, n_p),
        pad_vec(y, m_p), pad_vec(mu, n_p),
    )

    vec_n = jax.ShapeDtypeStruct((1, n_p), dtype)
    vec_m = jax.ShapeDtypeStruct((1, m_p), dtype)
    out = pl.pallas_call(
        functools.partial(
            _segment_kernel, sigma=sigma, alpha=alpha, n_iters=n_iters,
            triangular=triangular,
        ),
        out_shape=(vec_n, vec_m, vec_n, vec_m, vec_n, vec_n, vec_m, vec_n),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * len(args),
        out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 8),
        interpret=interpret,
    )(*args)

    x_n, z_n, w_n, y_n, mu_n, dx, dy, dmu = out
    return (
        x_n[0, :n], z_n[0, :m], w_n[0, :n], y_n[0, :m], mu_n[0, :n],
        dx[0, :n], dy[0, :m], dmu[0, :n],
    )


@functools.partial(
    jax.jit,
    static_argnames=("sigma", "alpha", "n_iters", "interpret",
                     "refine_steps"),
)
def admm_segment_factored(W: jax.Array,
                          inv_d: jax.Array,
                          Y0: jax.Array,
                          Ginv: jax.Array,
                          V: jax.Array,
                          Dv: jax.Array,
                          C: jax.Array,
                          q: jax.Array,
                          l: jax.Array,
                          u: jax.Array,
                          lb: jax.Array,
                          ub: jax.Array,
                          rho: jax.Array,
                          rho_b: jax.Array,
                          l1w: jax.Array,
                          l1c: jax.Array,
                          x: jax.Array,
                          z: jax.Array,
                          w: jax.Array,
                          y: jax.Array,
                          mu: jax.Array,
                          *,
                          sigma: float,
                          alpha: float,
                          n_iters: int,
                          interpret: bool = False,
                          refine_steps: int = 0) -> Tuple[jax.Array, ...]:
    """Run ``n_iters`` fused factored-operator ADMM iterations on one
    problem (capacitance/Woodbury form).

    ``W`` (k x n), ``inv_d`` (n), ``Y0`` (n x m), ``Ginv`` (m x m) are
    the per-segment operator pieces the XLA woodbury path builds
    (``qp/admm.py:segment``); the build stays in XLA — this kernel
    fuses only the iteration loop, which is where the HBM traffic is.
    With ``refine_steps > 0`` the factor ``V`` (k x n) and diagonal
    ``Dv`` also stay resident for the in-kernel iterative refinement
    (the library-default refine=1 accuracy mode); at refine_steps=0
    they are replaced by tile-sized placeholders the kernel never
    reads. Batching is ``jax.vmap`` exactly as for
    :func:`admm_segment`.

    Padding: k, n, m each round up to lane multiples of 128. Padded W
    rows/cols and Y0 entries are zero, padded ``Ginv`` carries a unit
    diagonal, padded bounds are ``[0, 0]`` / ``(-inf, inf)`` with unit
    step sizes — padded variables fix at exactly zero and cannot
    perturb the real entries (same argument as :func:`admm_segment`).
    """
    dtype = x.dtype
    n = x.shape[-1]
    m = z.shape[-1]
    k = W.shape[-2]
    n_p = _round_up(max(n, 1), 128)
    m_p = _round_up(max(m, 1), 128)
    k_p = _round_up(max(k, 1), 128)
    inf = jnp.asarray(jnp.inf, dtype)

    def pad_vec(v, size, value=0.0):
        pad = size - v.shape[-1]
        if pad == 0:
            return v[None, :]
        return jnp.concatenate(
            [v, jnp.full((pad,), value, dtype)], axis=-1
        )[None, :]

    W_p = jnp.zeros((k_p, n_p), dtype).at[:k, :n].set(W)
    Y0_p = jnp.zeros((n_p, m_p), dtype).at[:n, :m].set(Y0)
    Ginv_p = jnp.eye(m_p, dtype=dtype).at[:m, :m].set(Ginv)
    C_p = jnp.zeros((m_p, n_p), dtype).at[:m, :n].set(C)
    if refine_steps:
        # Padded V columns are zero and padded Dv entries 1.0, so the
        # refinement residual of a padded (fixed-at-zero) variable is
        # exactly zero — padding neutrality as for the rest.
        V_p = jnp.zeros((k_p, n_p), dtype).at[:k, :n].set(V)
        Dv_p = pad_vec(Dv, n_p, 1.0)
    else:
        # Never read by the kernel (static refine_steps gate); keep
        # one tile so VMEM is not spent on a dead (k x n) array.
        V_p = jnp.zeros((8, 128), dtype)
        Dv_p = jnp.zeros((1, 128), dtype)
    args = (
        W_p, pad_vec(inv_d, n_p, 1.0), Y0_p, Ginv_p, V_p, Dv_p, C_p,
        pad_vec(q, n_p),
        pad_vec(l, m_p, -inf), pad_vec(u, m_p, inf),
        pad_vec(lb, n_p), pad_vec(ub, n_p),
        pad_vec(rho, m_p, 1.0), pad_vec(rho_b, n_p, 1.0),
        pad_vec(l1w, n_p), pad_vec(l1c, n_p),
        pad_vec(x, n_p), pad_vec(z, m_p), pad_vec(w, n_p),
        pad_vec(y, m_p), pad_vec(mu, n_p),
    )

    vec_n = jax.ShapeDtypeStruct((1, n_p), dtype)
    vec_m = jax.ShapeDtypeStruct((1, m_p), dtype)
    out = pl.pallas_call(
        functools.partial(
            _factored_segment_kernel, sigma=sigma, alpha=alpha,
            n_iters=n_iters, refine_steps=refine_steps,
        ),
        out_shape=(vec_n, vec_m, vec_n, vec_m, vec_n, vec_n, vec_m, vec_n),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * len(args),
        out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 8),
        interpret=interpret,
    )(*args)

    x_n, z_n, w_n, y_n, mu_n, dx, dy, dmu = out
    return (
        x_n[0, :n], z_n[0, :m], w_n[0, :n], y_n[0, :m], mu_n[0, :n],
        dx[0, :n], dy[0, :m], dmu[0, :n],
    )
