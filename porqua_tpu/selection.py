"""Named-filter asset selection.

Same capability as the reference's selection layer
(``/root/reference/src/selection.py``: named binary/score filters whose
conjunction decides the investable universe) with a different
implementation: filters live in a flat registry of normalized frames,
and the selected universe is computed by intersecting the id sets that
each binary filter admits — no MultiIndex concatenation.

Host-side: selection produces the per-date universe that the device
backtest consumes as a static-shape 0/1 mask vector.
"""

from __future__ import annotations

from typing import Optional, Union

import pandas as pd


def _check_binary(values: pd.Series) -> pd.Series:
    bad = ~values.isin([0, 1])
    if bad.any():
        raise ValueError(
            f"binary filter values must be 0 or 1; offending ids: "
            f"{list(values.index[bad][:5])}")
    return values.astype(int)


class Selection:
    """Universe chooser: the ids admitted by every binary filter.

    Filters are pandas Series/DataFrames keyed by name. A Series named
    ``binary`` — or a frame column called ``binary`` — constrains the
    universe; other columns (scores, ranks) ride along for downstream
    consumers. Ids missing from any filter are excluded.
    """

    def __init__(self, ids: pd.Index = pd.Index([])):
        self._filters: dict = {}
        self.selected = ids

    @property
    def selected(self) -> pd.Index:
        return self._selected

    @selected.setter
    def selected(self, value):
        if not isinstance(value, pd.Index):
            raise ValueError("'selected' must be set to a pd.Index")
        self._selected = value

    @property
    def filtered(self) -> dict:
        return self._filters

    def clear(self) -> None:
        self._filters = {}
        self.selected = pd.Index([])

    def add_filtered(self,
                     filter_name: str,
                     value: Union[pd.Series, pd.DataFrame]) -> None:
        if not isinstance(filter_name, str) or not filter_name.strip():
            raise ValueError("'filter_name' must be a nonempty string")
        if isinstance(value, pd.Series):
            if value.name == "binary":
                value = _check_binary(value)
        elif isinstance(value, pd.DataFrame):
            if "binary" in value.columns:
                value = value.assign(binary=_check_binary(value["binary"]))
        else:
            raise ValueError(
                "a filter must be a pd.Series or a pd.DataFrame")
        self._filters[filter_name] = value
        self.selected = self.get_selected()

    def _binary_part(self, name: str) -> Optional[pd.Series]:
        """The 0/1 series a filter contributes, if any."""
        value = self._filters[name]
        if isinstance(value, pd.Series):
            return value if value.name == "binary" else None
        return value["binary"] if "binary" in value.columns else None

    def get_selected(self, filter_names: Optional[list] = None) -> pd.Index:
        """Ids present in every named filter and admitted (== 1) by
        every binary one, in sorted order."""
        names = list(self._filters) if filter_names is None else filter_names
        universe = None
        for name in names:
            idx = self._filters[name].index
            universe = idx if universe is None else universe.union(idx)
        if universe is None:
            return pd.Index([])
        admitted = universe.sort_values()
        for name in names:
            binary = self._binary_part(name)
            if binary is not None:
                admitted = admitted.intersection(
                    binary.index[binary == 1])
        return admitted

    def df(self, filter_names: Optional[list] = None) -> pd.DataFrame:
        """All filters side by side under a (filter, column) MultiIndex."""
        names = list(self._filters) if filter_names is None else filter_names
        blocks = {}
        for name in names:
            value = self._filters[name]
            blocks[name] = value.to_frame() if isinstance(
                value, pd.Series) else value
        return pd.concat(blocks, axis=1)

    def df_binary(self, filter_names: Optional[list] = None) -> pd.DataFrame:
        """One column per binary filter, restricted to ids every binary
        filter covers."""
        names = list(self._filters) if filter_names is None else filter_names
        cols = {name: binary for name in names
                if (binary := self._binary_part(name)) is not None}
        if not cols:
            return pd.DataFrame(index=self.get_selected(names))
        return pd.DataFrame(cols).dropna().astype(int)
