"""Named-filter asset selection (mirror of reference ``src/selection.py``).

Each filter is a pandas Series/DataFrame; an asset is selected when all
binary filters agree (== 1). Host-side: selection decides the *universe
mask* that the device-side batched backtest consumes as a static-shape
0/1 vector per rebalance date.
"""

from __future__ import annotations

from typing import Optional, Union

import pandas as pd


class Selection:

    def __init__(self, ids: pd.Index = pd.Index([])):
        self._filtered: dict = {}
        self.selected = ids

    @property
    def selected(self) -> pd.Index:
        return self._selected

    @selected.setter
    def selected(self, value):
        if not isinstance(value, pd.Index):
            raise ValueError(
                "Inconsistent input type for selected.setter. Needs to be a pd.Index."
            )
        self._selected = value

    @property
    def filtered(self):
        return self._filtered

    def get_selected(self, filter_names: Optional[list] = None) -> pd.Index:
        df = self.df_binary(filter_names)
        return df[df.eq(1).all(axis=1)].index

    def clear(self) -> None:
        self.selected = pd.Index([])
        self._filtered = {}

    def add_filtered(self,
                     filter_name: str,
                     value: Union[pd.Series, pd.DataFrame]) -> None:
        if not isinstance(filter_name, str) or not filter_name.strip():
            raise ValueError("Argument 'filter_name' must be a nonempty string.")

        if not isinstance(value, (pd.Series, pd.DataFrame)):
            raise ValueError(
                "Inconsistent input type. Needs to be a pd.Series or a pd.DataFrame."
            )

        if isinstance(value, pd.Series) and value.name == "binary":
            if not value.isin([0, 1]).all():
                raise ValueError("Column 'binary' must contain only 0s and 1s.")
            value = value.astype(int)

        if isinstance(value, pd.DataFrame) and "binary" in value.columns:
            if not value["binary"].isin([0, 1]).all():
                raise ValueError("Column 'binary' must contain only 0s and 1s.")
            value["binary"] = value["binary"].astype(int)

        self._filtered[filter_name] = value
        self.selected = self.get_selected()

    def df(self, filter_names: Optional[list] = None) -> pd.DataFrame:
        if filter_names is None:
            filter_names = self.filtered.keys()
        return pd.concat(
            {
                key: (
                    pd.DataFrame(self.filtered[key])
                    if isinstance(self.filtered[key], pd.Series)
                    else self.filtered[key]
                )
                for key in filter_names
            },
            axis=1,
        )

    def df_binary(self, filter_names: Optional[list] = None) -> pd.DataFrame:
        if filter_names is None:
            filter_names = self.filtered.keys()
        df = self.df(filter_names=filter_names).filter(like="binary").dropna()
        df.columns = df.columns.droplevel(1)
        return df
