"""Deterministic fault injection: seeded seams at the serve chokepoints.

The serve stack carries real recovery machinery — a circuit breaker
with TPU→XLA-CPU degradation, sanitizer enforcement, continuous-
batching cohorts, an event bus — but without induced failure none of
it is *exercised*: the breaker opens only when a probe happens to
fail. This module is the induction side: a process-global
:class:`FaultInjector` holding a :class:`Scenario` (a list of
:class:`FaultSpec` rules), consulted at **named seams** compiled into
the existing chokepoints:

=====================  ====================================================
seam                   where it fires
=====================  ====================================================
``serve.admission``    ``SolveService.submit`` (queue stall, clock skew)
``serve.dispatch``     ``MicroBatcher._execute`` before the device call
``serve.result``       batcher result read-back (NaN/Inf lane corruption)
``serve.continuous``   ``ContinuousBatcher._tick`` before the step dispatch
``health.probe``       ``DeviceHealth._probe_with_timeout``
``cache.get``          ``ExecutableCache._get`` (post-warmup compile storm)
``data.feed``          ``loadgen`` request stream (corrupt problem data)
``backtest.chunk``     checkpointed backtest loops, after each chunk save
=====================  ====================================================

Every seam follows ONE pattern, enforced mechanically by graftcheck
rule GC007 (:mod:`porqua_tpu.analysis.lint`)::

    from porqua_tpu.resilience import faults as _faults
    ...
    if _faults.enabled():
        act = _faults.fire("serve.dispatch", bucket=label)
        # interpret ``act`` if the seam handles directives

Disabled (the default, and the only production state) the seam is a
single module-global ``is not None`` predicate — no injector object,
no RNG, no allocation — and the traced device programs are untouched:
seams live strictly in host dispatch code, which the GC104 jaxpr-
identity contract (:mod:`porqua_tpu.analysis.contracts`) proves by
tracing the solve/serve entry points with and without an installed
injector and requiring string-identical jaxprs.

Determinism: each ``(seam, kind)`` rule carries its own counter and
its own ``numpy`` Generator seeded from ``(scenario.seed, seam,
kind)``, so a seam's fault sequence depends only on how many times
*that seam* was hit — not on thread interleavings across seams — and
replaying a scenario replays its faults exactly.

Fault kinds (the scenario DSL):

``device_lost``     raise :class:`InjectedFault` at a dispatch seam —
                    the batcher's device-fault path counts it toward
                    the circuit breaker, exactly like a real XLA error.
``probe_fail``      directive ``fail`` at ``health.probe`` — the probe
                    reports unhealthy without touching a device
                    (models both fast device loss and the black-hole
                    timeout; an optional ``stall_s`` sleeps first).
``nan_lanes``       directive at ``serve.result`` — corrupt ``lanes``
                    result rows to NaN/Inf *on the host copy* (the
                    device program never sees it); the retry layer's
                    validation must catch it, or the caller would
                    receive a wrong answer.
``compile_storm``   directive at ``cache.get`` — evict the cache entry
                    so a post-warmup dispatch pays a fresh AOT compile.
``queue_stall``     directive ``stall_s`` at ``serve.admission`` —
                    admission sleeps, aging every queued deadline.
``clock_skew``      directive ``skew_s`` at ``serve.admission`` — the
                    request's deadline budget is shortened as if the
                    submitter's clock ran ahead of the service's.
``feed_corrupt``    directive at ``data.feed`` — poison the request's
                    objective vector with NaN before submission.
``crash``           raise :class:`InjectedCrash` at ``backtest.chunk``
                    — kill a checkpointed backtest mid-run to drive
                    the crash-resume parity tests.

Host-only module by design: importing it must never initialize a JAX
backend (it is imported by every serve module for the seam predicate).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "FaultAction",
    "FaultClock",
    "FaultInjector",
    "FaultSpec",
    "InjectedCrash",
    "InjectedFault",
    "KINDS",
    "SEAMS",
    "Scenario",
    "active",
    "builtin_scenarios",
    "corrupt_feed",
    "enabled",
    "fire",
    "install",
    "uninstall",
]

#: Every seam name compiled into the stack (unknown names raise at
#: Scenario construction — a typo'd seam must not silently never fire).
SEAMS = (
    "serve.admission",
    "serve.dispatch",
    "serve.result",
    "serve.continuous",
    "health.probe",
    "cache.get",
    "data.feed",
    "backtest.chunk",
    "loadgen.worker",
)

#: kind -> seams it is allowed to target (the DSL's type system).
#: ``crash`` additionally targets ``loadgen.worker``: a fleet loadgen
#: shard (scripts/fleet_loadgen.py) dies mid-soak through the same
#: seeded kind the crash-resume backtests use — the fleet collector's
#: liveness tracking must turn that into a ``worker_lost`` incident.
KINDS: Dict[str, Tuple[str, ...]] = {
    "device_lost": ("serve.dispatch", "serve.continuous"),
    "probe_fail": ("health.probe",),
    "nan_lanes": ("serve.result",),
    "compile_storm": ("cache.get",),
    "queue_stall": ("serve.admission",),
    "clock_skew": ("serve.admission",),
    "feed_corrupt": ("data.feed",),
    "crash": ("backtest.chunk", "loadgen.worker"),
}


class InjectedFault(RuntimeError):
    """A deliberately induced device/dispatch fault. Deliberately a
    plain RuntimeError subclass: the serve stack must treat it through
    the SAME containment paths as a real XLA error (breaker counting,
    fallback retry) — special-casing it would test nothing."""


class InjectedCrash(BaseException):
    """A deliberately induced process death for crash-resume tests.

    Derives from BaseException so ordinary ``except Exception``
    containment (the batcher's, the checkpoint loop's) cannot swallow
    it — a real ``kill -9`` wouldn't be swallowed either.
    """


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One injection rule: *at this seam, starting at hit index
    ``start``, fire ``count`` times with probability ``p`` per
    eligible hit*. ``args`` parameterizes the kind (``lanes``,
    ``stall_s``, ``skew_s``, ...)."""

    seam: str
    kind: str
    start: int = 0               # first eligible hit index (0-based)
    count: int = 1               # max fires (None/inf not allowed: bounded)
    p: float = 1.0               # per-hit probability, seeded RNG
    args: Tuple[Tuple[str, Any], ...] = ()

    def __post_init__(self):
        if self.seam not in SEAMS:
            raise ValueError(
                f"unknown seam {self.seam!r}; known: {', '.join(SEAMS)}")
        allowed = KINDS.get(self.kind)
        if allowed is None:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: "
                f"{', '.join(KINDS)}")
        if self.seam not in allowed:
            raise ValueError(
                f"fault kind {self.kind!r} cannot target seam "
                f"{self.seam!r} (allowed: {', '.join(allowed)})")
        if self.count < 1:
            raise ValueError("count must be >= 1")
        if not 0.0 < self.p <= 1.0:
            raise ValueError("p must be in (0, 1]")

    @staticmethod
    def make(seam: str, kind: str, start: int = 0, count: int = 1,
             p: float = 1.0, **args) -> "FaultSpec":
        """Keyword-args convenience constructor (``args`` as kwargs)."""
        return FaultSpec(seam=seam, kind=kind, start=int(start),
                         count=int(count), p=float(p),
                         args=tuple(sorted(args.items())))


@dataclasses.dataclass(frozen=True)
class Scenario:
    """A named, seeded fault program: the unit the chaos suite runs."""

    name: str
    faults: Tuple[FaultSpec, ...]
    seed: int = 0

    def __post_init__(self):
        object.__setattr__(self, "faults", tuple(self.faults))


class FaultAction:
    """What :func:`fire` hands back to a directive-interpreting seam."""

    __slots__ = ("kind", "args", "rng")

    def __init__(self, kind: str, args: Dict[str, Any],
                 rng: np.random.Generator) -> None:
        self.kind = kind
        self.args = args
        self.rng = rng  # the spec's own stream, for e.g. lane choice

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"FaultAction({self.kind!r}, {self.args!r})"


class _SpecState:
    """Per-spec mutable state: hit counter, fire counter, RNG."""

    __slots__ = ("spec", "hits", "fires", "rng")

    def __init__(self, spec: FaultSpec, seed: int) -> None:
        self.spec = spec
        self.hits = 0
        self.fires = 0
        # Seeded from (scenario seed, seam, kind, start): the stream is
        # a function of the rule's identity alone, so concurrent seams
        # cannot perturb each other's draw sequences.
        self.rng = np.random.default_rng(
            np.random.SeedSequence(
                [seed, _stable_hash(spec.seam), _stable_hash(spec.kind),
                 spec.start]))


def _stable_hash(s: str) -> int:
    """Deterministic across processes (builtin hash() is salted)."""
    h = 2166136261
    for ch in s.encode():
        h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
    return h


class FaultInjector:
    """One scenario's live state. Thread-safe; optional metrics/events
    hooks so every injected fault is a counter bump
    (``faults_injected``) and a ``fault_injected`` event next to the
    recovery it is supposed to trigger."""

    def __init__(self, scenario: Scenario, metrics=None,
                 events=None) -> None:
        self.scenario = scenario
        self.metrics = metrics
        self.events = events
        self._lock = threading.Lock()
        self._states: Dict[str, List[_SpecState]] = {}  # guarded-by: self._lock
        for spec in scenario.faults:
            self._states.setdefault(spec.seam, []).append(
                _SpecState(spec, scenario.seed))
        self._log: List[Dict[str, Any]] = []            # guarded-by: self._lock

    # -- seam side ----------------------------------------------------

    def fire(self, seam: str, **ctx) -> Optional[FaultAction]:
        """Consult the scenario at one seam hit. Raising kinds
        (``device_lost``, ``crash``) raise; directive kinds return a
        :class:`FaultAction` the seam interprets; a quiet hit returns
        None. At most one rule fires per hit (specs are consulted in
        scenario order)."""
        with self._lock:
            states = self._states.get(seam)
            if not states:
                return None
            fired: Optional[_SpecState] = None
            for st in states:
                idx = st.hits
                st.hits += 1
                spec = st.spec
                if fired is not None or idx < spec.start \
                        or st.fires >= spec.count:
                    continue
                if spec.p < 1.0 and st.rng.random() >= spec.p:
                    continue
                st.fires += 1
                fired = st
            if fired is None:
                return None
            spec = fired.spec
            self._log.append({"seam": seam, "kind": spec.kind,
                              "hit": fired.hits - 1, **ctx})
        # Hooks run outside the injector lock: emit/inc take their own.
        if self.metrics is not None:
            self.metrics.inc("faults_injected")
        if self.events is not None:
            reserved = ("kind", "fault_kind", "severity", "trace_id",
                        "seam", "scenario", "t")
            self.events.emit("fault_injected", "warn", seam=seam,
                             fault_kind=spec.kind,
                             scenario=self.scenario.name,
                             **{k: v for k, v in ctx.items()
                                if k not in reserved
                                and isinstance(v, (str, int, float, bool))})
        if spec.kind == "device_lost":
            raise InjectedFault(
                f"injected device loss at {seam} "
                f"(scenario {self.scenario.name!r})")
        if spec.kind == "crash":
            raise InjectedCrash(
                f"injected crash at {seam} "
                f"(scenario {self.scenario.name!r})")
        return FaultAction(spec.kind, dict(spec.args), fired.rng)

    # -- readers ------------------------------------------------------

    def log(self) -> List[Dict[str, Any]]:
        """Every fault fired so far (deterministic replay record)."""
        with self._lock:
            return list(self._log)

    def fires(self, seam: Optional[str] = None) -> int:
        with self._lock:
            return sum(st.fires for s, sts in self._states.items()
                       if seam is None or s == seam for st in sts)

    def exhausted(self) -> bool:
        """Every rule has fired its full count (the scenario's induced-
        failure window is over; recovery invariants may be asserted)."""
        with self._lock:
            return all(st.fires >= st.spec.count
                       for sts in self._states.values() for st in sts)


# ---------------------------------------------------------------------------
# process-global install point (the seams' single predicate)
# ---------------------------------------------------------------------------

_INJECTOR: Optional[FaultInjector] = None
_install_lock = threading.Lock()


def enabled() -> bool:
    """The seam predicate: True iff an injector is installed. One
    module-global read — the entire disabled-path cost."""
    return _INJECTOR is not None


def install(injector: FaultInjector) -> FaultInjector:
    """Install the process-global injector (exclusive: installing over
    a live one raises — two scenarios sharing seams would destroy both
    scenarios' determinism)."""
    global _INJECTOR
    with _install_lock:
        if _INJECTOR is not None:
            raise RuntimeError(
                f"a fault injector is already installed (scenario "
                f"{_INJECTOR.scenario.name!r}); uninstall() it first")
        _INJECTOR = injector
    return injector


def uninstall() -> None:
    global _INJECTOR
    with _install_lock:
        _INJECTOR = None


def fire(seam: str, **ctx) -> Optional[FaultAction]:
    """Module-level seam entry: delegates to the installed injector.
    Callers MUST guard with ``if faults.enabled():`` (GC007) — the
    injector reference is re-read here, so a concurrent uninstall
    degrades to a no-op rather than an AttributeError."""
    inj = _INJECTOR
    if inj is None:
        return None
    return inj.fire(seam, **ctx)


def corrupt_feed(qp, action: FaultAction):
    """Apply a ``feed_corrupt`` directive to one request: NaN the
    first ``lanes`` entries (default 1) of the objective vector and
    return the poisoned problem. ONE definition shared by every
    ``data.feed`` driver (``serve.loadgen`` and the chaos suite), so
    the suite exercises exactly the corruption the load generator
    injects — partial-lane poison included."""
    bad_q = np.array(qp.q, copy=True)
    bad_q[: max(int(action.args.get("lanes", 1)), 1)] = np.nan
    return qp._replace(q=bad_q)


@contextlib.contextmanager
def active(scenario: Scenario, metrics=None, events=None):
    """``with faults.active(scenario) as inj:`` — install for the
    block, uninstall on exit (exception-safe; the chaos suite's and
    the tests' entry point)."""
    inj = install(FaultInjector(scenario, metrics=metrics, events=events))
    try:
        yield inj
    finally:
        uninstall()


# ---------------------------------------------------------------------------
# injectable clock
# ---------------------------------------------------------------------------

class FaultClock:
    """A steppable monotonic clock for deterministic replay of time-
    dependent recovery paths (breaker re-close, deadline give-up).
    Thread-safe; call it like ``time.monotonic`` (``DeviceHealth`` and
    ``RetryManager`` accept any zero-arg float callable)."""

    def __init__(self, start: float = 0.0) -> None:
        self._lock = threading.Lock()
        self._now = float(start)   # guarded-by: self._lock

    def __call__(self) -> float:
        with self._lock:
            return self._now

    def advance(self, seconds: float) -> float:
        """Step time forward; returns the new now."""
        with self._lock:
            self._now += float(seconds)
            return self._now


# ---------------------------------------------------------------------------
# builtin scenario catalog (the chaos suite's degradation matrix)
# ---------------------------------------------------------------------------

def builtin_scenarios(seed: int = 0) -> Dict[str, Scenario]:
    """The named scenario grid ``scripts/chaos_suite.py`` runs and
    ``loadgen --chaos NAME`` replays. Counts are small and bounded on
    purpose: every scenario has a failure window that CLOSES, so the
    recovery invariant (breaker re-closes, retries drain, service
    returns to steady state) is assertable after it."""
    mk = FaultSpec.make
    return {
        # Two consecutive dispatch losses: exactly the breaker's
        # failure_threshold default, so the scenario proves open →
        # fallback-retry → (probe ok) → re-close.
        "device_lost": Scenario("device_lost", (
            mk("serve.dispatch", "device_lost", count=2),
            mk("serve.continuous", "device_lost", count=2),
        ), seed=seed),
        # The VERDICT.md black-hole: probes fail (as timeouts do) until
        # the window closes, then the primary answers again.
        "probe_blackhole": Scenario("probe_blackhole", (
            mk("health.probe", "probe_fail", count=3),
        ), seed=seed),
        # Corrupt result lanes: the zero-wrong-answers invariant's
        # sharpest test — validation must catch every one.
        "nan_lanes": Scenario("nan_lanes", (
            mk("serve.result", "nan_lanes", count=3, lanes=2),
        ), seed=seed),
        # Post-warmup compile storm: evict executables mid-traffic.
        "compile_storm": Scenario("compile_storm", (
            mk("cache.get", "compile_storm", start=1, count=3),
        ), seed=seed),
        # Admission stalls age the queue into deadline territory.
        "queue_stall": Scenario("queue_stall", (
            mk("serve.admission", "queue_stall", count=4, stall_s=0.05),
        ), seed=seed),
        # Submitter clock running ahead: deadlines arrive pre-aged.
        "clock_skew": Scenario("clock_skew", (
            mk("serve.admission", "clock_skew", count=4, p=0.5,
               skew_s=30.0),
        ), seed=seed),
        # Poisoned feed data: the request must FAIL, never mis-answer.
        "feed_corrupt": Scenario("feed_corrupt", (
            mk("data.feed", "feed_corrupt", count=2),
        ), seed=seed),
    }
