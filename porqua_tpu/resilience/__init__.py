"""Resilience plane: deterministic fault injection + recovery policies.

Two halves, one package:

* :mod:`porqua_tpu.resilience.faults` — the induction side. A seeded
  :class:`FaultInjector` drives a scenario DSL (:class:`Scenario` /
  :class:`FaultSpec`) through named seams compiled into the existing
  chokepoints (executable-cache dispatch, batcher/continuous execute,
  queue admission, device probe, data feed, checkpoint loop). Zero
  overhead and bit-identical programs when disabled: seams are one
  module-global predicate, proven program-neutral by the GC104 jaxpr-
  identity contract, and every seam is guarded by the mechanically
  enforced ``if faults.enabled():`` pattern (graftcheck GC007).
* :mod:`porqua_tpu.resilience.retry` — the recovery side.
  :class:`RetryPolicy` / :class:`RetryManager` wire per-request retry
  with exponential backoff + seeded jitter, idempotent resubmission
  keyed by request id (one id, one future, one resolution), deadline-
  aware give-up, optional hedged duplicates for tail latency, and
  result validation (the zero-wrong-answers gate) into
  ``SolveService(retry=RetryPolicy(...))``.

The degradation matrix lives in ``scripts/chaos_suite.py`` (scenario
grid x {classic, continuous} serve modes, invariant assertions, JSON
verdict report); ``serve_loadgen.py --chaos NAME`` replays one
scenario under load. See README "Resilience & chaos testing".
"""

from porqua_tpu.resilience.faults import (
    FaultAction,
    FaultClock,
    FaultInjector,
    FaultSpec,
    InjectedCrash,
    InjectedFault,
    Scenario,
    builtin_scenarios,
)

_RETRY_NAMES = ("RetryManager", "RetryPolicy", "validate_result")


def __getattr__(name):
    # retry.py imports the serve stack (for the failure taxonomy it
    # classifies), and the serve stack imports `faults` for its seam
    # predicates — loading retry lazily keeps this package importable
    # from inside a serve module's own import (no cycle), same pattern
    # as porqua_tpu.analysis defers `contracts`.
    if name in _RETRY_NAMES:
        import importlib

        mod = importlib.import_module("porqua_tpu.resilience.retry")
        return getattr(mod, name)
    raise AttributeError(name)

__all__ = [
    "FaultAction",
    "FaultClock",
    "FaultInjector",
    "FaultSpec",
    "InjectedCrash",
    "InjectedFault",
    "RetryManager",
    "RetryPolicy",
    "Scenario",
    "builtin_scenarios",
    "validate_result",
]
