"""Recovery policies for the online solve service: retry, hedge, validate.

The batcher already contains one recovery loop — the in-dispatch
device-fault retry that rides the circuit breaker (TPU → XLA-CPU).
This module adds the *request-level* policies that sit above it,
because a production caller cares about exactly three things the
dispatch loop cannot provide:

* **Per-request retry with backoff + jitter.** A request whose batch
  failed (device fault that exhausted the dispatch loop, sanitizer
  refusal, a validation failure) is resubmitted after an exponential
  backoff with seeded jitter, bounded by ``max_attempts`` and — always
  — by the request's own deadline: a retry that cannot finish before
  the deadline is not scheduled (``retry_giveups``).
* **Idempotent resubmission keyed by request id.** ``submit(...,
  request_id=...)`` registers the request; submitting the same id
  again — a client retrying over a flaky transport, a replayed
  message — returns the SAME ticket, whether the request is in flight
  or already resolved. One id, one future, one resolution: no
  double-resolve, no double-counted metrics, no duplicate device work.
* **Hedged duplicates for tail latency.** With ``hedge_after_s`` set,
  a request still unresolved that long after submission fires one
  duplicate; first valid result wins, the loser is discarded at the
  resolution gate (``hedges_fired`` / ``hedges_won``).

Result validation (``validate=True``) is the zero-wrong-answers gate:
a solution whose primal vector or certificates are non-finite — a
corrupted lane, a numerically destroyed solve — is treated as a
*failure* (counted in ``validation_failures``, eligible for retry),
never handed to the caller as an answer.

All timing flows through an injectable ``clock`` (default
``time.monotonic``) so chaos scenarios replay deterministically
against a :class:`porqua_tpu.resilience.faults.FaultClock`; the
scheduler thread polls in short bounded waits precisely so a stepped
fake clock is observed without real-time sleeps of the same length.

Everything here is host-side policy over the existing submit path —
the device programs, and the jaxpr contracts over them, are untouched.
"""

from __future__ import annotations

import dataclasses
import heapq
import threading
import time
import uuid
from collections import OrderedDict
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional

import numpy as np

from porqua_tpu.analysis import tsan
from porqua_tpu.serve.batcher import DeadlineExpired, SolveError

__all__ = ["RetryPolicy", "RetryManager", "validate_result"]


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Knobs for :class:`RetryManager` (frozen: policy is service
    identity, like SolverParams)."""

    max_attempts: int = 3          # primary attempts (1 = no retry)
    backoff_base_s: float = 0.02   # first retry delay
    backoff_mult: float = 2.0      # exponential growth per retry
    jitter: float = 0.5            # +- fraction of the delay, seeded
    hedge_after_s: Optional[float] = None  # None = no hedging
    validate: bool = True          # reject non-finite results
    registry_capacity: int = 8192  # idempotency window (LRU)
    seed: int = 0                  # jitter RNG seed

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")

    def backoff_s(self, attempt: int, rng: np.random.Generator) -> float:
        """Delay before retry number ``attempt`` (1-based)."""
        base = self.backoff_base_s * self.backoff_mult ** (attempt - 1)
        if self.jitter:
            base *= 1.0 + self.jitter * float(rng.uniform(-1.0, 1.0))
        return max(base, 0.0)


def validate_result(res) -> Optional[str]:
    """None when ``res`` is fit to hand to a caller; otherwise the
    reason it is not. The gate is finiteness — a NaN/Inf primal vector
    or certificate is by definition not a solution, whatever status
    claims — so an injected ``nan_lanes`` corruption (or a real
    numerically destroyed lane) converts to a retryable failure
    instead of a wrong answer."""
    x = np.asarray(res.x)
    if not np.all(np.isfinite(x)):
        return "non-finite primal solution"
    for name in ("prim_res", "dual_res", "obj_val"):
        if not np.isfinite(getattr(res, name)):
            return f"non-finite {name}"
    return None


class _Entry:
    """One registered request's lifecycle state (guarded by the
    manager lock except the Future, which is its own sync point)."""

    __slots__ = ("request_id", "qp", "warm_key", "deadline", "future",
                 "submitted", "attempts", "hedges", "inflight",
                 "resolved", "last_exc", "tenant")

    def __init__(self, request_id: str, qp, warm_key, deadline,
                 submitted: float, tenant=None) -> None:
        self.request_id = request_id
        self.qp = qp
        self.warm_key = warm_key
        # Tenant id for per-tenant attribution of validation failures
        # and give-ups (and quota enforcement on every inner attempt).
        self.tenant = tenant
        self.deadline = deadline        # absolute, manager clock; None
        self.future: Future = Future()  # the caller's future
        self.submitted = submitted
        self.attempts = 0               # primary attempts issued
        self.hedges = 0
        self.inflight = 0               # inner futures not yet done
        self.resolved = False
        self.last_exc: Optional[BaseException] = None


class RetryManager:
    """Request-level recovery layer over one :class:`SolveService`.

    Created by ``SolveService(retry=RetryPolicy(...))``; every public
    ``submit`` routes through :meth:`submit` here, which fans inner
    attempts into the service's raw path (``SolveService._submit_raw``)
    and resolves exactly one caller-facing future per request id.
    """

    def __init__(self, service, policy: RetryPolicy, metrics,
                 events=None,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.service = service
        self.policy = policy
        self.metrics = metrics
        self.events = events
        self.clock = time.monotonic if clock is None else clock
        self._rng = np.random.default_rng(policy.seed)
        self._lock = tsan.lock("RetryManager")
        # guarded-by: self._lock
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._timers: list = []         # guarded-by: self._lock (heap)
        self._timer_seq = 0             # guarded-by: self._lock
        self._cond = threading.Condition(self._lock)
        self._thread: Optional[threading.Thread] = None
        self._stopping = False          # guarded-by: self._lock

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        with self._lock:
            self._stopping = False
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run_timers, name="porqua-serve-retry",
                daemon=True)
            self._thread.start()

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        with self._lock:
            self._stopping = True
            self._timers.clear()
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        # Stopping abandons every scheduled retry/hedge — each
        # unresolved entry's future must fail NOW, or a caller blocked
        # in service.result() waits forever on a timer that will never
        # fire. Marked resolved under the lock first so a late
        # _on_inner_done from a still-draining inner future discards
        # itself instead of racing the resolution.
        abandoned = []
        with self._lock:
            for entry in self._entries.values():
                if not entry.resolved:
                    entry.resolved = True
                    entry.qp = None
                    abandoned.append(entry)
        for entry in abandoned:
            self.metrics.inc("retry_giveups")
            self.metrics.inc_tenant(entry.tenant or "default",
                                    "retry_giveups")
            if self.events is not None:
                last = entry.last_exc
                self.events.emit(
                    "retry_giveup", "error",
                    request_id=entry.request_id, reason="stopped",
                    attempts=entry.attempts, hedges=entry.hedges,
                    tenant=entry.tenant or "default",
                    error=(None if last is None
                           else f"{type(last).__name__}: {last}"))
            entry.future.set_exception(SolveError(
                f"service stopped before request {entry.request_id} "
                f"resolved (attempts={entry.attempts})"))

    # -- public -------------------------------------------------------

    def submit(self, qp, deadline_s: Optional[float] = None,
               warm_key: Optional[str] = None,
               timeout: Optional[float] = None,
               request_id: Optional[str] = None,
               tenant: Optional[str] = None):
        """Register (or deduplicate) one request and issue its first
        attempt; returns the service's Ticket type over the caller's
        future. A ``request_id`` already registered — in flight OR
        resolved — returns the existing ticket untouched."""
        from porqua_tpu.serve.service import Ticket

        now = self.clock()
        if request_id is None:
            request_id = uuid.uuid4().hex
        with self._lock:
            entry = self._entries.get(request_id)
            if entry is not None:
                # Idempotent resubmission: same id -> same future. No
                # inner work is issued, no counter moves; the LRU
                # refresh just extends the dedupe window.
                self._entries.move_to_end(request_id)
                return Ticket(future=entry.future, submitted=entry.submitted)
            entry = _Entry(request_id, qp, warm_key,
                           None if deadline_s is None else now + deadline_s,
                           submitted=time.monotonic(), tenant=tenant)
            self._entries[request_id] = entry
            # LRU-evict RESOLVED entries only: evicting an in-flight
            # one would fork its id (a duplicate submit registers a
            # second future) and orphan the original future at stop(),
            # which only fails entries still in the registry. If every
            # entry is unresolved the registry transiently exceeds
            # capacity — bounded by the caller's in-flight window.
            excess = len(self._entries) - self.policy.registry_capacity
            if excess > 0:
                # Walk oldest-first and stop once the excess is
                # covered: in steady state the head IS resolved, so
                # this is O(excess) under the lock, not a full
                # registry scan per submit.
                stale = []
                for rid, e in self._entries.items():
                    if len(stale) >= excess:
                        break
                    if e.resolved:
                        stale.append(rid)
                for rid in stale:
                    del self._entries[rid]
        self._issue(entry, kind="primary", submit_timeout=timeout,
                    propagate_queue_full=(timeout is not None
                                          and timeout <= 0))
        if self.policy.hedge_after_s is not None:
            self._schedule(now + self.policy.hedge_after_s,
                           lambda: self._maybe_hedge(entry))
        return Ticket(future=entry.future, submitted=entry.submitted)

    # -- attempts -----------------------------------------------------

    def _remaining(self, entry: _Entry) -> Optional[float]:
        return (None if entry.deadline is None
                else entry.deadline - self.clock())

    def _issue(self, entry: _Entry, kind: str,
               submit_timeout: Optional[float] = None,
               propagate_queue_full: bool = False) -> None:
        """Issue one inner attempt (primary/retry/hedge). A submit that
        fails synchronously (QueueFull, stopped service) flows through
        the same completion path as a failed future — except a
        ``QueueFull`` under ``propagate_queue_full``, which unregisters
        the entry and re-raises so a non-blocking caller (open-loop
        load generation) sees the backpressure it asked to observe."""
        with self._lock:
            if entry.resolved:
                return
            if kind != "hedge":
                entry.attempts += 1
            entry.inflight += 1
            qp = entry.qp  # read under the lock: resolution drops it
        remaining = self._remaining(entry)
        if remaining is not None and remaining <= 0:
            failed: Future = Future()
            failed.set_exception(DeadlineExpired(
                f"request {entry.request_id} deadline passed before "
                f"{kind} attempt could be issued"))
            self._on_inner_done(entry, kind, failed)
            return
        try:
            ticket = self.service._submit_raw(
                qp, deadline_s=remaining, warm_key=entry.warm_key,
                timeout=submit_timeout, tenant=entry.tenant)
        except Exception as exc:  # noqa: BLE001 - policy boundary
            from porqua_tpu.serve.service import QueueFull

            if propagate_queue_full and isinstance(exc, QueueFull):
                with self._lock:
                    entry.inflight -= 1
                    self._entries.pop(entry.request_id, None)
                raise
            failed = Future()
            failed.set_exception(exc)
            self._on_inner_done(entry, kind, failed)
            return
        ticket.future.add_done_callback(
            lambda f, e=entry, k=kind: self._on_inner_done(e, k, f))

    def _maybe_hedge(self, entry: _Entry) -> None:
        with self._lock:
            if entry.resolved or self._stopping:
                return
            remaining = self._remaining(entry)
            if remaining is not None and remaining <= 0:
                return
            entry.hedges += 1
        self.metrics.inc("hedges_fired")
        if self.events is not None:
            self.events.emit("hedge_fired", "info",
                             request_id=entry.request_id,
                             attempt=entry.attempts)
        # Non-blocking submit: this runs on the single timer thread,
        # which must never block on a full queue — a QueueFull becomes
        # a failed attempt (eligible for backoff) instead of stalling
        # every other scheduled retry and hedge behind it.
        self._issue(entry, kind="hedge", submit_timeout=0.0)

    # -- completion ---------------------------------------------------

    def _on_inner_done(self, entry: _Entry, kind: str,
                       fut: Future) -> None:
        exc = fut.exception()
        res = None if exc is not None else fut.result()
        if exc is None and self.policy.validate:
            reason = validate_result(res)
            if reason is not None:
                self.metrics.inc("validation_failures")
                self.metrics.inc_tenant(entry.tenant or "default",
                                        "validation_failures")
                if self.events is not None:
                    # `kind` (the event kind) is emit's first
                    # positional; the attempt kind rides under its own
                    # name. The tenant rides along so a corrupt-feed
                    # incident bundle names the offending tenant.
                    self.events.emit(
                        "validation_failed", "error",
                        request_id=entry.request_id, attempt_kind=kind,
                        trace_id=getattr(res, "trace_id", None),
                        reason=reason, tenant=entry.tenant or "default")
                exc = SolveError(
                    f"result validation failed ({reason}); the answer "
                    f"was withheld and the attempt treated as a failure")
                res = None

        resolve_exc: Optional[BaseException] = None
        resolve_res = None
        retry_delay: Optional[float] = None
        giveup_reason: Optional[str] = None
        won_hedge = was_resumed = False
        with self._lock:
            entry.inflight -= 1
            if entry.resolved:
                return  # a sibling attempt already won; discard
            if exc is None:
                entry.resolved = True
                # Resolution drops the problem payload: the entry only
                # outlives this point as the idempotency record (id ->
                # future), and up to registry_capacity retained QP
                # matrices is real memory on real problem sizes.
                entry.qp = None
                resolve_res = res
                won_hedge = kind == "hedge"
                was_resumed = entry.attempts > 1 or won_hedge
            else:
                entry.last_exc = exc
                now = self.clock()
                if isinstance(exc, DeadlineExpired):
                    # Deadline-aware give-up: the budget is spent; a
                    # retry would expire in the queue all over again.
                    giveup_reason = "deadline"
                elif entry.attempts >= self.policy.max_attempts:
                    giveup_reason = "attempts"
                else:
                    delay = self.policy.backoff_s(entry.attempts,
                                                  self._rng)
                    if entry.deadline is not None \
                            and now + delay >= entry.deadline:
                        giveup_reason = "deadline"
                    else:
                        retry_delay = delay
                if giveup_reason is not None and entry.inflight > 0:
                    # A hedge is still racing: let it decide the
                    # request rather than failing a future its twin
                    # may yet resolve.
                    return
                if giveup_reason is not None:
                    entry.resolved = True
                    entry.qp = None
                    resolve_exc = exc

        if resolve_res is not None:
            if won_hedge:
                self.metrics.inc("hedges_won")
            if was_resumed:
                # The request completed only because the policy
                # re-drove it (a retry or a hedge) — the figure the
                # loadgen report surfaces as `resumed_requests`.
                self.metrics.inc("resumed_requests")
            entry.future.set_result(resolve_res)
            return
        if resolve_exc is not None:
            self.metrics.inc("retry_giveups")
            self.metrics.inc_tenant(entry.tenant or "default",
                                    "retry_giveups")
            if self.events is not None:
                self.events.emit(
                    "retry_giveup", "error",
                    request_id=entry.request_id, reason=giveup_reason,
                    attempts=entry.attempts, hedges=entry.hedges,
                    tenant=entry.tenant or "default",
                    error=f"{type(resolve_exc).__name__}: {resolve_exc}")
            entry.future.set_exception(resolve_exc)
            return
        if retry_delay is not None:
            self.metrics.inc("retries")
            if self.events is not None:
                self.events.emit(
                    "retry_scheduled", "warn",
                    request_id=entry.request_id,
                    attempt=entry.attempts + 1,
                    delay_s=round(retry_delay, 4),
                    error=f"{type(exc).__name__}: {exc}")
            # submit_timeout=0.0: retries are issued from the single
            # timer thread, which must never block on a full queue
            # (a QueueFull is just another failed attempt).
            self._schedule(self.clock() + retry_delay,
                           lambda: self._issue(entry, kind="retry",
                                               submit_timeout=0.0))

    # -- timer wheel --------------------------------------------------

    def _schedule(self, due: float, fn: Callable[[], None]) -> None:
        with self._lock:
            self._timer_seq += 1
            heapq.heappush(self._timers, (due, self._timer_seq, fn))
            self._cond.notify_all()

    def _run_timers(self) -> None:
        """Fire due timers; wait in SHORT bounded slices so a stepped
        FaultClock is observed promptly without busy-spinning (50 ms
        poll floor — far below any backoff/hedge delay that matters,
        invisible next to a real device dispatch)."""
        while True:
            fns = []
            with self._lock:
                if self._stopping:
                    return
                now = self.clock()
                while self._timers and self._timers[0][0] <= now:
                    _, _, fn = heapq.heappop(self._timers)
                    fns.append(fn)
                if not fns:
                    wait = 0.05
                    if self._timers:
                        wait = min(wait, max(self._timers[0][0] - now,
                                             1e-4))
                    self._cond.wait(timeout=wait)
            for fn in fns:
                try:
                    fn()
                except Exception:  # noqa: BLE001 - timer containment
                    # A policy bug must not kill the timer thread (it
                    # would silently disable every later retry/hedge).
                    pass

    # -- readers ------------------------------------------------------

    def pending(self) -> int:
        with self._lock:
            return sum(1 for e in self._entries.values()
                       if not e.resolved)

    def entry_stats(self, request_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            e = self._entries.get(request_id)
            if e is None:
                return None
            return {"attempts": e.attempts, "hedges": e.hedges,
                    "resolved": e.resolved, "inflight": e.inflight}
