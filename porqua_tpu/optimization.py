"""Optimization strategy classes (objective formulation + solve).

Mirror of the reference's strategy layer (``src/optimization.py``):
``MeanVariance``, ``QEQW``, ``LeastSquares``, ``WeightedLeastSquares``,
``LAD``, ``PercentilePortfolios`` — with the solve path inverted. The
reference assembles a ``qpsolvers`` problem and crosses into a C solver
per call (``optimization.py:77-143``); here ``solve()`` lowers the
problem to a padded :class:`~porqua_tpu.qp.canonical.CanonicalQP` and
runs the batched JAX ADMM solver — on TPU, inside jit, warm-startable.

Reference quirks intentionally fixed (SURVEY.md section 7):
``MeanVariance`` instantiates the mean estimator (reference
``optimization.py:165`` assigns the class), and the LAD leverage branch
uses the corrected lift (reference ``optimization.py:333,341``).
"""

from __future__ import annotations

import warnings
from abc import ABC, abstractmethod
from typing import Optional

import numpy as np
import pandas as pd

from porqua_tpu.constraints import Constraints
from porqua_tpu.estimators.covariance import Covariance
from porqua_tpu.estimators.mean import MeanEstimator
from porqua_tpu.optimization_data import OptimizationData
from porqua_tpu.qp import lift
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.solve import QPSolution, SolverParams, Status, solve_qp
from porqua_tpu.utils.helpers import to_numpy

# Solver-parameter keys that OptimizationParameter forwards to SolverParams.
_SOLVER_KEYS = tuple(SolverParams.__dataclass_fields__.keys())


class OptimizationParameter(dict):
    """Free-form parameter dict (reference ``optimization.py:40-47``) that
    can project itself onto the typed, hashable :class:`SolverParams`."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.__dict__ = self
        # Key-presence checks: an explicit falsy value (solver_name="",
        # allow_suboptimal=False, verbose=False) must survive — the
        # reference's truthiness-based defaulting silently re-defaults
        # them, which is exactly the dict-config looseness the typed
        # SolverParams retires. ``allow_suboptimal`` is deliberately
        # NOT materialized here: absent reads as falsy (strict success
        # semantics) via ``.get()``, and key presence then faithfully
        # records that the caller set it — which lets strategy classes
        # with a different default (LAD) distinguish "caller said
        # False" from "caller said nothing".
        if "solver_name" not in self:
            self["solver_name"] = "jax_admm"
        if "verbose" not in self:
            self["verbose"] = True

    def to_solver_params(self) -> SolverParams:
        fields = {k: self[k] for k in _SOLVER_KEYS if k in self}
        return SolverParams(**fields)


class Objective(dict):

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)


class Optimization(ABC):
    """Template-method base (reference ``optimization.py:56-143``):
    ``set_objective(data)`` then ``solve()``."""

    def __init__(self,
                 params: OptimizationParameter = None,
                 constraints: Constraints = None,
                 **kwargs):
        self.params = OptimizationParameter(**kwargs) if params is None else params
        self.objective = Objective()
        self.constraints = Constraints() if constraints is None else constraints
        self.model = None          # CanonicalQP after model_canonical()
        self.solution: Optional[QPSolution] = None
        self.results = None

    @abstractmethod
    def set_objective(self, optimization_data: OptimizationData) -> None:
        raise NotImplementedError("Method 'set_objective' must be implemented in derived class.")

    def solve(self) -> bool:
        self.solve_jax()
        return self.results["status"]

    # ------------------------------------------------------------------
    # Canonical lowering + device solve (replaces solve_qpsolvers /
    # model_qpsolvers, reference optimization.py:77-143)
    # ------------------------------------------------------------------

    def solver_params(self, solve_dtype=None) -> SolverParams:
        """Resolved solver configuration for this strategy's active
        lowering. Pure: consults but never mutates ``self.params``, so
        callers may derive it before or after ``canonical_parts`` and
        see the same answer. Subclasses with lowering-dependent solver
        defaults (LAD's prox-form LP settings) merge them here.

        ``solve_dtype``: the dtype the consumer will actually solve in,
        when it differs from the strategy's own declaration — the batch
        engine casts problems to ITS dtype argument (f32 default), so
        dtype-sensitive defaults must key on the solve dtype, not the
        declaration."""
        return self.params.to_solver_params()

    def solve_jax(self) -> None:
        name = self.params.get("solver_name", "jax_admm")
        if name not in (None, "", "jax_admm", "default"):
            # Reference parity: dispatch by solver name (the reference
            # routes through qpsolvers' backend strings,
            # ``optimization.py:45`` + ``qp_problems.py:211``). Every
            # backend of the compare harness is addressable here.
            self._solve_via_backend(name)
            return
        qp = self.model_canonical()
        solver_params = self.solver_params()

        x0 = self._x_init_array()
        if x0 is not None and x0.shape[0] != qp.n:
            x0 = np.concatenate([x0, np.zeros(qp.n - x0.shape[0])])

        l1 = getattr(self, "_l1_pair", None)
        sol = solve_qp(
            qp, solver_params,
            x0=None if x0 is None else np.asarray(x0, dtype=np.asarray(qp.q).dtype),
            l1_weight=None if l1 is None else l1[0],
            l1_center=None if l1 is None else l1[1],
        )
        self.solution = sol
        self._publish_results(np.asarray(sol.x), int(sol.status))

    def _publish_results(self, x: np.ndarray, status_code: int,
                         suboptimal_acceptable: bool = True) -> None:
        """One copy of the results contract for every solve path:
        status from the code (with the ``allow_suboptimal`` MAX_ITER
        acceptance where the backend can vouch for MAX_ITER-ness),
        weights = x's universe slice on success, Nones on failure."""
        status = bool(status_code == Status.SOLVED)
        if (not status and suboptimal_acceptable
                and self.params.get("allow_suboptimal")):
            status = bool(status_code == Status.MAX_ITER)
        universe = self.constraints.selection
        weights = pd.Series(
            x[: len(universe)] if status else [None] * len(universe),
            index=universe,
        )
        self.results = {"weights": weights.to_dict(), "status": status}

    # Reference solver-name spellings -> compare-harness backend keys.
    # The reference's set (cvxopt/osqp/quadprog/daqp/highs/qpalm,
    # ``qp_problems.py:19-30``) maps onto qpsolvers-* rows, which exist
    # only where the qpsolvers package is installed.
    _SOLVER_ALIASES = {
        "ipm": "ipm-f64",
        "interior-point": "ipm-f64",
        "native": "native-cpp-admm",
        "cpp": "native-cpp-admm",
        "scipy": "scipy-slsqp",
        "slsqp": "scipy-slsqp",
        "admm-f32": "device-admm-f32",
        "admm-f64": "device-admm-f64",
        **{s: f"qpsolvers-{s}" for s in
           ("cvxopt", "osqp", "quadprog", "daqp", "highs", "qpalm",
            "clarabel", "ecos", "scs", "piqp", "proxqp")},
    }

    def _solve_via_backend(self, name: str) -> None:
        """Solve through a named compare-harness backend (f64 IPM, the
        native C++ ADMM core, scipy, qpsolvers-* when installed).

        These backends consume the *unpadded* canonical parts and return
        (x, y, mu, found); they do not implement the native L1 prox or
        warm starts — cost terms must use the lifted formulation (the
        reference's own, ``qp_problems.py:120-157``), which
        ``canonical_parts`` emits whenever ``l1_native`` is unset.
        """
        from types import SimpleNamespace

        from porqua_tpu.compare import available_backends

        parts = self.canonical_parts()
        if "l1_weight" in parts:
            raise ValueError(
                f"solver_name={name!r} cannot solve the native-L1 prox "
                "form; drop l1_native (the lifted formulation is "
                "backend-agnostic) or use the default jax_admm solver")
        key = self._SOLVER_ALIASES.get(name, name)
        backends = available_backends()
        if key not in backends:
            raise ValueError(
                f"solver {name!r} (backend key {key!r}) is not available "
                f"in this environment; have {sorted(backends)}")
        x, y, mu, found = backends[key](parts, self.solver_params())

        if not found and self.params.get("allow_suboptimal"):
            # The backend contract reports only found/not-found; unlike
            # the device solver's status codes it cannot distinguish
            # "hit max_iter near the optimum" from "infeasible", so the
            # MAX_ITER acceptance cannot be applied safely here.
            warnings.warn(
                f"solver {name!r} reported failure; allow_suboptimal "
                "cannot be honored through named backends (no MAX_ITER/"
                "infeasible distinction) — use the default jax_admm "
                "solver for suboptimal acceptance", stacklevel=3)
        self.solution = SimpleNamespace(
            x=x, y=y, mu=mu, found=bool(found),
            status=Status.SOLVED if found else Status.MAX_ITER,
            iters=-1, prim_res=np.nan, dual_res=np.nan,
        )
        self._publish_results(
            np.asarray(x),
            Status.SOLVED if found else Status.MAX_ITER,
            suboptimal_acceptable=False,
        )

    def canonical_parts(self) -> dict:
        """Assemble objective + constraints into *unpadded* canonical parts
        ``{P, q, C, l, u, lb, ub, constant}`` (numpy).

        The batched backtest (:mod:`porqua_tpu.batch`) collects these for
        every rebalance date first, finds the maximum dimensions, and only
        then pads — so all dates share one static shape.
        """
        if "P" in self.objective:
            P = to_numpy(self.objective["P"])
        else:
            raise ValueError("Missing matrix 'P' in objective.")
        q = (
            to_numpy(self.objective["q"]).reshape(-1)
            if "q" in self.objective
            else np.zeros(len(self.constraints.selection))
        )
        constant = self.objective.get("constant") or 0.0

        constraints = self.constraints
        n = len(constraints.selection)
        C, l, u = constraints.interval_rows()
        lb, ub = constraints.bounds()

        parts = lift._as_parts(np.asarray(P, float), np.asarray(q, float), C, l, u, lb, ub)
        # Low-rank objective structure (P == 2 Pf' Pf + diag(Pdiag)),
        # when the objective exposes it. The dimension-expanding lifts
        # below rebuild `parts` from scratch, so a lifted problem
        # naturally sheds the factor (it would no longer reproduce the
        # expanded P); the native-L1 path keeps the problem — and the
        # factor — intact.
        if "Pf" in self.objective:
            parts["Pf"] = to_numpy(self.objective["Pf"])
            pd_ = self.objective.get("Pdiag")
            if pd_ is not None:
                parts["Pdiag"] = to_numpy(pd_)

        # L1 terms (reference optimization.py:125-142). The two turnover
        # rewrites are mutually exclusive: a zero/absent transaction cost
        # means "no cost term", in which case a turnover *constraint* (if
        # declared) applies — never both, since each expands the variable
        # space and the second lift would see a stale x_init length.
        x_init = self._x_init_array()
        transaction_cost = self.params.get("transaction_cost")
        tocon = self.constraints.l1.get("turnover")
        if transaction_cost and x_init is not None:
            if self.params.get("l1_native"):
                # Native prox path: keep the problem at n variables and
                # hand the turnover-cost term to the solver's w-block
                # soft-threshold (admm_solve l1_weight/l1_center) — the
                # static-shape alternative to the reference's 2x
                # variable expansion (qp_problems.py:120-157).
                parts["l1_weight"] = np.full(n, float(transaction_cost))
                parts["l1_center"] = np.asarray(x_init, dtype=float)
            else:
                parts = lift.lift_turnover_objective(parts, x_init, transaction_cost)
        elif tocon and x_init is not None:
            parts = lift.lift_turnover_constraint(parts, x_init, tocon["rhs"])
        levcon = self.constraints.l1.get("leverage")
        if levcon is not None:
            # The lift rebuilds the parts dict; carry the native-L1 keys
            # across it (they address the first n variables, which the
            # leverage lift leaves in place before its aux block).
            l1_keys = {k: parts[k] for k in ("l1_weight", "l1_center")
                       if k in parts}
            parts = lift.lift_leverage_constraint(parts, levcon["rhs"])
            parts.update(l1_keys)

        parts["constant"] = float(constant)
        return parts

    def model_canonical(self) -> CanonicalQP:
        """Lower to a padded :class:`CanonicalQP` (device-ready)."""
        parts = self.canonical_parts()
        self.model = CanonicalQP.build(
            parts["P"], parts["q"], C=parts["C"], l=parts["l"], u=parts["u"],
            lb=parts["lb"], ub=parts["ub"], constant=parts["constant"],
            n_max=self.params.get("n_max"), m_max=self.params.get("m_max"),
            dtype=self.params.get("dtype"),
            Pf=parts.get("Pf"), Pdiag=parts.get("Pdiag"),
        )
        if "l1_weight" in parts:
            n_pad = self.model.n
            dt = np.asarray(self.model.q).dtype
            self._l1_pair = (
                np.pad(parts["l1_weight"], (0, n_pad - len(parts["l1_weight"]))).astype(dt),
                np.pad(parts["l1_center"], (0, n_pad - len(parts["l1_center"]))).astype(dt),
            )
        else:
            self._l1_pair = None
        return self.model

    def _x_init_array(self) -> Optional[np.ndarray]:
        """Reference-position x0 from the turnover constraint or params
        (reference ``optimization.py:126-128``)."""
        tocon = self.constraints.l1.get("turnover")
        x0 = (
            tocon["x0"]
            if tocon is not None and tocon.get("x0") is not None
            else self.params.get("x0")
        )
        if x0 is None:
            return None
        universe = self.constraints.selection
        return np.array([x0.get(asset, 0) for asset in universe], dtype=float)

    def is_feasible(self) -> bool:
        """Zero-objective probe solve (reference ``qp_problems.py:159-182``)."""
        import jax.numpy as jnp

        qp = self.model_canonical()
        # Drop any objective factor with the objective: the factored
        # polish/linsolve paths would otherwise solve against the REAL
        # Hessian the stale Pf still describes, not the probe's.
        probe = qp._replace(P=jnp.eye(qp.n, dtype=qp.P.dtype) * 1e-6,
                            q=jnp.zeros(qp.n, dtype=qp.q.dtype),
                            Pf=None, Pdiag=None)
        sol = solve_qp(probe, self.params.to_solver_params())
        return bool(sol.status == Status.SOLVED)


class EmptyOptimization(Optimization):

    def set_objective(self, optimization_data: OptimizationData = None) -> None:
        pass

    def solve(self) -> bool:
        return super().solve()


class MeanVariance(Optimization):

    def __init__(self,
                 covariance: Optional[Covariance] = None,
                 mean_estimator: Optional[MeanEstimator] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self.covariance = Covariance() if covariance is None else covariance
        # Reference bug fixed: optimization.py:165 assigns the class.
        self.mean_estimator = MeanEstimator() if mean_estimator is None else mean_estimator
        self.params.setdefault("risk_aversion", 1)

    def set_objective(self, optimization_data: OptimizationData) -> None:
        X = optimization_data["return_series"]
        ra = self.params["risk_aversion"]
        mu = self.mean_estimator.estimate(X=X) * (-1)
        fac = self.covariance.factor(X)
        if fac is not None:
            # Assemble P FROM the factor form Sigma == F'F + diag(d):
            # P = 2 ra Sigma = 2 (sqrt(ra) F)'(sqrt(ra) F) + diag(2 ra d)
            # — PSD by construction (no repair can desynchronize the
            # dense and factored views), and the solver's capacitance
            # paths get the structure.
            F, dvec = fac
            Pf = np.sqrt(float(ra)) * F
            Pdiag = 2.0 * float(ra) * dvec
            P = 2.0 * Pf.T @ Pf + np.diag(Pdiag)
            self.objective = Objective(q=to_numpy(mu), P=P,
                                       Pf=Pf, Pdiag=Pdiag)
        else:
            covmat = self.covariance.estimate(X=X) * ra * 2
            self.objective = Objective(q=to_numpy(mu), P=to_numpy(covmat))

    def solve(self) -> bool:
        return super().solve()


class QEQW(Optimization):
    """Quasi-equal-weight: identity covariance (reference
    ``optimization.py:180-194``)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.covariance = Covariance(method="duv")

    def set_objective(self, optimization_data: OptimizationData) -> None:
        X = optimization_data["return_series"]
        covmat = self.covariance.estimate(X=X) * 2
        mu = np.zeros(X.shape[1])
        self.objective = Objective(P=to_numpy(covmat), q=mu)

    def solve(self) -> bool:
        return super().solve()


class LeastSquares(Optimization):
    """Index tracking: min ||Xw - y||^2 (reference ``optimization.py:198-229``)."""

    def __init__(self, covariance: Optional[Covariance] = None, **kwargs):
        super().__init__(**kwargs)
        self.covariance = covariance

    def set_objective(self, optimization_data: OptimizationData) -> None:
        X = optimization_data["return_series"]
        y = optimization_data["bm_series"]
        if self.params.get("log_transform"):
            X = np.log(1 + X)
            y = np.log(1 + y)

        P = 2 * (X.T @ X)
        q = to_numpy(-2 * X.T @ y).reshape((-1,))
        constant = float(np.asarray(to_numpy(y.T @ y)).item())

        l2_penalty = self.params.get("l2_penalty")
        Pdiag = np.zeros(X.shape[1])
        if l2_penalty is not None and l2_penalty != 0:
            P = to_numpy(P) + 2 * l2_penalty * np.eye(X.shape[1])
            Pdiag = np.full(X.shape[1], 2.0 * l2_penalty)

        # Expose the Gram structure (P == 2 X'X + diag(2 l2)): the
        # polish factors the (T+m)-dim capacitance instead of n x n,
        # and the capacitance linear-solve mode needs it.
        self.objective = Objective(P=to_numpy(P), q=q, constant=constant,
                                   Pf=to_numpy(X), Pdiag=Pdiag)

    def solve(self) -> bool:
        return super().solve()


class WeightedLeastSquares(Optimization):
    """Exponentially-weighted tracking with half-life ``tau`` (reference
    ``optimization.py:232-259``)."""

    def set_objective(self, optimization_data: OptimizationData) -> None:
        X = optimization_data["return_series"]
        y = optimization_data["bm_series"]
        if self.params.get("log_transform"):
            X = np.log(1 + X)
            y = np.log(1 + y)

        tau = self.params["tau"]
        lambda_val = np.exp(-np.log(2) / tau)
        i = np.arange(X.shape[0])
        wt_tmp = lambda_val ** i
        wt = np.flip(wt_tmp / np.sum(wt_tmp) * len(wt_tmp))

        Xv = to_numpy(X)
        yv = to_numpy(y).reshape(-1)
        Xw = Xv * wt[:, None]
        P = 2 * (Xv.T @ Xw)
        q = -2 * (Xw.T @ yv)
        constant = float(yv @ (wt * yv))
        # P == 2 (sqrt(wt) X)'(sqrt(wt) X): same factor form as plain
        # least squares, with the observation weights inside the factor.
        self.objective = Objective(P=P, q=q, constant=constant,
                                   Pf=np.sqrt(wt)[:, None] * Xv,
                                   Pdiag=np.zeros(Xv.shape[1]))

    def solve(self) -> bool:
        return super().solve()


class LAD(Optimization):
    """Least absolute deviation tracking (reference
    ``optimization.py:263-352``).

    Two lowerings:

    * ``prox_form=True`` (default, device path): variables ``[w, s]``
      with equality rows ``s = X w`` and the objective
      ``sum_t |s_t - y_t|`` applied by the solver's NATIVE L1 prox —
      N+T variables, no nonnegative residual splitting. Measured at
      the reference's production scale (N=500, T=252,
      ``scripts/lad_scale_experiment.py``, f64): solves to eps 1e-5
      within +2.4e-4 of the f64 IPM oracle in 4,200 Halpern-anchored
      iterations, where the epigraph through the same ADMM stalls at
      a +13% gap. The eps target is dtype-aware (solver_params):
      f32 — the device and batch default — targets 1e-4 (1e-5 sits
      below the f32 residual floor; measured equal objective, 25x
      fewer iterations), f64 keeps 1e-5.
    * ``prox_form=False``: the reference's epigraph LP — variables
      [w, e+, e-], ``X w + e+ - e- = y``, cost ``sum(e+ + e-)``. This
      remains what ``canonical_parts`` emits (it is the only form the
      external backends — IPM, C++, scipy, qpsolvers — can consume).
    """

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.params["use_level"] = self.params.get("use_level", True)
        self.params["use_log"] = self.params.get("use_log", True)
        # An LP in epigraph form run through first-order ADMM reaches
        # LP-grade accuracy via the polish but rarely meets a tight QP
        # eps in-loop, so MAX_ITER-with-good-polish is the expected
        # terminal state: accept it by default (the reference defines
        # allow_suboptimal but never consults it — optimization.py:47;
        # here it gates exactly this acceptance). Pass
        # allow_suboptimal=False for strict residual-based success;
        # only a value the caller never supplied is upgraded.
        # OptimizationParameter materializes the key iff the caller set
        # it, so key presence IS the explicitness record.
        if "allow_suboptimal" not in self.params:
            self.params["allow_suboptimal"] = True
        if "prox_form" not in self.params:
            self.params["prox_form"] = True

    def set_objective(self, optimization_data: OptimizationData) -> None:
        X = optimization_data["return_series"]
        y = optimization_data["bm_series"]
        if self.params.get("use_level"):
            X = (1 + X).cumprod()
            y = (1 + y).cumprod()
            if self.params.get("use_log"):
                X = np.log(X)
                y = np.log(y)
        self.objective = Objective(X=X, y=y)

    # solve() is inherited: the base solve_jax already runs
    # model_canonical (this class's lowering), applies the
    # allow_suboptimal MAX_ITER acceptance (defaulted True above), and
    # Nones the weights on failure — one copy of the acceptance logic.

    def _wants_prox(self) -> bool:
        """Prox lowering applies when requested AND the consumer can
        run it: the default device solver, no leverage lifting (lowered
        on the epigraph parts only), no external backend (they cannot
        consume the native L1 term)."""
        name = self.params.get("solver_name", "jax_admm")
        return bool(
            self.params.get("prox_form")
            and name in (None, "", "jax_admm", "default")
            and "leverage" not in self.constraints.l1)

    # LP-appropriate solver defaults for the prox lowering, applied in
    # solver_params() only where the caller did not say otherwise.
    # First-order ADMM on a pure LP needs a FIXED, larger step size:
    # the residual-balancing adaptive rho drives a wander that never
    # converges (measured on the production shape: +13% objective gap
    # and worsening with more iterations under adaptive rho, vs solved
    # with rho fixed — scripts/lad_scale_experiment.py). Round 5 adds
    # restarted Halpern anchoring (qp/admm.py SolverParams.halpern):
    # measured at the production shape (N=500, T=252, f64,
    # scripts/lad_accel_sweep.py + lad_scale_experiment.py), the
    # round-4 fixed-rho config took 16,125 iterations to a +4.3e-4
    # objective gap vs the f64 IPM oracle; halpern + alpha 1.8 +
    # rho0 60 + a 200-iteration restart window solves in 4,200
    # iterations at +2.4e-4 — 3.8x fewer at better quality.
    # These were measured on the prox form ONLY, so they live in an
    # overlay consulted iff the prox form is the active lowering —
    # never written into self.params, so an epigraph fallback (leverage
    # constraint / external backend) keeps its pre-round-4 behavior
    # regardless of whether params are derived before or after
    # canonical_parts.
    _LP_PROX_DEFAULTS = {"adaptive_rho": False, "rho0": 60.0,
                         "halpern": True, "alpha": 1.8,
                         "check_interval": 200, "rho_l1_scale": 10.0,
                         "max_iter": 40000, "eps_abs": 1e-5,
                         "eps_rel": 1e-5}

    def solver_params(self, solve_dtype=None) -> SolverParams:
        if not self._wants_prox():
            return self.params.to_solver_params()
        fields = {k: v for k, v in self._LP_PROX_DEFAULTS.items()
                  if k not in self.params}
        # The overlay eps is dtype-aware: 1e-5 sits below the f32
        # residual floor, so an f32 solve burns max_iter stalled there
        # (measured on the MSCI LAD: 40,000 iterations at eps 1e-5 vs
        # 1,600 at 1e-4 with the objective within +7e-4 of the f64
        # reference — the polish lands the active set either way).
        # The SOLVE dtype decides: the batch engine casts problems to
        # its own dtype argument and passes it here; the serial path
        # solves in the declared params dtype.
        # An explicit eps on EITHER key is a complete statement of the
        # caller's accuracy intent — the relaxation then applies to
        # neither (loosening the other key 10x behind an explicit
        # tightening would undermine the request: the stop test is
        # eps_abs + eps_rel * denom, so the looser key dominates).
        dt = solve_dtype if solve_dtype is not None else self.params.get("dtype")
        if ((dt is None or np.dtype(dt) != np.float64)
                and "eps_abs" not in self.params
                and "eps_rel" not in self.params):
            fields["eps_abs"] = fields["eps_rel"] = 1e-4
        fields.update({k: self.params[k] for k in _SOLVER_KEYS
                       if k in self.params})
        return SolverParams(**fields)

    def canonical_parts(self) -> dict:
        return self._prox_parts() if self._wants_prox() else self._epigraph_parts()

    def _prox_parts(self) -> dict:
        """Native residual-prox lowering: variables [w, s], rows
        [constraint rows on w; s - X w = 0], and the objective
        sum_t |s_t - y_t| emitted as (l1_weight, l1_center) for the
        solver's native prox. P = 0 (pure LP); s is unboxed. Consumed
        by both the serial path (model_canonical -> _l1_pair) and the
        batched engine (batch.build_problems stacks the l1 arrays)."""
        X = to_numpy(self.objective["X"])
        y = to_numpy(self.objective["y"]).reshape(-1)
        N, T = X.shape[1], X.shape[0]
        dim = N + T

        Cw, lw, uw = self.constraints.interval_rows()
        resid = np.concatenate([X, -np.eye(T)], axis=1)
        C = np.concatenate([np.pad(Cw, [(0, 0), (0, T)]), resid], axis=0)
        l = np.concatenate([lw, np.zeros(T)])
        u = np.concatenate([uw, np.zeros(T)])
        lb_w, ub_w = self.constraints.bounds()
        lb = np.concatenate([lb_w, np.full(T, -np.inf)])
        ub = np.concatenate([ub_w, np.full(T, np.inf)])

        parts = lift._as_parts(np.zeros((dim, dim)), np.zeros(dim),
                               C, l, u, lb, ub)
        parts["constant"] = 0.0
        parts["l1_weight"] = np.concatenate([np.zeros(N), np.ones(T)])
        parts["l1_center"] = np.concatenate([np.zeros(N), y])
        return parts

    def _epigraph_parts(self) -> dict:
        X = to_numpy(self.objective["X"])
        y = to_numpy(self.objective["y"]).reshape(-1)
        N = X.shape[1]
        T = X.shape[0]
        dim = N + 2 * T

        # Constraint rows on w, widened with zero columns for the
        # residual-splitting aux block, then the T equality rows
        # X w + e+ - e- = y.
        Cw, lw, uw = self.constraints.interval_rows()
        resid = np.concatenate([X, np.eye(T), -np.eye(T)], axis=1)
        C = np.concatenate(
            [np.pad(Cw, [(0, 0), (0, 2 * T)]), resid], axis=0)
        l = np.concatenate([lw, y])
        u = np.concatenate([uw, y])

        lb_w, ub_w = self.constraints.bounds()
        lb = np.concatenate([lb_w, np.zeros(2 * T)])
        ub = np.concatenate([ub_w, np.full(2 * T, np.inf)])

        q = np.concatenate([np.zeros(N), np.ones(2 * T)])
        P = np.zeros((dim, dim))
        parts = lift._as_parts(P, q, C, l, u, lb, ub)

        # Corrected leverage branch (reference optimization.py:327-341 is buggy)
        if "leverage" in self.constraints.l1:
            parts = lift.lift_leverage_constraint(
                parts, self.constraints.l1["leverage"]["rhs"]
            )

        parts["constant"] = 0.0
        return parts

    def model_canonical(self) -> CanonicalQP:
        parts = self.canonical_parts()
        self.model = CanonicalQP.build(
            parts["P"], parts["q"], C=parts["C"], l=parts["l"], u=parts["u"],
            lb=parts["lb"], ub=parts["ub"],
            n_max=self.params.get("n_max"), m_max=self.params.get("m_max"),
            dtype=self.params.get("dtype"),
        )
        if "l1_weight" in parts:
            # l1 arrays must match the (possibly padded) model
            # dimension; padded variables carry zero weight and center.
            n_pad = self.model.n
            dt = np.asarray(self.model.q).dtype
            l1w = np.zeros(n_pad, dt)
            l1w[:len(parts["l1_weight"])] = parts["l1_weight"]
            l1c = np.zeros(n_pad, dt)
            l1c[:len(parts["l1_center"])] = parts["l1_center"]
            self._l1_pair = (l1w, l1c)
        else:
            self._l1_pair = None
        return self.model


class PercentilePortfolios(Optimization):
    """Score-ranked bucket portfolios, no QP (reference
    ``optimization.py:356-417``): long top bucket, short bottom bucket,
    equal weight within bucket."""

    def __init__(self,
                 field: Optional[str] = None,
                 estimator: Optional[MeanEstimator] = None,
                 n_percentiles: int = 5,
                 **kwargs):
        super().__init__(**kwargs)
        self.estimator = estimator
        self.params.update(solver_name="percentile",
                           n_percentiles=n_percentiles, field=field)

    def _score_series(self, optimization_data: OptimizationData) -> pd.Series:
        """Resolve the ranking signal: an estimator over returns, a
        named column of the scores frame, a weighted column blend, or
        the plain cross-column mean — in that precedence order."""
        field = self.params.get("field")
        if self.estimator is not None:
            if field is not None:
                raise ValueError(
                    "'field' and 'estimator' are mutually exclusive")
            return self.estimator.estimate(
                X=optimization_data["return_series"])
        frame = optimization_data["scores"]
        if isinstance(frame, pd.Series):
            # A plain per-asset score vector needs no cross-column
            # reduction; 'field' / 'score_weights' address columns of
            # a frame, so silently honoring a Series instead would
            # drop the caller's selection or blend.
            if field is not None or self.params.get("score_weights"):
                raise ValueError(
                    "'field'/'score_weights' were given but the scores "
                    "entry is a Series (one score per asset), not a "
                    "frame")
            return frame
        if field is not None:
            return frame[field]
        blend = self.params.get("score_weights")
        if blend is not None:
            cols = frame[list(blend.keys())]
            return (cols * pd.Series(blend)).sum(axis=1)
        return frame.mean(axis=1).squeeze()

    def set_objective(self, optimization_data: OptimizationData) -> None:
        scores = self._score_series(optimization_data)
        # Zero scores would create duplicate percentile thresholds; add
        # deterministic sub-numerical jitter (the reference draws from
        # the global np.random state at optimization.py:393 — a seeded
        # generator keeps runs reproducible).
        zeros = scores == 0
        if zeros.any():
            rng = np.random.default_rng(int(self.params.get("seed", 0)))
            scores = scores.copy()
            scores[zeros] = rng.normal(0.0, 1e-10, int(zeros.sum()))
        self.objective = Objective(scores=-scores)

    def solve(self) -> bool:
        scores = self.objective["scores"]
        N = self.params["n_percentiles"]
        th = np.percentile(scores, np.linspace(0, 100, N + 1))

        # Vectorized bucket assignment: bucket b covers
        # th[b-1] < s <= th[b], with the lowest bucket closed below.
        vals = scores.to_numpy()
        buckets = np.minimum(
            np.searchsorted(th[1:], vals, side="left") + 1, N)

        w_dict = {}
        for b in range(1, N + 1):
            members = scores.index[buckets == b]
            w_dict[b] = pd.Series(1.0 / max(len(members), 1), index=members)

        # Negated scores: bucket 1 holds the highest raw scores (long),
        # bucket N the lowest (short); everything between stays flat.
        weights = pd.Series(0.0, index=scores.index)
        weights[w_dict[1].index] = 1.0 / max(len(w_dict[1]), 1)
        weights[w_dict[N].index] = -1.0 / max(len(w_dict[N]), 1)
        # Parity with the reference's results contract: the dict always
        # carries "status" (reference ``optimization.py:86-87``) so
        # Backtest.run's prev-weights bookkeeping fires, and an
        # "objective" value (the long-short raw-score spread between the
        # top and bottom buckets) so ``append_custom``'s default
        # "objective" key records something meaningful (reference
        # ``backtest.py:245-270``). ``scores`` here are negated, so the
        # raw-score spread is mean(-s | bucket 1) - mean(-s | bucket N).
        raw = -vals
        top, bot = raw[buckets == 1], raw[buckets == N]
        # Degenerate score distributions can leave a bucket empty (the
        # weights code above guards the same case); spread is 0 then,
        # not NaN.
        spread = (float(top.mean() - bot.mean())
                  if top.size and bot.size else 0.0)
        self.results = {"weights": weights.to_dict(), "w_dict": w_dict,
                        "status": True, "objective": spread}
        return True
