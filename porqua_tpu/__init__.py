"""PorQua-TPU: a TPU-native portfolio optimization and backtesting framework.

A ground-up re-design of the capability surface of PorQua
(github.com/amolrpatil21/PorQua — portfolio optimization and backtesting
library) for TPU hardware via JAX/XLA:

* The reference dispatches every rebalance date to an external C/C++ QP
  solver through ``qpsolvers`` (reference ``src/qp_problems.py:211``).
  Here the solver is a *batched* first-order ADMM solver written in JAX
  (``porqua_tpu.qp``): a whole backtest of quadratic programs is solved
  in one XLA program on the MXU.
* The reference's rolling-rebalance loop is a serial Python ``for``
  (reference ``src/backtest.py:203``). Here problem *building* stays
  host-side (pandas-friendly), and the solve/accounting path is
  ``vmap``/``lax.scan`` over rebalance dates on device.
* Multi-chip scaling shards the (dates x benchmarks) batch over a
  ``jax.sharding.Mesh`` (``porqua_tpu.parallel``).

Public API mirrors the reference's capability surface: constraints DSL,
optimization objectives, covariance/mean estimators, selection, item
builders, backtest engine and portfolio accounting.
"""

__version__ = "0.3.0"  # keep in sync with pyproject.toml

from porqua_tpu.constraints import Constraints
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.diff import solve_qp_diff, solve_qp_l1_diff
from porqua_tpu.qp.solve import solve_qp, solve_qp_batch, QPSolution, SolverParams
from porqua_tpu.estimators.covariance import Covariance, CovarianceSpecification
from porqua_tpu.estimators.mean import MeanEstimator
from porqua_tpu.optimization_data import OptimizationData
from porqua_tpu.optimization import (
    Optimization,
    OptimizationParameter,
    Objective,
    EmptyOptimization,
    MeanVariance,
    QEQW,
    LeastSquares,
    WeightedLeastSquares,
    LAD,
    PercentilePortfolios,
)
from porqua_tpu.selection import Selection
from porqua_tpu.builders import SelectionItemBuilder, OptimizationItemBuilder
from porqua_tpu.portfolio import Portfolio, Strategy, floating_weights
from porqua_tpu.backtest import Backtest, BacktestData, BacktestService
from porqua_tpu.batch import (
    FIXED_UNIVERSE,
    as_requests,
    build_problems,
    run_batch,
    solve_scan_l1,
    solve_scan_l1_grid,
    solve_scan_turnover,
)
from porqua_tpu.compare import compare_solvers, available_backends
from porqua_tpu.obs import Observability
from porqua_tpu.serve import SolveService

__all__ = [
    "Constraints",
    "CanonicalQP",
    "solve_qp",
    "solve_qp_batch",
    "solve_qp_diff",
    "solve_qp_l1_diff",
    "QPSolution",
    "SolverParams",
    "Covariance",
    "CovarianceSpecification",
    "MeanEstimator",
    "OptimizationData",
    "Optimization",
    "OptimizationParameter",
    "Objective",
    "EmptyOptimization",
    "MeanVariance",
    "QEQW",
    "LeastSquares",
    "WeightedLeastSquares",
    "LAD",
    "PercentilePortfolios",
    "Selection",
    "SelectionItemBuilder",
    "OptimizationItemBuilder",
    "Portfolio",
    "Strategy",
    "floating_weights",
    "Backtest",
    "BacktestData",
    "BacktestService",
    "FIXED_UNIVERSE",
    "as_requests",
    "build_problems",
    "run_batch",
    "solve_scan_l1",
    "solve_scan_l1_grid",
    "solve_scan_turnover",
    "compare_solvers",
    "available_backends",
    "Observability",
    "SolveService",
]
