"""Dense primal-dual interior-point QP solver (host-side, float64).

An *algorithmically independent* high-accuracy reference for the
cross-solver harness (:mod:`porqua_tpu.compare`). The device solver,
the Pallas kernel, and the native C++ core all implement the same
OSQP-style ADMM splitting, so agreement among them could in principle
share a bug; this module solves the same QPs by a completely different
method — a Mehrotra predictor-corrector interior point, the family the
reference's default backend (cvxopt, ``src/optimization.py:45``)
belongs to — giving the parity tables a genuinely independent column.

Pure numpy, deliberately: this is a correctness oracle, not a device
path. Problems arrive in the canonical interval form and are expanded
to the standard IPM shape

    min 1/2 x'Px + q'x   s.t.  A x = b,  G x <= h

with box bounds and finite interval sides folded into G.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

_EQ_TOL = 1e-9


class IPMSolution(NamedTuple):
    x: np.ndarray
    y: np.ndarray            # equality multipliers
    z: np.ndarray            # inequality multipliers (>= 0)
    found: bool
    iters: int
    mu: float                # final complementarity
    prim_res: float
    dual_res: float


def _standard_form(parts: dict):
    """Interval rows + box -> (A, b, G, h); infinite sides dropped."""
    C, l, u = parts["C"], parts["l"], parts["u"]
    lb, ub = parts["lb"], parts["ub"]
    n = len(parts["q"])

    eq = (u - l) <= _EQ_TOL if C.size else np.zeros(0, bool)
    A = C[eq] if C.size and eq.any() else np.zeros((0, n))
    b = u[eq] if C.size and eq.any() else np.zeros(0)

    G_blocks, h_blocks = [], []
    if C.size and (~eq).any():
        Ci, li, ui = C[~eq], l[~eq], u[~eq]
        hi_ok, lo_ok = np.isfinite(ui), np.isfinite(li)
        if hi_ok.any():
            G_blocks.append(Ci[hi_ok])
            h_blocks.append(ui[hi_ok])
        if lo_ok.any():
            G_blocks.append(-Ci[lo_ok])
            h_blocks.append(-li[lo_ok])
    eye = np.eye(n)
    ub_ok, lb_ok = np.isfinite(ub), np.isfinite(lb)
    if ub_ok.any():
        G_blocks.append(eye[ub_ok])
        h_blocks.append(ub[ub_ok])
    if lb_ok.any():
        G_blocks.append(-eye[lb_ok])
        h_blocks.append(-lb[lb_ok])

    G = np.concatenate(G_blocks) if G_blocks else np.zeros((0, n))
    h = np.concatenate(h_blocks) if h_blocks else np.zeros(0)
    return A, b, G, h


def solve_ipm(parts: dict,
              tol: float = 1e-10,
              max_iter: int = 60) -> IPMSolution:
    """Mehrotra predictor-corrector on the QP KKT system.

    Each iteration eliminates the slack/multiplier pair into the
    condensed system ``[P + G'(z/s)G, A'; A, 0]`` and takes an affine
    (predictor) step to pick the centering weight, then a corrected
    step. Converges quadratically near the solution; 20-40 iterations
    reach mu ~ 1e-12 on the portfolio problems in the suite.
    """
    P = np.asarray(parts["P"], np.float64)
    q = np.asarray(parts["q"], np.float64)
    A, b, G, h = _standard_form(parts)
    n, me, mi = len(q), len(b), len(h)

    # Strictly feasible-ish start: centered x, unit slacks/multipliers.
    x = np.zeros(n)
    if np.isfinite(parts["lb"]).all() and np.isfinite(parts["ub"]).all():
        x = 0.5 * (parts["lb"] + parts["ub"])
    y = np.zeros(me)
    s = np.maximum(h - G @ x, 1.0) if mi else np.zeros(0)
    z = np.ones(mi)

    def residuals(x, y, s, z):
        r_d = P @ x + q + (A.T @ y if me else 0.0) + (G.T @ z if mi else 0.0)
        r_e = (A @ x - b) if me else np.zeros(0)
        r_i = (G @ x + s - h) if mi else np.zeros(0)
        return r_d, r_e, r_i

    def kkt_solve(w, r1, r2):
        """Solve [P + G' diag(w) G, A'; A, 0] [dx, dy] = [r1, r2]."""
        H = P + (G.T * w) @ G if mi else P.copy()
        H[np.diag_indices_from(H)] += 1e-12  # keep factorizable at mu->0
        if me:
            K = np.block([[H, A.T], [A, np.zeros((me, me))]])
            sol = np.linalg.solve(K, np.concatenate([r1, r2]))
            return sol[:n], sol[n:]
        return np.linalg.solve(H, r1), np.zeros(0)

    def max_step(v, dv):
        """Largest alpha in (0, 1] keeping v + alpha dv > 0."""
        shrink = dv < 0
        if not shrink.any():
            return 1.0
        return min(1.0, float(np.min(-v[shrink] / dv[shrink])))

    found = False
    it = 0
    for it in range(1, max_iter + 1):
        r_d, r_e, r_i = residuals(x, y, s, z)
        mu = float(s @ z / mi) if mi else 0.0
        prim = max(np.abs(r_e).max() if me else 0.0,
                   np.abs(r_i).max() if mi else 0.0)
        dual = np.abs(r_d).max() if n else 0.0
        if prim < tol and dual < tol and mu < tol:
            found = True
            break

        # Condensed Newton step: substituting ds = -r_i - G dx and
        # dz = (z/s) G dx + (z .* r_i - rc)/s into the dual equation
        # gives  [P + G'(z/s)G] dx + A' dy = -r_d + G'[(rc - z .* r_i)/s]
        # where rc is the complementarity residual of the step (s .* z
        # for the predictor; Mehrotra-corrected for the final step).
        def direction(rc):
            if mi:
                r1 = -r_d + G.T @ ((rc - z * r_i) / s)
            else:
                r1 = -r_d
            dx, dy = kkt_solve(z / s if mi else None, r1, -r_e)
            if mi:
                ds = -r_i - G @ dx
                dz = -(rc + z * ds) / s
            else:
                ds = dz = np.zeros(0)
            return dx, dy, ds, dz

        dx_a, dy_a, ds_a, dz_a = direction(s * z)
        if mi:
            # One step length for ALL variables: with P != 0 the dual
            # residual couples x and z, so the LP-style split
            # primal/dual steps destroy the Newton decrement and the
            # iteration oscillates.
            a_aff = min(max_step(s, ds_a), max_step(z, dz_a))
            mu_aff = float((s + a_aff * ds_a) @ (z + a_aff * dz_a) / mi)
            sigma = (mu_aff / mu) ** 3 if mu > 0 else 0.0
            rc = s * z + ds_a * dz_a - sigma * mu
            dx, dy, ds, dz = direction(rc)
            alpha = 0.995 * min(max_step(s, ds), max_step(z, dz))
        else:
            dx, dy, ds, dz = dx_a, dy_a, ds_a, dz_a
            alpha = 1.0

        x = x + alpha * dx
        y = y + alpha * dy
        if mi:
            s = s + alpha * ds
            z = z + alpha * dz

    r_d, r_e, r_i = residuals(x, y, s, z)
    return IPMSolution(
        x=x, y=y, z=z, found=found, iters=it,
        mu=float(s @ z / mi) if mi else 0.0,
        prim_res=float(max(np.abs(r_e).max() if me else 0.0,
                           np.abs(np.maximum(G @ x - h, 0.0)).max()
                           if mi else 0.0)),
        dual_res=float(np.abs(r_d).max() if n else 0.0),
    )


def dual_for_canonical(parts: dict, sol: IPMSolution):
    """Map the (y, z) multipliers back onto the canonical interval rows
    and box, so the harness can compute a dual residual uniformly.

    Returns ``(y_rows, mu_box)`` matching the layout of ``parts['C']``
    rows and the n box constraints.
    """
    C, l, u = parts["C"], parts["l"], parts["u"]
    lb, ub = parts["lb"], parts["ub"]
    n = len(parts["q"])
    m = C.shape[0] if C.size else 0

    y_rows = np.zeros(m)
    mu_box = np.zeros(n)
    eq = (u - l) <= _EQ_TOL if m else np.zeros(0, bool)
    y_rows[eq] = sol.y[: eq.sum()] if eq.any() else y_rows[eq]

    k = 0
    if m and (~eq).any():
        idx = np.flatnonzero(~eq)
        ui, li = u[~eq], l[~eq]
        hi_ok, lo_ok = np.isfinite(ui), np.isfinite(li)
        nh = int(hi_ok.sum())
        y_rows[idx[hi_ok]] += sol.z[k:k + nh]
        k += nh
        nl = int(lo_ok.sum())
        y_rows[idx[lo_ok]] -= sol.z[k:k + nl]
        k += nl
    ub_ok, lb_ok = np.isfinite(ub), np.isfinite(lb)
    nu = int(ub_ok.sum())
    mu_box[ub_ok] += sol.z[k:k + nu]
    k += nu
    nl = int(lb_ok.sum())
    mu_box[lb_ok] -= sol.z[k:k + nl]
    return y_rows, mu_box
