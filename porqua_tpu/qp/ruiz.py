"""Ruiz equilibration for the canonical QP, fully jittable.

First-order methods are sensitive to problem scaling; the interior-point
solvers the reference dispatches to (cvxopt et al. via
``qp_problems.py:211``) are much less so. To match their robustness on
ill-conditioned covariance/Gram matrices (near-singular X'X windows) we
apply modified Ruiz equilibration (as in OSQP) before the ADMM loop:
diagonal scalings D (variables), E (general rows) and a cost scalar c
that drive the row/column infinity-norms of the KKT matrix

    [[c * D P D,  (E C D)'],
     [ E C D,     0      ]]

toward 1. The implicit box block needs no E of its own: with
``x = D xhat`` the scaled box is simply ``lb / D <= xhat <= ub / D``
(identity rows are perfectly equilibrated by construction).

All iteration counts are static, so this lowers to a handful of fused
XLA ops and is batchable with ``vmap``.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from porqua_tpu.qp.canonical import CanonicalQP


class Scaling(NamedTuple):
    """Diagonal scalings mapping the scaled problem back to the original.

    x = D xhat;  y = (1/c) E yhat;  mu = (1/c) D^-1 muhat.
    """

    D: jax.Array  # (n,)
    E: jax.Array  # (m,)
    c: jax.Array  # ()


def _safe_inv_sqrt(norms, guard: float = 1e-8):
    norms = jnp.where(norms < guard, 1.0, norms)
    return 1.0 / jnp.sqrt(norms)


def equilibrate(qp: CanonicalQP, iters: int = 10) -> Tuple[CanonicalQP, Scaling]:
    """Iteratively scale P, q, C and bounds; returns (scaled_qp, scaling)."""
    dtype = qp.P.dtype
    n, m = qp.n, qp.m

    def body(carry, _):
        P, q, C, D, E, c = carry
        col_norm = jnp.maximum(
            jnp.max(jnp.abs(P), axis=0), jnp.max(jnp.abs(C), axis=0) if m else 0.0
        )
        delta_d = _safe_inv_sqrt(col_norm)
        row_norm = jnp.max(jnp.abs(C), axis=1) if m else jnp.zeros((0,), dtype)
        delta_e = _safe_inv_sqrt(row_norm)

        P = delta_d[:, None] * P * delta_d[None, :]
        q = delta_d * q
        C = delta_e[:, None] * C * delta_d[None, :]
        D = D * delta_d
        E = E * delta_e

        # Cost normalization (OSQP: mean column norm of P and ||q||_inf).
        gamma_denom = jnp.maximum(
            jnp.mean(jnp.max(jnp.abs(P), axis=0)), jnp.max(jnp.abs(q))
        )
        gamma = 1.0 / jnp.where(gamma_denom < 1e-8, 1.0, gamma_denom)
        P = gamma * P
        q = gamma * q
        c = c * gamma
        return (P, q, C, D, E, c), None

    init = (
        qp.P, qp.q, qp.C,
        jnp.ones(n, dtype), jnp.ones(m, dtype), jnp.asarray(1.0, dtype),
    )
    (P, q, C, D, E, c), _ = jax.lax.scan(body, init, None, length=iters)

    scaled = qp._replace(
        P=P,
        q=q,
        C=C,
        l=qp.l * E,
        u=qp.u * E,
        lb=qp.lb / D,
        ub=qp.ub / D,
        constant=qp.constant * c,
    )
    if qp.Pf is not None:
        # P = 2 Pf'Pf + diag(Pdiag) -> c D P D = 2 (sqrt(c) Pf D)' (...)
        # + diag(c D^2 Pdiag): the factor form survives diagonal scaling,
        # so the Woodbury solve path stays available on the scaled
        # problem.
        scaled = scaled._replace(Pf=jnp.sqrt(c) * qp.Pf * D[None, :])
        if qp.Pdiag is not None:
            scaled = scaled._replace(Pdiag=c * D * D * qp.Pdiag)
    return scaled, Scaling(D=D, E=E, c=c)
