"""Ruiz equilibration for the canonical QP, fully jittable.

First-order methods are sensitive to problem scaling; the interior-point
solvers the reference dispatches to (cvxopt et al. via
``qp_problems.py:211``) are much less so. To match their robustness on
ill-conditioned covariance/Gram matrices (near-singular X'X windows) we
apply modified Ruiz equilibration (as in OSQP) before the ADMM loop:
diagonal scalings D (variables), E (general rows) and a cost scalar c
that drive the row/column infinity-norms of the KKT matrix

    [[c * D P D,  (E C D)'],
     [ E C D,     0      ]]

toward 1. The implicit box block needs no E of its own: with
``x = D xhat`` the scaled box is simply ``lb / D <= xhat <= ub / D``
(identity rows are perfectly equilibrated by construction).

All iteration counts are static, so this lowers to a handful of fused
XLA ops and is batchable with ``vmap``.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from porqua_tpu.qp.canonical import CanonicalQP


class Scaling(NamedTuple):
    """Diagonal scalings mapping the scaled problem back to the original.

    x = D xhat;  y = (1/c) E yhat;  mu = (1/c) D^-1 muhat.
    """

    D: jax.Array  # (n,)
    E: jax.Array  # (m,)
    c: jax.Array  # ()


def _safe_inv_sqrt(norms, guard: float = 1e-8):
    norms = jnp.where(norms < guard, 1.0, norms)
    return 1.0 / jnp.sqrt(norms)


def equilibrate(qp: CanonicalQP, iters: int = 10) -> Tuple[CanonicalQP, Scaling]:
    """Iteratively scale P, q, C and bounds; returns (scaled_qp, scaling)."""
    dtype = qp.P.dtype
    n, m = qp.n, qp.m

    def body(carry, _):
        P, q, C, D, E, c = carry
        col_norm = jnp.maximum(
            jnp.max(jnp.abs(P), axis=0), jnp.max(jnp.abs(C), axis=0) if m else 0.0
        )
        delta_d = _safe_inv_sqrt(col_norm)
        row_norm = jnp.max(jnp.abs(C), axis=1) if m else jnp.zeros((0,), dtype)
        delta_e = _safe_inv_sqrt(row_norm)

        P = delta_d[:, None] * P * delta_d[None, :]
        q = delta_d * q
        C = delta_e[:, None] * C * delta_d[None, :]
        D = D * delta_d
        E = E * delta_e

        # Cost normalization (OSQP: mean column norm of P and ||q||_inf).
        gamma_denom = jnp.maximum(
            jnp.mean(jnp.max(jnp.abs(P), axis=0)), jnp.max(jnp.abs(q))
        )
        gamma = 1.0 / jnp.where(gamma_denom < 1e-8, 1.0, gamma_denom)
        P = gamma * P
        q = gamma * q
        c = c * gamma
        return (P, q, C, D, E, c), None

    init = (
        qp.P, qp.q, qp.C,
        jnp.ones(n, dtype), jnp.ones(m, dtype), jnp.asarray(1.0, dtype),
    )
    (P, q, C, D, E, c), _ = jax.lax.scan(body, init, None, length=iters)
    return _apply_scaling(qp, P, q, C, D, E, c), Scaling(D=D, E=E, c=c)


def _apply_scaling(qp: CanonicalQP, P, q, C, D, E, c) -> CanonicalQP:
    """Assemble the scaled problem from (already-scaled) P/q/C and the
    diagonal scalings — ONE copy of the bounds/constant/factor scaling
    conventions shared by every equilibration mode (a drifted second
    copy would silently give the modes different unscale semantics).

    Conventions: l,u scale by E; lb,ub by 1/D; constant by c; the
    objective factor as P = 2 Pf'Pf + diag(Pdiag) -> c D P D =
    2 (sqrt(c) Pf D)'(sqrt(c) Pf D) + diag(c D^2 Pdiag), so the
    Woodbury solve path stays available on the scaled problem.
    """
    scaled = qp._replace(
        P=P,
        q=q,
        C=C,
        l=qp.l * E,
        u=qp.u * E,
        lb=qp.lb / D,
        ub=qp.ub / D,
        constant=qp.constant * c,
    )
    if qp.Pf is not None:
        scaled = scaled._replace(Pf=jnp.sqrt(c) * qp.Pf * D[None, :])
        if qp.Pdiag is not None:
            scaled = scaled._replace(Pdiag=c * D * D * qp.Pdiag)
    return scaled


def equilibrate_factored(qp: CanonicalQP) -> Tuple[CanonicalQP, Scaling]:
    """Jacobi equilibration computed from the objective FACTOR alone.

    Each modified-Ruiz sweep above reads the dense n x n ``P`` three
    times and writes it once — for the north-star batch that is the
    single largest HBM consumer outside the ADMM iterations
    (BASELINE.md roofline notes). When the problem carries its factor
    (``P = 2 Pf'Pf + diag(Pdiag)``), the diagonal is available from
    column norms of ``Pf`` — a (T x n) read, ~T/n of the dense bytes —
    and Jacobi scaling ``D_j = P_jj^(-1/2)`` (unit scaled diagonal) is
    the SPD-natural diagonal equilibration (van der Sluis: within a
    factor of the optimal diagonal conditioning). The scaled dense P is
    then materialized in ONE fused read+write, so total P traffic drops
    from ~4 passes/sweep to 2 passes flat.

    Scope: requires ``qp.Pf``; callers opt in via
    ``SolverParams.scaling_mode="factored"``. Iteration-count parity
    with 2-sweep Ruiz on the tracking workload is pinned by
    ``tests/test_woodbury.py``; quality on real data by the MSCI sweep.
    """
    if qp.Pf is None:
        raise ValueError("equilibrate_factored requires the factored "
                         "objective (qp.Pf)")
    dtype = qp.P.dtype
    n, m = qp.n, qp.m

    diagP = 2.0 * jnp.sum(qp.Pf * qp.Pf, axis=-2)
    if qp.Pdiag is not None:
        diagP = diagP + qp.Pdiag
    # Masked/padded columns carry an EXACTLY-zero diagonal (zero Pf
    # columns, zero Pdiag), so > 0 is the precise live/padded cut —
    # no magnitude floor at all. This keeps a uniformly tiny-scaled
    # objective equilibrating (every positive P_jj scales, however
    # small), without a relative cut's failure mode of misclassifying
    # live-but-small columns as padding on wide-dynamic-range
    # problems. ``tiny`` only guards the division in the branch not
    # taken.
    tiny = jnp.asarray(jnp.finfo(diagP.dtype).tiny, diagP.dtype)
    D = jnp.where(diagP > 0,
                  1.0 / jnp.sqrt(jnp.maximum(diagP, tiny)), 1.0)

    # Constraint rows: one pass over C (m x n), Ruiz-style row norms of
    # the column-scaled matrix. Same exact-zero cut: only genuinely
    # empty (padded) rows stay unscaled.
    if m:
        row_norm = jnp.max(jnp.abs(qp.C) * D[None, :], axis=1)
        E = jnp.where(row_norm > 0,
                      1.0 / jnp.maximum(row_norm, tiny), 1.0)
    else:
        E = jnp.ones((0,), dtype)

    # Cost normalization: the scaled P has unit diagonal (mean col
    # norm ~ 1 for the Gram matrices this path serves), so only |D q|
    # can push the cost scale around.
    gamma_denom = jnp.maximum(1.0, jnp.max(jnp.abs(D * qp.q)))
    c = jnp.asarray(1.0 / gamma_denom, dtype)
    D = D.astype(dtype)
    E = E.astype(dtype)

    scaled = _apply_scaling(
        qp,
        c * D[:, None] * qp.P * D[None, :],
        c * D * qp.q,
        E[:, None] * qp.C * D[None, :],
        D, E, c,
    )
    return scaled, Scaling(D=D, E=E, c=c)
