"""Seeded subspace embedding for the tracking-QP Gram build.

For a universe of N assets and a (T, N) return window X, the dense
objective assembly ``P = 2 X'X`` costs O(T N^2) — at N = 5,000 the
Gram build dominates the whole rebalance step. A count-sketch
(Clarkson-Woodruff sparse embedding) ``S`` of the *row* (date) space —
each date hashed to one of ``sketch_dim`` buckets with a random sign —
compresses the window to ``Xs = S X`` of shape (sketch_dim, N) in one
O(T N) pass (a signed segment-sum, MXU-free), after which every
downstream consumer is cheaper by T/sketch_dim: the Gram build, the
``Pf`` factor rows the Woodbury dual-space linsolve carries, and the
PDHG backend's per-iteration ``apply_P``.

Because S is applied to the stacked ``[X | y]`` window, the sketched
problem is the least-squares objective ``||S(Xw - y)||^2`` — a
subspace embedding of the true residual, so the minimizer is near the
true one with the usual (1 +- eps) Gram guarantee. The error is not
assumed, it is *measured*: :func:`gram_rel_err` probes
``||X'Xv - Xs'Xs v|| / ||X'Xv||`` with seeded random vectors and the
bound rides the result (``SketchInfo.gram_rel_err``), so promotion
gates can reject a sketch that is too lossy for a given universe.

Disabled (``sketch_dim == 0``, the default — or a sketch_dim that
would not compress) the pipeline is a bit-exact passthrough: the same
``build_tracking_qp`` call on the untouched window, pinned by the
bench ``config_sketch`` A/B and ``bench_gate``'s
``sketch_off_te_drift <= 1e-6`` rule.

Everything is jittable with ``SketchParams`` static (it is frozen and
hashable, same convention as ``SolverParams``); the sketch itself is
seeded and deterministic — same (seed, shapes) => same embedding, so
reruns and multi-host replays reconcile.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from porqua_tpu.qp.canonical import HP, sketch_rows
from porqua_tpu.tracking import TrackingResult, _sketch_window, build_tracking_qp

__all__ = [
    "SketchParams",
    "SketchInfo",
    "count_sketch",
    "gram_rel_err",
    "sketched_tracking_qp",
    "tracking_step_sketched",
]


@dataclasses.dataclass(frozen=True)
class SketchParams:
    """Static sketch configuration (hashable, jit-static — part of an
    executable's identity exactly like ``SolverParams``).

    sketch_dim: embedding rows. 0 disables the sketch entirely
        (bit-exact passthrough). A value >= the window length T also
        passes through — a "sketch" that does not compress must not
        perturb the problem.
    seed: the embedding's PRNG seed (bucket hashes + signs + the
        error-probe vectors all derive from it).
    probes: random probe vectors for the measured Gram error bound.
    """

    sketch_dim: int = 0
    seed: int = 0
    probes: int = 8


class SketchInfo(NamedTuple):
    """What the sketch did, surfaced on the solution path: ``sketch_dim``
    is the *effective* dim (0 when passthrough — disabled or
    non-compressing), ``gram_rel_err`` the measured probe bound (exact
    0 on the passthrough path)."""

    sketch_dim: jax.Array    # () int32, effective embedding rows
    rows_in: jax.Array       # () int32, window length T
    gram_rel_err: jax.Array  # () max_k ||(G - Gs) v_k|| / ||G v_k||


def count_sketch(M: jax.Array, sketch_dim: int, key: jax.Array) -> jax.Array:
    """Apply a Clarkson-Woodruff count-sketch to the leading (row) axis:
    ``(T, k) -> (sketch_dim, k)``. Alias of
    :func:`porqua_tpu.qp.canonical.sketch_rows` — the primitive moved
    to the canonical lowering layer when ``build_tracking_qp`` grew the
    in-program sketch-fed path, so the solve path and this certificate
    path share one embedding by construction."""
    return sketch_rows(M, sketch_dim, key)


def gram_rel_err(X: jax.Array, Xs: jax.Array, key: jax.Array,
                 probes: int) -> jax.Array:
    """Measured Gram-error bound: ``max_k ||X'(Xv_k) - Xs'(Xs v_k)|| /
    ||X'(Xv_k)||`` over seeded Gaussian probes — four tall-skinny
    matvecs per probe, never the O(N^2) Grams themselves, so the bound
    stays cheap at the universe sizes the sketch exists for."""
    n = X.shape[-1]
    V = jax.random.normal(key, (probes, n), X.dtype)

    def one(v):
        gv = jnp.dot(jnp.dot(X, v, precision=HP), X, precision=HP)
        gsv = jnp.dot(jnp.dot(Xs, v, precision=HP), Xs, precision=HP)
        return (jnp.linalg.norm(gv - gsv)
                / jnp.maximum(jnp.linalg.norm(gv), 1e-12))

    return jnp.max(jax.vmap(one)(V))


def _effective_dim(sketch: SketchParams, T: int) -> int:
    """The dim actually applied: 0 (passthrough) unless the sketch both
    is enabled and compresses."""
    d = sketch.sketch_dim
    return d if 0 < d < T else 0


def sketched_tracking_qp(X: jax.Array,
                         y: jax.Array,
                         sketch: SketchParams = SketchParams(),
                         ridge: float = 0.0,
                         lb: float = 0.0,
                         ub: float = 1.0):
    """Lower one (T, N) window to the tracking QP through the (optional)
    embedding; returns ``(CanonicalQP, SketchInfo)``.

    The sketch is applied to the stacked ``[X | y]`` window so the
    sketched problem is exactly ``min ||S(Xw - y)||^2`` over the same
    polytope — then handed to the *same*
    :func:`porqua_tpu.tracking.build_tracking_qp`, which is what makes
    the disabled path bit-exact: passthrough is literally the identical
    call on the untouched arrays (and ``jax.eval_shape``-visible: the
    sketched QP carries ``Pf`` with ``sketch_dim`` rows, a distinct
    serving bucket).
    """
    T = X.shape[0]
    d = _effective_dim(sketch, T)
    if d == 0:
        qp = build_tracking_qp(X, y, ridge=ridge, lb=lb, ub=ub)
        info = SketchInfo(
            sketch_dim=jnp.asarray(0, jnp.int32),
            rows_in=jnp.asarray(T, jnp.int32),
            gram_rel_err=jnp.asarray(0.0, X.dtype),
        )
        return qp, info

    Xs, ys, k_probe = _sketch_window(X, y, d, sketch.seed)
    qp = build_tracking_qp(Xs, ys, ridge=ridge, lb=lb, ub=ub)
    info = SketchInfo(
        sketch_dim=jnp.asarray(d, jnp.int32),
        rows_in=jnp.asarray(T, jnp.int32),
        gram_rel_err=gram_rel_err(X, Xs, k_probe, sketch.probes),
    )
    return qp, info


def tracking_step_sketched(Xs: jax.Array,
                           ys: jax.Array,
                           params=None,
                           sketch: SketchParams = SketchParams(),
                           ridge: float = 0.0
                           ) -> Tuple[TrackingResult, SketchInfo]:
    """The sketched twin of :func:`porqua_tpu.tracking.tracking_step`:
    build (through the embedding) + solve + evaluate for a batch of
    date windows, one XLA program. The tracking error is ALWAYS
    measured against the true (unsketched) window — the sketch may
    only approximate the problem, never the evaluation — so the bench
    A/B's TE drift is a real quality delta, not a self-graded one.
    Jittable with ``(params, sketch, ridge)`` static."""
    from porqua_tpu.qp.solve import SolverParams, _solve_impl

    if params is None:
        params = SolverParams()

    def one(X, y):
        qp, info = sketched_tracking_qp(X, y, sketch, ridge=ridge)
        sol = _solve_impl(qp, params, None, None)
        resid = jnp.dot(X, sol.x, precision=HP) - y
        te = jnp.sqrt(jnp.mean(resid * resid))
        return sol, te, info

    sols, tes, infos = jax.vmap(one)(Xs, ys)
    return TrackingResult(
        weights=sols.x,
        tracking_error=tes,
        status=sols.status,
        iters=sols.iters,
        prim_res=sols.prim_res,
        dual_res=sols.dual_res,
    ), infos
