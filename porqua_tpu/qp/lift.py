"""Dimension-expanding L1 linearizations (host-side, numpy).

Mirrors of the reference's three problem rewrites
(``src/qp_problems.py:40-157``) in canonical interval form:

* turnover constraint  ||w - w0||_1 <= budget  -> n aux vars t with
  w - t <= w0, -w - t <= -w0, sum(t) <= budget;
* leverage constraint  sum|w_i| <= L  -> 2n aux vars (p, m) with
  w + p - m = 0, sum(p + m) <= L, p, m >= 0;
* turnover transaction-cost objective  tc * ||w - w0||_1  -> n aux vars
  with cost tc each and the same absolute-value rows.

These keep shapes *static across rebalance dates* (only the right-hand
side x0 varies), which is what lets a turnover-coupled backtest run as
``lax.scan`` over dates with a fixed compiled program. The ADMM solver
handles the expanded problem directly — no special-casing needed. An
alternative prox-operator formulation (no dimension expansion) is
planned for the solver itself; the lifted form is the exactness
reference.

All functions take and return a dict with keys
``P, q, C, l, u, lb, ub`` (numpy, unpadded).
"""

from __future__ import annotations

import numpy as np

INF = np.inf


def _as_parts(P, q, C, l, u, lb, ub):
    n = len(q)
    if C is None or C.size == 0:
        C = np.zeros((0, n))
        l = np.zeros((0,))
        u = np.zeros((0,))
    return dict(P=P, q=q, C=C, l=l, u=u, lb=lb, ub=ub)


def lift_turnover_constraint(parts: dict, x_init: np.ndarray, to_budget: float) -> dict:
    """Reference ``linearize_turnover_constraint`` (``qp_problems.py:40-77``)."""
    P, q, C, l, u = parts["P"], parts["q"], parts["C"], parts["l"], parts["u"]
    lb, ub = parts["lb"], parts["ub"]
    n = len(q)
    m = C.shape[0]
    x_init = np.asarray(x_init, dtype=float).reshape(-1)

    P_new = np.zeros((2 * n, 2 * n))
    P_new[:n, :n] = P
    q_new = np.concatenate([q, np.zeros(n)])

    eye = np.eye(n)
    C_new = np.zeros((m + 2 * n + 1, 2 * n))
    C_new[:m, :n] = C
    C_new[m:m + n, :n] = eye
    C_new[m:m + n, n:] = -eye
    C_new[m + n:m + 2 * n, :n] = -eye
    C_new[m + n:m + 2 * n, n:] = -eye
    C_new[m + 2 * n, n:] = 1.0

    l_new = np.concatenate([l, np.full(2 * n + 1, -INF)])
    u_new = np.concatenate([u, x_init, -x_init, [to_budget]])

    lb_new = np.concatenate([lb, np.zeros(n)])
    ub_new = np.concatenate([ub, np.full(n, INF)])
    return dict(P=P_new, q=q_new, C=C_new, l=l_new, u=u_new, lb=lb_new, ub=ub_new)


def lift_leverage_constraint(parts: dict, leverage_budget: float) -> dict:
    """Reference ``linearize_leverage_constraint`` (``qp_problems.py:79-118``),
    with its two latent bugs fixed (SURVEY.md section 2): aux vars p, m >= 0
    split w = m - p so sum(p + m) bounds the leverage."""
    P, q, C, l, u = parts["P"], parts["q"], parts["C"], parts["l"], parts["u"]
    lb, ub = parts["lb"], parts["ub"]
    n = len(q)
    m_rows = C.shape[0]

    P_new = np.zeros((3 * n, 3 * n))
    P_new[:n, :n] = P
    q_new = np.concatenate([q, np.zeros(2 * n)])

    eye = np.eye(n)
    # Equality block: w + p - m = 0
    C_eq = np.concatenate([eye, eye, -eye], axis=1)
    # Leverage row: sum(p + m) <= L
    C_lev = np.concatenate([np.zeros(n), np.ones(2 * n)])[None, :]
    C_orig = np.concatenate([C, np.zeros((m_rows, 2 * n))], axis=1)
    C_new = np.concatenate([C_orig, C_eq, C_lev], axis=0)

    l_new = np.concatenate([l, np.zeros(n), [-INF]])
    u_new = np.concatenate([u, np.zeros(n), [leverage_budget]])

    lb_new = np.concatenate([lb, np.zeros(2 * n)])
    ub_new = np.concatenate([ub, np.full(2 * n, INF)])
    return dict(P=P_new, q=q_new, C=C_new, l=l_new, u=u_new, lb=lb_new, ub=ub_new)


def lift_turnover_objective(parts: dict, x_init: np.ndarray, transaction_cost: float) -> dict:
    """Reference ``linearize_turnover_objective`` (``qp_problems.py:120-157``):
    adds tc * sum(t) to the objective with t >= |w - x0|."""
    P, q, C, l, u = parts["P"], parts["q"], parts["C"], parts["l"], parts["u"]
    lb, ub = parts["lb"], parts["ub"]
    n = len(q)
    m = C.shape[0]
    x_init = np.asarray(x_init, dtype=float).reshape(-1)

    P_new = np.zeros((2 * n, 2 * n))
    P_new[:n, :n] = P
    q_new = np.concatenate([q, np.full(n, transaction_cost)])

    eye = np.eye(n)
    C_new = np.zeros((m + 2 * n, 2 * n))
    C_new[:m, :n] = C
    C_new[m:m + n, :n] = eye
    C_new[m:m + n, n:] = -eye
    C_new[m + n:, :n] = -eye
    C_new[m + n:, n:] = -eye

    l_new = np.concatenate([l, np.full(2 * n, -INF)])
    u_new = np.concatenate([u, x_init, -x_init])

    lb_new = np.concatenate([lb, np.zeros(n)])
    ub_new = np.concatenate([ub, np.full(n, INF)])
    return dict(P=P_new, q=q_new, C=C_new, l=l_new, u=u_new, lb=lb_new, ub=ub_new)
