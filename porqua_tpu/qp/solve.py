"""Public QP solve API: single and batched.

``solve_qp`` is the TPU-native analog of the reference's
``QuadraticProgram.solve`` -> ``qpsolvers.solve_problem`` hop
(reference ``src/qp_problems.py:184-216``), except it is a pure jittable
function: equilibrate -> ADMM -> polish -> unscale, all on device.
``solve_qp_batch`` is its ``vmap`` over a leading problem axis — the
building block that turns a backtest's per-date solver calls into one
XLA program.
"""

from __future__ import annotations

import contextlib
import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from porqua_tpu.qp.admm import (
    ADMMCarry,
    ADMMState,
    SolverParams,
    Status,
    admm_init,
    admm_segment_step,
    admm_solve,
    _residuals,
    _support,
)
from porqua_tpu.qp.canonical import CanonicalQP, HP
from porqua_tpu.qp.napg import napg_init, napg_segment_step, napg_solve
from porqua_tpu.qp.pdhg import pdhg_init, pdhg_segment_step, pdhg_solve
from porqua_tpu.qp.polish import polish_iterate as _polish_iterate
from porqua_tpu.qp.ruiz import Scaling, equilibrate, equilibrate_factored


def _backend(params: SolverParams):
    """Resolve ``params.method`` to the ``(init, segment_step, solve)``
    triple of the selected first-order backend. Both backends carry
    their iterate as an ``ADMMState`` and share :func:`_prepare_impl` /
    :func:`_finalize_impl`, so this is the ONLY dispatch point — every
    driver above (fused solve, compaction, continuous serving) is
    backend-agnostic. A typo'd method silently running the wrong solver
    would poison routing tables and promotion evidence — fail loudly
    (same idiom as ``scaling_mode``)."""
    if params.method == "admm":
        return admm_init, admm_segment_step, admm_solve
    if params.method == "pdhg":
        return pdhg_init, pdhg_segment_step, pdhg_solve
    if params.method == "napg":
        return napg_init, napg_segment_step, napg_solve
    raise ValueError(
        f"unknown method {params.method!r}; expected 'admm', 'pdhg' "
        "or 'napg'")


class QPSolution(NamedTuple):
    """Solution + certificates, mirroring what the reference reads off a
    ``qpsolvers`` solution object (x, found, obj, residuals — reference
    ``example/compare_solver.ipynb`` cell 8 metric set)."""

    x: jax.Array          # (n,) primal solution (unscaled)
    z: jax.Array          # (m,) constraint activity Cx (unscaled)
    y: jax.Array          # (m,) duals for C rows (unscaled)
    mu: jax.Array         # (n,) duals for box (unscaled)
    status: jax.Array     # () int, see Status
    iters: jax.Array      # () int
    prim_res: jax.Array   # () unscaled primal residual (inf-norm)
    dual_res: jax.Array   # () unscaled dual residual (inf-norm)
    obj_val: jax.Array    # () 0.5 x'Px + q'x + constant
    duality_gap: jax.Array  # () |primal - dual objective|
    # Convergence telemetry (params.ring_size > 0 only; None
    # otherwise — an empty pytree subtree, so the default program and
    # its output structure are unchanged). Slot j % ring_size holds
    # segment j's residual check; decode chronologically with
    # porqua_tpu.obs.rings.ring_history (uses `iters` to locate the
    # write head). The residuals are the ADMM iterate's — the final
    # prim_res/dual_res above are recomputed post-polish, so with
    # polish=False the last ring sample equals them exactly.
    ring_prim: Optional[jax.Array] = None  # (ring_size,)
    ring_dual: Optional[jax.Array] = None  # (ring_size,)
    ring_rho: Optional[jax.Array] = None   # (ring_size,)

    @property
    def found(self):
        return self.status == Status.SOLVED


def _prepare_impl(qp: CanonicalQP,
                  params: SolverParams,
                  x0: Optional[jax.Array],
                  y0: Optional[jax.Array],
                  l1_weight: Optional[jax.Array] = None,
                  l1_center: Optional[jax.Array] = None):
    """The front half of :func:`_solve_impl`: equilibrate and map warm
    starts / the native L1 term into the scaled frame. Returns
    ``(scaled, scaling, x0_s, y0_s, l1w_s, l1c_s)``. Split out so
    segment-stepped drivers (batch compaction, continuous serving) run
    the identical preparation the fused solve does."""
    if params.scaling_mode == "factored":
        scaled, scaling = equilibrate_factored(qp)
    elif params.scaling_mode == "ruiz":
        scaled, scaling = equilibrate(qp, iters=params.scaling_iters)
    else:
        # A typo'd mode silently measuring the wrong equilibration
        # would poison promotion evidence — fail loudly instead.
        raise ValueError(
            f"unknown scaling_mode {params.scaling_mode!r}; "
            "expected 'ruiz' or 'factored'")

    x0_s = None if x0 is None else x0 / scaling.D
    y0_s = None if y0 is None else scaling.c * y0 / jnp.where(scaling.E > 0, scaling.E, 1.0)

    # The L1 term sum_i w_i |x_i - c_i| is stated in the original frame;
    # with x = D xhat and objective scaling c it becomes
    # sum_i (c * w_i * D_i) |xhat_i - c_i / D_i| in the scaled frame.
    l1w_s = None if l1_weight is None else scaling.c * l1_weight * scaling.D
    l1c_s = None if l1_center is None else l1_center / scaling.D
    return scaled, scaling, x0_s, y0_s, l1w_s, l1c_s


def _finalize_impl(qp: CanonicalQP,
                   scaled: CanonicalQP,
                   scaling: Scaling,
                   state: ADMMState,
                   params: SolverParams,
                   l1_weight: Optional[jax.Array] = None,
                   l1_center: Optional[jax.Array] = None,
                   l1w_s: Optional[jax.Array] = None,
                   l1c_s: Optional[jax.Array] = None) -> QPSolution:
    """The tail half of :func:`_solve_impl`: retire a still-``RUNNING``
    state to ``MAX_ITER`` (idempotent — ``admm_solve`` already did it;
    segment-budget drivers hand in raw stepper states), then polish,
    unscale, and assemble the :class:`QPSolution`. This is the
    "MAX_ITER + polish fallback": a lane retired out of budget still
    gets the active-set polish, and is re-graded ``SOLVED`` when the
    polished point actually meets tolerance."""
    state = state._replace(
        status=jnp.where(state.status == Status.RUNNING, Status.MAX_ITER,
                         state.status).astype(jnp.int32))
    x, z, w, y, mu = state.x, state.z, state.w, state.y, state.mu

    # Active-set polish. With a live L1 term the polish is prox-aware
    # (see qp.polish): kink variables are pinned, the fixed subgradient
    # shifts q, and the smooth KKT system is solved — so cost-aware
    # dates get the same high-accuracy finish as plain ones. The passes
    # form a true active-set iteration (each pass re-classifies from
    # the previous CANDIDATE, not from the possibly-unchanged pick —
    # see polish_iterate for why the old loop could fix-point on a
    # rejected first pass).
    if params.polish:
        x, z, w, y, mu = _polish_iterate(
            scaled, scaling, params, x, z, w, y, mu,
            l1_weight=l1w_s, l1_center=l1c_s)

    r_prim, r_dual, eps_p, eps_d, _, _ = _residuals(
        scaled, scaling, x, z, w, y, mu, params
    )
    solved_now = (r_prim <= eps_p) & (r_dual <= eps_d)
    status = jnp.where(
        (state.status == Status.MAX_ITER) & solved_now, Status.SOLVED, state.status
    ).astype(jnp.int32)

    # Unscale
    x_u = scaling.D * x * qp.var_mask
    z_u = (z / jnp.where(scaling.E > 0, scaling.E, 1.0))
    y_u = (1.0 / scaling.c) * scaling.E * y * qp.row_mask
    mu_u = (1.0 / scaling.c) * (1.0 / scaling.D) * mu * qp.var_mask

    obj = qp.objective_value(x_u)
    if l1_weight is not None:
        obj = obj + jnp.sum(l1_weight * jnp.abs(x_u - (
            jnp.zeros_like(x_u) if l1_center is None else l1_center
        )))
    # Duality gap: primal - dual objective = x'Px + q'x + support terms,
    # computed against the original (unscaled) bounds.
    if l1_weight is None:
        gap = jnp.abs(
            jnp.dot(x_u, qp.apply_P(x_u), precision=HP)
            + jnp.dot(qp.q, x_u, precision=HP)
            + _support(qp.u, qp.l, y_u) + _support(qp.ub, qp.lb, mu_u)
        )
    else:
        # With a native L1 term the combined box dual mu carries the L1
        # subgradient g in w * d|x - c|; the plain support formula is
        # invalid. Split mu = mu_box + g with g = clip(mu, -w, w): any
        # |g| <= w split is a feasible dual point (so the gap below is
        # a valid weak-duality bound), and the dual-based split is the
        # tight one — at (near-)optimality mu rests at +/-w for
        # smooth-side and box-active variables and strictly inside for
        # kink-resters, so the residual mu_box mass vanishes with the
        # KKT error (a position-based split inflates the bound whenever
        # a kink-rester sits iterate-error off its kink). The conjugate
        # of the L1 term contributes c'g.
        c_vec = jnp.zeros_like(x_u) if l1_center is None else l1_center
        dx_c = x_u - c_vec
        g = jnp.clip(mu_u, -l1_weight, l1_weight)
        mu_box = mu_u - g
        gap = jnp.abs(
            jnp.dot(x_u, qp.apply_P(x_u), precision=HP)
            + jnp.dot(qp.q, x_u, precision=HP)
            + jnp.sum(l1_weight * jnp.abs(dx_c))
            + jnp.dot(c_vec, g, precision=HP)
            + _support(qp.u, qp.l, y_u) + _support(qp.ub, qp.lb, mu_box)
        )

    return QPSolution(
        x=x_u, z=z_u, y=y_u, mu=mu_u,
        status=status,
        iters=state.iters,
        prim_res=r_prim,
        dual_res=r_dual,
        obj_val=obj,
        duality_gap=gap,
        ring_prim=state.ring_prim,
        ring_dual=state.ring_dual,
        ring_rho=state.ring_rho,
    )


def _solve_impl(qp: CanonicalQP,
                params: SolverParams,
                x0: Optional[jax.Array],
                y0: Optional[jax.Array],
                l1_weight: Optional[jax.Array] = None,
                l1_center: Optional[jax.Array] = None) -> QPSolution:
    scaled, scaling, x0_s, y0_s, l1w_s, l1c_s = _prepare_impl(
        qp, params, x0, y0, l1_weight, l1_center)
    _, _, solver = _backend(params)
    state = solver(scaled, scaling, params, x0=x0_s, y0=y0_s,
                   l1_weight=l1w_s, l1_center=l1c_s)
    return _finalize_impl(qp, scaled, scaling, state, params,
                          l1_weight, l1_center, l1w_s, l1c_s)


@functools.partial(jax.jit, static_argnames=("params",))
def solve_qp(qp: CanonicalQP,
             params: SolverParams = SolverParams(),
             x0: Optional[jax.Array] = None,
             y0: Optional[jax.Array] = None,
             l1_weight: Optional[jax.Array] = None,
             l1_center: Optional[jax.Array] = None) -> QPSolution:
    """Solve one canonical QP on device.

    ``l1_weight``/``l1_center`` add a native nonsmooth objective term
    sum_i l1_weight_i |x_i - l1_center_i| (see
    :func:`porqua_tpu.qp.admm.admm_solve`) — e.g. a turnover
    transaction-cost with l1_center = previous holdings — without the
    reference's 2x variable expansion (``qp_problems.py:120-157``).
    """
    return _solve_impl(qp, params, x0, y0, l1_weight, l1_center)


def _solve_batch_impl(qp: CanonicalQP,
                      params: SolverParams,
                      x0: Optional[jax.Array] = None,
                      y0: Optional[jax.Array] = None,
                      l1_weight: Optional[jax.Array] = None,
                      l1_center: Optional[jax.Array] = None) -> QPSolution:
    """The vmapped batch solve, un-jitted — shared by the jit entry point
    below and the AOT lowering path (:func:`aot_compile_batch`)."""
    in_axes = tuple(None if a is None else 0
                    for a in (qp, x0, y0, l1_weight, l1_center))
    return jax.vmap(
        lambda q, xx, yy, lw, lc: _solve_impl(q, params, xx, yy, lw, lc),
        in_axes=(0,) + in_axes[1:],
    )(qp, x0, y0, l1_weight, l1_center)


# ---------------------------------------------------------------------------
# Segment-stepped batch API (the compaction / continuous-batching core)
# ---------------------------------------------------------------------------
#
# The three phases of ``_solve_batch_impl`` exposed separately, each
# vmapped over a leading lane axis, so batch orchestration — run K
# segments, retire/repack/refill lanes, keep going — can live *above*
# the device program instead of inside one while_loop that charges
# every lane for the slowest. Per-lane arithmetic is the exact code
# the fused path runs (shared ``_prepare_impl`` / ``admm_segment_step``
# / ``_finalize_impl``), which is what makes the compacted results
# bit-identical for lanes that converge (pinned by
# tests/test_compaction.py).

def default_segment_budget(params: SolverParams) -> int:
    """The per-lane segment budget that reproduces plain ``max_iter``
    semantics: ``ceil(max_iter / check_interval)``. One definition,
    shared by the compaction driver and the continuous batcher so the
    two retirement policies cannot fork."""
    import math

    return max(1, math.ceil(params.max_iter / params.check_interval))


def select_lanes(mask, new, old):
    """Per-lane select over a pytree: ``mask`` is (b,), leaves are
    (b, ...) — the same freeze the vmapped while_loop applies to lanes
    whose cond went false. Shared by every segment-stepped driver so
    the broadcast rule cannot drift."""
    def sel(a, b):
        m = mask.reshape(mask.shape + (1,) * (a.ndim - 1))
        return jnp.where(m, a, b)

    return jax.tree.map(sel, new, old)


def prepare_batch(qp: CanonicalQP,
                  params: SolverParams,
                  x0: Optional[jax.Array] = None,
                  y0: Optional[jax.Array] = None,
                  l1_weight: Optional[jax.Array] = None,
                  l1_center: Optional[jax.Array] = None):
    """Equilibrate every lane and build its segment-loop carry.

    Returns ``(scaled, scaling, carry, l1w_s, l1c_s)``, all with a
    leading lane axis (the l1 leaves are ``None`` when no L1 term was
    given — empty pytree subtrees, same convention as everywhere).
    """
    in_axes = tuple(None if a is None else 0
                    for a in (qp, x0, y0, l1_weight, l1_center))
    init, _, _ = _backend(params)

    def one(q, xx, yy, lw, lc):
        scaled, scaling, x0_s, y0_s, l1w_s, l1c_s = _prepare_impl(
            q, params, xx, yy, lw, lc)
        carry = init(scaled, params, x0_s, y0_s)
        return scaled, scaling, carry, l1w_s, l1c_s

    return jax.vmap(one, in_axes=(0,) + in_axes[1:])(
        qp, x0, y0, l1_weight, l1_center)


def segment_step_batch(scaled: CanonicalQP,
                       scaling: Scaling,
                       carry: ADMMCarry,
                       params: SolverParams,
                       l1w_s: Optional[jax.Array] = None,
                       l1c_s: Optional[jax.Array] = None) -> ADMMCarry:
    """Advance every lane one residual-check segment (the vmapped
    segment stepper of the backend ``params.method`` selects —
    :func:`porqua_tpu.qp.admm.admm_segment_step` or
    :func:`porqua_tpu.qp.pdhg.pdhg_segment_step`; the carry is the
    matching backend's, always with ``.state: ADMMState``). Per-lane
    status lives in ``carry.state.status``."""
    in_axes = (0, 0, 0,
               None if l1w_s is None else 0,
               None if l1c_s is None else 0)
    _, seg_step, _ = _backend(params)

    def one(c, s, sc, lw, lc):
        return seg_step(c, s, sc, params, lw, lc)[0]

    return jax.vmap(one, in_axes=in_axes)(carry, scaled, scaling,
                                          l1w_s, l1c_s)


def finalize_batch(qp: CanonicalQP,
                   scaled: CanonicalQP,
                   scaling: Scaling,
                   state: ADMMState,
                   params: SolverParams,
                   l1_weight: Optional[jax.Array] = None,
                   l1_center: Optional[jax.Array] = None,
                   l1w_s: Optional[jax.Array] = None,
                   l1c_s: Optional[jax.Array] = None) -> QPSolution:
    """Polish + unscale + grade every lane (vmapped
    :func:`_finalize_impl`). Still-``RUNNING`` lanes (retired out of
    segment budget) are graded ``MAX_ITER`` and get the polish
    fallback, exactly like the fused path's out-of-iterations exit."""
    axes = [0, 0, 0, 0] + [None if a is None else 0
                           for a in (l1_weight, l1_center, l1w_s, l1c_s)]

    def one(q, s, sc, st, lw, lc, lws, lcs):
        return _finalize_impl(q, s, sc, st, params, lw, lc, lws, lcs)

    return jax.vmap(one, in_axes=tuple(axes))(
        qp, scaled, scaling, state, l1_weight, l1_center, l1w_s, l1c_s)


@functools.partial(jax.jit, static_argnames=("params",))
def solve_qp_batch(qp: CanonicalQP,
                   params: SolverParams = SolverParams(),
                   x0: Optional[jax.Array] = None,
                   y0: Optional[jax.Array] = None,
                   l1_weight: Optional[jax.Array] = None,
                   l1_center: Optional[jax.Array] = None) -> QPSolution:
    """Solve a batch of canonical QPs (leading axis) in one XLA program."""
    return _solve_batch_impl(qp, params, x0, y0, l1_weight, l1_center)


def batch_shape_struct(batch: int, n: int, m: int,
                       dtype=jnp.float32,
                       factor_rows: Optional[int] = None) -> CanonicalQP:
    """Abstract (shape/dtype-only) ``CanonicalQP`` batch for AOT lowering.

    ``factor_rows`` adds the optional low-rank objective factor
    ``Pf (batch, r, n)`` / ``Pdiag (batch, n)`` to the pytree — the
    factor's row count is part of the static shape, so executables for
    factored and dense problems are distinct cache entries.
    """
    s = lambda *shape: jax.ShapeDtypeStruct((batch,) + shape, dtype)
    return CanonicalQP(
        P=s(n, n), q=s(n), C=s(m, n), l=s(m), u=s(m), lb=s(n), ub=s(n),
        var_mask=s(n), row_mask=s(m), constant=s(),
        Pf=None if factor_rows is None else s(factor_rows, n),
        Pdiag=None if factor_rows is None else s(n),
    )


def continuous_entries(params: SolverParams = SolverParams()):
    """The continuous-batching entry closures ``(admit, step,
    finalize)`` as pure functions — :func:`aot_compile_continuous`
    lowers exactly these, and the GC101–103 jaxpr contracts
    (:mod:`porqua_tpu.analysis.contracts`) trace the same objects, so
    the compiled programs and the machine-checked ones cannot drift."""
    _sel = select_lanes

    def admit(qp, x0, y0, mask, scaled_old, scaling_old, carry_old):
        scaled, scaling, carry, _, _ = prepare_batch(qp, params, x0, y0)
        return (qp,
                _sel(mask, scaled, scaled_old),
                _sel(mask, scaling, scaling_old),
                _sel(mask, carry, carry_old))

    def step(scaled, scaling, carry, active):
        new = segment_step_batch(scaled, scaling, carry, params)
        new = _sel(active, new, carry)
        return new, new.state.status, new.state.iters

    def fin(qp, scaled, scaling, state):
        return finalize_batch(qp, scaled, scaling, state, params)

    return admit, step, fin


def aot_compile_continuous(qp_struct: CanonicalQP,
                           params: SolverParams = SolverParams(),
                           device=None):
    """AOT-compile the continuous-batching executable triple for one
    static cohort shape; returns ``(admit, step, finalize, structs)``.

    The serving cohort holds a fixed number of lanes whose membership
    changes at segment boundaries (freed slots refilled from the
    queue), so the fused solve program is split in three — each a
    fixed-shape program compiled once per ``(bucket, slots, device)``:

    * ``admit(qp, x0, y0, mask, scaled_old, scaling_old, carry_old)
      -> (qp, scaled, scaling, carry)`` — equilibrate + carry-init for
      every slot, then per-lane select: admitted slots take the fresh
      state, others keep theirs. ``qp`` is passed through so the
      cohort's problem data stays device-resident for ``finalize``.
    * ``step(scaled, scaling, carry, active) -> (carry, status,
      iters)`` — one residual-check segment, with inactive lanes
      frozen by the same select the vmapped while_loop applies.
    * ``finalize(qp, scaled, scaling, state) -> QPSolution`` — polish
      + unscale + grade for the whole cohort; the batcher reads only
      the retiring lanes' rows. Still-``RUNNING`` lanes retired out of
      segment budget grade ``MAX_ITER`` with the polish fallback.

    ``structs`` is ``(scaled, scaling, carry)`` as shape structs — the
    batcher materializes the zero initial state from it at cohort
    creation.
    """
    B = qp_struct.q.shape[0]
    n, m = qp_struct.q.shape[-1], qp_struct.l.shape[-1]
    dtype = qp_struct.q.dtype
    x0_s = jax.ShapeDtypeStruct((B, n), dtype)
    y0_s = jax.ShapeDtypeStruct((B, m), dtype)
    mask_s = jax.ShapeDtypeStruct((B,), jnp.bool_)

    admit, step, fin = continuous_entries(params)

    structs = jax.eval_shape(
        lambda q, x, y: prepare_batch(q, params, x, y)[:3],
        qp_struct, x0_s, y0_s)
    scaled_s, scaling_s, carry_s = structs
    ctx = (jax.default_device(device) if device is not None
           else contextlib.nullcontext())
    with ctx:
        admit_exe = jax.jit(admit).lower(
            qp_struct, x0_s, y0_s, mask_s,
            scaled_s, scaling_s, carry_s).compile()
        step_exe = jax.jit(step).lower(
            scaled_s, scaling_s, carry_s, mask_s).compile()
        fin_exe = jax.jit(fin).lower(
            qp_struct, scaled_s, scaling_s, carry_s.state).compile()
    return admit_exe, step_exe, fin_exe, structs


def aot_compile_batch(qp_struct: CanonicalQP,
                      params: SolverParams = SolverParams(),
                      device=None):
    """AOT-compile the batch solve for one static shape: the serving
    entry point (``jit(...).lower(...).compile()``).

    The returned executable takes ``(qp, x0, y0)`` with concrete arrays
    matching ``qp_struct`` plus ``x0 (batch, n)`` / ``y0 (batch, m)``
    warm starts, and returns a batched :class:`QPSolution`. Warm starts
    are ALWAYS part of the signature — ``x0=None`` and ``x0=zeros`` run
    the identical program (``admm_solve`` initializes at zero), so one
    executable serves both cold and warm requests and the compiled-
    executable cache never forks on warm-start presence.

    ``device`` pins compilation to a specific :class:`jax.Device`
    (serving compiles one executable per device so the circuit breaker
    can fall back from TPU to XLA-CPU without a recompile-on-failover
    stall); ``None`` compiles for the default backend.
    """
    B = qp_struct.q.shape[0]
    n, m = qp_struct.q.shape[-1], qp_struct.l.shape[-1]
    dtype = qp_struct.q.dtype
    x0_s = jax.ShapeDtypeStruct((B, n), dtype)
    y0_s = jax.ShapeDtypeStruct((B, m), dtype)

    def entry(qp, x0, y0):
        return _solve_batch_impl(qp, params, x0, y0)

    ctx = (jax.default_device(device) if device is not None
           else contextlib.nullcontext())
    with ctx:
        return jax.jit(entry).lower(qp_struct, x0_s, y0_s).compile()
