"""Batched OSQP-style ADMM core in pure JAX.

This module is the TPU-native replacement for the reference's external
C/C++ QP solver backends (cvxopt/osqp/quadprog/... reached through
``qpsolvers.solve_problem`` at reference ``src/qp_problems.py:211``).
One solve is a dense operator-splitting iteration whose hot ops — an
n x n Cholesky factorization and triangular solves, plus m x n matmuls —
map straight onto the MXU; a *batch* of problems (one per rebalance
date / benchmark) is handled by ``vmap`` over the leading axis, so an
entire backtest's worth of QPs is a single XLA program.

Algorithm (OSQP, Stellato et al. 2020, adapted to an implicit box
block):

    minimize 0.5 x'Px + q'x   s.t.  l <= Cx <= u,  lb <= x <= ub

ADMM splitting with slack z for the C-block and w for the box block,
duals y and mu, step sizes rho (per-row, x1000 on equality rows) and
sigma:

    (P + sigma I + C' diag(rho) C + diag(rho_b)) xt = sigma x - q
          + C'(rho z - y) + (rho_b w - mu)
    x+  = alpha xt + (1-alpha) x
    z+  = clip(alpha C xt + (1-alpha) z + y/rho, l, u);   y += rho (.. - z+)
    w+  = clip(alpha xt + (1-alpha) w + mu/rho_b, lb, ub); mu += rho_b (.. - w+)

Control flow is compiler-friendly: a ``lax.while_loop`` over *segments*
of ``check_interval`` iterations (a ``fori_loop``), with the Cholesky
factor recomputed once per segment so adaptive-rho updates amortize to
one n^3/3 factorization per residual check. No data-dependent shapes,
no host round-trips; termination and infeasibility certificates are
evaluated on device.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.scipy.linalg import cho_factor, cho_solve

from porqua_tpu.qp.canonical import HP as _HP, CanonicalQP
from porqua_tpu.qp.ruiz import Scaling


class Status:
    """Per-problem termination codes carried as device integers."""

    RUNNING = 0
    SOLVED = 1
    MAX_ITER = 2
    PRIMAL_INFEASIBLE = 3
    DUAL_INFEASIBLE = 4

    NAMES = {
        RUNNING: "running",
        SOLVED: "solved",
        MAX_ITER: "max_iter",
        PRIMAL_INFEASIBLE: "primal_infeasible",
        DUAL_INFEASIBLE: "dual_infeasible",
    }


@dataclasses.dataclass(frozen=True)
class SolverParams:
    """Static solver configuration (hashable, safe as a jit static arg).

    Typed replacement for the reference's free-form
    ``OptimizationParameter`` dict (reference ``optimization.py:40-47``).
    """

    max_iter: int = 4000
    check_interval: int = 25
    # First-order backend: "admm" (this module), "pdhg" (restarted
    # primal-dual hybrid gradient, qp/pdhg.py) or "napg" (Nesterov-
    # accelerated projected gradient for the box-dominated regime,
    # qp/napg.py). All implement the same segment-stepper contract
    # (init / segment_step / shared finalize), run on the same
    # Ruiz-equilibrated canonical form, and carry their state as an
    # ADMMState — so compaction, continuous batching, serving,
    # harvest, and the ring telemetry work unmodified for any of
    # them. Part of the params hash, hence of every executable-cache
    # identity: per-backend executables come for free.
    method: str = "admm"
    # "auto" == "xla" everywhere: the fused Pallas kernel is opt-in
    # only (its explicit f32 inverse costs iterations — see the backend
    # selection note in admm_solve); "pallas" forces the fused segment.
    backend: str = "auto"  # "auto" | "xla" | "pallas"
    # Linear-solve strategy inside a segment for the XLA backend:
    # "chol"    — cho_solve (two triangular solves) per iteration;
    #             most accurate, but a one-RHS trsm is the slowest
    #             primitive on the MXU (measured ~12 ms/iteration for
    #             the 252 x 500 north-star batch, ~20x off roofline).
    # "trinv"   — invert the Cholesky factor L once per segment, then
    #             each iteration applies K^-1 = L^-T L^-1 as two dense
    #             matvecs: pure MXU/HBM-streaming work, with solve
    #             error ~cond(L)*eps = sqrt(cond(K))*eps — measured to
    #             preserve the chol path's iteration counts where the
    #             full explicit K^-1 (cond(K)*eps) does not.
    # "inverse" — explicit KKT inverse with one Newton refinement;
    #             cheapest per iteration but the f32 error budget costs
    #             extra segments on ill-conditioned problems.
    # "woodbury"— explicit opt-in; requires ``qp.Pf`` (P = 2 Pf'Pf +
    #             diag(Pdiag)) and raises ValueError without it: the
    #             segment factorizations run on the r x r capacitance
    #             matrix S = I + V D^-1 V' instead of the n x n KKT.
    #             Round 2 measured this NOT to pay — but that regime
    #             (refine>=1 forced by eq_scale 1e3's conditioning,
    #             straggler lanes charging extra segments) died with
    #             the x1000 equality weighting. At rho_eq_scale=1.0,
    #             refine=0 converges at trinv-grade iteration counts,
    #             and with check_interval=35 the round-3 on-chip batch
    #             measured 35.0 ms vs trinv's 62.6 ms on the north-star
    #             B=252 (252/252 solved, TE parity) — it is the TPU
    #             headline config in bench.py. The factored structure
    #             is also exploited by the polish, unconditionally,
    #             whenever qp.Pf is present (_kkt_solve_factored).
    # "auto"    — "trinv" for f32 on every backend (the f32 cho_solve
    #             substitution stalls at production scale, see
    #             resolve_linsolve); f64: "trinv" on TPU, "chol"
    #             elsewhere.
    linsolve: str = "auto"
    # Inner iterative-refinement steps of the Woodbury apply (residual
    # via the factor form, two extra matvec pairs each). The default 1
    # is the safe setting for arbitrary rho_eq_scale; at the library
    # default eq_scale 1.0 the raw apply (0) converges at trinv-grade
    # iteration counts (the round-2 "stalls just above eps" finding was
    # an artifact of the x1000 equality weighting's conditioning) and
    # is what the bench's TPU headline config uses.
    woodbury_refine: int = 1
    # VMEM budget for the fused Pallas segment (Kinv + C + state vectors
    # must all be core-resident; ~16 MB/core on v5e, leave headroom).
    # backend="auto" falls back to the XLA path above this footprint.
    vmem_limit_mb: float = 12.0
    eps_abs: float = 1e-6
    eps_rel: float = 1e-6
    eps_pinf: float = 1e-5
    eps_dinf: float = 1e-5
    rho0: float = 0.1
    # Extra step-size weight on equality rows (l == u). The OSQP-style
    # x1000 was the round-1/2 default, but it provably *hurts*: on
    # primal-degenerate problems (e.g. the real-MSCI 2020-10-01 window,
    # where the budget row is the sum of two box-active variables) the
    # mismatched row weights drive the iteration into a ~1e-4 limit
    # cycle that never meets a tight eps — measured 2000+ stalled
    # iterations at eq_scale 1e3 vs 50-75 clean iterations at 1.0 on
    # BOTH the MSCI window and the 500-asset north-star batch, with
    # identical iteration counts at loose eps. Equality rows still
    # converge (the eps criterion covers them) and the polish pins them
    # exactly; 1.0 also keeps K's conditioning lower, which the f32
    # paths appreciate.
    rho_eq_scale: float = 1.0
    rho_min: float = 1e-6
    rho_max: float = 1e6
    sigma: float = 1e-6
    alpha: float = 1.6
    adaptive_rho: bool = True
    # Halpern anchoring with adaptive restarts (the HPR-LP recipe) on
    # the ADMM fixed-point map: the iterate is pulled toward a carried
    # anchor a with the Halpern weight,
    # s_{k+1} = a/(k+2) + (k+1)/(k+2) * T(s_k), k counting iterations
    # since the last restart. The restart decision lives at each
    # residual check (segment boundary): re-anchor at the current
    # point on sufficient decrease of the scaled residual (factor
    # 1/4), or forcibly after 8*check_interval iterations without one
    # (a stale anchor slows the pull). Halpern carries Lieder's O(1/k)
    # fixed-point-residual rate per restart window vs the plain
    # averaged iteration's O(1/sqrt(k)) — measured ~4-10x fewer
    # iterations on pure LPs (the LAD prox lowering turns it on via
    # its solver-params overlay; scripts/lad_accel_sweep.py +
    # BASELINE.md). Note alpha stays in its averaged range: the full
    # Peaceman-Rachford step alpha=2 that Halpern theory prefers
    # DIVERGES through this OSQP-style splitting (measured — the
    # relaxed map is not nonexpansive there with sigma>0 and
    # per-block rho). XLA path only: with backend="pallas" the fused
    # kernel ignores the anchor, so admm_solve falls back to the XLA
    # segment and warns.
    halpern: bool = False
    # Restart tuning: re-anchor when the scaled residual has decayed
    # to halpern_decrease * (its value at the last restart), or
    # forcibly after halpern_max_windows restart windows
    # (check_interval iterations each) without one. Defaults from the
    # production-scale sweep (scripts/lad_accel_sweep.py).
    halpern_decrease: float = 0.25
    halpern_max_windows: int = 8
    # Step-size multiplier on variables carrying a native L1 term (the
    # w-block prox): the nonsmooth block's natural step differs from
    # the boxed variables'. 1.0 = uniform (no effect); LAD's overlay
    # promotes 10.0 (measured optimum at production scale — see the
    # segment body and BASELINE.md).
    rho_l1_scale: float = 1.0
    # PDHG backend knobs (method="pdhg" only; inert otherwise so the
    # ADMM executables' params identity is unchanged by their
    # presence). Restart rule (PDLP-style, arXiv:2311.07710): at each
    # residual check the normalized residual of the current iterate
    # AND of the restart-window average candidate are measured; the
    # solver restarts — adopting the better of the two and resetting
    # the averaging window — on sufficient decay
    # (pdhg_restart_decrease * the residual at the last restart) or
    # forcibly after pdhg_restart_max_windows checks without one.
    # pdhg_omega0 is the initial primal weight omega (tau =
    # 1/(L_P + omega*||C||), sigma = omega/||C||); adaptive_rho
    # rebalances it at restarts exactly like ADMM's rho.
    pdhg_restart_decrease: float = 0.25
    pdhg_restart_max_windows: int = 8
    pdhg_omega0: float = 1.0
    # Power-iteration count for the ||P||/||C|| spectral estimates
    # computed once at pdhg_init (they set the step sizes).
    pdhg_power_iters: int = 20
    # NAPG backend knobs (method="napg" only; inert otherwise so the
    # other backends' params identity is unchanged by their presence).
    # napg_power_iters: the one-time ||P|| power iteration at napg_init
    # (sets tau = 1/L). napg_project_cycles: dual coordinate-ascent
    # sweeps of the exact box(+L1) ∩ C-rows prox — 1 is exact for the
    # single-budget-row tracking family this backend exists for.
    # napg_bisect_iters: bisection steps per row multiplier (each
    # halves the dual bracket; 32 reaches f32 resolution).
    napg_power_iters: int = 20
    napg_project_cycles: int = 1
    napg_bisect_iters: int = 32
    # Sketch-fed problem assembly (the tracking path only — inert in
    # the solver itself, but part of the params hash so sketched and
    # dense pipelines compile to distinct executables). With
    # 0 < sketch_dim < window, porqua_tpu.tracking.build_tracking_qp
    # routes the Gram build through the count-sketch row embedding
    # (qp/canonical.sketch_rows, seeded by sketch_seed); 0 — the
    # default — is the bit-exact dense passthrough, pinned by the
    # bench sketch_off_identity rule.
    sketch_dim: int = 0
    sketch_seed: int = 0
    scaling_iters: int = 10
    # "ruiz": modified Ruiz sweeps over the dense P (scaling_iters of
    # them). "factored": Jacobi scaling computed from the objective
    # factor alone (requires qp.Pf) — same solve quality on Gram-matrix
    # workloads at a fraction of the HBM traffic; see
    # ruiz.equilibrate_factored. Opt-in (the bench's TPU headline
    # config uses it); "ruiz" stays the general-purpose default.
    scaling_mode: str = "ruiz"
    # Convergence telemetry: with ring_size=K the segment loop records
    # (prim_res, dual_res, rho_bar) into a K-slot circular buffer at
    # every residual check, entirely on device (three more carried
    # arrays; the ring holds the last K checks once a solve runs longer
    # than K segments). The default 0 compiles the exact program this
    # flag did not exist for — the ring fields stay None, which is an
    # empty pytree subtree, so the traced jaxpr is bit-identical
    # (pinned by the GC101-103 contracts, which trace both variants).
    # Decode host-side via porqua_tpu.obs.rings.ring_history.
    ring_size: int = 0
    polish: bool = True
    polish_delta: float = 1e-7
    polish_refine_steps: int = 3
    # Polish is re-run with the active set re-guessed from the polished
    # point; from a loosely-converged iterate one pass cannot identify
    # the active set exactly, but the pass-to-pass refinement converges
    # like an active-set method (accept-only-if-better keeps it safe).
    polish_passes: int = 3


class ADMMState(NamedTuple):
    x: jax.Array       # (n,) scaled primal
    z: jax.Array       # (m,) scaled C-block slack
    w: jax.Array       # (n,) scaled box-block slack
    y: jax.Array       # (m,) scaled C-block dual
    mu: jax.Array      # (n,) scaled box dual
    rho_bar: jax.Array  # () adaptive step-size scalar
    iters: jax.Array   # () total iterations run
    status: jax.Array  # () Status code
    prim_res: jax.Array
    dual_res: jax.Array
    # Convergence rings (params.ring_size > 0 only; None — an empty
    # pytree subtree — otherwise, keeping the default program
    # untouched). Slot j%K holds the residuals/rho of segment j.
    ring_prim: Optional[jax.Array] = None  # (ring_size,)
    ring_dual: Optional[jax.Array] = None  # (ring_size,)
    ring_rho: Optional[jax.Array] = None   # (ring_size,)


class ADMMCarry(NamedTuple):
    """The segment-loop carry: solver state plus the Halpern anchor.

    This is exactly what :func:`admm_solve`'s ``lax.while_loop``
    carries between segments — exposed so batch orchestration
    (compaction, continuous serving) can hoist the loop *above* the
    device program: ``admm_init`` builds it, ``admm_segment_step``
    advances it one residual-check segment, and ``admm_solve`` is the
    thin while_loop over the two.
    """

    state: ADMMState
    # Halpern anchor point (x, z, w, y, mu); carried unconditionally
    # (five vector copies) so the carry structure does not fork on
    # params.halpern and one compacted executable serves both.
    anchor: tuple
    k_anchor: jax.Array    # () int32, iterations since the last restart
    res_anchor: jax.Array  # () scaled residual at the last restart


def _inf_norm(v):
    return jnp.max(jnp.abs(v)) if v.size else jnp.asarray(0.0, v.dtype)


def l1_box_prox(v, lb, ub, l1w_over_rho, l1c):
    """Exact prox of ``I_[lb,ub] + l1w |. - l1c|`` (elementwise).

    Clipped shifted soft-threshold: in 1-D a convex objective restricted
    to an interval attains its minimum at the projection of the
    unconstrained minimizer. Reduces to the plain box projection when
    the weight is zero. Shared by the XLA iteration and the Pallas
    segment kernel so the two backends cannot drift.
    """
    s = v - l1c
    soft = jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1w_over_rho, 0.0)
    return jnp.clip(l1c + soft, lb, ub)


def _support(bound_u, bound_l, dual):
    """Support function of [l, u] at the dual direction, inf-safe."""
    pos = jnp.maximum(dual, 0.0)
    neg = jnp.minimum(dual, 0.0)
    up = jnp.where(pos > 0, bound_u * pos, 0.0)
    lo = jnp.where(neg < 0, bound_l * neg, 0.0)
    return jnp.sum(up + lo)


def _rho_vectors(qp: CanonicalQP, rho_bar, params: SolverParams):
    """Per-row step sizes: equality rows (l == u) get rho_eq_scale * rho."""
    eq_rows = jnp.isfinite(qp.l) & jnp.isfinite(qp.u) & ((qp.u - qp.l) <= 1e-10)
    rho = jnp.where(eq_rows, rho_bar * params.rho_eq_scale, rho_bar)
    eq_box = jnp.isfinite(qp.lb) & jnp.isfinite(qp.ub) & ((qp.ub - qp.lb) <= 1e-10)
    rho_b = jnp.where(eq_box, rho_bar * params.rho_eq_scale, rho_bar)
    return rho, rho_b


def _residuals(qp: CanonicalQP, scaling: Scaling, x, z, w, y, mu, params: SolverParams):
    """Unscaled residual norms and OSQP-style tolerance thresholds.

    All matvecs here run at Precision.HIGHEST: the TPU MXU computes f32
    ``@`` in bf16 passes by default, whose ~4e-3 relative error puts a
    floor under the measurable dual residual — on chip the LAD prox
    config stalled at r_dual ~1e-3 against its 1e-4 target purely from
    the residual measurement (TPU_TESTS_r05). The solve is memory-bound
    (MFU < 3%), so the extra passes are free.
    """
    Cx = jnp.dot(qp.C, x, precision=_HP)
    Einv = 1.0 / scaling.E
    Dinv = 1.0 / scaling.D
    cinv = 1.0 / scaling.c

    r_prim = jnp.maximum(
        _inf_norm(Einv * (Cx - z)), _inf_norm(scaling.D * (x - w))
    )
    # P applied through the factor when present (qp.apply_P): keeps the
    # dense P unread on the factored pipeline so XLA can eliminate its
    # construction altogether.
    Px = qp.apply_P(x)
    CTy = jnp.dot(y, qp.C, precision=_HP)
    dual_vec = Px + qp.q + CTy + mu
    r_dual = cinv * _inf_norm(Dinv * dual_vec)

    denom_p = jnp.max(jnp.array([
        _inf_norm(Einv * Cx), _inf_norm(Einv * z),
        _inf_norm(scaling.D * x), _inf_norm(scaling.D * w),
    ]))
    denom_d = cinv * jnp.max(jnp.array([
        _inf_norm(Dinv * Px), _inf_norm(Dinv * CTy),
        _inf_norm(Dinv * qp.q), _inf_norm(Dinv * mu),
    ]))

    eps_prim = params.eps_abs + params.eps_rel * denom_p
    eps_dual = params.eps_abs + params.eps_rel * denom_d
    return r_prim, r_dual, eps_prim, eps_dual, denom_p, denom_d


def _infeasibility(qp: CanonicalQP, scaling: Scaling, dx, dy, dmu,
                   params: SolverParams, l1w=None):
    """OSQP certificates from one-iteration increments (unscaled).

    ``l1w`` (scaled frame) is the native L1 term's per-variable weight:
    along a recession direction the nonsmooth term grows like
    ``sum l1w |dx|``, which must be added to the objective slope before
    declaring dual infeasibility (otherwise a problem bounded only by
    the L1 penalty is misreported as unbounded)."""
    dtype = dx.dtype
    # Unscaled increments
    dy_u = (1.0 / scaling.c) * scaling.E * dy
    dmu_u = (1.0 / scaling.c) * (1.0 / scaling.D) * dmu
    dx_u = scaling.D * dx

    norm_dy = jnp.maximum(_inf_norm(dy_u), _inf_norm(dmu_u))
    # Primal infeasibility: C' dy + dmu ~ 0 and support < 0
    l_un = qp.l / scaling.E
    u_un = qp.u / scaling.E
    lb_un = qp.lb * scaling.D
    ub_un = qp.ub * scaling.D
    # C_un' dy_u = D^-1 C_hat' E^-1 dy_u = (1/c) D^-1 (C_hat' dyhat)
    CTdy = (1.0 / scaling.D) * jnp.dot(dy, qp.C, precision=_HP) * (1.0 / scaling.c)
    pinf_resid = _inf_norm(CTdy + dmu_u)
    support = (
        _support(u_un, l_un, dy_u) + _support(ub_un, lb_un, dmu_u)
    )
    prim_infeas = (
        (norm_dy > params.eps_pinf)
        & (pinf_resid <= params.eps_pinf * norm_dy)
        & (support <= -params.eps_pinf * norm_dy)
    )

    # Dual infeasibility: P dx ~ 0, q'dx < 0, C dx in recession cone
    norm_dx = _inf_norm(dx_u)
    Pdx = (1.0 / scaling.c) * (1.0 / scaling.D) * qp.apply_P(dx)
    qdx = (1.0 / scaling.c) * jnp.dot(qp.q, dx, precision=_HP)
    if l1w is not None:
        # Unscaled L1 slope: sum_i w_i |D_i dx_i| = (1/c) sum_i l1w_i |dx_i|.
        qdx = qdx + (1.0 / scaling.c) * jnp.sum(l1w * jnp.abs(dx))
    Cdx = (1.0 / scaling.E) * jnp.dot(qp.C, dx, precision=_HP)
    tol = params.eps_dinf * norm_dx
    cone_ok = jnp.all(
        jnp.where(jnp.isfinite(u_un), Cdx <= tol, True)
        & jnp.where(jnp.isfinite(l_un), Cdx >= -tol, True)
    ) & jnp.all(
        jnp.where(jnp.isfinite(ub_un), dx_u <= tol, True)
        & jnp.where(jnp.isfinite(lb_un), dx_u >= -tol, True)
    )
    dual_infeas = (
        (norm_dx > params.eps_dinf)
        & (_inf_norm(Pdx) <= tol)
        & (qdx <= -tol)
        & cone_ok
    )
    return prim_infeas.astype(jnp.bool_), dual_infeas.astype(jnp.bool_), jnp.asarray(0, dtype)


def resolve_linsolve(params: SolverParams, qp: CanonicalQP) -> str:
    """Resolve ``params.linsolve`` against this problem's structure.

    Governs the ADMM segment's linear solve only. The polish chooses
    its factored path independently (on ``qp.Pf`` presence + dimension,
    ``qp/polish.py``) — the exact-pinning KKT solve is a win there
    regardless of which operator the segments used.
    """
    ls = params.linsolve
    if ls == "woodbury":
        # Explicit opt-in only — because it needs qp.Pf and its payoff
        # is regime-dependent. Round 2 (eq_scale 1e3) measured it NOT
        # to pay (refinement tripled per-iteration cost, straggler
        # lanes charged extra segments to the whole batch: 3.7 s vs
        # 95 ms for trinv); at the round-3 default rho_eq_scale=1.0
        # with refine=0 + check_interval=35 it *wins* on chip (35.0 ms
        # vs trinv's 62.6 ms on the north-star B=252, TE parity) and
        # is the bench's TPU headline config. The factored structure
        # also pays in the *polish* (exact pinning, no penalty
        # amplification), which uses it automatically whenever qp.Pf
        # is present — see qp.polish.
        if qp.Pf is None:
            raise ValueError(
                "linsolve='woodbury' requires the factored objective "
                "(qp.Pf with P = 2 Pf'Pf + diag(Pdiag))")
        return "woodbury"
    if ls == "auto":
        if jnp.dtype(qp.P.dtype) == jnp.float32:
            # f32 chol substitution stalls ADMM at production scale:
            # measured at n=500 (north-star shape) the cho_solve path's
            # primal residual floors at ~5e-3 — above eps — on CPU,
            # while the trinv apply (two HIGHEST-precision GEMVs with
            # the inverted factor) converges in 25 iterations with the
            # same K. f64 shows no such gap (both converge, chol is
            # cheaper), so chol remains the f64 host default.
            return "trinv"
        return "trinv" if jax.default_backend() == "tpu" else "chol"
    return ls


def factored_solve_pieces(Dv: jax.Array, V: jax.Array):
    """(inv_d, W) such that ``K^-1 r = inv_d r - W'(W r)`` for
    ``K = diag(Dv) + V'V`` — the raw Woodbury/capacitance apply. Shared
    by :func:`factored_spd_solve_operator` (XLA path) and the fused
    Pallas factored segment (``ops/admm_kernel.py``), which keeps
    exactly these two arrays VMEM-resident across a whole segment."""
    dtype = V.dtype
    k = V.shape[-2]
    hp = _HP
    inv_d = 1.0 / Dv
    Vd = V * inv_d[None, :]
    S = jnp.eye(k, dtype=dtype) + jnp.dot(Vd, V.T, precision=hp)
    Linv = blocked_triangular_inverse(jnp.linalg.cholesky(S))
    W = jnp.dot(Linv, Vd, precision=hp)
    return inv_d, W


def factored_spd_solve_operator(Dv: jax.Array, V: jax.Array,
                                refine_steps: int = 1):
    """Solve operator for the SPD matrix ``K = diag(Dv) + V' V``.

    Woodbury identity with the capacitance matrix
    ``S = I + (V D^-1) V'`` (k x k, k = V rows):

        K^-1 r = D^-1 r - W' (W r),   W = L_S^-1 (V D^-1),  S = L_S L_S'

    Every factorization-class op (Cholesky + triangular inverse) runs at
    k x k instead of n x n — for a least-squares objective over a
    T-observation window with m constraint rows, k = T + m, i.e.
    ~((T+m)/n)^3 of the dense-KKT FLOPs, and each application is two
    (k x n) MXU matvecs reading half the bytes of an n x n factor.

    The raw Woodbury apply cancels ``D^-1 r`` against the correction
    term, so its relative error scales with cond(K) * eps — enough to
    stall f32 ADMM (measured 100 vs 25 segments-north-star). Each
    ``refine_steps`` round of iterative refinement (residual via the
    factor form ``K x = D x + V'(V x)``, two extra matvec pairs)
    multiplies the error by that same factor, restoring trinv-grade
    accuracy for ~2x the (cheap) per-application cost.
    """
    inv_d, W = factored_solve_pieces(Dv, V)
    return factored_solve_from_pieces(Dv, V, inv_d, W, refine_steps)


def factored_solve_from_pieces(Dv, V, inv_d, W, refine_steps: int = 1):
    """Assemble the Woodbury solve closure from already-built pieces —
    callers that also need ``(inv_d, W)`` directly (the fused Pallas
    factored segment) build them once and share, instead of paying the
    k x k factorization twice per segment."""
    hp = _HP

    def base(rhs):
        t = jnp.dot(W, rhs, precision=hp)
        return rhs * inv_d - jnp.dot(t, W, precision=hp)

    def apply_K(x):
        return Dv * x + jnp.dot(jnp.dot(V, x, precision=hp), V, precision=hp)

    def solve(rhs):
        x = base(rhs)
        for _ in range(refine_steps):
            x = x + base(rhs - apply_K(x))
        return x

    return solve


def blocked_triangular_inverse(L: jax.Array,
                               threshold: int = 192) -> jax.Array:
    """Explicit inverse of a lower-triangular ``L`` by block recursion.

    The stock n-RHS ``solve_triangular`` runs an n-step substitution
    whose wall-clock on TPU scales with the step count, not the FLOPs
    (measured 13.4 ms at n=500 over a 252-problem batch — 2.4 TFLOP/s).
    The 2x2 block identity

        [[L11, 0], [L21, L22]]^-1
            = [[L11^-1, 0], [-L22^-1 L21 L11^-1, L22^-1]]

    halves the substitution depth per level and moves the rest to MXU
    matmuls: the two diagonal-block inverses are *stacked into one
    batched* ``solve_triangular`` (the second block zero-padded with a
    unit diagonal, which inverts exactly), so each recursion level costs
    one half-size substitution plus two matmuls. Below ``threshold``
    the plain substitution wins and the recursion stops. Exact in
    exact arithmetic; parity with the flat substitution is pinned by
    tests/test_admm.py::test_blocked_triangular_inverse_matches_flat.
    """
    from jax.scipy.linalg import solve_triangular

    n = L.shape[-1]
    dtype = L.dtype
    if n <= threshold:
        eye = jnp.broadcast_to(jnp.eye(n, dtype=dtype), L.shape)
        return solve_triangular(L, eye, lower=True)

    n1 = (n + 1) // 2     # >= n - n1, so both blocks fit in (n1, n1)
    n2 = n - n1
    hp = _HP
    L11 = L[..., :n1, :n1]
    L21 = L[..., n1:, :n1]
    L22 = L[..., n1:, n1:]

    pad = n1 - n2
    L22p = jnp.zeros(L.shape[:-2] + (n1, n1), dtype)
    L22p = L22p.at[..., :n2, :n2].set(L22)
    if pad:
        L22p = L22p.at[..., n2:, n2:].set(jnp.eye(pad, dtype=dtype))

    stacked = jnp.stack([L11, L22p], axis=-3)       # (..., 2, n1, n1)
    invs = blocked_triangular_inverse(stacked, threshold)
    inv11 = invs[..., 0, :, :]
    inv22 = invs[..., 1, :n2, :n2]
    inv21 = -jnp.matmul(
        jnp.matmul(inv22, L21, precision=hp), inv11, precision=hp)

    out = jnp.zeros_like(L)
    out = out.at[..., :n1, :n1].set(inv11)
    out = out.at[..., n1:, :n1].set(inv21)
    out = out.at[..., n1:, n1:].set(inv22)
    return out


def admm_init(qp: CanonicalQP,
              params: SolverParams,
              x0: Optional[jax.Array] = None,
              y0: Optional[jax.Array] = None) -> ADMMCarry:
    """Build the segment-loop carry for one *scaled* problem.

    ``x0``/``y0`` warm starts are in the scaled frame. The returned
    carry is advanced by :func:`admm_segment_step`; :func:`admm_solve`
    is exactly a ``lax.while_loop`` of that step over this value, so a
    driver that reads per-lane status at each boundary (and repacks or
    retires lanes) runs the identical per-lane program.
    """
    dtype = qp.P.dtype
    n, m = qp.n, qp.m
    x_init = jnp.zeros(n, dtype) if x0 is None else x0
    y_init = jnp.zeros(m, dtype) if y0 is None else y0
    z_init = jnp.dot(qp.C, x_init, precision=_HP)
    w_init = jnp.clip(x_init, qp.lb, qp.ub)

    # ring_size is static (a hashable SolverParams field), so the
    # default 0 traces the exact pre-telemetry program (ring leaves
    # stay None = empty subtrees).
    ring_size = params.ring_size
    init = ADMMState(
        x=x_init, z=z_init, w=w_init, y=y_init, mu=jnp.zeros(n, dtype),
        rho_bar=jnp.asarray(params.rho0, dtype),
        iters=jnp.asarray(0, jnp.int32),
        status=jnp.asarray(Status.RUNNING, jnp.int32),
        prim_res=jnp.asarray(jnp.inf, dtype),
        dual_res=jnp.asarray(jnp.inf, dtype),
        ring_prim=jnp.full((ring_size,), jnp.inf, dtype)
        if ring_size else None,
        ring_dual=jnp.full((ring_size,), jnp.inf, dtype)
        if ring_size else None,
        ring_rho=jnp.zeros((ring_size,), dtype) if ring_size else None,
    )
    return ADMMCarry(
        state=init,
        anchor=(init.x, init.z, init.w, init.y, init.mu),
        k_anchor=jnp.asarray(0, jnp.int32),
        res_anchor=jnp.asarray(jnp.inf, dtype),
    )


class _SegmentPlan(NamedTuple):
    """Static (host-side) decisions for one segment program: the
    resolved linear-solve mode, the Pallas opt-in, and the f32
    adaptive-rho clamp. Derived only from params + problem *structure*
    (shapes/dtypes), so it is identical under jit/vmap tracing."""

    linsolve: str
    use_pallas: bool
    rho_lo: float
    rho_hi: float


def _segment_plan(qp: CanonicalQP, params: SolverParams,
                  warn: bool = False) -> _SegmentPlan:
    """Resolve the backend/linsolve/clamp decisions ``admm_solve`` has
    always made up front. ``warn=False`` (the steppable API) keeps the
    resolution silent — per-segment callers would otherwise emit the
    same warning every boundary."""
    dtype = qp.P.dtype
    n, m = qp.n, qp.m
    # Estimated VMEM footprint of the fused segment. Dense forms hold
    # the explicit KKT inverse (n x n) + the constraint matrix (m x n);
    # the factored (woodbury) form holds the capacitance pieces
    # W (k x n), Y0 (n x m), Ginv (m x m) instead of any n x n array —
    # which is exactly why it still fits where the dense kernel OOMs.
    # Either way ~16 working vectors ride along, and the kernel pads
    # every dim up to lane multiples of 128 (ops/admm_kernel.py), so
    # the estimate must use the padded sizes.
    linsolve = resolve_linsolve(params, qp)
    n_pad = ((max(n, 1) + 127) // 128) * 128
    m_pad = ((max(m, 1) + 127) // 128) * 128
    if linsolve == "woodbury":
        k_pad = ((max(qp.Pf.shape[-2], 1) + 127) // 128) * 128
        # refine >= 1 additionally keeps the factor V resident.
        n_kxn = 2 if params.woodbury_refine else 1
        vmem_bytes = (
            (n_kxn * k_pad * n_pad + 2 * m_pad * n_pad + m_pad * m_pad
             + 16 * (n_pad + m_pad + k_pad))
            * jnp.dtype(dtype).itemsize
        )
    else:
        vmem_bytes = (
            (n_pad * n_pad + m_pad * n_pad + 16 * (n_pad + m_pad))
            * jnp.dtype(dtype).itemsize
        )
    fits_vmem = vmem_bytes <= params.vmem_limit_mb * 2**20
    # The fused kernel is opt-in only. Its trinv mode matches the XLA
    # path's accuracy, but measured wall-clock is at parity on the
    # north-star batch (the iteration stage is latency-bound there, not
    # HBM-bound — BASELINE.md), so backend="auto" keeps the simpler XLA
    # path; the kernel's residency advantage grows with n and iteration
    # count. (Its non-trinv mode also carries the explicit-f32-K^-1
    # accuracy penalty: measured 100 vs 25 iterations.)
    use_pallas = params.backend == "pallas" and not params.halpern
    if warn and params.backend == "pallas" and params.halpern:
        warnings.warn(
            "backend='pallas' does not implement Halpern anchoring; "
            "running the XLA segment instead (halpern=False restores "
            "the fused kernel)", stacklevel=3)
    if warn and use_pallas:
        if not fits_vmem:
            warnings.warn(
                f"backend='pallas' requested but the estimated VMEM footprint "
                f"({vmem_bytes / 2**20:.1f} MB for n={n}, m={m}) exceeds "
                f"vmem_limit_mb={params.vmem_limit_mb}; the kernel may fail "
                f"to compile or spill. backend='auto' would use the XLA path.",
                stacklevel=3,
            )
        if jax.default_backend() != "tpu":
            warnings.warn(
                "backend='pallas' on a non-TPU host runs the kernel in "
                "interpret mode (orders of magnitude slower than the XLA "
                "path); use backend='auto' unless this is a parity test.",
                stacklevel=3,
            )
    use_inverse = use_pallas or linsolve in ("inverse", "trinv", "woodbury")

    # Every explicit-inverse linear solve — the Pallas kernel,
    # linsolve="inverse", and linsolve="trinv" (the TPU default) —
    # loses accuracy with conditioning; K carries rho_eq_scale * rho on
    # equality rows, so in f32 the adaptive-rho clamp must stay inside
    # what the inverted factor can represent. [1e-3, 1e2] keeps cond(K)
    # within f32 range on Ruiz-equilibrated problems (OSQP's wider f64
    # clamp makes the inverse diverge on TPU). Only the per-iteration
    # cho_solve path (linsolve="chol") and any f64 solve keep the
    # caller's clamp.
    if use_inverse and jnp.dtype(dtype) == jnp.float32:
        rho_lo = max(params.rho_min, 1e-3)
        rho_hi = min(params.rho_max, 1e2)
        defaults = SolverParams()
        caller_tuned = (params.rho_min != defaults.rho_min
                        or params.rho_max != defaults.rho_max)
        if warn and caller_tuned and (rho_lo != params.rho_min
                                      or rho_hi != params.rho_max):
            warnings.warn(
                f"f32 inverse-based linear solve narrows the adaptive-rho "
                f"clamp from [{params.rho_min:g}, {params.rho_max:g}] to "
                f"[{rho_lo:g}, {rho_hi:g}] (wider conditioning exceeds what "
                f"the refined f32 inverse can represent); set "
                f"linsolve='chol' and backend='xla' to keep the requested "
                f"bounds.",
                stacklevel=3,
            )
    else:
        rho_lo, rho_hi = params.rho_min, params.rho_max
    return _SegmentPlan(linsolve=linsolve, use_pallas=use_pallas,
                        rho_lo=rho_lo, rho_hi=rho_hi)


def _make_segment(qp: CanonicalQP,
                  scaling: Scaling,
                  params: SolverParams,
                  l1w: jax.Array,
                  l1c: jax.Array,
                  plan: _SegmentPlan,
                  track_l1: bool):
    """Build the one-segment transition ``ADMMCarry -> ADMMCarry``:
    ``check_interval`` iterations (with the Cholesky/capacitance
    factorization amortized across them), one residual check, the
    status/adaptive-rho/ring/Halpern updates. Shared verbatim by
    :func:`admm_solve`'s while_loop and :func:`admm_segment_step`, so
    the hoisted loop cannot drift from the fused one. ``track_l1``
    marks a live native L1 term (the dual-infeasibility certificate
    must include its slope)."""
    dtype = qp.P.dtype
    n = qp.n
    sigma = jnp.asarray(params.sigma, dtype)
    alpha = jnp.asarray(params.alpha, dtype)
    linsolve, use_pallas = plan.linsolve, plan.use_pallas
    rho_lo, rho_hi = plan.rho_lo, plan.rho_hi
    ring_size = params.ring_size

    def one_iteration(carry, solve, rho, rho_b):
        x, z, w, y, mu = carry
        rhs = (sigma * x - qp.q + jnp.dot(rho * z - y, qp.C, precision=_HP)
               + (rho_b * w - mu))
        xt = solve(rhs)
        zt = jnp.dot(qp.C, xt, precision=_HP)

        x_new = alpha * xt + (1 - alpha) * x

        z_arg = alpha * zt + (1 - alpha) * z + y / rho
        z_new = jnp.clip(z_arg, qp.l, qp.u)
        y_new = y + rho * (alpha * zt + (1 - alpha) * z - z_new)

        w_arg = alpha * xt + (1 - alpha) * w + mu / rho_b
        w_new = l1_box_prox(w_arg, qp.lb, qp.ub, l1w / rho_b, l1c)
        mu_new = mu + rho_b * (alpha * xt + (1 - alpha) * w - w_new)
        return (x_new, z_new, w_new, y_new, mu_new)

    def refined_inverse(K, chol):
        """Explicit K^-1 with one Newton step: Kinv <- Kinv (2I - K Kinv).

        The plain f32 inverse carries ~cond(K)*eps relative error, which
        degrades the ADMM fixed point enough to cost extra segments
        (measured: 100 vs 25 iterations on the north-star problem); one
        Newton refinement squares the error down to the f32 floor for
        two extra n^3 matmuls — MXU work that amortizes over the
        segment."""
        eye = jnp.eye(n, dtype=dtype)
        Kinv = cho_solve(chol, eye)
        hp = _HP
        return jnp.dot(
            Kinv, 2.0 * eye - jnp.dot(K, Kinv, precision=hp), precision=hp
        )

    def triangular_inverse(K):
        """L^-1 for K = L L^T. Applying K^-1 = L^-T L^-1 as two dense
        matvecs costs ~cond(L)*eps = sqrt(cond(K))*eps per solve — an
        order better than the explicit K^-1, which is what keeps the
        chol path's convergence rate. One copy shared by the XLA and
        Pallas branches so the two cannot drift (bit-parity is pinned
        by TestTriangularKernel)."""
        return blocked_triangular_inverse(jnp.linalg.cholesky(K))

    def segment(loop_carry: ADMMCarry) -> ADMMCarry:
        state, anchor, k_anchor, res_anchor = loop_carry
        rho, rho_b = _rho_vectors(qp, state.rho_bar, params)
        if params.rho_l1_scale != 1.0:
            # Extra step-size weight on the variables carrying a native
            # L1 term (LAD's free residual block): their only
            # regularizer is the prox itself, and up-weighting its step
            # accelerates the nonsmooth block without touching the
            # boxed variables. Production LAD (N=500, T=252): 4,200 ->
            # 3,400 iterations at a better objective gap at the
            # promoted x10 (scripts/lad_accel_sweep.py round-5 notes).
            rho_b = jnp.where(l1w > 0, rho_b * params.rho_l1_scale,
                              rho_b)
        if linsolve == "woodbury":
            # K = diag(sigma + Pdiag + rho_b) + 2 Pf'Pf + C' diag(rho) C.
            # The factor block goes through the capacitance matrix; the
            # m constraint rows are eliminated by their own (tiny) Schur
            # complement G = diag(1/rho) + C K0^-1 C' instead of being
            # stacked into V — their rho carries the rho_eq_scale
            # up-weighting (1e3x on equality rows), which would square
            # the capacitance conditioning and stall the worst lanes
            # (measured: 26/252 north-star dates at max_iter when
            # stacked). The dense n x n K is never materialized.
            pd = 0.0 if qp.Pdiag is None else qp.Pdiag
            Dv = sigma + pd + rho_b
            V = jnp.sqrt(jnp.asarray(2.0, dtype)) * qp.Pf
            # Pieces built ONCE per segment and shared between the XLA
            # solve closure and (on the pallas path) the fused kernel —
            # XLA CSE is not guaranteed to merge two copies of the
            # control-flow-bearing blocked triangular inverse.
            inv_d_w, W_w = factored_solve_pieces(Dv, V)
            psolve0 = factored_solve_from_pieces(
                Dv, V, inv_d_w, W_w, refine_steps=params.woodbury_refine)
            hp = _HP
            Y0 = jax.vmap(psolve0, in_axes=1, out_axes=1)(qp.C.T)  # (n, m)
            G = jnp.diag(1.0 / rho) + jnp.dot(qp.C, Y0, precision=hp)

            def solve(rhs):
                x0 = psolve0(rhs)
                t = jnp.linalg.solve(G, jnp.dot(qp.C, x0, precision=hp))
                return x0 - jnp.dot(Y0, t, precision=hp)

            K = None
        else:
            K = (
                qp.P
                + sigma * jnp.eye(n, dtype=dtype)
                + jnp.dot(qp.C.T * rho, qp.C, precision=_HP)
                + jnp.diag(rho_b)
            )

        if use_pallas:
            # Fused segment with the linear-solve operator VMEM-resident
            # across the whole check_interval (see
            # porqua_tpu.ops.admm_kernel). With linsolve="woodbury" the
            # resident state is the capacitance pieces (W, inv_d, Y0,
            # Ginv; refine>=1 additionally keeps the factor V and Dv
            # for in-kernel iterative refinement) — ~((T+m) x n)
            # instead of n x n, so this form fits VMEM in the regimes
            # where the dense kernel OOMs, and saves the XLA path's two
            # W re-reads per iteration. With linsolve="trinv" the
            # resident matrix is L^-1 applied twice — the same accuracy
            # story as the XLA trinv path; otherwise the refined
            # explicit K^-1 once.
            from porqua_tpu.ops.admm_kernel import (admm_segment,
                                                    admm_segment_factored)

            if linsolve == "woodbury":
                # Ginv is explicit (m x m, tiny): the in-kernel row-
                # Schur correction becomes one matvec. The XLA path LU-
                # solves G per iteration instead; for the m's this path
                # serves the explicit-inverse error is negligible.
                Ginv = jnp.linalg.inv(G)
                x, z, w, y, mu, dx, dy, dmu = admm_segment_factored(
                    W_w, inv_d_w, Y0, Ginv, V, Dv, qp.C, qp.q, qp.l,
                    qp.u, qp.lb, qp.ub, rho, rho_b, l1w, l1c,
                    state.x, state.z, state.w, state.y, state.mu,
                    sigma=params.sigma, alpha=params.alpha,
                    n_iters=params.check_interval,
                    interpret=jax.default_backend() != "tpu",
                    refine_steps=params.woodbury_refine,
                )
            else:
                if linsolve == "trinv":
                    op = triangular_inverse(K)
                    triangular = True
                else:
                    op = refined_inverse(K, cho_factor(K))
                    triangular = False
                x, z, w, y, mu, dx, dy, dmu = admm_segment(
                    op, qp.C, qp.q, qp.l, qp.u, qp.lb, qp.ub, rho, rho_b,
                    l1w, l1c,
                    state.x, state.z, state.w, state.y, state.mu,
                    sigma=params.sigma, alpha=params.alpha,
                    n_iters=params.check_interval,
                    interpret=jax.default_backend() != "tpu",
                    triangular=triangular,
                )
        else:
            hp = _HP
            if linsolve == "woodbury":
                pass  # `solve` built above with the eq-row Schur split
            elif linsolve == "trinv":
                Linv = triangular_inverse(K)
                solve = lambda rhs: jnp.dot(
                    jnp.dot(Linv, rhs, precision=hp), Linv, precision=hp)
            elif linsolve == "inverse":
                Kinv = refined_inverse(K, cho_factor(K))
                # Apply as rhs @ Kinv (the transpose side), matching the
                # Pallas kernel: the one-sided Newton refinement leaves
                # the transpose application markedly more accurate in
                # f32 (measured 40x residual difference on the
                # north-star problem), and K^-1 is symmetric in exact
                # arithmetic so the two sides agree mathematically.
                solve = lambda rhs: jnp.dot(rhs, Kinv, precision=hp)
            else:
                chol = cho_factor(K)
                solve = lambda rhs: cho_solve(chol, rhs)

            carry0 = (state.x, state.z, state.w, state.y, state.mu)
            if params.halpern:
                # Restarted Halpern: pull toward the carried anchor
                # with weight 1/(k+2), k counting iterations since the
                # last restart (continuing across segments — the
                # restart decision lives at the segment boundary,
                # below). Two extra vector axpys per iteration — noise
                # next to the linear solve.
                def body(j, carry):
                    t = one_iteration(carry, solve, rho, rho_b)
                    lam = 1.0 / (jnp.asarray(k_anchor + j, dtype) + 2.0)
                    return tuple(lam * a + (1.0 - lam) * tn
                                 for a, tn in zip(anchor, t))
            else:
                def body(_, carry):
                    return one_iteration(carry, solve, rho, rho_b)

            # Run check_interval - 1 iterations, then one more recording deltas
            carry = jax.lax.fori_loop(
                0, params.check_interval - 1, body, carry0
            )
            carry_next = body(params.check_interval - 1, carry)
            x, z, w, y, mu = carry_next
            dx = x - carry[0]
            dy = y - carry[3]
            dmu = mu - carry[4]

        r_prim, r_dual, eps_p, eps_d, denom_p, denom_d = _residuals(
            qp, scaling, x, z, w, y, mu, params
        )
        solved = (r_prim <= eps_p) & (r_dual <= eps_d)
        p_inf, d_inf, _ = _infeasibility(
            qp, scaling, dx, dy, dmu, params,
            l1w=l1w if track_l1 else None,
        )

        status = jnp.where(
            solved,
            Status.SOLVED,
            jnp.where(
                p_inf, Status.PRIMAL_INFEASIBLE,
                jnp.where(d_inf, Status.DUAL_INFEASIBLE, Status.RUNNING),
            ),
        ).astype(jnp.int32)

        # Adaptive rho: balance scaled primal/dual residual ratios
        if params.adaptive_rho:
            ratio = jnp.sqrt(
                (r_prim / jnp.maximum(denom_p, 1e-12))
                / jnp.maximum(r_dual / jnp.maximum(denom_d, 1e-12), 1e-12)
            )
            rho_new = jnp.clip(state.rho_bar * ratio, rho_lo, rho_hi)
        else:
            rho_new = state.rho_bar

        if ring_size:
            # Segment index = iters/check_interval (iters advances by
            # exactly check_interval per segment); the ring write is a
            # device-side dynamic-index store — no host participation,
            # which is the whole point (GC002/GC102 enforce it).
            slot = jax.lax.rem(state.iters // params.check_interval,
                               jnp.asarray(ring_size, jnp.int32))
            ring_prim = state.ring_prim.at[slot].set(r_prim)
            ring_dual = state.ring_dual.at[slot].set(r_dual)
            ring_rho = state.ring_rho.at[slot].set(state.rho_bar)
        else:
            ring_prim = ring_dual = ring_rho = None
        new_state = ADMMState(
            x=x, z=z, w=w, y=y, mu=mu,
            rho_bar=rho_new,
            iters=state.iters + params.check_interval,
            status=status,
            prim_res=r_prim,
            dual_res=r_dual,
            ring_prim=ring_prim,
            ring_dual=ring_dual,
            ring_rho=ring_rho,
        )
        if params.halpern:
            # HPR-LP-style adaptive restart: re-anchor on sufficient
            # decrease of the scaled residual (factor 1/4 — the rate
            # the O(1/k) bound can actually deliver between restarts),
            # or after a long window without one (a stale anchor far
            # from the solution slows the pull). Measured against the
            # fixed per-segment restart in scripts/lad_accel_sweep.py.
            res_now = jnp.maximum(
                r_prim / jnp.maximum(denom_p, 1e-12),
                r_dual / jnp.maximum(denom_d, 1e-12))
            k_new = k_anchor + params.check_interval
            restart = ((res_now <= params.halpern_decrease * res_anchor)
                       | (k_new >= params.halpern_max_windows
                          * params.check_interval))
            cur = (x, z, w, y, mu)
            anchor = tuple(jnp.where(restart, c, a)
                           for c, a in zip(cur, anchor))
            k_anchor = jnp.where(restart, 0, k_new).astype(jnp.int32)
            res_anchor = jnp.where(restart, res_now, res_anchor)
        return ADMMCarry(state=new_state, anchor=anchor,
                         k_anchor=k_anchor, res_anchor=res_anchor)

    return segment


def admm_segment_step(carry: ADMMCarry,
                      qp: CanonicalQP,
                      scaling: Scaling,
                      params: SolverParams,
                      l1_weight: Optional[jax.Array] = None,
                      l1_center: Optional[jax.Array] = None):
    """Advance one residual-check segment; returns ``(carry,
    per_lane_status)``.

    The steppable half of :func:`admm_solve`: ``check_interval``
    iterations, one on-device residual/infeasibility check, the
    adaptive-rho / convergence-ring / Halpern-restart updates. The
    returned status is ``carry.state.status`` (a :class:`Status` code,
    per lane once vmapped) so batch orchestration living *above* the
    loop — compaction, continuous batching — can retire converged
    lanes at segment boundaries. Note the step itself never flips
    ``RUNNING`` to ``MAX_ITER``: the iteration budget is the
    orchestrator's policy (``admm_solve`` applies it after its
    while_loop; drivers apply a per-lane segment budget instead).
    """
    dtype = qp.P.dtype
    n = qp.n
    l1w = jnp.zeros(n, dtype) if l1_weight is None else l1_weight
    l1c = jnp.zeros(n, dtype) if l1_center is None else l1_center
    plan = _segment_plan(qp, params, warn=False)
    segment = _make_segment(qp, scaling, params, l1w, l1c, plan,
                            track_l1=l1_weight is not None)
    new = segment(carry)
    return new, new.state.status


def admm_solve(qp: CanonicalQP,
               scaling: Scaling,
               params: SolverParams,
               x0: Optional[jax.Array] = None,
               y0: Optional[jax.Array] = None,
               l1_weight: Optional[jax.Array] = None,
               l1_center: Optional[jax.Array] = None) -> ADMMState:
    """Run the ADMM loop on one *scaled* problem. Returns the final state.

    ``x0``/``y0`` warm starts are in the scaled frame (callers go through
    :func:`porqua_tpu.qp.solve.solve_qp`, which handles scaling).

    ``l1_weight``/``l1_center`` (scaled frame, per-variable) add a
    nonsmooth objective term sum_i l1_weight_i * |x_i - l1_center_i|
    handled *natively* by the w-block prox — the box projection becomes
    a clipped shifted soft-threshold (in 1-D,
    ``prox_{I_[lb,ub] + lam|.-c|} = clip(c + soft(v - c, lam))`` since a
    convex 1-D objective restricted to an interval attains its minimum
    at the projection of the unconstrained minimizer). This is the
    static-shape TPU alternative to the reference's dimension-expanding
    turnover-cost linearization (reference ``qp_problems.py:120-157``,
    mirrored by :func:`porqua_tpu.qp.lift.lift_turnover_objective`).

    Structurally this is now a thin ``lax.while_loop`` over the
    steppable API (:func:`admm_init` + the segment transition
    :func:`admm_segment_step` advances), so batch drivers that hoist
    the loop to the host run the identical per-lane program.
    """
    dtype = qp.P.dtype
    n = qp.n
    l1w = jnp.zeros(n, dtype) if l1_weight is None else l1_weight
    l1c = jnp.zeros(n, dtype) if l1_center is None else l1_center
    plan = _segment_plan(qp, params, warn=True)
    segment = _make_segment(qp, scaling, params, l1w, l1c, plan,
                            track_l1=l1_weight is not None)

    def cond(loop_carry: ADMMCarry):
        state = loop_carry.state
        return (state.status == Status.RUNNING) & (state.iters < params.max_iter)

    init_carry = admm_init(qp, params, x0, y0)
    final = jax.lax.while_loop(cond, segment, init_carry).state
    final = final._replace(
        status=jnp.where(
            final.status == Status.RUNNING, Status.MAX_ITER, final.status
        ).astype(jnp.int32)
    )
    return final
