"""Restarted primal-dual hybrid gradient (PDHG) backend.

The second first-order solver behind ``SolverParams(method="pdhg")`` —
a restarted PDHG for the same interval-form QP the ADMM core solves
("A Practical and Optimal First-Order Method for Large-Scale Convex
Quadratic Programming", arXiv:2311.07710; the restart machinery is the
PDLP recipe):

    minimize 0.5 x'Px + q'x   s.t.  l <= Cx <= u,  lb <= x <= ub

One iteration (Condat-Vu form — the quadratic enters through its
gradient, the box/L1 block through its prox, the C-block dual through
the Moreau decomposition of the interval indicator's conjugate):

    v     = x_k - tau (P x_k + q + C' y_k)
    x_+   = prox_{tau(I_[lb,ub] + l1)}(v)        # l1_box_prox
    ytil  = y_k + sigma C (2 x_+ - x_k)
    z_+   = clip(ytil / sigma, l, u)             # constraint activity
    y_+   = ytil - sigma z_+                     # Moreau: prox of h*
    mu_+  = (v - x_+) / tau                      # in N_box + d|l1| at x_+

with tau = 1/(L_P + omega ||C||), sigma = omega / ||C|| (the Condat-Vu
step condition 1/tau - sigma ||C||^2 >= L_P holds with slack L_P/2);
the spectral estimates come from a one-time power iteration at
``pdhg_init``. No factorization anywhere: a segment is
``check_interval`` rounds of two C-matvecs plus one P-apply — pure
MXU/HBM-streaming work, which is exactly the regime where this backend
can beat ADMM's per-segment n^3/3 factorization on wall-clock.

**State mapping.** The iterate is carried as the same
:class:`~porqua_tpu.qp.admm.ADMMState` the ADMM backend uses — with
``w = x`` (always box-feasible post-prox) and ``mu`` the prox residual
above — so the *shared* residual measure
(:func:`porqua_tpu.qp.admm._residuals`), the shared finalize
(MAX_ITER + polish fallback, ``qp/solve.py``), lane selection,
compaction's repack, continuous batching, and the harvest bridge all
work unmodified: at a PDHG fixed point ``P x + q + C' y + mu = 0`` and
``Cx = z`` exactly, so the OSQP-style residuals measure true KKT error
for this backend too. ``state.rho_bar`` carries the primal weight
omega.

**Restarts.** At every residual check (segment boundary) the solver
evaluates the normalized residual of BOTH the current iterate and a
one-iteration step from the restart-window average, restarting — it
adopts the better candidate and resets the window — on sufficient
decay (``pdhg_restart_decrease`` x the residual at the last restart)
or forcibly after ``pdhg_restart_max_windows`` checks without one.
``adaptive_rho`` rebalances omega at restarts. The convergence rings
record ``(prim_res, dual_res, restart_count)`` — the third ring slot
holds the cumulative restart count instead of ADMM's rho, which is
how ``obs/rings.py`` trajectories expose the restart behavior the
diagnosis needs (the decoder is field-name agnostic).

Infeasibility certificates reuse the shared OSQP increment tests on
the last iteration's deltas (PDLP detects certificates from iterate
differences the same way).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from porqua_tpu.qp.admm import (
    ADMMState,
    SolverParams,
    Status,
    _infeasibility,
    _residuals,
    l1_box_prox,
)
from porqua_tpu.qp.canonical import HP as _HP, CanonicalQP
from porqua_tpu.qp.ruiz import Scaling

__all__ = ["PDHGCarry", "pdhg_init", "pdhg_segment_step", "pdhg_solve"]

#: Primal-weight clamp (same role as ADMM's f32 adaptive-rho clamp:
#: keep the step-size ratio inside what f32 arithmetic supports).
_OMEGA_LO = 1e-3
_OMEGA_HI = 1e3

#: Norm-estimate floor — a neutral/padded lane can carry an all-zero C
#: block, and sigma = omega/||C|| must stay finite on it.
_NORM_FLOOR = 1e-6


class PDHGCarry(NamedTuple):
    """The PDHG segment-loop carry — same contract as
    :class:`~porqua_tpu.qp.admm.ADMMCarry` (``.state`` is an
    ``ADMMState``; everything else is per-lane scalars/vectors), so the
    batch orchestration layers treat the two backends uniformly.
    """

    state: ADMMState
    # Restart-window running sums of the primal/dual iterates (the
    # averaged candidate is (avg_x / n_avg, avg_y / n_avg)).
    avg_x: jax.Array       # (n,)
    avg_y: jax.Array       # (m,)
    n_avg: jax.Array       # () iterates accumulated since last restart
    k_restart: jax.Array   # () int32, iterations since last restart
    res_restart: jax.Array  # () normalized residual at last restart
    restart_count: jax.Array  # () int32, cumulative restarts
    # Spectral estimates fixed at init (power iteration): ||P||_2 and
    # ||C||_2 upper estimates — they set tau/sigma every segment.
    norm_P: jax.Array      # ()
    norm_C: jax.Array      # ()


def _norm2(v):
    return jnp.sqrt(jnp.sum(v * v)) if v.size else jnp.asarray(0.0, v.dtype)


def _power_norm(matvec, n: int, dtype, iters: int) -> jax.Array:
    """Largest-eigenvalue estimate of a symmetric PSD operator by
    deterministic power iteration (fixed start, fixed count — fully
    traceable, no data-dependent control flow). Returns an estimate
    inflated by a small safety margin: power iteration converges from
    below, and PDHG's step condition needs an upper bound."""
    v0 = jnp.full((n,), 1.0, dtype) / jnp.sqrt(jnp.asarray(n, dtype))

    def body(_, v):
        w = matvec(v)
        return w / jnp.maximum(_norm2(w), _NORM_FLOOR)

    v = jax.lax.fori_loop(0, iters, body, v0)
    lam = _norm2(matvec(v))  # ||Av||_2 with ||v|| ~= 1, A sym PSD
    return 1.1 * lam


def pdhg_init(qp: CanonicalQP,
              params: SolverParams,
              x0: Optional[jax.Array] = None,
              y0: Optional[jax.Array] = None) -> PDHGCarry:
    """Build the segment-loop carry for one *scaled* problem — the PDHG
    twin of :func:`porqua_tpu.qp.admm.admm_init` (warm starts in the
    scaled frame, rings initialized iff ``params.ring_size``)."""
    dtype = qp.q.dtype
    n, m = qp.n, qp.m
    x_init = jnp.zeros(n, dtype) if x0 is None else x0
    y_init = jnp.zeros(m, dtype) if y0 is None else y0
    x_init = jnp.clip(x_init, qp.lb, qp.ub)
    z_init = jnp.dot(qp.C, x_init, precision=_HP)

    norm_P = _power_norm(qp.apply_P, n, dtype, params.pdhg_power_iters)
    norm_C = jnp.sqrt(_power_norm(
        lambda v: jnp.dot(jnp.dot(qp.C, v, precision=_HP), qp.C,
                          precision=_HP),
        n, dtype, params.pdhg_power_iters))
    norm_C = jnp.maximum(norm_C, jnp.asarray(_NORM_FLOOR, dtype))

    ring_size = params.ring_size
    state = ADMMState(
        x=x_init, z=z_init, w=x_init, y=y_init, mu=jnp.zeros(n, dtype),
        rho_bar=jnp.asarray(params.pdhg_omega0, dtype),
        iters=jnp.asarray(0, jnp.int32),
        status=jnp.asarray(Status.RUNNING, jnp.int32),
        prim_res=jnp.asarray(jnp.inf, dtype),
        dual_res=jnp.asarray(jnp.inf, dtype),
        ring_prim=jnp.full((ring_size,), jnp.inf, dtype)
        if ring_size else None,
        ring_dual=jnp.full((ring_size,), jnp.inf, dtype)
        if ring_size else None,
        ring_rho=jnp.zeros((ring_size,), dtype) if ring_size else None,
    )
    return PDHGCarry(
        state=state,
        avg_x=jnp.zeros(n, dtype),
        avg_y=jnp.zeros(m, dtype),
        n_avg=jnp.asarray(0.0, dtype),
        k_restart=jnp.asarray(0, jnp.int32),
        res_restart=jnp.asarray(jnp.inf, dtype),
        restart_count=jnp.asarray(0, jnp.int32),
        norm_P=norm_P.astype(dtype),
        norm_C=norm_C.astype(dtype),
    )


def _make_pdhg_segment(qp: CanonicalQP,
                       scaling: Scaling,
                       params: SolverParams,
                       l1w: jax.Array,
                       l1c: jax.Array,
                       track_l1: bool):
    """Build the one-segment transition ``PDHGCarry -> PDHGCarry`` —
    the structural twin of ``admm._make_segment``: ``check_interval``
    iterations, one residual check, status / restart / omega / ring
    updates. Shared verbatim by :func:`pdhg_solve`'s while_loop and
    :func:`pdhg_segment_step` so the hoisted loop cannot drift."""
    dtype = qp.q.dtype
    ring_size = params.ring_size
    tiny = jnp.asarray(1e-12, dtype)

    def one_iteration(x, y, tau, sigma):
        grad = (qp.apply_P(x) + qp.q
                + jnp.dot(y, qp.C, precision=_HP))
        v = x - tau * grad
        x_new = l1_box_prox(v, qp.lb, qp.ub, tau * l1w, l1c)
        ytil = y + sigma * jnp.dot(qp.C, 2.0 * x_new - x, precision=_HP)
        z_new = jnp.clip(ytil / sigma, qp.l, qp.u)
        y_new = ytil - sigma * z_new
        mu_new = (v - x_new) / tau
        return x_new, y_new, z_new, mu_new

    def segment(carry: PDHGCarry) -> PDHGCarry:
        state = carry.state
        omega = state.rho_bar
        tau = 1.0 / (carry.norm_P + omega * carry.norm_C)
        sigma = omega / carry.norm_C

        def body(_, c):
            x, y, sx, sy = c
            x2, y2, _, _ = one_iteration(x, y, tau, sigma)
            return (x2, y2, sx + x2, sy + y2)

        c0 = (state.x, state.y,
              jnp.zeros_like(state.x), jnp.zeros_like(state.y))
        c = jax.lax.fori_loop(0, params.check_interval - 1, body, c0)
        x_prev, y_prev, sx, sy = c
        x, y, z, mu = one_iteration(x_prev, y_prev, tau, sigma)
        sx = sx + x
        sy = sy + y
        dx = x - x_prev
        dy = y - y_prev
        dmu = mu - state.mu

        # Current-iterate candidate (w = x: box-feasible by the prox).
        r_prim, r_dual, eps_p, eps_d, denom_p, denom_d = _residuals(
            qp, scaling, x, z, x, y, mu, params)
        res_cur = jnp.maximum(r_prim / jnp.maximum(denom_p, tiny),
                              r_dual / jnp.maximum(denom_d, tiny))

        # Averaged candidate: ONE iteration from the restart-window
        # average — yields a fully consistent (x, z, w, y, mu) tuple at
        # one extra iteration per segment (~1/check_interval overhead).
        n_avg = carry.n_avg + jnp.asarray(params.check_interval, dtype)
        x_bar = (carry.avg_x + sx) / n_avg
        y_bar = (carry.avg_y + sy) / n_avg
        xa, ya, za, mua = one_iteration(x_bar, y_bar, tau, sigma)
        ra_prim, ra_dual, ea_p, ea_d, da_p, da_d = _residuals(
            qp, scaling, xa, za, xa, ya, mua, params)
        res_avg = jnp.maximum(ra_prim / jnp.maximum(da_p, tiny),
                              ra_dual / jnp.maximum(da_d, tiny))

        # Restart decision (normalized-residual decay, or forced).
        k_new = carry.k_restart + params.check_interval
        res_best = jnp.minimum(res_cur, res_avg)
        restart = ((res_best <= params.pdhg_restart_decrease
                    * carry.res_restart)
                   | (k_new >= params.pdhg_restart_max_windows
                      * params.check_interval))
        use_avg = restart & (res_avg < res_cur)

        def pick(a, b):
            return jnp.where(use_avg, a, b)

        x_f = pick(xa, x)
        z_f = pick(za, z)
        y_f = pick(ya, y)
        mu_f = pick(mua, mu)
        prim_f = pick(ra_prim, r_prim)
        dual_f = pick(ra_dual, r_dual)
        eps_pf = pick(ea_p, eps_p)
        eps_df = pick(ea_d, eps_d)
        denom_pf = pick(da_p, denom_p)
        denom_df = pick(da_d, denom_d)
        res_f = pick(res_avg, res_cur)

        solved = (prim_f <= eps_pf) & (dual_f <= eps_df)
        p_inf, d_inf, _ = _infeasibility(
            qp, scaling, dx, dy, dmu, params,
            l1w=l1w if track_l1 else None)
        status = jnp.where(
            solved,
            Status.SOLVED,
            jnp.where(
                p_inf, Status.PRIMAL_INFEASIBLE,
                jnp.where(d_inf, Status.DUAL_INFEASIBLE, Status.RUNNING),
            ),
        ).astype(jnp.int32)

        # Primal-weight rebalance at restarts only (the PDLP cadence):
        # primal residual lagging -> larger omega -> larger dual step.
        if params.adaptive_rho:
            ratio = jnp.sqrt(
                (prim_f / jnp.maximum(denom_pf, tiny))
                / jnp.maximum(dual_f / jnp.maximum(denom_df, tiny), tiny))
            omega_new = jnp.where(
                restart, jnp.clip(omega * ratio, _OMEGA_LO, _OMEGA_HI),
                omega)
        else:
            omega_new = omega

        restart_count = (carry.restart_count
                         + restart.astype(jnp.int32))
        if ring_size:
            slot = jax.lax.rem(state.iters // params.check_interval,
                               jnp.asarray(ring_size, jnp.int32))
            ring_prim = state.ring_prim.at[slot].set(prim_f)
            ring_dual = state.ring_dual.at[slot].set(dual_f)
            # Third slot: cumulative restart count (the PDHG trajectory
            # diagnostic), where ADMM records rho.
            ring_rho = state.ring_rho.at[slot].set(
                restart_count.astype(dtype))
        else:
            ring_prim = ring_dual = ring_rho = None

        new_state = ADMMState(
            x=x_f, z=z_f, w=x_f, y=y_f, mu=mu_f,
            rho_bar=omega_new,
            iters=state.iters + params.check_interval,
            status=status,
            prim_res=prim_f,
            dual_res=dual_f,
            ring_prim=ring_prim,
            ring_dual=ring_dual,
            ring_rho=ring_rho,
        )
        zero_x = jnp.zeros_like(x_f)
        zero_y = jnp.zeros_like(y_f)
        return PDHGCarry(
            state=new_state,
            avg_x=jnp.where(restart, zero_x, carry.avg_x + sx),
            avg_y=jnp.where(restart, zero_y, carry.avg_y + sy),
            n_avg=jnp.where(restart, jnp.asarray(0.0, dtype), n_avg),
            k_restart=jnp.where(restart, 0, k_new).astype(jnp.int32),
            res_restart=jnp.where(restart, res_f, carry.res_restart),
            restart_count=restart_count,
            norm_P=carry.norm_P,
            norm_C=carry.norm_C,
        )

    return segment


def pdhg_segment_step(carry: PDHGCarry,
                      qp: CanonicalQP,
                      scaling: Scaling,
                      params: SolverParams,
                      l1_weight: Optional[jax.Array] = None,
                      l1_center: Optional[jax.Array] = None):
    """Advance one residual-check segment; returns ``(carry,
    per_lane_status)`` — the exact contract of
    :func:`porqua_tpu.qp.admm.admm_segment_step` (the step never flips
    ``RUNNING`` to ``MAX_ITER``; the budget is the orchestrator's)."""
    dtype = qp.q.dtype
    n = qp.n
    l1w = jnp.zeros(n, dtype) if l1_weight is None else l1_weight
    l1c = jnp.zeros(n, dtype) if l1_center is None else l1_center
    segment = _make_pdhg_segment(qp, scaling, params, l1w, l1c,
                                 track_l1=l1_weight is not None)
    new = segment(carry)
    return new, new.state.status


def pdhg_solve(qp: CanonicalQP,
               scaling: Scaling,
               params: SolverParams,
               x0: Optional[jax.Array] = None,
               y0: Optional[jax.Array] = None,
               l1_weight: Optional[jax.Array] = None,
               l1_center: Optional[jax.Array] = None) -> ADMMState:
    """Run the restarted-PDHG loop on one *scaled* problem; returns the
    final :class:`~porqua_tpu.qp.admm.ADMMState` (``RUNNING`` retired
    to ``MAX_ITER``, exactly like ``admm_solve``). Structurally a thin
    ``lax.while_loop`` over :func:`pdhg_init` +
    :func:`pdhg_segment_step`'s transition, so hoisted drivers run the
    identical per-lane program."""
    dtype = qp.q.dtype
    n = qp.n
    l1w = jnp.zeros(n, dtype) if l1_weight is None else l1_weight
    l1c = jnp.zeros(n, dtype) if l1_center is None else l1_center
    segment = _make_pdhg_segment(qp, scaling, params, l1w, l1c,
                                 track_l1=l1_weight is not None)

    def cond(carry: PDHGCarry):
        state = carry.state
        return ((state.status == Status.RUNNING)
                & (state.iters < params.max_iter))

    final = jax.lax.while_loop(cond, segment,
                               pdhg_init(qp, params, x0, y0)).state
    return final._replace(
        status=jnp.where(
            final.status == Status.RUNNING, Status.MAX_ITER, final.status
        ).astype(jnp.int32))
