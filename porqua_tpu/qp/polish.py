"""Active-set solution polish for the ADMM solver.

First-order methods land near the optimum; interior-point solvers (the
reference's default cvxopt path) land *on* it. To close that accuracy
gap — "matched tracking error" is the acceptance bar — we replicate
OSQP's polish step on device: guess the active constraint set from the
converged duals/slacks, then solve the equality-constrained KKT system

    [[P + dI,  C_act',  I_act],      [x ]     [-q        ]
     [C_act,   -dI,     0    ],   @  [nu]  =  [bound_act ]
     [I_act,   0,       -dI  ]]      [tau]    [boundb_act]

with inactive dual rows pinned to zero so the shape stays static. The
dual rows are eliminated analytically, leaving the SPD Schur complement
``M = P + dI + (1/d)(C_a' C_a + I_a)`` solved by an n x n Cholesky —
~16x fewer FLOPs than LU on the full (2n+m) system and far better MXU
tiling — with a few refinement steps against the *unperturbed* KKT
residuals (so the fixed point is the true active-set solution, not the
d-regularized one). The polished point is accepted only where it
improves the residuals — per problem, via ``jnp.where`` — so polish can
never hurt.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.linalg import cho_factor, cho_solve

from porqua_tpu.qp.admm import (
    SolverParams,
    _residuals,
    factored_spd_solve_operator,
)
from porqua_tpu.qp.canonical import HP as _HP, CanonicalQP
from porqua_tpu.qp.ruiz import Scaling


def polish_capacitance_dim(qp: CanonicalQP):
    """Capacitance dimension (r + m) the factored polish will use for
    this problem, or ``None`` when the dense penalty polish runs — the
    single source of truth for the gate (bench.py's roofline model and
    :func:`polish` both consult it so they cannot drift)."""
    if qp.Pf is None:
        return None
    k = qp.Pf.shape[-2] + qp.m
    return k if k < qp.n else None


def _kkt_solve_factored(qp: CanonicalQP, params: SolverParams,
                        aB, aC, bound_B, bound_C, q_eff, delta):
    """Active-set KKT solve in the factored (Woodbury) regime.

    The penalty form the dense path uses (``M = P + dI + (1/d) actives``)
    is hostile to the Woodbury apply: the (1/d) up-weighting squares the
    capacitance conditioning and the refinement rhs multiplies residual
    roundoff by 1/d. Here actives are instead pinned *exactly*:

        x = aB * bound_B + Z x_f,   Z = 1 - aB,
        (Z P Z + diag(aB) + sigma I) x_f = Z (-q_eff - P x_a - C'aC nu),
        aC C x = aC bound_C           (Schur complement on the m duals)

    The projected Hessian keeps the factor form (Z P Z = 2 (Pf Z)'(Pf Z)
    + diag(Pdiag Z)), so every solve is a (r+m)-dim capacitance solve;
    pinned coordinates are reproduced exactly (their V columns vanish),
    the m x m Schur system handles the general rows exactly, and the
    refinement loop below iterates the TRUE KKT residuals with no 1/d
    amplification anywhere.
    """
    dtype = qp.P.dtype
    n, m = qp.n, qp.m
    sigma = delta  # same clamped regularizer the dense path uses
    hp = _HP

    pd = jnp.zeros(n, dtype) if qp.Pdiag is None else qp.Pdiag
    Z = 1.0 - aB
    x_a = aB * bound_B
    apply_P = qp.apply_P  # the one canonical factor-product implementation

    Dt = aB + sigma + pd * Z
    V = jnp.sqrt(jnp.asarray(2.0, dtype)) * qp.Pf * Z[None, :]
    psolve = factored_spd_solve_operator(
        Dt, V, refine_steps=params.woodbury_refine)

    CaT = (qp.C * aC[:, None]).T                      # (n, m) masked rows
    Y = jax.vmap(psolve, in_axes=1, out_axes=1)(Z[:, None] * CaT)
    G_raw = aC[:, None] * jnp.dot(qp.C, Y, precision=hp)  # (m, m)
    # Degenerate active rows: if every variable a row touches is pinned
    # (C_i Z == 0), its Schur diagonal is exactly 0 (SPD form) and G is
    # singular. Drop such rows from the Schur system instead of letting
    # linalg.solve emit inf/NaN — the pinned coordinates already carry
    # the row's content, and a wrong guess is still caught by the
    # accept-only-if-better test.
    # A truly-dead row's diagonal is exactly 0.0 (the Z mask is {0,1}),
    # so the cutoff only needs to absorb roundoff in C K0^-1 C' —
    # scale-relative with NO absolute floor: flooring the scale at 1
    # would turn the cutoff into ~1e-4 absolute (f32) whenever every
    # Schur diagonal sits below 1, dropping live rows with uniformly
    # small scaled sensitivity. When max(gdiag) == 0 every active row's
    # diagonal is exactly zero and `<= 0` still classifies them dead.
    gdiag = jnp.abs(jnp.diagonal(G_raw))
    dead = (aC > 0) & (gdiag <= 1e3 * jnp.finfo(dtype).eps
                       * jnp.max(gdiag))
    aC_eff = aC * (1.0 - dead.astype(dtype))
    Y = Y * aC_eff[None, :]
    # aC_eff is a {0,1} subset of aC, so masking G_raw is exact — no
    # second (m,n)@(n,m) matmul needed.
    G = aC_eff[:, None] * G_raw * aC_eff[None, :] + jnp.diag(1.0 - aC_eff)

    def schur_step(rhs_z, r2):
        """Solve the projected KKT for (dx, dnu) given Z-space rhs and
        the active-row residual r2 = aC_eff (bound - C x)."""
        b0 = psolve(rhs_z)
        g = aC_eff * jnp.dot(qp.C, b0, precision=hp) - r2
        dnu = jnp.linalg.solve(G, g)
        dx = b0 - jnp.dot(Y, dnu, precision=hp)
        return dx, dnu

    x, nu = x_a, jnp.zeros(m, dtype)
    for _ in range(1 + params.polish_refine_steps):
        s = apply_P(x) + q_eff + jnp.dot(aC_eff * nu, qp.C, precision=hp)
        r2 = aC_eff * (bound_C - jnp.dot(qp.C, x, precision=hp))
        dx, dnu = schur_step(-Z * s, r2)
        x = x + dx
        nu = nu + dnu

    tau = -aB * (apply_P(x) + q_eff
                 + jnp.dot(aC_eff * nu, qp.C, precision=hp))
    return x, aC_eff * nu, tau


def classify_l1(x, mu, l1_weight, l1_center, err, dual_mode="iterate"):
    """Kink-vs-smooth classification of a native-L1 point; shared by
    the prox-aware polish pass and the differentiable-solve adjoint
    (``qp/diff.py``) — the two MUST agree or the backward gradient
    describes a different piece of the piecewise-smooth solution map
    than the forward polish landed on.

    The point leaves variables that belong ON the kink up to ~its own
    error away from it, so primal proximity alone cannot decide:
    candidates within a window that tracks ``err`` (the caller's
    measure of the point's error — iterate infeasibility in the polish,
    solution residuals in the adjoint) are classified by the DUAL — at
    (near-)optimality the combined box dual carries the L1 subgradient,
    strictly inside [-w, w] exactly for kink-resters, pinned at +/-w
    for smooth-side variables (whose side ``sign(mu)`` reports more
    reliably than ``sign(x - c)`` when x sits within error of the
    kink). Returns ``(at_kink, sub_sign, mu_box_est, window)`` where
    ``mu_box_est`` is the dual with the L1 subgradient shrunk away (so
    box-activity tests see only the box part) and ``sub_sign`` the
    fixed local gradient sign of smooth live variables.

    ``dual_mode`` picks the dual-interior margin. ``"iterate"`` (the
    polish): duals are noisy, so anything within 0.75 w counts as
    interior — a wrong guess only costs a rejected pass. ``"solution"``
    (the differentiable-solve adjoint): duals are converged and there
    is NO acceptance guard downstream, so the margin must be exact —
    movers saturate |mu| = w to roundoff while resters can carry
    subgradients arbitrarily close below it; interior means
    ``|mu| <= w - max(10 err, sqrt(eps) w)``.
    """
    dtype = x.dtype
    kink_tol = jnp.sqrt(jnp.asarray(jnp.finfo(dtype).eps, dtype))
    live = l1_weight > 0
    window = 10.0 * (err + kink_tol)
    near = live & (jnp.abs(x - l1_center) <= window)
    if dual_mode == "iterate":
        dual_interior = jnp.abs(mu) <= 0.75 * l1_weight
    else:
        slack = jnp.maximum(10.0 * err, kink_tol * l1_weight)
        dual_interior = jnp.abs(mu) <= l1_weight - slack
    at_kink = near & dual_interior
    sub_sign = jnp.where(
        live & ~at_kink,
        jnp.where(near, jnp.sign(mu), jnp.sign(x - l1_center)),
        0.0).astype(dtype)
    mu_box_est = mu - jnp.clip(mu, -l1_weight, l1_weight)
    return at_kink, sub_sign, mu_box_est, window


def classify_active(qp: CanonicalQP, zC, xB, y, mu, prox_tol, dual_tol):
    """Shared active-set classification: dual sign (OSQP's criterion)
    with an on-(finite-)bound proximity fallback, equality rows/boxes
    always active. ``zC``/``xB`` are the row activities and box
    variables of the point being classified (the ADMM iterate's ``z``/
    ``w`` in the polish, the solution's ``Cx``/``x`` in the
    differentiable-solve adjoint — both callers MUST share this logic
    or forward polish and backward gradient drift apart on the same
    point). Returns the raw pieces ``(act_low_C, act_up_C, eq_C,
    act_low_B, act_up_B, eq_B)``; callers combine and mask.
    """
    act_low_C = (y < -dual_tol) | (jnp.isfinite(qp.l) & (zC - qp.l <= prox_tol))
    act_up_C = (y > dual_tol) | (jnp.isfinite(qp.u) & (qp.u - zC <= prox_tol))
    eq_C = jnp.isfinite(qp.l) & jnp.isfinite(qp.u) & ((qp.u - qp.l) <= 1e-10)
    act_low_B = (mu < -dual_tol) | (
        jnp.isfinite(qp.lb) & (xB - qp.lb <= prox_tol))
    act_up_B = (mu > dual_tol) | (
        jnp.isfinite(qp.ub) & (qp.ub - xB <= prox_tol))
    eq_B = jnp.isfinite(qp.lb) & jnp.isfinite(qp.ub) & (
        (qp.ub - qp.lb) <= 1e-10)
    return act_low_C, act_up_C, eq_C, act_low_B, act_up_B, eq_B


def _kkt_solve_dense(qp: CanonicalQP, params: SolverParams,
                     aB, aC, bound_B, bound_C, q_eff, delta):
    """Active-set KKT solve, dense penalty-Schur form.

    Instead of the full (2n+m) indefinite KKT LU, eliminate the dual
    rows: with actives aC/aB the perturbed system reduces to the SPD
    Schur complement

        M = P + delta I + (1/delta)(C' diag(aC) C + diag(aB))

    solved by an n x n Cholesky — ~16x fewer FLOPs than the LU and a
    primitive the MXU tiles well. Refinement iterates against the
    UNPERTURBED KKT residuals (r1, r2, r3 below), so the fixed point is
    the true active-set solution, not the delta-regularized one (same
    scheme as OSQP's polish, reduced). Shared by the polish pass and
    the differentiable-solve adjoint (``qp/diff.py``), which calls it
    with rhs ``q_eff = -cotangent`` and zero bounds — a fix here
    reaches both.
    """
    dtype = qp.P.dtype
    hp = _HP
    inv_d = 1.0 / delta
    bC = aC * bound_C
    bB = aB * bound_B
    M = (
        qp.P + delta * jnp.eye(qp.n, dtype=dtype)
        + inv_d * (jnp.dot(qp.C.T * aC, qp.C, precision=hp) + jnp.diag(aB))
    )
    cholM = cho_factor(M)
    msolve = lambda v: cho_solve(cholM, v)
    x_i = msolve(-q_eff + inv_d * (jnp.dot(bC, qp.C, precision=hp) + bB))
    nu = aC * (jnp.dot(qp.C, x_i, precision=hp) - bound_C) * inv_d
    tau = aB * (x_i - bound_B) * inv_d
    for _ in range(params.polish_refine_steps):
        r1 = -q_eff - (jnp.dot(qp.P, x_i, precision=hp)
                       + jnp.dot(nu, qp.C, precision=hp) + tau)
        r2 = aC * (bound_C - jnp.dot(qp.C, x_i, precision=hp))
        r3 = aB * (bound_B - x_i)
        dx = msolve(r1 + inv_d * (jnp.dot(r2, qp.C, precision=hp) + r3))
        nu = nu + aC * (jnp.dot(qp.C, dx, precision=hp) - r2) * inv_d
        tau = tau + aB * (dx - r3) * inv_d
        x_i = x_i + dx
    return x_i, nu, tau


def polish(qp: CanonicalQP,
           scaling: Scaling,
           params: SolverParams,
           x, z, w, y, mu,
           l1_weight=None,
           l1_center=None):
    """One polish pass; returns possibly-improved (x, z, w, y, mu).

    With a native L1 term (``l1_weight``/``l1_center``, scaled frame)
    the polish is *prox-aware*: variables resting on the kink
    (x_i ~ c_i) are pinned there as active equalities, while for the
    rest the L1 term is locally smooth with fixed gradient
    ``w_i sign(x_i - c_i)``, which simply shifts q. The resulting KKT
    system is smooth again, so cost-aware dates get the same
    high-accuracy finish as plain ones; the returned ``mu`` carries the
    L1 subgradient exactly as the ADMM iterate's does, keeping the
    residual accounting consistent.
    """
    return polish_iterate(qp, scaling, params, x, z, w, y, mu,
                          l1_weight, l1_center, passes=1)


def polish_iterate(qp: CanonicalQP,
                   scaling: Scaling,
                   params: SolverParams,
                   x, z, w, y, mu,
                   l1_weight=None,
                   l1_center=None,
                   passes: int = None):
    """Active-set *iteration*: thread each pass's candidate forward as
    the next pass's classification point, keep the best point seen.

    Rationale (round 3, found on the north-star batch at loose eps):
    one pass classifies actives from the ADMM iterate, whose ~eps-sized
    noise leaves borderline variables unpinned; the candidate then dips
    those coordinates slightly out of bounds and loses the
    accept-only-if-better test on primal residual — and since a
    REJECTED pass returns the unchanged iterate, re-running passes
    re-derives the identical guess: rejection was a fixed point and
    ``polish_passes`` could never recover. Re-classifying from the
    CANDIDATE (clipped back into the box) pins exactly the coordinates
    that dipped, converging like a proper active-set method in 2-3
    passes; the final answer is the best point by the max-residual
    metric, so a mis-guessed excursion still cannot degrade the result.
    """
    passes = params.polish_passes if passes is None else passes
    rp0, rd0, *_ = _residuals(qp, scaling, x, z, w, y, mu, params)
    best = (x, z, w, y, mu)
    best_err = jnp.maximum(rp0, rd0)
    guess = (x, z, w, y, mu)
    for _ in range(passes):
        cand, cand_err, finite, gates_ok = _polish_pass(
            qp, scaling, params, *guess, l1_weight, l1_center)
        accept = finite & gates_ok & (cand_err < best_err)
        best = tuple(jnp.where(accept, c, b) for c, b in zip(cand, best))
        best_err = jnp.where(accept, cand_err, best_err)
        # Thread the candidate as the next classification point even
        # when not (yet) better — but only when it passes the sanity
        # gates: an L1 candidate that failed them is PROVABLY
        # misclassified (a kink/sign pattern the KKT residuals cannot
        # vouch for, since mu absorbs any subgradient), and classifying
        # from it can produce a kink-degenerate point whose residuals
        # look clean while the chain silently freezes at its center.
        # Without an L1 term gates_ok is constant True and threading is
        # unconditional (modulo finiteness).
        thread = finite & gates_ok
        guess = tuple(jnp.where(thread, c, g) for c, g in zip(cand, guess))
    return best


def _polish_pass(qp: CanonicalQP,
                 scaling: Scaling,
                 params: SolverParams,
                 x, z, w, y, mu,
                 l1_weight=None,
                 l1_center=None):
    """Compute one polish candidate from the given point's active-set
    classification. Returns ``(candidate_5tuple, cand_err, finite,
    gates_ok)`` where ``cand_err = max(primal, dual residual)`` of the
    candidate and ``gates_ok`` folds the L1 sanity gates (True without
    an L1 term)."""
    dtype = qp.P.dtype
    n, m = qp.n, qp.m
    delta = jnp.asarray(params.polish_delta, dtype)

    # Active sets from dual signs (OSQP's criterion), with a tight
    # exact-on-bound proximity fallback. The dual threshold is a few
    # ulps, not eps_abs-scaled: a loose iterate's duals are noisy, but a
    # wrong guess only costs a rejected pass (accept-only-if-better
    # below), while an eps_abs-sized threshold classifies everything
    # whose dual is merely small as inactive/active wholesale.
    prox_err = jnp.maximum(
        jnp.max(jnp.abs(jnp.dot(qp.C, x, precision=_HP) - z))
        if m else jnp.asarray(0.0, dtype),
        jnp.max(jnp.abs(x - w)),
    )
    tiny = 1e3 * jnp.asarray(jnp.finfo(dtype).eps, dtype)
    dual_tol = tiny
    prox_tol = tiny

    has_l1 = l1_weight is not None
    if has_l1:
        l1c = jnp.zeros(n, dtype) if l1_center is None else l1_center
        at_kink, sub_sign, mu_box_est, window = classify_l1(
            x, mu, l1_weight, l1c, prox_err)
        q_eff = qp.q + l1_weight * sub_sign
        # Used by the crossing-repair and sanity gates below.
        kink_tol = jnp.sqrt(jnp.asarray(jnp.finfo(dtype).eps, dtype))
        live = l1_weight > 0
    else:
        at_kink = jnp.zeros(n, bool)
        sub_sign = jnp.zeros(n, dtype)
        q_eff = qp.q
        l1c = jnp.zeros(n, dtype)
        window = 10.0 * prox_err + tiny
        mu_box_est = mu
    (act_low_C, act_up_C, eq_C, act_low_B, act_up_B, eq_B
     ) = classify_active(qp, z, w, y, mu_box_est, prox_tol, dual_tol)
    act_C = (act_low_C | act_up_C | eq_C) & (qp.row_mask > 0)
    bound_C = jnp.where(act_up_C & ~act_low_C, qp.u, qp.l)
    bound_C = jnp.where(jnp.isfinite(bound_C), bound_C, 0.0)
    bound_B = jnp.where(act_up_B & ~act_low_B, qp.ub, qp.lb)
    bound_B = jnp.where(jnp.isfinite(bound_B), bound_B, 0.0)

    # The exact-pinning factored KKT solve is used whenever the
    # objective factor pays, independent of the ADMM segment's linsolve
    # choice — it is both cheaper (capacitance-sized factorizations)
    # and at least as accurate (no 1/delta penalty amplification) than
    # the dense penalty form; parity is pinned by test_woodbury.py.
    use_woodbury = polish_capacitance_dim(qp) is not None
    # In f32 the (1/delta)-weighted Schur complement must stay within
    # what a Cholesky + refinement can represent; sqrt(machine eps) is
    # the classic regularization compromise (f64 keeps the caller's
    # tighter delta).
    delta = jnp.maximum(
        delta, jnp.sqrt(jnp.asarray(jnp.finfo(dtype).eps, dtype)))

    def kkt_solve(at_kink_i, sub_sign_i):
        """Equality-KKT solve for one active-set/sign hypothesis.

        Instead of the full (2n+m) indefinite KKT LU, eliminate the
        dual rows: with actives a_C/a_B the perturbed system reduces to
        the SPD Schur complement

            M = P + delta I + (1/delta)(C' diag(a_C) C + diag(a_B))

        solved by an n x n Cholesky — ~16x fewer FLOPs than the LU and
        a primitive the MXU tiles well. Refinement iterates against the
        UNPERTURBED KKT residuals (r1, r2, r3 below), so the fixed
        point is the true active-set solution, not the
        delta-regularized one (same scheme as OSQP's polish, reduced).
        """
        aB_i = (act_low_B | act_up_B | eq_B | at_kink_i).astype(dtype)
        aC_i = act_C.astype(dtype)
        bound_B_i = jnp.where(
            at_kink_i, jnp.clip(l1c, qp.lb, qp.ub), bound_B)
        q_eff_i = qp.q + (l1_weight * sub_sign_i if has_l1 else 0.0)

        if use_woodbury:
            return _kkt_solve_factored(
                qp, params, aB_i, aC_i, bound_B_i, bound_C, q_eff_i, delta)
        return _kkt_solve_dense(
            qp, params, aB_i, aC_i, bound_B_i, bound_C, q_eff_i, delta)

    x_p, y_p, tau_p = kkt_solve(at_kink, sub_sign)

    if has_l1:
        # A smooth-classified variable whose solution crossed its kink
        # has a mis-guessed subgradient sign; the true optimum rests ON
        # the kink for exactly those variables. Reclassify them as
        # pinned and re-solve (one inner active-set refinement step) —
        # the KKT residuals cannot catch this themselves because mu
        # absorbs whatever subgradient the solve implies.
        kt = jnp.asarray(kink_tol, dtype)
        crossed = live & ~at_kink & ((x_p - l1c) * sub_sign < -kt)
        any_crossed = jnp.any(crossed)
        at_kink2 = at_kink | crossed
        sub_sign2 = jnp.where(crossed, 0.0, sub_sign)
        x_p2, y_p2, tau_p2 = kkt_solve(at_kink2, sub_sign2)
        pick2 = lambda b2, b1: jnp.where(any_crossed, b2, b1)
        x_p = pick2(x_p2, x_p)
        y_p = pick2(y_p2, y_p)
        tau_p = pick2(tau_p2, tau_p)
        at_kink = jnp.where(any_crossed, at_kink2, at_kink)
        sub_sign = pick2(sub_sign2, sub_sign)

    # Fold the fixed L1 subgradient back into the box dual so the
    # stationarity vector P x + q + C'y + mu is evaluated against the
    # ORIGINAL q, matching how the ADMM iterate carries the L1 term.
    mu_p = tau_p + (l1_weight * sub_sign if has_l1 else 0.0)
    z_p = jnp.clip(jnp.dot(qp.C, x_p, precision=_HP), qp.l, qp.u)
    w_p = jnp.clip(x_p, qp.lb, qp.ub)

    rp1, rd1, *_ = _residuals(qp, scaling, x_p, z_p, w_p, y_p, mu_p, params)
    cand_err = jnp.maximum(rp1, rd1)
    finite = jnp.all(jnp.isfinite(x_p)) & jnp.all(jnp.isfinite(y_p))

    gates_ok = jnp.asarray(True)
    if has_l1:
        # A mis-guessed kink/sign pattern that survived reclassification
        # must still be rejected: a variable pinned at the kink strictly
        # inside the box needs its implied multiplier within
        # [-w_i, w_i], and a smooth-side variable must sit strictly on
        # its assumed side (up to roundoff) after the re-solve.
        inside = (x_p > qp.lb + window) & (x_p < qp.ub - window)
        kink_dual_ok = jnp.where(at_kink & inside,
                                 jnp.abs(tau_p) <= l1_weight + window,
                                 True)
        side_ok = jnp.where(live & ~at_kink,
                            (x_p - l1c) * sub_sign >= -kink_tol,
                            True)
        gates_ok = jnp.all(kink_dual_ok) & jnp.all(side_ok)

    return (x_p, z_p, w_p, y_p, mu_p), cand_err, finite, gates_ok
