"""Active-set solution polish for the ADMM solver.

First-order methods land near the optimum; interior-point solvers (the
reference's default cvxopt path) land *on* it. To close that accuracy
gap — "matched tracking error" is the acceptance bar — we replicate
OSQP's polish step on device: guess the active constraint set from the
converged duals/slacks, then solve the equality-constrained KKT system

    [[P + dI,  C_act',  I_act],      [x ]     [-q        ]
     [C_act,   -dI,     0    ],   @  [nu]  =  [bound_act ]
     [I_act,   0,       -dI  ]]      [tau]    [boundb_act]

with inactive dual rows replaced by ``nu_i = 0`` so the shape stays
static. The system is solved by batched LU with a few steps of
iterative refinement (recovers near-working-precision accuracy in f32).
The polished point is accepted only where it improves the residuals —
per problem, via ``jnp.where`` — so polish can never hurt.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.linalg import lu_factor, lu_solve

from porqua_tpu.qp.admm import SolverParams, _residuals
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.ruiz import Scaling


def polish(qp: CanonicalQP,
           scaling: Scaling,
           params: SolverParams,
           x, z, w, y, mu,
           l1_weight=None,
           l1_center=None):
    """One polish pass; returns possibly-improved (x, z, w, y, mu).

    With a native L1 term (``l1_weight``/``l1_center``, scaled frame)
    the polish is *prox-aware*: variables resting on the kink
    (x_i ~ c_i) are pinned there as active equalities, while for the
    rest the L1 term is locally smooth with fixed gradient
    ``w_i sign(x_i - c_i)``, which simply shifts q. The resulting KKT
    system is smooth again, so cost-aware dates get the same
    high-accuracy finish as plain ones; the returned ``mu`` carries the
    L1 subgradient exactly as the ADMM iterate's does, keeping the
    residual accounting consistent.
    """
    dtype = qp.P.dtype
    n, m = qp.n, qp.m
    delta = jnp.asarray(params.polish_delta, dtype)

    # Active sets from dual signs, with a slack-proximity fallback so
    # weakly-active constraints (tiny dual) are still caught.
    slack_tol = 1e3 * jnp.asarray(params.eps_abs, dtype)

    has_l1 = l1_weight is not None
    if has_l1:
        # Kink classification must NOT scale with the solve tolerance:
        # at a loose eps the iterate sits far from the optimum and an
        # eps-sized window would pin every variable. A dtype-resolution
        # window classifies only genuine kink-resters; misclassified
        # sign patterns are caught by the dual-feasibility guard below.
        kink_tol = jnp.sqrt(jnp.asarray(jnp.finfo(dtype).eps, dtype))
        l1c = jnp.zeros(n, dtype) if l1_center is None else l1_center
        live = l1_weight > 0
        at_kink = live & (jnp.abs(x - l1c) <= kink_tol)
        sub_sign = jnp.where(live & ~at_kink, jnp.sign(x - l1c), 0.0)
        q_eff = qp.q + l1_weight * sub_sign
    else:
        at_kink = jnp.zeros(n, bool)
        sub_sign = jnp.zeros(n, dtype)
        q_eff = qp.q
        l1c = jnp.zeros(n, dtype)
    act_low_C = (y < -slack_tol) | (jnp.isfinite(qp.l) & (z - qp.l <= slack_tol))
    act_up_C = (y > slack_tol) | (jnp.isfinite(qp.u) & (qp.u - z <= slack_tol))
    # Equality rows are always active (l == u)
    eq_C = jnp.isfinite(qp.l) & jnp.isfinite(qp.u) & ((qp.u - qp.l) <= 1e-10)
    act_C = (act_low_C | act_up_C | eq_C) & (qp.row_mask > 0)
    bound_C = jnp.where(act_up_C & ~act_low_C, qp.u, qp.l)
    bound_C = jnp.where(jnp.isfinite(bound_C), bound_C, 0.0)

    act_low_B = (mu < -slack_tol) | (jnp.isfinite(qp.lb) & (w - qp.lb <= slack_tol))
    act_up_B = (mu > slack_tol) | (jnp.isfinite(qp.ub) & (qp.ub - w <= slack_tol))
    eq_B = jnp.isfinite(qp.lb) & jnp.isfinite(qp.ub) & ((qp.ub - qp.lb) <= 1e-10)
    act_B = act_low_B | act_up_B | eq_B | at_kink
    bound_B = jnp.where(act_up_B & ~act_low_B, qp.ub, qp.lb)
    bound_B = jnp.where(jnp.isfinite(bound_B), bound_B, 0.0)
    # A variable resting on the L1 kink is pinned there (clipped into
    # the box in case the kink sits outside it).
    bound_B = jnp.where(at_kink, jnp.clip(l1c, qp.lb, qp.ub), bound_B)

    aC = act_C.astype(dtype)
    aB = act_B.astype(dtype)

    eye_n = jnp.eye(n, dtype=dtype)
    # KKT blocks; inactive dual rows become identity rows pinning the dual to 0.
    top = jnp.concatenate([qp.P + delta * eye_n, qp.C.T, eye_n], axis=1)
    midC = jnp.concatenate(
        [aC[:, None] * qp.C,
         jnp.diag(-delta * aC + (1.0 - aC)),
         jnp.zeros((m, n), dtype)],
        axis=1,
    )
    midB = jnp.concatenate(
        [jnp.diag(aB),
         jnp.zeros((n, m), dtype),
         jnp.diag(-delta * aB + (1.0 - aB))],
        axis=1,
    )
    KKT = jnp.concatenate([top, midC, midB], axis=0)
    rhs = jnp.concatenate([-q_eff, aC * bound_C, aB * bound_B])

    lu = lu_factor(KKT)
    sol = lu_solve(lu, rhs)
    for _ in range(params.polish_refine_steps):
        resid = rhs - KKT @ sol
        sol = sol + lu_solve(lu, resid)

    x_p = sol[:n]
    y_p = sol[n:n + m]
    tau_p = sol[n + m:]
    # Fold the fixed L1 subgradient back into the box dual so the
    # stationarity vector P x + q + C'y + mu is evaluated against the
    # ORIGINAL q, matching how the ADMM iterate carries the L1 term.
    mu_p = tau_p + (l1_weight * sub_sign if has_l1 else 0.0)
    z_p = jnp.clip(qp.C @ x_p, qp.l, qp.u)
    w_p = jnp.clip(x_p, qp.lb, qp.ub)

    # Keep the polished iterate only where it strictly improves.
    rp0, rd0, *_ = _residuals(qp, scaling, x, z, w, y, mu, params)
    rp1, rd1, *_ = _residuals(qp, scaling, x_p, z_p, w_p, y_p, mu_p, params)
    finite = jnp.all(jnp.isfinite(x_p)) & jnp.all(jnp.isfinite(y_p))
    better = finite & (jnp.maximum(rp1, rd1) < jnp.maximum(rp0, rd0))

    if has_l1:
        # The stationarity residual cannot see an invalid L1
        # subgradient (mu absorbs whatever the KKT solve implies), so a
        # mis-guessed kink/sign pattern must be rejected explicitly:
        # a variable pinned at the kink strictly inside the box needs
        # its implied multiplier within [-w_i, w_i], and a smooth-side
        # variable must not have crossed to the other side of its kink.
        inside = (x_p > qp.lb + slack_tol) & (x_p < qp.ub - slack_tol)
        kink_dual_ok = jnp.where(at_kink & inside,
                                 jnp.abs(tau_p) <= l1_weight + slack_tol,
                                 True)
        side_ok = jnp.where(live & ~at_kink,
                            (x_p - l1c) * sub_sign >= -kink_tol,
                            True)
        better = better & jnp.all(kink_dual_ok) & jnp.all(side_ok)

    pick = lambda a, b: jnp.where(better, a, b)
    return (
        pick(x_p, x), pick(z_p, z), pick(w_p, w), pick(y_p, y), pick(mu_p, mu)
    )
