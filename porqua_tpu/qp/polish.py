"""Active-set solution polish for the ADMM solver.

First-order methods land near the optimum; interior-point solvers (the
reference's default cvxopt path) land *on* it. To close that accuracy
gap — "matched tracking error" is the acceptance bar — we replicate
OSQP's polish step on device: guess the active constraint set from the
converged duals/slacks, then solve the equality-constrained KKT system

    [[P + dI,  C_act',  I_act],      [x ]     [-q        ]
     [C_act,   -dI,     0    ],   @  [nu]  =  [bound_act ]
     [I_act,   0,       -dI  ]]      [tau]    [boundb_act]

with inactive dual rows replaced by ``nu_i = 0`` so the shape stays
static. The system is solved by batched LU with a few steps of
iterative refinement (recovers near-working-precision accuracy in f32).
The polished point is accepted only where it improves the residuals —
per problem, via ``jnp.where`` — so polish can never hurt.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.linalg import lu_factor, lu_solve

from porqua_tpu.qp.admm import SolverParams, _residuals
from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.ruiz import Scaling


def polish(qp: CanonicalQP,
           scaling: Scaling,
           params: SolverParams,
           x, z, w, y, mu):
    """One polish pass; returns possibly-improved (x, z, w, y, mu)."""
    dtype = qp.P.dtype
    n, m = qp.n, qp.m
    delta = jnp.asarray(params.polish_delta, dtype)

    # Active sets from dual signs, with a slack-proximity fallback so
    # weakly-active constraints (tiny dual) are still caught.
    slack_tol = 1e3 * jnp.asarray(params.eps_abs, dtype)
    act_low_C = (y < -slack_tol) | (jnp.isfinite(qp.l) & (z - qp.l <= slack_tol))
    act_up_C = (y > slack_tol) | (jnp.isfinite(qp.u) & (qp.u - z <= slack_tol))
    # Equality rows are always active (l == u)
    eq_C = jnp.isfinite(qp.l) & jnp.isfinite(qp.u) & ((qp.u - qp.l) <= 1e-10)
    act_C = (act_low_C | act_up_C | eq_C) & (qp.row_mask > 0)
    bound_C = jnp.where(act_up_C & ~act_low_C, qp.u, qp.l)
    bound_C = jnp.where(jnp.isfinite(bound_C), bound_C, 0.0)

    act_low_B = (mu < -slack_tol) | (jnp.isfinite(qp.lb) & (w - qp.lb <= slack_tol))
    act_up_B = (mu > slack_tol) | (jnp.isfinite(qp.ub) & (qp.ub - w <= slack_tol))
    eq_B = jnp.isfinite(qp.lb) & jnp.isfinite(qp.ub) & ((qp.ub - qp.lb) <= 1e-10)
    act_B = act_low_B | act_up_B | eq_B
    bound_B = jnp.where(act_up_B & ~act_low_B, qp.ub, qp.lb)
    bound_B = jnp.where(jnp.isfinite(bound_B), bound_B, 0.0)

    aC = act_C.astype(dtype)
    aB = act_B.astype(dtype)

    eye_n = jnp.eye(n, dtype=dtype)
    # KKT blocks; inactive dual rows become identity rows pinning the dual to 0.
    top = jnp.concatenate([qp.P + delta * eye_n, qp.C.T, eye_n], axis=1)
    midC = jnp.concatenate(
        [aC[:, None] * qp.C,
         jnp.diag(-delta * aC + (1.0 - aC)),
         jnp.zeros((m, n), dtype)],
        axis=1,
    )
    midB = jnp.concatenate(
        [jnp.diag(aB),
         jnp.zeros((n, m), dtype),
         jnp.diag(-delta * aB + (1.0 - aB))],
        axis=1,
    )
    KKT = jnp.concatenate([top, midC, midB], axis=0)
    rhs = jnp.concatenate([-qp.q, aC * bound_C, aB * bound_B])

    lu = lu_factor(KKT)
    sol = lu_solve(lu, rhs)
    for _ in range(params.polish_refine_steps):
        resid = rhs - KKT @ sol
        sol = sol + lu_solve(lu, resid)

    x_p = sol[:n]
    y_p = sol[n:n + m]
    mu_p = sol[n + m:]
    z_p = jnp.clip(qp.C @ x_p, qp.l, qp.u)
    w_p = jnp.clip(x_p, qp.lb, qp.ub)

    # Keep the polished iterate only where it strictly improves.
    rp0, rd0, *_ = _residuals(qp, scaling, x, z, w, y, mu, params)
    rp1, rd1, *_ = _residuals(qp, scaling, x_p, z_p, w_p, y_p, mu_p, params)
    finite = jnp.all(jnp.isfinite(x_p)) & jnp.all(jnp.isfinite(y_p))
    better = finite & (jnp.maximum(rp1, rd1) < jnp.maximum(rp0, rd0))

    pick = lambda a, b: jnp.where(better, a, b)
    return (
        pick(x_p, x), pick(z_p, z), pick(w_p, w), pick(y_p, y), pick(mu_p, mu)
    )
