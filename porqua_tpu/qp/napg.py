"""Nesterov-accelerated projected-gradient (NAPG) backend.

The third first-order solver behind ``SolverParams(method="napg")`` —
accelerated projected gradient for the box-dominated regime
("Scalable Mean-Variance Portfolio Optimization via Subspace
Embeddings and GPU-Friendly Nesterov-Accelerated Projected Gradient",
PAPERS.md): the tracking family's polytope is a box plus one budget
row, and paying ADMM's per-segment factorization or PDHG's full
primal-dual machinery for it buys nothing. One iteration:

    y_k   = x_k + beta_k (x_k - x_{k-1})          # Nesterov momentum
    v     = y_k - tau (P y_k + q)                 # gradient step
    x_+   = prox_Omega(v)                         # box (+L1) ∩ rows
    y_+   = lam / tau                             # row duals from prox
    mu_+  = (v - x_+) / tau - C' y_+              # box(+L1) subgradient

with tau = 1/L_P from a one-time power iteration at ``napg_init``
(the estimate is inflated by a safety factor — see ``_power_norm``)
and beta_k = k/(k+3) on the iterations-since-restart counter. The
prox is computed EXACTLY for the box(+native-L1) block by dual
coordinate ascent over the C rows: per row, the multiplier lam_i
solves ``c_i' l1_box_prox(v - C'lam) = clip(., l_i, u_i)`` by a
fixed-count bisection (the function is monotone in lam_i), which for
the single-budget-row tracking family is the exact capped-simplex
projection in one sweep. Multi-row problems get
``napg_project_cycles`` coordinate-ascent sweeps — exact in the limit
but NOT the regime this backend is for: on general-C buckets the
residuals honestly report the gap, the lane retires MAX_ITER, and the
evidence-driven router simply never routes NAPG there. No
factorization and no C-norm coupling anywhere: a segment is
``check_interval`` rounds of one P-apply plus an O(m n) projection.

**State mapping.** The iterate is carried as the same
:class:`~porqua_tpu.qp.admm.ADMMState` the other backends use — with
``w = x`` (box-feasible post-prox), ``z = clip(Cx, l, u)``, and
``y``/``mu`` the prox multipliers above — so the *shared* residual
measure (:func:`porqua_tpu.qp.admm._residuals`), the shared finalize
(MAX_ITER + polish fallback, ``qp/solve.py``), compaction's repack,
continuous batching, and the harvest bridge all work unmodified: at a
NAPG fixed point ``P x + q + C' y + mu = 0`` and ``Cx = z`` exactly,
so the OSQP-style residuals measure true KKT error for this backend
too. ``state.rho_bar`` carries the step size tau.

**Restarts.** The O'Donoghue-Candes gradient criterion, evaluated
every iteration at zero extra matvecs: momentum is discarded
(``k`` reset, so beta collapses to 0) whenever
``<y_k - x_+, x_+ - x_k> > 0`` — the momentum direction opposes the
descent direction. The convergence rings record ``(prim_res,
dual_res, restart_count)``: the third slot holds the cumulative
restart count exactly like PDHG's, where ADMM records rho.

Infeasibility certificates are deliberately NOT produced: the
box+budget family this backend exists for is feasible by
construction (finite box, budget inside its range), and a lane that
cannot converge retires MAX_ITER through the shared finalize —
infeasibility detection stays an ADMM/PDHG property.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from porqua_tpu.qp.admm import (
    ADMMState,
    SolverParams,
    Status,
    _residuals,
    l1_box_prox,
)
from porqua_tpu.qp.canonical import HP as _HP, CanonicalQP
from porqua_tpu.qp.pdhg import _norm2, _power_norm
from porqua_tpu.qp.ruiz import Scaling

__all__ = ["NAPGCarry", "napg_init", "napg_segment_step", "napg_solve"]


class NAPGCarry(NamedTuple):
    """The NAPG segment-loop carry — same contract as
    :class:`~porqua_tpu.qp.admm.ADMMCarry` (``.state`` is an
    ``ADMMState``; everything else is per-lane scalars/vectors), so the
    batch orchestration layers treat all three backends uniformly.
    """

    state: ADMMState
    x_prev: jax.Array         # (n,) previous iterate (momentum source)
    k_mom: jax.Array          # () iterations since the last restart
    restart_count: jax.Array  # () int32, cumulative restarts
    # Spectral estimate fixed at init (power iteration): ||P||_2 upper
    # estimate — it sets the step tau = 1/L every segment.
    norm_P: jax.Array         # ()


def _row_prox(v: jax.Array,
              lam: jax.Array,
              qp: CanonicalQP,
              tau_l1w: jax.Array,
              l1c: jax.Array,
              params: SolverParams):
    """Exact prox of ``I_[lb,ub] + l1 + I_{l <= Cx <= u}`` at ``v`` by
    dual coordinate ascent, warm-started at ``lam``.

    Per sweep, each row's multiplier is re-solved by bisection:
    ``h(lam_i) = c_i' prox1(v - C'lam)`` is nonincreasing in lam_i
    (prox1 — the separable box+L1 prox — is elementwise nondecreasing
    in its input), so the complementarity target ``clip(h, l_i, u_i)``
    has a bracketable root. One sweep is exact for a single row (the
    tracking budget); ``napg_project_cycles`` sweeps tighten the
    multi-row intersection. Returns ``(x, lam)`` with
    ``x = prox1(v - C'lam)``.
    """
    m = qp.m
    dtype = v.dtype
    floor = jnp.asarray(1e-12, dtype)

    def prox1(t):
        return l1_box_prox(t, qp.lb, qp.ub, tau_l1w, l1c)

    def row_update(i, lam):
        c = qp.C[i]
        # Other rows' contribution held fixed (coordinate ascent).
        w = v - jnp.dot(lam, qp.C, precision=_HP) + lam[i] * c
        s0 = jnp.dot(c, prox1(w), precision=_HP)
        target = jnp.clip(s0, qp.l[i], qp.u[i])
        active = s0 != target
        cc = jnp.maximum(jnp.dot(c, c, precision=_HP), floor)
        lam_lin = (s0 - target) / cc
        # Bisection bracket from the per-coordinate kink points of
        # lam -> prox1(w - lam c): coordinate k saturates at
        # ub_k (+ the L1 shift) when w_k - lam c_k >= ub_k + tau*l1w_k,
        # at lb_k below lb_k - tau*l1w_k. Outside every kink h is
        # constant, so the root lies inside [lo, hi]; the linear
        # estimate covers the all-infinite-box (pure linear) case.
        up = qp.ub + tau_l1w
        lo_b = qp.lb - tau_l1w
        with_c = c != 0.0
        cand_a = jnp.where(with_c, (w - up) / jnp.where(with_c, c, 1.0),
                           jnp.nan)
        cand_b = jnp.where(with_c, (w - lo_b) / jnp.where(with_c, c, 1.0),
                           jnp.nan)
        k_lo = jnp.minimum(cand_a, cand_b)
        k_hi = jnp.maximum(cand_a, cand_b)
        big = jnp.asarray(jnp.finfo(dtype).max, dtype)
        lo = jnp.min(jnp.where(jnp.isfinite(k_lo), k_lo, big))
        hi = jnp.max(jnp.where(jnp.isfinite(k_hi), k_hi, -big))
        lo = jnp.minimum(jnp.minimum(lo, lam_lin), 0.0) - 1.0
        hi = jnp.maximum(jnp.maximum(hi, lam_lin), 0.0) + 1.0

        def bisect(_, ab):
            a, b = ab
            mid = 0.5 * (a + b)
            hmid = jnp.dot(c, prox1(w - mid * c), precision=_HP)
            go_right = hmid > target
            return (jnp.where(go_right, mid, a),
                    jnp.where(go_right, b, mid))

        a, b = jax.lax.fori_loop(0, params.napg_bisect_iters, bisect,
                                 (lo, hi))
        lam_i = jnp.where(active, 0.5 * (a + b), 0.0)
        return lam.at[i].set(lam_i.astype(dtype))

    def sweep(_, lam):
        return jax.lax.fori_loop(0, m, row_update, lam)

    if m:
        lam = jax.lax.fori_loop(0, params.napg_project_cycles, sweep, lam)
    x = prox1(v - jnp.dot(lam, qp.C, precision=_HP))
    return x, lam


def napg_init(qp: CanonicalQP,
              params: SolverParams,
              x0: Optional[jax.Array] = None,
              y0: Optional[jax.Array] = None) -> NAPGCarry:
    """Build the segment-loop carry for one *scaled* problem — the NAPG
    twin of :func:`porqua_tpu.qp.admm.admm_init` (warm starts in the
    scaled frame, rings initialized iff ``params.ring_size``). ``y0``
    is accepted for signature parity but the row duals are recovered
    from the projection every iteration, so only ``x0`` seeds."""
    del y0  # duals are a by-product of the projection, not state
    dtype = qp.q.dtype
    n, m = qp.n, qp.m
    x_init = jnp.zeros(n, dtype) if x0 is None else x0
    x_init = jnp.clip(x_init, qp.lb, qp.ub)
    z_init = jnp.dot(qp.C, x_init, precision=_HP)

    norm_P = jnp.maximum(
        _power_norm(qp.apply_P, n, dtype, params.napg_power_iters),
        jnp.asarray(1e-6, dtype))

    ring_size = params.ring_size
    state = ADMMState(
        x=x_init, z=z_init, w=x_init, y=jnp.zeros(m, dtype),
        mu=jnp.zeros(n, dtype),
        rho_bar=1.0 / norm_P.astype(dtype),  # the step tau, telemetry
        iters=jnp.asarray(0, jnp.int32),
        status=jnp.asarray(Status.RUNNING, jnp.int32),
        prim_res=jnp.asarray(jnp.inf, dtype),
        dual_res=jnp.asarray(jnp.inf, dtype),
        ring_prim=jnp.full((ring_size,), jnp.inf, dtype)
        if ring_size else None,
        ring_dual=jnp.full((ring_size,), jnp.inf, dtype)
        if ring_size else None,
        ring_rho=jnp.zeros((ring_size,), dtype) if ring_size else None,
    )
    return NAPGCarry(
        state=state,
        x_prev=x_init,
        k_mom=jnp.asarray(0.0, dtype),
        restart_count=jnp.asarray(0, jnp.int32),
        norm_P=norm_P.astype(dtype),
    )


def _make_napg_segment(qp: CanonicalQP,
                       scaling: Scaling,
                       params: SolverParams,
                       l1w: jax.Array,
                       l1c: jax.Array):
    """Build the one-segment transition ``NAPGCarry -> NAPGCarry`` —
    the structural twin of ``pdhg._make_pdhg_segment``:
    ``check_interval`` iterations, one residual check, status /
    restart / ring updates. Shared verbatim by :func:`napg_solve`'s
    while_loop and :func:`napg_segment_step` so the hoisted loop
    cannot drift."""
    dtype = qp.q.dtype
    m = qp.m
    ring_size = params.ring_size

    def segment(carry: NAPGCarry) -> NAPGCarry:
        state = carry.state
        tau = 1.0 / carry.norm_P
        tau_l1w = tau * l1w

        def one_iteration(x, x_prev, k_mom, lam):
            beta = k_mom / (k_mom + 3.0)
            yk = x + beta * (x - x_prev)
            v = yk - tau * (qp.apply_P(yk) + qp.q)
            x_new, lam_new = _row_prox(v, lam, qp, tau_l1w, l1c, params)
            # Gradient restart: momentum opposes descent -> discard it.
            restart = jnp.dot(yk - x_new, x_new - x,
                              precision=_HP) > 0.0
            k_next = jnp.where(restart, 0.0, k_mom + 1.0)
            return x_new, x, k_next, restart, lam_new, v

        def body(_, c):
            x, x_prev, k_mom, rcount, lam = c
            x2, xp2, k2, restart, lam2, _ = one_iteration(
                x, x_prev, k_mom, lam)
            return (x2, xp2, k2, rcount + restart.astype(jnp.int32),
                    lam2)

        c0 = (state.x, carry.x_prev, carry.k_mom, carry.restart_count,
              tau * state.y)
        x, x_prev, k_mom, rcount, lam = jax.lax.fori_loop(
            0, params.check_interval - 1, body, c0)
        # Final iteration outside the loop to capture the dual
        # by-products the residual check consumes.
        x, x_prev, k_mom, restart, lam, v = one_iteration(
            x, x_prev, k_mom, lam)
        rcount = rcount + restart.astype(jnp.int32)
        y = lam / tau
        mu = (v - jnp.dot(lam, qp.C, precision=_HP) - x) / tau
        z = jnp.clip(jnp.dot(qp.C, x, precision=_HP), qp.l, qp.u)

        r_prim, r_dual, eps_p, eps_d, _, _ = _residuals(
            qp, scaling, x, z, x, y, mu, params)
        solved = (r_prim <= eps_p) & (r_dual <= eps_d)
        status = jnp.where(solved, Status.SOLVED,
                           Status.RUNNING).astype(jnp.int32)

        if ring_size:
            slot = jax.lax.rem(state.iters // params.check_interval,
                               jnp.asarray(ring_size, jnp.int32))
            ring_prim = state.ring_prim.at[slot].set(r_prim)
            ring_dual = state.ring_dual.at[slot].set(r_dual)
            # Third slot: cumulative restart count (same trajectory
            # diagnostic as PDHG's), where ADMM records rho.
            ring_rho = state.ring_rho.at[slot].set(rcount.astype(dtype))
        else:
            ring_prim = ring_dual = ring_rho = None

        new_state = ADMMState(
            x=x, z=z, w=x, y=y, mu=mu,
            rho_bar=jnp.asarray(tau, dtype),
            iters=state.iters + params.check_interval,
            status=status,
            prim_res=r_prim,
            dual_res=r_dual,
            ring_prim=ring_prim,
            ring_dual=ring_dual,
            ring_rho=ring_rho,
        )
        return NAPGCarry(
            state=new_state,
            x_prev=x_prev,
            k_mom=k_mom,
            restart_count=rcount,
            norm_P=carry.norm_P,
        )

    return segment


def napg_segment_step(carry: NAPGCarry,
                      qp: CanonicalQP,
                      scaling: Scaling,
                      params: SolverParams,
                      l1_weight: Optional[jax.Array] = None,
                      l1_center: Optional[jax.Array] = None):
    """Advance one residual-check segment; returns ``(carry,
    per_lane_status)`` — the exact contract of
    :func:`porqua_tpu.qp.admm.admm_segment_step` (the step never flips
    ``RUNNING`` to ``MAX_ITER``; the budget is the orchestrator's)."""
    dtype = qp.q.dtype
    n = qp.n
    l1w = jnp.zeros(n, dtype) if l1_weight is None else l1_weight
    l1c = jnp.zeros(n, dtype) if l1_center is None else l1_center
    segment = _make_napg_segment(qp, scaling, params, l1w, l1c)
    new = segment(carry)
    return new, new.state.status


def napg_solve(qp: CanonicalQP,
               scaling: Scaling,
               params: SolverParams,
               x0: Optional[jax.Array] = None,
               y0: Optional[jax.Array] = None,
               l1_weight: Optional[jax.Array] = None,
               l1_center: Optional[jax.Array] = None) -> ADMMState:
    """Run the accelerated projected-gradient loop on one *scaled*
    problem; returns the final :class:`~porqua_tpu.qp.admm.ADMMState`
    (``RUNNING`` retired to ``MAX_ITER``, exactly like ``admm_solve``).
    Structurally a thin ``lax.while_loop`` over :func:`napg_init` +
    :func:`napg_segment_step`'s transition, so hoisted drivers run the
    identical per-lane program."""
    dtype = qp.q.dtype
    n = qp.n
    l1w = jnp.zeros(n, dtype) if l1_weight is None else l1_weight
    l1c = jnp.zeros(n, dtype) if l1_center is None else l1_center
    segment = _make_napg_segment(qp, scaling, params, l1w, l1c)

    def cond(carry: NAPGCarry):
        state = carry.state
        return ((state.status == Status.RUNNING)
                & (state.iters < params.max_iter))

    final = jax.lax.while_loop(cond, segment,
                               napg_init(qp, params, x0, y0)).state
    return final._replace(
        status=jnp.where(
            final.status == Status.RUNNING, Status.MAX_ITER, final.status
        ).astype(jnp.int32))
