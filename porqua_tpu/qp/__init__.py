from porqua_tpu.qp.canonical import CanonicalQP, stack_qps
from porqua_tpu.qp.diff import solve_qp_diff, solve_qp_l1_diff
from porqua_tpu.qp.solve import solve_qp, solve_qp_batch, QPSolution, SolverParams, Status

__all__ = [
    "CanonicalQP",
    "stack_qps",
    "solve_qp",
    "solve_qp_batch",
    "solve_qp_diff",
    "solve_qp_l1_diff",
    "QPSolution",
    "SolverParams",
    "Status",
]
