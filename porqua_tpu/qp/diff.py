"""Differentiable QP solve: gradients through the optimizer.

The reference's solver boundary is a black box — ``qpsolvers`` hands
back a float array and the chain rule stops there (reference
``src/qp_problems.py:211``). Here the solve is an implicit function of
its inputs, so hyperparameters that *shape the problem* — ridge
strength, covariance shrinkage, transaction-cost weights, constraint
bounds — can be tuned by gradient descent through the whole backtest
(objective assembly -> batched solve -> tracking error), all in one
XLA program.

Method (Amos & Kolter's OptNet sensitivity / standard NLP sensitivity):
at a solution with active set A fixed and strict complementarity, x*
solves the equality-constrained KKT system

    [P     C_A'  E_A'] [x ]   [-q ]
    [C_A   0     0   ] [nu] = [bC ]   (active general rows)
    [E_A   0     0   ] [tau]  [bB ]   (active box coordinates)

and the solution map's vjp needs one solve with the SAME (symmetric)
KKT operator: K [u, wC, wB] = [g, 0, 0] for the incoming cotangent g.
The solve reuses the polish's penalty-Schur + iterative-refinement
scheme (``qp/polish.py``): M = P + delta I + (1/delta)(C'aC C + aB),
with refinement against the unperturbed KKT residuals, so the adjoint
is as accurate as the polish itself. Cotangents follow from
F(x, nu, tau; theta) = 0:

    q_bar  = -u
    P_bar  = -(u x' + x u') / 2            (P symmetric)
    C_bar  = -(nu u' + wC x')              (zero on inactive rows)
    bound_bar = +wC / +wB, routed to l/u (lb/ub) by the active side.

Caveats, stated rather than hidden:

* The map x*(theta) is piecewise-smooth; AT an active-set change the
  gradient is a subgradient of the piece the classifier picks. Strict
  complementarity is the differentiability condition, exactly as for
  qpsolvers' own sensitivity results.
* Native-L1 (prox) solves have their own entry point,
  :func:`solve_qp_l1_diff`, which adds the kink-set classification
  (shared with the prox-aware polish) and cotangents for the L1
  weights and centers.
* Gradients are meaningful only where ``status == SOLVED``; the
  backward pass zeroes cotangents of unsolved problems rather than
  propagating garbage.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from porqua_tpu.qp.canonical import CanonicalQP
from porqua_tpu.qp.polish import (
    _kkt_solve_dense,
    _kkt_solve_factored,
    classify_active,
    classify_l1,
    polish_capacitance_dim,
)
from porqua_tpu.qp.solve import QPSolution, SolverParams, Status, solve_qp

__all__ = ["solve_qp_diff", "solve_qp_l1_diff", "active_sets"]


def _classification_tols(sol: QPSolution, dtype):
    """(prox_tol, dual_tol) for active-set classification at a solved
    point, both floored at 1e3*machine-eps and scaled with the
    solution's own residuals — the gradient is taken on the piece the
    *achieved* accuracy can actually distinguish."""
    tiny = 1e3 * jnp.asarray(jnp.finfo(dtype).eps, dtype)
    tol = jnp.maximum(tiny, 10.0 * jnp.maximum(sol.prim_res, sol.dual_res))
    return tol, tol


def active_sets(qp: CanonicalQP, sol: QPSolution):
    """Classify active rows/box coordinates at a solution.

    Same criterion family as the polish (dual sign with an
    exact-on-bound proximity fallback, ``qp/polish.py``): a coordinate
    is active when its dual is decisively signed or the primal sits on
    the (finite) bound. Returns a 6-tuple ``(aC, bound_C, aB, bound_B,
    up_side_C, up_side_B)``: float {0,1} active masks, the active-side
    bound values (0 where inactive or the bound is infinite), and the
    boolean which-side indicators the bound cotangent routing uses.
    """
    dtype = qp.P.dtype
    # BOTH thresholds scale with the solution's residuals (round-3
    # advisor finding): at loose eps a residual dual of order the
    # solver tolerance is noise, and a machine-eps dual_tol would read
    # it as a decisively-signed active constraint. A truly active
    # constraint whose dual is below the residual scale still
    # classifies active through the on-bound proximity fallback.
    prox, dual_tol = _classification_tols(sol, dtype)

    (act_low_C, act_up_C, eq_C, act_low_B, act_up_B, eq_B
     ) = classify_active(qp, sol.z, sol.x, sol.y, sol.mu, prox, dual_tol)
    aC = ((act_low_C | act_up_C | eq_C) & (qp.row_mask > 0)).astype(dtype)
    up_side_C = act_up_C & ~act_low_C
    bound_C = jnp.where(up_side_C, qp.u, qp.l)
    bound_C = jnp.where(jnp.isfinite(bound_C), bound_C, 0.0) * aC

    aB = ((act_low_B | act_up_B | eq_B) & (qp.var_mask > 0)).astype(dtype)
    up_side_B = act_up_B & ~act_low_B
    bound_B = jnp.where(up_side_B, qp.ub, qp.lb)
    bound_B = jnp.where(jnp.isfinite(bound_B), bound_B, 0.0) * aB
    return aC, bound_C, aB, bound_B, up_side_C, up_side_B


def _adjoint_kkt_solve(qp: CanonicalQP, params: SolverParams, aC, aB, g):
    """Solve the symmetric active-set KKT adjoint system

        P u + C'(aC*wC) + aB*wB = g,   aC*(C u) = 0,   aB*u = 0

    This is exactly the polish's equality-KKT system with the rhs
    ``-q_eff`` replaced by the cotangent ``g`` and all active bounds at
    zero — so it dispatches to the SAME solvers the polish uses
    (``qp/polish.py``): the exact-pinning capacitance path when the
    objective factor pays (``qp.Pf``, (r+m)-dim factorizations), the
    dense penalty-Schur + refinement otherwise. The adjoint therefore
    inherits the polish's cost profile and accuracy, and a fix in
    either solver reaches the gradient path automatically.
    """
    dtype = qp.P.dtype
    delta = jnp.maximum(
        jnp.asarray(params.polish_delta, dtype),
        jnp.sqrt(jnp.asarray(jnp.finfo(dtype).eps, dtype)))
    zero_b = jnp.zeros(qp.n, dtype)
    zero_c = jnp.zeros(qp.m, dtype)
    solver = (_kkt_solve_factored
              if polish_capacitance_dim(qp) is not None
              else _kkt_solve_dense)
    return solver(qp, params, aB, aC, zero_b, zero_c, -g, delta)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def solve_qp_diff(qp: CanonicalQP, params: SolverParams) -> jax.Array:
    """``solve_qp(qp, params).x`` with an implicit-function vjp.

    Differentiable in ``P, q, C, l, u, lb, ub`` (the ``Pf``/``Pdiag``
    factor leaves get zero cotangents: ``P`` alone determines the
    solution, the factor is a computational alias — gradients w.r.t.
    data that built both flow through the ``P`` path). Compose with
    ``jax.vmap`` for batches and ``jax.grad`` for tuning loops; see
    ``tests/test_diff.py`` and ``examples/differentiable_tuning.py``.
    """
    return solve_qp(qp, params).x


def _qp_cotangents(qp, sol, u, wC, aC, up_side_C, lb_bar, ub_bar):
    """Assemble the CanonicalQP cotangent shared by the smooth and
    native-L1 vjps; callers supply their own box-bound routing.

    Bound cotangents: +w on the active side (F2 = aC*(Cx - bound) has
    d/dbound = -aC, so bound_bar = +wC; likewise box). Equality rows
    (l == u) classify as lower-side by convention — their cotangent
    lands on l; callers moving an equality bound move both l and u
    together, so the total differential is identical.
    """
    dtype = qp.P.dtype
    x = sol.x
    nu = aC * sol.y
    zero_m = jnp.zeros(qp.m, dtype)
    l_bar = jnp.where(up_side_C, zero_m, wC)
    u_bar = jnp.where(up_side_C, wC, zero_m)
    return CanonicalQP(
        P=-0.5 * (jnp.outer(u, x) + jnp.outer(x, u)),
        q=-u,
        C=-(jnp.outer(nu, u) + jnp.outer(wC, x)),
        l=l_bar,
        u=u_bar,
        lb=lb_bar,
        ub=ub_bar,
        var_mask=jnp.zeros_like(qp.var_mask),
        row_mask=jnp.zeros_like(qp.row_mask),
        constant=jnp.zeros_like(qp.constant),
        Pf=None if qp.Pf is None else jnp.zeros_like(qp.Pf),
        Pdiag=None if qp.Pdiag is None else jnp.zeros_like(qp.Pdiag),
    )


def _fwd(qp: CanonicalQP, params: SolverParams):
    sol = solve_qp(qp, params)
    return sol.x, (qp, sol)


def _bwd(params: SolverParams, res, g):
    qp, sol = res
    dtype = qp.P.dtype
    # Unsolved problems have no meaningful sensitivity; zero their
    # cotangent instead of backpropagating a garbage KKT solve.
    ok = (sol.status == Status.SOLVED).astype(dtype)
    g = g * ok

    aC, _, aB, _, up_side_C, up_side_B = active_sets(qp, sol)
    u, wC, wB = _adjoint_kkt_solve(qp, params, aC, aB, g)

    zero_n = jnp.zeros(qp.n, dtype)
    lb_bar = jnp.where(up_side_B, zero_n, wB)
    ub_bar = jnp.where(up_side_B, wB, zero_n)
    return (_qp_cotangents(qp, sol, u, wC, aC, up_side_C, lb_bar, ub_bar),)


solve_qp_diff.defvjp(_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def solve_qp_l1_diff(qp: CanonicalQP, l1_weight, l1_center,
                     params: SolverParams) -> jax.Array:
    """Differentiable solve of the NATIVE nonsmooth problem

        min 1/2 x'Px + q'x + sum_i w_i |x_i - c_i|   s.t. rows, box

    (the n-variable prox path, not the reference's 2n lift). Locally
    the L1 term splits the coordinates: *kink-resters* (x_i = c_i, dual
    strictly inside [-w_i, w_i]) behave as pinned equalities at c_i,
    and *smooth-side* coordinates see a constant gradient w_i
    sign(x_i - c_i) — exactly the classification the prox-aware polish
    uses (``qp/polish.py``). The vjp therefore adds two cotangents to
    :func:`solve_qp_diff`'s set:

        w_bar_i = -u_i sign_i   (smooth live coordinates; a
                                 kink-rester's solution is locally
                                 independent of its weight)
        c_bar_i = +wB_i         (kink-resters, via their pin row;
                                 smooth coordinates see c only through
                                 the locally-constant sign)

    Differentiability holds under strict complementarity AND strict
    kink classification (no coordinate exactly at the sign boundary);
    at a classification change the gradient is one-sided, as for the
    active sets. ``l1_center`` must lie strictly inside the box for
    kink-resters (else the pin and a box bound coincide — the box
    cotangent wins).
    """
    return solve_qp(qp, params, l1_weight=l1_weight,
                    l1_center=l1_center).x


def _l1_fwd(qp, l1_weight, l1_center, params):
    sol = solve_qp(qp, params, l1_weight=l1_weight, l1_center=l1_center)
    return sol.x, (qp, l1_weight, l1_center, sol)


def _l1_bwd(params, res, g):
    qp, w, c_in, sol = res
    dtype = qp.P.dtype
    # The forward solve treats a missing center as zeros (the polish's
    # convention); the backward must too — and hand back a None
    # cotangent for a None input.
    c = jnp.zeros(qp.n, dtype) if c_in is None else c_in
    ok = (sol.status == Status.SOLVED).astype(dtype)
    g = g * ok

    x, mu = sol.x, sol.mu
    err = jnp.maximum(sol.prim_res, sol.dual_res)
    prox, dual_tol = _classification_tols(sol, dtype)
    # Shared classification with the prox-aware polish: kink set, the
    # smooth-side signs, and the de-L1'd box dual come from ONE helper
    # (classify_l1), with `err` the solution's residual scale.
    at_kink, sign_s, mu_box, window = classify_l1(x, mu, w, c, err,
                                                  dual_mode="solution")
    # classify_l1 gates on live = w > 0, which zeroes sub_sign for a
    # coordinate whose weight IS zero — but the tuning derivative at
    # w_i = 0 is the one-sided limit -u_i sign(x_i - c_i) (switching on
    # an infinitesimal penalty pulls x_i toward c_i), which is
    # generically nonzero. Only a coordinate sitting on the would-be
    # kink (x_i = c_i) has a genuinely ambiguous (two-sided) limit,
    # where zero is the defensible subgradient choice.
    dead_side = jnp.where(jnp.abs(x - c) > window, jnp.sign(x - c), 0.0)
    sign_s = jnp.where(w > 0, sign_s, dead_side).astype(dtype)
    (act_low_C, act_up_C, eq_C, act_low_B, act_up_B, eq_B
     ) = classify_active(qp, sol.z, x, sol.y, mu_box, prox, dual_tol)
    aC = ((act_low_C | act_up_C | eq_C) & (qp.row_mask > 0)).astype(dtype)
    up_side_C = act_up_C & ~act_low_C
    box_act = (act_low_B | act_up_B | eq_B) & (qp.var_mask > 0)
    up_side_B = act_up_B & ~act_low_B
    # A coordinate that is both box-active and on its kink (c on a box
    # bound) is a genuinely one-sided point; the box cotangent wins, as
    # the entry-point docstring states.
    at_kink = at_kink & ~box_act

    aB_all = (box_act | at_kink).astype(dtype)
    u, wC, wB = _adjoint_kkt_solve(qp, params, aC, aB_all, g)

    zero_n = jnp.zeros(qp.n, dtype)
    lb_bar = jnp.where(box_act & ~up_side_B, wB, zero_n)
    ub_bar = jnp.where(box_act & up_side_B, wB, zero_n)
    c_bar = jnp.where(at_kink, wB, zero_n)
    w_bar = -u * sign_s
    qp_bar = _qp_cotangents(qp, sol, u, wC, aC, up_side_C, lb_bar, ub_bar)
    return (qp_bar, w_bar, None if c_in is None else c_bar)


solve_qp_l1_diff.defvjp(_l1_fwd, _l1_bwd)
