"""Static-shape canonical QP representation.

The canonical problem is

    minimize    0.5 x' P x + q' x + constant
    subject to  l  <= C x <= u          (m general rows; eq rows have l == u)
                lb <=   x <= ub         (box, kept separate from C)

This is the OSQP interval form, except the box is *not* materialized as
identity rows of ``C`` — the ADMM solver handles it implicitly, saving
an m x n matmul block per iteration and keeping the reduced KKT matrix
at n x n for the MXU.

Why this shape: the reference lowers each rebalance date to
``(P, q, G, h, A, b, lb, ub)`` with *data-dependent* row counts
(reference ``src/constraints.py:114-167``) and hands each problem to a
C solver one at a time. XLA needs one static shape for the whole batch,
so problems are padded:

* padded variables get ``lb = ub = 0``, ``q = 0`` and a unit diagonal in
  ``P`` — they solve to exactly 0 and do not perturb conditioning;
* padded rows are all-zero with ``l = -inf, u = +inf`` — always
  satisfied, zero dual.

Padding neutrality comes from this construction alone: padded entries
contribute exactly zero to every residual and projection, so the solver
needs no special-casing. ``var_mask``/``row_mask`` mark the real entries
for *consumers* (extracting weights, reporting universe sizes) — the
ADMM loop itself does not read them.

A :class:`CanonicalQP` is a NamedTuple of arrays, hence a JAX pytree:
``vmap``/``scan``/``pjit`` over a leading batch dimension just work.
"""

from __future__ import annotations

import warnings
from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

import numpy as np

# The one precision policy for every residual-bearing matvec/Gram in the
# QP stack (admm, polish, canonical): HIGHEST, because the TPU MXU
# computes f32 ``@`` in bf16 passes by default (~4e-3 relative error),
# which perturbs iterates and floors measurable residuals; the ADMM
# stages are memory-bound, so the extra passes cost nothing measurable.
HP = jax.lax.Precision.HIGHEST


class CanonicalQP(NamedTuple):
    """One (or a batch of) canonical QP(s); all fields are arrays.

    Shapes given for a single problem; a batch adds a leading axis.
    """

    P: jax.Array          # (n, n) objective quadratic (symmetric PSD)
    q: jax.Array          # (n,)   objective linear
    C: jax.Array          # (m, n) general constraint rows
    l: jax.Array          # (m,)   row lower bounds (-inf for pure <=)
    u: jax.Array          # (m,)   row upper bounds (+inf for pure >=)
    lb: jax.Array         # (n,)   variable lower bounds
    ub: jax.Array         # (n,)   variable upper bounds
    var_mask: jax.Array   # (n,)   1.0 for real variables, 0.0 for padding
    row_mask: jax.Array   # (m,)   1.0 for real rows, 0.0 for padding
    constant: jax.Array   # ()     objective constant
    # Optional low-rank structure: P == 2 Pf' Pf + diag(Pdiag) exactly.
    # Least-squares objectives (index tracking, P = 2 X'X — reference
    # ``optimization.py:206-226``) and sample-covariance objectives have
    # r = window << n on large universes; when present, the solver's
    # linear solves run in the r x r dual space (Woodbury) instead of
    # n x n — ~ (r/n)^3 of the factorization FLOPs (see qp.admm,
    # linsolve="woodbury"). ``None`` means "no known structure": every
    # consumer must fall back to the dense ``P``.
    Pf: Optional[jax.Array] = None     # (r, n) objective factor
    Pdiag: Optional[jax.Array] = None  # (n,)   diagonal completion

    @property
    def n(self) -> int:
        return self.P.shape[-1]

    @property
    def m(self) -> int:
        return self.C.shape[-2]

    @property
    def batch_shape(self):
        return self.P.shape[:-2]

    def apply_P(self, v):
        """``P @ v`` through the factor when one is present.

        With ``Pf`` the product is ``2 Pf'(Pf v) + Pdiag * v`` — two
        skinny (r x n) matvecs instead of a dense n x n one, and (the
        structural point) it leaves the dense ``P`` array UNREAD: in a
        pipeline where every P consumer routes through here (residuals,
        infeasibility certificates, objective/gap) XLA dead-code-
        eliminates the Gram build and the scaled-P materialization
        entirely — at the north-star batch that is ~32 of 75 GFLOP and
        ~1 GB of HBM traffic (BASELINE.md round-4 roofline). The factor
        form agrees with the dense product to rounding by the
        ``P == 2 Pf'Pf + diag(Pdiag)`` build invariant.
        """
        hp = HP
        if self.Pf is None:
            return jnp.einsum("...ij,...j->...i", self.P, v, precision=hp)
        t = jnp.einsum("...rj,...j->...r", self.Pf, v, precision=hp)
        out = 2.0 * jnp.einsum("...rj,...r->...j", self.Pf, t, precision=hp)
        if self.Pdiag is not None:
            out = out + self.Pdiag * v
        return out

    def objective_value(self, x, with_const: bool = True):
        """0.5 x'Px + q'x (+ constant); mirrors reference
        ``qp_problems.py:219-221``. P is applied through the factor
        when present (see :meth:`apply_P`)."""
        val = 0.5 * jnp.einsum(
            "...i,...i->...", x, self.apply_P(x), precision=HP
        ) + jnp.einsum("...i,...i->...", self.q, x, precision=HP)
        return val + self.constant if with_const else val

    @staticmethod
    def build(P: np.ndarray,
              q: np.ndarray,
              C: Optional[np.ndarray] = None,
              l: Optional[np.ndarray] = None,
              u: Optional[np.ndarray] = None,
              lb: Optional[np.ndarray] = None,
              ub: Optional[np.ndarray] = None,
              constant: float = 0.0,
              n_max: Optional[int] = None,
              m_max: Optional[int] = None,
              dtype=None,
              Pf: Optional[np.ndarray] = None,
              Pdiag: Optional[np.ndarray] = None) -> "CanonicalQP":
        """Assemble + pad a single problem from host-side numpy arrays.

        ``dtype=None`` means float32 (the TPU default). ``Pf``/``Pdiag``
        optionally expose the objective's low-rank structure
        ``P == 2 Pf' Pf + diag(Pdiag)`` (checked here), which the
        active-set polish — and the capacitance linear-solve mode —
        exploit to factor at the (r + m)-dim capacitance instead of
        n x n. The factor's row count r must match across problems that
        will be stacked (it is not padded)."""
        dtype = jnp.float32 if dtype is None else dtype
        P = np.asarray(P, dtype=np.float64)
        q = np.asarray(q, dtype=np.float64).reshape(-1)
        n = q.shape[0]
        if C is None or C.size == 0:
            C = np.zeros((0, n))
            l = np.zeros((0,))
            u = np.zeros((0,))
        C = np.asarray(C, dtype=np.float64).reshape(-1, n)
        l = np.asarray(l, dtype=np.float64).reshape(-1)
        u = np.asarray(u, dtype=np.float64).reshape(-1)
        m = C.shape[0]
        lb = np.full(n, -np.inf) if lb is None else np.asarray(lb, dtype=np.float64)
        ub = np.full(n, np.inf) if ub is None else np.asarray(ub, dtype=np.float64)

        n_max = n if n_max is None else int(n_max)
        m_max = m if m_max is None else int(m_max)
        if n_max < n or m_max < m:
            raise ValueError(f"padding target ({n_max},{m_max}) smaller than problem ({n},{m})")

        dn, dm = n_max - n, m_max - m
        P_pad = np.zeros((n_max, n_max))
        P_pad[:n, :n] = P
        if dn:
            P_pad[n:, n:] = np.eye(dn)
        q_pad = np.concatenate([q, np.zeros(dn)])
        C_pad = np.zeros((m_max, n_max))
        C_pad[:m, :n] = C
        l_pad = np.concatenate([l, np.full(dm, -np.inf)])
        u_pad = np.concatenate([u, np.full(dm, np.inf)])
        lb_pad = np.concatenate([lb, np.zeros(dn)])
        ub_pad = np.concatenate([ub, np.zeros(dn)])
        var_mask = np.concatenate([np.ones(n), np.zeros(dn)])
        row_mask = np.concatenate([np.ones(m), np.zeros(dm)])

        Pf_pad = Pd_pad = None
        if Pf is not None:
            Pf = np.asarray(Pf, dtype=np.float64).reshape(-1, n)
            Pd = (np.zeros(n) if Pdiag is None
                  else np.asarray(Pdiag, dtype=np.float64).reshape(-1))
            # Consistency probe for P == 2 Pf' Pf + diag(Pdiag): one
            # matvec against a fixed dense direction (O(r n) instead of
            # rebuilding the O(r n^2) Gram the caller just assembled).
            # Rounding-grade drift (e.g. P assembled from float32
            # source data) quietly degrades to the dense path — the
            # factor is a performance structure, not semantics; only a
            # gross mismatch (wrong factor) is an error.
            v = np.cos(np.arange(n, dtype=np.float64))
            pv = P @ v
            fv = 2.0 * (Pf.T @ (Pf @ v)) + Pd * v
            dev = float(np.max(np.abs(pv - fv)))
            scale = max(float(np.max(np.abs(pv))), 1e-30)
            if dev > 1e-3 * scale:
                raise ValueError(
                    "Pf/Pdiag do not reproduce P (convention: "
                    "P == 2 Pf' Pf + diag(Pdiag)); matvec deviation "
                    f"{dev:.3e} vs scale {scale:.3e}")
            if dev > 1e-7 * scale:
                warnings.warn(
                    f"objective factor reproduces P only to {dev/scale:.1e} "
                    "relative (float32-source rounding?); using the dense "
                    "path", stacklevel=2)
            else:
                Pf_pad = np.concatenate(
                    [Pf, np.zeros((Pf.shape[0], dn))], axis=1)
                # Padded variables carry P = I on the diagonal block:
                # put it in the diagonal completion so the factor form
                # stays exact.
                Pd_pad = np.concatenate([Pd, np.ones(dn)])
        elif Pdiag is not None:
            raise ValueError("Pdiag without Pf has no meaning")

        as_dev = lambda a: jnp.asarray(a, dtype=dtype)
        return CanonicalQP(
            P=as_dev(P_pad), q=as_dev(q_pad), C=as_dev(C_pad),
            l=as_dev(l_pad), u=as_dev(u_pad), lb=as_dev(lb_pad), ub=as_dev(ub_pad),
            var_mask=as_dev(var_mask), row_mask=as_dev(row_mask),
            constant=jnp.asarray(constant, dtype=dtype),
            Pf=None if Pf_pad is None else as_dev(Pf_pad),
            Pdiag=None if Pd_pad is None else as_dev(Pd_pad),
        )


def stack_qps(qps: Sequence[CanonicalQP], stack_fn=None) -> CanonicalQP:
    """Stack same-shape problems into one batch along a new leading axis.

    ``stack_fn`` selects the array backend: the default ``jnp.stack``
    places the batch on the default device (the batched-backtest path);
    the serve batcher passes ``np.stack`` so the assembled batch stays
    host-side numpy and the AOT executable — compiled for a *specific*
    device — performs the one transfer itself (a jnp-stacked batch
    committed to the wrong device would be rejected at call time).
    """
    if not qps:
        raise ValueError("cannot stack an empty sequence of QPs")
    shapes = {(qp.n, qp.m) for qp in qps}
    if len(shapes) != 1:
        raise ValueError(
            f"all problems must share one padded shape; got {sorted(shapes)}. "
            "Pass n_max/m_max to CanonicalQP.build."
        )
    stack_fn = jnp.stack if stack_fn is None else stack_fn
    return jax.tree.map(lambda *xs: stack_fn(xs), *qps)


def pad_qp(qp: CanonicalQP, n_max: int, m_max: int) -> CanonicalQP:
    """Host-side re-pad of an already-built single problem to a larger
    static shape, with the same neutrality scheme as :meth:`build`
    (padded variables: unit diagonal, ``lb = ub = 0``; padded rows:
    all-zero with infinite bounds; masks extended with zeros).

    This is the serving bucketizer's workhorse: incoming requests carry
    problems at their natural shape and are padded up to a small ladder
    of shape buckets so a stream of heterogeneous problems compiles to a
    handful of executables. Returns **numpy** fields (zero-copy when no
    padding is needed beyond the array conversion) — batching keeps the
    host-side representation until the one stacked device transfer.
    """
    n, m = qp.n, qp.m
    if n_max < n or m_max < m:
        raise ValueError(
            f"padding target ({n_max},{m_max}) smaller than problem "
            f"({n},{m})")
    dn, dm = n_max - n, m_max - m
    f = lambda a: np.asarray(a)
    dtype = f(qp.q).dtype
    if dn == 0 and dm == 0:
        out = CanonicalQP(*(None if x is None else f(x) for x in qp))
        if out.Pf is not None and out.Pdiag is None:
            # Normalize the factored pytree structure: a factored
            # problem must ALWAYS carry a Pdiag leaf after padding
            # (the padded path materializes it, the AOT shape struct
            # expects it, and stack_qps cannot mix None with arrays).
            out = out._replace(Pdiag=np.zeros(n, dtype))
        return out

    P_pad = np.zeros((n_max, n_max), dtype)
    P_pad[:n, :n] = f(qp.P)
    if dn:
        P_pad[n:, n:] = np.eye(dn, dtype=dtype)
    C_pad = np.zeros((m_max, n_max), dtype)
    C_pad[:m, :n] = f(qp.C)
    pad_n = lambda v, fill: np.concatenate(
        [f(v), np.full(dn, fill, dtype)]) if dn else f(v)
    pad_m = lambda v, fill: np.concatenate(
        [f(v), np.full(dm, fill, dtype)]) if dm else f(v)
    Pf_pad = Pd_pad = None
    if qp.Pf is not None:
        # Factor rows are a capacitance dimension, never padded; only
        # the variable axis grows. The padding block's unit diagonal
        # lives in the diagonal completion, as in build().
        Pf_pad = (np.concatenate(
            [f(qp.Pf), np.zeros((f(qp.Pf).shape[0], dn), dtype)], axis=1)
            if dn else f(qp.Pf))
        Pd = f(qp.Pdiag) if qp.Pdiag is not None else np.zeros(n, dtype)
        Pd_pad = np.concatenate([Pd, np.ones(dn, dtype)]) if dn else Pd
    return CanonicalQP(
        P=P_pad, q=pad_n(qp.q, 0.0), C=C_pad,
        l=pad_m(qp.l, -np.inf), u=pad_m(qp.u, np.inf),
        lb=pad_n(qp.lb, 0.0), ub=pad_n(qp.ub, 0.0),
        var_mask=pad_n(qp.var_mask, 0.0), row_mask=pad_m(qp.row_mask, 0.0),
        constant=f(qp.constant).astype(dtype),
        Pf=Pf_pad, Pdiag=Pd_pad,
    )


def sketch_rows(M: jax.Array, sketch_dim: int, key: jax.Array) -> jax.Array:
    """Clarkson-Woodruff count-sketch of the leading (row) axis:
    ``(T, k) -> (sketch_dim, k)``. Each row lands in one signed bucket,
    so the whole embedding is a single ``segment_sum`` — O(T k), no
    matmul, trivially fused by XLA into the surrounding assembly.

    This is the Gram-compression primitive the canonical lowering layer
    owns: applied to a stacked ``[X | y]`` return window before
    ``build_tracking_qp``, the assembled ``P = 2 Xs'Xs`` is a subspace
    embedding of the true Gram with the usual (1 ± eps) guarantee, and
    the ``Pf`` factor the Woodbury/first-order paths carry shrinks from
    T to ``sketch_dim`` rows. Seeded and deterministic: same
    ``(key, shapes)`` => same embedding, so reruns and multi-host
    replays reconcile. ``qp.sketch`` layers the measured
    ``gram_rel_err`` certificate and passthrough policy on top.
    """
    T = M.shape[0]
    kb, ks = jax.random.split(key)
    bucket = jax.random.randint(kb, (T,), 0, sketch_dim)
    sign = jax.random.rademacher(ks, (T,), M.dtype)
    return jax.ops.segment_sum(sign[:, None] * M, bucket,
                               num_segments=sketch_dim)
