"""Shape buckets + the AOT compiled-executable cache.

XLA compiles one program per static shape, so an online stream of
arbitrarily-shaped problems would recompile constantly — the
first-call latency (~seconds on CPU, ~a minute cold on TPU) would
dwarf every solve. The serving answer, borrowed from the shape-
bucketing inference stacks use for variable-length sequences: pad each
incoming problem up a small geometric ladder of ``(n_vars, m_rows)``
buckets (amortized padding waste is bounded by the ladder ratio), and
pad each *batch* up a power-of-two slot ladder, so the set of shapes
that can ever reach the compiler is the finite product
``rungs x slot-sizes``. Every entry is compiled once, ahead of time,
via ``jit(...).lower(...).compile()`` (:func:`qp.solve.aot_compile_batch`)
and cached — steady-state serving never recompiles (the
``compiles`` counter after warmup is the regression signal).

The padding itself is :func:`porqua_tpu.qp.canonical.pad_qp` — the
same neutrality scheme the batched backtest uses, so a padded request
solves to exactly the same solution with zeros in the padding slots.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from porqua_tpu.analysis import sanitize, tsan
from porqua_tpu.qp.canonical import CanonicalQP, pad_qp
from porqua_tpu.resilience import faults as _faults
from porqua_tpu.qp.solve import (
    SolverParams,
    aot_compile_batch,
    aot_compile_continuous,
    batch_shape_struct,
)

#: Default shape ladder. n covers the repo's workloads (24-asset MSCI
#: grid -> 32; 500-asset north star -> 512; headroom to 2048) at ratio
#: 2; m is sparser (most portfolio polytopes carry few general rows —
#: budget + a handful of linear constraints; the lifted turnover form
#: adds 2n).
DEFAULT_N_RUNGS: Tuple[int, ...] = (32, 64, 128, 256, 512, 1024, 2048)
DEFAULT_M_RUNGS: Tuple[int, ...] = (8, 32, 128, 512, 2048)


class Bucket(NamedTuple):
    """One shape bucket. ``factor_rows`` is part of the identity: a
    problem carrying the low-rank objective factor (``Pf``) compiles a
    different program than a dense one, and factor rows are a
    capacitance dimension that is never padded — problems only share a
    bucket when their factor shape matches exactly."""

    n: int
    m: int
    factor_rows: Optional[int] = None


class BucketOverflow(ValueError):
    """The problem exceeds the ladder's largest rung."""


class BucketLadder:
    """Maps a problem's natural shape to its padded bucket."""

    def __init__(self,
                 n_rungs: Sequence[int] = DEFAULT_N_RUNGS,
                 m_rungs: Sequence[int] = DEFAULT_M_RUNGS) -> None:
        if not n_rungs or not m_rungs:
            raise ValueError("ladder needs at least one rung per axis")
        self.n_rungs = tuple(sorted(int(r) for r in n_rungs))
        self.m_rungs = tuple(sorted(int(r) for r in m_rungs))

    @staticmethod
    def _select(rungs: Tuple[int, ...], value: int, axis: str) -> int:
        i = bisect.bisect_left(rungs, value)
        if i == len(rungs):
            raise BucketOverflow(
                f"problem {axis}={value} exceeds the ladder's largest "
                f"rung {rungs[-1]}; extend the ladder explicitly rather "
                f"than letting one request mint an unbounded shape")
        return rungs[i]

    def select(self, qp: CanonicalQP) -> Bucket:
        r = None if qp.Pf is None else int(np.asarray(qp.Pf).shape[-2])
        return Bucket(self._select(self.n_rungs, qp.n, "n_vars"),
                      self._select(self.m_rungs, qp.m, "m_rows"), r)

    def pad(self, qp: CanonicalQP) -> Tuple[Bucket, CanonicalQP]:
        """Select the bucket and pad the problem into it (host numpy)."""
        bucket = self.select(qp)
        return bucket, pad_qp(qp, bucket.n, bucket.m)


def slot_count(n_requests: int, max_batch: int) -> int:
    """Batch-size ladder: next power of two >= n_requests, capped at
    ``max_batch``. Guarantees occupancy >= 50% for every partial batch
    (and 100% at size 1), while keeping the executable count per bucket
    at ~log2(max_batch)."""
    if n_requests <= 0:
        raise ValueError("empty batch")
    if n_requests >= max_batch:
        return max_batch
    return min(1 << (n_requests - 1).bit_length(), max_batch)


def slot_ladder(max_batch: int) -> Tuple[int, ...]:
    """Every batch size :func:`slot_count` can produce for a cap."""
    out = []
    s = 1
    while s < max_batch:
        out.append(s)
        s <<= 1
    out.append(max_batch)
    return tuple(out)


class ExecutableCache:
    """(bucket, slots, dtype, device) -> AOT-compiled batch solve.

    ``SolverParams`` is fixed per cache (it is part of the service
    identity, not the request); the device is part of the key so the
    circuit breaker's fallback device gets its own executables instead
    of a cross-device crash. Thread-safe; compiles happen OUTSIDE the
    lock — a multi-second AOT compile under the cache lock would wedge
    every other bucket's cache hit behind one cold shape (graftcheck
    GC010). Two threads racing the same miss still compile once: the
    first claims the key with a pending marker and builds, the rest
    wait on the marker and re-read the cache.
    """

    def __init__(self, params: SolverParams = SolverParams(),
                 metrics=None, events=None, cost_log=None) -> None:
        self.params = params
        self.metrics = metrics
        # Optional porqua_tpu.obs.EventBus: every AOT compile becomes a
        # structured event (post-warmup ones at "warn" — they are the
        # steady-state-recompile regression the counters gate on).
        self.events = events
        # Device-truth cost warehouse (porqua_tpu.obs.devprof): every
        # compiled executable's XLA cost_analysis / memory_analysis is
        # harvested into one CostRecord — flops, bytes accessed, peak
        # memory, compile seconds, HLO fingerprint. Runs once per
        # compile, strictly host-side (contract GC107 pins that the
        # plane changes no traced program; cost_log=False disables it
        # entirely, pinned bit-identical by tests/test_devprof.py).
        # None = a default in-memory CostLog, so per-bucket peak-memory
        # gauges always have data; pass CostLog(path) to persist.
        if cost_log is False:
            self.cost_log = None
        elif cost_log is None:
            from porqua_tpu.obs.devprof import CostLog

            self.cost_log = CostLog()
        else:
            self.cost_log = cost_log
        self._lock = tsan.lock("ExecutableCache")
        self._cache: Dict[tuple, object] = {}  # guarded-by: self._lock
        # Latest CostRecord per (kind, bucket, slots, dtype, device,
        # entry) — the lookup the batcher's measured profile and the
        # flight recorder's incident bundles read.
        self._cost_records: Dict[tuple, dict] = {}  # guarded-by: self._lock
        # Per-bucket cache health: hits / misses / compile seconds,
        # keyed by the bucket label ("NxM"[xfR]). Cumulative (cache
        # state, not window state): prewarm compiles are exactly what
        # a scraper wants to see here.
        self._bucket_stats: Dict[str, Dict[str, float]] = {}  # guarded-by: self._lock
        # key -> threading.Event while a compile for it is in flight
        # (set + removed by the builder; waiters re-read the cache)
        self._inflight: Dict[tuple, threading.Event] = {}  # guarded-by: self._lock
        # Sanitizer warmup state, scoped per cache AND per device: a
        # device whose ladder prewarmed is sealed — misses on it are
        # steady-state recompiles (raise under PORQUA_SANITIZE=1) —
        # while a device never prewarmed (the deliberately-skipped
        # black-holed primary that later recovers) pays its compiles
        # lazily, as documented, without hard-failing traffic. Scoped
        # here, not process-globally, so two services cannot close
        # each other's windows.
        self._warmed_devices: set = set()  # guarded-by: self._lock
        # (bucket, device_key) -> in-flight prewarm depth: their
        # compiles are warmup even when the device is sealed, WITHOUT
        # exempting concurrent misses on other buckets or other
        # devices (a mid-traffic prewarm must not open a wider
        # enforcement hole), and concurrent same-bucket prewarms each
        # hold their own exemption (counter, not a flag).
        self._warming: Dict[tuple, int] = {}  # guarded-by: self._lock

    @staticmethod
    def _device_key(device) -> tuple:
        if device is None:
            return ("default",)
        return (device.platform, device.id)

    @staticmethod
    def _bucket_label(bucket: Bucket) -> str:
        label = f"{bucket.n}x{bucket.m}"
        if bucket.factor_rows is not None:
            label += f"xf{bucket.factor_rows}"
        return label

    def _bucket_stat(self, bucket: Bucket) -> Dict[str, float]:  # guarded-by: self._lock
        return self._bucket_stats.setdefault(
            self._bucket_label(bucket),
            {"cache_hits": 0, "compiles": 0, "compile_seconds": 0.0})

    def get(self, bucket: Bucket, slots: int, dtype, device=None):
        """The compiled executable for one (bucket, batch, device)."""
        return self._get(bucket, slots, dtype, device)[0]

    def get_continuous(self, bucket: Bucket, slots: int, dtype,
                       device=None):
        """The continuous-batching executable triple ``(admit, step,
        finalize, structs)`` for one cohort shape (see
        :func:`porqua_tpu.qp.solve.aot_compile_continuous`). Cached and
        warmup-accounted exactly like the one-shot executables — the
        triple is one cache entry / one compile event."""
        return self._get(bucket, slots, dtype, device,
                         kind="continuous")[0]

    def _get(self, bucket: Bucket, slots: int, dtype, device=None,
             kind: str = "solve"):
        """(executable, missed): ``missed`` lets prewarm count ITS OWN
        compiles exactly instead of diffing cache sizes across threads."""
        dev_key = self._device_key(device)
        key = (kind, bucket, int(slots), np.dtype(dtype).str, dev_key)
        if _faults.enabled():
            # cache.get seam: a compile_storm directive evicts this
            # entry, so a post-warmup dispatch pays a fresh AOT
            # compile — the induced form of the steady-state-
            # recompile regression the compile counters/events (and
            # PORQUA_SANITIZE) exist to surface. Fired outside the
            # cache lock (the injector takes its own).
            act = _faults.fire("cache.get", cache_kind=kind,
                               slots=int(slots))
            if act is not None and act.kind == "compile_storm":
                with self._lock:
                    self._cache.pop(key, None)
        while True:
            wait_for = None
            with self._lock:
                exe = self._cache.get(key)
                if exe is not None:
                    hit = True
                    self._bucket_stat(bucket)["cache_hits"] += 1
                else:
                    hit = False
                    wait_for = self._inflight.get(key)
                    if wait_for is None:
                        # Claim the key: this thread builds; the
                        # warmup decision snapshots atomically with
                        # the claim.
                        self._inflight[key] = threading.Event()
                        post_warmup = (
                            dev_key in self._warmed_devices
                            and not self._warming.get((bucket, dev_key)))
            if hit:
                if self.metrics is not None:
                    self.metrics.inc("cache_hits")
                return exe, False
            if wait_for is not None:
                # Another thread is compiling this exact key: wait for
                # it (NOT under the lock — other buckets keep hitting)
                # and re-read; if the builder failed, the loop retries
                # and this thread becomes the builder.
                wait_for.wait()
                continue
            return self._build(key, bucket, slots, dtype, device,
                               kind, dev_key, post_warmup), True

    def _build(self, key, bucket: Bucket, slots: int, dtype, device,
               kind: str, dev_key, post_warmup: bool):
        """Compile one claimed cache entry OUTSIDE the cache lock (a
        multi-second AOT compile must not block unrelated hits), then
        publish it and release the waiters."""
        t0 = time.perf_counter()
        try:
            # Sanitizer hook: every AOT compile is counted; after
            # prewarm() closes this cache's warmup window, a miss here
            # raises under PORQUA_SANITIZE=1 (the zero-steady-state-
            # recompiles invariant) instead of silently paying a
            # multi-second compile mid-traffic.
            try:
                sanitize.note_compile(
                    f"kind={kind} bucket={bucket} slots={int(slots)} "
                    f"device={dev_key}",
                    post_warmup=post_warmup)
            except sanitize.SanitizerError as exc:
                if self.events is not None:
                    self.events.emit(
                        "sanitizer_violation", "error",
                        what="post_warmup_compile_refused",
                        bucket=f"{bucket.n}x{bucket.m}",
                        slots=int(slots), device=str(dev_key),
                        detail=str(exc))
                raise
            struct = batch_shape_struct(
                int(slots), bucket.n, bucket.m, dtype=dtype,
                factor_rows=bucket.factor_rows)
            if kind == "continuous":
                exe = aot_compile_continuous(struct, self.params,
                                             device=device)
            else:
                exe = aot_compile_batch(struct, self.params, device=device)
            with self._lock:
                self._cache[key] = exe
        finally:
            # Success or failure, drop the claim and wake the waiters
            # (on failure they re-race the miss; one re-raises the
            # same refusal rather than hanging on an orphaned event).
            with self._lock:
                pending = self._inflight.pop(key, None)
            if pending is not None:
                pending.set()
        seconds = time.perf_counter() - t0
        with self._lock:
            stat = self._bucket_stat(bucket)
            stat["compiles"] += 1
            stat["compile_seconds"] += seconds
        self._harvest_cost(bucket, slots, dtype, dev_key, kind, exe,
                           seconds)
        if self.metrics is not None:
            self.metrics.observe_compile(seconds)
        if self.events is not None:
            self.events.emit(
                "compile", "warn" if post_warmup else "info",
                bucket=f"{bucket.n}x{bucket.m}",
                factor_rows=bucket.factor_rows, slots=int(slots),
                device=str(dev_key), seconds=round(seconds, 4),
                post_warmup=post_warmup)
        return exe

    def _harvest_cost(self, bucket: Bucket, slots: int, dtype,
                      dev_key, kind: str, exe, seconds: float) -> None:
        """Harvest the freshly-compiled executable's XLA cost/memory
        analysis into CostRecords (one for a one-shot solve, three for
        the continuous admit/step/finalize triple — the compile event
        stays one, the cost truth is per program). Host-only, once per
        compile, never raises."""
        if self.cost_log is None:
            return
        try:
            from porqua_tpu.obs.devprof import cost_record

            label = self._bucket_label(bucket)
            dev_label = ":".join(str(p) for p in dev_key)
            dtype_str = np.dtype(dtype).str
            if kind == "continuous":
                entries = list(zip(("admit", "step", "finalize"), exe[:3]))
            else:
                entries = [("solve", exe)]
            for entry, compiled in entries:
                rec = cost_record(
                    compiled, entry=entry, kind=kind, bucket=label,
                    slots=int(slots), dtype=dtype_str, device=dev_label,
                    compile_s=seconds)
                with self._lock:
                    self._cost_records[
                        (kind, label, int(slots), dtype_str, dev_label,
                         entry)] = rec
                self.cost_log.emit(rec)
        except Exception:  # noqa: BLE001 - cost truth is evidence, not
            # a dependency: a backend that refuses an analysis (or a
            # jax version that renames one) must not fail the compile.
            pass

    # -- device-truth readers ------------------------------------------

    def cost_records(self) -> list:
        """Every harvested CostRecord (latest per executable identity)."""
        with self._lock:
            return [dict(r) for r in self._cost_records.values()]

    def cost_record_for(self, bucket: Bucket, slots: int, dtype,
                        kind: str = "solve",
                        entry: Optional[str] = None,
                        device_label: Optional[str] = None):
        """The CostRecord of one cached executable, or ``None`` —
        the batcher reads this to switch a dispatch's MFU/bandwidth
        numerators from the analytic model to XLA's own accounting.
        ``device_label`` (``"platform:id"``) narrows to one device;
        ``None`` matches any (program cost is device-kind-invariant
        for a fixed backend, and the caller usually knows the label)."""
        if entry is None:
            entry = "step" if kind == "continuous" else "solve"
        label = self._bucket_label(bucket)
        dtype_str = np.dtype(dtype).str
        with self._lock:
            if device_label is not None:
                rec = self._cost_records.get(
                    (kind, label, int(slots), dtype_str, device_label,
                     entry))
                return None if rec is None else dict(rec)
            for key, rec in self._cost_records.items():
                if key[:4] == (kind, label, int(slots), dtype_str) \
                        and key[5] == entry:
                    return dict(rec)
        return None

    def bucket_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-bucket cache health: hits, compiles (== misses that
        built), cumulative compile seconds, and the max harvested
        peak-memory / bytes-accessed across the bucket's executables."""
        with self._lock:
            out = {label: dict(stat)
                   for label, stat in self._bucket_stats.items()}
            for (kind, label, slots, _dt, _dev, entry), rec \
                    in self._cost_records.items():
                stat = out.setdefault(
                    label, {"cache_hits": 0, "compiles": 0,
                            "compile_seconds": 0.0})
                for field, key in (("peak_bytes_max", "peak_bytes"),
                                   ("bytes_accessed_max",
                                    "bytes_accessed")):
                    v = rec.get(key)
                    if v is not None:
                        stat[field] = max(stat.get(field, 0.0), float(v))
        return out

    def prometheus_gauges(self) -> Dict[str, list]:
        """Per-bucket cache-health series for the ``/metrics``
        exposition (``prometheus_text(labeled_gauges=...)``): compile
        seconds, compile and hit counters, and peak device memory —
        cache health was previously visible only as EventBus events."""
        stats = self.bucket_stats()
        out: Dict[str, list] = {
            "bucket_compile_seconds_total": [],
            "bucket_compiles_total": [],
            "bucket_cache_hits_total": [],
            "bucket_peak_bytes": [],
        }
        for label in sorted(stats):
            stat = stats[label]
            tag = {"bucket": label}
            out["bucket_compile_seconds_total"].append(
                (tag, stat.get("compile_seconds", 0.0)))
            out["bucket_compiles_total"].append(
                (tag, stat.get("compiles", 0)))
            out["bucket_cache_hits_total"].append(
                (tag, stat.get("cache_hits", 0)))
            if "peak_bytes_max" in stat:
                out["bucket_peak_bytes"].append(
                    (tag, stat["peak_bytes_max"]))
        return out

    @property
    def warmed(self) -> bool:
        """At least one device's ladder prewarmed successfully
        (sanitizer enforcement armed for that device)."""
        with self._lock:
            return bool(self._warmed_devices)

    def prewarm(self, bucket: Bucket, max_batch: int, dtype,
                device=None, continuous: bool = False,
                include_solve: bool = True) -> int:
        """Compile the whole slot ladder for one bucket; returns the
        number of executables compiled (cache misses). ``(bucket,
        device)``'s compiles count as warmup for the duration (so
        re-prewarming a missing bucket mid-traffic is the sanctioned
        fix, not itself a violation), while concurrent misses on other
        buckets or devices stay enforced. The device is sealed only
        when the whole ladder compiled — a prewarm that died partway
        must not arm enforcement over a half-warm cache.
        ``continuous=True`` compiles the continuous-batching triple
        for every rung (cohorts are created at ladder sizes, so any
        cohort a ``ContinuousBatcher`` can mint dispatches into an
        already-compiled triple); ``include_solve=False`` skips the
        one-shot solve executables — a continuous service never
        dispatches them, and at production shapes each dead AOT
        compile costs real startup seconds."""
        compiled = 0
        key = (bucket, self._device_key(device))
        with self._lock:
            self._warming[key] = self._warming.get(key, 0) + 1
        try:
            for s in slot_ladder(max_batch):
                if include_solve:
                    compiled += self._get(bucket, s, dtype, device)[1]
                if continuous:
                    compiled += self._get(bucket, s, dtype, device,
                                          kind="continuous")[1]
        finally:
            with self._lock:
                depth = self._warming[key] - 1
                if depth:
                    self._warming[key] = depth
                else:
                    del self._warming[key]
        with self._lock:
            self._warmed_devices.add(self._device_key(device))
        return compiled

    def __len__(self) -> int:
        return len(self._cache)
